"""Overlap-pipeline correctness on the 8-device CPU mesh.

These are the re-creations of the reference's nvFuser pipeline algorithms
(/root/reference/ddlb/primitives/TPColumnwise/fuser.py:59-146,
TPRowwise/fuser.py:62-169); chunk-reassembly order is the risky part
(SURVEY.md section 7 step 7), so validation runs for every algorithm,
stage count, and ring direction.
"""

import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 256, 64, 96  # m % (8*4) == 0, k % 8 == 0


@pytest.mark.parametrize("algorithm", ["default", "coll_pipeline", "p2p_pipeline"])
@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_algorithms_validate(primitive, algorithm):
    cls = load_impl_class(primitive, "overlap")
    impl = cls(M, N, K, dtype="float32", algorithm=algorithm, s=4)
    result = impl.run()
    assert result.shape == (M, N)
    assert impl.validate(result)


@pytest.mark.parametrize("s", [1, 2, 4])
@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_coll_pipeline_stage_counts(primitive, s):
    cls = load_impl_class(primitive, "overlap")
    impl = cls(M, N, K, dtype="float32", algorithm="coll_pipeline", s=s)
    assert impl.validate(impl.run())


@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_p2p_bidirectional(primitive):
    cls = load_impl_class(primitive, "overlap")
    impl = cls(
        M, N, K, dtype="float32",
        algorithm="p2p_pipeline", direction="bidirectional",
    )
    assert impl.validate(impl.run())


def test_bf16_pipelines():
    for primitive in ("tp_columnwise", "tp_rowwise"):
        cls = load_impl_class(primitive, "overlap")
        impl = cls(M, N, K, dtype="bfloat16", algorithm="p2p_pipeline")
        assert impl.validate(impl.run())


def test_coll_pipeline_divisibility():
    cls = load_impl_class("tp_columnwise", "overlap")
    # m=256 not divisible by d*s = 8*48
    with pytest.raises(ValueError, match="divisible by partitions\\*s"):
        cls(M, N, K, algorithm="coll_pipeline", s=48)


def test_stage_count_range():
    cls = load_impl_class("tp_columnwise", "overlap")
    with pytest.raises(ValueError, match="outside allowed range"):
        cls(M, N, K, algorithm="coll_pipeline", s=0)
