"""Grouped-query attention + non-causal mode: kernels and model.

The flash kernels read the shared KV tile straight from the head index
map (query head hh -> kv head hh // G), dK/dV group-sum back to kv-head
shape; everything is pinned against an einsum oracle that materializes
the repetition. Model level: the gathered train path, the serving
prefill/decode paths, and the kv-head cache shrink.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _oracle(q, k, v, scale, causal=True):
    G = q.shape[1] // k.shape[1]
    kr = jnp.repeat(k, G, axis=1)
    vr = jnp.repeat(v, G, axis=1)
    s = jnp.einsum(
        "qhd,khd->hqk", q.astype(jnp.float32), kr.astype(jnp.float32)
    ) * scale
    if causal:
        sq, skv = q.shape[0], k.shape[0]
        rows = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 1)
        s = jnp.where((rows >= cols)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, vr.astype(jnp.float32))


def _qkv(sq=256, h=8, h_kv=2, dh=32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(sq, h, dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(sq, h_kv, dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(sq, h_kv, dh)), jnp.float32)
    return q, k, v


class TestKernelGQA:
    @pytest.mark.parametrize("h_kv", [1, 2, 4, 8])
    def test_forward_matches_oracle(self, h_kv):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv(h_kv=h_kv)
        scale = 1 / np.sqrt(q.shape[-1])
        o = flash_attention(
            q, k, v, scale=scale, block_q=64, block_kv=64, interpret=True
        )
        want = _oracle(q, k, v, scale)
        assert float(jnp.max(jnp.abs(o - want))) < 1e-5

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_oracle(self, causal):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv()
        scale = 1 / np.sqrt(q.shape[-1])

        def f(q, k, v):
            return flash_attention(
                q, k, v, scale=scale, block_q=64, block_kv=64,
                interpret=True, causal=causal,
            ).sum()

        def f0(q, k, v):
            return _oracle(q, k, v, scale, causal).sum()

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f0, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", got, want):
            assert a.shape == b.shape
            err = float(jnp.max(jnp.abs(a - b)))
            assert err < 2e-5, f"d{name}: {err:.2e}"
        # dk/dv come back with kv-head shape — the group sum happened
        assert got[1].shape == k.shape

    def test_non_causal_forward(self):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv(h_kv=8)  # MHA, pure causal-flag test
        scale = 1 / np.sqrt(q.shape[-1])
        o = flash_attention(
            q, k, v, scale=scale, block_q=64, block_kv=64,
            interpret=True, causal=False,
        )
        want = _oracle(q, k, v, scale, causal=False)
        assert float(jnp.max(jnp.abs(o - want))) < 1e-5

    def test_indivisible_heads_rejected(self):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv(h=8, h_kv=3)
        with pytest.raises(ValueError, match="GQA"):
            flash_attention(
                q, k, v, scale=0.1, block_q=64, block_kv=64, interpret=True
            )

    @pytest.mark.parametrize("d", [2, 4])
    def test_ring_flash_gqa_matches_full(self, d):
        """ring_flash_attention with kv-head-width chunks: forward and
        grads vs the single-device full-sequence oracle (plain interpret
        mode, test_flash_grad.py's pattern — the ring uses ppermute, not
        RDMA, so the distributed interpreter isn't needed)."""
        from jax.sharding import PartitionSpec as P

        from ddlb_tpu.ops.flash_attention import ring_flash_attention

        S, h, h_kv, dh = 16 * d, 2, 1, 8
        rng = np.random.default_rng(11)
        q = jnp.asarray(rng.normal(size=(S, h, dh)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(S, h_kv, dh)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(S, h_kv, dh)), jnp.float32)
        scale = 1 / np.sqrt(dh)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))

        def ring(q, k, v):
            body = lambda q, k, v: ring_flash_attention(
                q, k, v, axis_name="tp", axis_size=d, scale=scale,
                block_q=8, block_kv=8, interpret=True,
            )
            return jax.shard_map(
                body, mesh=mesh, in_specs=(P("tp"),) * 3,
                out_specs=P("tp"), check_vma=False,
            )(q, k, v)

        o_ring = ring(q, k, v)
        o_ref = _oracle(q, k, v, scale)
        np.testing.assert_allclose(
            np.asarray(o_ref), np.asarray(o_ring), rtol=0, atol=1e-5
        )
        got = jax.jit(
            jax.grad(lambda q, k, v: ring(q, k, v).sum(), argnums=(0, 1, 2))
        )(q, k, v)
        want = jax.grad(
            lambda q, k, v: _oracle(q, k, v, scale).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for name, a, b in zip("qkv", got, want):
            assert a.shape == b.shape
            err = float(jnp.max(jnp.abs(np.asarray(a) - np.asarray(b))))
            assert err < 2e-5, f"d{name}: {err:.2e}"

    def test_ring_rejects_indivisible_heads(self):
        from ddlb_tpu.ops.flash_attention import ring_flash_attention

        q, k, v = _qkv(h=8, h_kv=3)
        with pytest.raises(ValueError, match="GQA"):
            ring_flash_attention(
                q, k, v, axis_name="tp", axis_size=2, scale=0.1,
            )


class TestModelGQA:
    def _cfg(self, **kw):
        from ddlb_tpu.models.transformer import TransformerConfig

        base = dict(
            vocab=64, d_model=64, n_heads=8, n_kv_heads=2, d_ff=64,
            layers_per_stage=1, microbatches=2,
        )
        base.update(kw)
        return TransformerConfig(**base)

    @pytest.mark.parametrize("attn_kernel", ["einsum", "flash"])
    def test_train_matches_oracle(self, attn_kernel):
        from ddlb_tpu.models.transformer import (
            example_tokens,
            init_params,
            make_loss_fn,
            reference_loss,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp", "pp"), shape=(2, 2, 2))
        cfg = self._cfg(attn_kernel=attn_kernel)
        params = init_params(cfg, pp=2, n_experts=2)
        tokens, targets = example_tokens(4, 16, cfg.vocab)
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss_fn, sh = make_loss_fn(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        tok = jax.device_put(tokens, sh["data"])
        tgt = jax.device_put(targets, sh["data"])
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(p, tok, tgt)
        assert abs(float(loss) - want) < 1e-5
        assert float(np.max(np.abs(np.asarray(grads["w_kv"])))) > 0

    def test_cache_shrinks_and_decode_consistent(self):
        from ddlb_tpu.models.decode import (
            init_cache,
            make_decode_fn,
            make_prefill_fn,
            reference_logits,
        )
        from ddlb_tpu.models.transformer import (
            example_tokens,
            init_params,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        cfg = self._cfg(attn_kernel="einsum", microbatches=1)
        B, S0 = 8, 8
        params = init_params(cfg, pp=1, n_experts=2)
        cache = init_cache(cfg, B, S0 + 1, mesh=mesh)
        assert cache["k"].shape[3] == 2  # kv heads, not 8: 4x smaller
        prompt, _ = example_tokens(B, S0, cfg.vocab)
        prefill, sh = make_prefill_fn(mesh, cfg)
        decode, _ = make_decode_fn(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        logits, cache = jax.jit(prefill)(p, cache, prompt)
        want = reference_logits(params, prompt, cfg, tp=2, dp=4)
        assert float(np.max(np.abs(np.asarray(logits) - np.asarray(want)))) < 1e-4
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        logits2, cache = jax.jit(decode)(p, cache, nxt, S0)
        toks2 = np.concatenate(
            [np.asarray(prompt), np.asarray(nxt)[:, None]], 1
        )
        want2 = reference_logits(params, toks2, cfg, tp=2, dp=4)
        assert float(np.max(np.abs(np.asarray(logits2) - np.asarray(want2)))) < 1e-4

    def test_transformer_step_sweeps_n_kv_heads(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_gqa",
                "base_implementation": "spmd",
                "options": {
                    "batch": 4, "vocab": 64, "n_heads": 8, "n_kv_heads": 2,
                    "microbatches": 2, "attn_kernel": "einsum",
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    def test_transformer_decode_sweeps_n_kv_heads(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_gqa",
                "base_implementation": "spmd",
                "options": {
                    "batch": 8, "vocab": 64, "n_heads": 8, "n_kv_heads": 2,
                    "phase": "decode", "attn_kernel": "einsum",
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    @pytest.mark.parametrize("attn_kernel", ["einsum", "flash"])
    def test_ring_attention_gqa_matches_oracle(self, attn_kernel):
        """Context-parallel GQA: the ring ships kv-head-width chunks;
        loss must still match the full-attention oracle."""
        from ddlb_tpu.models.transformer import (
            example_tokens,
            init_params,
            make_loss_fn,
            reference_loss,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp", "pp"), shape=(2, 2, 2))
        cfg = self._cfg(attention="ring", attn_kernel=attn_kernel)
        params = init_params(cfg, pp=2, n_experts=2)
        tokens, targets = example_tokens(4, 16, cfg.vocab)
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss_fn, sh = make_loss_fn(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        tok = jax.device_put(tokens, sh["data"])
        tgt = jax.device_put(targets, sh["data"])
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(p, tok, tgt)
        assert abs(float(loss) - want) < 1e-5
        assert float(np.max(np.abs(np.asarray(grads["w_kv"])))) > 0
