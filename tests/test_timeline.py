"""Cross-rank timeline observatory (ISSUE 14): the clock-offset
estimator, the row skew fold, the world-timeline builder, and the skew
regression gate. Everything here is synthetic-clock math — no JAX, no
launched worlds (test_multiprocess covers the live path; the demo
``scripts/skew_demo.py`` is the end-to-end acceptance)."""

from __future__ import annotations

import json
import os
import random

import pytest

from ddlb_tpu.observatory import regress, timeline
from ddlb_tpu.telemetry import clocksync


def _synthetic_spans(n, delta, rng, width_s=0.004, start=100.0, gap=0.15):
    """(ref_spans, shifted_spans): n rendezvous exchanges observed by a
    reference clock and by a clock offset by ``delta`` seconds."""
    ref, shifted = [], []
    t = start
    for _ in range(n):
        t += gap + rng.uniform(0.0, gap)
        w0, e0 = rng.uniform(0, width_s), rng.uniform(0, width_s)
        w1, e1 = rng.uniform(0, width_s), rng.uniform(0, width_s)
        ref.append((t - w0, t + e0))
        shifted.append((t - w1 + delta, t + e1 + delta))
    return ref, shifted


class TestOffsetEstimator:
    def test_recovers_synthetic_offset_within_uncertainty(self):
        rng = random.Random(7)
        for delta in (-2.25, 0.0, 3.7, 120.0):
            ref, shifted = _synthetic_spans(10, delta, rng)
            fit = clocksync.fit_offsets({0: ref, 1: shifted})[1]
            assert fit.n_exchanges == 10
            assert abs(fit.offset_s - delta) <= fit.uncertainty_s
            # the bound is conservative but must stay usefully tight
            # against millisecond-scale exchange widths
            assert fit.uncertainty_s < 0.1
            # aligned midpoints coincide within the bound
            mid = sum(shifted[3]) / 2.0
            ref_mid = sum(ref[3]) / 2.0
            assert abs(fit.align(mid) - ref_mid) <= fit.uncertainty_s

    def test_reference_rank_is_identity(self):
        rng = random.Random(1)
        ref, shifted = _synthetic_spans(4, 5.0, rng)
        fits = clocksync.fit_offsets({0: ref, 1: shifted})
        assert fits[0].offset_s == 0.0
        assert fits[0].uncertainty_s == 0.0
        assert fits[0].align(123.0) == 123.0

    def test_drift_fit_recovers_slope(self):
        rng = random.Random(3)
        drift = 2e-4  # 200 us/s — visible over a 20 s window
        ref, shifted = [], []
        t = 50.0
        for _ in range(24):
            t += 1.0
            w = rng.uniform(0, 0.002)
            off = 1.5 + drift * (t - 50.0)
            ref.append((t - w, t + w))
            shifted.append((t - w + off, t + w + off))
        fit = clocksync.fit_offsets({0: ref, 1: shifted})[1]
        assert fit.drift_per_s == pytest.approx(drift, rel=0.2)
        # a late stamp aligns within the bound despite the drift
        local = shifted[-1][1]
        assert abs(fit.align(local) - ref[-1][1]) <= fit.uncertainty_s

    def test_robust_to_one_skewed_exchange(self):
        """One exchange where a rank genuinely arrived late (a real
        straggler) must not drag the offset: the median absorbs it."""
        rng = random.Random(5)
        ref, shifted = _synthetic_spans(9, 2.0, rng)
        # exchange 4: the shifted rank arrives 0.5s late — its span
        # starts late, the ref rank's span starts early and waits
        b, e = shifted[4]
        shifted[4] = (b + 0.5, e + 0.5)
        rb, re_ = ref[4]
        ref[4] = (rb - 0.0, re_ + 0.5)
        fit = clocksync.fit_offsets({0: ref, 1: shifted})[1]
        assert abs(fit.offset_s - 2.0) < 0.05

    def test_empty_and_missing_rank_spans(self):
        fits = clocksync.fit_offsets({0: [], 1: []})
        assert fits[1].uncertainty_s == float("inf")
        assert clocksync.fit_offsets({}) == {}


class TestRowSkewFold:
    def test_pure_fold_attributes_injected_straggler(self):
        """Rank 1's clock is offset by 5s AND it arrives 0.4s late at
        one collective: the fold must align the clocks away and blame
        exactly the injected lateness."""
        delta = 5.0
        sites, enters, exits = [], [[], []], [[], []]
        t = 10.0
        for j in range(8):
            t += 0.1
            late = 0.4 if j == 5 else 0.0
            sites.append(
                "runtime.collective" if j == 5 else "runtime.barrier"
            )
            enters[0].append(t)
            exits[0].append(t + late + 0.005)
            enters[1].append(t + late + delta)
            exits[1].append(t + late + 0.005 + delta)
        out = clocksync.skew_from_spans(sites, enters, exits)
        assert out["straggler_rank"] == 1
        assert out["skew_enter_s"] == pytest.approx(0.4, abs=0.02)
        assert out["straggler_frac"] > 0.5
        assert out["clock_unc_s"] < 0.05

    def test_fold_without_fit_sites_never_fits_from_skewed_spans(self):
        """No barrier exchange in the row: the fold must NOT fit
        offsets from the skew-bearing collectives themselves (that
        would absorb half an injected slowdown into the clock model) —
        raw stamps are used and clock_unc_s honestly claims nothing."""
        import math

        sites = ["runtime.collective"]
        out = clocksync.skew_from_spans(
            sites, [[10.0], [10.4]], [[10.41], [10.41]]
        )
        assert out["skew_enter_s"] == pytest.approx(0.4)
        assert out["straggler_rank"] == 1
        assert math.isnan(out["clock_unc_s"])

    def test_fold_declines_single_exchange_fit(self):
        """One barrier exchange is not a clock model: a rank 0.4 s late
        at the ONLY barrier would otherwise become a +0.2 s 'offset'
        that halves the real skew and shifts blame onto the innocent
        peer at the next collective. Below MIN_FIT_EXCHANGES the fold
        must keep raw stamps and attribute the full skew."""
        import math

        sites = ["runtime.barrier", "runtime.collective"]
        enters = [[10.0, 11.0], [10.4, 11.0]]
        exits = [[10.41, 11.1], [10.41, 11.1]]
        out = clocksync.skew_from_spans(sites, enters, exits)
        assert out["skew_enter_s"] == pytest.approx(0.4)
        assert out["straggler_rank"] == 1
        assert math.isnan(out["clock_unc_s"])

    def test_fold_zero_skew_names_no_straggler(self):
        sites = ["runtime.barrier"] * 3
        enters = [[1.0, 2.0, 3.0], [1.0, 2.0, 3.0]]
        exits = [[1.1, 2.1, 3.1], [1.1, 2.1, 3.1]]
        out = clocksync.skew_from_spans(sites, enters, exits)
        assert out["straggler_rank"] == -1
        assert out["skew_enter_s"] == 0.0
        assert out["straggler_frac"] == 0.0

    def test_fold_single_rank_is_defaults(self):
        out = clocksync.skew_from_spans(
            ["runtime.barrier"], [[1.0]], [[1.5]]
        )
        assert out == clocksync.SKEW_ROW_DEFAULTS

    def test_fold_row_skew_single_process_defaults(self):
        class _Rt:
            num_processes = 1

        clocksync.record_span("runtime.barrier", 1.0, 2.0)
        try:
            assert clocksync.fold_row_skew(_Rt()) == (
                clocksync.SKEW_ROW_DEFAULTS
            )
        finally:
            clocksync.reset_row()

    def test_span_log_reset_and_bound(self):
        clocksync.reset_row()
        clocksync.record_span("runtime.barrier", 1.0, 2.0)
        clocksync.record_span("runtime.collective", 3.0, 4.0)
        assert [s[0] for s in clocksync.row_spans()] == [
            "runtime.barrier", "runtime.collective",
        ]
        clocksync.reset_row()
        assert clocksync.row_spans() == []


def _write_flight(run_dir, rank, events):
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, f"flight-p{rank}.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        for event in events:
            f.write(json.dumps(event) + "\n")


def _world_events(rank, offset, barriers, late_at=None, late_s=0.0,
                  i_am_late=False, pid=1000):
    """One rank's flight stream: init + phase marks + barriers + one
    runtime.collective, stamps on a clock shifted by ``offset``.

    Rendezvous semantics when ``late_at`` names a barrier index: the
    late rank (``i_am_late``) ENTERS ``late_s`` after everyone else;
    every rank EXITS at the release (late arrival + 0.01), and the rest
    of the world's timeline shifts by ``late_s`` — exactly what a real
    single-rank stall does to a lock-step world.
    """
    seq = 0
    events = []

    def emit(ph, site, t, **extra):
        events.append(
            {"seq": seq, "ph": ph, "site": site, "t": t + offset,
             "pid": pid + rank, "rank": rank, **extra}
        )

    t = 100.0
    seq += 1
    emit("B", "runtime.init", t)
    emit("E", "runtime.init", t + 0.3)
    t += 0.4
    seq += 1
    emit("I", "worker.phase", t, stage="setup begin (x)")
    t += 0.05
    seq += 1
    emit("I", "worker.phase", t, stage="warmup done; measuring")
    for j in range(barriers):
        t += 0.1
        late = late_s if late_at == j else 0.0
        enter = t + (late if i_am_late else 0.0)
        release = t + late
        seq += 1
        emit("B", "runtime.barrier", enter)
        emit("E", "runtime.barrier", release + 0.01)
        t = release + 0.01
    t += 0.05
    seq += 1
    emit("B", "runtime.collective", t)
    emit("E", "runtime.collective", t + 0.02)
    t += 0.1
    seq += 1
    emit("I", "worker.phase", t, stage="measured")
    return events


def _paired_world(tmp_path, late_rank=None, late_s=0.0, offset1=50.0):
    """A 2-rank flight dir: sequence-aligned collectives, rank 1's
    clock shifted by ``offset1``, optionally one rank 0.?s late at
    barrier index 3 (the other rank waits there)."""
    run_dir = str(tmp_path / "flight")
    late_at = 3 if late_rank is not None else None
    for rank in range(2):
        _write_flight(
            run_dir,
            rank,
            _world_events(
                rank,
                offset1 if rank == 1 else 0.0,
                6,
                late_at=late_at,
                late_s=late_s,
                i_am_late=rank == late_rank,
            ),
        )
    return run_dir


class TestWorldTimeline:
    def test_aligns_known_offset_and_flags_mode(self, tmp_path):
        run_dir = _paired_world(tmp_path, offset1=50.0)
        doc = timeline.build_world_timeline(run_dir, expected_ranks=2)
        assert doc["alignment"] == "barrier"
        fit = doc["offsets"][1]
        assert abs(fit["offset_s"] - 50.0) <= fit["uncertainty_s"]
        assert fit["uncertainty_s"] < 0.5
        # aligned events: the two ranks' barrier entries coincide
        barriers = [
            e for e in doc["events"] if e["site"] == "runtime.barrier"
        ]
        by_seq = {}
        for e in barriers:
            by_seq.setdefault(e["seq"], []).append(e)
        for seq, pair in by_seq.items():
            if len(pair) == 2:
                assert abs(
                    pair[0]["aligned_ts"] - pair[1]["aligned_ts"]
                ) <= max(p["unc_s"] for p in pair) + 0.02

    def test_attributes_seeded_straggler_to_rank(self, tmp_path):
        run_dir = _paired_world(tmp_path, late_rank=1, late_s=0.5)
        doc = timeline.build_world_timeline(run_dir, expected_ranks=2)
        assert doc["total_skew_s"] == pytest.approx(0.5, abs=0.1)
        assert doc["worst_ranks"][0]["rank"] == 1
        worst = max(
            doc["collectives"], key=lambda c: c["skew_enter_s"]
        )
        assert worst["straggler_rank"] == 1
        assert worst["site"] == "runtime.barrier"
        assert worst["skew_enter_s"] == pytest.approx(0.5, abs=0.05)
        # the WAITING rank (0) accrues the skew-wait seconds
        assert doc["attribution"][0]["skew_wait_s"] == pytest.approx(
            0.5, abs=0.1
        )
        assert "rank 1" in doc["headline"]

    def test_attribution_splits_compute_and_host(self, tmp_path):
        run_dir = _paired_world(tmp_path)
        doc = timeline.build_world_timeline(run_dir, expected_ranks=2)
        acc = doc["attribution"][0]
        # gaps between the measuring-window barriers are compute; the
        # init->first-barrier gap (setup) is host
        assert acc["compute_s"] > 0.0
        assert acc["host_s"] > 0.0

    def test_empty_dir_and_missing_rank(self, tmp_path):
        doc = timeline.build_world_timeline(str(tmp_path / "nope"))
        assert doc["alignment"] == "none"
        assert "no flight files" in doc["headline"]
        run_dir = str(tmp_path / "half")
        _write_flight(run_dir, 0, _world_events(0, 0.0, 2))
        doc = timeline.build_world_timeline(run_dir, expected_ranks=2)
        assert doc["missing_ranks"] == [1]
        assert doc["alignment"] == "none"  # nothing to exchange against

    def test_flight_report_json_carries_aligned_entries(
        self, tmp_path, capsys
    ):
        from scripts.flight_report import main as flight_main

        run_dir = _paired_world(tmp_path)
        rc = flight_main([run_dir, "--ranks", "2", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["alignment"] == "barrier"
        assert doc["entries"], "every flight entry must be in the doc"
        for entry in doc["entries"]:
            assert "aligned_ts" in entry and "unc_s" in entry

    def test_json_documents_stay_strictly_valid(self, tmp_path, capsys):
        """An unalignable world carries inf/NaN sentinels internally;
        the --json renderers must never leak them as bare Infinity
        (invalid under RFC 8259 — jq/JSON.parse reject the document)."""
        from scripts.flight_report import main as flight_main

        run_dir = str(tmp_path / "half")
        _write_flight(run_dir, 0, _world_events(0, 0.0, 2))
        flight_main([run_dir, "--ranks", "2", "--json"])
        out = capsys.readouterr().out
        assert "Infinity" not in out and "NaN" not in out
        json.loads(out)
        assert timeline.json_safe(
            {"x": float("inf"), "y": [float("nan"), 1.0]}
        ) == {"x": None, "y": [None, 1.0]}


def _skew_row(run, frac, skew_s, rank=1, impl="jax_spmd_0"):
    return {
        "implementation": impl,
        "base_implementation": "jax_spmd",
        "primitive": "tp_columnwise",
        "option": "-",
        "m": 64, "n": 32, "k": 32,
        "dtype": "float32",
        "chip": "cpu-sim",
        "world_size": 2,
        "time_measurement_backend": "host_clock",
        "median time (ms)": 1.0,
        "straggler_frac": frac,
        "skew_enter_s": skew_s,
        "straggler_rank": rank,
        "_run": run,
    }


def _bank(rows):
    return [
        {"key": regress.row_key(row), "run_id": row["_run"], "kind": "row",
         "row": row}
        for row in rows
    ]


class TestSkewGate:
    def test_seeded_straggler_detected_and_ranked_first(self):
        history = _bank(
            [
                _skew_row("clean-0", 0.15, 0.008, rank=0),
                _skew_row("clean-1", 0.22, 0.012, rank=1),
            ]
        )
        current = [_skew_row("seeded", 0.88, 0.41, rank=1)]
        findings = regress.detect_skew(
            current, history, exclude_run="seeded"
        )
        assert findings, "the seeded straggler must be flagged"
        assert findings[0]["metric"] in ("straggler_frac", "skew_enter_s")
        assert findings[0]["straggler_rank"] == 1
        # detect_all merges the skew gate into the one ranked report
        merged = regress.detect_all(current, history, exclude_run="seeded")
        assert any(
            f["metric"] in ("straggler_frac", "skew_enter_s")
            for f in merged
        )

    def test_clean_jitter_never_alarms(self):
        """Clean-run scheduler jitter — small absolute values moving by
        large RATIOS — must stay below the absolute floors."""
        history = _bank(
            [
                _skew_row("clean-0", 0.10, 0.004),
                _skew_row("clean-1", 0.18, 0.009),
            ]
        )
        current = [_skew_row("clean-2", 0.27, 0.02)]
        assert regress.detect_skew(
            current, history, exclude_run="clean-2"
        ) == []

    def test_zero_baseline_yields_finite_ratio(self):
        """A perfectly clean baseline (median 0.0 skew) against a real
        regression: the finding must fire with a FINITE ratio (these
        documents ship through --json; bare Infinity is invalid)."""
        import math

        history = _bank(
            [
                _skew_row("clean-0", 0.0, 0.0),
                _skew_row("clean-1", 0.0, 0.0),
            ]
        )
        current = [_skew_row("seeded", 0.9, 0.45, rank=1)]
        findings = regress.detect_skew(
            current, history, exclude_run="seeded"
        )
        assert findings
        assert all(math.isfinite(f["ratio"]) for f in findings)

    def test_unalignable_row_never_alarms_on_skew_seconds(self):
        """clock_unc_s NaN = the fold made no alignment claim (raw
        possibly-cross-host stamps): skew_enter_s findings drop; a
        finite bound drops only excesses inside it."""
        history = _bank(
            [
                _skew_row("clean-0", 0.02, 0.005),
                _skew_row("clean-1", 0.03, 0.008),
            ]
        )
        seeded = _skew_row("seeded", 0.03, 5.0, rank=1)
        seeded["clock_unc_s"] = float("nan")
        findings = regress.detect_skew(
            [seeded], history, exclude_run="seeded"
        )
        assert all(f["metric"] != "skew_enter_s" for f in findings)
        # finite bound larger than the excess: also dropped
        seeded["clock_unc_s"] = 10.0
        findings = regress.detect_skew(
            [seeded], history, exclude_run="seeded"
        )
        assert all(f["metric"] != "skew_enter_s" for f in findings)
        # tight bound: the finding stands and carries the bound
        seeded["clock_unc_s"] = 0.001
        findings = regress.detect_skew(
            [seeded], history, exclude_run="seeded"
        )
        assert any(f["metric"] == "skew_enter_s" for f in findings)

    def test_rows_without_skew_columns_contribute_nothing(self):
        row = _skew_row("clean-0", float("nan"), float("nan"))
        history = _bank([_skew_row("clean-1", 0.1, 0.01)])
        assert regress.detect_skew([row], history) == []
