"""DPAllReduce (data-parallel GEMM+AR) validation on the CPU mesh.

The output is replicated: every addressable shard must equal the full
single-device product (the layout an optimizer step consumes).
"""

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 96, 64, 128  # k % 8 == 0; m deliberately not divisible by 8*s


def _check_replicated(impl, result):
    assert result.shape == (M, N)
    # replicated: every shard is the full array
    shard_shapes = {s.data.shape for s in result.addressable_shards}
    assert shard_shapes == {(M, N)}
    assert impl.validate(result)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("strategy", ["all_reduce", "rs_ag"])
def test_jax_spmd(dtype, strategy):
    cls = load_impl_class("dp_allreduce", "jax_spmd")
    impl = cls(M, N, K, dtype=dtype, strategy=strategy)
    _check_replicated(impl, impl.run())


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_xla_gspmd(dtype):
    cls = load_impl_class("dp_allreduce", "xla_gspmd")
    impl = cls(M, N, K, dtype=dtype)
    _check_replicated(impl, impl.run())


@pytest.mark.parametrize("size", ["sharded", "unsharded"])
def test_compute_only(size):
    cls = load_impl_class("dp_allreduce", "compute_only")
    impl = cls(M, N, K, dtype="float32", size=size)
    result = impl.run()
    assert impl.validate(result)
    if size == "unsharded":
        assert result.shape == (M, N)


@pytest.mark.parametrize("algorithm", ["default", "coll_pipeline", "p2p_pipeline"])
def test_overlap_algorithms(algorithm):
    cls = load_impl_class("dp_allreduce", "overlap")
    impl = cls(M, N, K, dtype="float32", algorithm=algorithm, s=4)
    _check_replicated(impl, impl.run())


def test_overlap_p2p_bidirectional():
    cls = load_impl_class("dp_allreduce", "overlap")
    impl = cls(
        128, N, K, dtype="float32",
        algorithm="p2p_pipeline", direction="bidirectional",
    )
    result = impl.run()
    assert result.shape == (128, N)
    assert impl.validate(result)


def test_overlap_matches_jax_spmd():
    """Ring all-reduce vs one-shot psum on identical seeded inputs."""
    m2 = 128  # divisible by the 8-device ring
    spmd = load_impl_class("dp_allreduce", "jax_spmd")(m2, N, K, dtype="float32")
    ring = load_impl_class("dp_allreduce", "overlap")(
        m2, N, K, dtype="float32", algorithm="p2p_pipeline"
    )
    np.testing.assert_allclose(
        np.asarray(spmd.run()), np.asarray(ring.run()), atol=1e-4
    )


def test_int32_exact():
    cls = load_impl_class("dp_allreduce", "jax_spmd")
    impl = cls(M, N, K, dtype="int32")
    assert impl.validate(impl.run())


def test_shape_constraints():
    cls = load_impl_class("dp_allreduce", "jax_spmd")
    with pytest.raises(ValueError, match="k="):
        cls(M, N, K + 1)
    with pytest.raises(ValueError, match="strategy=rs_ag"):
        cls(M + 1, N, K, strategy="rs_ag")
    ov = load_impl_class("dp_allreduce", "overlap")
    with pytest.raises(ValueError, match="coll_pipeline"):
        ov(M + 1, N, K, algorithm="coll_pipeline", s=8)
    with pytest.raises(ValueError, match="p2p_pipeline"):
        ov(M + 4, N, K, algorithm="p2p_pipeline")
    with pytest.raises(ValueError, match="Unknown option"):
        cls(M, N, K, bogus=1)
    with pytest.raises(ValueError, match="strategy"):
        cls(M, N, K, strategy="tree")


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_pallas_xla_collective(dtype):
    cls = load_impl_class("dp_allreduce", "pallas")
    impl = cls(M, N, K, dtype=dtype, block_m=128, block_n=128, block_k=128)
    _check_replicated(impl, impl.run())


@pytest.mark.parametrize("detect_races", [False, True])
def test_pallas_ring_rdma(detect_races):
    """The RDMA ring GEMM+RS kernel composed with an all-gather forms the
    replicated all-reduce; validated under the distributed interpreter
    (with the race detector on in one case)."""
    cls = load_impl_class("dp_allreduce", "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="ring_rdma", block_n=128, block_k=128,
        detect_races=detect_races,
    )
    result = impl.run()
    assert result.shape == (128, 128)
    assert {s.data.shape for s in result.addressable_shards} == {(128, 128)}
    assert impl.validate(result)


def test_pallas_option_constraints():
    cls = load_impl_class("dp_allreduce", "pallas")
    with pytest.raises(ValueError, match="ring_rdma"):
        cls(M + 1, N, K, algorithm="ring_rdma")  # m % d != 0
    with pytest.raises(ValueError, match="no effect"):
        cls(128, N, K, algorithm="ring_rdma", block_m=256)
    with pytest.raises(ValueError, match="no effect"):
        cls(M, N, K, detect_races=True)
