"""Flagship MoE transformer: manual-SPMD (dp, tp, pp) train step vs the
single-device oracle, and descent over a few steps."""

import numpy as np
import pytest

import jax

from ddlb_tpu.models.transformer import (
    TransformerConfig,
    example_tokens,
    init_params,
    make_train_step,
    reference_loss,
)

CFG = TransformerConfig(
    vocab=64, d_model=32, n_heads=4, d_ff=64, layers_per_stage=1, microbatches=2
)


def _setup(dp, tp, pp, lr=1e-2, cfg=CFG):
    mesh = jax.make_mesh((dp, tp, pp), ("dp", "tp", "pp"))
    train_step, init_opt, shardings = make_train_step(mesh, cfg, lr)
    params = init_params(cfg, pp, n_experts=tp)
    params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    opt_state = init_opt(params)
    tokens, targets = example_tokens(dp * cfg.microbatches, 8 * tp, cfg.vocab)
    tokens = jax.device_put(tokens, shardings["data"])
    targets = jax.device_put(targets, shardings["data"])
    return train_step, params, opt_state, tokens, targets


@pytest.mark.parametrize("dp,tp,pp", [(2, 2, 2), (1, 2, 4)])
def test_matches_single_device_oracle(dp, tp, pp):
    train_step, params, opt_state, tokens, targets = _setup(dp, tp, pp)
    host_params = init_params(CFG, pp, n_experts=tp)
    expected = float(
        reference_loss(
            host_params,
            np.asarray(tokens),
            np.asarray(targets),
            CFG,
            tp=tp,
            dp=dp,
        )
    )
    _, _, loss = train_step(params, opt_state, tokens, targets)
    assert np.isclose(float(loss), expected, rtol=0, atol=1e-4), (
        float(loss),
        expected,
    )


def test_descends():
    train_step, params, opt_state, tokens, targets = _setup(2, 2, 2, lr=3e-2)
    shard = tokens.sharding
    losses = []
    for _ in range(6):
        tok = jax.device_put(np.asarray(tokens), shard)
        tgt = jax.device_put(np.asarray(targets), shard)
        params, opt_state, loss = train_step(params, opt_state, tok, tgt)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_ring_attention_matches_oracle():
    """Context-parallel (ring) attention computes the exact same function:
    the single-device oracle needs no changes."""
    import dataclasses

    cfg = dataclasses.replace(CFG, attention="ring")
    train_step, params, opt_state, tokens, targets = _setup(2, 2, 2, cfg=cfg)
    host_params = init_params(cfg, 2, n_experts=2)
    expected = float(
        reference_loss(
            host_params,
            np.asarray(tokens),
            np.asarray(targets),
            cfg,
            tp=2,
            dp=2,
        )
    )
    _, _, loss = train_step(params, opt_state, tokens, targets)
    assert np.isclose(float(loss), expected, rtol=0, atol=1e-4)


@pytest.mark.slow  # four sequential ring-attention train steps (~18 s,
# dominated by the ring train_step compile) for a descent smoke the
# oracle-parity test above already implies — outside the tier-1 870 s
# budget; exact ring-vs-oracle equality stays in-tier
def test_ring_attention_descends():
    import dataclasses

    cfg = dataclasses.replace(CFG, attention="ring")
    train_step, params, opt_state, tokens, targets = _setup(
        2, 2, 2, lr=3e-2, cfg=cfg
    )
    shard = tokens.sharding
    losses = []
    for _ in range(4):
        tok = jax.device_put(np.asarray(tokens), shard)
        tgt = jax.device_put(np.asarray(targets), shard)
        params, opt_state, loss = train_step(params, opt_state, tok, tgt)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_degenerate_axes():
    """tp=1 (no sp/ep peers) and pp=1 (no pipeline) still run and match."""
    train_step, params, opt_state, tokens, targets = _setup(8, 1, 1)
    host_params = init_params(CFG, 1, n_experts=1)
    expected = float(
        reference_loss(
            host_params,
            np.asarray(tokens),
            np.asarray(targets),
            CFG,
            tp=1,
            dp=8,
        )
    )
    _, _, loss = train_step(params, opt_state, tokens, targets)
    assert np.isclose(float(loss), expected, rtol=0, atol=1e-4)
