"""Calibration observatory (ISSUE 17): fitter recovery, gate 3, drift.

Three layers, mirroring the acceptance criteria:

- stdlib-only fitter tests: synthetic banked histories with KNOWN
  injected constants — the IRLS-LAD fit must recover them within 10%
  (here: to float precision on clean linear data, with gross outliers
  present for the robustness claim), deterministically;
- gate-3 tests (JAX stubs): calibrated replays of a synthetic bank
  must land within CALIBRATION_RTOL of the banked measured medians
  while the uncalibrated lower bound is demonstrably >20% off;
- drift-gate tests: ``regress.detect_calibration`` fires on a seeded
  2x overhead shift, stays silent on clean replays, and fences
  baselines across ``cal_version`` refits. Plus: the uncalibrated row
  path is byte-identical (defaults only, no cal stamping).
"""

import json
import math
import os

import pytest

from ddlb_tpu.observatory import calibrate, regress, store
from ddlb_tpu.perfmodel import calib

# the injected ground truth every synthetic history below is built from
ALPHA = 5e-4  # dispatch_s
BETA = 1.2e-4  # step_s
GAMMA_ICI = 3e-5  # hop_s[ici]
GAMMA_DCN = 4e-4  # hop_s[dcn]
TRUTH = calib.GroupCalibration(
    chip="cpu-sim",
    backend="host_clock",
    dispatch_s=ALPHA,
    step_s=BETA,
    hop_s={"ici": GAMMA_ICI, "dcn": GAMMA_DCN},
)


def _overhead(census) -> float:
    """The injected linear overhead for one row's census."""
    over = ALPHA + BETA * census["steps"]
    for cls, hops in census["hops"].items():
        over += TRUTH.hop_s[cls] * hops
    return over


def _row(
    family,
    member,
    d,
    predicted_s,
    *,
    option="",
    has_compute=True,
    has_wire=True,
    chunks=None,
    transport="ici",
    measured_s=None,
    m=256,
    n=64,
    k=64,
    **extra,
):
    """One synthetic banked row whose measured median embeds the
    injected constants through the SAME census the fitter derives."""
    census = calib.schedule_census(
        calib.family_op(family, calib._parse_options(option)),
        d,
        has_compute=has_compute,
        has_wire=has_wire,
        chunks=chunks,
        link_class=calib.scope_link_class(transport),
    )
    if measured_s is None:
        measured_s = predicted_s + _overhead(census)
    row = {
        "primitive": family,
        "base_implementation": member,
        "implementation": f"{member}_0",
        "option": option,
        "m": m,
        "n": n,
        "k": k,
        "dtype": "float32",
        "world_size": d,
        "chip": "cpu-sim",
        "time_measurement_backend": "host_clock",
        "median time (ms)": measured_s * 1e3,
        "predicted_s": predicted_s,
        "phase_compute_s": predicted_s * 0.5 if has_compute else 0.0,
        "phase_comm_s": predicted_s * 0.5 if has_wire else 0.0,
        "error": "",
        "quarantined": False,
        "world_degraded": False,
    }
    row.update(extra)
    return row


def _synthetic_rows():
    """A linear-exact history spanning compute-only, GEMM+wire (both
    transports), wire-only and chunked censuses — every constant
    identifiable — plus two gross outliers the LAD fit must shrug off."""
    rows = []
    for d in (2, 4, 8):
        rows.append(_row("dp_allreduce", "jax_spmd", d, 1e-4 * d))
        rows.append(
            _row(
                "collectives", "jax_spmd", d, 5e-5 * d,
                option="op=all_reduce", has_compute=False,
            )
        )
        rows.append(
            _row(
                "collectives", "jax_spmd", d, 8e-5 * d,
                option="op=all_reduce;transport=dcn",
                has_compute=False, transport="dcn",
            )
        )
    rows.append(
        _row("transformer_step", "compute_only", 8, 2e-4, has_wire=False)
    )
    rows.append(
        _row(
            "dp_allreduce", "overlap", 8, 3e-4,
            option="algorithm=chunked;chunk_count=2", chunks=2,
        )
    )
    # gross outliers (a contended host's 10x rows): LAD must not budge
    rows.append(_row("dp_allreduce", "jax_spmd", 4, 1e-4, measured_s=5e-2))
    rows.append(
        _row(
            "collectives", "jax_spmd", 8, 5e-5,
            option="op=all_reduce", has_compute=False, measured_s=8e-2,
        )
    )
    return rows


def _records(rows, run_id="run-a"):
    return [
        {"kind": "row", "run_id": run_id, "key": store.row_key(r), "row": r}
        for r in rows
    ]


class TestFitter:
    def test_recovers_injected_constants_within_10pct(self):
        table = calibrate.calibrate_history(records=_records(_synthetic_rows()))
        assert table is not None
        group = table.group("cpu-sim", "host_clock")
        assert group is not None
        assert group.dispatch_s == pytest.approx(ALPHA, rel=0.10)
        assert group.step_s == pytest.approx(BETA, rel=0.10)
        assert group.hop_s["ici"] == pytest.approx(GAMMA_ICI, rel=0.10)
        assert group.hop_s["dcn"] == pytest.approx(GAMMA_DCN, rel=0.10)
        # fit metadata rides the table
        assert group.rows == len(_synthetic_rows())
        assert group.keys > 0
        assert group.residual_mad_s < 1e-3  # outliers inflate it, bounded

    def test_fit_is_deterministic(self):
        a = calibrate.calibrate_history(records=_records(_synthetic_rows()))
        b = calibrate.calibrate_history(records=_records(_synthetic_rows()))
        assert a.group("cpu-sim") == b.group("cpu-sim")
        assert a.version == b.version

    def test_thin_group_refuses_to_fit(self):
        rows = _synthetic_rows()[:3]
        assert calibrate.calibrate_history(records=_records(rows)) is None

    def test_ineligible_rows_are_excluded(self):
        clean = _synthetic_rows()
        poisoned = clean + [
            _row("dp_allreduce", "jax_spmd", 4, 1e-4,
                 measured_s=1.0, error="worker died"),
            _row("dp_allreduce", "jax_spmd", 4, 1e-4,
                 measured_s=1.0, world_degraded=True),
            _row("serving_load", "static", 8, 1e-4, measured_s=1.0),
        ]
        a = calibrate.calibrate_history(records=_records(clean))
        b = calibrate.calibrate_history(records=_records(poisoned))
        assert a.group("cpu-sim") == b.group("cpu-sim")

    def test_row_features_census_matches_frontend_counts(self):
        # dp_allreduce at d=8: 2(d-1)=14 wire steps + 1 compute step
        feat = calib.row_features(_row("dp_allreduce", "jax_spmd", 8, 1e-4))
        assert feat["steps"] == 15
        assert feat["hops"] == {"ici": 14, "dcn": 0}
        # chunked doubles both
        feat = calib.row_features(
            _row("dp_allreduce", "overlap", 8, 1e-4,
                 option="algorithm=chunked;chunk_count=2", chunks=2)
        )
        assert feat["steps"] == 30
        assert feat["hops"]["ici"] == 28


class TestTable:
    def test_round_trip_and_version(self, tmp_path):
        table = calibrate.calibrate_history(records=_records(_synthetic_rows()))
        path = str(tmp_path / "calib.json")
        calibrate.write_table(table, path)
        loaded = calib.load_table(path)
        assert loaded.version == table.version
        assert loaded.group("cpu-sim") == table.group("cpu-sim")
        # version is a content fingerprint: same constants, same version
        assert table.version == calib.table_version(table.groups)
        moved = {
            key: calib.GroupCalibration(
                chip=g.chip, backend=g.backend,
                dispatch_s=g.dispatch_s * 2, step_s=g.step_s,
                hop_s=g.hop_s, rows=g.rows,
            )
            for key, g in table.groups.items()
        }
        assert calib.table_version(moved) != table.version

    def test_corrupt_table_loads_as_none(self, tmp_path):
        path = tmp_path / "bad.json"
        path.write_text("{not json")
        assert calib.load_table(str(path)) is None

    def test_get_table_env_gated(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DDLB_TPU_CALIB", raising=False)
        assert calib.get_table() is None
        table = calibrate.calibrate_history(records=_records(_synthetic_rows()))
        path = str(tmp_path / "calib.json")
        calibrate.write_table(table, path)
        monkeypatch.setenv("DDLB_TPU_CALIB", path)
        loaded = calib.get_table()
        assert loaded is not None and loaded.version == table.version
        monkeypatch.delenv("DDLB_TPU_CALIB", raising=False)
        assert calib.get_table() is None

    def test_group_lookup_fallback(self):
        g1 = calib.GroupCalibration("v5e", "host_clock", 1e-4, 1e-5, {"ici": 0.0, "dcn": 0.0})
        g2 = calib.GroupCalibration("v5e", "device_loop", 2e-4, 2e-5, {"ici": 0.0, "dcn": 0.0})
        table = calib.make_table({("v5e", "host_clock"): g1, ("v5e", "device_loop"): g2})
        assert table.group("v5e", "device_loop") is g2
        assert table.group("v5e", "unknown") is g1  # host_clock fallback
        assert table.group("v5e") is g1
        assert table.group("v6e") is None


class TestIterHistory:
    def _bank(self, tmp_path):
        directory = str(tmp_path)
        rows = [
            _row("dp_allreduce", "jax_spmd", 4, 1e-4),
            _row("collectives", "jax_spmd", 4, 5e-5, option="op=all_reduce",
                 has_compute=False),
        ]
        rows[1]["chip"] = "v5e"
        for r in rows:
            store.bank_row(r, directory=directory)
        store.bank_row({"metric": "bench", "chip": "cpu-sim"},
                       kind="bench", directory=directory)
        path = store.history_path(directory)
        with open(path, "a", encoding="utf-8") as f:
            # unknown columns from a future schema ride along untouched
            future = dict(rows[0])
            future["column_from_2027"] = "x"
            f.write(json.dumps({"kind": "row", "row": future}) + "\n")
            # a torn tail: a process killed mid-append
            f.write('{"kind": "row", "row": {"chip": "cpu-s')
        return directory

    def test_filters_and_tolerance(self, tmp_path):
        directory = self._bank(tmp_path)
        got = list(store.iter_history(directory))
        assert len(got) == 3  # 2 rows + future-schema row; bench + torn out
        assert list(store.iter_history(directory, chip="v5e"))[0]["row"][
            "primitive"
        ] == "collectives"
        assert len(list(store.iter_history(directory, family="dp_allreduce"))) == 2
        assert len(list(store.iter_history(directory, impl="jax_spmd"))) == 3
        assert len(list(store.iter_history(directory, kind=None))) == 4
        assert len(list(store.iter_history(
            directory, chip=("v5e", "cpu-sim")))) == 3
        assert any(
            r["row"].get("column_from_2027") == "x"
            for r in store.iter_history(directory)
        )

    def test_predicate_and_missing_file(self, tmp_path):
        assert list(store.iter_history(str(tmp_path / "nope"))) == []
        directory = self._bank(tmp_path)
        got = list(
            store.iter_history(
                directory, predicate=lambda rec: rec["row"].get("world_size") == 4
            )
        )
        assert len(got) == 3


class TestGate3:
    """Calibrated replays vs banked medians on real impl stubs."""

    @pytest.fixture(scope="class")
    def bank(self):
        from ddlb_tpu.perfmodel.cost import estimate
        from ddlb_tpu.perfmodel.specs import get_spec
        from ddlb_tpu.simulator.validate import build_stub

        spec = get_spec("cpu-sim")
        rows = []
        for family, member, option, opts in (
            ("dp_allreduce", "jax_spmd", "", {}),
            ("collectives", "jax_spmd", "op=all_reduce", {"op": "all_reduce"}),
            ("tp_columnwise", "jax_spmd", "", {}),
        ):
            for d in (2, 4, 8):
                impl = build_stub(family, member, 256, 64, 64, d,
                                  dtype="float32", **opts)
                est = estimate(impl, spec)
                row = _row(
                    family, member, d, est.predicted_s,
                    option=option,
                    has_compute=est.compute_s > 0.0,
                    has_wire=est.comm_s > 0.0,
                )
                row["phase_compute_s"] = est.compute_s
                row["phase_comm_s"] = est.comm_s
                # measured embeds the constants through the SAME census
                # the fitter will derive from this row's option string
                feat = calib.row_features(row)
                census = {"steps": feat["steps"], "hops": feat["hops"]}
                measured = est.predicted_s + _overhead(census)
                row["median time (ms)"] = measured * 1e3
                rows.append(row)
        return _records(rows)

    def test_calibrated_replay_within_tolerance(self, bank):
        from ddlb_tpu.simulator.validate import calibration_check

        table = calibrate.calibrate_history(records=bank)
        assert table is not None
        summary = calibration_check(records=bank, table=table)
        assert summary["checked"] == 9
        assert summary["violations"] == []
        assert summary["ok"] is True
        assert summary["table_version"] == table.version

    def test_uncalibrated_bound_is_far_off(self, bank):
        """The >20% demonstration: without constants the lower bound
        misses every banked median by a wide margin — the gap the
        calibration exists to close."""
        from ddlb_tpu.perfmodel.topology import flat_topology
        from ddlb_tpu.simulator.engine import replay
        from ddlb_tpu.simulator.frontends import program_from_impl
        from ddlb_tpu.simulator.validate import build_stub, parse_option_string

        for rec in bank:
            row = rec["row"]
            measured = row["median time (ms)"] * 1e-3
            topo = flat_topology(row["world_size"], "cpu-sim")
            impl = build_stub(
                row["primitive"], row["base_implementation"],
                row["m"], row["n"], row["k"], row["world_size"],
                dtype=row["dtype"],
                **parse_option_string(row["option"]),
            )
            sim = replay(program_from_impl(impl, topo), topo).makespan_s
            assert abs(sim - measured) / measured > 0.20

    def test_gate_fails_without_table(self, bank, monkeypatch):
        from ddlb_tpu.simulator.validate import calibration_check

        monkeypatch.delenv("DDLB_TPU_CALIB", raising=False)
        summary = calibration_check(records=bank)
        assert summary["ok"] is False
        assert "no calibration table" in summary["skipped_reasons"]

    def test_gate_catches_seeded_drift(self, bank):
        from ddlb_tpu.simulator.validate import calibration_check

        table = calibrate.calibrate_history(records=bank)
        drifted = []
        for rec in bank:
            row = dict(rec["row"])
            row["median time (ms)"] *= 2.0
            drifted.append({**rec, "row": row})
        summary = calibration_check(records=drifted, table=table)
        assert summary["ok"] is False
        assert summary["violations"]


class TestDriftGate:
    VERSION = "v1-abcdef0123"

    def _calibrated_rows(self, residual, run_id="cur", version=VERSION):
        rows = []
        for d in (4, 8):
            row = _row("dp_allreduce", "jax_spmd", d, 1e-4 * d)
            measured = row["median time (ms)"] * 1e-3
            pcal = measured / (1.0 + residual)
            row["predicted_cal_s"] = pcal
            row["cal_residual_frac"] = (measured - pcal) / pcal
            row["cal_version"] = version
            rows.append(row)
        return rows

    def _history(self, runs=3, residual=0.004):
        records = []
        for i in range(runs):
            records.extend(
                _records(
                    self._calibrated_rows(residual + 0.001 * i),
                    run_id=f"base-{i}",
                )
            )
        return records

    def test_fires_on_seeded_2x_overhead_shift(self):
        history = self._history()
        # a 2x overhead shift: measured doubles against a model that
        # predicted it, residual jumps ~1.0
        current = self._calibrated_rows(1.0)
        findings = regress.detect_calibration(current, history)
        assert findings, "2x drift must fire"
        assert findings[0]["metric"] == "cal_residual_frac"
        assert findings[0]["cal_version"] == self.VERSION
        assert findings[0]["z"] > regress.Z_TOL
        # and it outranks the plain time regression in the merged gate
        merged = regress.detect_all(current, history)
        assert merged[0]["metric"] == "cal_residual_frac"

    def test_silent_on_clean_replays(self):
        history = self._history()
        current = self._calibrated_rows(0.006)
        assert regress.detect_calibration(current, history) == []

    def test_version_fence_resets_baseline(self):
        history = self._history()
        # same huge residuals, but priced against a REFIT table: the
        # old version's baselines must not gate the new model
        current = self._calibrated_rows(1.0, version="v1-ffffffffff")
        assert regress.detect_calibration(current, history) == []

    def test_noop_when_uncalibrated(self):
        history = self._history()
        current = [_row("dp_allreduce", "jax_spmd", 4, 1e-4)]
        for row in current:
            row["cal_residual_frac"] = float("nan")
            row["cal_version"] = ""
        assert regress.detect_calibration(current, history) == []

    def test_prior_fallback_prefers_calibrated(self):
        row = _row("dp_allreduce", "jax_spmd", 4, 1e-6)
        row["median time (ms)"] = 100.0
        row["predicted_cal_s"] = 1e-3
        findings = regress.detect(
            [row], [], prior_factor=regress.PRIOR_FACTOR
        )
        assert findings and findings[0]["prior"] == "calibrated"
        assert findings[0]["baseline_ms"] == pytest.approx(1.0)
        row.pop("predicted_cal_s")
        findings = regress.detect([row], [])
        assert findings and findings[0]["prior"] == "analytical"


class TestUncalibratedPath:
    def test_defaults_registered_and_inert(self, monkeypatch):
        from ddlb_tpu import schema
        from ddlb_tpu.benchmark import PERF_ROW_DEFAULTS

        for column in ("predicted_cal_s", "cal_residual_frac", "cal_version"):
            assert column in schema.ROW_COLUMNS
            assert column in PERF_ROW_DEFAULTS
        assert math.isnan(PERF_ROW_DEFAULTS["predicted_cal_s"])
        assert math.isnan(PERF_ROW_DEFAULTS["cal_residual_frac"])
        assert PERF_ROW_DEFAULTS["cal_version"] == ""

    def test_calibrated_estimate_none_without_table(self, monkeypatch):
        from ddlb_tpu.perfmodel.cost import calibrated_estimate
        from ddlb_tpu.simulator.validate import build_stub

        monkeypatch.delenv("DDLB_TPU_CALIB", raising=False)
        impl = build_stub("dp_allreduce", "jax_spmd", 256, 64, 64, 8)
        assert calibrated_estimate(impl) is None

    def test_replay_without_calibration_is_unchanged(self):
        from ddlb_tpu.perfmodel.cost import estimate
        from ddlb_tpu.perfmodel.specs import get_spec
        from ddlb_tpu.perfmodel.topology import flat_topology
        from ddlb_tpu.simulator.engine import replay
        from ddlb_tpu.simulator.frontends import program_from_impl
        from ddlb_tpu.simulator.validate import build_stub

        impl = build_stub("dp_allreduce", "jax_spmd", 256, 64, 64, 8,
                          dtype="float32")
        topo = flat_topology(8, "cpu-sim")
        program = program_from_impl(impl, topo)
        bare = replay(program, topo)
        explicit = replay(program, topo, calibration=None)
        assert bare.makespan_s == explicit.makespan_s
        assert "calibration" not in bare.meta
        # gate 1 unchanged: the uncalibrated replay still equals the
        # closed form to float precision
        est = estimate(impl, get_spec("cpu-sim"))
        assert bare.makespan_s == pytest.approx(est.predicted_s, rel=1e-9)

    def test_calibrated_closed_form_matches_calibrated_replay(self):
        """The calibrated gate-1 analogue: overhead inflates each phase
        uniformly, so the closed form and the engine agree to float
        precision for sequential, ideal-overlap AND chunked shapes."""
        from ddlb_tpu.perfmodel.cost import calibrated_estimate
        from ddlb_tpu.perfmodel.topology import flat_topology
        from ddlb_tpu.simulator.engine import replay
        from ddlb_tpu.simulator.frontends import program_from_impl
        from ddlb_tpu.simulator.validate import build_stub

        table = calib.make_table({("cpu-sim", "host_clock"): TRUTH})
        for family, member, opts in (
            ("dp_allreduce", "jax_spmd", {}),
            ("dp_allreduce", "overlap",
             {"algorithm": "chunked", "chunk_count": 2}),
            ("tp_columnwise", "overlap", {}),
            ("collectives", "jax_spmd", {}),
        ):
            impl = build_stub(family, member, 256, 64, 64, 8,
                              dtype="float32", **opts)
            topo = flat_topology(8, "cpu-sim")
            closed = calibrated_estimate(
                impl, table=table, backend="host_clock"
            )
            sim = replay(
                program_from_impl(impl, topo), topo, calibration=TRUTH
            )
            assert sim.makespan_s == pytest.approx(
                closed.predicted_cal_s, rel=1e-9
            )
            assert sim.meta["calibration"]["chip"] == "cpu-sim"


# ---------------------------------------------------------------------------
# the KV-handoff fit (ISSUE 19): serving rows, the residual fit's
# excluded slice, feed their own two-constant model
# ---------------------------------------------------------------------------

KV_SETUP = 2e-4   # s per bundle
KV_PER_BYTE = 1.5e-9  # s per byte


def _kv_row(i, *, handoffs=None, nbytes=None, error="", chip="cpu-sim"):
    h = float(handoffs if handoffs is not None else 4 + i)
    b = float(
        nbytes if nbytes is not None else h * (1.0e6 + 2.0e5 * i)
    )
    return {
        "primitive": "serving_load",
        "base_implementation": "disagg",
        "implementation": f"disagg_{i}",
        "option": "-", "m": 16, "n": 64, "k": 128,
        "dtype": "float32", "world_size": 4,
        "chip": chip, "time_measurement_backend": "host_clock",
        "error": error, "quarantined": False, "world_degraded": False,
        "serve_handoffs": h,
        "serve_handoff_bytes": b,
        "serve_handoff_ms": (KV_SETUP * h + KV_PER_BYTE * b) * 1e3,
    }


class TestKVFit:
    def test_features_eligibility(self):
        assert calib.kv_row_features(_kv_row(0)) is not None
        assert calib.kv_row_features(_kv_row(0, error="boom")) is None
        assert calib.kv_row_features(_kv_row(0, handoffs=0)) is None
        no_serve = {k: v for k, v in _kv_row(0).items()
                    if not k.startswith("serve_")}
        assert calib.kv_row_features(no_serve) is None
        wrong_family = dict(_kv_row(0), primitive="tp_columnwise")
        assert calib.kv_row_features(wrong_family) is None

    def test_fit_recovers_injected_constants(self):
        samples = [calib.kv_row_features(_kv_row(i)) for i in range(10)]
        fit = calib.fit_kv_group(samples, min_rows=8)
        assert fit is not None
        setup_s, per_byte_s, rows = fit
        assert rows == 10
        assert setup_s == pytest.approx(KV_SETUP, rel=0.05)
        assert per_byte_s == pytest.approx(KV_PER_BYTE, rel=0.05)

    def test_collinear_bundles_pin_one_constant_nonnegative(self):
        """Every bundle the same size makes count and bytes collinear;
        the active-set rule must keep both constants >= 0 while the
        surviving pair still reproduces the per-row handoff time."""
        rows = [
            _kv_row(i, handoffs=3 + i, nbytes=(3 + i) * 2.0e6)
            for i in range(10)
        ]
        samples = [calib.kv_row_features(r) for r in rows]
        fit = calib.fit_kv_group(samples, min_rows=8)
        assert fit is not None
        setup_s, per_byte_s, _ = fit
        assert setup_s >= 0.0 and per_byte_s >= 0.0
        predicted = setup_s * 7.0 + per_byte_s * 7.0 * 2.0e6
        assert predicted == pytest.approx(
            KV_SETUP * 7.0 + KV_PER_BYTE * 7.0 * 2.0e6, rel=0.05
        )

    def test_calibrate_history_attaches_kv_constants(self):
        """A bank holding only serving rows still yields a table: the
        group stands residual-zero (dispatch/step contribute nothing)
        but carries the fitted kv constants, and
        ``cost.kv_handoff_seconds`` prefers them over the census floor
        exactly when ``kv_rows > 0``."""
        from ddlb_tpu.perfmodel.cost import kv_handoff_seconds
        from ddlb_tpu.perfmodel.specs import get_spec

        records = [
            {"kind": "row", "run_id": f"r{i}", "row": _kv_row(i)}
            for i in range(10)
        ]
        table = calibrate.calibrate_history(records=records, min_rows=8)
        assert table is not None
        group = table.group("cpu-sim", "host_clock")
        assert group is not None
        assert group.kv_rows == 10
        payload = 3.0e6
        spec = get_spec("v5e")
        fitted = kv_handoff_seconds(payload, spec, calib=group)
        assert fitted == pytest.approx(
            KV_SETUP + KV_PER_BYTE * payload, rel=0.05
        )
        # uncalibrated paths are byte-identical to the census floor
        floor = kv_handoff_seconds(payload, spec)
        assert kv_handoff_seconds(payload, spec, calib=None) == floor
        unfitted = calib.GroupCalibration(
            chip="cpu-sim", backend="host_clock",
            dispatch_s=0.0, step_s=0.0,
        )
        assert kv_handoff_seconds(payload, spec, calib=unfitted) == floor
