"""int8 quantized GEMM: ops-level error bounds and primitive validation.

The quantized members have no reference analogue (the reference dtype
floor is fp16); correctness is pinned against the framework's own f32
oracle under the statistical tolerance derived in
ops/quantized_matmul.py:quantization_atol.
"""

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 128, 64, 96


def _uniform_operands(m, k, n, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.uniform(-1.0, 1.0, (m, k)).astype(np.float32)
    b = rng.uniform(-1.0, 1.0, (k, n)).astype(np.float32)
    return a, b


class TestOps:
    def test_quantize_roundtrip(self):
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import (
            quantize_colwise,
            quantize_rowwise,
        )

        a, b = _uniform_operands(32, 48, 16)
        qa, sa = quantize_rowwise(jnp.asarray(a))
        qb, sb = quantize_colwise(jnp.asarray(b))
        assert qa.dtype == jnp.int8 and qb.dtype == jnp.int8
        assert sa.shape == (32, 1) and sb.shape == (1, 16)
        # dequantized operands are within half a quantization step
        assert np.max(np.abs(np.asarray(qa, np.float32) * np.asarray(sa) - a)) <= (
            np.max(np.abs(a), axis=1, keepdims=True) / 127 / 2 + 1e-7
        ).max()
        # extremes hit the grid ends exactly
        assert int(np.max(np.abs(np.asarray(qa, np.int32)))) == 127

    def test_zero_row_guard(self):
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import quantize_rowwise

        x = jnp.zeros((4, 8), jnp.float32)
        q, s = quantize_rowwise(x)
        assert np.all(np.isfinite(np.asarray(s)))
        assert np.all(np.asarray(q) == 0)

    def test_error_bound_across_seeds(self):
        """The sqrt(k)/32 bound holds with margin across many seeds and
        shapes — the statistical claim behind every quantized member's
        validate(), fuzzed rather than spot-checked."""
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import (
            int8_matmul,
            quantization_atol,
            quantize_colwise,
            quantize_rowwise,
        )

        worst = 0.0
        for seed in range(10):
            m, k, n = [(64, 128, 32), (32, 768, 48), (16, 256, 96)][seed % 3]
            a, b = _uniform_operands(m, k, n, seed=seed)
            qa, sa = quantize_rowwise(jnp.asarray(a))
            qb, sb = quantize_colwise(jnp.asarray(b))
            got = np.asarray(
                int8_matmul(qa, qb, sa, sb, out_dtype=jnp.float32), np.float32
            )
            ratio = np.max(np.abs(got - a @ b)) / quantization_atol(k)
            worst = max(worst, float(ratio))
        assert worst < 1.0, worst
        # the bound is meaningfully tight, not vacuous
        assert worst > 0.1, worst

    @pytest.mark.parametrize("k", [96, 512])
    def test_int8_matmul_error_bound(self, k):
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import (
            int8_matmul,
            quantization_atol,
            quantize_colwise,
            quantize_rowwise,
        )

        a, b = _uniform_operands(64, k, 32)
        qa, sa = quantize_rowwise(jnp.asarray(a))
        qb, sb = quantize_colwise(jnp.asarray(b))
        got = np.asarray(
            int8_matmul(qa, qb, sa, sb, out_dtype=jnp.float32), np.float32
        )
        want = a @ b
        err = np.max(np.abs(got - want))
        assert err <= quantization_atol(k), (err, quantization_atol(k))
        # and the bound is tight enough to mean something: within ~8x
        assert err >= quantization_atol(k) / 8

    def test_pallas_kernel_matches_xla(self):
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import (
            int8_matmul,
            int8_matmul_pallas,
            quantize_colwise,
            quantize_rowwise,
        )

        a, b = _uniform_operands(256, 256, 256, seed=3)
        qa, sa = quantize_rowwise(jnp.asarray(a))
        qb, sb = quantize_colwise(jnp.asarray(b))
        want = np.asarray(
            int8_matmul(qa, qb, sa, sb, out_dtype=jnp.float32), np.float32
        )
        got = np.asarray(
            int8_matmul_pallas(
                qa, qb, sa, sb,
                block_m=128, block_n=128, block_k=128,
                out_dtype=jnp.float32, interpret=True,
            ),
            np.float32,
        )
        # same int32 accumulation, same epilogue -> bitwise-equal floats
        assert np.array_equal(got, want)


@pytest.mark.parametrize(
    "family", ["tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall"]
)
class TestPrimitive:
    @pytest.mark.parametrize("quantize", ["static", "dynamic"])
    def test_validates(self, family, quantize):
        cls = load_impl_class(family, "quantized")
        impl = cls(M, N, K if family == "tp_columnwise" else 128,
                   dtype="bfloat16", quantize=quantize)
        result = impl.run()
        assert impl.validate(result)

    def test_pallas_kernel_validates(self, family):
        cls = load_impl_class(family, "quantized")
        impl = cls(
            1024, 256, 1024, dtype="bfloat16",
            kernel="pallas", block_m=128, block_n=128, block_k=128,
        )
        assert impl.validate(impl.run())

    def test_static_dynamic_agree(self, family):
        cls = load_impl_class(family, "quantized")
        k = K if family == "tp_columnwise" else 128
        r_static = cls(M, N, k, dtype="bfloat16", quantize="static").run()
        r_dynamic = cls(M, N, k, dtype="bfloat16", quantize="dynamic").run()
        assert np.array_equal(
            np.asarray(r_static, np.float32), np.asarray(r_dynamic, np.float32)
        )

    def test_int_dtype_rejected(self, family):
        cls = load_impl_class(family, "quantized")
        with pytest.raises(ValueError, match="floating"):
            cls(M, N, 128, dtype="int32")

    def test_dead_block_options_rejected(self, family):
        cls = load_impl_class(family, "quantized")
        with pytest.raises(ValueError, match="no effect"):
            cls(M, N, 128, dtype="bfloat16", kernel="xla", block_m=256)


class TestSTE:
    def test_forward_matches_int8_matmul(self):
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import (
            int8_matmul,
            int8_ste_matmul,
            quantize_colwise,
            quantize_rowwise,
        )

        a, b = _uniform_operands(64, 96, 32, seed=5)
        qa, sa = quantize_rowwise(jnp.asarray(a))
        qb, sb = quantize_colwise(jnp.asarray(b))
        want = np.asarray(int8_matmul(qa, qb, sa, sb, out_dtype=jnp.float32))
        got = np.asarray(int8_ste_matmul(jnp.asarray(a), jnp.asarray(b)))
        assert np.array_equal(got, want)

    def test_row_batching_invariance(self):
        """Per-row scales make the forward bit-identical under any row
        split — the property the model oracle pinning relies on."""
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import int8_ste_matmul

        a, b = _uniform_operands(64, 96, 32, seed=6)
        a, b = jnp.asarray(a), jnp.asarray(b)
        whole = np.asarray(int8_ste_matmul(a, b))
        parts = np.concatenate(
            [np.asarray(int8_ste_matmul(a[i : i + 16], b)) for i in range(0, 64, 16)]
        )
        assert np.array_equal(whole, parts)

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_gradients_are_straight_through(self, dtype):
        """STE gradients equal the unquantized matmul's exactly (same
        operands, same dot_general form); the f32 cotangent must contract
        at full width even for bf16 operands (code-review finding)."""
        import jax
        import jax.numpy as jnp

        from ddlb_tpu.ops.quantized_matmul import int8_ste_matmul

        a, b = _uniform_operands(32, 48, 16, seed=7)
        a, b = jnp.asarray(a, dtype), jnp.asarray(b, dtype)

        def loss_q(x, w):
            return jnp.sum(int8_ste_matmul(x, w) ** 2) / 100

        def loss_f(x, w):
            return (
                jnp.sum(
                    jnp.matmul(x, w, preferred_element_type=jnp.float32) ** 2
                )
                / 100
            )

        gq = jax.grad(loss_q, argnums=(0, 1))(a, b)
        gf = jax.grad(loss_f, argnums=(0, 1))(a, b)
        # the cotangents differ (quantized vs exact forward), so compare
        # against the STE definition instead: grads of the EXACT matmul
        # evaluated at the quantized forward's cotangent
        import jax.numpy as jnp

        out_q = int8_ste_matmul(a, b)
        g_in = out_q * 2 / 100  # f32 cotangent
        want_dx = np.asarray(g_in @ b.astype(jnp.float32).T)
        want_dw = np.asarray(a.astype(jnp.float32).T @ g_in)
        # f32: exact up to float noise; bf16: only the final downcast of
        # dx/dw rounds (the contraction itself stays f32)
        atol = 1e-5 if dtype == "float32" else 0.05
        assert np.allclose(np.asarray(gq[0], np.float32), want_dx, atol=atol)
        assert np.allclose(np.asarray(gq[1], np.float32), want_dw, atol=atol)
        # and they are close to the float grads (quantization-level noise)
        assert np.allclose(
            np.asarray(gq[0], np.float32),
            np.asarray(gf[0], np.float32),
            atol=0.2,
        )


class TestModelInt8:
    def test_train_matches_oracle(self):
        import jax

        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
            make_train_step,
            reference_loss,
        )

        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=2, mlp_kernel="int8",
        )
        dp, tp, pp = 2, 2, 2
        mesh = jax.make_mesh((dp, tp, pp), ("dp", "tp", "pp"))
        train_step, init_opt, shardings = make_train_step(mesh, cfg)
        params = init_params(cfg, pp, n_experts=tp)
        params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
        opt_state = init_opt(params)
        tokens, targets = example_tokens(dp * cfg.microbatches, 8 * tp, cfg.vocab)
        host_params = init_params(cfg, pp, n_experts=tp)
        expected = float(
            reference_loss(
                host_params, np.asarray(tokens), np.asarray(targets),
                cfg, tp=tp, dp=dp,
            )
        )
        tokens = jax.device_put(tokens, shardings["data"])
        targets = jax.device_put(targets, shardings["data"])
        _, _, loss = train_step(params, opt_state, tokens, targets)
        assert np.isclose(float(loss), expected, rtol=0, atol=1e-4), (
            float(loss), expected,
        )

    @pytest.mark.slow  # a full benchmark_worker round through the
    # GSPMD-partitioned flagship with int8 STE autodiff (~14 s of XLA
    # CPU compile) — outside the tier-1 870 s budget; int8 training
    # parity stays in-tier (test_train_matches_oracle,
    # test_transformer_step_int8_validates) and GSPMD x int8 composition
    # via test_other_members_int8_weights_forward[xla_gspmd]
    def test_xla_gspmd_train_int8_validates(self):
        """int8 STE autodiff composes with GSPMD auto-partitioning."""
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "gspmd_int8",
                "base_implementation": "xla_gspmd",
                "options": {"mlp_kernel": "int8", "batch": 4, "vocab": 64,
                            "n_heads": 4},
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert not row["error"], row["error"]
        assert row["valid"]

    def test_forward_int8_weights_matches_oracle(self):
        """The serving form: pre-quantized weight leaves, forward loss
        pins the oracle (both consume the same init_params output)."""
        import jax

        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
            make_loss_fn,
            reference_loss,
        )

        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=2, mlp_kernel="int8_weights",
        )
        dp, tp, pp = 2, 2, 2
        mesh = jax.make_mesh((dp, tp, pp), ("dp", "tp", "pp"))
        loss_fn, shardings = make_loss_fn(mesh, cfg)
        params = init_params(cfg, pp, n_experts=tp)
        assert str(params["moe_w1"].dtype) == "int8"
        assert "moe_w1_scale" in params
        tokens, targets = example_tokens(dp * cfg.microbatches, 8 * tp, cfg.vocab)
        expected = float(
            reference_loss(
                params, np.asarray(tokens), np.asarray(targets),
                cfg, tp=tp, dp=dp,
            )
        )
        dev_params = {
            k: jax.device_put(v, shardings[k]) for k, v in params.items()
        }
        tokens = jax.device_put(tokens, shardings["data"])
        targets = jax.device_put(targets, shardings["data"])
        loss = jax.jit(loss_fn)(dev_params, tokens, targets)
        assert np.isclose(float(loss), expected, rtol=0, atol=1e-4), (
            float(loss), expected,
        )

    def test_int8_weights_train_rejected(self):
        import jax

        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            make_train_step,
        )

        cfg = TransformerConfig(mlp_kernel="int8_weights")
        mesh = jax.make_mesh((2, 2, 2), ("dp", "tp", "pp"))
        with pytest.raises(ValueError, match="forward-only"):
            make_train_step(mesh, cfg)

    def test_transformer_step_int8_weights_forward_validates(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_int8w",
                "base_implementation": "spmd",
                "options": {"mlp_kernel": "int8_weights", "mode": "forward",
                            "batch": 4, "vocab": 64, "n_heads": 4},
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert not row["error"], row["error"]
        assert row["valid"]

    @pytest.mark.parametrize("member", ["compute_only", "xla_gspmd"])
    def test_other_members_int8_weights_forward(self, member):
        """The single-program members thread the serving mode through
        reference_loss + param_specs too."""
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_step", member)
        impl = cls(16, 32, 64, dtype="float32", mlp_kernel="int8_weights",
                   mode="forward", batch=4, vocab=64, n_heads=4)
        assert impl.validate(impl.run())

    def test_transformer_step_int8_weights_train_rejected(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_step", "spmd")
        with pytest.raises(ValueError, match="forward"):
            cls(16, 32, 64, dtype="float32", mlp_kernel="int8_weights",
                mode="train", batch=4, vocab=64, n_heads=4)

    def test_transformer_step_int8_validates(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_int8",
                "base_implementation": "spmd",
                "options": {"mlp_kernel": "int8", "batch": 4, "vocab": 64,
                            "n_heads": 4},
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert not row["error"], row["error"]
        assert row["valid"]


def test_runs_through_benchmark_worker():
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(
        {
            "primitive": "tp_columnwise",
            "impl_id": "quantized_0",
            "base_implementation": "quantized",
            "options": {"quantize": "dynamic"},
            "m": 128,
            "n": 64,
            "k": 96,
            "dtype": "bfloat16",
            "num_iterations": 2,
            "num_warmups": 1,
            "validate": True,
            "time_measurement_backend": "host_clock",
            "barrier_at_each_iteration": False,
        }
    )
    assert not row["error"]
    assert row["valid"]
    assert row["Throughput (TFLOPS)"] > 0
