"""Fused decode-attention kernel vs the einsum cache path.

The kernel must reproduce ``_cache_attend``'s semantics — live mask at
per-sequence positions, sliding window, GQA grouping, int8 dequant
through the model dtype — to float tolerance, and the serving step with
``decode_kernel='pallas'`` must still pin to the teacher-forced oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _rand_cache(rng, b, S, h_kv, dh, int8):
    from ddlb_tpu.models.decode import _quantize_kv

    k = jnp.asarray(rng.normal(0, 1, (b, S, h_kv, dh)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (b, S, h_kv, dh)), jnp.float32)
    if not int8:
        return {"k": k, "v": v}
    qk, sk = _quantize_kv(k)
    qv, sv = _quantize_kv(v)
    return {"k": qk, "k_scale": sk, "v": qv, "v_scale": sv}


def _einsum_reference(q, cache, pos, window):
    """The _cache_attend math on direct arrays (layer axis pre-stripped):
    grouped scores, live mask, softmax, value read, f32."""
    from ddlb_tpu.models.decode import _cache_attend

    layered = {name: arr[None] for name, arr in cache.items()}
    b, h, dh = q.shape
    return _cache_attend(
        q[:, None], layered, 0, dh, pos, jnp.float32, window=window
    ).reshape(b, h, dh)


@pytest.mark.parametrize(
    "case",
    [
        dict(),                               # MHA
        dict(h_kv=2),                         # GQA
        dict(int8=True),                      # int8 dequant in-kernel
        dict(h_kv=2, int8=True, window=6),    # everything at once
        dict(window=5),                       # sliding window
    ],
    ids=["mha", "gqa", "int8", "gqa-int8-window", "window"],
)
def test_kernel_matches_einsum_path(case):
    from ddlb_tpu.ops.decode_attention import decode_attention

    b, S, h, dh = 4, 24, 4, 8
    h_kv = case.get("h_kv", h)
    int8 = case.get("int8", False)
    window = case.get("window", 0)
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(0, 1, (b, h, dh)), jnp.float32)
    cache = _rand_cache(rng, b, S, h_kv, dh, int8)
    pos = jnp.asarray(rng.integers(0, S, b), jnp.int32)

    got = decode_attention(
        q, cache["k"], cache["v"], pos,
        k_scale=cache.get("k_scale"), v_scale=cache.get("v_scale"),
        window=window, block_s=8, interpret=True,
    )
    want = _einsum_reference(q, cache, pos, window)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=0, atol=1e-5
    )


def _page_scatter(cache, ps, rng, unmap_tail_for=None):
    """Scatter a contiguous ``[b, S, ...]`` cache into a paged pool with
    a SHUFFLED page assignment (pages deliberately non-contiguous in the
    pool) plus a few never-mapped pages; ``unmap_tail_for[i]`` (a
    position per slot) additionally sentinels every table entry strictly
    past that position's page — the allocator's true shape, where the
    unwritten tail has no pages at all."""
    b, S = np.asarray(cache["k"]).shape[:2]
    mp = S // ps
    num_pages = b * mp + 3
    perm = rng.permutation(b * mp)
    table = np.full((b, mp), num_pages, np.int32)
    pools = {
        name: np.zeros(
            (num_pages, ps) + np.asarray(arr).shape[2:],
            np.asarray(arr).dtype,
        )
        for name, arr in cache.items()
    }
    for i in range(b):
        for j in range(mp):
            if unmap_tail_for is not None and j > unmap_tail_for[i] // ps:
                continue
            pg = int(perm[i * mp + j])
            table[i, j] = pg
            for name, arr in cache.items():
                pools[name][pg] = np.asarray(arr)[i, j * ps : (j + 1) * ps]
    out = {name: jnp.asarray(p) for name, p in pools.items()}
    out["table"] = jnp.asarray(table)
    return out


@pytest.mark.parametrize(
    "case",
    [
        dict(),
        dict(h_kv=2),
        dict(int8=True),
        dict(h_kv=2, int8=True, window=6),
        dict(window=5),
    ],
    ids=["mha", "gqa", "int8", "gqa-int8-window", "window"],
)
def test_paged_kernel_matches_einsum_path(case):
    from ddlb_tpu.ops.decode_attention import paged_decode_attention

    b, S, h, dh, ps = 4, 24, 4, 8, 8
    h_kv = case.get("h_kv", h)
    int8 = case.get("int8", False)
    window = case.get("window", 0)
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(0, 1, (b, h, dh)), jnp.float32)
    cache = _rand_cache(rng, b, S, h_kv, dh, int8)
    pos = jnp.asarray(rng.integers(0, S, b), jnp.int32)
    paged = _page_scatter(cache, ps, rng, unmap_tail_for=np.asarray(pos))

    got = paged_decode_attention(
        q, paged["k"], paged["v"], paged["table"], pos,
        k_scale=paged.get("k_scale"), v_scale=paged.get("v_scale"),
        window=window, interpret=True,
    )
    want = _einsum_reference(q, cache, pos, window)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=0, atol=1e-5
    )


def test_scalar_pos_broadcasts_and_blocks_shrink():
    from ddlb_tpu.ops.decode_attention import decode_attention

    b, S, h, dh = 2, 9, 4, 8  # S=9: block auto-shrinks to a divisor
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(0, 1, (b, h, dh)), jnp.float32)
    cache = _rand_cache(rng, b, S, h, dh, False)
    got = decode_attention(
        q, cache["k"], cache["v"], jnp.int32(5), block_s=4, interpret=True
    )
    want = _einsum_reference(q, cache, jnp.full(b, 5, jnp.int32), 0)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=0, atol=1e-5
    )


def test_bad_args():
    from ddlb_tpu.ops.decode_attention import decode_attention

    q = jnp.zeros((2, 4, 8), jnp.float32)
    k8 = jnp.zeros((2, 8, 4, 8), jnp.int8)
    with pytest.raises(ValueError, match="needs k_scale"):
        decode_attention(q, k8, k8, jnp.int32(0), interpret=True)
    with pytest.raises(ValueError, match="divisible"):
        decode_attention(
            q, jnp.zeros((2, 8, 3, 8), jnp.float32),
            jnp.zeros((2, 8, 3, 8), jnp.float32), jnp.int32(0),
            interpret=True,
        )


class TestServingIntegration:
    """decode_kernel='pallas' through the member: oracle-pinned."""

    def _run(self, **opts):
        from ddlb_tpu.benchmark import benchmark_worker

        return benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_dka",
                "base_implementation": "spmd",
                "options": {
                    "phase": "decode", "batch": 8, "vocab": 64,
                    "n_heads": 4, "decode_kernel": "pallas",
                    "attn_kernel": "einsum", **opts,
                },
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )

    @pytest.mark.parametrize(
        "opts",
        [
            {},
            {"kv_cache": "int8", "n_kv_heads": 2},
            {"rope": True, "attn_window": 6},
        ],
        ids=["plain", "int8-gqa", "rope-window"],
    )
    def test_decode_step_validates(self, opts):
        row = self._run(**opts)
        assert row["error"] == ""
        assert row["valid"] is True

    def test_generate_loop_validates(self):
        row = self._run(phase="generate", n_new=5)
        assert row["error"] == ""
        assert row["valid"] is True

    def test_xla_gspmd_rejects_pallas_decode_kernel(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_decode", "xla_gspmd")
        with pytest.raises(ValueError, match="decode_kernel"):
            cls(16, 32, 64, dtype="float32", decode_kernel="pallas",
                batch=8, vocab=64, n_heads=4)
