"""Speculative decoding: chunk verify + the lossless greedy guarantee.

Greedy speculative decoding must produce EXACTLY the target model's own
greedy chain for any draft — the draft only changes speed. That makes the
strongest possible oracle: integer equality against ``make_generate_fn``
(no tolerances), across the fast-decode axes (GQA, RoPE, int8 cache,
sliding window) and adversarial drafts (random weights, draft == target).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _setup(cfg, B, S0, seed=0, tp=2):
    from ddlb_tpu.models.transformer import example_tokens, init_params
    from ddlb_tpu.runtime import Runtime

    mesh = Runtime().mesh(("dp", "tp"), shape=(8 // tp, tp))
    params = init_params(cfg, pp=1, n_experts=tp, seed=seed)
    prompt, _ = example_tokens(B, S0, cfg.vocab)
    return mesh, params, prompt


def _cfg(layers=2, **kw):
    from ddlb_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab=64, d_model=32, n_heads=4, d_ff=64,
        layers_per_stage=layers, microbatches=1, attn_kernel="einsum",
        **kw,
    )


def _greedy(mesh, cfg, params, prompt, n_new):
    from ddlb_tpu.models.decode import init_cache, make_generate_fn

    gen, sh = make_generate_fn(mesh, cfg, n_new=n_new)
    p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    B, S0 = prompt.shape
    cache = init_cache(cfg, B, S0 + n_new, mesh=mesh)
    return p, np.asarray(jax.jit(gen)(p, cache, prompt))


def _speculate(mesh, cfg, cfg_d, p, params_d, prompt, n_new, k):
    from ddlb_tpu.models.decode import init_cache, make_speculate_fn

    spec, (_, sh_d) = make_speculate_fn(mesh, cfg, cfg_d, n_new=n_new,
                                        spec_k=k)
    pd = {kk: jax.device_put(v, sh_d[kk]) for kk, v in params_d.items()}
    B, S0 = prompt.shape
    return np.asarray(
        jax.jit(spec)(
            p, pd,
            init_cache(cfg, B, S0 + n_new + k, mesh=mesh),
            init_cache(cfg_d, B, S0 + n_new + k, mesh=mesh),
            prompt,
        )
    )


class TestChunkDecode:
    """make_chunk_decode_fn == t sequential decode steps."""

    @pytest.mark.parametrize("kv_cache", ["bf16", "int8"])
    def test_chunk_equals_sequential_decode(self, kv_cache):
        from ddlb_tpu.models.decode import (
            init_cache,
            make_chunk_decode_fn,
            make_decode_fn,
            make_prefill_fn,
        )

        cfg = _cfg(kv_cache=kv_cache, rope=True, n_kv_heads=2)
        B, S0, t = 8, 8, 3
        mesh, params, prompt = _setup(cfg, B, S0)
        prefill, sh = make_prefill_fn(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        rng = np.random.default_rng(3)
        toks = jnp.asarray(rng.integers(0, cfg.vocab, (B, t)), jnp.int32)

        cache = init_cache(cfg, B, S0 + t, mesh=mesh)
        _, cache = jax.jit(prefill)(p, cache, prompt)
        chunk, _ = make_chunk_decode_fn(mesh, cfg)
        lg_c, cache_c = jax.jit(chunk)(p, cache, toks, jnp.int32(S0))

        decode, _ = make_decode_fn(mesh, cfg)
        cache2 = init_cache(cfg, B, S0 + t, mesh=mesh)
        _, cache2 = jax.jit(prefill)(p, cache2, prompt)
        seq_logits = []
        for j in range(t):
            lg, cache2 = jax.jit(decode)(
                p, cache2, toks[:, j], jnp.int32(S0 + j)
            )
            seq_logits.append(np.asarray(lg))
        np.testing.assert_allclose(
            np.asarray(lg_c), np.stack(seq_logits, axis=1),
            rtol=0, atol=1e-5,
        )
        # the caches agree too — same rows written; batched-vs-sequential
        # f32 GEMMs reorder accumulation, so the pin is a tight tolerance
        # (int8 payloads may flip one quantization bucket at a cliff)
        for name in cache_c:
            a = np.asarray(cache_c[name])
            b_ = np.asarray(cache2[name])
            if a.dtype == np.int8:
                assert np.abs(
                    a.astype(np.int16) - b_.astype(np.int16)
                ).max() <= 1, name
            else:
                np.testing.assert_allclose(
                    a.astype(np.float32), b_.astype(np.float32),
                    rtol=0, atol=1e-4, err_msg=name,
                )

    def test_chunk_rejects_vector_start(self):
        from ddlb_tpu.models.decode import init_cache, make_chunk_decode_fn

        cfg = _cfg()
        B, S0 = 8, 8
        mesh, params, prompt = _setup(cfg, B, S0)
        chunk, sh = make_chunk_decode_fn(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        cache = init_cache(cfg, B, S0 + 2, mesh=mesh)
        toks = jnp.zeros((B, 2), jnp.int32)
        with pytest.raises(ValueError, match="scalar start"):
            jax.jit(chunk)(p, cache, toks, jnp.zeros((B,), jnp.int32))


class TestLossless:
    """speculate == plain greedy, integer equality, across the axes."""

    N_NEW, K = 12, 3

    @pytest.mark.parametrize(
        "axes",
        [
            {},
            {"n_kv_heads": 2, "rope": True},
            {"kv_cache": "int8"},
            {"attn_window": 4},
        ],
        ids=["plain", "gqa-rope", "int8-cache", "window"],
    )
    def test_exact_chain(self, axes):
        from ddlb_tpu.models.transformer import init_params

        cfg = _cfg(layers=2, **axes)
        cfg_d = _cfg(layers=1, **axes)
        mesh, params, prompt = _setup(cfg, 8, 8)
        p, want = _greedy(mesh, cfg, params, prompt, self.N_NEW)
        params_d = init_params(cfg_d, pp=1, n_experts=2, seed=1)
        got = _speculate(
            mesh, cfg, cfg_d, p, params_d, prompt, self.N_NEW, self.K
        )
        if axes.get("kv_cache") == "int8":
            # under int8 the verify chunk's batched projection can flip
            # one quantization bucket vs generate's t=1 writes (~1e-2
            # logits drift), so exactness holds only up to near-ties: a
            # divergence is legitimate IFF the target itself was near-
            # tied (top-2 gap below the drift) at that row's first
            # mismatch, given the common prefix
            self._assert_chain_up_to_ties(
                got, want, params, cfg, prompt, tie_tol=2e-2
            )
        else:
            np.testing.assert_array_equal(got, want)

    @staticmethod
    def _assert_chain_up_to_ties(got, want, params, cfg, prompt, tie_tol):
        from ddlb_tpu.models.decode import reference_logits

        if (got == want).all():
            return
        _, S0 = prompt.shape
        for i in np.argwhere((got[:, S0:] != want[:, S0:]).any(axis=1))[:, 0]:
            t = int(np.argmax(got[i, S0:] != want[i, S0:]))
            # teacher-force the agreed prefix; the divergent step must be
            # a near-tie in the target's own logits
            ctx = jnp.asarray(want[:, : S0 + t])
            logits = np.asarray(
                reference_logits(params, ctx, cfg, tp=2, dp=4), np.float32
            )
            # sibling of TransformerDecode._validate_generate's
            # tie-forgiveness rule (keep semantics aligned), plus a
            # stronger membership check: the flipped token must BE one of
            # the two near-tied candidates — a wrong-token bug at a
            # near-tied step must not hide behind the forgiveness
            order = np.argsort(logits[i])
            top2 = logits[i][order[-2:]]
            gap = float(top2[1] - top2[0])
            assert gap < tie_tol, (
                f"row {i} leaves the greedy chain at step {t} with a "
                f"decisive top-2 gap {gap:.3e} (not an int8 near-tie)"
            )
            assert got[i, S0 + t] in order[-2:], (
                f"row {i} step {t}: divergent token {got[i, S0 + t]} is "
                f"not one of the near-tied top-2 {order[-2:]}"
            )
            # beyond the first (forgiven) flip the contexts differ, so
            # later tokens legitimately diverge — nothing more to check

    def test_draft_equals_target_is_exact(self):
        cfg = _cfg(layers=2)
        mesh, params, prompt = _setup(cfg, 8, 8)
        p, want = _greedy(mesh, cfg, params, prompt, self.N_NEW)
        got = _speculate(
            mesh, cfg, cfg, p, params, prompt, self.N_NEW, self.K
        )
        np.testing.assert_array_equal(got, want)

    def test_adversarial_random_draft_is_exact(self):
        """A draft whose proposals are near-always wrong still yields the
        target chain — only slower (advance degenerates to 1/round)."""
        from ddlb_tpu.models.transformer import init_params

        cfg = _cfg(layers=2)
        cfg_d = _cfg(layers=1)
        mesh, params, prompt = _setup(cfg, 8, 8)
        p, want = _greedy(mesh, cfg, params, prompt, self.N_NEW)
        params_bad = init_params(cfg_d, pp=1, n_experts=2, seed=999)
        got = _speculate(
            mesh, cfg, cfg_d, p, params_bad, prompt, self.N_NEW, self.K
        )
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("n_new", [1, 2])
    def test_tiny_n_new(self, n_new):
        from ddlb_tpu.models.transformer import init_params

        cfg = _cfg(layers=2)
        cfg_d = _cfg(layers=1)
        mesh, params, prompt = _setup(cfg, 8, 8)
        p, want = _greedy(mesh, cfg, params, prompt, n_new)
        params_d = init_params(cfg_d, pp=1, n_experts=2, seed=1)
        got = _speculate(mesh, cfg, cfg_d, p, params_d, prompt, n_new, 4)
        np.testing.assert_array_equal(got, want)

    def test_cache_too_small_rejected(self):
        from ddlb_tpu.models.decode import init_cache, make_speculate_fn

        cfg = _cfg(layers=2)
        mesh, params, prompt = _setup(cfg, 8, 8)
        spec, (sh_t, _) = make_speculate_fn(
            mesh, cfg, cfg, n_new=4, spec_k=4
        )
        p = {k: jax.device_put(v, sh_t[k]) for k, v in params.items()}
        small = init_cache(cfg, 8, 8 + 4, mesh=mesh)  # missing + spec_k
        with pytest.raises(ValueError, match="cache holds"):
            jax.jit(spec)(p, p, small, small, prompt)

    def test_bad_args_rejected(self):
        from dataclasses import replace

        from ddlb_tpu.models.decode import make_speculate_fn

        cfg = _cfg()
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        with pytest.raises(ValueError, match="spec_k"):
            make_speculate_fn(mesh, cfg, cfg, n_new=4, spec_k=0)
        with pytest.raises(ValueError, match="n_new"):
            make_speculate_fn(mesh, cfg, cfg, n_new=0)
        with pytest.raises(ValueError, match="vocab"):
            make_speculate_fn(mesh, cfg, replace(cfg, vocab=32), n_new=4)


class TestSpeculateMember:
    """phase=speculate through the benchmark worker, oracle-validated."""

    def _run(self, impl, **opts):
        from ddlb_tpu.benchmark import benchmark_worker

        return benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": f"{impl}_spec",
                "base_implementation": impl,
                "options": {
                    "phase": "speculate", "n_new": 6, "spec_k": 2,
                    "draft_layers": 1, "layers": 2, "batch": 8,
                    "vocab": 64, "n_heads": 8, "attn_kernel": "einsum",
                    **opts,
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )

    @pytest.mark.parametrize("impl", ["spmd", "compute_only"])
    def test_validates_against_oracle_chain(self, impl):
        row = self._run(impl)
        assert row["error"] == ""
        assert row["valid"] is True

    def test_fast_decode_levers_compose(self):
        row = self._run("spmd", kv_cache="int8", n_kv_heads=2)
        assert row["error"] == ""
        assert row["valid"] is True

    def test_xla_gspmd_rejects_speculate(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_decode", "xla_gspmd")
        with pytest.raises(ValueError, match="spmd/compute_only"):
            cls(16, 64, 64, dtype="float32", phase="speculate",
                batch=8, vocab=64, n_heads=8)


class TestAcceptanceStats:
    """with_stats=True: the measured acceptance counters the benchmark
    row reports. Invariant from the loop: every verify round emits
    a + 1 tokens, so rounds + accepted == n_new - 1 exactly; and the
    tokens are the SAME chain as the stats-free form."""

    def test_stats_invariants_and_identical_tokens(self):
        from ddlb_tpu.models.decode import init_cache, make_speculate_fn
        from ddlb_tpu.models.transformer import init_params

        n_new, k = 12, 4
        cfg = _cfg(layers=2)
        cfg_d = _cfg(layers=1)
        B, S0 = 8, 8
        mesh, params, prompt = _setup(cfg, B, S0)
        params_d = init_params(cfg_d, pp=1, n_experts=2, seed=7)

        spec_s, (sh_t, sh_d) = make_speculate_fn(
            mesh, cfg, cfg_d, n_new=n_new, spec_k=k, with_stats=True
        )
        p = {kk: jax.device_put(v, sh_t[kk]) for kk, v in params.items()}
        pd = {kk: jax.device_put(v, sh_d[kk]) for kk, v in params_d.items()}

        def caches():
            return (
                init_cache(cfg, B, S0 + n_new + k, mesh=mesh),
                init_cache(cfg_d, B, S0 + n_new + k, mesh=mesh),
            )

        c_t, c_d = caches()
        toks_s, stats = jax.jit(spec_s)(p, pd, c_t, c_d, prompt)
        rounds, accepted = int(stats["rounds"]), int(stats["accepted"])
        proposals = int(stats["proposals"])
        assert rounds >= 1
        assert 0 <= accepted <= proposals <= rounds * k
        assert rounds + accepted == n_new - 1

        plain = _speculate(mesh, cfg, cfg_d, p, params_d, prompt, n_new, k)
        np.testing.assert_array_equal(np.asarray(toks_s), plain)

    def test_full_acceptance_counts_only_requested_tokens(self):
        # draft == target: every proposal accepted, every round advances
        # spec_k + 1 — including a FINAL round that overshoots n_new.
        # The invariant must hold exactly (surplus tokens are sliced
        # from the output, so they are not accepted work either).
        from ddlb_tpu.models.decode import init_cache, make_speculate_fn

        n_new, k = 12, 4  # rounds of 5: 5, 10, 15 > 11 -> overshoot
        cfg = _cfg(layers=2)
        B, S0 = 8, 8
        mesh, params, prompt = _setup(cfg, B, S0)
        spec_s, (sh_t, _) = make_speculate_fn(
            mesh, cfg, cfg, n_new=n_new, spec_k=k, with_stats=True
        )
        p = {kk: jax.device_put(v, sh_t[kk]) for kk, v in params.items()}
        toks, stats = jax.jit(spec_s)(
            p, p,
            init_cache(cfg, B, S0 + n_new + k, mesh=mesh),
            init_cache(cfg, B, S0 + n_new + k, mesh=mesh),
            prompt,
        )
        rounds, accepted = int(stats["rounds"]), int(stats["accepted"])
        proposals = int(stats["proposals"])
        assert rounds + accepted == n_new - 1
        # identical models accept everything: ceil((n_new-1)/(k+1)) rounds
        assert rounds == -(-(n_new - 1) // (k + 1))
        # the rate is UNBIASED: a perfect draft measures exactly 1.0
        # (the clipped final round charges only the proposals that
        # could land inside n_new)
        assert accepted == proposals
        # and the tokens are still the target's own greedy chain
        _, greedy = _greedy(mesh, cfg, params, prompt, n_new)
        np.testing.assert_array_equal(np.asarray(toks), greedy)

    def test_worker_row_carries_acceptance_rate(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_spec",
                "base_implementation": "spmd",
                "options": {
                    "phase": "speculate", "n_new": 8, "spec_k": 2,
                    "draft_layers": 1, "layers": 2, "batch": 8,
                    "vocab": 64, "n_heads": 8, "attn_kernel": "einsum",
                },
                "m": 16, "n": 32, "k": 64, "dtype": "bfloat16",
                "num_iterations": 1, "num_warmups": 0, "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["valid"], row["error"]
        assert 0.0 <= row["spec_accept_rate"] <= 1.0
        accepted = round(row["spec_accept_rate"] * row["spec_proposals"])
        assert row["spec_rounds"] + accepted == 8 - 1
