"""bench.py resilience: the driver artifact must land no matter what.

Round-1 failure mode: the TPU relay was down, ``jax.devices()`` raised in
the parent and the driver recorded ``rc=1`` with no perf number. These
tests run the real two-layer bench entry end-to-end in subprocesses under
(a) a live CPU backend and (b) a dead/hanging backend, and assert both
produce rc=0 and one parseable JSON line (the reference's soft-failure
stance, /root/reference/ddlb/benchmark.py:242-245, applied to the bench
entry itself).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _clean_env(**over):
    env = dict(os.environ)
    # The suite's conftest sim settings must not leak into the child.
    # NOTE: JAX_PLATFORMS is NOT a reliable CPU-forcing mechanism here —
    # the local TPU plugin overrides it; DDLB_TPU_SIM_DEVICES routes
    # through jax.config, which wins (see ddlb_tpu.runtime).
    env.pop("DDLB_TPU_SIM_DEVICES", None)
    env.pop("XLA_FLAGS", None)
    env.update(over)
    return env


def _last_json_line(stdout: str) -> dict:
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no JSON line in output:\n{stdout}")


@pytest.mark.slow
def test_bench_live_cpu_backend():
    """Probe succeeds (cpu), worker measures, validation runs: rc=0 + JSON."""
    out = subprocess.run(
        [sys.executable, BENCH],
        env=_clean_env(
            DDLB_TPU_SIM_DEVICES="1",
            DDLB_TPU_BENCH_SHAPE="256,256,256",
            DDLB_TPU_BENCH_TIMEOUT="600",
        ),
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    row = _last_json_line(out.stdout)
    assert row.get("error", "") == ""
    assert row["unit"] == "TFLOPS"
    assert row["value"] > 0
    assert row["platform"] == "cpu"
    assert row["valid"] is True
    assert "fallback_reason" not in row  # the primary path succeeded
    assert row["vs_baseline"] == 0.0  # MXU fraction is cpu-meaningless


@pytest.mark.slow
def test_bench_dead_backend_falls_back_to_cpu():
    """A backend whose probe fails/hangs must still yield rc=0 + a measured
    CPU row tagged with fallback_reason (VERDICT r1 next-round item #1)."""
    out = subprocess.run(
        [sys.executable, BENCH],
        env=_clean_env(
            # Deterministic dead-backend hook: the real outage (a down
            # relay) hangs the probe subprocess until its timeout, which
            # lands in exactly the same fallback path but costs
            # timeout*retries of wall clock per test run.
            DDLB_TPU_BENCH_FORCE_PROBE_FAIL="1",
            # bypass the committed TPU results cache: this test pins the
            # CPU re-measurement layer specifically
            DDLB_TPU_BENCH_NO_CACHE="1",
            DDLB_TPU_BENCH_SMOKE_SHAPE="256,256,256",
            DDLB_TPU_BENCH_SMOKE_TIMEOUT="600",
        ),
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    row = _last_json_line(out.stdout)
    assert row.get("error", "") == ""
    assert row["value"] > 0
    assert row["platform"] == "cpu"
    assert row["fallback_reason"]
    assert row["vs_baseline"] == 0.0  # roofline fraction is CPU-meaningless


def test_bench_worker_emits_validated_row():
    """The worker itself (in-process entry) validates the winning config."""
    out = subprocess.run(
        [sys.executable, BENCH, "--worker"],
        env=_clean_env(
            DDLB_TPU_SIM_DEVICES="1", DDLB_TPU_BENCH_SHAPE="128,128,128"
        ),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    row = _last_json_line(out.stdout)
    assert row["valid"] is True
    assert row["mean_ms"] > 0


def _load_bench_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_under_test", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_worker_hang_salvages_printed_headline(monkeypatch):
    """A worker that prints the validated headline and THEN hangs (e.g. the
    int8 sidecar stalls on a halted device) must not lose the headline:
    _run_worker parses the timeout's partial stdout."""
    bench = _load_bench_module()
    headline = json.dumps(
        {"metric": "tp_x", "value": 1.0, "unit": "TFLOPS", "valid": True}
    )

    def fake_run(*args, **kwargs):
        raise subprocess.TimeoutExpired(
            cmd="worker", timeout=1.0,
            output=f"progress noise\n{headline}\n".encode(),
        )

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    row, reason = bench._run_worker(dict(os.environ), timeout=1.0)
    assert reason == ""
    assert row["metric"] == "tp_x" and row["value"] == 1.0


def test_worker_hang_with_no_output_still_reports_hang(monkeypatch):
    bench = _load_bench_module()

    def fake_run(*args, **kwargs):
        raise subprocess.TimeoutExpired(cmd="worker", timeout=1.0, output=None)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    row, reason = bench._run_worker(dict(os.environ), timeout=1.0)
    assert row is None
    assert "hung" in reason


def test_bench_dead_backend_emits_cached_tpu_row(tmp_path, monkeypatch):
    """With a TPU headline in the results cache, a dead backend emits the
    cached row — provenance-tagged — instead of the CPU smoke row
    (VERDICT r2 next-round #1: a relay outage at capture time becomes a
    provenance note, not evidence loss)."""
    bench = _load_bench_module()
    cache = tmp_path / "bench_tpu_cache.json"
    captured = {
        "metric": "tp_columnwise_gemm_pallas_8192x8192x8192_bf16",
        "value": 175.8,
        "unit": "TFLOPS",
        "vs_baseline": 0.8924,
        "platform": "tpu",
        "world_size": 1,
        "valid": True,
        "captured_at": "2026-07-30T05:10:00Z",
        "protocol": dict(bench.BENCH_PROTOCOL),
    }
    cache.write_text(json.dumps([captured]))
    monkeypatch.setattr(bench, "CACHE_PATH", str(cache))
    monkeypatch.setenv("DDLB_TPU_BENCH_FORCE_PROBE_FAIL", "1")
    monkeypatch.delenv("DDLB_TPU_BENCH_NO_CACHE", raising=False)

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.main()
    row = _last_json_line(buf.getvalue())
    assert row["cached"] is True
    assert row["status"] == "cached"
    assert row["platform"] == "tpu"
    assert row["value"] == 175.8
    assert row["captured_at"] == "2026-07-30T05:10:00Z"
    assert "forced probe failure" in row["fallback_reason"]


def test_bench_cache_rejects_mismatched_world_or_protocol(
    tmp_path, monkeypatch
):
    """A cached row measured on a different device count or under an older
    protocol may NOT stand in for this run's headline (ADVICE r3) — the
    fallback goes to the CPU smoke layer instead. Short-circuit that layer
    too, so the test pins the filter without a 15-min smoke run."""
    bench = _load_bench_module()
    base = {
        "metric": "tp_columnwise_gemm_pallas_8192x8192x8192_bf16",
        "value": 175.8,
        "unit": "TFLOPS",
        "platform": "tpu",
        "valid": True,
        "captured_at": "2026-07-30T05:10:00Z",
    }
    stale_world = dict(base, world_size=8, protocol=dict(bench.BENCH_PROTOCOL))
    stale_proto = dict(
        base, world_size=1,
        protocol=dict(bench.BENCH_PROTOCOL, device_loop_windows=3),
    )
    cache = tmp_path / "bench_tpu_cache.json"
    cache.write_text(json.dumps([stale_world, stale_proto]))
    monkeypatch.setattr(bench, "CACHE_PATH", str(cache))
    monkeypatch.setenv("DDLB_TPU_BENCH_FORCE_PROBE_FAIL", "1")
    monkeypatch.delenv("DDLB_TPU_BENCH_NO_CACHE", raising=False)
    monkeypatch.setattr(
        bench, "_run_worker", lambda env, timeout: (None, "short-circuit")
    )

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.main()
    row = _last_json_line(buf.getvalue())
    assert "cached" not in row  # neither stale row stood in
    assert row["value"] == 0.0


def test_bench_cache_roundtrip(tmp_path, monkeypatch):
    """_save_tpu_cache appends timestamp+protocol and caps the history."""
    bench = _load_bench_module()
    cache = tmp_path / "cache.json"
    monkeypatch.setattr(bench, "CACHE_PATH", str(cache))
    for i in range(bench._CACHE_KEEP + 3):
        bench._save_tpu_cache(
            {"metric": "m", "value": float(i), "platform": "tpu",
             "valid": True}
        )
    entries = bench._load_tpu_cache()
    assert len(entries) == bench._CACHE_KEEP
    assert entries[-1]["value"] == float(bench._CACHE_KEEP + 2)
    assert entries[-1]["captured_at"]
    assert entries[-1]["protocol"]["device_loop_windows"] == 8


def test_device_loop_reports_real_distribution():
    """measure_device_loop returns one entry per window — a genuine
    distribution, never one scalar broadcast N times (VERDICT r1 weak #2)."""
    import jax.numpy as jnp

    from ddlb_tpu.utils.timing import measure_device_loop

    a = jnp.ones((64, 64), jnp.float32)
    windows = measure_device_loop(jnp.matmul, (a, a), num_iterations=8,
                                  num_windows=5)
    assert isinstance(windows, np.ndarray)
    assert windows.shape == (5,)
    assert np.all(windows > 0)
    # Independent host-timed windows essentially never coincide exactly;
    # identical values would mean the scalar-broadcast bug is back.
    assert len(set(windows.tolist())) > 1


def test_device_loop_row_stats_not_fabricated():
    """A device_loop benchmark row must carry non-degenerate statistics."""
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(
        {
            "primitive": "tp_columnwise",
            "impl_id": "compute_only_0",
            "base_implementation": "compute_only",
            "options": {"size": "unsharded"},
            "m": 128,
            "n": 64,
            "k": 64,
            "dtype": "float32",
            "num_iterations": 8,
            "num_warmups": 1,
            "validate": False,
            "time_measurement_backend": "device_loop",
            "barrier_at_each_iteration": False,
            "device_loop_windows": 5,
        }
    )
    assert row["error"] == ""
    assert row["mean time (ms)"] > 0
    # std computed across real windows; exact zero would mean broadcast
    assert row["std time (ms)"] > 0
    assert row["min time (ms)"] < row["max time (ms)"]


def test_device_loop_scales_tiny_windows(capsys):
    """A window far below the floor is scaled up so the differential is
    measured against enough device time (sub-ms windows over the jittery
    relay otherwise produce silently inflated, even above-roofline,
    per-iteration rates)."""
    import jax.numpy as jnp

    from ddlb_tpu.utils.timing import measure_device_loop

    a = jnp.ones((8, 8), jnp.float32)
    windows = measure_device_loop(
        jnp.matmul, (a, a), num_iterations=2, num_windows=2,
        min_window_s=0.2,
    )
    assert (windows > 0).all()
    out = capsys.readouterr().out
    assert "scaling to" in out


def test_bench_cache_rejects_stale_row(tmp_path, monkeypatch):
    """VERDICT r5 weak #2: a months-old cached row may not satisfy the
    driver forever — past DDLB_TPU_BENCH_CACHE_MAX_AGE_DAYS the cache
    layer steps aside (here the short-circuited smoke layer reports
    failure, so the total-failure line proves no cached row stood in)."""
    bench = _load_bench_module()
    stale = {
        "metric": "tp_columnwise_gemm_pallas_8192x8192x8192_bf16",
        "value": 175.8, "unit": "TFLOPS", "platform": "tpu",
        "world_size": 1, "valid": True,
        "captured_at": "2026-01-01T00:00:00Z",  # months before today
        "protocol": dict(bench.BENCH_PROTOCOL),
    }
    cache = tmp_path / "bench_tpu_cache.json"
    cache.write_text(json.dumps([stale]))
    monkeypatch.setattr(bench, "CACHE_PATH", str(cache))
    monkeypatch.setenv("DDLB_TPU_BENCH_FORCE_PROBE_FAIL", "1")
    monkeypatch.delenv("DDLB_TPU_BENCH_NO_CACHE", raising=False)
    monkeypatch.delenv("DDLB_TPU_BENCH_CACHE_MAX_AGE_DAYS", raising=False)
    monkeypatch.setattr(
        bench, "_run_worker", lambda env, timeout: (None, "short-circuit")
    )

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.main()
    row = _last_json_line(buf.getvalue())
    assert "cached" not in row
    assert row["value"] == 0.0


def test_bench_cached_row_surfaces_its_age(tmp_path, monkeypatch):
    """A fresh-enough cached row still stands in — and now carries
    cache_age_days so the BENCH_*.json artifact shows how old the
    stand-in is."""
    import time as time_mod

    bench = _load_bench_module()
    captured_at = time_mod.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time_mod.gmtime(time_mod.time() - 2 * 86400)
    )
    fresh = {
        "metric": "tp_columnwise_gemm_pallas_8192x8192x8192_bf16",
        "value": 175.8, "unit": "TFLOPS", "platform": "tpu",
        "world_size": 1, "valid": True, "captured_at": captured_at,
        "protocol": dict(bench.BENCH_PROTOCOL),
    }
    cache = tmp_path / "bench_tpu_cache.json"
    cache.write_text(json.dumps([fresh]))
    monkeypatch.setattr(bench, "CACHE_PATH", str(cache))
    monkeypatch.setenv("DDLB_TPU_BENCH_FORCE_PROBE_FAIL", "1")
    monkeypatch.delenv("DDLB_TPU_BENCH_NO_CACHE", raising=False)

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.main()
    row = _last_json_line(buf.getvalue())
    assert row["cached"] is True
    assert 1.5 <= row["cache_age_days"] <= 2.5


def test_bench_cache_age_unparseable_counts_as_stale():
    bench = _load_bench_module()
    assert bench._cache_age_days({}) == float("inf")
    assert bench._cache_age_days({"captured_at": "garbled"}) == float("inf")
    assert bench._cache_age_days(
        {"captured_at": "2026-08-01T00:00:00Z"}
    ) < 30.0


def test_roofline_regression_gate(tmp_path, monkeypatch):
    """A fresh capture whose roofline_frac drops more than the tolerance
    below the previous cached capture gets soft-flagged (annotated, not
    failed — the bench contract is always rc=0); a within-tolerance or
    fraction-less row passes untouched."""
    bench = _load_bench_module()
    cache = tmp_path / "bench_tpu_cache.json"
    prev = {
        "metric": "tp_columnwise_gemm_pallas_8192x8192x8192_bf16",
        "world_size": 1,
        "roofline_frac": 0.80,
        "captured_at": "2026-08-01T00:00:00Z",
    }
    cache.write_text(json.dumps([prev]))
    monkeypatch.setattr(bench, "CACHE_PATH", str(cache))

    fresh = dict(prev, roofline_frac=0.60, captured_at=None)
    bench._check_roofline_regression(fresh)
    assert fresh["roofline_regression"] is True
    assert fresh["roofline_frac_prev"] == 0.80

    ok = dict(prev, roofline_frac=0.75)
    bench._check_roofline_regression(ok)
    assert "roofline_regression" not in ok

    # env-tunable tolerance: 30% makes the 0.60 row acceptable
    monkeypatch.setenv("DDLB_TPU_BENCH_ROOFLINE_TOL", "0.30")
    loose = dict(prev, roofline_frac=0.60)
    bench._check_roofline_regression(loose)
    assert "roofline_regression" not in loose

    # no fraction (pre-perfmodel row or cpu fallback): a no-op
    bare = {"metric": prev["metric"], "world_size": 1}
    bench._check_roofline_regression(bare)
    assert "roofline_regression" not in bare

    # a different shape's capture is not a comparator
    other = dict(prev, metric="tp_columnwise_gemm_pallas_512x512x512_bf16",
                 roofline_frac=0.10)
    bench._check_roofline_regression(other)
    assert "roofline_regression" not in other
