"""Failure detection (hung-worker timeout) and sweep resume-from-CSV.

Both close gaps SURVEY.md section 5 identifies in the reference: a hung
child blocks ``queue.get`` forever (benchmark.py:369, "no retries, no
timeouts"), and the incremental CSV is the only resumable artifact but
nothing consumes it.
"""

import os

import numpy as np
import pytest

from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

SHAPE = dict(m=128, n=32, k=64)


def test_worker_timeout_requires_subprocess():
    with pytest.raises(ValueError, match="subprocess"):
        PrimitiveBenchmarkRunner(
            "tp_columnwise",
            implementations={"jax_spmd_0": {}},
            worker_timeout=5.0,
            **SHAPE,
        )


def test_resume_refused_multiprocess(monkeypatch, tmp_path):
    monkeypatch.setenv("DDLB_TPU_NUM_PROCESSES", "2")
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"jax_spmd_0": {}},
        resume=True,
        output_csv=str(tmp_path / "r.csv"),
        **SHAPE,
    )
    with pytest.raises(ValueError, match="single-process"):
        runner.run()


def test_resume_skips_completed_rows(tmp_path):
    csv = str(tmp_path / "sweep.csv")
    common = dict(
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        **SHAPE,
    )
    df1 = PrimitiveBenchmarkRunner("tp_columnwise", **common).run()
    assert len(df1) == 1

    # second run adds an implementation; the recorded one is skipped
    common["implementations"] = {
        "jax_spmd_0": {"implementation": "jax_spmd"},
        "compute_only_0": {"implementation": "compute_only"},
    }
    df2 = PrimitiveBenchmarkRunner(
        "tp_columnwise", resume=True, **common
    ).run()
    assert list(df2["implementation"]) == ["compute_only_0"]

    import pandas as pd

    full = pd.read_csv(csv)
    assert sorted(full["implementation"]) == ["compute_only_0", "jax_spmd_0"]

    # a third resume run with nothing new is a no-op
    df3 = PrimitiveBenchmarkRunner(
        "tp_columnwise", resume=True, **common
    ).run()
    assert len(df3) == 0


def test_resume_retries_error_rows(tmp_path):
    """A crashed/timed-out row (non-empty error) is retried on resume."""
    csv = str(tmp_path / "sweep.csv")
    common = dict(
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        **SHAPE,
    )
    # bogus option -> crash-isolation error row
    PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={
            "jax_spmd_0": {"implementation": "jax_spmd", "bogus": 1}
        },
        **common,
    ).run()
    df = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        resume=True,
        **common,
    ).run()
    assert len(df) == 1  # retried, not skipped
    assert df.iloc[0]["error"] == ""


def test_resume_distinguishes_primitives(tmp_path):
    """Primitives sharing one CSV do not false-skip each other."""
    csv = str(tmp_path / "sweep.csv")
    common = dict(
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        m=128, n=32, k=64,
    )
    PrimitiveBenchmarkRunner("tp_columnwise", **common).run()
    df = PrimitiveBenchmarkRunner("tp_rowwise", resume=True, **common).run()
    assert len(df) == 1  # same impl/shape/dtype, different primitive


def test_cli_resume_requires_fixed_csv():
    from ddlb_tpu.cli.benchmark import run_benchmark

    cfg = {
        "benchmark": {
            "primitive": "tp_columnwise",
            "m": [128], "n": [32], "k": [64],
            "implementations": [{"name": "jax_spmd"}],
            "resume": True,
            "output_csv": "results/x_{timestamp}.csv",
        }
    }
    with pytest.raises(ValueError, match="fixed output_csv"):
        run_benchmark(cfg)


def test_resume_widened_option_sweep(tmp_path):
    """Editing the sweep renumbers impl_ids; resume must match by options,
    not position: only the genuinely new config runs."""
    csv = str(tmp_path / "sweep.csv")
    common = dict(
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        **SHAPE,
    )
    PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={
            "jax_spmd_0": {"implementation": "jax_spmd", "order": "AG_before"},
        },
        **common,
    ).run()
    # widened sweep: AG_after now takes slot 0
    df = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={
            "jax_spmd_0": {"implementation": "jax_spmd", "order": "AG_after"},
            "jax_spmd_1": {"implementation": "jax_spmd", "order": "AG_before"},
        },
        resume=True,
        **common,
    ).run()
    assert len(df) == 1
    assert df.iloc[0]["option"] == "order=AG_after;transport=ici"


def test_resume_key_matches_recorded_option_column(tmp_path):
    """ADVICE r1: the resume key must be derived through the SAME merge
    path the worker records (OptionsManager.parse), including dropping
    keys that bind to named Primitive.__init__ params (seed), so resume
    cannot fail open and re-run completed rows."""
    csv = str(tmp_path / "sweep.csv")
    common = dict(
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        **SHAPE,
    )
    spec = {"implementation": "jax_spmd", "order": "AG_after", "seed": 7}
    PrimitiveBenchmarkRunner(
        "tp_columnwise", implementations={"jax_spmd_0": dict(spec)}, **common
    ).run()
    df = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"jax_spmd_0": dict(spec)},
        resume=True,
        **common,
    ).run()
    assert len(df) == 0  # skipped: key matched the recorded option column


def test_resume_key_option_repr_parity(tmp_path):
    """For every registered implementation of every primitive, the option
    component of the resume key equals the option string the worker would
    record for a default-options run."""
    from ddlb_tpu.benchmark import _format_options
    from ddlb_tpu.options import OptionsManager
    from ddlb_tpu.primitives.registry import (
        ALLOWED_PRIMITIVES,
        implementation_names,
        load_impl_class,
    )

    for primitive in ALLOWED_PRIMITIVES:
        runner = PrimitiveBenchmarkRunner(
            primitive, implementations={}, output_csv=None, **SHAPE
        )
        for base in implementation_names(primitive):
            cls = load_impl_class(primitive, base)
            recorded = _format_options(
                OptionsManager(*cls.option_schema()).parse({})
            )
            key = runner._resume_key(f"{base}_0", {"implementation": base})
            assert key[2] == recorded, (primitive, base)


def test_resume_legacy_csv_rejected(tmp_path):
    import pandas as pd

    path = tmp_path / "legacy.csv"
    pd.DataFrame(
        [{"implementation": "jax_spmd_0", "m": 128, "n": 32, "k": 64,
          "dtype": "float32"}]
    ).to_csv(path, index=False)
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        resume=True,
        output_csv=str(path),
        **SHAPE,
    )
    with pytest.raises(ValueError, match="predates resume"):
        runner.run()


def test_resume_different_shape_not_skipped(tmp_path):
    csv = str(tmp_path / "sweep.csv")
    common = dict(
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
    )
    PrimitiveBenchmarkRunner("tp_columnwise", **SHAPE, **common).run()
    df = PrimitiveBenchmarkRunner(
        "tp_columnwise", m=256, n=32, k=64, resume=True, **common
    ).run()
    assert len(df) == 1  # same impl, new shape -> runs


def test_resume_across_retried_row(tmp_path, monkeypatch):
    """ISSUE 4: a row that RECOVERED via the self-healing retry path
    (retries > 0, valid=True) is a completed measurement — resume must
    skip it, not re-run it; and the recorded row carries the retry
    attribution columns."""
    import json

    from ddlb_tpu import faults

    csv = str(tmp_path / "sweep.csv")
    common = dict(
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        retry_backoff_s=0.01,
        **SHAPE,
    )
    monkeypatch.setenv(
        "DDLB_TPU_FAULT_PLAN",
        json.dumps({"seed": 0, "rules": [
            {"site": "worker.warmup", "kind": "transient_error",
             "fail_attempts": 1},
        ]}),
    )
    faults.reset()
    try:
        df1 = PrimitiveBenchmarkRunner(
            "tp_columnwise", max_retries=1, **common
        ).run()
    finally:
        monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
        faults.reset()
    assert len(df1) == 1
    assert df1.iloc[0]["valid"] == True  # noqa: E712
    assert df1.iloc[0]["retries"] == 1
    assert df1.iloc[0]["fault_injected"] == "worker.warmup"

    # the recovered row is complete: a fault-free resume skips it
    df2 = PrimitiveBenchmarkRunner(
        "tp_columnwise", resume=True, **common
    ).run()
    assert len(df2) == 0

    import pandas as pd

    on_disk = pd.read_csv(csv)
    assert len(on_disk) == 1  # exactly one recorded row for the config
    assert int(on_disk.iloc[0]["retries"]) == 1


def test_resume_retries_row_with_exhausted_retries(tmp_path, monkeypatch):
    """A row whose retry budget ran out (error recorded) is NOT complete:
    resume runs it again, and the clean re-run supersedes it."""
    import json

    from ddlb_tpu import faults

    csv = str(tmp_path / "sweep.csv")
    common = dict(
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        retry_backoff_s=0.01,
        **SHAPE,
    )
    monkeypatch.setenv(
        "DDLB_TPU_FAULT_PLAN",
        json.dumps({"seed": 0, "rules": [
            {"site": "worker.warmup", "kind": "transient_error",
             "fail_attempts": 99},
        ]}),
    )
    faults.reset()
    try:
        df1 = PrimitiveBenchmarkRunner(
            "tp_columnwise", max_retries=1, **common
        ).run()
    finally:
        monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
        faults.reset()
    assert df1.iloc[0]["retries"] == 1
    assert "injected transient fault" in df1.iloc[0]["error"]

    df2 = PrimitiveBenchmarkRunner(
        "tp_columnwise", resume=True, **common
    ).run()
    assert len(df2) == 1  # retried on resume, not skipped
    assert df2.iloc[0]["error"] == ""
    assert df2.iloc[0]["valid"] == True  # noqa: E712


@pytest.mark.slow
def test_hung_worker_killed(tmp_path, monkeypatch):
    """A SILENT hung worker becomes an error row instead of blocking the
    sweep forever. (The original form of this test spun ~10M barriered
    iterations — but the timing loop has beaten the heartbeat channel at
    every iteration since the PR-4 deadline rework, so a spinning child
    is by design slow-but-ALIVE and never killed; the test then hung
    for the whole loop. The hang fault plan produces what worker_timeout
    actually guards against: a child gone silent.)"""
    import json

    from ddlb_tpu import faults

    plan = {
        "seed": 0,
        "rules": [
            {"site": "subprocess.entry", "kind": "hang",
             "fail_attempts": 99},
        ],
    }
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", json.dumps(plan))
    faults.reset()
    try:
        runner = PrimitiveBenchmarkRunner(
            "tp_columnwise",
            implementations={
                "compute_only_0": {"implementation": "compute_only"},
            },
            dtype="float32",
            num_iterations=2,
            num_warmups=0,
            isolation="subprocess",
            worker_timeout=8.0,
            max_retries=0,
            progress=False,
            output_csv=str(tmp_path / "t.csv"),
            **SHAPE,
        )
        df = runner.run()
    finally:
        monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
        faults.reset()
    assert len(df) == 1
    row = df.iloc[0]
    assert row["valid"] == False  # noqa: E712
    assert "TimeoutError" in row["error"]
    assert np.isnan(row["mean time (ms)"])
