"""EPAllToAll (expert-parallel dispatch/GEMM/combine) validation on the CPU
mesh.

Output is row-sharded ``[m/d, n]`` per partition in original token order;
validation routes every token group through its expert on the host oracle.
"""

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 128, 64, 96  # m % d^2 == 0 with d=8


def _check_rowsharded(impl, result):
    assert result.shape == (M, N)
    shard_shapes = {s.data.shape for s in result.addressable_shards}
    assert shard_shapes == {(M // 8, N)}
    assert impl.validate(result)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_jax_spmd(dtype):
    cls = load_impl_class("ep_alltoall", "jax_spmd")
    impl = cls(M, N, K, dtype=dtype)
    _check_rowsharded(impl, impl.run())


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_xla_gspmd(dtype):
    cls = load_impl_class("ep_alltoall", "xla_gspmd")
    impl = cls(M, N, K, dtype=dtype)
    _check_rowsharded(impl, impl.run())


@pytest.mark.parametrize("size", ["sharded", "unsharded"])
def test_compute_only(size):
    cls = load_impl_class("ep_alltoall", "compute_only")
    impl = cls(M, N, K, dtype="float32", size=size)
    result = impl.run()
    assert impl.validate(result)
    if size == "unsharded":
        assert result.shape == (M, N)


@pytest.mark.parametrize("algorithm", ["default", "coll_pipeline"])
def test_overlap_algorithms(algorithm):
    cls = load_impl_class("ep_alltoall", "overlap")
    impl = cls(M, N, K, dtype="float32", algorithm=algorithm, s=2)
    _check_rowsharded(impl, impl.run())


def test_routing_is_not_identity():
    """The routed product must differ from a single shared-weight GEMM —
    guards against an implementation that ignores expert identity."""
    cls = load_impl_class("ep_alltoall", "jax_spmd")
    impl = cls(M, N, K, dtype="float32")
    out = np.asarray(impl.run())
    a, w = impl._host_tokens_experts()
    shared = a @ w[0]
    assert not np.allclose(out, shared, atol=1e-3)


def test_overlap_matches_jax_spmd():
    spmd = load_impl_class("ep_alltoall", "jax_spmd")(M, N, K, dtype="float32")
    ov = load_impl_class("ep_alltoall", "overlap")(
        M, N, K, dtype="float32", algorithm="coll_pipeline", s=2
    )
    np.testing.assert_allclose(
        np.asarray(spmd.run()), np.asarray(ov.run()), atol=1e-4
    )


def test_int32_exact():
    cls = load_impl_class("ep_alltoall", "jax_spmd")
    impl = cls(M, N, K, dtype="int32")
    assert impl.validate(impl.run())


def test_shape_constraints():
    cls = load_impl_class("ep_alltoall", "jax_spmd")
    with pytest.raises(ValueError, match="partitions"):
        cls(M + 8, N, K)  # not divisible by d^2=64
    ov = load_impl_class("ep_alltoall", "overlap")
    with pytest.raises(ValueError, match="coll_pipeline"):
        ov(M, N, K, algorithm="coll_pipeline", s=3)
    with pytest.raises(ValueError, match="Unknown option"):
        cls(M, N, K, bogus=1)


class TestPallasMember:
    """Hand-kernel slot (VERDICT r2 #6): fused RDMA all-to-all program +
    the xla_collective comparator, both through the member contract."""

    def test_xla_collective_validates(self):
        cls = load_impl_class("ep_alltoall", "pallas")
        impl = cls(256, 128, 128, dtype="float32",
                   algorithm="xla_collective", block_n=128, block_k=128)
        assert impl.validate(impl.run())

    def test_a2a_rdma_validates(self):
        cls = load_impl_class("ep_alltoall", "pallas")
        impl = cls(256, 128, 128, dtype="float32",
                   algorithm="a2a_rdma", block_n=128, block_k=128)
        assert impl.validate(impl.run())

    def test_a2a_rdma_race_detector_clean(self):
        """The distributed interpreter's race detector runs clean on the
        fused dispatch/GEMM/combine protocol at d=8."""
        cls = load_impl_class("ep_alltoall", "pallas")
        impl = cls(256, 128, 128, dtype="float32", algorithm="a2a_rdma",
                   block_n=128, block_k=128, detect_races=True)
        assert impl.validate(impl.run())

    def test_dead_option_rejected(self):
        cls = load_impl_class("ep_alltoall", "pallas")
        with pytest.raises(ValueError, match="no effect"):
            cls(256, 128, 128, algorithm="a2a_rdma", block_m=256)
        with pytest.raises(ValueError, match="no effect"):
            cls(256, 128, 128, algorithm="xla_collective",
                detect_races=True)

    def test_bf16(self):
        cls = load_impl_class("ep_alltoall", "pallas")
        impl = cls(256, 128, 128, dtype="bfloat16", algorithm="a2a_rdma",
                   block_n=128, block_k=128)
        assert impl.validate(impl.run())
