"""Context-parallel ring attention validates against full causal attention.

This family has no reference analogue (the reference has no attention op,
SURVEY.md section 2.5); validation is against a single-device numpy
softmax-attention oracle, same spirit as the GEMM primitives' runtime
validation.
"""

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 128, 64, 16  # seq=128, 4 heads x head_dim=16


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("skip", [True, False])
def test_ring(dtype, skip):
    cls = load_impl_class("cp_ring_attention", "ring")
    impl = cls(M, N, K, dtype=dtype, skip_masked_blocks=skip)
    result = impl.run()
    assert result.shape == (M, N // K, K)
    assert impl.validate(result)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_allgather(dtype):
    cls = load_impl_class("cp_ring_attention", "allgather")
    impl = cls(M, N, K, dtype=dtype)
    result = impl.run()
    assert impl.validate(result)


@pytest.mark.parametrize("size", ["sharded", "unsharded"])
def test_compute_only(size):
    cls = load_impl_class("cp_ring_attention", "compute_only")
    impl = cls(M, N, K, dtype="float32", size=size)
    result = impl.run()
    assert impl.validate(result)
    rows = M if size == "unsharded" else M // impl.num_partitions
    assert result.shape == (rows, N // K, K)


def test_flops_override():
    cls = load_impl_class("cp_ring_attention", "ring")
    impl = cls(M, N, K, dtype="float32")
    assert impl.flops() == 2.0 * M * M * N  # causal half of 4*m^2*n
    # window census: min(window, q+1) live keys per query
    w = 16
    impl_w = cls(M, N, K, dtype="float32", window=w)
    assert impl_w.flops() == 4.0 * (w * M - w * (w - 1) / 2.0) * N
    # a band covering the whole triangle reports the causal census
    impl_big = cls(M, N, K, dtype="float32", window=M)
    assert impl_big.flops() == 2.0 * M * M * N


class TestWindowSweep:
    """window > 0 across every member, validated against the windowed
    oracle — the band crosses chunk boundaries on the sharded members and
    the ring members skip hops entirely behind it."""

    W = 48  # spans 1-2 chunks at M=128 on 8 partitions (s_loc=16)

    @pytest.mark.parametrize(
        "impl,opts",
        [
            ("ring", {"skip_masked_blocks": True}),
            ("ring", {"skip_masked_blocks": False}),
            ("ring_flash", {"block_q": 8, "block_kv": 8}),
            ("ring_flash",
             {"block_q": 8, "block_kv": 8, "skip_masked_blocks": False}),
            ("allgather", {}),
            ("flash", {"block_q": 16, "block_kv": 16}),
            ("ulysses", {"compute": "einsum"}),
            ("ulysses",
             {"compute": "flash", "block_q": 16, "block_kv": 16}),
            ("compute_only", {"size": "unsharded"}),
        ],
        ids=[
            "ring-skip", "ring-noskip", "ring_flash", "ring_flash-noskip",
            "allgather", "flash", "ulysses-einsum", "ulysses-flash",
            "compute_only",
        ],
    )
    def test_members_validate_windowed(self, impl, opts):
        cls = load_impl_class("cp_ring_attention", impl)
        # ulysses shards heads over the 8 partitions: give it 8 heads
        n = 8 * K if impl == "ulysses" else N
        inst = cls(M, n, K, dtype="float32", window=self.W, **opts)
        assert inst.validate(inst.run())

    def test_window_with_gqa(self):
        cls = load_impl_class("cp_ring_attention", "ring")
        inst = cls(M, N, K, dtype="float32", window=self.W, n_kv_heads=2)
        assert inst.validate(inst.run())

    def test_window_changes_result(self):
        cls = load_impl_class("cp_ring_attention", "ring")
        full = np.asarray(cls(M, N, K, dtype="float32").run(), np.float32)
        win = np.asarray(
            cls(M, N, K, dtype="float32", window=16).run(), np.float32
        )
        assert float(np.max(np.abs(full - win))) > 1e-3


def test_shape_constraints():
    cls = load_impl_class("cp_ring_attention", "ring")
    with pytest.raises(ValueError, match="divisible by partitions"):
        cls(M + 1, N, K)
    with pytest.raises(ValueError, match="model width"):
        cls(M, 65, K)
    with pytest.raises(ValueError, match="floating"):
        cls(M, N, K, dtype="int32")


def test_ring_matches_allgather_exactly_fp32():
    ring = load_impl_class("cp_ring_attention", "ring")(M, N, K, dtype="float32")
    ag = load_impl_class("cp_ring_attention", "allgather")(M, N, K, dtype="float32")
    r1 = np.asarray(ring.run(), np.float32)
    r2 = np.asarray(ag.run(), np.float32)
    np.testing.assert_allclose(r1, r2, rtol=0, atol=1e-5)


def test_runner_integration(tmp_path):
    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    runner = PrimitiveBenchmarkRunner(
        "cp_ring_attention",
        m=M,
        n=N,
        k=K,
        implementations={
            "ring_0": {"implementation": "ring"},
            "allgather_0": {"implementation": "allgather"},
        },
        dtype="float32",
        # one iteration: Throughput = mean(flops/t) and mean time = mean(t)
        # only multiply back to the exact flop count when N == 1 (mean of
        # reciprocals); more iterations made this flaky on noisy CPU
        num_iterations=1,
        num_warmups=1,
        output_csv=str(tmp_path / "attn.csv"),
        progress=False,
    )
    df = runner.run()
    assert len(df) == 2
    assert df["valid"].all()
    # attention flops (2*m^2*n), not the GEMM 2*m*n*k
    expect_gflops = 2.0 * M * M * N / 1e9
    row = df.iloc[0]
    assert abs(
        row["Throughput (TFLOPS)"] * row["mean time (ms)"] - expect_gflops
    ) / expect_gflops < 1e-6


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_flash(dtype):
    cls = load_impl_class("cp_ring_attention", "flash")
    impl = cls(M, N, K, dtype=dtype, block_q=16, block_kv=16)
    result = impl.run()
    assert result.shape == (M, N // K, K)
    assert impl.validate(result)


def test_flash_kernel_direct_interpret():
    from ddlb_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(5)
    s, h, dh = 64, 2, 16
    q = np.asarray(rng.uniform(-1, 1, (s, h, dh)), np.float32)
    k = np.asarray(rng.uniform(-1, 1, (s, h, dh)), np.float32)
    v = np.asarray(rng.uniform(-1, 1, (s, h, dh)), np.float32)
    import jax.numpy as jnp

    out = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            scale=dh ** -0.5, block_q=16, block_kv=16, interpret=True,
        )
    )
    # oracle per head
    for head in range(h):
        sc = (q[:, head] @ k[:, head].T) * dh ** -0.5
        mask = np.tril(np.ones((s, s), bool))
        sc = np.where(mask, sc, -np.inf)
        sc -= sc.max(-1, keepdims=True)
        p = np.exp(sc)
        p /= p.sum(-1, keepdims=True)
        np.testing.assert_allclose(
            out[:, head], p @ v[:, head], rtol=0, atol=1e-5
        )


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_ulysses(dtype):
    n = 8 * K  # 8 heads, one per simulated device
    cls = load_impl_class("cp_ring_attention", "ulysses")
    impl = cls(M, n, K, dtype=dtype)
    result = impl.run()
    assert result.shape == (M, n // K, K)
    assert impl.validate(result)


def test_ulysses_flash_compute():
    cls = load_impl_class("cp_ring_attention", "ulysses")
    impl = cls(M, 8 * K, K, dtype="float32", compute="flash",
               block_q=16, block_kv=16)
    result = impl.run()
    assert impl.validate(result)


def test_ulysses_head_constraint():
    cls = load_impl_class("cp_ring_attention", "ulysses")
    with pytest.raises(ValueError, match="num_heads"):
        cls(M, 3 * K, K)  # 3 heads over 8 devices


def test_ulysses_matches_allgather_exactly_fp32():
    n = 8 * K  # 8 heads so the all-to-all divides evenly
    uly = load_impl_class("cp_ring_attention", "ulysses")(M, n, K, dtype="float32")
    ag = load_impl_class("cp_ring_attention", "allgather")(M, n, K, dtype="float32")
    r1 = np.asarray(uly.run(), np.float32)
    r2 = np.asarray(ag.run(), np.float32)
    np.testing.assert_allclose(r1, r2, rtol=0, atol=1e-5)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("blocks", [(16, 16), (8, 8)])
def test_ring_flash(dtype, blocks):
    """Ring communication + flash-kernel compute (interpret mode on CPU);
    the (8, 8) case exercises multiple (qi, kj) grid blocks per chunk —
    carried-accumulator revisiting and the block-granular causal guard."""
    bq, bkv = blocks
    cls = load_impl_class("cp_ring_attention", "ring_flash")
    impl = cls(M, N, K, dtype=dtype, block_q=bq, block_kv=bkv)
    result = impl.run()
    assert result.shape == (M, N // K, K)
    assert impl.validate(result)


@pytest.mark.parametrize("skip", [True, False])
def test_ring_flash_matches_ring(skip):
    rf = load_impl_class("cp_ring_attention", "ring_flash")(
        M, N, K, dtype="float32", block_q=16, block_kv=8,
        skip_masked_blocks=skip,
    )
    ring = load_impl_class("cp_ring_attention", "ring")(M, N, K, dtype="float32")
    np.testing.assert_allclose(
        np.asarray(rf.run()), np.asarray(ring.run()), atol=2e-5
    )


class TestGQASweep:
    """n_kv_heads on the family: K/V operands (and therefore the ring /
    all-to-all wire bytes) shrink by the group factor; every member must
    still match the grouped-attention oracle."""

    @pytest.mark.parametrize(
        "impl,opts",
        [
            ("compute_only", {"size": "unsharded"}),
            ("allgather", {}),
            ("ring", {}),
            ("flash", {"block_q": 16, "block_kv": 16}),
            ("ring_flash", {"block_q": 16, "block_kv": 16}),
        ],
    )
    def test_members_validate_with_gqa(self, impl, opts):
        cls = load_impl_class("cp_ring_attention", impl)
        inst = cls(128, 256, 32, dtype="float32", n_kv_heads=2, **opts)
        assert inst.validate(inst.run())

    def test_ulysses_gqa_needs_divisible_kv_heads(self):
        cls = load_impl_class("cp_ring_attention", "ulysses")
        # 8 kv heads / 8 devices: fine
        inst = cls(128, 256, 32, dtype="float32", n_kv_heads=8)
        assert inst.validate(inst.run())
        with pytest.raises(ValueError, match="kv heads"):
            cls(128, 256, 32, dtype="float32", n_kv_heads=2)

    def test_indivisible_group_rejected(self):
        cls = load_impl_class("cp_ring_attention", "ring")
        with pytest.raises(ValueError, match="n_kv_heads"):
            cls(128, 256, 32, dtype="float32", n_kv_heads=3)

    def test_kv_operands_shrink(self):
        cls = load_impl_class("cp_ring_attention", "ring")
        inst = cls(128, 256, 32, dtype="float32", n_kv_heads=2)
        q, k, v = inst.get_inputs()
        assert q.shape[1] == 8 and k.shape[1] == 2 and v.shape[1] == 2
