"""Continuous-batching engine: scheduling changes, tokens never do.

The oracle is ``make_generate_fn`` on the engine's own mesh: the block
router's expert assignment is slot-stable, so a completion that ran in
slot ``s`` must equal row ``s`` of a greedy generate whose batch carries
that prompt in row ``s``. Every test reduces to that integer equality —
through staggered admissions, slot reuse across waves, eos exits, and
the int8 cache.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _cfg(**kw):
    from ddlb_tpu.models.transformer import TransformerConfig

    kw.setdefault("attn_kernel", "einsum")
    return TransformerConfig(
        vocab=64, d_model=32, n_heads=4, d_ff=64,
        layers_per_stage=2, microbatches=1,
        **kw,
    )


def _engine(cfg, B=4, S_max=40, eos_id=None, **engine_kw):
    from ddlb_tpu.models.decode import make_decode_fn
    from ddlb_tpu.models.serving import ContinuousBatchingEngine
    from ddlb_tpu.models.transformer import init_params
    from ddlb_tpu.runtime import Runtime

    mesh = Runtime().mesh(("dp", "tp"), shape=(1, 2))
    params = init_params(cfg, pp=1, n_experts=2, seed=0)
    _, sh = make_decode_fn(mesh, cfg)
    params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    eng = ContinuousBatchingEngine(
        mesh, cfg, params, max_batch=B, max_len=S_max, eos_id=eos_id,
        **engine_kw,
    )
    return eng, mesh, params


def _oracle_chain(mesh, cfg, params, prompt, slot, B, n_new):
    """Row ``slot`` of a greedy generate over a batch carrying ``prompt``
    in every row (attention and routing are per-sequence, so only the
    row index — the expert assignment — matters)."""
    from ddlb_tpu.models.decode import init_cache, make_generate_fn

    gen, _ = make_generate_fn(mesh, cfg, n_new=n_new)
    S0 = prompt.size
    batch = jnp.asarray(np.broadcast_to(prompt, (B, S0)).copy())
    cache = init_cache(cfg, B, S0 + n_new, mesh=mesh)
    return np.asarray(jax.jit(gen)(params, cache, batch))[slot]


def _prompts(n, S0, vocab=64, seed=5):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, S0).astype(np.int32) for _ in range(n)]


class TestLosslessScheduling:
    def test_single_request_matches_generate(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, mesh, params = _engine(cfg)
        (prompt,) = _prompts(1, 8)
        eng.submit(Request(prompt, max_new=6))
        done = eng.run()
        assert len(done) == 1
        c = done[0]
        want = _oracle_chain(mesh, cfg, params, prompt, c.slot, eng.B, 6)
        np.testing.assert_array_equal(c.tokens, want)
        assert c.finished_by == "max_new"

    @pytest.mark.parametrize("kv_cache", ["bf16", "int8"])
    def test_staggered_waves_and_slot_reuse(self, kv_cache):
        """6 requests with different lengths-of-generation through 4
        slots: some finish early, their slots are re-admitted mid-flight
        (wave 2 reuses caches holding a previous occupant's stale rows),
        and every completion still equals its slot's oracle chain."""
        from ddlb_tpu.models.serving import Request

        cfg = _cfg(kv_cache=kv_cache)
        eng, mesh, params = _engine(cfg)
        prompts = _prompts(6, 8)
        new_counts = [3, 7, 2, 5, 4, 6]
        for p, n in zip(prompts, new_counts):
            eng.submit(Request(p, max_new=n))
        done = eng.run()
        assert len(done) == 6
        assert eng.stats.admissions == 6
        # continuous batching actually happened: more requests than slots
        # and at least one admission after the first tick
        assert any(c.admitted_at_step > 0 for c in done)
        for c in done:
            want = _oracle_chain(
                mesh, cfg, params, prompts[c.request_index], c.slot,
                eng.B, new_counts[c.request_index],
            )
            np.testing.assert_array_equal(
                c.tokens, want,
                err_msg=f"request {c.request_index} in slot {c.slot}",
            )

    def test_varied_prompt_lengths(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, mesh, params = _engine(cfg)
        prompts = [_prompts(1, s, seed=s)[0] for s in (4, 8, 12, 6, 10)]
        for p in prompts:
            eng.submit(Request(p, max_new=4))
        done = eng.run()
        assert len(done) == 5
        for c in done:
            want = _oracle_chain(
                mesh, cfg, params, prompts[c.request_index], c.slot,
                eng.B, 4,
            )
            np.testing.assert_array_equal(c.tokens, want)

    def test_eos_frees_slot_early(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, mesh, params = _engine(cfg)
        (prompt,) = _prompts(1, 8)
        # find what the chain actually emits, then make its 3rd new
        # token the eos: the engine must stop there, tokens ending in eos
        probe = _oracle_chain(mesh, cfg, params, prompt, 0, 4, 6)
        eos = int(probe[8 + 2])
        eng2, mesh2, params2 = _engine(cfg, eos_id=eos)
        eng2.submit(Request(prompt, max_new=6))
        done = eng2.run()
        c = done[0]
        assert c.finished_by == "eos"
        assert c.tokens[-1] == eos
        np.testing.assert_array_equal(c.tokens, probe[: 8 + 3])

    def test_occupancy_stats(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, _, _ = _engine(cfg)
        for p in _prompts(4, 8):
            eng.submit(Request(p, max_new=5))
        eng.run()
        assert eng.stats.steps > 0
        assert 0.0 < eng.stats.occupancy <= 1.0
        assert eng.stats.generated == 4 * 5


class TestSharedPrefix:
    """Prefix caching: admissions reuse the shared-prefix KV rows and
    prefill only the suffix — and the tokens still equal the per-slot
    greedy oracle of the FULL prompt (the lossless bar, again)."""

    @pytest.mark.parametrize(
        "kv_cache,attn_kernel",
        [("bf16", "einsum"), ("int8", "einsum"), ("bf16", "flash")],
        ids=["bf16", "int8", "bf16-flash-prefill"],
    )
    def test_prefix_hits_are_lossless(self, kv_cache, attn_kernel):
        # the flash case pins the cross-kernel claim: the oracle chain
        # and non-prefix admissions prefill through the flash kernel
        # while prefix hits chunk-decode with einsum cache attention —
        # tokens must still match
        from ddlb_tpu.models.serving import Request

        cfg = _cfg(kv_cache=kv_cache, rope=True, attn_kernel=attn_kernel)
        eng, mesh, params = _engine(cfg)
        rng = np.random.default_rng(9)
        prefix = rng.integers(1, 64, 6).astype(np.int32)
        eng.set_shared_prefix(prefix)
        prompts = [
            np.concatenate([prefix, rng.integers(1, 64, s).astype(np.int32)])
            for s in (3, 5, 2, 4, 6)
        ]
        for p in prompts:
            eng.submit(Request(p, max_new=4))
        done = eng.run()
        assert len(done) == 5
        assert eng.stats.prefix_hits == 5
        assert eng.stats.prefill_tokens_saved == 5 * prefix.size
        for c in done:
            want = _oracle_chain(
                mesh, cfg, params, prompts[c.request_index], c.slot,
                eng.B, 4,
            )
            np.testing.assert_array_equal(
                c.tokens, want,
                err_msg=f"request {c.request_index} in slot {c.slot}",
            )

    def test_mismatch_and_exact_prefix_fall_back(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, mesh, params = _engine(cfg)
        rng = np.random.default_rng(10)
        prefix = rng.integers(1, 64, 6).astype(np.int32)
        eng.set_shared_prefix(prefix)
        other = rng.integers(1, 64, 8).astype(np.int32)
        other[0] = (prefix[0] + 1) % 64  # diverges at token 0
        for p in (other, prefix.copy()):  # mismatch; prompt == prefix
            eng.submit(Request(p, max_new=3))
        done = eng.run()
        assert len(done) == 2
        assert eng.stats.prefix_hits == 0  # both took the full prefill
        for c in done:
            prompt = (other, prefix)[c.request_index]
            want = _oracle_chain(
                mesh, cfg, params, prompt, c.slot, eng.B, 3
            )
            np.testing.assert_array_equal(c.tokens, want)

    def test_prefix_survives_reset(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, mesh, params = _engine(cfg)
        rng = np.random.default_rng(11)
        prefix = rng.integers(1, 64, 4).astype(np.int32)
        eng.set_shared_prefix(prefix)
        prompt = np.concatenate(
            [prefix, rng.integers(1, 64, 4).astype(np.int32)]
        )
        eng.submit(Request(prompt, max_new=3))
        first = eng.run()[0].tokens
        eng.reset()
        eng.submit(Request(prompt, max_new=3))
        again = eng.run()[0].tokens
        np.testing.assert_array_equal(first, again)
        assert eng.stats.prefix_hits == 1  # post-reset stats count anew

    def test_bad_prefix_rejected_and_none_clears(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, _, _ = _engine(cfg)
        with pytest.raises(ValueError, match="non-empty"):
            eng.set_shared_prefix(np.zeros((0,), np.int32))
        rng = np.random.default_rng(12)
        prefix = rng.integers(1, 64, 4).astype(np.int32)
        eng.set_shared_prefix(prefix)
        eng.set_shared_prefix(None)  # cleared: back to full prefills
        prompt = np.concatenate(
            [prefix, rng.integers(1, 64, 4).astype(np.int32)]
        )
        eng.submit(Request(prompt, max_new=2))
        eng.run()
        assert eng.stats.prefix_hits == 0


class TestServeMember:
    """phase=serve through the benchmark worker: the engine drain as a
    measured row, oracle-validated."""

    def _run(self, impl, **opts):
        from ddlb_tpu.benchmark import benchmark_worker

        return benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": f"{impl}_serve",
                "base_implementation": impl,
                "options": {
                    "phase": "serve", "n_new": 5, "n_requests": 6,
                    "batch": 8, "vocab": 64, "n_heads": 8,
                    "attn_kernel": "einsum", **opts,
                },
                "m": 8,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )

    @pytest.mark.parametrize("impl", ["spmd", "compute_only"])
    def test_validates_against_oracle_chains(self, impl):
        row = self._run(impl)
        assert row["error"] == ""
        assert row["valid"] is True

    def test_device_loop_rejected(self):
        # the device_loop backend must produce an error row, not a
        # silent mis-measurement of the host-scheduled drain
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_serve_dl",
                "base_implementation": "spmd",
                "options": {
                    "phase": "serve", "n_new": 4, "batch": 8,
                    "vocab": 64, "n_heads": 8, "attn_kernel": "einsum",
                },
                "m": 8,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": False,
                "time_measurement_backend": "device_loop",
                "barrier_at_each_iteration": False,
            }
        )
        assert "host_clock" in row["error"]


class TestEngineErrors:
    def test_dp_mesh_rejected(self):
        from ddlb_tpu.models.serving import ContinuousBatchingEngine
        from ddlb_tpu.runtime import Runtime

        cfg = _cfg()
        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        with pytest.raises(ValueError, match="dp=1"):
            ContinuousBatchingEngine(mesh, cfg, {}, max_batch=8, max_len=32)

    def test_bad_batch_and_oversize_request(self):
        from ddlb_tpu.models.serving import (
            ContinuousBatchingEngine,
            Request,
        )
        from ddlb_tpu.runtime import Runtime

        cfg = _cfg()
        mesh = Runtime().mesh(("dp", "tp"), shape=(1, 2))
        with pytest.raises(ValueError, match="divisible"):
            ContinuousBatchingEngine(mesh, cfg, {}, max_batch=3, max_len=32)
        # oversize requests fail fast at submission, never mid-drain
        eng, _, _ = _engine(cfg, S_max=12)
        with pytest.raises(ValueError, match="exceeds max_len"):
            eng.submit(Request(np.ones(8, np.int32), max_new=8))

    def test_bad_request(self):
        from ddlb_tpu.models.serving import Request

        with pytest.raises(ValueError, match="non-empty"):
            Request(np.zeros((0,), np.int32), max_new=2)
        with pytest.raises(ValueError, match="max_new"):
            Request(np.ones(4, np.int32), max_new=0)


class TestBucketedPrefill:
    """Prompts pad to power-of-two buckets at admission (the default):
    compile count is O(log S_max), tokens are byte-identical to
    exact-length prefill — the pad tail is causally downstream of every
    real row, so it can never influence the kept logits or cache."""

    LENGTHS = (9, 10, 11, 12, 13, 14, 15, 16, 17, 18)  # 2 buckets: 16, 32

    def _drain(self, bucket):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, _, _ = _engine(cfg, S_max=48, bucket_prefill=bucket)
        rng = np.random.default_rng(21)
        for s in self.LENGTHS:
            eng.submit(Request(rng.integers(1, 64, s).astype(np.int32),
                               max_new=4))
        done = {c.request_index: np.asarray(c.tokens) for c in eng.run()}
        return eng, done

    def test_two_buckets_compile_two_prefills_tokens_identical(self):
        bucketed, tok_b = self._drain(bucket=True)
        exact, tok_e = self._drain(bucket=False)
        assert tok_b.keys() == tok_e.keys()
        for idx in tok_b:
            np.testing.assert_array_equal(tok_b[idx], tok_e[idx])
        # 10 distinct lengths span buckets {16, 32}: two compiled
        # prefill programs vs one per distinct length without bucketing
        assert bucketed._prefill._cache_size() == 2
        assert exact._prefill._cache_size() == len(set(self.LENGTHS))

    def test_prefix_suffix_buckets(self):
        # suffix lengths 1..6 against a 9-token prefix: one chunk
        # compile (bucket 16) where exact-length admission compiles one
        # per distinct suffix length; tokens equal the exact engine's
        from ddlb_tpu.models.serving import Request

        prefix = np.arange(1, 10, dtype=np.int32)
        rng = np.random.default_rng(22)
        prompts = []
        for s in (1, 2, 3, 4, 5, 6):
            prompts.append(np.concatenate(
                [prefix, rng.integers(1, 64, s).astype(np.int32)]
            ))
        outs = []
        engines = []
        for bucket in (True, False):
            cfg = _cfg()
            eng, _, _ = _engine(cfg, S_max=48, bucket_prefill=bucket)
            eng.set_shared_prefix(prefix)
            for p in prompts:
                eng.submit(Request(p, max_new=4))
            outs.append(
                {c.request_index: np.asarray(c.tokens) for c in eng.run()}
            )
            engines.append(eng)
        for idx in outs[0]:
            np.testing.assert_array_equal(outs[0][idx], outs[1][idx])
        assert engines[0].stats.prefix_hits == len(prompts)
        assert engines[0]._chunk._cache_size() == 1
        assert engines[1]._chunk._cache_size() == len({1, 2, 3, 4, 5, 6})
