"""Degraded-world resilience (ISSUE 15): topology fault model,
persistent-straggler indictment, degraded simulator, mitigation policy.

CPU-only and JAX-free except where noted — the fault plan, the health
verdict, the Degradation overlay and the degraded replay are all
stdlib tiers. The end-to-end loop (seeded link_slow -> skew gate ->
indictment -> degraded relaunch -> simulator bracket) is proven by
``scripts/chaos_degrade.py`` (``make chaos-degrade``); these tests pin
the edge cases the ISSUE names.
"""

from __future__ import annotations

import json
import math
import time

import pytest

from ddlb_tpu.faults import plan
from ddlb_tpu.faults.classify import (
    DEGRADED,
    DETERMINISTIC,
    TRANSIENT,
    classify_error,
)
from ddlb_tpu.observatory import health, regress
from ddlb_tpu.perfmodel.cost import (
    degraded_bw,
    degraded_ring_time_s,
    link_slow_extra_s,
    ring_wire_bytes,
)
from ddlb_tpu.perfmodel.specs import get_spec
from ddlb_tpu.perfmodel.topology import (
    Degradation,
    Topology,
    parse_degradation,
)


@pytest.fixture(autouse=True)
def _reset_plan(monkeypatch):
    plan.reset()
    yield
    plan.reset()


def _load(rules, seed=0):
    return plan.load_plan(json.dumps({"seed": seed, "rules": rules}))


# ---------------------------------------------------------------------------
# topology fault kinds (faults.plan)
# ---------------------------------------------------------------------------


class TestTopoFaultRules:
    def test_topo_kinds_need_topo_dict(self):
        with pytest.raises(ValueError, match="topo"):
            plan.FaultRule({"site": "x", "kind": "link_slow"})

    def test_factor_must_be_fraction(self):
        with pytest.raises(ValueError, match="factor"):
            plan.FaultRule(
                {"site": "x", "kind": "chip_slow",
                 "topo": {"index": 0, "factor": 4.0}}
            )

    def test_direction_validated(self):
        with pytest.raises(ValueError, match="direction"):
            plan.FaultRule(
                {"site": "x", "kind": "link_slow",
                 "topo": {"index": 0, "direction": "up", "factor": 0.5}}
            )

    def test_affected_rank_tx_rx_and_chip(self, monkeypatch):
        monkeypatch.setenv("DDLB_TPU_NUM_PROCESSES", "3")
        tx = plan.FaultRule(
            {"site": "x", "kind": "link_slow",
             "topo": {"index": 1, "direction": "tx", "factor": 0.5}}
        )
        rx = plan.FaultRule(
            {"site": "x", "kind": "link_slow",
             "topo": {"index": 2, "direction": "rx", "factor": 0.5}}
        )
        chip = plan.FaultRule(
            {"site": "x", "kind": "chip_slow",
             "topo": {"index": 2, "factor": 0.5}}
        )
        assert tx.affected_rank() == 1
        assert rx.affected_rank() == 0  # (2+1) % 3 wraps the ring
        assert chip.affected_rank() == 2
        assert tx.link_label() == "ici[1->2]"
        assert chip.link_label() == "chip[2]"

    def test_delay_is_the_shared_closed_form(self):
        rule = plan.FaultRule(
            {"site": "x", "kind": "link_slow", "sim_link_gbs": 1e-6,
             "topo": {"index": 0, "factor": 0.25}}
        )
        # 1000 B at 1000 B/s healthy: 1s healthy, 4s at quarter rate
        assert rule.delay_s(1000) == pytest.approx(
            link_slow_extra_s(1000, 1000.0, 0.25)
        )
        assert rule.delay_s(1000) == pytest.approx(3.0)
        assert rule.delay_s(0) == 0.0

    def test_default_rate_is_the_chip_spec(self):
        rule = plan.FaultRule(
            {"site": "x", "kind": "link_slow",
             "topo": {"index": 0, "factor": 0.5}}
        )
        spec = get_spec("cpu-sim")
        assert rule.delay_s(1 << 20) == pytest.approx(
            link_slow_extra_s(1 << 20, spec.link_bw("ici"), 0.5)
        )

    def test_inject_sleeps_only_on_the_affected_rank(self, monkeypatch):
        # delay = 64 B * (1/0.25 - 1) / 3200 B/s = 0.06 s on rank 1 only
        _load([
            {"site": "runtime.collective", "kind": "link_slow",
             "topo": {"index": 1, "direction": "tx", "factor": 0.25},
             "sim_link_gbs": 3.2e-6, "fail_attempts": 99},
        ])
        monkeypatch.setenv("DDLB_TPU_PHYS_RANK", "0")
        t0 = time.monotonic()
        plan.inject("runtime.collective", payload_bytes=64)
        assert time.monotonic() - t0 < 0.05
        monkeypatch.setenv("DDLB_TPU_PHYS_RANK", "1")
        t0 = time.monotonic()
        plan.inject("runtime.collective", payload_bytes=64)
        assert time.monotonic() - t0 >= 0.05

    def test_rx_neighbor_wraps_the_physical_ring(self, monkeypatch):
        """After a degraded relaunch the process count shrinks but slot
        ids keep full-world numbering: the rx receiver must wrap the
        FULL physical ring (DDLB_TPU_PHYS_WORLD), else the fault would
        re-target a surviving healthy slot."""
        monkeypatch.setenv("DDLB_TPU_NUM_PROCESSES", "2")  # shrunk
        monkeypatch.setenv("DDLB_TPU_PHYS_WORLD", "3")     # full ring
        rx = plan.FaultRule(
            {"site": "x", "kind": "link_slow",
             "topo": {"index": 2, "direction": "rx", "factor": 0.5}}
        )
        assert rx.affected_rank() == 0  # (2+1) % 3, never % 2
        assert rx.link_label() == "ici[2->0]"

    def test_physical_rank_dodges_after_exclusion(self, monkeypatch):
        """A degraded relaunch keys fault targeting on the PHYSICAL
        slot: the surviving rank that inherited process id 1 must not
        inherit slot 1's fault."""
        _load([
            {"site": "runtime.collective", "kind": "link_slow",
             "topo": {"index": 1, "direction": "tx", "factor": 0.25},
             "sim_link_gbs": 3.2e-6, "fail_attempts": 99},
        ])
        # the shrunken world's process 1 runs physical slot 2
        monkeypatch.setenv("DDLB_TPU_PROCESS_ID", "1")
        monkeypatch.setenv("DDLB_TPU_PHYS_RANK", "2")
        t0 = time.monotonic()
        plan.inject("runtime.collective", payload_bytes=64)
        assert time.monotonic() - t0 < 0.05

    def test_link_down_raises_degraded_classified_error(self, monkeypatch):
        monkeypatch.setenv("DDLB_TPU_NUM_PROCESSES", "2")
        monkeypatch.setenv("DDLB_TPU_PHYS_RANK", "0")
        _load([
            {"site": "runtime.barrier", "kind": "link_down",
             "topo": {"index": 0, "direction": "tx"}, "fail_attempts": 99},
        ])
        with pytest.raises(ConnectionError, match="link_down.*ici\\[0->1\\]"):
            plan.inject("runtime.barrier", payload_bytes=8)

    def test_new_sites_registered(self):
        assert "overlap.ring_step" in plan.SITES


# ---------------------------------------------------------------------------
# three-way classification
# ---------------------------------------------------------------------------


class TestClassification:
    def test_link_down_degraded_not_transient(self):
        # ConnectionError alone is transient; the link_down shape must
        # win (degraded patterns checked first) — relaunching the same
        # world onto the same dead link just fails again
        err = "ConnectionError: injected link_down at x: ici[0->1] is down"
        assert classify_error(err) == DEGRADED
        assert classify_error("ConnectionError: reset by peer") == TRANSIENT

    def test_plan_validation_errors_stay_deterministic(self):
        # a malformed topo rule raises a ValueError MENTIONING the kind
        # — a config error, not degraded hardware: classifying it
        # degraded would shrink a healthy world per relaunch attempt
        for err in (
            "ValueError: link_slow topo.factor must be in (0, 1], got 1.5",
            "ValueError: topology fault kind 'link_down' needs a 'topo' "
            "dict with at least 'index'",
        ):
            assert classify_error(err) == DETERMINISTIC

    def test_slow_peer_degraded(self):
        assert classify_error(
            "SlowPeer: rank 1 silent for 30.0s while 2 peer(s) kept "
            "beating (freshest 0.4s ago)"
        ) == DEGRADED

    def test_existing_classes_unchanged(self):
        assert classify_error("TimeoutError: hung") == TRANSIENT
        assert classify_error("ValueError: bad shape") == DETERMINISTIC
        assert classify_error("", valid=True) == ""

    def test_link_down_on_two_rank_world_is_fatal_not_degraded(self):
        """ISSUE 15 edge case: the class says DEGRADED but the
        mitigation policy refuses — excluding either endpoint of a
        2-rank world leaves a single-rank non-world."""
        err = "injected link_down at runtime.barrier: ici[0->1] is down"
        assert classify_error(err) == DEGRADED
        assert health.relaunch_policy(2) == "fatal"
        assert health.relaunch_policy(3) == "exclude"
        assert health.relaunch_policy(3, n_excluded=1) == "fatal"


# ---------------------------------------------------------------------------
# persistent-straggler indictment (observatory.health)
# ---------------------------------------------------------------------------


def _obs(rank=1, skew=0.4, unc=0.01, run="r0"):
    return {"rank": rank, "skew_s": skew, "unc_s": unc, "run_id": run}


class TestHealthVerdict:
    def test_single_observation_refused(self):
        v = health.verdict_from_observations([_obs()])
        assert v["status"] == health.TRANSIENT
        assert "never indicts" in v["reason"]

    def test_two_observations_still_refused(self):
        v = health.verdict_from_observations([_obs(), _obs(run="r1")])
        assert v["status"] == health.TRANSIENT

    def test_three_corroborating_rows_indict(self):
        v = health.verdict_from_observations(
            [_obs(run=f"r{i}") for i in range(3)], world=3
        )
        assert v["status"] == health.PERSISTENT
        assert v["rank"] == 1
        assert v["links"] == ["chip[1]", "ici[0->1]", "ici[1->2]"]
        assert v["per_rank"][1]["runs"] == 3

    def test_skew_within_clock_uncertainty_never_indicts(self):
        v = health.verdict_from_observations(
            [_obs(skew=0.3, unc=0.5) for _ in range(6)]
        )
        assert v["status"] == health.HEALTHY
        assert v["qualifying"] == 0

    def test_no_alignment_claim_never_indicts(self):
        v = health.verdict_from_observations(
            [_obs(unc=float("nan")) for _ in range(6)]
        )
        assert v["status"] == health.HEALTHY

    def test_below_noise_floor_never_indicts(self):
        v = health.verdict_from_observations(
            [_obs(skew=0.01, unc=0.0) for _ in range(6)]
        )
        assert v["status"] == health.HEALTHY

    def test_alternating_ranks_classify_transient(self):
        obs = [_obs(rank=i % 2, run=f"r{i}") for i in range(6)]
        v = health.verdict_from_observations(obs)
        assert v["status"] == health.TRANSIENT
        assert v["rank"] == -1
        assert "alternate" in v["reason"]

    def test_dominant_rank_survives_minority_noise(self):
        obs = [_obs(rank=1, run=f"r{i}") for i in range(5)]
        obs.append(_obs(rank=0, run="r9"))
        v = health.verdict_from_observations(obs, world=2)
        assert v["status"] == health.PERSISTENT
        assert v["rank"] == 1

    def test_observations_from_history_and_rows(self):
        row = {
            "straggler_rank": 2, "skew_enter_s": 0.2, "clock_unc_s": 0.01,
            "implementation": "jax_spmd_0",
        }
        records = [
            {"kind": "row", "run_id": "a", "row": row},
            {"kind": "bench", "run_id": "a", "row": row},  # not a row
            {"kind": "row", "run_id": "b", "row": {"valid": True}},  # no skew
        ]
        obs = health.observations_from_history(records)
        assert len(obs) == 1 and obs[0]["rank"] == 2
        assert health.observations_from_history(records, run_id="zzz") == []
        assert len(health.observations_from_rows([row])) == 1

    def test_observations_from_timeline_require_alignment(self):
        coll = {
            "seq": 5, "site": "runtime.collective", "straggler_rank": 1,
            "skew_enter_s": 0.3, "unc_s": 0.005,
        }
        aligned = {"alignment": "barrier", "collectives": [coll],
                   "run_dir": "/x"}
        unaligned = {"alignment": "none", "collectives": [coll]}
        assert len(health.observations_from_timeline(aligned)) == 1
        assert health.observations_from_timeline(unaligned) == []


class TestHealthGate:
    def _rows(self, n=4, rank=1):
        return [
            {
                "straggler_rank": rank, "skew_enter_s": 0.4,
                "clock_unc_s": 0.01, "implementation": "jax_spmd_0",
                "base_implementation": "jax_spmd", "primitive": "tp",
                "option": "-", "m": 1, "n": 1, "k": 1, "chip": "cpu-sim",
                "num_processes": 3,
            }
            for _ in range(n)
        ]

    def test_detect_health_fires_and_ranks_first(self):
        rows = self._rows()
        findings = regress.detect_all(rows, [])
        assert findings and findings[0]["metric"] == "persistent_straggler"
        assert findings[0]["straggler_rank"] == 1
        assert findings[0]["source"] == "health"
        # world derived from the rows' num_processes column: the
        # finding names the neighbor-link candidates, not just the chip
        assert findings[0]["links"] == [
            "chip[1]", "ici[0->1]", "ici[1->2]"
        ]

    def test_detect_health_needs_current_corroboration(self):
        """Old banked indictments must not re-flag clean runs forever."""
        history = [
            {"kind": "row", "run_id": "old", "row": row}
            for row in self._rows()
        ]
        clean = [
            {**row, "straggler_rank": -1, "skew_enter_s": 0.001}
            for row in self._rows()
        ]
        assert regress.detect_health(clean, history) == []

    def test_detect_health_excludes_own_banked_copies(self):
        rows = self._rows(n=2)  # 2 current + 2 banked copies != 3 distinct
        history = [
            {"kind": "row", "run_id": "me", "row": row} for row in rows
        ]
        # with the self-copies excluded only 2 observations remain
        assert regress.detect_health(rows, history, exclude_run="me") == []


# ---------------------------------------------------------------------------
# Degradation overlay + degraded replay
# ---------------------------------------------------------------------------


class TestDegradation:
    def test_parse(self):
        deg = parse_degradation("dcn=0.25,ici1=0")
        assert deg.factors == {"dcn": 0.25}
        assert deg.down == ("ici1",)
        assert deg.factor("dcn") == 0.25
        assert deg.factor("ici1") == 0.0
        assert deg.factor("ici0") == 1.0

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_degradation("")
        with pytest.raises(ValueError):
            parse_degradation("dcn")
        with pytest.raises(ValueError):
            parse_degradation("dcn=fast")

    def test_factor_range_validated(self):
        with pytest.raises(ValueError, match="down"):
            Degradation(factors={"dcn": 0.0})
        with pytest.raises(ValueError):
            Degradation(factors={"dcn": 2.0})

    def test_resource_rates_scale(self):
        topo = Topology(
            chip=get_spec("v5p"), pods=2, ici_mesh=(4, 4)
        ).degraded(parse_degradation("dcn=0.5,ici1=0"))
        healthy = Topology(chip=get_spec("v5p"), pods=2, ici_mesh=(4, 4))
        assert topo.resource_rate("dcn") == pytest.approx(
            degraded_bw(healthy.resource_rate("dcn"), 0.5)
        )
        assert topo.resource_rate("ici1") == 0.0
        assert topo.resource_rate("ici0") == healthy.resource_rate("ici0")
        assert topo.alive_ici_axes() == (0,)
        # the world-spanning flat snake crosses the dead axis: rate 0
        assert topo.flat_bw == 0.0
        assert "!" in topo.name and topo.degradation is not None

    def test_degraded_replay_matches_closed_form(self):
        from ddlb_tpu.simulator.engine import replay
        from ddlb_tpu.simulator.frontends import flat_ring_program

        topo = Topology(chip=get_spec("v5e"), pods=1, ici_mesh=(8,))
        deg = topo.degraded(Degradation(factors={"ici0": 0.25}))
        payload = float(1 << 20)
        got = replay(
            flat_ring_program("psum", payload, deg), deg
        ).makespan_s
        want = degraded_ring_time_s(
            "psum", payload, 8, topo.ici_bw, 0.25
        )
        assert got == pytest.approx(want, rel=1e-12)
        # and the degraded-minus-healthy delta is the per-crossing
        # extra the fault realization sleeps, summed over ring steps
        healthy = replay(
            flat_ring_program("psum", payload, topo), topo
        ).makespan_s
        assert got - healthy == pytest.approx(
            link_slow_extra_s(
                ring_wire_bytes("psum", payload, 8), topo.ici_bw, 0.25
            ),
            rel=1e-9,
        )

    def test_striped_reroutes_around_downed_axis(self):
        from ddlb_tpu.simulator.engine import replay
        from ddlb_tpu.simulator.frontends import striped_program

        topo = Topology(chip=get_spec("v5p"), pods=2, ici_mesh=(8, 8))
        deg = topo.degraded(Degradation(down=("ici1",)))
        payload = float(1 << 24)
        result = replay(striped_program("psum", payload, deg), deg)
        assert math.isfinite(result.makespan_s)
        links = result.link_utilization(deg)
        assert links["ici1"]["bytes"] == 0.0  # the reroute, visible
        assert links["ici0"]["bytes"] > 0.0
        assert result.meta["stripe_axes"] == [0]
        # the healthy twin spreads the same payload across both axes
        healthy = replay(striped_program("psum", payload, topo), topo)
        assert healthy.meta["stripe_axes"] == [0, 1]
        assert links["ici0"]["bytes"] == pytest.approx(
            healthy.link_utilization(topo)["ici0"]["bytes"] * 2, rel=1e-9
        )

    def test_hierarchical_reroutes_intra_axis(self):
        from ddlb_tpu.simulator.frontends import hierarchical_program

        topo = Topology(
            chip=get_spec("v5p"), pods=2, ici_mesh=(8, 8)
        ).degraded(Degradation(down=("ici0",)))
        prog = hierarchical_program("psum", float(1 << 20), topo)
        assert prog.meta["intra_scope"] == "ici1"

    def test_flat_unroutable_replays_infinite(self):
        from ddlb_tpu.simulator.engine import replay
        from ddlb_tpu.simulator.frontends import flat_ring_program

        topo = Topology(
            chip=get_spec("v5p"), pods=2, ici_mesh=(8,)
        ).degraded(Degradation(down=("dcn",)))
        result = replay(
            flat_ring_program("psum", float(1 << 20), topo), topo
        )
        assert math.isinf(result.makespan_s)


# ---------------------------------------------------------------------------
# sim_report --degrade CLI
# ---------------------------------------------------------------------------


class TestSimReportDegrade:
    def test_json_shape_and_graceful_striped(self, capsys):
        from scripts.sim_report import main

        rc = main([
            "--topology", "v5p:4x8x8", "--families", "dp_allreduce",
            "--payload-mib", "16", "--degrade", "ici1=0", "--json",
        ])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        rows = doc["degraded"][0]["families"][0]["rows"]
        by_algo = {r["algo"]: r for r in rows}
        assert not by_algo["flat"]["routable"]
        assert by_algo["striped"]["routable"]
        assert by_algo["striped"]["links"]["ici1"]["bytes"] == 0.0
        # ranked: routable compositions first
        assert rows[-1]["algo"] == "flat"

    def test_bad_spec_exits_2(self):
        from scripts.sim_report import main

        with pytest.raises(SystemExit) as exc:
            main(["--degrade", "nonsense"])
        assert exc.value.code == 2


# ---------------------------------------------------------------------------
# env accessors + row schema
# ---------------------------------------------------------------------------


class TestEnvAndSchema:
    def test_physical_rank_falls_back_to_process_id(self, monkeypatch):
        from ddlb_tpu import envs

        monkeypatch.delenv("DDLB_TPU_PHYS_RANK", raising=False)
        monkeypatch.setenv("DDLB_TPU_PROCESS_ID", "2")
        assert envs.get_physical_rank() == 2
        monkeypatch.setenv("DDLB_TPU_PHYS_RANK", "5")
        assert envs.get_physical_rank() == 5

    def test_world_degraded_flag(self, monkeypatch):
        from ddlb_tpu import envs

        monkeypatch.delenv("DDLB_TPU_WORLD_DEGRADED", raising=False)
        assert envs.get_world_degraded() is False
        monkeypatch.setenv("DDLB_TPU_WORLD_DEGRADED", "1")
        assert envs.get_world_degraded() is True

    def test_row_carries_world_degraded(self, monkeypatch):
        import numpy as np

        from ddlb_tpu.benchmark import make_result_row
        from ddlb_tpu.schema import ROW_COLUMNS

        assert "world_degraded" in ROW_COLUMNS
        monkeypatch.setenv("DDLB_TPU_WORLD_DEGRADED", "1")
        row = make_result_row(
            config={"impl_id": "x", "primitive": "tp_columnwise",
                    "m": 1, "n": 1, "k": 1},
            times_ms=np.array([1.0]),
            flop_count=1.0,
            option_repr="-",
            valid=True,
            error="",
            world_size=1,
            num_processes=1,
            platform="cpu",
        )
        assert row["world_degraded"] is True
