"""Fault-injection harness + self-healing runner (ISSUE 4).

What matters: a seeded plan injects deterministically (same seed, same
sites); the runner retries only transient failures, on the documented
backoff schedule; deterministic failures are classified and recorded
without retry; an impl failing repeatedly is quarantined with cheap
classified rows; and the heartbeat channel extends a slow-but-alive
child's deadline while a silent hang is killed at worker_timeout.
"""

import json
import queue as queue_mod
import time

import numpy as np
import pytest

from ddlb_tpu import faults
from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner, make_result_row
from ddlb_tpu.faults import heartbeat
from ddlb_tpu.faults.classify import (
    DETERMINISTIC,
    TRANSIENT,
    classify_error,
)
from ddlb_tpu.faults.plan import FaultPlan, backoff_delays

SHAPE = dict(m=128, n=32, k=64)


@pytest.fixture(autouse=True)
def _fresh_plan(monkeypatch):
    """Each test starts and ends with no cached plan or site counters."""
    monkeypatch.delenv("DDLB_TPU_FAULT_PLAN", raising=False)
    faults.reset()
    yield
    faults.reset()


def _set_plan(monkeypatch, rules, seed=0):
    monkeypatch.setenv(
        "DDLB_TPU_FAULT_PLAN", json.dumps({"seed": seed, "rules": rules})
    )
    faults.reset()


# ---------------------------------------------------------------------------
# Plan mechanics
# ---------------------------------------------------------------------------


def test_plan_determinism_same_seed_same_sites():
    """Probabilistic rules fire on the same call indices for the same
    seed, in any process — and on different ones for a different seed."""
    def pattern(seed):
        plan = FaultPlan(
            {"seed": seed,
             "rules": [{"site": "s", "kind": "hang", "probability": 0.5,
                        "fail_attempts": 99}]}
        )
        return [
            plan.pick("s", count, {}, attempt=0) is not None
            for count in range(200)
        ]

    a, b, c = pattern(7), pattern(7), pattern(8)
    assert a == b
    assert a != c
    assert 50 < sum(a) < 150  # it is actually probabilistic


def test_plan_env_gating_and_zero_overhead(monkeypatch):
    # unset -> inject is a no-op (and stays one cached None check)
    faults.inject("worker.setup")
    assert not faults.active()
    _set_plan(monkeypatch, [
        {"site": "worker.setup", "kind": "deterministic_error"}
    ])
    assert faults.active()
    with pytest.raises(ValueError, match="injected deterministic"):
        faults.inject("worker.setup")


def test_plan_file_form(tmp_path, monkeypatch):
    path = tmp_path / "plan.json"
    path.write_text(json.dumps({"seed": 1, "rules": [
        {"site": "x", "kind": "transient_error"}
    ]}))
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", str(path))
    faults.reset()
    with pytest.raises(TimeoutError, match="injected transient"):
        faults.inject("x")


def test_rule_site_glob_and_match_filters(monkeypatch):
    _set_plan(monkeypatch, [
        {"site": "worker.*", "kind": "deterministic_error",
         "match": {"impl": "overlap"}, "fail_attempts": 99},
    ])
    # context mismatch: no fire
    with faults.scope(impl="jax_spmd_0"):
        faults.inject("worker.setup")
    # glob + substring context match: fires
    with faults.scope(impl="overlap_3"):
        with pytest.raises(ValueError):
            faults.inject("worker.timing")


def test_fail_attempts_gates_on_retry_attempt(monkeypatch):
    """The transient-recovery shape: attempt 0 faults, attempt 1 clean."""
    _set_plan(monkeypatch, [
        {"site": "s", "kind": "transient_error", "fail_attempts": 1}
    ])
    with faults.scope(attempt=0):
        with pytest.raises(TimeoutError):
            faults.inject("s")
    with faults.scope(attempt=1):
        faults.inject("s")  # no raise


def test_until_expires_rule_by_site_call_count(monkeypatch):
    """The fault-that-clears-mid-run shape (ISSUE 19): a rule with
    ``until: 2`` fires on the site's first two calls and never again —
    the chaos-elastic drill's probation probes depend on the fault
    going quiet while the drill is still running."""
    _set_plan(monkeypatch, [
        {"site": "s", "kind": "transient_error", "until": 2,
         "fail_attempts": 99},
    ])
    with faults.scope(attempt=0):
        for _ in range(2):
            with pytest.raises(TimeoutError):
                faults.inject("s")
        for _ in range(5):
            faults.inject("s")  # expired: quiet forever after


def test_scope_collects_fired_sites(monkeypatch):
    _set_plan(monkeypatch, [
        {"site": "a", "kind": "transient_error", "fail_attempts": 99}
    ])
    with faults.scope() as fs:
        with pytest.raises(TimeoutError):
            faults.inject("a")
        faults.inject("b")  # no rule: not recorded
    assert fs.fired == ["a"]


def test_corrupt_array_and_row(monkeypatch):
    _set_plan(monkeypatch, [
        {"site": "worker.result", "kind": "corrupt", "fail_attempts": 99},
        {"site": "subprocess.result", "kind": "corrupt", "fail_attempts": 99},
    ])
    arr = np.ones(4)
    out = faults.corrupt("worker.result", arr)
    assert not np.allclose(out, arr)
    assert np.allclose(faults.corrupt("other.site", arr), arr)

    row = {"median time (ms)": 1.0, "Throughput (TFLOPS)": 2.0,
           "valid": True, "error": ""}
    row = faults.corrupt_row("subprocess.result", row)
    assert row["valid"] is False
    assert "CorruptedResult" in row["error"]
    assert np.isnan(row["median time (ms)"])
    assert row["error_class"] == DETERMINISTIC


def test_corrupt_pytree_and_inapplicable_value(monkeypatch):
    """Corruption reaches tuple/list leaves; a value it cannot touch is
    passed through WITHOUT being recorded as injected (a chaos CSV must
    never claim a fault that did not happen)."""
    _set_plan(monkeypatch, [
        {"site": "worker.result", "kind": "corrupt", "fail_attempts": 99},
    ])
    with faults.scope() as fs:
        a, b = faults.corrupt("worker.result", (np.ones(2), [np.ones(3)]))
    assert not np.allclose(a, np.ones(2))
    assert not np.allclose(b[0], np.ones(3))
    assert fs.fired == ["worker.result"]
    with faults.scope() as fs:
        out = faults.corrupt("worker.result", object())
    assert fs.fired == []  # inapplicable: passed through, not claimed


def test_fire_listener_announces_fired_rules(monkeypatch):
    _set_plan(monkeypatch, [
        {"site": "subprocess.entry", "kind": "transient_error",
         "fail_attempts": 99},
    ])
    announced = []
    faults.set_fire_listener(lambda site, kind: announced.append((site, kind)))
    with pytest.raises(TimeoutError):
        faults.inject("subprocess.entry")
    assert announced == [("subprocess.entry", "transient_error")]


def test_rule_ranks_selector_targets_one_process(monkeypatch):
    """A world-shared plan with ``ranks: [1]`` fires only in rank 1 —
    the rank-targeted chaos surface of scripts/chaos_launch.py."""
    _set_plan(monkeypatch, [
        {"site": "runtime.barrier", "kind": "transient_error",
         "ranks": [1], "fail_attempts": 99},
    ])
    monkeypatch.setenv("DDLB_TPU_PROCESS_ID", "0")
    faults.inject("runtime.barrier")  # rank 0: no fire
    monkeypatch.setenv("DDLB_TPU_PROCESS_ID", "1")
    with pytest.raises(TimeoutError):
        faults.inject("runtime.barrier")


def test_world_attempt_floors_fail_attempts_gate(monkeypatch):
    """The supervised relaunch exports DDLB_TPU_WORLD_ATTEMPT; a rule
    with the default fail_attempts=1 fires on the first world launch
    and clears on the relaunch, even though the fresh child's scope
    attempt restarts at 0."""
    _set_plan(monkeypatch, [
        {"site": "launch.child", "kind": "transient_error",
         "fail_attempts": 1},
    ])
    with pytest.raises(TimeoutError):
        faults.inject("launch.child")
    monkeypatch.setenv("DDLB_TPU_WORLD_ATTEMPT", "1")
    faults.reset()
    faults.inject("launch.child")  # relaunched world: cleared


def test_malformed_plan_raises(monkeypatch):
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", '{"rules": [{"kind": "hang"}]}')
    faults.reset()
    with pytest.raises(ValueError, match="site"):
        faults.active()


# ---------------------------------------------------------------------------
# Classification + backoff
# ---------------------------------------------------------------------------


def test_classify_error_split():
    assert classify_error("") == ""
    assert classify_error("", valid=False) == DETERMINISTIC  # validation
    assert classify_error("TimeoutError: worker silent for 25s") == TRANSIENT
    assert classify_error("WorkerDied: exit code -9 with no result") == TRANSIENT
    assert classify_error("RESOURCE_EXHAUSTED: out of memory") == TRANSIENT
    assert classify_error("ValueError: m=96 must be divisible") == DETERMINISTIC
    assert classify_error("validation crashed: TypeError: x") == DETERMINISTIC
    assert classify_error("SomethingNovel: who knows") == DETERMINISTIC


def test_classify_distributed_bootstrap_flaps_transient():
    """Coordinator-unreachable / distributed-init timeouts must be
    retryable: the supervised launcher's world relaunch (and the
    queue's parking policy) treats a flapped bootstrap as the
    environment's fault, not the config's."""
    for error in (
        "RuntimeError: Unable to initialize backend 'tpu'",
        "DEADLINE_EXCEEDED: could not reach coordinator at 10.0.0.2:8476",
        "XlaRuntimeError: Barrier timed out after 300s",
        "grpc error: failed to connect to all addresses",
        "Gloo all-reduce failed: Connection closed by peer",
    ):
        assert classify_error(error) == TRANSIENT, error


def test_backoff_schedule_exponential_with_jitter():
    delays = backoff_delays(0.5, 4, seed="impl_0")
    assert delays == backoff_delays(0.5, 4, seed="impl_0")  # deterministic
    assert delays != backoff_delays(0.5, 4, seed="impl_1")
    for i, d in enumerate(delays):
        assert 0.5 * 2 ** i <= d < 0.5 * 2 ** i * 2  # base*2^i * (1+U[0,1))


# ---------------------------------------------------------------------------
# Self-healing runner (stubbed worker: no device work)
# ---------------------------------------------------------------------------


def _stub_row(config, error="", valid=True, error_class=None):
    return make_result_row(
        config,
        times_ms=np.array([1.0]) if not error else np.array([float("nan")]),
        flop_count=1e9,
        option_repr="-",
        valid=valid,
        error=error,
        world_size=8,
        num_processes=1,
        platform="cpu",
        error_class=(
            classify_error(error, valid) if error_class is None else error_class
        ),
    )


def _runner(**over):
    kwargs = dict(
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        dtype="float32",
        progress=False,
        retry_backoff_s=0.01,
        **SHAPE,
    )
    kwargs.update(over)
    return PrimitiveBenchmarkRunner("tp_columnwise", **kwargs)


def test_transient_failures_retry_then_succeed(monkeypatch):
    calls = []

    def worker(config):
        calls.append(config.get("fault_attempt"))
        if len(calls) < 3:
            return _stub_row(config, error="TimeoutError: flaky", valid=False)
        return _stub_row(config)

    monkeypatch.setattr("ddlb_tpu.benchmark.benchmark_worker", worker)
    sleeps = []
    monkeypatch.setattr("ddlb_tpu.benchmark.time.sleep", sleeps.append)
    df = _runner(max_retries=2).run()
    assert calls == [0, 1, 2]  # attempt number threaded into the config
    row = df.iloc[0]
    assert row["valid"] == True  # noqa: E712
    assert row["retries"] == 2
    assert row["error"] == ""
    # the documented schedule: exponential backoff with jitter
    assert sleeps == backoff_delays(0.01, 2, seed="jax_spmd_0")[:2]


def test_deterministic_failure_not_retried(monkeypatch):
    calls = []

    def worker(config):
        calls.append(1)
        return _stub_row(config, error="ValueError: bad option", valid=False)

    monkeypatch.setattr("ddlb_tpu.benchmark.benchmark_worker", worker)
    df = _runner(max_retries=3).run()
    assert len(calls) == 1  # no retry burned on a deterministic failure
    row = df.iloc[0]
    assert row["retries"] == 0
    assert row["error_class"] == DETERMINISTIC


def test_completed_measurement_not_retried_on_validation_crash(monkeypatch):
    """A validation-phase crash AFTER a completed timing loop keeps the
    measurement ('times stand') — even a transient-looking error must
    not discard it for a full-row re-run."""
    calls = []

    def worker(config):
        calls.append(1)
        # finite times + transient-pattern error = the oracle-OOM shape
        return _stub_row(
            config,
            error="validation crashed: XlaRuntimeError: RESOURCE_EXHAUSTED",
            valid=False,
        ) | {"median time (ms)": 1.0}

    monkeypatch.setattr("ddlb_tpu.benchmark.benchmark_worker", worker)
    df = _runner(max_retries=3).run()
    assert len(calls) == 1  # the measurement stood; no retry
    assert df.iloc[0]["retries"] == 0
    assert df.iloc[0]["error_class"] == TRANSIENT


def test_retries_exhaust_and_record_last_error(monkeypatch):
    def worker(config):
        return _stub_row(config, error="TimeoutError: always", valid=False)

    monkeypatch.setattr("ddlb_tpu.benchmark.benchmark_worker", worker)
    monkeypatch.setattr("ddlb_tpu.benchmark.time.sleep", lambda _s: None)
    df = _runner(max_retries=2).run()
    row = df.iloc[0]
    assert row["retries"] == 2
    assert row["error_class"] == TRANSIENT
    assert "TimeoutError" in row["error"]


def test_quarantine_after_consecutive_failures(monkeypatch):
    ran = []

    def worker(config):
        ran.append(config["impl_id"])
        return _stub_row(config, error="TimeoutError: dead impl", valid=False)

    monkeypatch.setattr("ddlb_tpu.benchmark.benchmark_worker", worker)
    # identical specs so signature grouping cannot reorder the sweep
    impls = {
        f"jax_spmd_{i}": {"implementation": "jax_spmd"} for i in range(4)
    }
    df = _runner(
        implementations=impls, max_retries=0, quarantine_after=2
    ).run()
    # only the first two configs ever spawned workers
    assert ran == ["jax_spmd_0", "jax_spmd_1"]
    assert list(df["quarantined"]) == [False, False, True, True]
    for _, row in df[df["quarantined"]].iterrows():
        assert "quarantined" in row["error"]
        assert row["error_class"] == "quarantined"
        assert row["valid"] == False  # noqa: E712
    # the CSV-schema columns exist on every path
    for col in ("retries", "fault_injected", "error_class", "quarantined"):
        assert col in df.columns


def test_success_resets_quarantine_strikes(monkeypatch):
    calls = {"n": 0}

    def worker(config):
        calls["n"] += 1
        if calls["n"] == 2:
            return _stub_row(config)  # one success between failures
        return _stub_row(config, error="TimeoutError: x", valid=False)

    monkeypatch.setattr("ddlb_tpu.benchmark.benchmark_worker", worker)
    impls = {
        f"jax_spmd_{i}": {"implementation": "jax_spmd"} for i in range(4)
    }
    df = _runner(
        implementations=impls, max_retries=0, quarantine_after=2
    ).run()
    # fail, success, fail, fail -> strikes never reach 2 consecutively
    # until the very last row, so nothing was quarantined
    assert not df["quarantined"].any()
    assert calls["n"] == 4


# ---------------------------------------------------------------------------
# Heartbeats: deadline extension vs hang kill (scripted child)
# ---------------------------------------------------------------------------


class _FakeProc:
    """Alive until killed OR joined (a real child exits right after
    posting its row, so the post-row bounded join observes it dead)."""

    def __init__(self):
        self.killed = False
        self.joined = False
        self.exitcode = None

    def is_alive(self):
        return not (self.killed or self.joined)

    def kill(self):
        self.killed = True

    def join(self, timeout=None):
        self.joined = True


class _FakeQueue:
    """queue.Queue plus the mp.Queue release surface, delivering ``row``
    only after ``ready_at`` (wall clock)."""

    def __init__(self, row=None, ready_at=None):
        self.row = row
        self.ready_at = ready_at
        self.closed = False
        self.join_cancelled = False

    def get(self, timeout=1.0):
        time.sleep(timeout)
        if (
            self.row is not None
            and self.ready_at is not None
            and time.time() >= self.ready_at
        ):
            return self.row
        raise queue_mod.Empty

    def close(self):
        self.closed = True

    def cancel_join_thread(self):
        self.join_cancelled = True


class _Channel:
    def __init__(self, value=0.0):
        self.value = value


class _BeatingChannel:
    """A child that beats continuously (always alive, just slow)."""

    @property
    def value(self):
        return time.monotonic()


def test_silent_hang_killed_at_worker_timeout():
    """The per-row deadline policy (now shared via pool.await_row): a
    silent child is killed worker_timeout after dispatch, and the
    runner's error row classifies it transient."""
    from ddlb_tpu import pool as pool_mod

    proc, q = _FakeProc(), _FakeQueue()
    t0 = time.time()
    res = pool_mod.await_row(proc, q, _Channel(0.0), worker_timeout=1.5)
    assert proc.killed
    assert time.time() - t0 < 10.0
    assert res.row is None and res.worker_dead
    assert "TimeoutError" in res.error
    assert "no heartbeat" in res.error
    # the killed child's queue is released so interpreter exit can never
    # block on its feeder thread
    assert q.closed and q.join_cancelled
    runner = _runner(isolation="subprocess", worker_timeout=1.5)
    config = runner._worker_config("jax_spmd_0", {"implementation": "jax_spmd"})
    row = runner._error_row(config, res.error)
    assert row["error_class"] == TRANSIENT


def test_heartbeat_extends_deadline_past_worker_timeout():
    """A child that is slower than worker_timeout but keeps beating is
    NOT killed: the row arrives after ~2x the timeout."""
    from ddlb_tpu import pool as pool_mod

    proc = _FakeProc()
    q = _FakeQueue(row={"valid": True, "error": ""}, ready_at=time.time() + 3.0)
    res = pool_mod.await_row(proc, q, _BeatingChannel(), worker_timeout=1.5)
    assert not proc.killed
    assert not res.worker_dead
    assert res.row == {"valid": True, "error": ""}


def test_hard_timeout_kills_even_a_beating_child():
    """The hardware queue's per-attempt wall budget: a child that beats
    forever but never posts a row still dies at hard_timeout (heartbeats
    must not let one unbounded row wedge a capture window)."""
    from ddlb_tpu import pool as pool_mod

    proc, q = _FakeProc(), _FakeQueue()
    t0 = time.time()
    res = pool_mod.await_row(
        proc, q, _BeatingChannel(), worker_timeout=60.0, hard_timeout=1.5
    )
    assert proc.killed
    assert time.time() - t0 < 10.0
    assert res.row is None and res.worker_dead
    assert "exceeded" in res.error


def test_fault_marker_attributes_child_killing_fault():
    """A child that announces a fired lifecycle fault and then dies
    without a row leaves the site in the error row's fault_injected."""
    from ddlb_tpu import pool as pool_mod

    proc, q = _FakeProc(), _FakeQueue()
    # scripted child: marker posted, then death with nothing else queued
    q.row = None
    marker = {"__fault_marker__": "subprocess.entry", "kind": "exit"}
    delivered = [marker]

    def scripted_get(timeout=1.0):
        if delivered:
            return delivered.pop(0)
        proc.joined = True  # child gone after executing the fault
        raise queue_mod.Empty

    q.get = scripted_get
    res = pool_mod.await_row(proc, q, _Channel(0.0), worker_timeout=5.0)
    assert res.row is None and res.worker_dead
    assert "WorkerDied" in res.error
    assert res.markers == ["subprocess.entry"]
    runner = _runner(isolation="subprocess", worker_timeout=5.0)
    config = runner._worker_config("jax_spmd_0", {"implementation": "jax_spmd"})
    row = pool_mod.merge_fault_markers(
        runner._error_row(config, res.error), res.markers
    )
    assert row["fault_injected"] == "subprocess.entry"
    assert row["error_class"] == TRANSIENT


def test_heartbeat_channel_beats():
    channel = _Channel(0.0)
    heartbeat.set_channel(channel)
    try:
        assert channel.value > 0  # set_channel beats immediately
        before = channel.value
        time.sleep(0.01)
        heartbeat.beat()
        assert heartbeat.last_beat(channel) > before
    finally:
        heartbeat.set_channel(None)


# ---------------------------------------------------------------------------
# Worker integration (in-process: injection -> row columns)
# ---------------------------------------------------------------------------


def test_worker_row_carries_fault_columns(monkeypatch):
    _set_plan(monkeypatch, [
        {"site": "worker.warmup", "kind": "transient_error",
         "fail_attempts": 99},
    ])
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker({
        "primitive": "tp_columnwise",
        "impl_id": "jax_spmd_0",
        "base_implementation": "jax_spmd",
        "options": {},
        "dtype": "float32",
        "num_iterations": 2,
        "num_warmups": 1,
        "fault_attempt": 3,
        **SHAPE,
    })
    assert row["fault_injected"] == "worker.warmup"
    assert row["error_class"] == TRANSIENT
    assert row["retries"] == 3
    assert "injected transient fault" in row["error"]


def test_plain_sweep_schema_unchanged_except_new_columns(monkeypatch):
    """With no plan, rows differ from the pre-ISSUE-4 schema only by the
    four robustness columns (all defaults)."""
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker({
        "primitive": "tp_columnwise",
        "impl_id": "compute_only_0",
        "base_implementation": "compute_only",
        "options": {},
        "dtype": "float32",
        "num_iterations": 2,
        "num_warmups": 1,
        **SHAPE,
    })
    assert row["retries"] == 0
    assert row["fault_injected"] == ""
    assert row["error_class"] == ""
    assert row["quarantined"] is False
    assert row["valid"] is True
