"""Env fallback-chain behavior (reference /root/reference/ddlb/envs.py:12-82)."""

from ddlb_tpu import envs


def test_defaults(monkeypatch):
    for var in (
        "DDLB_TPU_PROCESS_ID",
        "CLOUD_TPU_TASK_ID",
        "TPU_WORKER_ID",
        "OMPI_COMM_WORLD_RANK",
        "SLURM_PROCID",
        "PMI_RANK",
        "DDLB_TPU_NUM_PROCESSES",
        "OMPI_COMM_WORLD_SIZE",
        "SLURM_NTASKS",
        "PMI_SIZE",
    ):
        monkeypatch.delenv(var, raising=False)
    assert envs.get_process_id() == 0
    assert envs.get_num_processes() == 1
    assert envs.get_local_process_id() == 0


def test_explicit_override_wins(monkeypatch):
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("DDLB_TPU_PROCESS_ID", "1")
    assert envs.get_process_id() == 1


def test_launcher_fallback_order(monkeypatch):
    monkeypatch.delenv("DDLB_TPU_PROCESS_ID", raising=False)
    monkeypatch.delenv("CLOUD_TPU_TASK_ID", raising=False)
    monkeypatch.delenv("TPU_WORKER_ID", raising=False)
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "5")
    monkeypatch.setenv("SLURM_PROCID", "7")
    assert envs.get_process_id() == 5


def test_coordinator_address(monkeypatch):
    monkeypatch.delenv("DDLB_TPU_COORD_ADDR", raising=False)
    monkeypatch.delenv("JAX_COORD_ADDR", raising=False)
    monkeypatch.delenv("DDLB_TPU_MASTER_ADDR", raising=False)
    monkeypatch.delenv("DDLB_TPU_MASTER_PORT", raising=False)
    assert envs.get_coordinator_address() == "127.0.0.1:12355"
    monkeypatch.setenv("DDLB_TPU_MASTER_ADDR", "10.0.0.1")
    monkeypatch.setenv("DDLB_TPU_MASTER_PORT", "999")
    assert envs.get_coordinator_address() == "10.0.0.1:999"
    monkeypatch.setenv("JAX_COORD_ADDR", "host:1234")
    assert envs.get_coordinator_address() == "host:1234"
    monkeypatch.setenv("DDLB_TPU_COORD_ADDR", "other:1")
    assert envs.get_coordinator_address() == "other:1"


def test_sim_device_count(monkeypatch):
    monkeypatch.setenv("DDLB_TPU_SIM_DEVICES", "16")
    assert envs.get_sim_device_count() == 16
