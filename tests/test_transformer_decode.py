"""Serving step: KV-cache decode/prefill vs the teacher-forced oracle.

The incremental cache path and the non-incremental full forward share no
attention code, so logit agreement (sharded vs single-device) is a real
consistency check — the serving-side analogue of the train-step oracle
pinning (tests/test_transformer.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddlb_tpu.benchmark import benchmark_worker
from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 12, 32, 64  # context length, d_model, d_ff
COMMON = dict(batch=8, vocab=64, n_heads=4)


class TestModel:
    def test_decode_loop_matches_oracle_and_prefill(self):
        """Token-by-token decode from an empty cache == prefill+decode ==
        the single-device oracle."""
        from ddlb_tpu.models.decode import (
            init_cache,
            make_decode_fn,
            make_prefill_fn,
            reference_logits,
        )
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )

        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64, layers_per_stage=2
        )
        dp, tp = 2, 4
        mesh = jax.make_mesh((dp, tp), ("dp", "tp"))
        decode, sh = make_decode_fn(mesh, cfg)
        prefill, _ = make_prefill_fn(mesh, cfg)
        params = init_params(cfg, pp=1, n_experts=tp)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        B, S = 8, 6
        rng = np.random.default_rng(3)
        toks = jnp.asarray(rng.integers(0, 64, (B, S + 1)), jnp.int32)
        host = init_params(cfg, pp=1, n_experts=tp)
        want = np.asarray(
            reference_logits(host, np.asarray(toks), cfg, tp=tp, dp=dp)
        )

        cache = init_cache(cfg, B, 8, mesh)
        dstep = jax.jit(decode)
        for p in range(S + 1):
            logits, cache = dstep(params, cache, toks[:, p], jnp.int32(p))
        assert np.max(np.abs(np.asarray(logits) - want)) < 1e-4

        cache2 = init_cache(cfg, B, 8, mesh)
        _, cache2 = jax.jit(prefill)(params, cache2, toks[:, :S])
        logits2, _ = dstep(params, cache2, toks[:, S], jnp.int32(S))
        assert np.max(np.abs(np.asarray(logits2) - want)) < 1e-4

    def test_generate_matches_stepwise_decode(self):
        """The one-program fori_loop generation reproduces the same
        greedy tokens as explicit python-loop stepping, and its first
        sampled token matches the oracle's argmax."""
        from ddlb_tpu.models.decode import (
            init_cache,
            make_decode_fn,
            make_generate_fn,
            make_prefill_fn,
            reference_logits,
        )
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, d_ff=64)
        dp, tp = 2, 4
        mesh = jax.make_mesh((dp, tp), ("dp", "tp"))
        n_new = 4
        gen, sh = make_generate_fn(mesh, cfg, n_new)
        decode, _ = make_decode_fn(mesh, cfg)
        prefill, _ = make_prefill_fn(mesh, cfg)
        params = init_params(cfg, pp=1, n_experts=tp)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        B, S0 = 8, 5
        rng = np.random.default_rng(9)
        prompt = jnp.asarray(rng.integers(0, 64, (B, S0)), jnp.int32)

        cache = init_cache(cfg, B, S0 + n_new, mesh)
        out = np.asarray(jax.jit(gen)(params, cache, prompt))
        assert out.shape == (B, S0 + n_new)
        assert np.array_equal(out[:, :S0], np.asarray(prompt))

        # python-loop stepping with the same decode fn
        cache2 = init_cache(cfg, B, S0 + n_new, mesh)
        logits, cache2 = jax.jit(prefill)(params, cache2, prompt)
        toks = [np.asarray(prompt)]
        dstep = jax.jit(decode)
        for i in range(n_new):
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            toks.append(np.asarray(nxt)[:, None])
            logits, cache2 = dstep(params, cache2, nxt, jnp.int32(S0 + i))
        assert np.array_equal(out, np.concatenate(toks, axis=1))

        # oracle spot check on the first sampled token
        host = init_params(cfg, pp=1, n_experts=tp)
        want0 = np.argmax(
            np.asarray(
                reference_logits(host, np.asarray(prompt), cfg, tp=tp, dp=dp)
            ),
            axis=-1,
        )
        assert np.array_equal(out[:, S0], want0)

    def test_temperature_sampling(self):
        """temperature>0 draws deterministically under a fixed key, stays
        in-vocab, and requires a key; temperature=0 stays greedy."""
        from ddlb_tpu.models.decode import init_cache, make_generate_fn
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, d_ff=64)
        mesh = jax.make_mesh((2, 4), ("dp", "tp"))
        gen_t, sh = make_generate_fn(mesh, cfg, 4, temperature=0.8)
        params = init_params(cfg, pp=1, n_experts=4)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        rng = np.random.default_rng(11)
        prompt = jnp.asarray(rng.integers(0, 64, (8, 5)), jnp.int32)
        key = jax.random.PRNGKey(0)

        cache = init_cache(cfg, 8, 9, mesh)
        a = np.asarray(jax.jit(gen_t)(params, cache, prompt, key))
        cache = init_cache(cfg, 8, 9, mesh)
        b = np.asarray(jax.jit(gen_t)(params, cache, prompt, key))
        assert np.array_equal(a, b)
        assert a.min() >= 0 and a.max() < 64

        cache = init_cache(cfg, 8, 9, mesh)
        c = np.asarray(
            jax.jit(gen_t)(params, cache, prompt, jax.random.PRNGKey(7))
        )
        assert not np.array_equal(a[:, 5:], c[:, 5:])  # key matters

        with pytest.raises(ValueError, match="PRNG key"):
            gen_t(params, init_cache(cfg, 8, 9, mesh), prompt)

    def test_ring_attention_rejected(self):
        from ddlb_tpu.models.decode import make_decode_fn
        from ddlb_tpu.models.transformer import TransformerConfig

        mesh = jax.make_mesh((2, 4), ("dp", "tp"))
        with pytest.raises(ValueError, match="gathered"):
            make_decode_fn(mesh, TransformerConfig(attention="ring"))


class TestPrimitive:
    @pytest.mark.parametrize("phase", ["decode", "prefill"])
    @pytest.mark.parametrize("impl", ["spmd", "compute_only", "xla_gspmd"])
    def test_validates(self, impl, phase):
        cls = load_impl_class("transformer_decode", impl)
        prim = cls(M, N, K, dtype="float32", phase=phase, **COMMON)
        assert prim.validate(prim.run())

    @pytest.mark.parametrize(
        "mlp_kernel", ["int8", "int8_weights"]
    )
    def test_int8_kernels_validate(self, mlp_kernel):
        cls = load_impl_class("transformer_decode", "spmd")
        prim = cls(
            M, N, K, dtype="float32", mlp_kernel=mlp_kernel, **COMMON
        )
        assert prim.validate(prim.run())

    @pytest.mark.parametrize("attn_kernel", ["flash", "einsum"])
    def test_prefill_attn_kernels_validate(self, attn_kernel):
        """Both prefill attention engines meet the oracle bound; flash is
        the default (prefill is the compute-bound long-S regime the
        Pallas kernels exist for)."""
        cls = load_impl_class("transformer_decode", "spmd")
        prim = cls(M, N, K, dtype="float32", phase="prefill",
                   attn_kernel=attn_kernel, **COMMON)
        assert prim.validate(prim.run())

    def test_gspmd_rejects_explicit_flash(self):
        cls = load_impl_class("transformer_decode", "xla_gspmd")
        with pytest.raises(ValueError, match="spmd member"):
            cls(M, N, K, dtype="float32", attn_kernel="flash", **COMMON)
        # the default-constructed comparator records the kernel it
        # actually measures
        prim = cls(M, N, K, dtype="float32", **COMMON)
        assert prim.options["attn_kernel"] == "einsum"

    def test_flash_prefill_non_pow2_context(self):
        """m=24 (not a power of two): the flash tile falls back to the
        largest divisor instead of failing deep in tracing."""
        cls = load_impl_class("transformer_decode", "spmd")
        prim = cls(24, N, K, dtype="float32", phase="prefill", **COMMON)
        assert prim.validate(prim.run())

    def test_decode_iterations_are_identical(self):
        """The measured decode call is re-runnable: the cache write is
        discarded, so every iteration decodes the same position."""
        cls = load_impl_class("transformer_decode", "spmd")
        prim = cls(M, N, K, dtype="float32", **COMMON)
        a = np.asarray(prim.run())
        b = np.asarray(prim.run())
        assert np.array_equal(a, b)

    def test_mesh_factor_errors(self):
        cls = load_impl_class("transformer_decode", "spmd")
        with pytest.raises(ValueError, match="devices"):
            cls(M, N, K, dtype="float32", dp=3, tp=2, **COMMON)
        with pytest.raises(ValueError, match="n_heads"):
            cls(M, N, K, dtype="float32", dp=1, tp=8, **COMMON)

    def test_through_benchmark_worker(self):
        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_0",
                "base_implementation": "spmd",
                "options": dict(COMMON),
                "m": M,
                "n": N,
                "k": K,
                "dtype": "float32",
                "num_iterations": 2,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert not row["error"], row["error"]
        assert row["valid"]
        assert row["Throughput (TFLOPS)"] > 0


class TestInt8KVCache:
    """Fast-decode member (VERDICT r2 #3): int8-quantized KV cache halves
    the per-token HBM cache read; oracle parity holds within the bounded
    quantization-cliff tolerance (base.py validate notes)."""

    def test_cache_dtype_and_scales(self):
        import jax.numpy as jnp

        from ddlb_tpu.models.decode import init_cache
        from ddlb_tpu.models.transformer import TransformerConfig
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        cfg = TransformerConfig(kv_cache="int8", n_heads=8, d_model=64)
        cache = init_cache(cfg, 8, 16, mesh=mesh)
        assert cache["k"].dtype == jnp.int8
        assert cache["k_scale"].shape == cache["k"].shape[:-1] + (1,)
        # payload bytes: int8 is 1/4 of the f32 default dtype
        assert cache["k"].dtype.itemsize == 1

    @pytest.mark.parametrize("impl", ["spmd", "xla_gspmd"])
    def test_decode_validates(self, impl):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": f"{impl}_int8kv",
                "base_implementation": impl,
                "options": {
                    "batch": 8, "vocab": 64, "n_heads": 8,
                    "phase": "decode", "kv_cache": "int8",
                    "attn_kernel": "einsum",
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    def test_prefill_validates_with_flash(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_int8kv_prefill",
                "base_implementation": "spmd",
                "options": {
                    "batch": 8, "vocab": 64, "n_heads": 8,
                    "phase": "prefill", "kv_cache": "int8",
                    "attn_kernel": "flash",
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    def test_generate_with_int8_cache(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ddlb_tpu.models.decode import init_cache, make_generate_fn
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=1, attn_kernel="einsum",
            kv_cache="int8",
        )
        generate, sh = make_generate_fn(mesh, cfg, n_new=4)
        params = init_params(cfg, pp=1, n_experts=2)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        prompt, _ = example_tokens(8, 8, cfg.vocab)
        cache = init_cache(cfg, 8, 12, mesh=mesh)
        toks = np.asarray(jax.jit(generate)(params, cache, prompt))
        assert toks.shape == (8, 12)
        assert (toks >= 0).all() and (toks < cfg.vocab).all()


class TestSampling:
    """top-k / top-p (nucleus) sampling in the compiled generate loop."""

    def _gen(self, **kw):
        import jax

        from ddlb_tpu.models.decode import init_cache, make_generate_fn
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=1, attn_kernel="einsum",
        )
        generate, sh = make_generate_fn(mesh, cfg, n_new=4, **kw)
        params = init_params(cfg, pp=1, n_experts=2)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        prompt, _ = example_tokens(8, 8, cfg.vocab)
        cache = init_cache(cfg, 8, 12, mesh=mesh)
        return generate, params, cache, prompt, cfg

    def test_topk1_equals_greedy(self):
        import jax
        import numpy as np

        gen_g, params, cache, prompt, cfg = self._gen(temperature=0.0)
        greedy = np.asarray(jax.jit(gen_g)(params, cache, prompt))
        gen_k, params, cache, prompt, cfg = self._gen(
            temperature=0.5, top_k=1
        )
        key = jax.random.PRNGKey(0)
        topk1 = np.asarray(jax.jit(gen_k)(params, cache, prompt, key))
        # top_k=1 leaves exactly the argmax in the support
        np.testing.assert_array_equal(greedy, topk1)

    def test_topp_tokens_in_range_and_deterministic(self):
        import jax
        import numpy as np

        gen, params, cache, prompt, cfg = self._gen(
            temperature=0.8, top_p=0.9, top_k=8
        )
        key = jax.random.PRNGKey(7)
        a = np.asarray(jax.jit(gen)(params, cache, prompt, key))
        b = np.asarray(jax.jit(gen)(params, cache, prompt, key))
        np.testing.assert_array_equal(a, b)
        assert a.shape == (8, 12)
        assert (a >= 0).all() and (a < cfg.vocab).all()

    def test_tiny_topp_equals_greedy(self):
        """top_p -> 0 keeps only the first-past-threshold (= argmax)."""
        import jax
        import numpy as np

        gen_g, params, cache, prompt, cfg = self._gen(temperature=0.0)
        greedy = np.asarray(jax.jit(gen_g)(params, cache, prompt))
        gen_p, params, cache, prompt, cfg = self._gen(
            temperature=1.0, top_p=1e-6
        )
        key = jax.random.PRNGKey(3)
        nucleus = np.asarray(jax.jit(gen_p)(params, cache, prompt, key))
        np.testing.assert_array_equal(greedy, nucleus)

    def test_bad_sampling_params_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="top_p"):
            self._gen(top_p=0.0)
        with _pytest.raises(ValueError, match="top_k"):
            self._gen(top_k=-1)


class TestRaggedDecode:
    """Continuous-batching foundation: per-sequence cache positions —
    one compiled step serves a batch at different generation depths."""

    def _setup(self, kv_cache="bf16"):
        import jax
        import jax.numpy as jnp

        from ddlb_tpu.models.decode import (
            init_cache,
            make_decode_fn,
            make_prefill_fn,
        )
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=2, microbatches=1, attn_kernel="einsum",
            kv_cache=kv_cache,
        )
        B, S0 = 8, 8
        params = init_params(cfg, pp=1, n_experts=2)
        prompt, _ = example_tokens(B, S0, cfg.vocab)
        prefill, sh = make_prefill_fn(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        cache = init_cache(cfg, B, S0 + 1, mesh=mesh)
        logits, cache = jax.jit(prefill)(p, cache, prompt)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        dec_s, _ = make_decode_fn(mesh, cfg)
        dec_r, _ = make_decode_fn(mesh, cfg, ragged=True)
        return mesh, cfg, p, cache, nxt, dec_s, dec_r, B, S0

    def test_equal_vector_equals_scalar(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        _, _, p, cache, nxt, dec_s, dec_r, B, S0 = self._setup()
        l_s, _ = jax.jit(dec_s)(p, cache, nxt, jnp.int32(S0))
        l_r, _ = jax.jit(dec_r)(p, cache, nxt, jnp.full((B,), S0, jnp.int32))
        np.testing.assert_array_equal(np.asarray(l_s), np.asarray(l_r))

    @pytest.mark.parametrize("kv_cache", ["bf16", "int8"])
    def test_per_sequence_rows_match_scalar_runs(self, kv_cache):
        """Row i of a ragged step at pos[i] must equal row i of a scalar
        step at that position (rows are per-sequence independent given
        the same batch slots)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        _, _, p, cache, nxt, dec_s, dec_r, B, S0 = self._setup(kv_cache)
        pos_vec = np.array([3, 5, 8, 2, 7, 4, 6, 1], np.int32)
        l_rag = np.asarray(
            jax.jit(dec_r)(p, cache, nxt, jnp.asarray(pos_vec))[0]
        )
        for i in range(B):
            l_i, _ = jax.jit(dec_s)(p, cache, nxt, jnp.int32(int(pos_vec[i])))
            np.testing.assert_array_equal(l_rag[i], np.asarray(l_i)[i])

    def test_ragged_cache_write_lands_per_sequence(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        _, _, p, cache, nxt, _, dec_r, B, S0 = self._setup()
        pos_vec = np.arange(1, B + 1, dtype=np.int32)
        _, cache2 = jax.jit(dec_r)(p, cache, nxt, jnp.asarray(pos_vec))
        k0, k2 = np.asarray(cache["k"]), np.asarray(cache2["k"])
        for i in range(B):
            # row i changed exactly at its own position
            changed = np.any(k0[:, i] != k2[:, i], axis=(0, 2, 3))
            assert changed[pos_vec[i]]
            assert not changed[: pos_vec[i]].any()
            assert not changed[pos_vec[i] + 1 :].any()

    def test_ragged_out_of_bounds_write_is_dropped(self):
        """A position past the cache must drop the write (mode="drop"),
        not clamp onto the last row — an overflowing sequence corrupts
        nothing (ADVICE r3)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        _, _, p, cache, nxt, _, dec_r, B, S0 = self._setup()
        S_max = cache["k"].shape[2]
        pos_vec = np.full(B, 2, np.int32)
        pos_vec[3] = S_max          # one sequence overflows
        pos_vec[5] = S_max + 100    # far overflow
        _, cache2 = jax.jit(dec_r)(p, cache, nxt, jnp.asarray(pos_vec))
        k0, k2 = np.asarray(cache["k"]), np.asarray(cache2["k"])
        for i in range(B):
            changed = np.any(k0[:, i] != k2[:, i], axis=(0, 2, 3))
            if pos_vec[i] >= S_max:
                assert not changed.any(), f"OOB write for seq {i} landed"
            else:
                assert changed[pos_vec[i]]
                assert changed.sum() == 1


class TestGeneratePhase:
    """phase=generate: the whole compiled serving loop (prefill + n_new
    greedy decode steps) as one measured call — end-to-end tokens/s."""

    def _run(self, impl, **opts):
        from ddlb_tpu.benchmark import benchmark_worker

        return benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": f"{impl}_gen",
                "base_implementation": impl,
                "options": {
                    "phase": "generate", "n_new": 6, "batch": 8,
                    "vocab": 64, "n_heads": 8, "attn_kernel": "einsum",
                    **opts,
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )

    @pytest.mark.parametrize("impl", ["spmd", "compute_only"])
    def test_validates_against_oracle_chain(self, impl):
        row = self._run(impl)
        assert row["error"] == ""
        assert row["valid"] is True

    def test_fast_decode_levers_compose(self):
        row = self._run("spmd", kv_cache="int8", n_kv_heads=2)
        assert row["error"] == ""
        assert row["valid"] is True

    def test_xla_gspmd_rejects_generate(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_decode", "xla_gspmd")
        with pytest.raises(ValueError, match="generate"):
            cls(16, 64, 64, dtype="float32", phase="generate",
                batch=8, vocab=64, n_heads=8)
