"""scripts/trace_report.py aggregation, the xprof_summary import guard
+ --json mode, and the lint print ban (ISSUE 2 satellites)."""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


tr = _load("trace_report")
xp = _load("xprof_summary")
lint = _load("lint")


def _shard(directory, events):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, "trace-testhost-p0-1234.jsonl")
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return path


def _ev(name, cat, ts_us, dur_us, pid=1234):
    return {
        "name": name, "cat": cat, "ph": "X", "ts": ts_us, "dur": dur_us,
        "pid": pid, "tid": 1,
        "args": {"rank": 0, "host": "testhost", "depth": 0},
    }


# ---------------------------------------------------------------------------
# trace_report
# ---------------------------------------------------------------------------


def test_phase_breakdown_and_top_spans(tmp_path):
    d = str(tmp_path / "t")
    _shard(d, [
        _ev("worker.timing", "timing", 0.0, 1000.0),
        _ev("runtime.barrier", "barrier", 100.0, 200.0),
        _ev("xla_compile", "compile", 1100.0, 400.0),
        _ev("worker.validate", "validate", 1600.0, 100.0),
    ])
    report = tr.build_report(d)
    phases = report["phases"]
    for cat in ("timing", "barrier", "compile", "validate"):
        assert cat in phases
    assert phases["timing"]["total_ms"] == pytest.approx(1.0)
    assert phases["barrier"]["count"] == 1
    assert report["wall_ms"] == pytest.approx(1.7)
    assert report["top_spans"][0]["name"] == "worker.timing"
    # merged Chrome trace produced and loadable
    with open(report["merged_trace"]) as f:
        doc = json.load(f)
    assert len(doc["traceEvents"]) == 4


def test_per_row_breakdown_groups_by_row_span_not_pid(tmp_path):
    """The warm-pool satellite (ISSUE 6): ONE process shard carries TWO
    rows (a reused pool worker), so per-row phase aggregation must group
    by worker.row span containment, never by pid — and a background
    prefetch compile on another thread of the same pid must not be
    attributed to the row it merely overlaps in time."""
    def _row_ev(name, cat, ts, dur, tid=1, **args):
        e = _ev(name, cat, ts, dur)
        e["tid"] = tid
        e["args"].update(args)
        return e

    d = str(tmp_path / "t")
    _shard(d, [
        # row 1: [0, 1000] with timing 700 + validate 200
        _row_ev("worker.row", "row", 0.0, 1000.0, impl="jax_spmd_0"),
        _row_ev("worker.timing", "timing", 50.0, 700.0),
        _row_ev("worker.validate", "validate", 760.0, 200.0),
        # row 2, SAME pid (pool reuse): [2000, 2600] with timing 500
        _row_ev("worker.row", "row", 2000.0, 600.0, impl="overlap_1"),
        _row_ev("worker.timing", "timing", 2050.0, 500.0),
        # prefetch on another thread, overlapping row 2 in time:
        # must not land in either row's phases
        _row_ev("compile_ahead.prefetch", "compile", 2000.0, 500.0, tid=2),
    ])
    report = tr.build_report(d)
    rows = report["rows"]
    assert [r["impl"] for r in rows] == ["jax_spmd_0", "overlap_1"]
    assert rows[0]["phases"]["timing"] == pytest.approx(0.7)
    assert rows[0]["phases"]["validate"] == pytest.approx(0.2)
    assert rows[1]["phases"] == {"timing": pytest.approx(0.5)}
    assert "compile" not in rows[1]["phases"]
    # the text report prints the per-row section
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        tr.print_report(report)
    assert "per-row phase breakdown (2 row(s)" in buf.getvalue()


def test_prefetch_overlap_ratio(tmp_path):
    d = str(tmp_path / "t")
    # prefetch [0, 1000] vs timing [500, 1500]: 500 µs hidden of 1000
    _shard(d, [
        _ev("compile_ahead.prefetch", "compile", 0.0, 1000.0),
        _ev("worker.timing", "timing", 500.0, 1000.0),
    ])
    ov = tr.build_report(d)["prefetch_overlap"]
    assert ov["prefetch_ms"] == pytest.approx(1.0)
    assert ov["overlapped_ms"] == pytest.approx(0.5)
    assert ov["ratio"] == pytest.approx(0.5)


def test_interval_overlap_merges_union():
    # overlapping covers must not double-count
    covered = tr._interval_overlap(
        (0.0, 10.0), [(0.0, 6.0), (4.0, 8.0), (20.0, 30.0)]
    )
    assert covered == pytest.approx(8.0)


def test_report_main_json_mode(tmp_path, capsys):
    d = str(tmp_path / "t")
    _shard(d, [_ev("worker.timing", "timing", 0.0, 100.0)])
    assert tr.main([d, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["events"] == 1
    assert "timing" in doc["phases"]


def test_report_main_empty_dir(tmp_path, capsys):
    d = tmp_path / "empty"
    d.mkdir()
    assert tr.main([str(d)]) == 1
    assert "no trace events" in capsys.readouterr().out


def test_report_xprof_join_degrades_actionably(tmp_path, monkeypatch, capsys):
    d = str(tmp_path / "t")
    _shard(d, [_ev("worker.timing", "timing", 0.0, 100.0)])
    report = tr.build_report(d, xprof_dir=str(tmp_path / "nonexistent"))
    xpj = report["xprof"]
    # either TF is present (no device events -> error) or absent
    # (actionable import error) — both must be a recorded string, never
    # an exception escaping the report
    assert "error" in xpj and isinstance(xpj["error"], str)


# ---------------------------------------------------------------------------
# xprof_summary import guard + --json
# ---------------------------------------------------------------------------


def test_xprof_guard_is_actionable(monkeypatch):
    import builtins

    real_import = builtins.__import__

    def _no_tf(name, *a, **kw):
        if name.startswith("tensorflow"):
            raise ImportError("No module named 'tensorflow'")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", _no_tf)
    with pytest.raises(xp.XplaneUnavailableError) as err:
        xp._import_xplane_pb2()
    assert "tensorflow-cpu" in str(err.value)  # tells the operator what to do


def test_xprof_main_json_error_mode(tmp_path, monkeypatch, capsys):
    import builtins

    real_import = builtins.__import__

    def _no_tf(name, *a, **kw):
        if name.startswith("tensorflow"):
            raise ImportError("No module named 'tensorflow'")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", _no_tf)
    assert xp.main(["x", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert "error" in doc and "XplaneUnavailable" in doc["error"]


def test_xprof_main_usage_line(capsys):
    assert xp.main(["x"]) == 2
    assert "--json" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# lint: bare-print ban inside ddlb_tpu/ (cli/ and telemetry/ exempt)
# ---------------------------------------------------------------------------


def _lint_file(tmp_path, rel, src):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return lint.check_file(path)


def test_lint_bans_bare_print_in_package(tmp_path):
    problems = _lint_file(
        tmp_path, "ddlb_tpu/foo.py",
        '"""Doc."""\nprint("hi")\n',
    )
    assert any("bare print()" in p for p in problems)


def test_lint_print_ban_exempts_cli_telemetry_and_scripts(tmp_path):
    src = '"""Doc."""\nprint("hi")\n'
    for rel in (
        "ddlb_tpu/cli/foo.py",
        "ddlb_tpu/telemetry/foo.py",
        "scripts/foo.py",
    ):
        problems = _lint_file(tmp_path, rel, src)
        assert not any("bare print()" in p for p in problems), rel


def test_repo_package_is_print_clean():
    """The ban holds on the real tree (Makefile lint wires this in)."""
    problems = []
    pkg = os.path.join(REPO, "ddlb_tpu")
    for root, _dirs, files in os.walk(pkg):
        for fn in files:
            if fn.endswith(".py"):
                from pathlib import Path

                problems += [
                    p
                    for p in lint.check_file(Path(os.path.join(root, fn)))
                    if "bare print()" in p
                ]
    assert problems == []
