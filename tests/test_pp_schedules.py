"""Pipeline training schedules: table properties + SPMD executor parity.

The schedule tables are exact (built by simulation, not measured), so the
classic results are asserted as equalities/inequalities, not trends:
- 1F1B and GPipe have the SAME synchronous-flush bubble at equal
  microbatches (the known result — 1F1B's win is memory, not ticks);
- 1F1B's activation stash is O(depth) vs GPipe's O(microbatches);
- interleaved (virtual chunks) strictly reduces the bubble vs the v=1
  schedules on the same device count and model.

The executor tests run the full fwd+bwd table-driven shard_map program on
the 8-device CPU mesh and validate output AND per-stage gradients against
the host chain oracle (schedules.py docstring).
"""

import numpy as np
import pytest

from ddlb_tpu.utils.pipeline_schedule import (
    KIND_BWD,
    KIND_FWD,
    KIND_IDLE,
    build_schedule,
)


class TestScheduleTables:
    def test_gpipe_1f1b_same_ticks_exact(self):
        # 2*(mb + d - 1): fill + drain on both sides of the flush
        for d, mb in [(2, 4), (4, 8), (8, 16), (8, 32)]:
            g = build_schedule("gpipe", d, mb)
            o = build_schedule("1f1b", d, mb)
            assert g.ticks == 2 * (mb + d - 1)
            assert o.ticks == g.ticks
            assert o.bubble_fraction == g.bubble_fraction

    def test_1f1b_stash_is_depth_not_microbatches(self):
        for d, mb in [(4, 16), (8, 32)]:
            g = build_schedule("gpipe", d, mb)
            o = build_schedule("1f1b", d, mb)
            assert g.peak_stash == mb
            assert o.peak_stash == d
            assert o.peak_stash < g.peak_stash

    def test_interleaved_cuts_bubble_vs_v1(self):
        # same devices, same model (d*v chunks vs d fat stages), same mb
        for d, mb, v in [(4, 8, 2), (8, 16, 2), (8, 16, 4)]:
            g = build_schedule("gpipe", d, mb)
            i = build_schedule("interleaved", d, mb, v)
            assert i.bubble_fraction < g.bubble_fraction

    def test_every_op_scheduled_exactly_once(self):
        t = build_schedule("interleaved", 4, 8, 2)
        seen = set()
        for tick in range(t.ticks):
            for p in range(t.n_devices):
                if t.kind[tick, p] == KIND_IDLE:
                    continue
                key = (int(t.kind[tick, p]), int(t.mb[tick, p]),
                       int(t.chunk[tick, p]), p)
                assert key not in seen
                seen.add(key)
        assert len(seen) == 2 * t.microbatches * t.n_stages

    def test_dependencies_respected(self):
        """fwd(i,s) strictly after fwd(i,s-1); bwd(i,s) after bwd(i,s+1)
        and after fwd(i,s) — with at least one tick of hop latency."""
        t = build_schedule("interleaved", 4, 6, 2)
        d, S = t.n_devices, t.n_stages
        fwd_t, bwd_t = {}, {}
        for tick in range(t.ticks):
            for p in range(d):
                k = t.kind[tick, p]
                if k == KIND_IDLE:
                    continue
                s = int(t.chunk[tick, p]) * d + p
                i = int(t.mb[tick, p])
                (fwd_t if k == KIND_FWD else bwd_t)[(i, s)] = tick
        for (i, s), tk in fwd_t.items():
            if s > 0:
                assert fwd_t[(i, s - 1)] < tk
        for (i, s), tk in bwd_t.items():
            assert fwd_t[(i, s)] < tk
            if s + 1 < S:
                assert bwd_t[(i, s + 1)] < tk

    def test_busy_accounting(self):
        t = build_schedule("1f1b", 4, 8)
        # every device does exactly 2*mb*v ops
        assert (t.busy == 2 * t.microbatches * t.virtual).all()

    def test_rejects_bad_combinations(self):
        with pytest.raises(ValueError, match="interleaved"):
            build_schedule("1f1b", 4, 8, virtual=2)
        with pytest.raises(ValueError, match="virtual >= 2"):
            build_schedule("interleaved", 4, 8, virtual=1)
        with pytest.raises(ValueError, match="unknown schedule"):
            build_schedule("pipedream", 4, 8)


class TestScheduleExecutor:
    @pytest.mark.parametrize(
        "schedule,virtual", [("gpipe", 1), ("1f1b", 1), ("interleaved", 2)]
    )
    def test_output_and_grads_validate_f32(self, schedule, virtual):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("pp_pipeline", "schedules")
        impl = cls(
            64, 128, 128, dtype="float32",
            schedule=schedule, microbatches=4, virtual=virtual,
        )
        assert impl.validate(impl.run())

    def test_bf16_validates(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("pp_pipeline", "schedules")
        impl = cls(
            64, 128, 128, dtype="bfloat16",
            schedule="1f1b", microbatches=8,
        )
        assert impl.validate(impl.run())

    def test_gpipe_chunked_equal_depth(self):
        """gpipe accepts virtual>1 (the equal-chain-depth comparison
        partner for interleaved): same placement, flush policy."""
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("pp_pipeline", "schedules")
        impl = cls(
            32, 64, 64, dtype="float32",
            schedule="gpipe", microbatches=4, virtual=2,
        )
        assert impl.validate(impl.run())
        assert impl.num_stages == impl.num_partitions * 2

    def test_schedule_through_benchmark_worker(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "pp_pipeline",
                "impl_id": "schedules_0",
                "base_implementation": "schedules",
                "options": {"schedule": "1f1b", "microbatches": 4},
                "m": 32,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 2,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    def test_rejects_indivisible_microbatches(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("pp_pipeline", "schedules")
        with pytest.raises(ValueError, match="divisible by microbatches"):
            cls(30, 64, 64, dtype="float32", schedule="1f1b", microbatches=4)


class TestModel1F1B:
    """The flagship model training under the 1F1B schedule
    (models/pipeline.py): manual-vjp loop vs autodiff-GPipe oracle."""

    def _setup(self, mb=4):
        import jax

        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
            make_loss_fn,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp", "pp"), shape=(2, 2, 2))
        # einsum attention: this class validates the SCHEDULE math
        # (1F1B manual-vjp vs autodiff GPipe); the default flash kernel
        # runs INTERPRETED on the CPU sim and would multiply the
        # value_and_grad compile severalfold for coverage that
        # test_flash_grad's flash-vs-einsum model test already owns
        # (the tier-1 870 s budget note in ROADMAP)
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=mb, attn_kernel="einsum",
        )
        params = init_params(cfg, pp=2, n_experts=2)
        tokens, targets = example_tokens(batch=8, seq=16, vocab=cfg.vocab)
        loss_fn, sh = make_loss_fn(mesh, cfg)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        tokens = jax.device_put(tokens, sh["data"])
        targets = jax.device_put(targets, sh["data"])
        return mesh, cfg, loss_fn, params, tokens, targets

    @pytest.mark.slow  # two full-model autodiff compiles (value_and_grad
    # through the 8-device shard_mapped flagship, plus the manual-vjp
    # 1F1B build) — minutes of XLA CPU compile; unlocked by the
    # transformer shard_map_compat migration but outside the tier-1
    # 870 s budget (the train-step smoke below keeps tier-1 coverage)
    def test_1f1b_loss_and_grads_match_autodiff_gpipe(self):
        import jax

        from ddlb_tpu.models.pipeline import make_loss_and_grads_1f1b

        mesh, cfg, loss_fn, params, tokens, targets = self._setup()
        loss_g, grads_g = jax.jit(jax.value_and_grad(loss_fn))(
            params, tokens, targets
        )
        fn, _ = make_loss_and_grads_1f1b(mesh, cfg)
        loss_o, grads_o = jax.jit(fn)(params, tokens, targets)
        assert abs(float(loss_g) - float(loss_o)) < 1e-6
        for k in grads_g:
            a = np.asarray(grads_g[k], np.float32)
            b = np.asarray(grads_o[k], np.float32)
            rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-12)
            assert rel < 2e-3, f"grad '{k}' diverges: rel={rel:.3e}"

    def test_1f1b_train_step_decreases_loss(self):
        import jax

        from ddlb_tpu.models.pipeline import make_train_step_1f1b

        mesh, cfg, _, params, tokens, targets = self._setup()
        step, init_opt, _ = make_train_step_1f1b(mesh, cfg, donate=False)
        opt_state = init_opt(params)
        losses = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tokens, targets)
            losses.append(float(jax.block_until_ready(loss)))
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # full benchmark_worker round over the 1F1B
    # member (~13 s, dominated by the manual-vjp train-step compile the
    # train-step smoke above already pays once) — outside the tier-1
    # 870 s budget; 1F1B semantics stay in-tier via
    # test_1f1b_train_step_decreases_loss and the worker-row plumbing
    # via test_schedule_through_benchmark_worker
    def test_spmd_member_sweeps_schedule(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_1f1b",
                "base_implementation": "spmd",
                "options": {
                    "schedule": "1f1b", "batch": 4, "vocab": 64,
                    "n_heads": 4, "microbatches": 2, "attn_kernel": "einsum",
                },
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    def test_1f1b_rejects_forward_mode(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_step", "spmd")
        with pytest.raises(ValueError, match="training schedule"):
            cls(
                16, 32, 64, dtype="float32",
                schedule="1f1b", mode="forward", batch=4, vocab=64,
                n_heads=4, microbatches=2,
            )


class TestModelInterleaved:
    """Interleaved virtual chunks at the MODEL level: chunk c of device p
    is global stage c*pp + p; the tick body dynamically indexes the
    chunk's param slice and grads accumulate per chunk."""

    @pytest.mark.slow  # same budget reasoning as the 1F1B grads-match
    # test: two full-model pipeline compiles for one equivalence check
    def test_matches_gpipe_on_same_model(self):
        """The same 4-layer model partitioned two ways — GPipe pp=2
        stages of 2 layers vs interleaved v=2 chunks of 1 layer on the
        same 2-device ring — must produce the same loss and grads."""
        import jax

        from ddlb_tpu.models.pipeline import (
            arrange_stage_stack,
            make_loss_and_grads_1f1b,
        )
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
            make_loss_fn,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp", "pp"), shape=(2, 2, 2))
        # einsum attention for the same budget reason as TestModel1F1B:
        # the partitioning equivalence under test is kernel-agnostic
        cfg_g = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=2, microbatches=4, attn_kernel="einsum",
        )
        cfg_i = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=4, attn_kernel="einsum",
        )
        params4 = init_params(cfg_i, pp=4, n_experts=2)
        tokens, targets = example_tokens(8, 16, 64)

        def to_gpipe(p):
            return {
                k: (
                    v.reshape((2, 2) + v.shape[2:])
                    if v.ndim and v.shape[:2] == (4, 1)
                    else v
                )
                for k, v in p.items()
            }

        loss_fn, sh_g = make_loss_fn(mesh, cfg_g)
        pg = {
            k: jax.device_put(v, sh_g[k])
            for k, v in to_gpipe(params4).items()
        }
        tok = jax.device_put(tokens, sh_g["data"])
        tgt = jax.device_put(targets, sh_g["data"])
        lg, gg = jax.jit(jax.value_and_grad(loss_fn))(pg, tok, tgt)

        fn_i, sh_i = make_loss_and_grads_1f1b(
            mesh, cfg_i, schedule="interleaved", virtual=2
        )
        pi = {
            k: jax.device_put(v, sh_i[k])
            for k, v in arrange_stage_stack(params4, pp=2, virtual=2).items()
        }
        li, gi = jax.jit(fn_i)(pi, tok, tgt)
        assert abs(float(lg) - float(li)) < 1e-6
        idx = np.array([c * 2 + p for p in range(2) for c in range(2)])
        inv = np.argsort(idx)
        for k in gg:
            a = np.asarray(gg[k], np.float32)
            b = np.asarray(gi[k], np.float32)
            if b.ndim and b.shape[:2] == (4, 1):
                b = b[inv].reshape(a.shape)
            rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-12)
            assert rel < 2e-3, f"grad '{k}': rel={rel:.3e}"

    @pytest.mark.slow  # a full benchmark_worker round (flagship compile
    # + validation oracle) per schedule flavor — ~18 s each, outside the
    # tier-1 870 s budget; interleaved executor semantics stay in-tier
    # (test_output_and_grads_validate_f32[interleaved-2]) and the
    # worker-row plumbing via test_schedule_through_benchmark_worker
    def test_member_sweeps_interleaved(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_interleaved",
                "base_implementation": "spmd",
                "options": {
                    "schedule": "interleaved", "virtual": 2, "batch": 4,
                    "vocab": 64, "n_heads": 4, "microbatches": 2,
                    "attn_kernel": "einsum",
                },
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    def test_arrange_stage_stack_leaves_replicated_alone(self):
        """Spec-classified: stage leaves permute device-major; replicated
        leaves stay put even when their leading dim equals the chain
        depth (the shape-collision hazard)."""
        import numpy as np_

        from ddlb_tpu.models.pipeline import arrange_stage_stack

        params = {
            "w_o": np_.arange(4)[:, None].repeat(3, 1),  # stage-stacked
            # vocab == chain depth: must NOT be permuted
            "embed": np_.arange(4)[:, None].repeat(2, 1),
        }
        out = arrange_stage_stack(params, pp=2, virtual=2)
        # device-major: [stage0, stage2, stage1, stage3]
        np_.testing.assert_array_equal(out["w_o"][:, 0], [0, 2, 1, 3])
        np_.testing.assert_array_equal(out["embed"], params["embed"])

    def test_bad_combinations_rejected(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_step", "spmd")
        with pytest.raises(ValueError, match="virtual >= 2"):
            cls(16, 32, 64, dtype="float32", schedule="interleaved",
                batch=4, vocab=64, n_heads=4, microbatches=2)
        with pytest.raises(ValueError, match="virtual=1 schedule"):
            cls(16, 32, 64, dtype="float32", schedule="1f1b", virtual=2,
                batch=4, vocab=64, n_heads=4, microbatches=2)
        # forward mode has no table executor: gpipe+virtual>1 must not
        # silently run one chunk per device through make_loss_fn
        with pytest.raises(ValueError, match="mode='train'"):
            cls(16, 32, 64, dtype="float32", schedule="gpipe", virtual=2,
                mode="forward", batch=4, vocab=64, n_heads=4, microbatches=2)

    @pytest.mark.slow  # same budget reasoning as the interleaved member
    # sweep above; gpipe+virtual executor semantics stay in-tier via
    # test_gpipe_chunked_equal_depth and the rejection guards
    def test_member_sweeps_gpipe_virtual(self):
        """gpipe+virtual>1 (the equal-chain-depth comparison partner for
        interleaved) is accepted and validates — same semantics as the
        pp_pipeline schedules member (ADVICE r3)."""
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_gpipe_v2",
                "base_implementation": "spmd",
                "options": {
                    "schedule": "gpipe", "virtual": 2, "batch": 4,
                    "vocab": 64, "n_heads": 4, "microbatches": 2,
                    "attn_kernel": "einsum",
                },
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True
