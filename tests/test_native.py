"""Native host-runtime layer: C++ planner/stats vs Python fallbacks.

The compiled library and the numpy fallbacks must agree exactly — the
suite compares them directly and also re-derives the schedule conventions
the overlap pipelines and Pallas ring kernels rely on.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from ddlb_tpu import native


def _py_reference_schedule(d, kind):
    out = np.empty((d, d), np.int32)
    for r in range(d):
        for t in range(d):
            out[r, t] = {
                "ag_fwd": (r - t) % d,
                "ag_bwd": (r + t) % d,
                "rs_fwd": (r + d - 1 - t) % d,
                "rs_bwd": (r + t + 1) % d,
            }[kind]
    return out


@pytest.mark.parametrize("d", [1, 2, 3, 4, 8])
@pytest.mark.parametrize("kind", sorted(native.RING_KINDS))
def test_ring_schedule(d, kind):
    table = native.ring_schedule(d, kind)
    np.testing.assert_array_equal(table, _py_reference_schedule(d, kind))
    # each rank touches every chunk exactly once
    for r in range(d):
        assert sorted(table[r]) == list(range(d))


def test_ring_schedule_rs_ends_on_own_chunk():
    # the reduce-scatter schedule must leave rank r holding chunk r
    for d in (2, 4, 8):
        table = native.ring_schedule(d, "rs_fwd")
        np.testing.assert_array_equal(table[:, d - 1], np.arange(d))


def test_ring_schedule_bad_args():
    with pytest.raises(ValueError, match="ring kind"):
        native.ring_schedule(4, "sideways")
    with pytest.raises(ValueError, match="positive"):
        native.ring_schedule(0)


@pytest.mark.parametrize("m,d,s", [(12, 2, 3), (64, 4, 4), (8, 8, 1), (6, 1, 3)])
def test_coll_pipeline_row_map(m, d, s):
    perm = native.coll_pipeline_row_map(m, d, s)
    b = m // (d * s)
    # definition: concat-order j = (stage*d + rank)*b + row maps to global
    # row rank*(s*b) + stage*b + row — i.e. the [s,d,b] -> [d,s,b] transpose
    expect = (
        np.arange(m, dtype=np.int32).reshape(d, s, b).transpose(1, 0, 2).ravel()
    )
    np.testing.assert_array_equal(perm, expect)
    assert sorted(perm) == list(range(m))


def test_coll_pipeline_row_map_matches_overlap_reassembly():
    # the on-device reassembly in tp_columnwise/overlap.py coll_pipeline is
    # reshape(s, d, b, n).transpose(1, 0, 2, 3): applying the planner's
    # permutation to concat-order rows must reproduce it
    m, d, s, n = 24, 2, 3, 5
    b = m // (d * s)
    rows = np.random.default_rng(0).normal(size=(m, n))
    via_transpose = (
        rows.reshape(s, d, b, n).transpose(1, 0, 2, 3).reshape(m, n)
    )
    perm = native.coll_pipeline_row_map(m, d, s)
    via_perm = np.empty_like(rows)
    via_perm[perm] = rows
    np.testing.assert_array_equal(via_perm, via_transpose)


def test_coll_pipeline_row_map_bad_args():
    with pytest.raises(ValueError, match="multiple"):
        native.coll_pipeline_row_map(10, 2, 3)


def test_robust_stats_matches_numpy():
    rng = np.random.default_rng(7)
    xs = rng.lognormal(0, 1, 501)
    s = native.robust_stats(xs)
    med = np.median(xs)
    np.testing.assert_allclose(s["mean"], np.mean(xs), rtol=1e-12)
    np.testing.assert_allclose(s["std"], np.std(xs), rtol=1e-12)
    np.testing.assert_allclose(s["min"], np.min(xs))
    np.testing.assert_allclose(s["max"], np.max(xs))
    np.testing.assert_allclose(s["median"], med, rtol=1e-12)
    np.testing.assert_allclose(s["p05"], np.percentile(xs, 5), rtol=1e-12)
    np.testing.assert_allclose(s["p95"], np.percentile(xs, 95), rtol=1e-12)
    np.testing.assert_allclose(
        s["mad"], np.median(np.abs(xs - med)), rtol=1e-12
    )


def test_robust_stats_single_sample():
    s = native.robust_stats([2.5])
    assert s["mean"] == s["median"] == s["min"] == s["max"] == 2.5
    assert s["std"] == s["mad"] == 0.0


def test_robust_stats_empty():
    with pytest.raises(ValueError, match="non-empty"):
        native.robust_stats([])


def test_now_ns_monotonic():
    a = native.now_ns()
    b = native.now_ns()
    assert b >= a
    assert b - a < 10**9  # two calls within a second


def test_fallback_parity():
    """Pure-Python fallbacks (DDLB_TPU_NO_NATIVE=1) agree with the library."""
    code = """
import numpy as np
from ddlb_tpu import native
assert not native.available()
print(native.ring_schedule(4, "rs_fwd").tolist())
print(native.coll_pipeline_row_map(12, 2, 3).tolist())
s = native.robust_stats([3.0, 1.0, 2.0, 10.0, 4.0])
print([round(s[k], 9) for k in native.STAT_NAMES])
print(native.now_ns() > 0)
"""
    env = dict(os.environ, DDLB_TPU_NO_NATIVE="1")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env, check=True,
    ).stdout.strip().splitlines()
    assert out[0] == str(native.ring_schedule(4, "rs_fwd").tolist())
    assert out[1] == str(native.coll_pipeline_row_map(12, 2, 3).tolist())
    s = native.robust_stats([3.0, 1.0, 2.0, 10.0, 4.0])
    assert out[2] == str([round(s[k], 9) for k in native.STAT_NAMES])
    assert out[3] == "True"


@pytest.mark.skipif(
    bool(os.environ.get("DDLB_TPU_NO_NATIVE")) or shutil.which("g++") is None,
    reason="native path disabled or no C++ toolchain (fallbacks are supported)",
)
def test_library_actually_built():
    """With a toolchain present the native path must be live."""
    assert native.available()
    from ddlb_tpu.native.build import LIBRARY

    assert os.path.exists(LIBRARY)


_SCHED_MATRIX = [
    ("gpipe", 2, 4, 1), ("gpipe", 4, 8, 1), ("gpipe", 4, 8, 2),
    ("gpipe", 8, 16, 1),
    ("1f1b", 2, 4, 1), ("1f1b", 4, 8, 1), ("1f1b", 4, 16, 1),
    ("1f1b", 8, 32, 1),
    ("interleaved", 2, 4, 2), ("interleaved", 4, 8, 2),
    ("interleaved", 4, 8, 4), ("interleaved", 8, 16, 2),
]


@pytest.mark.skipif(
    bool(os.environ.get("DDLB_TPU_NO_NATIVE")) or shutil.which("g++") is None,
    reason="native path disabled or no C++ toolchain (fallbacks are supported)",
)
@pytest.mark.parametrize("schedule,d,mb,v", _SCHED_MATRIX)
def test_pipeline_schedule_native_matches_python(schedule, d, mb, v):
    """The C++ schedule simulator is pinned exactly equal to the Python
    one — every table, slot assignment, and accounting field."""
    from ddlb_tpu.utils.pipeline_schedule import _build_schedule_py

    nat = native.pipeline_schedule(schedule, d, mb, v)
    assert nat is not None
    py = _build_schedule_py(schedule, d, mb, v)
    assert nat["ticks"] == py.ticks
    assert nat["act_slots"] == py.act_slots
    assert nat["land_slots"] == py.land_slots
    np.testing.assert_array_equal(nat["busy"], py.busy)
    for name in native.SCHEDULE_TABLE_NAMES:
        np.testing.assert_array_equal(
            nat[name], getattr(py, name), err_msg=f"table '{name}' diverges"
        )


def test_pipeline_schedule_bad_args():
    with pytest.raises(ValueError, match="unknown schedule"):
        native.pipeline_schedule("zigzag", 2, 4)
    if native.available():
        with pytest.raises(ValueError, match="positive"):
            native.pipeline_schedule("gpipe", 0, 4)
        with pytest.raises(RuntimeError, match="rc="):
            # 1f1b with virtual != 1 is rejected by the C ABI (rc=-3);
            # build_schedule screens it first with a friendlier message
            native.pipeline_schedule("1f1b", 2, 4, 2)


def test_build_schedule_routes_through_native():
    """With the library loaded, build_schedule uses the C++ simulator and
    the ScheduleTables it assembles matches the Python path field-by-field
    (pins the dict->dataclass mapping, not just the raw tables)."""
    from ddlb_tpu.utils import pipeline_schedule as ps

    t = ps.build_schedule("interleaved", 4, 8, virtual=2)
    assert t.ticks > 0 and t.kind.shape == (t.ticks, 4)
    if native.available():
        py = ps._build_schedule_py("interleaved", 4, 8, 2)
        for name in (
            "schedule", "n_devices", "n_stages", "virtual", "microbatches",
            "ticks", "act_slots", "land_slots",
        ):
            assert getattr(t, name) == getattr(py, name), name
        for name in native.SCHEDULE_TABLE_NAMES + ("busy",):
            np.testing.assert_array_equal(
                getattr(t, name), getattr(py, name), err_msg=name
            )
    # 1F1B keeps GPipe's tick count but shrinks the stash to O(depth)
    f = ps.build_schedule("1f1b", 4, 8)
    g = ps.build_schedule("gpipe", 4, 8)
    assert f.ticks == g.ticks
    assert f.peak_stash <= 4 + 1 < g.peak_stash


def test_build_schedule_bad_sizes_uniform_across_paths():
    # d/mb/v positivity is screened before the native/fallback split, so
    # both paths raise the same ValueError
    from ddlb_tpu.utils.pipeline_schedule import build_schedule

    with pytest.raises(ValueError, match="positive"):
        build_schedule("gpipe", 0, 4)
    with pytest.raises(ValueError, match="positive"):
        build_schedule("gpipe", 4, -1)


def test_robust_stats_nonfinite_is_all_nan():
    # pinned contract: both native and fallback paths return all-NaN for a
    # sample containing any non-finite value (C++ sort of NaNs is UB)
    s = native.robust_stats([1.0, float("nan"), 2.0])
    assert all(np.isnan(v) for v in s.values())
    s = native.robust_stats([float("inf")])
    assert all(np.isnan(v) for v in s.values())
