"""Native host-runtime layer: C++ planner/stats vs Python fallbacks.

The compiled library and the numpy fallbacks must agree exactly — the
suite compares them directly and also re-derives the schedule conventions
the overlap pipelines and Pallas ring kernels rely on.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from ddlb_tpu import native


def _py_reference_schedule(d, kind):
    out = np.empty((d, d), np.int32)
    for r in range(d):
        for t in range(d):
            out[r, t] = {
                "ag_fwd": (r - t) % d,
                "ag_bwd": (r + t) % d,
                "rs_fwd": (r + d - 1 - t) % d,
                "rs_bwd": (r + t + 1) % d,
            }[kind]
    return out


@pytest.mark.parametrize("d", [1, 2, 3, 4, 8])
@pytest.mark.parametrize("kind", sorted(native.RING_KINDS))
def test_ring_schedule(d, kind):
    table = native.ring_schedule(d, kind)
    np.testing.assert_array_equal(table, _py_reference_schedule(d, kind))
    # each rank touches every chunk exactly once
    for r in range(d):
        assert sorted(table[r]) == list(range(d))


def test_ring_schedule_rs_ends_on_own_chunk():
    # the reduce-scatter schedule must leave rank r holding chunk r
    for d in (2, 4, 8):
        table = native.ring_schedule(d, "rs_fwd")
        np.testing.assert_array_equal(table[:, d - 1], np.arange(d))


def test_ring_schedule_bad_args():
    with pytest.raises(ValueError, match="ring kind"):
        native.ring_schedule(4, "sideways")
    with pytest.raises(ValueError, match="positive"):
        native.ring_schedule(0)


@pytest.mark.parametrize("m,d,s", [(12, 2, 3), (64, 4, 4), (8, 8, 1), (6, 1, 3)])
def test_coll_pipeline_row_map(m, d, s):
    perm = native.coll_pipeline_row_map(m, d, s)
    b = m // (d * s)
    # definition: concat-order j = (stage*d + rank)*b + row maps to global
    # row rank*(s*b) + stage*b + row — i.e. the [s,d,b] -> [d,s,b] transpose
    expect = (
        np.arange(m, dtype=np.int32).reshape(d, s, b).transpose(1, 0, 2).ravel()
    )
    np.testing.assert_array_equal(perm, expect)
    assert sorted(perm) == list(range(m))


def test_coll_pipeline_row_map_matches_overlap_reassembly():
    # the on-device reassembly in tp_columnwise/overlap.py coll_pipeline is
    # reshape(s, d, b, n).transpose(1, 0, 2, 3): applying the planner's
    # permutation to concat-order rows must reproduce it
    m, d, s, n = 24, 2, 3, 5
    b = m // (d * s)
    rows = np.random.default_rng(0).normal(size=(m, n))
    via_transpose = (
        rows.reshape(s, d, b, n).transpose(1, 0, 2, 3).reshape(m, n)
    )
    perm = native.coll_pipeline_row_map(m, d, s)
    via_perm = np.empty_like(rows)
    via_perm[perm] = rows
    np.testing.assert_array_equal(via_perm, via_transpose)


def test_coll_pipeline_row_map_bad_args():
    with pytest.raises(ValueError, match="multiple"):
        native.coll_pipeline_row_map(10, 2, 3)


def test_robust_stats_matches_numpy():
    rng = np.random.default_rng(7)
    xs = rng.lognormal(0, 1, 501)
    s = native.robust_stats(xs)
    med = np.median(xs)
    np.testing.assert_allclose(s["mean"], np.mean(xs), rtol=1e-12)
    np.testing.assert_allclose(s["std"], np.std(xs), rtol=1e-12)
    np.testing.assert_allclose(s["min"], np.min(xs))
    np.testing.assert_allclose(s["max"], np.max(xs))
    np.testing.assert_allclose(s["median"], med, rtol=1e-12)
    np.testing.assert_allclose(s["p05"], np.percentile(xs, 5), rtol=1e-12)
    np.testing.assert_allclose(s["p95"], np.percentile(xs, 95), rtol=1e-12)
    np.testing.assert_allclose(
        s["mad"], np.median(np.abs(xs - med)), rtol=1e-12
    )


def test_robust_stats_single_sample():
    s = native.robust_stats([2.5])
    assert s["mean"] == s["median"] == s["min"] == s["max"] == 2.5
    assert s["std"] == s["mad"] == 0.0


def test_robust_stats_empty():
    with pytest.raises(ValueError, match="non-empty"):
        native.robust_stats([])


def test_now_ns_monotonic():
    a = native.now_ns()
    b = native.now_ns()
    assert b >= a
    assert b - a < 10**9  # two calls within a second


def test_fallback_parity():
    """Pure-Python fallbacks (DDLB_TPU_NO_NATIVE=1) agree with the library."""
    code = """
import numpy as np
from ddlb_tpu import native
assert not native.available()
print(native.ring_schedule(4, "rs_fwd").tolist())
print(native.coll_pipeline_row_map(12, 2, 3).tolist())
s = native.robust_stats([3.0, 1.0, 2.0, 10.0, 4.0])
print([round(s[k], 9) for k in native.STAT_NAMES])
print(native.now_ns() > 0)
"""
    env = dict(os.environ, DDLB_TPU_NO_NATIVE="1")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env, check=True,
    ).stdout.strip().splitlines()
    assert out[0] == str(native.ring_schedule(4, "rs_fwd").tolist())
    assert out[1] == str(native.coll_pipeline_row_map(12, 2, 3).tolist())
    s = native.robust_stats([3.0, 1.0, 2.0, 10.0, 4.0])
    assert out[2] == str([round(s[k], 9) for k in native.STAT_NAMES])
    assert out[3] == "True"


@pytest.mark.skipif(
    bool(os.environ.get("DDLB_TPU_NO_NATIVE")) or shutil.which("g++") is None,
    reason="native path disabled or no C++ toolchain (fallbacks are supported)",
)
def test_library_actually_built():
    """With a toolchain present the native path must be live."""
    assert native.available()
    from ddlb_tpu.native.build import LIBRARY

    assert os.path.exists(LIBRARY)


def test_robust_stats_nonfinite_is_all_nan():
    # pinned contract: both native and fallback paths return all-NaN for a
    # sample containing any non-finite value (C++ sort of NaNs is UB)
    s = native.robust_stats([1.0, float("nan"), 2.0])
    assert all(np.isnan(v) for v in s.values())
    s = native.robust_stats([float("inf")])
    assert all(np.isnan(v) for v in s.values())
