"""Traffic-scale serving observability (ISSUE 11).

Four layers, cheapest first: the workload generator's determinism
contract, the streaming-percentile accuracy bound, the SLO regression
gate's fire/stay-silent semantics on synthetic history, and the
engine-backed ``serving_load`` family end to end (SLO columns, the
preemption policy under overload, the serve fault sites).
"""

import csv
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))


# ---------------------------------------------------------------------------
# workload generator
# ---------------------------------------------------------------------------


class TestGenerator:
    def _spec(self, **kw):
        from ddlb_tpu.workload import WorkloadSpec

        base = dict(n_requests=64, rate_rps=20.0, seed=7)
        base.update(kw)
        return WorkloadSpec(**base)

    @pytest.mark.parametrize("process", ["poisson", "bursty"])
    def test_seeded_determinism(self, process):
        """Two runs, identical traces — arrivals, prompts, budgets,
        prefix picks, byte for byte (the satellite's pinned contract)."""
        from ddlb_tpu.workload import generate_trace

        spec = self._spec(
            process=process, prefix_pop=4, prefix_len=8, seed=13
        )
        t1 = generate_trace(spec)
        t2 = generate_trace(spec)
        assert len(t1) == len(t2) == 64
        for a, b in zip(t1, t2):
            assert a.arrival_s == b.arrival_s
            assert a.max_new == b.max_new
            assert a.prefix_id == b.prefix_id
            np.testing.assert_array_equal(a.prompt, b.prompt)

    def test_seed_changes_trace(self):
        from ddlb_tpu.workload import generate_trace

        t1 = generate_trace(self._spec(seed=1))
        t2 = generate_trace(self._spec(seed=2))
        assert any(
            a.arrival_s != b.arrival_s or not np.array_equal(a.prompt, b.prompt)
            for a, b in zip(t1, t2)
        )

    def test_arrivals_monotone_and_rate_shaped(self):
        from ddlb_tpu.workload import generate_trace

        trace = generate_trace(self._spec(n_requests=400, rate_rps=50.0))
        arr = np.array([r.arrival_s for r in trace])
        assert (np.diff(arr) >= 0).all()
        realized = len(arr) / arr[-1]
        assert 35.0 < realized < 70.0  # Poisson noise around 50 rps

    def test_bursty_mean_rate_preserved(self):
        """The MMPP's burst/quiet rates must average back to the
        offered rate — the process axis varies burstiness, not load."""
        from ddlb_tpu.workload import generate_trace

        trace = generate_trace(
            self._spec(
                n_requests=600, rate_rps=50.0, process="bursty",
                burst_factor=4.0, burst_duty=0.2, burst_len_s=0.5,
            )
        )
        arr = np.array([r.arrival_s for r in trace])
        realized = len(arr) / arr[-1]
        assert 35.0 < realized < 70.0

    def test_zipf_prefix_population(self):
        """Rank 0 is the hot prefix; prompts carry their prefix tokens
        inline."""
        from ddlb_tpu.workload import generate_trace, prefix_tokens

        spec = self._spec(
            n_requests=300, prefix_pop=6, prefix_len=12, prefix_alpha=1.2
        )
        trace = generate_trace(spec)
        counts = np.bincount(
            [r.prefix_id for r in trace], minlength=spec.prefix_pop
        )
        assert counts[0] == counts.max() and counts[0] > 0
        hot = prefix_tokens(spec, 0)
        for r in trace:
            want = prefix_tokens(spec, r.prefix_id)
            np.testing.assert_array_equal(r.prompt[: want.size], want)
        assert hot.size == 12

    def test_spec_validation(self):
        from ddlb_tpu.workload import WorkloadSpec

        with pytest.raises(ValueError, match="rate_rps"):
            self._spec(rate_rps=0.0)
        with pytest.raises(ValueError, match="process"):
            self._spec(process="steady")
        with pytest.raises(ValueError, match="quiet"):
            self._spec(process="bursty", burst_factor=6.0, burst_duty=0.2)
        with pytest.raises(ValueError, match="prefix_len"):
            self._spec(prefix_pop=2, prefix_len=0)
        assert WorkloadSpec(n_requests=1, rate_rps=1.0).max_total_tokens > 0


# ---------------------------------------------------------------------------
# streaming percentiles + SLO ledger
# ---------------------------------------------------------------------------


class TestStreamingQuantile:
    def test_within_one_percent_of_numpy(self):
        """The satellite's accuracy bar: 10k-sample reference, every
        reported percentile within 1% of exact numpy.quantile."""
        from ddlb_tpu.workload import StreamingQuantile

        rng = np.random.default_rng(3)
        samples = rng.lognormal(mean=2.5, sigma=1.1, size=10_000)
        sq = StreamingQuantile()
        for s in samples:
            sq.add(float(s))
        for q in (0.5, 0.9, 0.95, 0.99):
            exact = float(np.quantile(samples, q))
            est = sq.quantile(q)
            assert abs(est - exact) / exact < 0.01, (q, est, exact)

    def test_empty_and_clamped(self):
        from ddlb_tpu.workload import StreamingQuantile

        sq = StreamingQuantile()
        assert sq.quantile(0.5) != sq.quantile(0.5)  # NaN
        sq.add(5.0)
        assert sq.quantile(0.0) == sq.quantile(1.0) == 5.0
        with pytest.raises(ValueError):
            sq.quantile(1.5)


class TestSLOTracker:
    def test_ledger_and_goodput(self):
        from ddlb_tpu.workload import SLOTracker

        tr = SLOTracker(ttft_slo_ms=100.0, tpot_slo_ms=50.0)
        # request 0: meets both bounds (ttft 50ms, tpot 10ms over 3 tok)
        tr.arrived(0, 0.0)
        tr.first_token(0, 0.05)
        tr.finished(0, 0.07, new_tokens=3)
        # request 1: misses the TTFT bound
        tr.arrived(1, 0.0)
        tr.first_token(1, 0.5)
        tr.finished(1, 0.52, new_tokens=3)
        tr.observe_queue(2)
        tr.observe_queue(4)
        fields = tr.row_fields(makespan_s=1.0, offered_rps=2.0)
        assert fields["slo_completed"] == 2
        assert fields["slo_goodput_rps"] == pytest.approx(1.0)
        assert fields["slo_attainment"] == pytest.approx(0.5)
        assert fields["serve_queue_peak"] == 4
        assert fields["serve_queue_mean"] == pytest.approx(3.0)
        assert fields["slo_ttft_p50_ms"] == pytest.approx(50.0, rel=0.02)

    def test_pooling_across_drains(self):
        """new_drain keeps the distributions and counters, resets the
        per-request timelines — indices reuse cleanly."""
        from ddlb_tpu.workload import SLOTracker

        tr = SLOTracker(ttft_slo_ms=1000.0, tpot_slo_ms=1000.0)
        for _ in range(3):
            tr.arrived(0, 0.0)
            tr.first_token(0, 0.01)
            tr.finished(0, 0.02, new_tokens=2)
            tr.new_drain()
        assert tr.completed == 3

    def test_first_token_idempotent(self):
        """A preempted request's re-admission must not move its TTFT."""
        from ddlb_tpu.workload import SLOTracker

        tr = SLOTracker(ttft_slo_ms=1000.0, tpot_slo_ms=1000.0)
        tr.arrived(0, 0.0)
        tr.first_token(0, 0.02)
        tr.first_token(0, 0.9)  # re-admission after preemption: no-op
        tr.finished(0, 1.0, new_tokens=2)
        assert tr.row_fields(1.0, 1.0)["slo_ttft_p50_ms"] == pytest.approx(
            20.0, rel=0.02
        )


# ---------------------------------------------------------------------------
# the SLO regression gate (synthetic history — detector semantics)
# ---------------------------------------------------------------------------


def _serving_record(run, ttft95=20.0, goodput=5.0, med=10.0, rate="8.0"):
    from ddlb_tpu.observatory import regress

    row = {
        "implementation": "engine_0", "base_implementation": "engine",
        "primitive": "serving_load", "option": f"out_mean=4;rate={rate}",
        "m": 8, "n": 32, "k": 64, "dtype": "float32", "world_size": 4,
        "chip": "cpu-sim", "time_measurement_backend": "host_clock",
        "median time (ms)": med,
        "slo_ttft_p50_ms": ttft95 * 0.6,
        "slo_ttft_p95_ms": ttft95,
        "slo_ttft_p99_ms": ttft95 * 1.2,
        "slo_tpot_p95_ms": 3.0,
        "slo_goodput_rps": goodput,
    }
    return {
        "kind": "row", "run_id": run, "key": regress.row_key(row),
        "row": row,
    }


class TestSLOGate:
    def _history(self, n=4):
        return [
            _serving_record(f"r{i}", ttft95=20.0 + 0.3 * i) for i in range(n)
        ]

    def test_silent_on_clean(self):
        from ddlb_tpu.observatory import regress

        clean = [_serving_record("cur", ttft95=20.5)["row"]]
        assert (
            regress.detect_all(clean, self._history(), exclude_run="cur")
            == []
        )

    def test_fires_on_2x_slowdown_ranked_first(self):
        """A seeded 2x decode slowdown doubles the TTFT percentiles and
        halves goodput; the gate must fire with SLO-metric findings and
        rank by robust z."""
        from ddlb_tpu.observatory import regress

        slowed = [
            _serving_record("cur", ttft95=41.0, goodput=2.4, med=10.2)["row"]
        ]
        findings = regress.detect_all(
            slowed, self._history(), exclude_run="cur"
        )
        assert findings
        assert all(str(f["metric"]).startswith("slo_") for f in findings)
        assert findings[0]["ratio"] == pytest.approx(2.0, rel=0.1)
        zs = [f["z"] for f in findings]
        assert zs == sorted(zs, reverse=True)

    def test_goodput_direction_is_inverted(self):
        from ddlb_tpu.observatory import regress

        dropped = [_serving_record("cur", goodput=2.0)["row"]]
        findings = regress.detect_slo(
            dropped, self._history(), exclude_run="cur"
        )
        assert [f["metric"] for f in findings] == ["slo_goodput_rps"]
        assert findings[0]["ratio"] == pytest.approx(2.5)
        # goodput IMPROVING must never flag
        improved = [_serving_record("cur", goodput=50.0)["row"]]
        assert (
            regress.detect_slo(improved, self._history(), exclude_run="cur")
            == []
        )

    def test_min_history_withholds_judgment_on_thin_baselines(self):
        """ISSUE 19's false-positive rail #1: below SLO_MIN_HISTORY
        banked runs the MAD is meaningless (one or two rows -> spread
        ~0, every jitter z-scores to infinity), so the gate abstains
        even on a gross apparent slowdown — and fires once the
        baseline is deep enough."""
        from ddlb_tpu.observatory import regress

        slowed = [_serving_record("cur", ttft95=41.0)["row"]]
        thin = self._history(regress.SLO_MIN_HISTORY - 1)
        assert regress.detect_slo(slowed, thin, exclude_run="cur") == []
        deep = self._history(regress.SLO_MIN_HISTORY + 1)
        assert regress.detect_slo(slowed, deep, exclude_run="cur")

    def test_absolute_floors_ignore_sub_noise_excursions(self):
        """Rail #2: on a CPU-sim drill the percentiles live in
        single-digit milliseconds with near-zero MAD, so the relative
        machinery alone would flag sub-millisecond jitter. The
        ``SLO_ABS`` floors demand a real excess — and the same floors
        let a genuine excursion through."""
        from ddlb_tpu.observatory import regress

        history = [
            _serving_record(f"r{i}", ttft95=2.0) for i in range(4)
        ]
        _, min_excess = regress.SLO_ABS_DEFAULT
        # huge ratio (1.45x) and huge z (MAD ~ 0), excess below floor
        jitter = [_serving_record("cur", ttft95=2.0 + 0.9 * min_excess)["row"]]
        assert regress.detect_slo(jitter, history, exclude_run="cur") == []
        real = [_serving_record("cur", ttft95=2.0 + 2.0 * min_excess)["row"]]
        findings = regress.detect_slo(real, history, exclude_run="cur")
        # the fixture derives p99 from the same knob: both TTFT tails
        # clear the floors, nothing else does
        assert sorted(f["metric"] for f in findings) == [
            "slo_ttft_p95_ms", "slo_ttft_p99_ms",
        ]

    def test_goodput_floor_is_metric_scaled(self):
        """Goodput lives in single-digit rps, so it carries its own
        ``SLO_ABS`` entry — a 0.1 rps wobble is noise, a 1 rps drop on
        a 3 rps baseline is an incident."""
        from ddlb_tpu.observatory import regress

        history = [
            _serving_record(f"r{i}", goodput=3.0) for i in range(4)
        ]
        wobble = [_serving_record("cur", goodput=2.9)["row"]]
        assert regress.detect_slo(wobble, history, exclude_run="cur") == []
        drop = [_serving_record("cur", goodput=2.0)["row"]]
        findings = regress.detect_slo(drop, history, exclude_run="cur")
        assert [f["metric"] for f in findings] == ["slo_goodput_rps"]

    def test_non_serving_rows_contribute_nothing(self):
        from ddlb_tpu.observatory import regress

        row = {
            "implementation": "jax_spmd_0", "primitive": "tp_columnwise",
            "option": "-", "m": 64, "n": 64, "k": 64,
            "median time (ms)": 5.0,
        }
        assert regress.detect_slo([row], self._history()) == []

    def test_slo_metrics_are_registered_columns(self):
        """Every gated metric must be a schema-documented column — the
        gate cannot reference a column the rows will never carry."""
        from ddlb_tpu.observatory import regress
        from ddlb_tpu.schema import ROW_COLUMNS

        for metric, direction in regress.SLO_METRICS:
            assert metric in ROW_COLUMNS
            assert direction in ("high", "low")


# ---------------------------------------------------------------------------
# the report CLI: curves, knee, gate exit codes
# ---------------------------------------------------------------------------


def _curve_row(rate, ttft50, ttft95, goodput, impl="engine"):
    return {
        "primitive": "serving_load",
        "implementation": f"{impl}_0",
        "base_implementation": impl,
        "option": f"out_mean=4;rate={rate}",
        "m": 8, "n": 32, "k": 64, "dtype": "float32", "world_size": 4,
        "chip": "cpu-sim", "time_measurement_backend": "host_clock",
        "median time (ms)": 100.0,
        "slo_offered_rps": rate * 0.9,
        "slo_ttft_p50_ms": ttft50,
        "slo_ttft_p95_ms": ttft95,
        "slo_ttft_p99_ms": ttft95 * 1.2,
        "slo_tpot_p95_ms": 4.0,
        "slo_goodput_rps": goodput,
        "slo_attainment": 1.0,
        "serve_queue_peak": 0,
        "serve_preemptions": 0,
    }


class TestServingLoadReport:
    def _write_csv(self, path, rows):
        with open(path, "w", newline="", encoding="utf-8") as f:
            writer = csv.DictWriter(f, fieldnames=sorted(rows[0]))
            writer.writeheader()
            writer.writerows(rows)

    def test_curves_and_knee(self, tmp_path):
        import serving_load_report as rep

        rows = [
            _curve_row(4.0, 5.0, 9.0, 3.9),
            _curve_row(16.0, 6.0, 11.0, 15.0),
            _curve_row(64.0, 40.0, 120.0, 20.0),   # past the knee
            _curve_row(256.0, 160.0, 400.0, 21.0),
        ]
        curves = rep.build_curves(rows)
        assert len(curves) == 1
        knee = rep.find_knee(curves[0]["points"], 2.5)
        assert knee["detected"]
        assert knee["knee_rate"] == 64.0
        assert knee["sustained_rate"] == 16.0

    def test_no_knee_when_flat(self):
        import serving_load_report as rep

        points = rep.build_curves(
            [_curve_row(4.0, 5.0, 9.0, 3.9), _curve_row(8.0, 5.5, 9.5, 7.8)]
        )[0]["points"]
        assert not rep.find_knee(points, 2.5)["detected"]

    def test_cli_exit_codes(self, tmp_path, monkeypatch):
        """0 on clean vs history, 1 on a seeded regression, 2 usage —
        the observatory gating contract."""
        import serving_load_report as rep
        from ddlb_tpu.observatory import store

        monkeypatch.delenv("DDLB_TPU_HISTORY", raising=False)
        hist = tmp_path / "hist"
        # four banked runs: the gate's self-copy exclusion drops the
        # one whose (key, median) matches the current CSV, and the
        # survivors must still clear SLO_MIN_HISTORY
        for i, run in enumerate(("base-1", "base-2", "base-3", "base-4")):
            for rate in (4.0, 64.0):
                banked = _curve_row(rate, 5.0 + 0.1 * i, 9.0 + 0.1 * i, 3.9)
                # distinct medians per run: identical (key, median)
                # pairs would trip the gate's self-copy exclusion
                banked["median time (ms)"] = 100.0 + i
                store.bank_row(banked, run=run, directory=str(hist))
        clean_csv = tmp_path / "clean.csv"
        self._write_csv(
            clean_csv,
            [_curve_row(4.0, 5.1, 9.2, 3.85), _curve_row(64.0, 5.0, 9.1, 3.9)],
        )
        assert rep.main(
            ["--current", str(clean_csv), "--history", str(hist)]
        ) == 0
        slow_csv = tmp_path / "slow.csv"
        self._write_csv(
            slow_csv,
            [
                _curve_row(4.0, 10.4, 18.6, 1.9),   # 2x ttft, goodput halved
                _curve_row(64.0, 5.0, 9.1, 3.9),
            ],
        )
        assert rep.main(
            ["--current", str(slow_csv), "--history", str(hist)]
        ) == 1
        assert rep.main([]) == 2

    def test_json_document(self, tmp_path, capsys):
        import serving_load_report as rep

        path = tmp_path / "c.csv"
        self._write_csv(
            path, [_curve_row(4.0, 5.0, 9.0, 3.9), _curve_row(16.0, 30.0, 90.0, 4.0)]
        )
        rc = rep.main(["--current", str(path), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["curves"][0]["knee"]["detected"]


# ---------------------------------------------------------------------------
# dashboard: serving panel + forward-compat guard
# ---------------------------------------------------------------------------


class TestDashboardServingPanel:
    def _events(self):
        return [
            {"kind": "sweep_start", "total": 2, "pid": 1, "ts": 1.0},
            {"kind": "serving_tick", "pid": 1, "ts": 1.1, "queue_depth": 2,
             "active": 4, "done": 3, "total": 12},
            {"kind": "serving_tick", "pid": 1, "ts": 1.2, "queue_depth": 7,
             "active": 4, "done": 6, "total": 12},
            {"kind": "row_done", "pid": 1, "ts": 2.0, "impl": "engine_0",
             "median_ms": 900.0, "slo_ttft_p50_ms": 12.0,
             "slo_ttft_p95_ms": 31.0, "slo_ttft_p99_ms": 44.0,
             "slo_goodput_rps": 7.5, "slo_attainment": 0.96},
        ]

    def test_fold_serving_state(self):
        from ddlb_tpu.observatory import live

        state = live.fold(self._events())
        assert state["serving"]["depths"] == [2, 7]
        assert state["serving"]["latest"]["ttft_p95_ms"] == 31.0
        assert state["serving"]["progress"]["done"] == 6

    def test_unknown_kinds_counted_not_dropped(self):
        from ddlb_tpu.observatory import live

        events = self._events() + [
            {"kind": "from_the_future", "pid": 2, "ts": 3.0},
            {"kind": "from_the_future", "pid": 2, "ts": 3.1},
        ]
        state = live.fold(events)
        assert state["unknown"] == {"from_the_future": 2}

    def test_fold_tolerates_pre_serving_state_dict(self):
        """Forward compat the other way: an incremental fold onto a
        state dict built before the serving keys existed."""
        from ddlb_tpu.observatory import live

        old = live.fold([])
        old.pop("serving")
        old.pop("unknown")
        state = live.fold(self._events(), old)
        assert state["serving"]["latest"] is not None

    def test_text_frame_has_panel_and_note(self):
        import sweep_dash
        from ddlb_tpu.observatory import live

        state = live.fold(
            self._events() + [{"kind": "new_kind", "pid": 9, "ts": 4.0}]
        )
        text = sweep_dash.render_text(state)
        assert "serving:" in text
        assert "TTFT p50/p95/p99" in text
        assert "queue depth" in text
        assert "unrecognized" in text and "new_kind" in text

    def test_html_renders_unknown_kinds_not_blank(self):
        """The satellite: an --html snapshot over a stream full of
        unrecognized row kinds must render its tables + a loud note,
        never a blank frame."""
        import sweep_dash
        from ddlb_tpu.observatory import live

        foreign = [
            {"kind": f"kind_{i}", "pid": 1, "ts": float(i)} for i in range(5)
        ]
        state = live.fold(foreign)
        html = sweep_dash.render_html(state, source="test")
        assert "<table>" in html and "Workers" in html
        assert "unrecognized" in html and "kind_0" in html

    def test_html_serving_panel_sparkline(self):
        import sweep_dash
        from ddlb_tpu.observatory import live

        html = sweep_dash.render_html(live.fold(self._events()))
        assert "Serving" in html
        assert "polyline" in html and "queue depth" in html
        assert "TTFT p95" in html


# ---------------------------------------------------------------------------
# the engine under traffic (the expensive tier: two real drains)
# ---------------------------------------------------------------------------


def _worker_config(**options):
    base = {
        "batch": 8, "vocab": 64, "n_heads": 8, "layers": 1,
        "rate": 200.0, "n_requests": 10, "out_mean": 3, "out_max": 5,
        "slo_ttft_ms": 4000.0, "slo_tpot_ms": 2000.0,
    }
    base.update(options)
    return {
        "primitive": "serving_load",
        "impl_id": "engine_0",
        "base_implementation": "engine",
        "options": base,
        "m": 8, "n": 32, "k": 64, "dtype": "float32",
        "num_iterations": 1, "num_warmups": 1, "validate": True,
        "time_measurement_backend": "host_clock",
        "barrier_at_each_iteration": False,
    }


class TestServingLoadFamily:
    def test_row_carries_slo_columns_and_validates(self):
        from ddlb_tpu.benchmark import benchmark_worker
        from ddlb_tpu.schema import ROW_COLUMNS

        row = benchmark_worker(_worker_config())
        assert row["error"] == "" and bool(row["valid"])
        for col in (
            "slo_ttft_p50_ms", "slo_ttft_p95_ms", "slo_ttft_p99_ms",
            "slo_goodput_rps", "slo_attainment", "slo_offered_rps",
            "serve_queue_peak", "serve_queue_mean", "serve_preemptions",
            "serve_kv_evicted_tokens", "serve_occupancy",
        ):
            assert col in row, col
            assert col in ROW_COLUMNS, col
        assert row["slo_completed"] == 2 * 10  # timing + validation drains
        assert np.isfinite(float(row["slo_ttft_p95_ms"]))
        # the horizon floor: an open-loop drain can't beat its arrivals
        assert float(row["predicted_s"]) > 0.0

    def test_hol_preemption_fires_under_overload_and_accounts(self):
        """Head-of-line preemption under a burst of LONG generations:
        with every slot pinned by a long-running request (short ones
        free slots almost every tick — continuous batching alone
        relieves the head), the head would wait tens of ticks; the
        policy preempts instead, KV rows are evicted, and the
        accounting validation STILL holds (every request completes
        exactly once at full budget)."""
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            _worker_config(
                rate=2000.0, n_requests=12, out_mean=30, out_max=40,
                preempt_hol_ticks=3,
            )
        )
        assert row["error"] == "" and bool(row["valid"])
        assert int(row["serve_preemptions"]) > 0
        assert int(row["serve_kv_evicted_tokens"]) > 0

    def test_trace_identity_is_seed_stable(self):
        """Two impl constructions, identical workload (the bankable-row
        precondition)."""
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("serving_load", "engine")
        a = cls(8, 32, 64, dtype="float32", rate=50.0, n_requests=6,
                batch=8, vocab=64, n_heads=8)
        b = cls(8, 32, 64, dtype="float32", rate=50.0, n_requests=6,
                batch=8, vocab=64, n_heads=8)
        for ra, rb in zip(a._trace, b._trace):
            assert ra.arrival_s == rb.arrival_s
            np.testing.assert_array_equal(ra.prompt, rb.prompt)


class TestServeFaultSites:
    def test_decode_tick_site_fires(self, monkeypatch):
        """The chaos battery can target the serving path: a
        serve.decode_tick rule fires on every tick (the latency-
        injection shape the demo uses for its seeded slowdown)."""
        from ddlb_tpu import faults
        from ddlb_tpu.faults import plan as fault_plan

        plan = {
            "seed": 1,
            "rules": [{"site": "serve.decode_tick", "kind": "hang",
                       "duration_s": 0.0}],
        }
        monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", json.dumps(plan))
        fault_plan.reset()
        try:
            from ddlb_tpu.benchmark import benchmark_worker

            row = benchmark_worker(_worker_config(n_requests=4))
            assert "serve.decode_tick" in str(row["fault_injected"])
            assert row["error"] == ""
        finally:
            monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
            fault_plan.reset()

    def test_sites_registered(self):
        from ddlb_tpu.faults.plan import SITES

        assert "serve.admit" in SITES
        assert "serve.decode_tick" in SITES


# ---------------------------------------------------------------------------
# engine preemption mechanism (direct, no worker)
# ---------------------------------------------------------------------------


class TestEnginePreemption:
    def _engine(self, **kw):
        import jax

        from ddlb_tpu.models.decode import make_decode_fn
        from ddlb_tpu.models.serving import ContinuousBatchingEngine
        from ddlb_tpu.models.transformer import TransformerConfig, init_params
        from ddlb_tpu.runtime import Runtime

        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=1, attn_kernel="einsum",
            **kw.pop("cfg_kw", {}),
        )
        mesh = Runtime().mesh(("dp", "tp"), shape=(1, 2))
        params = init_params(cfg, pp=1, n_experts=2, seed=0)
        _, sh = make_decode_fn(mesh, cfg)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        eng = ContinuousBatchingEngine(
            mesh, cfg, params, max_batch=2, max_len=48, **kw
        )
        return eng, cfg, mesh, params

    def test_preempt_resumes_same_greedy_chain(self):
        from ddlb_tpu.models.serving import Request

        eng, *_ = self._engine()
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, 64, 8).astype(np.int32)
        eng.submit(Request(prompt, max_new=6))
        eng.admit_ready()
        eng.step()
        eng.step()
        baseline, *_ = self._engine()
        baseline.submit(Request(prompt, max_new=6))
        done_base = baseline.run()
        new_idx = eng.preempt(0)
        done = eng.run()
        assert eng.stats.preemptions == 1
        assert eng.stats.kv_evicted_tokens > 0
        resumed = [c for c in done if c.request_index == new_idx]
        assert len(resumed) == 1
        np.testing.assert_array_equal(
            resumed[0].tokens, done_base[0].tokens
        )

    def test_requeue_back_vs_front(self):
        from ddlb_tpu.models.serving import Request

        eng, *_ = self._engine()
        rng = np.random.default_rng(1)
        prompts = [rng.integers(1, 64, 6).astype(np.int32) for _ in range(4)]
        for p in prompts:
            eng.submit(Request(p, max_new=4))
        eng.admit_ready()  # fills both slots; 2 queued
        eng.step()
        head_before = eng.queue_head()
        back_idx = eng.preempt(0)  # default: back of the queue
        assert eng.queue_head() == head_before
        front_idx = eng.preempt(1, requeue="front")
        assert eng.queue_head() == front_idx
        assert back_idx != front_idx
        with pytest.raises(ValueError, match="idle"):
            eng.preempt(0)
        with pytest.raises(ValueError, match="requeue"):
            # both slots idle now, but the arg check comes first
            eng.preempt(0, requeue="sideways")
        done = eng.run()
        # 2 untouched originals + 2 remnants complete (the preempted
        # originals live on only through their remnants)
        assert len(done) == 4
        assert {c.request_index for c in done} == {2, 3, back_idx, front_idx}
        assert eng.stats.preemptions == 2

    def test_preempt_paged_releases_pages(self):
        from ddlb_tpu.models.serving import Request

        eng, *_ = self._engine(
            cfg_kw={"cache_layout": "paged", "page_size": 8},
            num_pages=12,
        )
        rng = np.random.default_rng(2)
        prompt = rng.integers(1, 64, 8).astype(np.int32)
        eng.submit(Request(prompt, max_new=4))
        eng.admit_ready()
        eng.step()
        in_use = eng.stats.pages_in_use
        assert in_use > 0
        eng.preempt(0)
        assert eng.stats.pages_in_use < in_use
        done = eng.run()
        assert len(done) == 1
        assert eng.stats.pages_in_use == 0


@pytest.mark.slow
class TestServingLoadSweepHeavy:
    """The heavy shapes (satellite: marked slow, outside tier-1): a
    full multi-rate sweep through the runner to an actual saturation
    knee, paged + bursty + shared-prefix member included."""

    def test_load_sweep_to_saturation_knee(self, tmp_path):
        import serving_load_report as rep
        from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

        common = {
            "implementation": "engine", "batch": 8, "vocab": 128,
            "n_heads": 8, "n_requests": 32, "out_mean": 4, "out_max": 8,
            "slo_ttft_ms": 100.0, "slo_tpot_ms": 40.0,
        }
        impls = {
            f"engine_{i}": {**common, "rate": rate}
            for i, rate in enumerate((10.0, 40.0, 1200.0))
        }
        impls["engine_paged"] = {
            **common, "rate": 40.0, "cache_layout": "paged",
            "page_size": 16, "page_pool_frac": 0.5,
            "prefix_pop": 4, "prefix_len": 16,
        }
        csv_path = tmp_path / "sweep.csv"
        df = PrimitiveBenchmarkRunner(
            "serving_load", m=16, n=64, k=128,
            implementations=impls, dtype="float32",
            num_iterations=2, num_warmups=1, validate=True,
            barrier_at_each_iteration=False, progress=False,
            output_csv=str(csv_path),
        ).run()
        assert (df["error"].astype(str) == "").all()
        assert df["valid"].astype(bool).all()
        paged = df[df["implementation"] == "engine_paged"].iloc[0]
        assert int(paged["serve_prefix_hits"]) > 0
        assert int(paged["serve_peak_pages"]) > 0
        curves = rep.build_curves(
            [r for r in rep.load_rows(str(csv_path))]
        )
        multi = [c for c in curves if len(c["points"]) >= 3]
        assert multi, "rate sweep did not form a curve"
        knee = rep.find_knee(multi[0]["points"], 2.5)
        assert knee["detected"], knee


# ---------------------------------------------------------------------------
# make lint / schema coverage rides the analyzer suite; here we pin the
# one schema property the lint can't see: every slo_* column the driver
# emits is registered
# ---------------------------------------------------------------------------


def test_every_emitted_slo_column_is_registered():
    from ddlb_tpu.schema import ROW_COLUMNS
    from ddlb_tpu.workload import SLOTracker

    tracker = SLOTracker(1.0, 1.0)
    for col in tracker.row_fields(1.0, 1.0):
        assert col in ROW_COLUMNS and ROW_COLUMNS[col], col
