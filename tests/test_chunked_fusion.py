"""Chunked-fusion engine: numerics, schedule pricing, zero wire drift.

The ISSUE 10 acceptance surface for the shared engine
(``ops/chunked_fusion.py``) behind every family's overlap member:

- per-family numerics against the single-device reference across
  ``chunk_count`` in {1, 2, world} on the 8-device CPU sim — chunk
  reassembly order is the risky part, same stance as test_overlap;
- the perfmodel's chunk-granularity fill/drain term
  (``overlap_chunks`` -> ``predicted_s = max + min/chunks``), with
  ``chunk_count=1`` degenerating to the sequential floor;
- the attribution contract: chunk-aware hideable windows, NaN (never
  inf) when the schedule hides nothing at its granularity;
- the DDLB123 zero-drift invariant, via the semantic SPMD tracer:
  chunking must not change the traced per-device wire bytes, only the
  schedule — each chunked member's trace must match its family
  ``wire_bytes()`` closed form exactly.
"""

import math

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 256, 64, 96      # m % (8 * 8) == 0, k % 8 == 0
M_EP = 512                 # ep needs m % (d^2 * chunk_count) at d = 8

WORLD = 8  # the CPU-sim mesh (tests/conftest.py)


def _shape(primitive):
    return (M_EP if primitive == "ep_alltoall" else M, N, K)


@pytest.mark.parametrize("chunk_count", [1, 2, WORLD])
@pytest.mark.parametrize(
    "primitive",
    ["tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall"],
)
def test_chunked_validates(primitive, chunk_count):
    cls = load_impl_class(primitive, "overlap")
    impl = cls(
        *_shape(primitive), dtype="float32",
        algorithm="chunked", chunk_count=chunk_count,
    )
    result = impl.run()
    assert impl.validate(result)


@pytest.mark.parametrize(
    "primitive,shape",
    [
        ("tp_columnwise", (M, N, K)),
        ("tp_rowwise", (M, N, K)),
        # dp's ring quantizes travelling partial sums to bf16 per hop
        # (comm-volume parity); at m=256,k=96 the worst element lands ~1%
        # over the reference atol, so the bf16 spot check pins a shape
        # where the ring convention holds with margin
        ("dp_allreduce", (128, N, K)),
        ("ep_alltoall", (M_EP, N, K)),
    ],
)
def test_chunked_bf16(primitive, shape):
    cls = load_impl_class(primitive, "overlap")
    impl = cls(*shape, dtype="bfloat16", algorithm="chunked", chunk_count=2)
    assert impl.validate(impl.run())


def test_chunked_matches_legacy_pipeline():
    """Same seeded inputs -> the chunked engine and the legacy p2p ring
    agree (both reduce in f32 over an f32 wire at this dtype)."""
    cls = load_impl_class("tp_rowwise", "overlap")
    p2p = cls(M, N, K, dtype="float32", algorithm="p2p_pipeline")
    chunked = cls(M, N, K, dtype="float32", algorithm="chunked", chunk_count=8)
    np.testing.assert_allclose(
        np.asarray(p2p.run()), np.asarray(chunked.run()), atol=1e-4
    )


@pytest.mark.parametrize(
    "primitive",
    ["tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall"],
)
def test_chunked_divisibility(primitive):
    cls = load_impl_class(primitive, "overlap")
    with pytest.raises(ValueError, match="chunk_count"):
        cls(*_shape(primitive), algorithm="chunked", chunk_count=3)


def test_chunk_count_range():
    cls = load_impl_class("tp_columnwise", "overlap")
    with pytest.raises(ValueError, match="outside allowed range"):
        cls(M, N, K, algorithm="chunked", chunk_count=0)


# ---------------------------------------------------------------------------
# perfmodel chunk-granularity term
# ---------------------------------------------------------------------------


def _stub(primitive, m, n, k, **options):
    """Shape-only instance (the test_perfmodel pattern): the cost model
    reads nothing an operand setup provides."""
    cls = load_impl_class(primitive, "overlap")
    impl = object.__new__(cls)
    impl.m, impl.n, impl.k = m, n, k
    impl.dtype = "bfloat16"
    impl.num_partitions = WORLD
    defaults, _ = cls.option_schema()
    impl.options = {**defaults, **options}
    return impl


@pytest.mark.parametrize(
    "primitive",
    ["tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall"],
)
def test_chunked_predicted_follows_schedule_law(primitive):
    """predicted_s = max(comp, comm) + min(comp, comm)/chunks on the
    chunked algorithm; c=1 is the serial floor; legacy algorithms keep
    the ideal max()."""
    from ddlb_tpu.perfmodel.cost import estimate
    from ddlb_tpu.perfmodel.specs import CHIP_SPECS

    spec = CHIP_SPECS["v5e"]
    ideal = estimate(
        _stub(primitive, 512, 512, 512, algorithm="coll_pipeline"), spec
    )
    assert ideal.predicted_s == pytest.approx(
        max(ideal.compute_s, ideal.comm_s)
    )
    for c in (1, 2, 8):
        est = estimate(
            _stub(
                primitive, 512, 512, 512, algorithm="chunked", chunk_count=c
            ),
            spec,
        )
        lo = min(est.compute_s, est.comm_s)
        hi = max(est.compute_s, est.comm_s)
        assert est.predicted_s == pytest.approx(hi + lo / c)
    serial = estimate(
        _stub(primitive, 512, 512, 512, algorithm="chunked", chunk_count=1),
        spec,
    )
    assert serial.predicted_s == pytest.approx(
        serial.compute_s + serial.comm_s
    )


def test_overlap_chunks_hook():
    assert _stub(
        "tp_rowwise", M, N, K, algorithm="chunked", chunk_count=4
    ).overlap_chunks() == 4
    assert _stub(
        "tp_rowwise", M, N, K, algorithm="p2p_pipeline"
    ).overlap_chunks() is None


# ---------------------------------------------------------------------------
# attribution: chunk-aware floors, NaN (never inf) clamp
# ---------------------------------------------------------------------------


class _Est:
    def __init__(self, compute, comm, hbm=0.0):
        self.compute_s, self.comm_s, self.hbm_s = compute, comm, hbm


def test_attribute_chunked_floor():
    """chunks tilts t_overlap to the member's own schedule: comp=2,
    comm=1, chunks=2 -> floor 2.5, hideable 0.5."""
    from ddlb_tpu.observatory import attribution

    out = attribution.attribute(_Est(2.0, 1.0), "overlap", 2.75, chunks=2)
    # t_serial=3, chunked floor=2.5: measured 2.75 hides half the window
    assert out["measured_overlap_frac"] == pytest.approx(0.5)
    assert out["phase_idle_s"] == pytest.approx(0.25)


def test_attribute_no_hideable_window_is_nan_not_inf():
    """chunks=1: t_serial == t_overlap — the divide-by-~0 row the ISSUE
    10 satellite clamps to the schema-documented NaN."""
    from ddlb_tpu.observatory import attribution

    out = attribution.attribute(_Est(2.0, 1.0), "overlap", 2.9, chunks=1)
    assert math.isnan(out["measured_overlap_frac"])
    assert not math.isinf(out["measured_overlap_frac"])
    # float-noise windows clamp identically (the old `> 0.0` guard let
    # a denormal window through and emitted junk fractions)
    tiny = attribution.attribute(_Est(2.0, 2e-15), "overlap", 1.0)
    assert math.isnan(tiny["measured_overlap_frac"])


def test_attribute_unchunked_behavior_unchanged():
    from ddlb_tpu.observatory import attribution

    out = attribution.attribute(_Est(2.0, 1.0), "overlap", 2.2)
    assert out["measured_overlap_frac"] == pytest.approx(0.8)


# ---------------------------------------------------------------------------
# DDLB123 zero drift: chunking changes the schedule, never the wire
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "primitive",
    ["tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall"],
)
def test_chunked_wire_matches_formula(primitive):
    """The semantic SPMD tracer drives the chunked member under the
    canonical shapes and must size EXACTLY the family's ``wire_bytes()``
    closed form — the statically-enforced half of the acceptance
    criterion (the analyzer's DDLB123 runs the same comparison over the
    whole member matrix)."""
    from ddlb_tpu.analysis.core import repo_root
    from ddlb_tpu.analysis.spmd.families import ClassRegistry, trace_member

    registry = ClassRegistry(repo_root())
    for chunk_count in (1, 2):
        report = trace_member(
            primitive, "overlap",
            {"algorithm": "chunked", "chunk_count": chunk_count},
            registry,
        )
        assert report.status == "verified", (
            f"{report.label()}: {report.status} ({report.reason})"
        )
        assert report.wire_traced == pytest.approx(report.wire_formula)


def test_pallas_path_pins_ring_granularity():
    """The VMEM-resident pallas path only speaks one chunk per RDMA
    step; any other granularity must refuse loudly."""
    from ddlb_tpu.ops import chunked_fusion

    with pytest.raises(ValueError, match="pins chunk_count"):
        chunked_fusion.build_chunked_ag_matmul(
            m=256, n=64, k=64, d=8, chunk_count=2, path="pallas"
        )
    step = chunked_fusion.build_chunked_ag_matmul(
        m=256, n=64, k=64, d=8, chunk_count=8, path="pallas"
    )
    assert callable(step)


def test_telemetry_names_registered():
    """The engine's plan spans are declared in the registry (DDLB106)."""
    from ddlb_tpu.telemetry.names import SPAN_NAMES

    assert "overlap.chunk" in SPAN_NAMES
    assert "overlap.ring_step" in SPAN_NAMES
