"""Prior-guided autotuner (ISSUE 20): knob spaces, priors, driver, table.

The search loop is exercised with INJECTED synthetic landscapes
(``driver.search(measure=...)``) so every contract — feasibility,
prune-keeps-the-winner, early-stop, banked-trial determinism — is
checked without a single real compile; the consult path is exercised on
a real overlap member against the 8-device CPU sim. The end-to-end
measured run lives in ``scripts/tune_demo.py``.
"""

import json
import os

import pytest

from ddlb_tpu.tuner import driver, priors
from ddlb_tpu.tuner import table as tables
from ddlb_tpu.tuner.space import (
    KNOB_FREE,
    SPACES,
    SearchSpec,
    chunk_feasible,
    default_knobs,
    propose,
    tile_feasible,
)
from ddlb_tpu.tuner.table import TuneEntry, canonical_knobs


def chunk_spec(m=256, n=64, k=64, d=8, **kw):
    return SearchSpec(
        family="dp_allreduce", impl="overlap", m=m, n=n, k=k,
        num_partitions=d, chip="cpu-sim",
        base_options=(("algorithm", "chunked"),), **kw,
    )


def landscape(medians):
    """A synthetic measure fn: chunk_count -> median ms."""
    def measure(config):
        chunk = config["options"]["chunk_count"]
        return {driver.MEASURE_COLUMN: medians[chunk], "error": ""}
    return measure


def entry_for(spec, knobs, measured_ms=1.0, prior_rank=1):
    return TuneEntry(
        family=spec.family, impl=spec.impl, m=spec.m, n=spec.n,
        k=spec.k, dtype=spec.dtype, world_size=spec.num_partitions,
        knobs=dict(knobs), measured_ms=measured_ms, prior_s=1e-4,
        prior_rank=prior_rank, trials=3, pruned=2, candidates=5,
    )


# -- space: static feasibility ----------------------------------------------


def test_tile_feasibility_rules():
    spec = SearchSpec("tp_columnwise", "pallas", 1024, 1024, 512,
                      num_partitions=2)
    ok, _ = tile_feasible(spec, 512, 512, 256)
    assert ok
    ok, why = tile_feasible(spec, 100, 128, 128)
    assert not ok and "divisibility" in why
    ok, why = tile_feasible(spec, 4, 128, 128)
    assert not ok and "granule" in why
    # the double-buffered working set of a huge tile blows the
    # conservative 16 MiB census budget at f32
    big = SearchSpec("tp_columnwise", "pallas", 2048, 2048, 2048,
                     num_partitions=2)
    ok, why = tile_feasible(big, 2048, 2048, 2048)
    assert not ok and "vmem" in why


def test_tile_space_only_proposes_buildable_points():
    spec = SearchSpec("tp_columnwise", "pallas", 2048, 2048, 2048,
                      num_partitions=2)
    space = propose(spec)
    assert space.candidates and space.rejected
    for knobs in space.candidates:
        ok, why = tile_feasible(
            spec, knobs["block_m"], knobs["block_n"], knobs["block_k"]
        )
        assert ok, why
    assert any("vmem" in why for _knobs, why in space.rejected)


def test_chunk_space_divisibility():
    spec = chunk_spec(m=48)
    space = propose(spec)
    assert [c["chunk_count"] for c in space.candidates] == [1, 2]
    assert all("divisibility" in why for _k, why in space.rejected)
    assert chunk_feasible(spec, 4) == (False, space.rejected[0][1])


def test_propose_unknown_target_raises():
    with pytest.raises(ValueError, match="no knob space"):
        propose(SearchSpec("dp_allreduce", "nope", 64, 64, 64))


def test_default_knobs_are_feasible_candidates():
    for spec in (
        chunk_spec(),
        SearchSpec("tp_columnwise", "pallas", 1024, 1024, 512,
                   num_partitions=2),
        SearchSpec("dp_allreduce", "jax_spmd_hier", 256, 64, 64,
                   num_partitions=8),
        SearchSpec("dp_allreduce", "xla_gspmd", 256, 64, 64,
                   num_partitions=8),
    ):
        default = default_knobs(spec)
        keys = {canonical_knobs(c) for c in propose(spec).candidates}
        assert canonical_knobs(default) in keys


def test_every_family_has_a_tuning_story():
    # the DDLB140 invariant, stated here as well so a coverage break
    # fails the fast tier too, not only `make analyze`
    from ddlb_tpu.primitives.registry import ALLOWED_PRIMITIVES

    declared = {family for family, _impl in SPACES}
    for family in ALLOWED_PRIMITIVES:
        assert family in declared or family in KNOB_FREE
        assert not (family in declared and family in KNOB_FREE)


# -- priors: pruning and rank agreement -------------------------------------


def test_prune_margin_and_keep():
    scored = [
        priors.ScoredCandidate({"chunk_count": c}, s, "analytic")
        for c, s in ((1, 2.0), (2, 1.0), (4, 1.2))
    ]
    survivors, pruned = priors.prune(scored, margin=1.5)
    assert [s.knobs["chunk_count"] for s in survivors] == [2, 4]
    assert [s.prior_rank for s in survivors] == [1, 2]
    assert [p.knobs["chunk_count"] for p in pruned] == [1]
    # keep= (the registered default) bypasses the margin
    survivors, pruned = priors.prune(
        scored, margin=1.5, keep={"chunk_count": 1}
    )
    assert [s.knobs["chunk_count"] for s in survivors] == [2, 4, 1]
    assert pruned == []


def test_prune_keeps_the_true_winner_under_a_decent_prior():
    # a synthetic landscape where the prior's ORDER is right but its
    # magnitudes are off 30%: the winner must survive a 1.5x margin
    truth = {1: 4.0, 2: 2.0, 4: 1.0, 8: 1.5, 16: 3.5}
    scored = [
        priors.ScoredCandidate({"chunk_count": c}, truth[c] * 1.3, "analytic")
        for c in truth
    ]
    survivors, _pruned = priors.prune(scored, margin=1.5)
    assert {"chunk_count": 4} in [s.knobs for s in survivors]
    assert survivors[0].knobs == {"chunk_count": 4}


def test_spearman():
    assert priors.spearman([1, 2, 3], [10, 20, 30]) == pytest.approx(1.0)
    assert priors.spearman([1, 2, 3], [30, 20, 10]) == pytest.approx(-1.0)
    assert priors.spearman([1.0, 1.0], [1.0, 2.0]) != priors.spearman(
        [1.0, 1.0], [1.0, 2.0]
    )  # constant side -> NaN
    assert priors.spearman([1.0], [1.0]) != priors.spearman([1.0], [1.0])


def test_priors_differentiate_chunk_depth_and_composition():
    chip = priors.chip_spec_for(chunk_spec())
    deep = priors.score(chunk_spec(), {"chunk_count": 16}, chip)
    shallow = priors.score(chunk_spec(), {"chunk_count": 1}, chip)
    assert deep.prior_s < shallow.prior_s  # pipelining hides wire time
    # all_to_all is where the two-level factorization moves real bytes
    # (a psum's hierarchical total equals its flat total by algebra)
    spec = SearchSpec("ep_alltoall", "jax_spmd_hier", 256, 64, 64,
                      num_partitions=8, num_slices=2, chip="cpu-sim")
    flat = priors.score(spec, {"composition": "flat"}, chip)
    hier = priors.score(spec, {"composition": "hierarchical"}, chip)
    assert flat.prior_s != hier.prior_s


# -- driver: search on synthetic landscapes ---------------------------------


def test_search_measures_default_first_and_finds_the_winner():
    truth = {1: 2.0, 2: 1.5, 4: 1.0, 8: 1.2, 16: 3.0}
    result = driver.search(
        chunk_spec(), measure=landscape(truth), prior_margin=100.0,
        force=True,
    )
    assert result.trials[0].knobs == {"chunk_count": 2}  # the default
    assert result.default_ms == pytest.approx(1.5)
    assert result.entry is not None
    assert result.entry.knobs == {"chunk_count": 4}
    assert result.entry.measured_ms <= result.default_ms
    assert result.candidates == 5 and not result.early_stopped
    assert -1.0 <= result.spearman() <= 1.0


def test_search_early_stops_at_patience():
    # default wins outright: every later probe is stale
    truth = {c: (1.0 if c == 2 else 2.0 + c) for c in (1, 2, 4, 8, 16)}
    result = driver.search(
        chunk_spec(), measure=landscape(truth), prior_margin=100.0,
        patience=2, force=True,
    )
    assert result.early_stopped
    assert len(result.trials) == 3  # default + `patience` stale probes
    assert result.entry.knobs == {"chunk_count": 2}


def test_search_survives_a_crashing_trial():
    def measure(config):
        if config["options"]["chunk_count"] == 16:
            raise RuntimeError("boom")
        return {driver.MEASURE_COLUMN: config["options"]["chunk_count"],
                "error": ""}

    result = driver.search(
        chunk_spec(), measure=measure, prior_margin=100.0, patience=10,
        force=True,
    )
    errored = [t for t in result.trials if t.error]
    assert errored and errored[0].median_ms != errored[0].median_ms
    assert result.entry.knobs == {"chunk_count": 1}


def test_trial_config_contract():
    config = driver.trial_config(chunk_spec(), {"chunk_count": 4})
    assert config["impl_id"] == "tune:dp_allreduce/overlap"
    assert config["base_implementation"] == "overlap"
    assert config["options"] == {"algorithm": "chunked", "chunk_count": 4}
    assert config["validate"] is False


def test_search_banks_trials_and_rerun_is_deterministic(tmp_path):
    from ddlb_tpu.observatory import store

    history = str(tmp_path / "hist")
    truth = {1: 2.0, 2: 1.5, 4: 1.0, 8: 1.2, 16: 3.0}
    first = driver.search(
        chunk_spec(), measure=landscape(truth), prior_margin=100.0,
        history_dir=history, force=True,
    )
    records = list(store.iter_history(history, kind="tune"))
    assert len(records) == len(first.trials)
    for record in records:
        assert record["kind"] == "tune"
        row = record["row"]
        assert row["tune_key"] == first.entry.key()
        assert json.loads(row["tune_candidate"])  # a knob dict
        assert row["prior_rank"] >= 1

    def exploded(_config):
        raise AssertionError("banked trials must be reused, not re-run")

    second = driver.search(
        chunk_spec(), measure=exploded, prior_margin=100.0,
        history_dir=history, force=True,
    )
    assert all(t.from_bank for t in second.trials)
    assert second.entry == first.entry

    path_a, path_b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    assert driver.bank_winners(
        [first], path_a, chip="cpu-sim", backend="host_clock"
    ) is not None
    driver.bank_winners(
        [second], path_b, chip="cpu-sim", backend="host_clock"
    )
    with open(path_a, "rb") as fa, open(path_b, "rb") as fb:
        assert fa.read() == fb.read()  # byte-identical table


def test_bank_winners_merges_and_skips_empty(tmp_path):
    path = str(tmp_path / "table.json")
    assert driver.bank_winners([], path) is None
    spec_a, spec_b = chunk_spec(), chunk_spec(m=512)
    result_a = driver.SearchResult(
        spec=spec_a, entry=entry_for(spec_a, {"chunk_count": 4})
    )
    table = driver.bank_winners([result_a], path, chip="cpu-sim")
    assert table is not None and len(table.entries) == 1
    result_b = driver.SearchResult(
        spec=spec_b, entry=entry_for(spec_b, {"chunk_count": 8})
    )
    merged = driver.bank_winners([result_b], path, chip="cpu-sim")
    assert len(merged.entries) == 2  # the earlier winner survived
    assert merged.version != table.version


# -- table: round-trip, fingerprint, env gating, invalidation ----------------


def test_table_roundtrip_and_fingerprint(tmp_path):
    spec = chunk_spec()
    entry = entry_for(spec, {"chunk_count": 4})
    table = tables.make_table(
        {entry.key(): entry}, chip="cpu-sim", backend="host_clock",
        git_rev="abc123",
    )
    path = str(tmp_path / "table.json")
    tables.save_table(table, path)
    loaded = tables.load_table(path)
    assert loaded is not None and loaded.to_json() == table.to_json()
    # the fingerprint is content-only: same winners -> same version,
    # a moved winner -> a new version (what the regression fence keys)
    assert tables.table_version({entry.key(): entry}) == table.version
    moved = entry_for(spec, {"chunk_count": 8})
    assert tables.table_version({moved.key(): moved}) != table.version


def test_load_table_tolerates_corruption(tmp_path):
    path = str(tmp_path / "broken.json")
    with open(path, "w") as handle:
        handle.write("{not json")
    assert tables.load_table(path) is None
    with open(path, "w") as handle:
        json.dump({"entries": "nope"}, handle)
    assert tables.load_table(path) is None


def test_get_table_env_gating(tmp_path, monkeypatch):
    monkeypatch.delenv("DDLB_TPU_TUNING", raising=False)
    assert tables.get_table() is None
    path = str(tmp_path / "table.json")
    monkeypatch.setenv("DDLB_TPU_TUNING", path)
    assert tables.get_table() is None  # not written yet: a quiet miss
    spec = chunk_spec()
    entry = entry_for(spec, {"chunk_count": 4})
    tables.save_table(tables.make_table({entry.key(): entry}), path)
    loaded = tables.get_table()
    assert loaded is not None and entry.key() in loaded.entries
    # a re-banked table (new mtime) invalidates the (path, mtime) cache
    other = entry_for(spec, {"chunk_count": 8})
    tables.save_table(tables.make_table({other.key(): other}), path)
    bumped = os.stat(path).st_mtime + 2
    os.utime(path, (bumped, bumped))
    reloaded = tables.get_table()
    assert reloaded.version != loaded.version


def test_lookup_chip_scope_and_degraded_invalidation(monkeypatch):
    spec = SearchSpec("dp_allreduce", "jax_spmd_hier", 256, 64, 64,
                      num_partitions=8, chip="cpu-sim")
    comp = entry_for(spec, {"composition": "flat"})
    table = tables.make_table({comp.key(): comp}, chip="cpu-sim")
    args = (spec.family, spec.impl, spec.m, spec.n, spec.k, spec.dtype,
            spec.num_partitions)
    assert table.lookup(*args, chip="tpu-v5e") is None  # cross-chip
    assert table.lookup(*args, chip="cpu-sim", degraded=False) is comp
    # a composition winner is invalidated while the world is degraded
    assert table.lookup(*args, degraded=True) is None
    monkeypatch.setenv("DDLB_TPU_WORLD_DEGRADED", "rank3")
    assert table.lookup(*args) is None  # degraded=None consults the signal
    monkeypatch.delenv("DDLB_TPU_WORLD_DEGRADED")
    assert table.lookup(*args) is comp
    # non-composition winners ignore the signal entirely
    chunked = entry_for(chunk_spec(), {"chunk_count": 4})
    chunk_table = tables.make_table({chunked.key(): chunked})
    assert chunk_table.lookup(
        "dp_allreduce", "overlap", 256, 64, 64, "float32", 8,
        degraded=True,
    ) is chunked


def test_search_short_circuits_on_a_table_hit(tmp_path, monkeypatch):
    spec = chunk_spec()
    entry = entry_for(spec, {"chunk_count": 4})
    path = str(tmp_path / "table.json")
    tables.save_table(tables.make_table({entry.key(): entry}), path)
    monkeypatch.setenv("DDLB_TPU_TUNING", path)

    def exploded(_config):
        raise AssertionError("a table hit must not measure")

    hit = driver.search(spec, measure=exploded)
    assert hit.table_hit and not hit.trials and hit.entry == entry
    # force=True re-searches through the hit
    forced = driver.search(
        spec, measure=landscape({c: float(c) for c in (1, 2, 4, 8, 16)}),
        prior_margin=100.0, force=True,
    )
    assert not forced.table_hit and forced.trials


# -- consult: members apply the banked winner by default ---------------------


def test_member_consults_table_by_default(tmp_path, monkeypatch):
    from ddlb_tpu.primitives.registry import load_impl_class

    cls = load_impl_class("dp_allreduce", "overlap")
    spec = SearchSpec("dp_allreduce", "overlap", 256, 64, 96,
                      num_partitions=8)
    entry = entry_for(spec, {"chunk_count": 4})
    path = str(tmp_path / "table.json")
    table = tables.make_table({entry.key(): entry})
    tables.save_table(table, path)

    monkeypatch.delenv("DDLB_TPU_TUNING", raising=False)
    untuned = cls(256, 64, 96, dtype="float32", algorithm="chunked")
    assert untuned.options["chunk_count"] == 2  # registered default
    assert untuned.tuning_stamp is None

    monkeypatch.setenv("DDLB_TPU_TUNING", path)
    tuned = cls(256, 64, 96, dtype="float32", algorithm="chunked")
    assert tuned.options["chunk_count"] == 4  # the banked winner
    assert tuned.tuning_stamp == {
        "tuned": True, "tuning_version": table.version, "prior_rank": 1,
    }
    assert tuned.validate(tuned.run())

    # an explicitly passed knob always beats the table
    pinned = cls(256, 64, 96, dtype="float32", algorithm="chunked",
                 chunk_count=8)
    assert pinned.options["chunk_count"] == 8
    assert pinned.tuning_stamp is None

    # a miss (unknown shape) stays on the registered defaults
    miss = cls(512, 64, 96, dtype="float32", algorithm="chunked")
    assert miss.options["chunk_count"] == 2
    assert miss.tuning_stamp is None
