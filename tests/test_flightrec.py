"""Collective flight recorder: crash-safe sequencing + attribution.

What matters: begin lines land on disk BEFORE the recorded body runs (a
SIGKILLed rank still shows the collective it entered), the per-rank
sequence join names the lagging rank and the divergence site, dumps
fire on SIGTERM, and the file-beat extension of the heartbeat channel
is atomic, throttled, and readable by a non-forking supervisor.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from ddlb_tpu.faults import flightrec, heartbeat


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Recorder and beat-file state reset around every test; the
    SIGTERM/SIGUSR1 dispositions a configure() may have installed are
    restored so later tests see the defaults."""
    monkeypatch.delenv("DDLB_TPU_FLIGHTREC", raising=False)
    monkeypatch.delenv("DDLB_TPU_BEAT_FILE", raising=False)
    flightrec.reset()
    heartbeat.reset_file()
    yield
    flightrec.reset()
    heartbeat.reset_file()
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGUSR1, signal.SIG_DFL)


def _read_lines(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _write_rank_file(run_dir, rank, lines):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, f"flight-p{rank}.jsonl"), "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")


def _entry(seq, ph, site, pid=100, **kw):
    return {"seq": seq, "ph": ph, "site": site, "t": float(seq),
            "pid": pid, **kw}


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------


def test_disabled_is_noop(tmp_path):
    assert not flightrec.enabled()
    with flightrec.record("runtime.barrier"):
        flightrec.mark("worker.phase", stage="x")
    flightrec.dump("nothing")  # no crash, no files anywhere


def test_record_emits_begin_before_body_and_end_after(
    tmp_path, monkeypatch
):
    """The crash-safety contract: the B line is flushed before the body
    runs, so a rank that dies inside still shows where."""
    monkeypatch.setenv("DDLB_TPU_FLIGHTREC", str(tmp_path))
    monkeypatch.setenv("DDLB_TPU_PROCESS_ID", "3")
    flightrec.reset()
    path = tmp_path / "flight-p3.jsonl"
    with flightrec.record(
        "runtime.barrier", axes="_barrier", payload_bytes=32
    ):
        mid = _read_lines(path)
        assert [e["ph"] for e in mid] == ["B"]
        assert mid[0]["site"] == "runtime.barrier"
        assert mid[0]["axes"] == "_barrier"
        assert mid[0]["bytes"] == 32
        assert mid[0]["rank"] == 3
    done = _read_lines(path)
    assert [e["ph"] for e in done] == ["B", "E"]
    assert done[1]["seq"] == done[0]["seq"]
    assert done[1]["t"] >= done[0]["t"]


def test_end_line_lands_even_when_body_raises(tmp_path, monkeypatch):
    """A collective that ERRORS (vs wedges) completes its entry — the
    attribution join must not mistake a crashed-through rank for a
    stuck one."""
    monkeypatch.setenv("DDLB_TPU_FLIGHTREC", str(tmp_path))
    flightrec.reset()
    with pytest.raises(RuntimeError):
        with flightrec.record("runtime.collective"):
            raise RuntimeError("peer closed")
    lines = _read_lines(tmp_path / "flight-p0.jsonl")
    assert [e["ph"] for e in lines] == ["B", "E"]


def test_marks_and_sequence_are_monotonic(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_FLIGHTREC", str(tmp_path))
    flightrec.reset()
    flightrec.mark("worker.phase", stage="setup")
    with flightrec.record("runtime.mesh_build"):
        pass
    flightrec.mark("pool.row", impl="jax_spmd_0")
    lines = _read_lines(tmp_path / "flight-p0.jsonl")
    seqs = [e["seq"] for e in lines if e["ph"] in ("B", "I")]
    assert seqs == [1, 2, 3]
    assert lines[0]["stage"] == "setup"


def test_dump_appends_reason_and_inflight(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_FLIGHTREC", str(tmp_path))
    flightrec.reset()
    with flightrec.record("runtime.barrier"):
        flightrec.dump("deadline")
    lines = _read_lines(tmp_path / "flight-p0.jsonl")
    dump = [e for e in lines if e["ph"] == "D"][0]
    assert dump["reason"] == "deadline"
    assert dump["inflight"] == [{"seq": 1, "site": "runtime.barrier"}]


def test_sigterm_dumps_then_dies_by_signal(tmp_path):
    """A real child: SIGTERM triggers the dump handler, then the child
    still dies BY the signal (exit status preserved for the
    supervisor's signal-name mapping)."""
    child = textwrap.dedent(
        """
        import time
        from ddlb_tpu.faults import flightrec
        with flightrec.record("runtime.barrier"):
            print("READY", flush=True)
            time.sleep(60)
        """
    )
    env = dict(
        os.environ,
        DDLB_TPU_FLIGHTREC=str(tmp_path),
        DDLB_TPU_PROCESS_ID="1",
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", child], env=env,
        stdout=subprocess.PIPE, text=True,
    )
    assert proc.stdout.readline().strip() == "READY"
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGTERM
    lines = _read_lines(tmp_path / "flight-p1.jsonl")
    phases = [e["ph"] for e in lines]
    assert "D" in phases
    dump = [e for e in lines if e["ph"] == "D"][0]
    assert dump["reason"] == "SIGTERM"
    assert dump["inflight"][0]["site"] == "runtime.barrier"
    assert "E" not in phases  # it genuinely died inside the entry


# ---------------------------------------------------------------------------
# Attribution (analyze_run / scripts/flight_report.py)
# ---------------------------------------------------------------------------


def test_analyze_names_lagging_rank_and_stuck_site(tmp_path):
    """Rank 1 never arrived at the barrier its peer is wedged in."""
    run = str(tmp_path)
    _write_rank_file(run, 0, [
        _entry(1, "I", "worker.phase"),
        _entry(2, "B", "runtime.barrier"),  # begun, never ended
    ])
    _write_rank_file(run, 1, [
        _entry(1, "I", "worker.phase"),
    ])
    report = flightrec.analyze_run(run, expected_ranks=2)
    assert report["common_seq"] == 1
    assert report["lagging_ranks"] == [1]
    assert report["divergence_site"] == "runtime.barrier"
    assert "rank 1 lagging" in report["headline"]


def test_analyze_divergence_from_completed_entries(tmp_path):
    """When nobody is stuck (peers ERROR through a dead-peer
    collective), the divergence is the first entry the ahead rank ran
    past the common seq."""
    run = str(tmp_path)
    _write_rank_file(run, 0, [
        _entry(1, "I", "worker.phase"),
        _entry(2, "B", "runtime.collective"),
        _entry(2, "E", "runtime.collective"),
        _entry(3, "I", "worker.phase"),
    ])
    _write_rank_file(run, 1, [
        _entry(1, "I", "worker.phase"),
    ])
    report = flightrec.analyze_run(run)
    assert report["lagging_ranks"] == [1]
    assert report["divergence_site"] == "runtime.collective"


def test_analyze_all_ranks_stuck_in_same_collective(tmp_path):
    """Equal sequences, everyone in flight: the collective itself
    wedged — no lagging rank to blame, and the report says so."""
    run = str(tmp_path)
    for rank in (0, 1):
        _write_rank_file(run, rank, [
            _entry(1, "B", "runtime.barrier", pid=100 + rank),
        ])
    report = flightrec.analyze_run(run)
    assert report["lagging_ranks"] == []
    assert report["divergence_site"] == "runtime.barrier"
    assert "collective itself wedged" in report["headline"]


def test_analyze_missing_rank_file(tmp_path):
    run = str(tmp_path)
    _write_rank_file(run, 0, [_entry(1, "B", "runtime.barrier")])
    report = flightrec.analyze_run(run, expected_ranks=2)
    assert report["missing_ranks"] == [1]
    assert "no flight file" in report["headline"]


def test_analyze_clean_world_and_torn_tail(tmp_path):
    run = str(tmp_path)
    _write_rank_file(run, 0, [
        _entry(1, "B", "runtime.barrier"),
        _entry(1, "E", "runtime.barrier"),
    ])
    # a torn final line (killed mid-append) must be skipped, not fatal
    with open(os.path.join(run, "flight-p0.jsonl"), "a") as f:
        f.write('{"seq": 2, "ph": "B", "si')
    report = flightrec.analyze_run(run)
    assert report["lagging_ranks"] == []
    assert "no divergence" in report["headline"]


def test_analyze_uses_dominant_pid_stream(tmp_path):
    """A rank file shared by the runner and a pool child: the busier
    stream (the rank's main process) defines the rank's progress."""
    run = str(tmp_path)
    _write_rank_file(run, 0, [
        _entry(1, "I", "pool.row", pid=50),
        _entry(1, "I", "worker.phase", pid=60),
        _entry(2, "I", "worker.phase", pid=60),
        _entry(3, "B", "runtime.barrier", pid=60),
    ])
    report = flightrec.analyze_run(run)
    assert report["ranks"][0]["pid"] == 60
    assert report["ranks"][0]["last_completed_seq"] == 2


def test_flight_report_cli_json_and_exit_codes(tmp_path):
    run = str(tmp_path)
    _write_rank_file(run, 0, [
        _entry(1, "B", "runtime.barrier"),
        _entry(1, "E", "runtime.barrier"),
    ])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    clean = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "flight_report.py"),
         run, "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    doc = json.loads(clean.stdout)
    assert doc["lagging_ranks"] == []
    diverged = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "flight_report.py"),
         run, "--ranks", "2"],
        capture_output=True, text=True, timeout=60,
    )
    assert diverged.returncode == 1  # rank 1 left no file
    assert "no flight file" in diverged.stdout


# ---------------------------------------------------------------------------
# File beats (the heartbeat extension the supervisor reads)
# ---------------------------------------------------------------------------


def test_file_beat_written_and_read(tmp_path, monkeypatch):
    path = str(tmp_path / "beat-p0")
    monkeypatch.setenv("DDLB_TPU_BEAT_FILE", path)
    heartbeat.reset_file()
    before = time.monotonic()
    heartbeat.beat()
    stamp = heartbeat.read_file_beat(path)
    assert before <= stamp <= time.monotonic()


def test_file_beat_throttled(tmp_path, monkeypatch):
    path = str(tmp_path / "beat-p0")
    monkeypatch.setenv("DDLB_TPU_BEAT_FILE", path)
    heartbeat.reset_file()
    heartbeat.beat()
    first = heartbeat.read_file_beat(path)
    heartbeat.beat()  # within FILE_BEAT_INTERVAL_S: no second write
    assert heartbeat.read_file_beat(path) == first
    time.sleep(heartbeat.FILE_BEAT_INTERVAL_S * 1.5)
    heartbeat.beat()
    assert heartbeat.read_file_beat(path) > first


def test_file_beat_unreadable_is_zero(tmp_path):
    assert heartbeat.read_file_beat(str(tmp_path / "missing")) == 0.0
    torn = tmp_path / "torn"
    torn.write_text("12.5garbage")
    assert heartbeat.read_file_beat(str(torn)) == 0.0


def test_no_beat_file_env_is_noop(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    heartbeat.reset_file()
    heartbeat.beat()  # no env: must not create any file
    assert os.listdir(tmp_path) == []
