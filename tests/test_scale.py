"""Multi-device correctness past the single-digit regime (VERDICT r3 #7).

The main suite runs on a fixed 8-device CPU sim (conftest.py); the torus
the framework targets ships with 16/32/64-chip slices. These tests spawn
fresh processes with larger virtual worlds and pin:

- the RDMA ring kernels (ag / rs) and the fused all-to-all expert GEMM
  under the distributed interpreter at d=16 (race detector ON) and d=32;
- the driver's multi-chip dry run (full train + serving step) at 16 and
  32 devices;
- the ring AG+GEMM protocol across a REAL 2-process boundary on the dcn
  transport layout (2 x 8 devices, every ring hop crossing a process).
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD_KERNELS = r"""
import os
import numpy as np

d = int(os.environ["DDLB_SCALE_D"])
detect = bool(int(os.environ.get("DDLB_SCALE_RACES", "0")))
from ddlb_tpu.runtime import enable_simulation
enable_simulation(d)

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.ops.alltoall_matmul import alltoall_expert_matmul
from ddlb_tpu.ops.collective_matmul import ring_ag_matmul, ring_matmul_rs

mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
params = pltpu.InterpretParams(detect_races=detect)
rng = np.random.default_rng(d)

# ring all-gather + GEMM
m, n, k = 8 * d, 32, 32
a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
f = jax.jit(jax.shard_map(
    lambda a_s, b_r: ring_ag_matmul(
        a_s, b_r, axis_size=d, block_n=32, block_k=32, interpret=params),
    mesh=mesh, in_specs=(P("tp", None), P(None, None)),
    out_specs=P(None, None), check_vma=False))
out = np.asarray(f(
    jax.device_put(a, NamedSharding(mesh, P("tp", None))),
    jax.device_put(b, NamedSharding(mesh, P(None, None)))))
np.testing.assert_allclose(out, a @ b, rtol=0, atol=1e-4)
print("AG_OK", d, flush=True)

# GEMM + ring reduce-scatter
m, n, k = 8 * d, 32, 16 * d
a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
f = jax.jit(jax.shard_map(
    lambda a_s, b_s: ring_matmul_rs(
        a_s, b_s, axis_size=d, block_n=16, block_k=16, interpret=params),
    mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
    out_specs=P("tp", None), check_vma=False))
out = np.asarray(f(
    jax.device_put(a, NamedSharding(mesh, P(None, "tp"))),
    jax.device_put(b, NamedSharding(mesh, P("tp", None)))))
np.testing.assert_allclose(out, a @ b, rtol=0, atol=1e-4)
print("RS_OK", d, flush=True)

# fused all-to-all expert GEMM
m, n, k = 4 * d * d, 32, 32
g = m // (d * d)
a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
w = rng.uniform(-1, 1, (d, k, n)).astype(np.float32)
f = jax.jit(jax.shard_map(
    lambda a_s, w_s: alltoall_expert_matmul(
        a_s, w_s[0], axis_size=d, block_n=32, block_k=32, interpret=params),
    mesh=mesh, in_specs=(P("tp", None), P("tp", None, None)),
    out_specs=P("tp", None), check_vma=False))
out = np.asarray(f(
    jax.device_put(a, NamedSharding(mesh, P("tp", None))),
    jax.device_put(w, NamedSharding(mesh, P("tp", None, None)))))
want = np.einsum("pegk,ekn->pegn", a.reshape(d, d, g, k), w).reshape(m, n)
np.testing.assert_allclose(out, want, rtol=0, atol=1e-4)
print("A2A_OK", d, flush=True)

# pure ring collectives (ops/ring_collectives.py): shard sizes stay
# inside the interpreter envelope noted in the module docstring. d<=16
# only: with NO compute between send and wait, 32 interpreter threads
# livelock even on 4 KB hops (the fused kernels above survive d=32
# because their GEMM sits in that window) — the d=16 run carries the
# race detector, which is the stronger pin anyway.
if d > 16:
    print("PURE_AG_SKIPPED", d, flush=True)
    print("PURE_RS_SKIPPED", d, flush=True)
    raise SystemExit(0)

from ddlb_tpu.ops.ring_collectives import ring_all_gather, ring_reduce_scatter

m, k = 8 * d, 128
x = rng.uniform(-1, 1, (m, k)).astype(np.float32)
xs = jax.device_put(x, NamedSharding(mesh, P("tp", None)))
f = jax.jit(jax.shard_map(
    lambda a_s: ring_all_gather(a_s, axis_size=d, interpret=params),
    mesh=mesh, in_specs=(P("tp", None),), out_specs=P(None, None),
    check_vma=False))
np.testing.assert_array_equal(np.asarray(f(xs)), x)
print("PURE_AG_OK", d, flush=True)

m = d * d * 2
x = rng.uniform(-1, 1, (m, k)).astype(np.float32)
xs = jax.device_put(x, NamedSharding(mesh, P("tp", None)))
f = jax.jit(jax.shard_map(
    lambda a_s: ring_reduce_scatter(a_s, axis_size=d, interpret=params),
    mesh=mesh, in_specs=(P("tp", None),), out_specs=P("tp", None),
    check_vma=False))
want = x.reshape(d, d, 2, k).sum(axis=0).reshape(m // d, k)
np.testing.assert_allclose(np.asarray(f(xs)), want, rtol=0, atol=1e-4)
print("PURE_RS_OK", d, flush=True)
"""

_CHILD_DRYRUN = r"""
import os, sys
sys.path.insert(0, os.environ["DDLB_REPO"])
import __graft_entry__ as ge
ge.dryrun_multichip(int(os.environ["DDLB_SCALE_D"]))
print("DRYRUN_OK", os.environ["DDLB_SCALE_D"], flush=True)
"""


def _run_child(src, env_extra, timeout, expects):
    env = dict(os.environ)
    # neutralize the ambient 8-device conftest world; the child builds its
    # own platform before first backend use
    env.pop("XLA_FLAGS", None)
    env["DDLB_TPU_SIM_DEVICES"] = "0"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["DDLB_REPO"] = _REPO
    env.update(env_extra)
    out = subprocess.run(
        [sys.executable, "-c", src],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=_REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    for token in expects:
        assert token in out.stdout, out.stdout + out.stderr
    return out.stdout


@pytest.mark.slow
@pytest.mark.parametrize(
    "d,races", [(16, 1), (32, 0)], ids=["d16-races", "d32"]
)
def test_ring_and_a2a_kernels_scale(d, races):
    """Ring ag/rs + fused a2a protocols pinned at d=16 (race detector on)
    and d=32 under the distributed interpreter."""
    _run_child(
        _CHILD_KERNELS,
        {"DDLB_SCALE_D": str(d), "DDLB_SCALE_RACES": str(races)},
        timeout=900,
        expects=[f"AG_OK {d}", f"RS_OK {d}", f"A2A_OK {d}"]
        + (
            [f"PURE_AG_OK {d}", f"PURE_RS_OK {d}"]
            if d <= 16
            else [f"PURE_AG_SKIPPED {d}", f"PURE_RS_SKIPPED {d}"]
        ),
    )


_CHILD_DCN_RING = r"""
import os
from ddlb_tpu.benchmark import benchmark_worker
from ddlb_tpu.runtime import Runtime

rt = Runtime()
assert rt.num_slices == 2, rt.slice_ids

# The RDMA ring kernel's distributed interpreter emulates remote DMA
# within ONE process (probing it across a real process boundary hangs by
# construction), so the cross-process pin is the ring PROTOCOL itself:
# the p2p_pipeline member runs the same ag_fwd ring schedule
# (native.ring_schedule) over ppermute hops, every one of which crosses
# the process boundary on the dcn layout; the pallas member's
# xla_collective algorithm pins the Pallas GEMM fed by a cross-process
# all-gather.
for base, opts, tag in [
    ("overlap", {"algorithm": "p2p_pipeline", "transport": "dcn"}, "RING"),
    ("pallas",
     {"algorithm": "xla_collective", "transport": "dcn",
      "block_m": 128, "block_n": 128, "block_k": 128},
     "PALLAS"),
]:
    row = benchmark_worker({
        "primitive": "tp_columnwise",
        "impl_id": f"{base}_dcn",
        "base_implementation": base,
        "options": opts,
        "m": 128, "n": 128, "k": 128,
        "dtype": "float32",
        "num_iterations": 2,
        "num_warmups": 1,
        "validate": True,
        "time_measurement_backend": "host_clock",
        "barrier_at_each_iteration": True,
        "profile_dir": None,
    })
    assert row["valid"], (tag, row)
    assert row["world_size"] == 8 and row["num_processes"] == 2, row
    print(f"DCN_{tag}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_dcn_ring_protocol():
    """VERDICT r3 #7: the ring schedule and the Pallas GEMM pinned across
    a REAL 2-process boundary on the dcn (interleaved-slice) layout."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            {
                "PALLAS_AXON_POOL_IPS": "",
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                "DDLB_TPU_SIM_DEVICES": "0",
                "DDLB_TPU_NUM_PROCESSES": "2",
                "DDLB_TPU_PROCESS_ID": str(pid),
                "DDLB_TPU_COORD_ADDR": f"127.0.0.1:{port}",
            }
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _CHILD_DCN_RING],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, cwd=_REPO,
            )
        )
    try:
        outputs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:  # a hung child must not outlive the test
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert "DCN_RING_OK" in out and "DCN_PALLAS_OK" in out, out


@pytest.mark.slow
@pytest.mark.parametrize("d", [16, 32])
def test_dryrun_multichip_scale(d):
    """The driver's full multi-chip dry run (GPipe + 1F1B modern-stack
    train steps + int8/GQA/RoPE serving) compiles and executes at 16 and
    32 virtual devices."""
    _run_child(
        _CHILD_DRYRUN,
        {"DDLB_SCALE_D": str(d)},
        timeout=900,
        expects=[f"DRYRUN_OK {d}"],
    )
