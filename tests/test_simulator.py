"""Static performance simulator: engine, front-ends, validation, CLI.

The acceptance surface of the simulator subsystem:

- topology parsing/presets and the resource-rate contract;
- event-engine determinism (bit-identical replays);
- closed-form agreement with ``perfmodel.cost`` on degenerate flat
  topologies for every registered family;
- the chunked pipeline law ``max(C, W) + min(C, W)/chunks`` reproduced
  from the REPLAYED double-buffered ring (traced front-end), not from a
  closed form;
- hierarchical-beats-flat on a 2-pod DCN-bound topology;
- the tolerance-gated history join against a seeded cpu-sim capture
  (clean passes, a faster-than-roofline row fails);
- ``scripts/sim_report.py`` exit codes and ``--json`` shape;
- the ``DDLB_TPU_TOPOLOGY`` accessor and the CLI ``--topology`` export.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import pytest

from ddlb_tpu.perfmodel.cost import (
    hierarchical_wire_bytes,
    ring_step_count,
    ring_wire_bytes,
)
from ddlb_tpu.perfmodel.topology import (
    PRESETS,
    Topology,
    flat_topology,
    parse_topology,
    resolve_topology,
)
from ddlb_tpu.simulator.engine import replay, summarize
from ddlb_tpu.simulator.frontends import (
    flat_ring_program,
    hierarchical_program,
    striped_program,
    synthetic_program,
)
from ddlb_tpu.simulator.program import (
    ComputeStep,
    Stage,
    WireStep,
    pipelined,
    sequential,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIM_REPORT = os.path.join(REPO, "scripts", "sim_report.py")

GB = 1e9
MiB = float(1 << 20)


# ---------------------------------------------------------------------------
# topology layer
# ---------------------------------------------------------------------------


class TestTopology:
    def test_parse_spec(self):
        topo = parse_topology("v5p:4x16x16")
        assert topo.chip.name == "v5p"
        assert topo.pods == 4
        assert topo.ici_mesh == (16, 16)
        assert topo.chips_per_pod == 256
        assert topo.num_chips == 1024

    def test_parse_degenerate_flat(self):
        topo = parse_topology("v5e:8")
        assert topo.pods == 1
        assert topo.num_chips == 8
        assert topo.flat_bw == topo.ici_bw

    def test_parse_rejects_malformed(self):
        for bad in ("v5e", "v5e:", ":4x4", "v5e:axb", "v5e:0x4"):
            with pytest.raises(ValueError):
                parse_topology(bad)
        with pytest.raises(KeyError):
            parse_topology("v99:4x4")

    def test_presets_resolve(self):
        for name in PRESETS:
            topo = resolve_topology(name)
            assert 256 <= topo.num_chips <= 4096

    def test_flat_bw_gated_by_dcn_on_multipod(self):
        topo = parse_topology("v5p:2x16")
        assert topo.flat_bw == topo.dcn_bw  # dcn is the slow class
        assert topo.resource_rate("ici0") == topo.ici_bw
        assert topo.resource_rate("mxu", "bfloat16") == 459e12

    def test_unknown_resource_raises(self):
        topo = parse_topology("v5e:8")
        with pytest.raises(ValueError):
            topo.resource_rate("ici5")  # only one ici mesh dim

    def test_flat_hop_fractions_sum_to_one(self):
        topo = parse_topology("v5e:4x8x8")
        fractions = topo.flat_hop_fractions()
        assert sum(fractions.values()) == pytest.approx(1.0)
        assert fractions["dcn"] == pytest.approx(4 / 256)


# ---------------------------------------------------------------------------
# engine determinism + schedule semantics
# ---------------------------------------------------------------------------


def _demo_program():
    stages = [
        Stage(
            [
                WireStep(8 * MiB, scope="ici0", tag=f"ring#{j}"),
                ComputeStep(1e9, tag=f"gemm#{j}"),
            ],
            label=f"chunk{j}",
        )
        for j in range(4)
    ]
    return pipelined("demo", stages)


class TestEngine:
    def test_deterministic_replay(self):
        topo = flat_topology(8, "v5e")
        first = replay(_demo_program(), topo)
        second = replay(_demo_program(), topo)
        assert first.makespan_s == second.makespan_s
        assert [
            (e.index, e.resource, e.start_s, e.finish_s)
            for e in first.timeline
        ] == [
            (e.index, e.resource, e.start_s, e.finish_s)
            for e in second.timeline
        ]
        assert first.events == second.events == 8

    def test_sequential_sums_overlap_races(self):
        topo = flat_topology(8, "v5e")
        comm = WireStep(50 * GB / 1e3, scope="ici0")  # exactly 1 ms
        comp = ComputeStep(197e12 / 1e3)  # exactly 1 ms at bf16 peak
        seq = replay(sequential("seq", [comm, comp]), topo)
        assert seq.makespan_s == pytest.approx(2e-3)
        ovl = replay(
            pipelined("ovl", [Stage([comp]), Stage([comm])]), topo
        )
        assert ovl.makespan_s == pytest.approx(1e-3)
        assert ovl.overlap_frac == pytest.approx(1.0)

    def test_overlap_frac_nan_without_hideable_window(self):
        topo = flat_topology(8, "v5e")
        result = replay(sequential("comm-only", [WireStep(MiB)]), topo)
        assert math.isnan(result.overlap_frac)

    def test_summarize_shape(self):
        topo = parse_topology("v5e:2x4")
        doc = summarize(replay(_demo_program(), topo), topo)
        assert doc["chips"] == 8
        assert set(doc["links"]) == {"ici0", "dcn", "flat"}
        for info in doc["links"].values():
            assert 0.0 <= info["busy_frac"] <= 1.0


# ---------------------------------------------------------------------------
# synthetic compositions
# ---------------------------------------------------------------------------


class TestSynthetics:
    def test_flat_ring_wire_census(self):
        topo = flat_topology(16, "v5e")
        payload = 4 * MiB
        program = flat_ring_program("all_reduce", payload, topo)
        assert program.total(WireStep) == pytest.approx(
            ring_wire_bytes("all_reduce", payload, 16)
        )
        assert program.num_steps() == ring_step_count("all_reduce", 16)

    def test_hierarchical_wire_split_matches_formula(self):
        topo = parse_topology("v5e:4x8")
        payload = 8 * MiB
        program = hierarchical_program("all_reduce", payload, topo)
        result = replay(program, topo)
        want = hierarchical_wire_bytes("all_reduce", payload, 8, 4)
        assert result.payload.get("ici0", 0.0) == pytest.approx(want["ici"])
        assert result.payload.get("dcn", 0.0) == pytest.approx(want["dcn"])

    def test_hierarchical_beats_flat_on_dcn_bound_2pod(self):
        # the acceptance topology: 2 pods, thin DCN — every flat-ring
        # step is gated by the cross-pod hop
        topo = parse_topology("v5p:2x16")
        payload = 64 * MiB
        for op in ("all_reduce", "all_gather", "reduce_scatter"):
            flat = replay(flat_ring_program(op, payload, topo), topo)
            hier = replay(hierarchical_program(op, payload, topo), topo)
            assert hier.makespan_s < flat.makespan_s, op
        # and the advantage is the DCN relief, not an accounting trick:
        # flat moves its whole census at the DCN rate
        flat = replay(flat_ring_program("all_reduce", payload, topo), topo)
        assert flat.makespan_s == pytest.approx(
            ring_wire_bytes("all_reduce", payload, 32) / topo.dcn_bw
        )

    def test_striped_degenerates_to_hierarchical_on_1d_mesh(self):
        topo = parse_topology("v5e:2x8")  # one ici dim -> one stripe
        payload = 8 * MiB
        hier = replay(hierarchical_program("all_reduce", payload, topo), topo)
        striped = replay(striped_program("all_reduce", payload, topo), topo)
        assert striped.makespan_s == pytest.approx(hier.makespan_s)

    def test_striped_beats_hierarchical_on_2d_mesh(self):
        topo = parse_topology("v5p:2x8x8")
        payload = 64 * MiB
        hier = replay(hierarchical_program("all_reduce", payload, topo), topo)
        striped = replay(striped_program("all_reduce", payload, topo), topo)
        assert striped.makespan_s < hier.makespan_s

    def test_unknown_algo_raises(self):
        from ddlb_tpu.simulator.frontends import ProgramBuildError

        with pytest.raises(ProgramBuildError):
            synthetic_program("magic", "all_reduce", MiB, flat_topology(8))


# ---------------------------------------------------------------------------
# closed-form agreement (every registered family)
# ---------------------------------------------------------------------------


class TestClosedFormAgreement:
    def test_every_family_agrees_to_float_precision(self):
        from ddlb_tpu.primitives.registry import ALLOWED_PRIMITIVES
        from ddlb_tpu.simulator.validate import closed_form_check

        results = closed_form_check()
        covered = {r["family"] for r in results}
        assert covered == set(ALLOWED_PRIMITIVES)
        for r in results:
            assert r["ok"], (
                f"{r['family']}/{r['member']} {r['options']}: "
                f"sim {r['predicted_sim_s']} vs cost "
                f"{r['predicted_cost_s']} (rel {r['rel_err']:.2e})"
            )

    def test_chunked_depths_checked(self):
        from ddlb_tpu.simulator.validate import closed_form_check

        results = closed_form_check(families=("dp_allreduce",))
        chunked = [
            r for r in results if r["options"].get("algorithm") == "chunked"
        ]
        assert {r["options"]["chunk_count"] for r in chunked} == {1, 2, 4}


# ---------------------------------------------------------------------------
# traced front-end: the replayed double-buffered ring
# ---------------------------------------------------------------------------


class TestTracedReplay:
    @pytest.mark.parametrize("chunks", [2, 4])
    @pytest.mark.parametrize("family", ["tp_columnwise", "tp_rowwise"])
    def test_chunk_law_emerges_from_replay(self, family, chunks):
        """The pipeline law is NOT coded into the traced path: the
        engine's FIFO arbitration of the literal c*(d-1) traced
        ppermutes must land on ``max(C, W) + min(C, W)/c``."""
        from ddlb_tpu.analysis.spmd.families import member_schedule
        from ddlb_tpu.simulator.frontends import program_from_schedule

        export = member_schedule(
            family, "overlap",
            {"algorithm": "chunked", "chunk_count": chunks},
        )
        assert export["status"] == "verified"
        d = export["partitions"]
        assert len(export["entries"]) > 0
        assert len(export["entries"]) % chunks == 0
        topo = flat_topology(d, "v5e")
        result = replay(program_from_schedule(export, topo), topo)
        compute, wire = result.compute_busy_s, result.comm_busy_s
        law = max(compute, wire) + min(compute, wire) / chunks
        assert result.makespan_s == pytest.approx(law, rel=1e-12)

    def test_sequential_member_replays_serial_floor(self):
        from ddlb_tpu.analysis.spmd.families import member_schedule
        from ddlb_tpu.simulator.frontends import program_from_schedule

        export = member_schedule("dp_allreduce", "jax_spmd", {})
        assert export["status"] == "verified"
        topo = flat_topology(export["partitions"], "v5e")
        result = replay(program_from_schedule(export, topo), topo)
        assert result.makespan_s == pytest.approx(
            result.compute_busy_s + result.comm_busy_s, rel=1e-12
        )
        # the traced wire census survives the lowering intact
        assert sum(
            v for r, v in result.payload.items() if r.startswith("ici")
        ) == pytest.approx(export["wire_traced"])

    def test_pipeline_schedule_table_replays_step_by_step(self):
        from ddlb_tpu.analysis.spmd.families import member_schedule
        from ddlb_tpu.simulator.frontends import program_from_schedule

        export = member_schedule("pp_pipeline", "schedules", {})
        assert export["status"] == "verified"
        # the dense tick table arrives as per-tick hops, not one blob
        assert len(export["entries"]) > 10
        topo = flat_topology(export["partitions"], "v5e")
        result = replay(program_from_schedule(export, topo), topo)
        assert result.makespan_s > 0.0


# ---------------------------------------------------------------------------
# history join (seeded cpu-sim capture)
# ---------------------------------------------------------------------------


def _seed_capture(tmp_path, slack: float = 3.0):
    """Bank a synthetic-but-honest cpu-sim capture: rows whose measured
    medians sit ``slack``x above their own closed-form predictions (the
    roofline contract every real capture satisfies)."""
    from ddlb_tpu.observatory.store import bank_row
    from ddlb_tpu.perfmodel.cost import estimate
    from ddlb_tpu.perfmodel.specs import get_spec
    from ddlb_tpu.simulator.validate import build_stub

    directory = str(tmp_path)
    spec = get_spec("cpu-sim")
    configs = [
        ("tp_columnwise", "jax_spmd", {}, "", (256, 64, 64)),
        ("dp_allreduce", "jax_spmd", {}, "", (256, 64, 64)),
        (
            "dp_allreduce",
            "overlap",
            {"algorithm": "chunked", "chunk_count": 2},
            "algorithm=chunked;chunk_count=2",
            (256, 64, 64),
        ),
        # outside REPRODUCIBLE_FAMILIES: must still face the
        # lower-bound gate (2b), never be skipped
        ("transformer_decode", "spmd", {}, "", (64, 64, 64)),
    ]
    for family, member, options, option_str, (m, n, k) in configs:
        impl = build_stub(
            family, member, m, n, k, 8, dtype="float32", **options
        )
        predicted = estimate(impl, spec).predicted_s
        row = {
            "primitive": family,
            "base_implementation": member,
            "option": option_str,
            "m": m, "n": n, "k": k,
            "dtype": "float32",
            "world_size": 8,
            "chip": "cpu-sim",
            "time_measurement_backend": "host_clock",
            "median time (ms)": predicted * slack * 1e3,
            "predicted_s": predicted,
            "error": "",
        }
        assert bank_row(row, kind="row", directory=directory)
    return directory


class TestHistoryJoin:
    def test_clean_capture_validates(self, tmp_path):
        from ddlb_tpu.simulator.validate import history_check

        directory = _seed_capture(tmp_path)
        verdict = history_check(directory)
        # all four keys face the lower-bound gate, including the
        # transformer_decode row outside REPRODUCIBLE_FAMILIES
        assert verdict["checked"] == 4
        assert verdict["violations"] == []
        assert verdict["ok"]

    def test_faster_than_roofline_row_fails(self, tmp_path):
        from ddlb_tpu.observatory.store import bank_row, load_history
        from ddlb_tpu.simulator.validate import history_check

        directory = _seed_capture(tmp_path)
        row = dict(load_history(directory)[0]["row"])
        row["m"] = 512  # fresh key: the clean medians cannot absorb it
        row["median time (ms)"] = float(row["predicted_s"]) * 1e3 / 4.0
        assert bank_row(row, kind="row", directory=directory)
        verdict = history_check(directory)
        assert not verdict["ok"]
        assert any(
            v["kind"] == "lower-bound" for v in verdict["violations"]
        )

    def test_empty_history_is_not_a_pass(self, tmp_path):
        from ddlb_tpu.simulator.validate import history_check

        assert not history_check(str(tmp_path))["ok"]


# ---------------------------------------------------------------------------
# the report CLI
# ---------------------------------------------------------------------------


def _run_report(*args):
    return subprocess.run(
        [sys.executable, SIM_REPORT, *args],
        capture_output=True, text=True, timeout=300,
    )


class TestSimReportCLI:
    def test_json_shape_and_exit_zero(self):
        out = _run_report(
            "--topology", "v5e:2x4", "--no-members", "--json",
            "--payload-mib", "4",
        )
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert doc["topology"]["chips"] == 8
        assert {b["family"] for b in doc["ranking"]} == {
            "tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall",
            "collectives",
        }
        for block in doc["ranking"]:
            algos = [r["algo"] for r in block["rows"]]
            assert sorted(algos) == ["flat", "hierarchical", "striped"]
            # rows arrive ranked fastest-first
            spans = [r["makespan_s"] for r in block["rows"]]
            assert spans == sorted(spans)

    def test_bad_topology_exits_two(self):
        out = _run_report("--topology", "nonsense")
        assert out.returncode == 2
        assert "topology" in out.stderr

    def test_bad_family_exits_two(self):
        out = _run_report("--families", "warp_drive")
        assert out.returncode == 2

    def test_validation_failure_exits_one(self, tmp_path):
        directory = _seed_capture(tmp_path)
        from ddlb_tpu.observatory.store import bank_row, load_history

        row = dict(load_history(directory)[0]["row"])
        row["m"] = 512
        row["median time (ms)"] = float(row["predicted_s"]) * 1e3 / 4.0
        assert bank_row(row, kind="row", directory=directory)
        out = _run_report("--validate", "--history", directory)
        assert out.returncode == 1, out.stdout
        assert "FAILED" in out.stdout


# ---------------------------------------------------------------------------
# env accessor + CLI threading
# ---------------------------------------------------------------------------


class TestTopologyOverride:
    def test_env_accessor(self, monkeypatch):
        from ddlb_tpu import envs

        monkeypatch.delenv("DDLB_TPU_TOPOLOGY", raising=False)
        assert envs.get_topology_override() == ""
        monkeypatch.setenv("DDLB_TPU_TOPOLOGY", " v5p:2x16 ")
        assert envs.get_topology_override() == "v5p:2x16"

    def test_cli_exports_topology(self, monkeypatch):
        from ddlb_tpu.cli import benchmark as cli

        monkeypatch.delenv("DDLB_TPU_TOPOLOGY", raising=False)
        captured = {}
        monkeypatch.setattr(
            cli, "run_benchmark", lambda config: captured.update(config)
        )
        cli.main(["--topology", "v5e:2x4", "--sim", "8"])
        assert os.environ.get("DDLB_TPU_TOPOLOGY") == "v5e:2x4"
        assert captured["primitive"] == "tp_columnwise"

    def test_cli_rejects_bad_topology(self, monkeypatch):
        from ddlb_tpu.cli import benchmark as cli

        with pytest.raises(SystemExit):
            cli.main(["--topology", "not-a-world"])


# ---------------------------------------------------------------------------
# member twins: real traced members vs synthetic compositions (ISSUE 16)
# ---------------------------------------------------------------------------


class TestMemberTwins:
    """The topology-adaptive members' traced schedules replayed next to
    the synthetic builders that predicted them (validate.member_twin_
    check), plus the traced-front-end lowering rules the replay relies
    on: sx/sy entries land on distinct ICI link classes, stripe-major
    traces split into concurrent stages, and a world-spanning flat
    member's ring bills the flat channel on a multi-pod world."""

    #: d=16 as 4 pods of a 2x2 torus — both torus axes alive, so the
    #: striped trace carries two true stripes
    SHAPES_16 = {
        "m": 256, "n": 1, "k": 64, "d": 16,
        "dcn": 4, "ici": 4, "sx": 2, "sy": 2,
    }

    def _schedule(self, overrides):
        from ddlb_tpu.analysis.spmd.families import member_schedule

        return member_schedule(
            "collectives", "jax_spmd_hier",
            {"op": "all_reduce", **overrides},
            shapes=self.SHAPES_16,
        )

    def test_twin_gate_passes(self):
        from ddlb_tpu.simulator.validate import member_twin_check

        out = member_twin_check()
        assert out["ok"], out["failures"]
        by_key = {
            (r["family"], r["composition"]): r for r in out["records"]
        }
        # all three families x three compositions replayed
        assert len(by_key) == 9
        for family in ("collectives", "dp_allreduce", "ep_alltoall"):
            # flat/hier traces lower to step-for-step identical programs
            assert by_key[(family, "flat")]["rel_err"] < 1e-9
            assert by_key[(family, "hierarchical")]["rel_err"] < 1e-9
            # the acceptance ranking: both adaptive compositions beat
            # flat on the 4-pod world, in the REAL members' replays
            flat_s = by_key[(family, "flat")]["traced_s"]
            assert by_key[(family, "hierarchical")]["traced_s"] < flat_s
            assert by_key[(family, "striped")]["traced_s"] < flat_s

    def test_striped_trace_splits_into_concurrent_stages(self):
        from ddlb_tpu.simulator.frontends import program_from_schedule

        export = self._schedule({"composition": "striped"})
        assert export["status"] == "verified", export["reason"]
        assert export["stripes"] == 2
        topo = Topology(
            chip=parse_topology("v5p:4x2x2").chip, pods=4, ici_mesh=(2, 2)
        )
        prog = program_from_schedule(dict(export, flops=0.0), topo)
        assert prog.overlap  # stripes are concurrent, not chained
        assert len(prog.stages) == 2
        scopes = {
            s.scope for stage in prog.stages for s in stage.steps
            if isinstance(s, WireStep)
        }
        # the two ring families ride DISTINCT link classes + shared DCN
        assert scopes == {"ici0", "ici1", "dcn"}
        # each stripe's big ring leads on its own axis
        lead0 = next(
            s for s in prog.stages[0].steps if isinstance(s, WireStep)
        )
        lead1 = next(
            s for s in prog.stages[1].steps if isinstance(s, WireStep)
        )
        assert {lead0.scope, lead1.scope} == {"ici0", "ici1"}

    def test_flat_member_bills_flat_channel_on_multipod(self):
        from ddlb_tpu.simulator.frontends import program_from_schedule

        export = self._schedule({"composition": "flat"})
        assert export["status"] == "verified", export["reason"]
        multipod = parse_topology("v5p:4x2x2")
        prog = program_from_schedule(dict(export, flops=0.0), multipod)
        scopes = {
            s.scope for stage in prog.stages for s in stage.steps
            if isinstance(s, WireStep)
        }
        assert scopes == {"flat"}
        # the same export on a single-pod world stays on ICI
        flat_world = flat_topology(16, "v5p")
        prog = program_from_schedule(dict(export, flops=0.0), flat_world)
        scopes = {
            s.scope for stage in prog.stages for s in stage.steps
            if isinstance(s, WireStep)
        }
        assert scopes == {"ici0"}

    def test_compare_members_cli(self):
        out = _run_report("--compare-members", "--json")
        assert out.returncode == 0, out.stderr or out.stdout
        doc = json.loads(out.stdout)
        assert doc["ok"]
        assert len(doc["records"]) == 9
