"""Topology-adaptive members (ISSUE 16): hier/striped compositions.

Every member x composition validates numerically on the 8-device CPU
mesh in both the 1-slice and 2-simulated-slice worlds, the selection
policy resolves ``auto`` from live topology / fault plan / degraded
overlay, and ``wire_bytes()`` tracks the resolved composition's closed
form (``cost.hierarchical_wire_bytes`` / ``cost.striped_wire_bytes``) —
the same identities DDLB123's traced census verifies at zero drift.
"""

import json

import pytest

from ddlb_tpu.perfmodel.cost import (
    hierarchical_wire_bytes,
    striped_wire_bytes,
    torus_factors,
)
from ddlb_tpu.primitives.registry import load_impl_class
from ddlb_tpu.primitives.topo_compose import (
    select_composition,
    two_level_factors,
)
from ddlb_tpu.runtime import Runtime

M, N, K = 256, 64, 64  # m % d^2 at d=8; all stripe/scatter splits exact


@pytest.fixture
def two_slices(monkeypatch):
    """8 CPU devices as 2 simulated slices x 4 (test_collectives.py
    idiom); restores the clean singleton afterwards."""
    monkeypatch.setenv("DDLB_TPU_SIM_SLICES", "2")
    Runtime.reset()
    yield
    monkeypatch.delenv("DDLB_TPU_SIM_SLICES")
    Runtime.reset()
    Runtime()


# -- selection policy ---------------------------------------------------------


def test_two_level_factors():
    assert two_level_factors(8, 1) == (8, 1)
    assert two_level_factors(8, 2) == (4, 2)
    assert two_level_factors(8, 4) == (2, 4)
    # a slice count that does not divide the world degenerates to flat
    assert two_level_factors(8, 3) == (8, 1)


def test_select_composition_pinned_passthrough():
    for comp in ("flat", "hierarchical", "striped"):
        assert select_composition(comp, 8, 2)[0] == comp
    with pytest.raises(ValueError):
        select_composition("bogus", 8, 2)


def test_select_composition_auto_healthy():
    # healthy 1-slice world -> flat; multi-slice -> hierarchical
    comp, reason = select_composition("auto", 8, 1)
    assert comp == "flat"
    comp, reason = select_composition("auto", 8, 2)
    assert comp == "hierarchical"
    assert "slice" in reason or "inter" in reason


def test_select_composition_auto_degraded_world(monkeypatch):
    monkeypatch.setenv("DDLB_TPU_WORLD_DEGRADED", "1")
    comp, reason = select_composition("auto", 8, 2)
    assert comp == "striped"
    assert "degraded" in reason


def test_select_composition_auto_fault_plan(monkeypatch, tmp_path):
    plan = {
        "seed": 7,
        "rules": [
            {
                "site": "runtime.collective",
                "kind": "link_slow",
                "topo": {"axis": "ici", "index": 1, "direction": "tx",
                         "factor": 0.25},
            }
        ],
    }
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(plan))
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", str(path))
    comp, reason = select_composition("auto", 8, 1)
    assert comp == "striped"
    assert "link" in reason


def test_composition_signature_tracks_health_inputs(monkeypatch, tmp_path):
    """The auto-resolution cache key (ISSUE 19): any input
    ``select_composition`` consults — degraded stamp, fault plan,
    history bank identity or content — moves the signature."""
    from ddlb_tpu.observatory import store
    from ddlb_tpu.primitives.topo_compose import composition_signature

    monkeypatch.delenv("DDLB_TPU_WORLD_DEGRADED", raising=False)
    monkeypatch.delenv("DDLB_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("DDLB_TPU_HISTORY", raising=False)
    base = composition_signature()
    assert composition_signature() == base  # stable while inputs hold
    monkeypatch.setenv("DDLB_TPU_WORLD_DEGRADED", "1")
    degraded = composition_signature()
    assert degraded != base
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", json.dumps({"rules": []}))
    planned = composition_signature()
    assert planned != degraded
    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path))
    banked_0 = composition_signature()
    assert banked_0 != planned
    store.bank_row(
        {"primitive": "collectives", "implementation": "x",
         "median time (ms)": 1.0},
        run="r1", directory=str(tmp_path),
    )
    assert composition_signature() != banked_0  # bank mtime moved


def test_auto_reresolves_when_world_degrades_mid_member(monkeypatch):
    """A live ``auto`` member re-resolves at the next row boundary when
    the degraded stamp lands mid-sweep — no relaunch — while a PINNED
    composition is never second-guessed."""
    monkeypatch.delenv("DDLB_TPU_WORLD_DEGRADED", raising=False)
    cls = load_impl_class("collectives", "jax_spmd_striped")
    auto = cls(M, 1, K, dtype="float32", composition="auto")
    assert auto._resolved_composition() == "flat"  # healthy 1-slice
    pinned = cls(M, 1, K, dtype="float32", composition="striped")
    assert pinned._resolved_composition() == "striped"
    monkeypatch.setenv("DDLB_TPU_WORLD_DEGRADED", "1")
    assert auto._resolved_composition() == "striped"
    assert pinned._resolved_composition() == "striped"
    monkeypatch.delenv("DDLB_TPU_WORLD_DEGRADED")
    assert auto._resolved_composition() == "flat"  # and back


# -- torus mesh ---------------------------------------------------------------



def test_torus_mesh_shape():
    mesh = Runtime().torus_mesh()
    sx, sy = torus_factors(Runtime().num_devices)
    assert mesh.axis_names == ("dcn", "sx", "sy")
    assert mesh.devices.shape == (1, sx, sy)


def test_torus_mesh_two_slices(two_slices):
    mesh = Runtime().torus_mesh()
    assert mesh.devices.shape == (2, 2, 2)
    # slice-major device order, hybrid_mesh-compatible
    hybrid = Runtime().hybrid_mesh(("dcn", "ici"))
    assert (mesh.devices.reshape(2, 4) == hybrid.devices).all()


# -- members: numerical correctness ------------------------------------------


COLLECTIVE_OPS = ("all_gather", "all_reduce", "reduce_scatter",
                  "all_to_all")


@pytest.mark.parametrize("op", COLLECTIVE_OPS)
def test_collectives_hier_two_slices(two_slices, op):
    cls = load_impl_class("collectives", "jax_spmd_hier")
    impl = cls(M, 1, K, dtype="float32", op=op)
    assert impl._resolved_composition() == "hierarchical"
    assert impl.mesh.axis_names == ("dcn", "ici")
    assert impl.validate(impl.run())


def test_collectives_striped_both_worlds(two_slices):
    cls = load_impl_class("collectives", "jax_spmd_striped")
    impl = cls(M, 1, K, dtype="float32")
    assert impl.options["op"] == "all_reduce"
    assert impl.mesh.axis_names == ("dcn", "sx", "sy")
    assert impl.validate(impl.run())


def test_collectives_striped_single_slice():
    cls = load_impl_class("collectives", "jax_spmd_striped")
    impl = cls(M, 1, K, dtype="float32")
    assert impl.validate(impl.run())


@pytest.mark.parametrize("comp", ["hierarchical", "striped"])
def test_dp_allreduce_members_two_slices(two_slices, comp):
    cls = load_impl_class("dp_allreduce", "jax_spmd_hier")
    impl = cls(M, N, K, dtype="float32", composition=comp)
    assert impl.validate(impl.run())


@pytest.mark.parametrize("comp", ["hierarchical", "striped"])
def test_ep_alltoall_members_two_slices(two_slices, comp):
    cls = load_impl_class("ep_alltoall", "jax_spmd_hier")
    impl = cls(M, N, K, dtype="float32", composition=comp)
    assert impl.validate(impl.run())


def test_ep_striped_single_slice():
    cls = load_impl_class("ep_alltoall", "jax_spmd_striped")
    impl = cls(M, N, K, dtype="float32")
    assert impl.validate(impl.run())


def test_auto_resolves_per_world(two_slices):
    cls = load_impl_class("dp_allreduce", "jax_spmd_hier")
    impl = cls(M, N, K, dtype="float32", composition="auto")
    assert impl._resolved_composition() == "hierarchical"
    assert impl.validate(impl.run())


# -- guards -------------------------------------------------------------------


def test_member_guards():
    hier = load_impl_class("collectives", "jax_spmd_hier")
    with pytest.raises(ValueError, match="single hop"):
        hier(M, 1, K, dtype="float32", op="ppermute")
    with pytest.raises(ValueError, match="transport axis"):
        hier(M, 1, K, dtype="float32", op="all_reduce", transport="dcn")
    striped = load_impl_class("collectives", "jax_spmd_striped")
    with pytest.raises(ValueError, match="all_reduce only"):
        striped(M, 1, K, dtype="float32", op="all_gather")
    dp = load_impl_class("dp_allreduce", "jax_spmd_hier")
    with pytest.raises(ValueError, match="scatter"):
        dp(12, N, K, dtype="float32", composition="striped")


# -- row stamp + closed-form wire bytes ---------------------------------------


def test_composition_stamped_on_rows():
    cls = load_impl_class("dp_allreduce", "jax_spmd_hier")
    impl = cls(M, N, K, dtype="float32", composition="hierarchical")
    assert impl.extra_row_fields()["composition"] == "hierarchical"
    flat = cls(M, N, K, dtype="float32", composition="flat")
    assert flat.extra_row_fields()["composition"] == "flat"


def test_composition_column_registered():
    # the row stamp is a schema-registered column (DDLB108 discipline):
    # an undocumented CSV contract change must not ship
    from ddlb_tpu.schema import ROW_COLUMNS

    assert "composition" in ROW_COLUMNS
    assert ROW_COLUMNS["composition"].strip()


def test_ddlb123_census_two_true_stripes():
    # d=16 factors to (dcn=4, sx=2, sy=2): BOTH torus axes alive, so the
    # striped members trace two genuinely concurrent ring families —
    # the canonical d=4 census only exercises the degenerate (1, 2)
    # slice. Zero drift against the striped closed form, and the
    # schedule export carries the stripe count the simulator splits on.
    from ddlb_tpu.analysis.core import repo_root
    from ddlb_tpu.analysis.spmd import families

    registry = families.ClassRegistry(repo_root())
    sizes = families._axis_sizes_for("collectives", 16)
    assert (sizes["sx"], sizes["sy"]) == (2, 2)
    for fam, member, shapes in [
        ("collectives", "jax_spmd_striped",
         {"m": 256, "n": 1, "k": 64, "d": 16}),
        ("dp_allreduce", "jax_spmd_striped",
         {"m": 256, "n": 64, "k": 64, "d": 16}),
        ("ep_alltoall", "jax_spmd_striped",
         {"m": 512, "n": 64, "k": 64, "d": 16}),
        ("collectives", "jax_spmd_hier",
         {"m": 256, "n": 1, "k": 64, "d": 16}),
    ]:
        report = families.trace_member(fam, member, {}, registry,
                                       shapes=shapes)
        assert report.status == "verified", (report.label(), report.reason)
        sched = families.member_schedule(fam, member, registry=registry,
                                         shapes=shapes)
        assert sched["stripes"] == 2


def test_wire_bytes_track_composition(two_slices):
    d = 8
    nbytes = M * N * 4  # full fp32 gradient
    intra, inter = two_level_factors(d, 2)
    cls = load_impl_class("dp_allreduce", "jax_spmd_hier")

    hier = cls(M, N, K, dtype="float32", composition="hierarchical")
    expect = hierarchical_wire_bytes("all_reduce", nbytes, intra, inter)
    assert hier.wire_bytes() == pytest.approx(expect["ici"] + expect["dcn"])

    striped = cls(M, N, K, dtype="float32", composition="striped")
    sx, sy = torus_factors(intra)
    expect = striped_wire_bytes("all_reduce", nbytes, inter, (sx, sy))
    assert striped.wire_bytes() == pytest.approx(
        expect["ici"] + expect["dcn"]
    )
