"""Perf observatory (ISSUE 6): run-history store, measured-overlap
attribution columns on every row, the regression detector + report CLI,
the live sweep stream + dashboard renderers, and the bench gate's
history layer."""

import importlib.util
import json
import math
import os
import subprocess
import sys

import pytest

from ddlb_tpu.observatory import attribution, live, regress, store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _row(impl="overlap_0", ms=1.0, **over):
    row = {
        "implementation": impl,
        "primitive": "tp_columnwise",
        "base_implementation": impl.rsplit("_", 1)[0],
        "option": "algorithm=default",
        "m": 64, "n": 64, "k": 64,
        "dtype": "float32",
        "world_size": 8,
        "chip": "cpu-sim",
        "time_measurement_backend": "host_clock",
        "median time (ms)": ms,
        "predicted_s": 1e-6,
        "error": "",
    }
    row.update(over)
    return row


# ---------------------------------------------------------------------------
# measured-overlap attribution
# ---------------------------------------------------------------------------


class _Est:
    def __init__(self, compute_s=0.0, comm_s=0.0, hbm_s=0.0):
        self.compute_s, self.comm_s, self.hbm_s = compute_s, comm_s, hbm_s


def test_attribution_hand_computed_overlap():
    """compute 2s + comm 1s, measured 2.2s: serial floor 3s, overlap
    floor 2s, hideable 1s -> 80% of the hideable window was hidden."""
    out = attribution.attribute(_Est(2.0, 1.0), "overlap", 2.2)
    assert out["measured_overlap_frac"] == pytest.approx(0.8)
    assert out["phase_compute_s"] == 2.0
    assert out["phase_comm_s"] == 1.0
    assert out["phase_idle_s"] == pytest.approx(0.2)


def test_attribution_clamps():
    # measured below the overlap floor (noise): clamp to 1, idle 0
    out = attribution.attribute(_Est(2.0, 1.0), "overlap", 1.9)
    assert out["measured_overlap_frac"] == 1.0
    assert out["phase_idle_s"] == 0.0
    # measured above the serial floor: nothing was hidden
    out = attribution.attribute(_Est(2.0, 1.0), "overlap", 5.0)
    assert out["measured_overlap_frac"] == 0.0
    assert out["phase_idle_s"] == pytest.approx(3.0)


def test_attribution_degenerate_and_non_overlap():
    # no comm term (1-device collective): nothing hideable -> NaN
    out = attribution.attribute(_Est(2.0, 0.0), "overlap", 2.5)
    assert math.isnan(out["measured_overlap_frac"])
    assert out["phase_idle_s"] == pytest.approx(0.5)
    # sequential member: phases attributed, overlap frac undefined
    out = attribution.attribute(_Est(2.0, 1.0), "sequential", 3.5)
    assert math.isnan(out["measured_overlap_frac"])
    assert out["phase_compute_s"] == 2.0
    # no measurement: everything NaN but the model floors
    out = attribution.attribute(_Est(2.0, 1.0), "overlap", float("nan"))
    assert math.isnan(out["measured_overlap_frac"])
    assert math.isnan(out["phase_idle_s"])
    assert out["phase_comm_s"] == 1.0


def test_runner_rows_carry_attribution_columns():
    """Every overlap-member row — measured AND error paths — carries
    measured_overlap_frac and the per-phase breakdown (the ISSUE 6
    acceptance criterion)."""
    from ddlb_tpu.benchmark import benchmark_worker

    cols = tuple(attribution.ATTRIBUTION_ROW_DEFAULTS)
    row = benchmark_worker({
        "primitive": "tp_columnwise", "impl_id": "overlap_0",
        "base_implementation": "overlap",
        "options": {"algorithm": "default"},
        "m": 64, "n": 64, "k": 64, "dtype": "float32",
        "num_iterations": 2, "num_warmups": 1, "validate": False,
    })
    assert row["error"] == ""
    for col in cols:
        assert col in row
    assert 0.0 <= row["measured_overlap_frac"] <= 1.0
    assert row["phase_comm_s"] > 0.0
    assert row["phase_idle_s"] >= 0.0
    # error path (impl construction fails): columns present, NaN
    err = benchmark_worker({
        "primitive": "tp_columnwise", "impl_id": "overlap_1",
        "base_implementation": "overlap",
        "options": {"algorithm": "no_such_algorithm"},
        "m": 64, "n": 64, "k": 64, "dtype": "float32",
    })
    assert err["error"]
    for col in cols:
        assert col in err
        assert math.isnan(err[col])


# ---------------------------------------------------------------------------
# run-history store
# ---------------------------------------------------------------------------


def test_store_disabled_is_noop(monkeypatch):
    monkeypatch.delenv("DDLB_TPU_HISTORY", raising=False)
    assert store.bank_row(_row()) is False
    assert store.load_history() == []


def test_store_roundtrip_and_key(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path / "hist"))
    assert store.bank_row(_row(ms=1.5), run="runA") is True
    assert store.bank_row(_row(ms=2.5), run="runB") is True
    records = store.load_history()
    assert len(records) == 2
    rec = records[0]
    assert rec["run_id"] == "runA"
    assert rec["kind"] == "row"
    assert rec["row"]["median time (ms)"] == 1.5
    # key: stable identity, identical across runs of the same config,
    # different when the config differs
    assert rec["key"] == records[1]["key"]
    assert store.row_key(_row(m=128)) != rec["key"]
    key = json.loads(rec["key"])
    assert key["chip"] == "cpu-sim"
    assert key["base_implementation"] == "overlap"


def test_store_skips_corrupt_lines(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path))
    store.bank_row(_row())
    path = store.history_path()
    with open(path, "a") as f:
        f.write('{"truncated mid-wri\n')
    store.bank_row(_row())
    assert len(store.load_history()) == 2


def test_sweep_runner_banks_rows_automatically(tmp_path, monkeypatch):
    """The acceptance wiring: a plain in-process sweep with
    DDLB_TPU_HISTORY set banks every row (error rows included) with no
    caller changes."""
    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path / "hist"))
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise", 64, 32, 64,
        implementations={
            "jax_spmd_0": {"implementation": "jax_spmd"},
            "overlap_1": {
                "implementation": "overlap", "algorithm": "no_such_algo",
            },
        },
        dtype="float32", num_iterations=2, num_warmups=1,
        validate=False, progress=False, max_retries=0,
    )
    df = runner.run()
    assert len(df) == 2
    records = store.load_history()
    assert len(records) == 2
    banked = {r["row"]["implementation"]: r["row"] for r in records}
    assert banked["jax_spmd_0"]["error"] == ""
    assert banked["overlap_1"]["error"]  # the error row banked too
    assert len({r["run_id"] for r in records}) == 1


# ---------------------------------------------------------------------------
# regression detection
# ---------------------------------------------------------------------------


def test_median_and_mad():
    assert regress.median([3.0, 1.0, 2.0]) == 2.0
    assert regress.median([1.0, 2.0, 3.0, 4.0]) == 2.5
    assert regress.mad([1.0, 2.0, 3.0, 100.0]) == 1.0  # outlier-immune
    assert math.isnan(regress.median([]))


def _history(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path / "hist"))
    for run in ("run1", "run2"):
        store.bank_row(_row("overlap_0", 1.0), run=run)
        store.bank_row(_row("jax_spmd_1", 2.0), run=run)
    return store.load_history()


def test_detect_seeded_slowdown_ranked_first(tmp_path, monkeypatch):
    history = _history(tmp_path, monkeypatch)
    current = [
        _row("jax_spmd_1", 2.6),   # 1.3x — a lesser regression
        _row("overlap_0", 3.0),    # the seeded 3x slowdown
    ]
    findings = regress.detect(current, history)
    assert len(findings) == 2
    assert findings[0]["implementation"] == "overlap_0"  # ranked first
    assert findings[0]["ratio"] == pytest.approx(3.0)
    assert findings[0]["source"] == "history"
    assert findings[0]["z"] > findings[1]["z"]


def test_detect_within_noise_is_clean(tmp_path, monkeypatch):
    history = _history(tmp_path, monkeypatch)
    current = [_row("overlap_0", 1.04), _row("jax_spmd_1", 2.05)]
    assert regress.detect(current, history) == []


def test_detect_excludes_current_run(tmp_path, monkeypatch):
    """A run must not baseline against its own banked rows: the current
    run's slow rows are already IN the bank (auto-banking), and leaving
    them in would dilute the baseline toward the regression itself."""
    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path / "hist"))
    store.bank_row(_row("overlap_0", 1.0), run="run1")
    store.bank_row(_row("overlap_0", 3.0), run="run3")  # current, banked
    history = store.load_history()
    current = [_row("overlap_0", 3.0)]
    # self-contaminated baseline (median of 1.0 and 3.0) hides the 3x
    assert regress.detect(current, history) == []
    # excluded: the baseline is run1's 1.0 and the slowdown is flagged
    findings = regress.detect(current, history, exclude_run="run3")
    assert len(findings) == 1 and findings[0]["ratio"] == pytest.approx(3.0)


def test_detect_perfmodel_prior_fallback(tmp_path, monkeypatch):
    """No history at all: the analytical lower bound is the baseline
    and a grossly-off row still gets flagged, ranked after any
    history-backed findings."""
    history = _history(tmp_path, monkeypatch)
    current = [
        _row("overlap_0", 3.0),  # history-backed 3x
        # new config never banked: 10 ms vs a 1 ms analytical floor
        _row("pallas_9", 10.0, option="kernel=pallas",
             **{"predicted_s": 1e-3}),
    ]
    findings = regress.detect(current, history)
    assert [f["source"] for f in findings] == ["history", "perfmodel_prior"]
    assert findings[1]["implementation"] == "pallas_9"
    assert findings[1]["ratio"] == pytest.approx(10.0)
    # and a new config within prior_factor of its bound stays clean
    ok = _row("pallas_9", 10.0, option="kernel=pallas",
              **{"predicted_s": 5e-3})
    assert regress.detect([ok], history) == []


def test_error_rows_never_regress(tmp_path, monkeypatch):
    history = _history(tmp_path, monkeypatch)
    nan_row = _row("overlap_0", float("nan"), error="CrashError: boom")
    assert regress.detect([nan_row], history) == []


# ---------------------------------------------------------------------------
# observatory_report.py CLI
# ---------------------------------------------------------------------------


def test_report_cli_detects_and_ranks(tmp_path, monkeypatch):
    """The ISSUE 6 acceptance criterion, end to end: two banked CPU-sim
    runs, a third with a seeded slowdown — the report detects it, ranks
    it first, and exits 1."""
    _history(tmp_path, monkeypatch)
    store.bank_row(_row("overlap_0", 3.0), run="run3")   # seeded 3x
    store.bank_row(_row("jax_spmd_1", 2.02), run="run3")  # in the noise
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "observatory_report.py")],
        env=dict(os.environ, DDLB_TPU_HISTORY=str(tmp_path / "hist")),
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    lines = [l for l in out.stdout.splitlines() if " overlap_0 " in l]
    assert lines and lines[0].lstrip().startswith("1 ")  # ranked first
    assert "jax_spmd_1" not in out.stdout  # noise row not flagged


def test_report_cli_json_and_csv_current(tmp_path, monkeypatch):
    _history(tmp_path, monkeypatch)
    # current run as a sweep CSV (stringly-typed like pandas writes it)
    csv_path = tmp_path / "current.csv"
    row = _row("overlap_0", 4.0)
    with open(csv_path, "w") as f:
        f.write(",".join(row.keys()) + "\n")
        f.write(",".join(str(v) for v in row.values()) + "\n")
    rep = _load_script("observatory_report")
    report = rep.build_report(
        str(tmp_path / "hist"), {"current": str(csv_path)}
    )
    assert report["current_rows"] == 1
    assert len(report["findings"]) == 1
    assert report["findings"][0]["source"] == "history"  # key matched CSV
    json.dumps(report)  # JSON-clean


def test_report_csv_mode_excludes_its_own_banked_copies(
    tmp_path, monkeypatch
):
    """A sweep run with history ON banks the very rows its CSV holds:
    --current CSV must not let the run baseline against itself (the
    2x regression would otherwise hide inside its own diluted
    median)."""
    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path / "hist"))
    store.bank_row(_row("overlap_0", 1.0), run="old")
    slow = _row("overlap_0", 2.0)
    store.bank_row(slow, run="current")  # the CSV's own banked copy
    csv_path = tmp_path / "current.csv"
    with open(csv_path, "w") as f:
        f.write(",".join(slow.keys()) + "\n")
        f.write(",".join(str(v) for v in slow.values()) + "\n")
    rep = _load_script("observatory_report")
    report = rep.build_report(
        str(tmp_path / "hist"), {"current": str(csv_path)}
    )
    assert len(report["findings"]) == 1  # baseline = old run's 1.0 only
    assert report["findings"][0]["ratio"] == pytest.approx(2.0)


def test_report_cli_no_history_is_usage_error(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "observatory_report.py")],
        env={k: v for k, v in os.environ.items() if k != "DDLB_TPU_HISTORY"},
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert out.returncode == 2
    assert "DDLB_TPU_HISTORY" in out.stdout


# ---------------------------------------------------------------------------
# live stream + dashboard
# ---------------------------------------------------------------------------


def _seed_live(monkeypatch, tmp_path):
    path = str(tmp_path / "live.jsonl")
    monkeypatch.setenv("DDLB_TPU_LIVE", path)
    live.post_event("sweep_start", total=3, primitive="tp_columnwise")
    live.post_event("worker_spawn", worker=999, reason="first")
    live.post_event("worker_ready", worker=999, setup_s=1.5, platform="cpu")
    live.post_event("row_start", impl="overlap_0",
                    primitive="tp_columnwise", m=64, n=64, k=64)
    live.post_event("row_phase", impl="overlap_0", stage="measuring")
    live.post_event("worker_beat", worker=999, age_s=0.5)
    live.post_event("row_done", impl="overlap_0", median_ms=1.2,
                    predicted_s=1e-4, roofline_frac=0.4,
                    measured_overlap_frac=0.7, error="", retries=0,
                    quarantined=False, worker_reused=True)
    live.post_event("row_done", impl="jax_spmd_1", median_ms=2.0,
                    predicted_s=2e-4, roofline_frac=0.2,
                    error="RuntimeError: boom", retries=1)
    live.post_event("queue_parked", label="bad", attempts=2)
    live.post_event("worker_dead", worker=999, error="silent (killed)")
    return path


def test_live_disabled_is_noop(monkeypatch, tmp_path):
    monkeypatch.delenv("DDLB_TPU_LIVE", raising=False)
    assert live.post_event("row_done") is False


def test_live_post_read_fold(monkeypatch, tmp_path):
    path = _seed_live(monkeypatch, tmp_path)
    events, offset = live.read_events(path)
    assert offset == os.path.getsize(path)
    assert [e["kind"] for e in events[:3]] == [
        "sweep_start", "worker_spawn", "worker_ready",
    ]
    state = live.fold(events)
    assert state["totals"] == {
        "total": 3, "done": 2, "errors": 1, "quarantined": 0,
        "parked": 1, "retries": 1,
    }
    assert state["workers"][999]["state"] == "dead"
    assert state["workers"][999]["setup_s"] == 1.5
    assert state["current"] == {}  # row_done cleared it
    assert len(state["recent"]) == 2
    # incremental tail: fold new events onto the same state
    live.post_event("row_start", impl="x_2", primitive="tp_columnwise",
                    m=1, n=1, k=1)
    more, offset2 = live.read_events(path, offset)
    assert [e["kind"] for e in more] == ["row_start"]
    state = live.fold(more, state)
    assert list(state["current"].values())[0]["impl"] == "x_2"


def test_fold_matches_phase_marks_across_pids(monkeypatch, tmp_path):
    """row_start is posted by the RUNNER, row_phase by the pool WORKER
    (a different pid): the fold must still attach the stage to the
    in-flight row, by impl id."""
    events = [
        {"ts": 1.0, "pid": 100, "kind": "row_start", "impl": "overlap_0",
         "primitive": "tp_columnwise", "m": 64, "n": 64, "k": 64},
        {"ts": 2.0, "pid": 200, "kind": "row_phase", "impl": "overlap_0",
         "stage": "warmup done; measuring"},
    ]
    state = live.fold(events)
    assert state["current"][100]["stage"] == "warmup done; measuring"


def test_fold_interleaved_multi_rank_writers(monkeypatch, tmp_path):
    """The dashboard's multi-process blind spot (ISSUE 14 satellite):
    two writer pids interleave their row lifecycles on one stream, one
    of them tears its tail mid-append, and a third batch arrives with
    out-of-order timestamps. The fold must keep the two in-flight rows
    separate, count every completion, fold the skew lanes, and produce
    the SAME state incrementally as in one pass."""
    path = str(tmp_path / "live.jsonl")
    monkeypatch.setenv("DDLB_TPU_LIVE", path)
    a, b = 111, 222  # two runner pids sharing the stream
    interleaved = [
        {"ts": 1.0, "pid": a, "kind": "sweep_start", "total": 2},
        {"ts": 1.1, "pid": b, "kind": "sweep_start", "total": 1},
        {"ts": 2.0, "pid": a, "kind": "row_start", "impl": "x_0",
         "primitive": "tp_columnwise", "m": 1, "n": 1, "k": 1},
        {"ts": 2.1, "pid": b, "kind": "row_start", "impl": "y_0",
         "primitive": "dp_allreduce", "m": 2, "n": 2, "k": 2},
        {"ts": 2.5, "pid": a, "kind": "row_phase", "impl": "x_0",
         "stage": "warmup done; measuring"},
        {"ts": 2.6, "pid": b, "kind": "row_phase", "impl": "y_0",
         "stage": "setup begin"},
        {"ts": 3.0, "pid": a, "kind": "row_done", "impl": "x_0",
         "median_ms": 1.5, "straggler_rank": 1, "skew_enter_s": 0.4,
         "straggler_frac": 0.8},
    ]
    with open(path, "w", encoding="utf-8") as f:
        for event in interleaved:
            f.write(json.dumps(event) + "\n")
        # writer b dies mid-append: a torn, newline-less tail
        f.write('{"ts": 3.1, "pid": 222, "kind": "row_do')
    events, offset = live.read_events(path)
    state = live.fold(events)
    # the torn line is deferred, so b's row is still in flight with its
    # OWN phase — never cross-attached to a's row
    assert state["totals"]["total"] == 3
    assert state["totals"]["done"] == 1
    assert set(state["current"]) == {b}
    assert state["current"][b]["stage"] == "setup begin"
    assert state["lanes"]["1"]["straggler_rows"] == 1
    assert state["lanes"]["1"]["skew_s"] == pytest.approx(0.4)
    assert state["lanes"]["1"]["last_frac"] == pytest.approx(0.8)

    # writer b recovers and completes; events land with out-of-order
    # timestamps (cross-process appends interleave arbitrarily)
    tail = [
        {"ts": 4.0, "pid": b, "kind": "row_done", "impl": "y_0",
         "median_ms": 9.9, "straggler_rank": 0, "skew_enter_s": 0.1,
         "straggler_frac": 0.3},
        {"ts": 3.5, "pid": a, "kind": "sweep_done", "rows": 2},
    ]
    with open(path, "a", encoding="utf-8") as f:
        f.write("\n")  # the torn line stays torn (skipped as corrupt)
        for event in tail:
            f.write(json.dumps(event) + "\n")
    more, _ = live.read_events(path, offset)
    state = live.fold(more, state)
    assert state["totals"]["done"] == 2
    assert state["current"] == {}
    assert state["sweep_done"] is True
    assert state["last_ts"] == 4.0  # out-of-order ts never regresses it
    assert set(state["lanes"]) == {"0", "1"}

    # one-pass fold over the full file equals the incremental fold
    all_events, _ = live.read_events(path)
    assert live.fold(all_events) == state


def test_live_tolerates_torn_multibyte_tail(monkeypatch, tmp_path):
    path = _seed_live(monkeypatch, tmp_path)
    with open(path, "ab") as f:
        f.write('{"kind": "row_done", "error": "x —'.encode()[:-1])
    events, offset = live.read_events(path)  # must not raise
    assert offset < os.path.getsize(path)
    assert all("—" not in str(e.get("error", "")) for e in events)


def test_live_partial_tail_line_deferred(monkeypatch, tmp_path):
    path = _seed_live(monkeypatch, tmp_path)
    with open(path, "a") as f:
        f.write('{"kind": "row_done", "half')  # no newline: in-flight
    events, offset = live.read_events(path)
    assert all(e["kind"] != "row_done" or "half" not in str(e)
               for e in events)
    assert offset < os.path.getsize(path)  # the partial line waits


def test_dashboard_text_and_html(monkeypatch, tmp_path, capsys):
    path = _seed_live(monkeypatch, tmp_path)
    dash = _load_script("sweep_dash")
    state = live.fold(live.read_events(path)[0])
    text = dash.render_text(state)
    assert "1/3 rows done" not in text  # 2 done of 3
    assert "2/3 rows done" in text
    assert "parked 1" in text
    assert "overlap_0" in text and "0.700" in text
    assert "pid 999" in text and "dead" in text
    html_doc = dash.render_html(state, source=path)
    assert html_doc.startswith("<!DOCTYPE html>")
    assert "2/3" in html_doc and "quarantined" in html_doc
    assert "&#10007; error" in html_doc  # status = icon + label
    # the CLI: --once prints a frame; --html writes the snapshot
    assert dash.main([path, "--once"]) == 0
    assert "rows done" in capsys.readouterr().out
    snap = tmp_path / "snap.html"
    assert dash.main([path, "--html", str(snap)]) == 0
    assert snap.stat().st_size > 500


def test_dashboard_missing_stream(tmp_path, capsys):
    dash = _load_script("sweep_dash")
    assert dash.main([str(tmp_path / "absent.jsonl"), "--once"]) == 1
    assert dash.main([]) == 2 if not os.environ.get("DDLB_TPU_LIVE") else True


def test_pooled_sweep_feeds_live_stream(tmp_path, monkeypatch):
    """The dashboard's acceptance surface: a POOLED sweep (one warm
    child) posts worker lifecycle + row completions into the stream."""
    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    path = str(tmp_path / "live.jsonl")
    monkeypatch.setenv("DDLB_TPU_LIVE", path)
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise", 64, 32, 64,
        implementations={
            "compute_only_0": {
                "implementation": "compute_only", "size": "unsharded",
            },
            "compute_only_1": {
                "implementation": "compute_only", "size": "unsharded",
            },
        },
        dtype="float32", num_iterations=2, num_warmups=1, validate=False,
        isolation="subprocess", progress=False, worker_pool=True,
    )
    df = runner.run()
    assert len(df) == 2
    events, _ = live.read_events(path)
    kinds = [e["kind"] for e in events]
    assert kinds.count("row_done") == 2
    assert "sweep_start" in kinds and "sweep_done" in kinds
    assert "worker_spawn" in kinds and "worker_ready" in kinds
    # phase marks arrive from the CHILD process (env inherited at spawn)
    child_pids = {e["pid"] for e in events if e["kind"] == "row_phase"}
    assert child_pids and child_pids != {os.getpid()}
    state = live.fold(events)
    assert state["totals"]["done"] == 2
    assert state["sweep_done"] is True
    ready = [w for w in state["workers"].values()
             if w.get("setup_s") is not None]
    assert ready and ready[0]["setup_s"] > 0


# ---------------------------------------------------------------------------
# xprof --json span-join contract (ISSUE 6 satellite)
# ---------------------------------------------------------------------------


def test_xprof_json_empty_doc_is_well_formed(tmp_path, monkeypatch, capsys):
    """TF absent: --json must still emit the FULL document shape, empty,
    so observatory consumers never special-case the failure."""
    import builtins

    real_import = builtins.__import__

    def _no_tf(name, *a, **kw):
        if name.startswith("tensorflow"):
            raise ImportError("No module named 'tensorflow'")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", _no_tf)
    xp = _load_script("xprof_summary")
    assert xp.main(["x", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["line"] is None
    assert doc["ops"] == []
    assert doc["window_ns"] is None
    assert doc["device_busy_ms"] == 0.0
    assert doc["event_count"] == 0
    assert "XplaneUnavailable" in doc["error"]


# ---------------------------------------------------------------------------
# bench gate history layer
# ---------------------------------------------------------------------------


def test_bench_gate_uses_history_median(tmp_path, monkeypatch, capsys):
    spec = importlib.util.spec_from_file_location(
        "bench_gate_test", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setenv("DDLB_TPU_HISTORY", str(tmp_path / "hist"))
    head = {
        "metric": "tp_columnwise_gemm_pallas_8192x8192x8192_bf16",
        "world_size": 1, "roofline_frac": 0.80,
        "platform": "tpu", "valid": True,
    }
    # one outlier capture among five: the MEDIAN baseline (0.80) must
    # win over the last-capture rule (which would compare against 0.30
    # and see no regression)
    for frac, run in ((0.80, "r1"), (0.81, "r2"), (0.79, "r3"),
                      (0.80, "r4"), (0.30, "r5")):
        store.bank_row(dict(head, roofline_frac=frac), kind="bench",
                       run=run)
    # an INVALID capture and a CPU-fallback capture also land in the
    # bank (_bank_headline is unconditional on the success path) but
    # must never shape the baseline — same gating as the cache layer
    store.bank_row(dict(head, roofline_frac=0.99, valid=False),
                   kind="bench", run="bad1")
    store.bank_row(dict(head, roofline_frac=0.01, platform="cpu"),
                   kind="bench", run="bad2")
    fresh = dict(head, roofline_frac=0.55)
    bench._check_roofline_regression(fresh)
    assert fresh.get("roofline_regression") is True
    assert fresh["roofline_frac_prev"] == pytest.approx(0.80)
    assert "history median" in capsys.readouterr().err
    # within tolerance of the median: clean
    ok = dict(head, roofline_frac=0.75)
    bench._check_roofline_regression(ok)
    assert "roofline_regression" not in ok
