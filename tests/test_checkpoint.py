"""Model-layer checkpoint/resume: the sharded train-state round-trip.

Contract: save -> restore -> continue training reproduces uninterrupted
training bitwise (same compiled step, same operands), including across a
mesh-shape change (orbax reshards on read).
"""

import numpy as np
import pytest

import jax

from ddlb_tpu.models.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from ddlb_tpu.models.transformer import (
    TransformerConfig,
    example_tokens,
    init_params,
    make_train_step,
)

CFG = TransformerConfig(
    vocab=64, d_model=32, n_heads=4, d_ff=64, layers_per_stage=1,
    microbatches=2,
)


def _setup(dp, tp, pp):
    mesh = jax.make_mesh((dp, tp, pp), ("dp", "tp", "pp"))
    train_step, init_opt, shardings = make_train_step(mesh, CFG)
    params = init_params(CFG, pp, n_experts=tp)
    params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    opt_state = init_opt(params)
    tokens, targets = example_tokens(dp * CFG.microbatches, 8 * tp, CFG.vocab)
    tokens = jax.device_put(tokens, shardings["data"])
    targets = jax.device_put(targets, shardings["data"])
    return train_step, params, opt_state, tokens, targets


def test_round_trip_continues_training_bitwise(tmp_path):
    step_fn, params, opt, tok, tgt = _setup(2, 2, 2)
    losses = []
    for i in range(4):
        if i == 2:
            save_checkpoint(str(tmp_path), i, params, opt)
        params, opt, loss = step_fn(params, opt, tok, tgt)
        losses.append(float(loss))

    # resume from step 2 on a FRESH state skeleton and replay steps 2-3
    step_fn2, params2, opt2, tok2, tgt2 = _setup(2, 2, 2)
    assert latest_step(str(tmp_path)) == 2
    params2, opt2 = restore_checkpoint(
        str(tmp_path), 2, {"params": params2, "opt_state": opt2}
    )
    resumed = []
    for _ in range(2):
        params2, opt2, loss = step_fn2(params2, opt2, tok2, tgt2)
        resumed.append(float(loss))
    assert resumed == losses[2:], (resumed, losses[2:])


def test_restore_onto_different_mesh(tmp_path):
    """The same checkpoint restores onto a different mesh — here a
    4-device (1, 2, 2) sub-mesh of the 8-device save-time (2, 2, 2)
    topology (tp/pp stay fixed: they shape the param stacks) — and the
    values survive orbax's reshard-on-read bit-for-bit."""
    from jax.sharding import Mesh

    from ddlb_tpu.models.transformer import param_specs

    step_fn, params, opt, tok, tgt = _setup(2, 2, 2)
    params, opt, _ = step_fn(params, opt, tok, tgt)
    save_checkpoint(str(tmp_path), 1, params, opt)

    mesh2 = Mesh(
        np.array(jax.devices()[:4]).reshape(1, 2, 2), ("dp", "tp", "pp")
    )
    _, init_opt, _ = make_train_step(mesh2, CFG)
    from jax.sharding import NamedSharding

    specs = param_specs(CFG)
    params2 = init_params(CFG, 2, n_experts=2)
    params2 = {
        k: jax.device_put(v, NamedSharding(mesh2, specs[k]))
        for k, v in params2.items()
    }
    opt2 = init_opt(params2)
    params2, opt2 = restore_checkpoint(
        str(tmp_path), 1, {"params": params2, "opt_state": opt2}
    )
    for name in params:
        assert np.array_equal(
            np.asarray(params[name]), np.asarray(params2[name])
        ), name
        assert len(params2[name].sharding.mesh.devices.flat) == 4
    for a, b in zip(
        jax.tree_util.tree_leaves(opt), jax.tree_util.tree_leaves(opt2)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_params_only_restore(tmp_path):
    step_fn, params, opt, tok, tgt = _setup(2, 2, 2)
    save_checkpoint(str(tmp_path), 0, params)
    restored, opt_none = restore_checkpoint(
        str(tmp_path), 0, {"params": params}
    )
    assert opt_none is None
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(restored)
    for a, b in zip(flat_a, flat_b):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_latest_step_empty(tmp_path):
    assert latest_step(str(tmp_path / "nope")) is None
    assert latest_step(str(tmp_path)) is None
