"""The static HBM budget model the measurement batches gate on.

The judgments pinned here are the round-4 postmortem turned arithmetic
(VERDICT r4 #2): the ctx=4096 OOM cliff (einsum/full-matrix scores), the
q-chunked oracle making ctx=4096 fit, and the ctx=64k bf16-MHA config
needing batch=4 on a 16-GB v5e — so the next live session right-sizes
up front instead of burning worker timeouts rediscovering them.
"""

from ddlb_tpu.utils.hbm_budget import (
    DEFAULT_LIMIT,
    GiB,
    decode_budget,
    fit_batch,
)

# the serving-table shape (scripts/measure_r3_hw.py)
SHAPE = dict(d_model=2048, d_ff=8192, vocab=16384, n_heads=16, layers=1)


def test_component_arithmetic_hand_checked():
    r = decode_budget(ctx=4096, batch=8, phase="decode", **SHAPE)
    # untied embed+head 2*V*D*2 + (q/o + k/v) 4*D^2*2 + MLP 2*D*F*2
    assert r.components["weights"] == (
        2 * 16384 * 2048 * 2 + 4 * 2048 * 2048 * 2 + 2 * 2048 * 8192 * 2
    )
    # bf16 K+V over ctx+1 positions: 2 (K,V) * B * S * D * 2 bytes
    assert r.components["kv_cache"] == 2 * 8 * 4097 * 16 * 128 * 2
    assert r.fits  # ~3.7 GiB with the q-chunked oracle


def test_int8_gqa_cache_shrink():
    mha = decode_budget(ctx=8192, batch=8, phase="decode", **SHAPE)
    lever = decode_budget(
        ctx=8192, batch=8, phase="decode", kv_cache="int8",
        n_kv_heads=4, **SHAPE,
    )
    # int8 quarters-heads cache = bf16 MHA cache / 8, plus f32 scales
    assert lever.components["kv_cache"] == (
        mha.components["kv_cache"] / 8 + 2 * 8 * 8193 * 4 * 4
    )


def test_einsum_prefill_cliff_at_4k():
    # two f32 [B, H, S, S] score copies: the observed ~4k einsum OOM
    # cliff (and the shape of the pre-fix full-matrix oracle OOM)
    r = decode_budget(
        ctx=4096, batch=8, phase="decode", attn_kernel="einsum", **SHAPE
    )
    assert not r.fits
    assert r.components["act_peak"] > 17e9


def test_64k_bf16_mha_needs_batch_4():
    # [B, S, F]-dominated prefill live set + 4.3-GiB cache: B=8 cannot
    # fit even unvalidated; B=4 fits WITH the q-chunked oracle
    r8 = decode_budget(
        ctx=65536, batch=8, phase="decode", validate=False, **SHAPE
    )
    assert not r8.fits
    b, rep = fit_batch(
        preferred_batch=8, ctx=65536, phase="decode", validate=True,
        **SHAPE,
    )
    assert b == 4 and rep.fits


def test_32k_keeps_batch_8_validated():
    b, rep = fit_batch(
        preferred_batch=8, ctx=32768, phase="decode", validate=True,
        **SHAPE,
    )
    assert b == 8 and rep.fits


def test_64k_int8_gqa_fits_b8_unvalidated():
    # the fast-decode levers are exactly what buys headroom at 64k
    r = decode_budget(
        ctx=65536, batch=8, phase="decode", validate=False,
        kv_cache="int8", n_kv_heads=4, **SHAPE,
    )
    assert r.fits


def test_serve_einsum_scores_still_counted():
    # the serve admission pass is 1-row, but its einsum score matrix is
    # still two f32 [1, H, S, S] copies — 8 GiB at ctx=8192 (counted,
    # still fits) and 32 GiB at 16k (the gate must reject); flash
    # admission at the same shapes stays ~flat
    e8 = decode_budget(
        ctx=8192, batch=8, phase="serve", attn_kernel="einsum", **SHAPE
    )
    assert e8.components["act_peak"] > 8e9 and e8.fits
    e16 = decode_budget(
        ctx=16384, batch=8, phase="serve", attn_kernel="einsum", **SHAPE
    )
    assert not e16.fits
    flash = decode_budget(
        ctx=16384, batch=8, phase="serve", attn_kernel="flash", **SHAPE
    )
    assert flash.fits


def test_speculate_counts_draft():
    base = decode_budget(
        ctx=2048, batch=8, phase="generate", n_new=64, layers=2,
        **{k: v for k, v in SHAPE.items() if k != "layers"},
    )
    spec = decode_budget(
        ctx=2048, batch=8, phase="speculate", n_new=64, spec_k=4,
        draft_layers=1, layers=2,
        **{k: v for k, v in SHAPE.items() if k != "layers"},
    )
    assert spec.components["weights"] > base.components["weights"]
    assert spec.components["kv_cache"] > base.components["kv_cache"]


def test_report_line_is_printable():
    r = decode_budget(ctx=2048, batch=8, phase="decode", **SHAPE)
    assert r.limit == DEFAULT_LIMIT
    line = r.line()
    assert "total" in line and "GiB" in line


def test_speculate_draft_counts_full_embed_and_head():
    """ADVICE r5: the draft is a FULL model (spmd.py builds it via
    init_params) — its own embed + LM head plus draft_layers decoder
    layers, counted explicitly. The old total-scaling form credited only
    draft_layers/L of an embed+head (~67 MB short at this shape)."""
    kw = {k: v for k, v in SHAPE.items() if k != "layers"}
    base = decode_budget(
        ctx=2048, batch=8, phase="generate", n_new=64, layers=2, **kw
    )
    spec = decode_budget(
        ctx=2048, batch=8, phase="speculate", n_new=64, spec_k=4,
        draft_layers=1, layers=2, **kw,
    )
    embed_head = 2 * 16384 * 2048 * 2
    per_layer = 4 * 2048 * 2048 * 2 + 2 * 2048 * 8192 * 2
    assert spec.components["weights"] == (
        base.components["weights"] + embed_head + per_layer
    )
    # the fixed arithmetic moves the estimate UP (the OOM direction)
    scaled = base.components["weights"] * (2 + 1) / 2
    assert spec.components["weights"] - scaled == embed_head / 2
