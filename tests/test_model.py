"""Flagship TP-MLP model: shard_map block vs single-device forward, and the
full GSPMD train step on a (dp, tp) mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def test_mlp_block_matches_reference():
    from ddlb_tpu.models.tp_mlp import init_params, mlp_block, mlp_forward

    mesh = jax.make_mesh((8,), ("tp",))
    d_model, d_ff, seq = 64, 128, 64
    params = init_params(d_model, d_ff, dtype=jnp.float32)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(0, 1, (seq, d_model)), dtype=jnp.float32)

    block = jax.jit(mlp_block(mesh))
    y = block(x, params["w1"], params["w2"])
    y_ref = mlp_forward(x, params["w1"], params["w2"])
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(y_ref), rtol=0, atol=1e-4
    )


@pytest.mark.parametrize("dp,tp", [(2, 4), (1, 8), (4, 2)])
def test_train_step_runs_and_descends(dp, tp):
    from ddlb_tpu.models.tp_mlp import (
        example_batch,
        init_params,
        make_train_step,
    )

    mesh = jax.make_mesh((dp, tp), ("dp", "tp"))
    d_model, d_ff = 32, 64
    train_step, init_opt, (x_sh, w1_sh, w2_sh) = make_train_step(
        mesh, learning_rate=0.1
    )
    params = init_params(d_model, d_ff, dtype=jnp.float32)
    params = {
        "w1": jax.device_put(params["w1"], w1_sh),
        "w2": jax.device_put(params["w2"], w2_sh),
    }
    opt_state = init_opt(params)
    x, t = example_batch(2 * dp, 8 * tp, d_model, dtype=jnp.float32)
    x = jax.device_put(x, x_sh)
    t = jax.device_put(t, x_sh)

    losses = []
    for _ in range(5):
        params, opt_state, loss = train_step(params, opt_state, x, t)
        x, t = jax.device_put(x, x_sh), jax.device_put(t, x_sh)  # donated
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # SGD descends on the toy objective


def test_graft_entry_single_chip():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == ()  # forward+loss on the flagship transformer
    assert np.isfinite(float(out))


@pytest.mark.slow  # full MoE train-step compile (flash kernels run
# INTERPRETED on the CPU sim) x two model variants on an 8-device mesh:
# several minutes of XLA CPU compile — unlocked by the transformer
# shard_map_compat migration (ISSUE 15), but far too heavy for the
# tier-1 870 s budget
def test_graft_dryrun_multichip():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
