"""TPColumnwise implementations validate on the 8-device CPU mesh.

Pytest re-expression of the reference's runtime validation design
(/root/reference/ddlb/primitives/TPColumnwise/tp_columnwise.py:137-162):
every implementation x dtype x option on small shapes must match the
single-device product.
"""

import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 128, 64, 96


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("order", ["AG_before", "AG_after"])
def test_jax_spmd(dtype, order):
    cls = load_impl_class("tp_columnwise", "jax_spmd")
    impl = cls(M, N, K, dtype=dtype, order=order)
    result = impl.run()
    assert result.shape == (M, N)
    assert impl.validate(result)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_xla_gspmd(dtype):
    cls = load_impl_class("tp_columnwise", "xla_gspmd")
    impl = cls(M, N, K, dtype=dtype)
    result = impl.run()
    assert result.shape == (M, N)
    assert impl.validate(result)


@pytest.mark.parametrize("size", ["sharded", "unsharded"])
def test_compute_only(size):
    cls = load_impl_class("tp_columnwise", "compute_only")
    impl = cls(M, N, K, dtype="float32", size=size)
    result = impl.run()
    expected_rows = M if size == "unsharded" else M // impl.num_partitions
    assert result.shape == (expected_rows, N)
    assert impl.validate(result)


def test_int_dtype_exact():
    cls = load_impl_class("tp_columnwise", "jax_spmd")
    impl = cls(M, N, K, dtype="int32")
    assert impl.validate(impl.run())


def test_shape_constraint():
    cls = load_impl_class("tp_columnwise", "jax_spmd")
    with pytest.raises(ValueError, match="divisible"):
        cls(M + 1, N, K)


def test_deterministic_seeding():
    cls = load_impl_class("tp_columnwise", "jax_spmd")
    a1 = cls(M, N, K, seed=7)._host_operands()[0]
    a2 = cls(M, N, K, seed=7)._host_operands()[0]
    a3 = cls(M, N, K, seed=8)._host_operands()[0]
    assert (a1 == a2).all()
    assert not (a1 == a3).all()


def test_bad_option_rejected():
    cls = load_impl_class("tp_columnwise", "jax_spmd")
    with pytest.raises(ValueError, match="not in allowed values"):
        cls(M, N, K, order="sideways")
