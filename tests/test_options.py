"""OptionsManager / EnvVarGuard behavior.

Codifies the reference's runtime option-validation semantics
(/root/reference/ddlb/primitives/TPColumnwise/utils.py:34-132) as tests the
reference never had (SURVEY.md section 4).
"""

import os

import pytest

from ddlb_tpu.options import BENCHMARK_OPTIONS, EnvVarGuard, OptionsManager


def test_defaults_returned_without_overrides():
    om = OptionsManager({"order": "AG_before", "s": 8})
    assert om.parse({}) == {"order": "AG_before", "s": 8}


def test_override_and_get():
    om = OptionsManager({"order": "AG_before"}, {"order": ["AG_before", "AG_after"]})
    opts = om.parse({"order": "AG_after"})
    assert opts["order"] == "AG_after"
    assert om.get("order") == "AG_after"
    assert om["order"] == "AG_after"
    assert "order" in om


def test_unknown_option_rejected():
    om = OptionsManager({"order": "AG_before"})
    with pytest.raises(ValueError, match="Unknown option"):
        om.parse({"oops": 1})


def test_disallowed_value_rejected():
    om = OptionsManager({"order": "AG_before"}, {"order": ["AG_before", "AG_after"]})
    with pytest.raises(ValueError, match="not in allowed values"):
        om.parse({"order": "bogus"})


def test_numeric_range():
    om = OptionsManager({"s": 8}, {"s": (1, None)})
    assert om.parse({"s": 4})["s"] == 4
    with pytest.raises(ValueError, match="outside allowed range"):
        om.parse({"s": 0})


def test_range_rejects_non_numeric():
    om = OptionsManager({"s": 8}, {"s": (1, None)})
    with pytest.raises(ValueError, match="expects a number"):
        om.parse({"s": "four"})


def test_benchmark_options_filtered():
    om = OptionsManager({"order": "AG_before"})
    opts = om.parse({"implementation": "whatever"})
    assert "implementation" not in opts
    assert "implementation" in BENCHMARK_OPTIONS


def test_env_var_guard_restores():
    os.environ["DDLB_TPU_TEST_GUARD"] = "before"
    with EnvVarGuard({"DDLB_TPU_TEST_GUARD": "inside", "DDLB_TPU_TEST_NEW": "x"}):
        assert os.environ["DDLB_TPU_TEST_GUARD"] == "inside"
        assert os.environ["DDLB_TPU_TEST_NEW"] == "x"
    assert os.environ["DDLB_TPU_TEST_GUARD"] == "before"
    assert "DDLB_TPU_TEST_NEW" not in os.environ
    del os.environ["DDLB_TPU_TEST_GUARD"]
