"""CLI parsing and config expansion (reference cli/benchmark.py:14-118).

The reference has no tests for any of this (SURVEY.md section 4); these
codify the spec-parsing and cartesian-expansion semantics.
"""

import pytest

from ddlb_tpu.cli.benchmark import (
    _infer_scalar,
    assign_impl_ids,
    generate_config_combinations,
    parse_impl_spec,
)


def test_infer_scalar():
    assert _infer_scalar("true") is True
    assert _infer_scalar("False") is False
    assert _infer_scalar("42") == 42
    assert _infer_scalar("2.5") == 2.5
    assert _infer_scalar("AG_before") == "AG_before"


def test_parse_impl_spec():
    name, opts = parse_impl_spec("overlap;algorithm=coll_pipeline,p2p_pipeline;s=4")
    assert name == "overlap"
    assert opts == {"algorithm": ["coll_pipeline", "p2p_pipeline"], "s": [4]}


def test_parse_impl_spec_no_options():
    name, opts = parse_impl_spec("jax_spmd")
    assert name == "jax_spmd"
    assert opts == {}


def test_parse_impl_spec_bad_option():
    with pytest.raises(ValueError, match="expected key=value"):
        parse_impl_spec("overlap;algorithm")


def test_generate_config_combinations():
    expanded = generate_config_combinations(
        {
            "overlap": [
                {"algorithm": ["coll_pipeline"], "s": [2, 4]},
                {"algorithm": ["p2p_pipeline"]},
            ],
            "jax_spmd": [{}],
        }
    )
    assert len(expanded["overlap"]) == 3
    assert {"algorithm": "coll_pipeline", "s": 2} in expanded["overlap"]
    assert {"algorithm": "coll_pipeline", "s": 4} in expanded["overlap"]
    assert {"algorithm": "p2p_pipeline"} in expanded["overlap"]
    assert expanded["jax_spmd"] == [{}]


def test_assign_impl_ids():
    impl_map = assign_impl_ids(
        {"jax_spmd": [{"order": "AG_before"}, {"order": "AG_after"}]}
    )
    assert set(impl_map) == {"jax_spmd_0", "jax_spmd_1"}
    assert impl_map["jax_spmd_1"] == {
        "order": "AG_after",
        "implementation": "jax_spmd",
    }
