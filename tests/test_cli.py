"""CLI parsing and config expansion (reference cli/benchmark.py:14-118).

The reference has no tests for any of this (SURVEY.md section 4); these
codify the spec-parsing and cartesian-expansion semantics.
"""

import pytest

from ddlb_tpu.cli.benchmark import (
    _infer_scalar,
    assign_impl_ids,
    generate_config_combinations,
    parse_impl_spec,
)


def test_infer_scalar():
    assert _infer_scalar("true") is True
    assert _infer_scalar("False") is False
    assert _infer_scalar("42") == 42
    assert _infer_scalar("2.5") == 2.5
    assert _infer_scalar("AG_before") == "AG_before"


def test_parse_impl_spec():
    name, opts = parse_impl_spec("overlap;algorithm=coll_pipeline,p2p_pipeline;s=4")
    assert name == "overlap"
    assert opts == {"algorithm": ["coll_pipeline", "p2p_pipeline"], "s": [4]}


def test_parse_impl_spec_no_options():
    name, opts = parse_impl_spec("jax_spmd")
    assert name == "jax_spmd"
    assert opts == {}


def test_parse_impl_spec_bad_option():
    with pytest.raises(ValueError, match="expected key=value"):
        parse_impl_spec("overlap;algorithm")


def test_generate_config_combinations():
    expanded = generate_config_combinations(
        {
            "overlap": [
                {"algorithm": ["coll_pipeline"], "s": [2, 4]},
                {"algorithm": ["p2p_pipeline"]},
            ],
            "jax_spmd": [{}],
        }
    )
    assert len(expanded["overlap"]) == 3
    assert {"algorithm": "coll_pipeline", "s": 2} in expanded["overlap"]
    assert {"algorithm": "coll_pipeline", "s": 4} in expanded["overlap"]
    assert {"algorithm": "p2p_pipeline"} in expanded["overlap"]
    assert expanded["jax_spmd"] == [{}]


def test_assign_impl_ids():
    impl_map = assign_impl_ids(
        {"jax_spmd": [{"order": "AG_before"}, {"order": "AG_after"}]}
    )
    assert set(impl_map) == {"jax_spmd_0", "jax_spmd_1"}
    assert impl_map["jax_spmd_1"] == {
        "order": "AG_after",
        "implementation": "jax_spmd",
    }


def test_shipped_configs_parse_and_expand():
    """Every scripts/config_*.json (the JSON list format) normalizes into
    the canonical dict form and expands to at least one impl_id —
    regression for the list-format crash."""
    import glob
    import os

    from ddlb_tpu.cli.benchmark import _normalize

    scripts_dir = os.path.join(os.path.dirname(__file__), "..", "scripts")
    paths = sorted(glob.glob(os.path.join(scripts_dir, "config*.json")))
    assert paths, "no shipped configs found"
    for path in paths:
        import json

        with open(path) as f:
            cfg = _normalize(json.load(f))
        assert isinstance(cfg["implementations"], dict), path
        impl_map = assign_impl_ids(
            generate_config_combinations(cfg["implementations"])
        )
        assert impl_map, path
        for spec in impl_map.values():
            assert "implementation" in spec


def test_list_format_config_runs_end_to_end(tmp_path):
    from ddlb_tpu.cli.benchmark import run_benchmark

    cfg = {
        "benchmark": {
            "primitive": "ep_alltoall",
            "m": [128], "n": [32], "k": [64],
            "dtype": "float32",
            "num_iterations": 1,
            "num_warmups": 0,
            "progress": False,
            "output_csv": str(tmp_path / "r.csv"),
            "implementations": [
                {"name": "jax_spmd"},
                {"name": "overlap", "algorithm": "coll_pipeline", "s": [1, 2]},
            ],
        }
    }
    df = run_benchmark(cfg)
    assert list(df["implementation"]) == ["jax_spmd_0", "overlap_0", "overlap_1"]
    assert df["valid"].all()
