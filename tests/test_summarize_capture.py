"""The post-capture summarizer: banked rows become markdown tables.

The watcher runs ``summarize_capture.py`` inside every commit_capture,
so a relay window that closes minutes before the round buzzer still
commits judge-readable tables. What matters: it digests every family's
rows, keeps the collectives unit honest, surfaces the round-5
instrumentation (acceptance rate, serve stats, hbm peak), lists error
rows, and never crashes on partial/garbled input.
"""

import importlib.util
import json
import os

_spec = importlib.util.spec_from_file_location(
    "summarize_capture",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "summarize_capture.py",
    ),
)
sc = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(sc)


def _row(**kw):
    base = {
        "implementation": "spmd_hw", "base_implementation": "spmd",
        "primitive": "transformer_decode", "m": 8192, "n": 2048, "k": 8192,
        "dtype": "bfloat16", "median time (ms)": 1.234,
        "std time (ms)": 0.01, "Throughput (TFLOPS)": 12.5,
        "unit": "TFLOPS", "valid": True, "error": "",
        "option": "phase=decode;kv_cache=int8;n_kv_heads=4;batch=8",
    }
    base.update(kw)
    return base


def test_summarize_all_sections(tmp_path):
    rows = [
        _row(hbm_peak_gib=4.21),
        _row(
            option="phase=speculate;spec_k=4;batch=8",
            spec_accept_rate=0.71, spec_rounds=20, spec_proposals=70,
        ),
        _row(
            option="phase=serve;cache_layout=paged;page_pool_frac=0.5;batch=8",
            serve_occupancy=0.82, serve_prefix_hits=6,
            serve_admissions_deferred=3, serve_peak_pages=10,
            serve_pages_capacity=16,
        ),
        _row(
            primitive="transformer_step", option="mode=train;microbatches=4",
            **{"Throughput (TFLOPS)": 157.0},
        ),
        _row(
            primitive="tp_columnwise", base_implementation="quantized",
            option="kernel=pallas;quantize=static;block_m=1024",
            **{"Throughput (TFLOPS)": 375.2},
        ),
        _row(
            primitive="collectives", base_implementation="jax_spmd",
            option="op=all_gather", unit="GB/s",
            **{"Throughput (TFLOPS)": 93.0},
        ),
        _row(
            option="phase=decode;batch=8",
            error="JaxRuntimeError: RESOURCE_EXHAUSTED",
            **{"median time (ms)": float("nan"),
               "Throughput (TFLOPS)": float("nan")},
        ),
    ]
    src = tmp_path / "rows.jsonl"
    src.write_text(
        "\n".join(json.dumps(r) for r in rows) + "\ngarbage-line\n"
    )
    dst = tmp_path / "SUMMARY.md"
    assert sc.main(["x", str(src), str(dst)]) == 0
    text = dst.read_text()
    assert "7 rows banked; 7 distinct configs (6 measured, 1 errors" in text
    assert "a_r=0.710" in text
    assert "occ=0.820" in text and "pages=10/16" in text
    assert "hbm=4.21GiB" in text
    assert "93.0 GB/s" in text          # the honest unit rides through
    assert "kernel=pallas" in text      # tile-sweep options visible
    assert "RESOURCE_EXHAUSTED" in text  # error rows listed, not dropped


def test_retry_supersedes_stale_error_row(tmp_path):
    # attempt 1 OOMs, attempt 2 (the watcher's documented second full
    # try) measures the SAME config: the summary must show the latest
    # outcome once, not a contradictory error + measured pair. The two
    # rows' own 'option' strings DIFFER (error rows format only the
    # caller's overrides, measured rows the DEFAULT-merged set) — the
    # pairing works through hw_common's bank_key, the caller's config
    key = '{"m": 8192, "options": {"kv_cache": "int8"}}'
    rows = [
        _row(option="kv_cache=int8", error="RESOURCE_EXHAUSTED",
             bank_key=key, **{"median time (ms)": float("nan")}),
        _row(option="phase=decode;kv_cache=int8;n_new=32;batch=8",
             bank_key=key, **{"median time (ms)": 2.5}),
    ]
    src = tmp_path / "rows.jsonl"
    src.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    dst = tmp_path / "SUMMARY.md"
    assert sc.main(["x", str(src), str(dst)]) == 0
    text = dst.read_text()
    assert "2 rows banked; 1 distinct configs (1 measured, 0 errors" in text
    assert "RESOURCE_EXHAUSTED" not in text


def test_no_rows_is_a_noop(tmp_path):
    dst = tmp_path / "SUMMARY.md"
    assert sc.main(["x", str(tmp_path / "missing.jsonl"), str(dst)]) == 0
    assert not dst.exists()


def test_keyless_error_row_collapses_onto_its_retry(tmp_path):
    """Rows banked before bank_key existed pair through the normalized
    fallback: an earlier error row's override-only option dict is a
    subset of its retry's DEFAULT-merged dict, so the pair collapses to
    one config — while a different lever config at the same shape stays
    distinct (its extras are non-default values, not merged defaults)."""
    rows = [
        _row(option="kv_cache=int8", error="RESOURCE_EXHAUSTED",
             **{"median time (ms)": float("nan")}),
        _row(option="phase=decode;kv_cache=int8;n_new=32;batch=8",
             **{"median time (ms)": 2.5}),
        _row(option="phase=decode;kv_cache=bf16;n_new=32;batch=8",
             **{"median time (ms)": 3.5}),
    ]
    src = tmp_path / "rows.jsonl"
    src.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    dst = tmp_path / "SUMMARY.md"
    assert sc.main(["x", str(src), str(dst)]) == 0
    text = dst.read_text()
    assert "3 rows banked; 2 distinct configs (2 measured, 0 errors" in text
    assert "RESOURCE_EXHAUSTED" not in text


def test_keyless_late_error_row_never_steals_a_measured_config(tmp_path):
    """The override-only subset relation is ambiguous in the other
    direction — an error row AFTER a measured superset row could be a
    different config whose absent keys mean defaults — so it must stay
    its own entry (the append-only log only guarantees error-then-retry
    ordering for the same config)."""
    rows = [
        _row(option="phase=decode;kv_cache=int8;n_kv_heads=4;batch=8",
             **{"median time (ms)": 2.5}),
        _row(option="phase=decode;batch=8", error="RESOURCE_EXHAUSTED",
             **{"median time (ms)": float("nan")}),
    ]
    src = tmp_path / "rows.jsonl"
    src.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    dst = tmp_path / "SUMMARY.md"
    assert sc.main(["x", str(src), str(dst)]) == 0
    text = dst.read_text()
    assert "2 rows banked; 2 distinct configs (1 measured, 1 errors" in text
    assert "RESOURCE_EXHAUSTED" in text


def test_keyless_empty_override_error_row_stays_distinct(tmp_path):
    """An all-defaults error row ('-' option string) subset-matches every
    config in its group — too promiscuous to pair on, so it must stay
    its own entry rather than vanish into an arbitrary lever row."""
    rows = [
        _row(option="-", error="RESOURCE_EXHAUSTED",
             **{"median time (ms)": float("nan")}),
        _row(option="phase=decode;kv_cache=int8;batch=8",
             **{"median time (ms)": 2.5}),
    ]
    src = tmp_path / "rows.jsonl"
    src.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    dst = tmp_path / "SUMMARY.md"
    assert sc.main(["x", str(src), str(dst)]) == 0
    assert "2 distinct configs (1 measured, 1 errors" in dst.read_text()


def test_keyless_equal_string_retry_wins_over_subset_ambiguity(tmp_path):
    """An exact option-string match pairs unconditionally (last wins),
    even when an unrelated error row also subset-matches the retry —
    the equal match takes precedence over the subset heuristic."""
    rows = [
        _row(option="phase=decode;kv_cache=int8;batch=8",
             **{"median time (ms)": 9.9}),
        _row(option="kv_cache=int8", error="RESOURCE_EXHAUSTED",
             **{"median time (ms)": float("nan")}),
        _row(option="phase=decode;kv_cache=int8;batch=8",
             **{"median time (ms)": 2.5}),
    ]
    src = tmp_path / "rows.jsonl"
    src.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    dst = tmp_path / "SUMMARY.md"
    assert sc.main(["x", str(src), str(dst)]) == 0
    text = dst.read_text()
    # retry replaced its equal-string predecessor; the error row stays
    assert "3 rows banked; 2 distinct configs (1 measured, 1 errors" in text
    assert "2.500" in text and "9.900" not in text
