"""Test bootstrap: 8-device CPU simulation.

Runs the whole suite on a virtual 8-device host mesh
(``--xla_force_host_platform_device_count``) so every shard_map collective,
sweep and validation path is exercised without TPU hardware — the testing
capability SURVEY.md section 4 identifies as the reference's biggest gap.
Must execute before anything creates a JAX backend.
"""

import os

os.environ.setdefault("DDLB_TPU_SIM_DEVICES", "8")

from ddlb_tpu.runtime import enable_simulation  # noqa: E402

enable_simulation(int(os.environ["DDLB_TPU_SIM_DEVICES"]))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def runtime():
    from ddlb_tpu.runtime import Runtime

    return Runtime()
