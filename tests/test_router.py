"""Learned top-k MoE router: oracle parity, drops, aux loss, sweeps.

The routed path must reproduce the single-device oracle exactly (same
slab in, same dispatch buffer, same capacity — models/transformer.py
router helpers are shared verbatim), including when the capacity factor
forces overflow drops, and the router gate must receive gradients through
the combine weights and the load-balance aux term.
"""

import numpy as np
import pytest

import jax


def _setup(cfg_kwargs, batch=4, seq=16, pp=2, tp=2, dp=2):
    from ddlb_tpu.models.transformer import (
        TransformerConfig,
        example_tokens,
        init_params,
    )
    from ddlb_tpu.runtime import Runtime

    mesh = Runtime().mesh(("dp", "tp", "pp"), shape=(dp, tp, pp))
    cfg = TransformerConfig(
        vocab=64, d_model=32, n_heads=4, d_ff=64,
        microbatches=2, router="topk", **cfg_kwargs,
    )
    params = init_params(cfg, pp=pp, n_experts=tp)
    tokens, targets = example_tokens(batch, seq, cfg.vocab)
    return mesh, cfg, params, tokens, targets


def _sharded_loss_and_grads(mesh, cfg, params, tokens, targets):
    from ddlb_tpu.models.transformer import make_loss_fn

    loss_fn, sh = make_loss_fn(mesh, cfg)
    p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    tok = jax.device_put(tokens, sh["data"])
    tgt = jax.device_put(targets, sh["data"])
    return jax.jit(jax.value_and_grad(loss_fn))(p, tok, tgt)


class TestTopkOracleParity:
    def test_sharded_matches_oracle(self):
        from ddlb_tpu.models.transformer import reference_loss

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=2)
        )
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss, _ = _sharded_loss_and_grads(mesh, cfg, params, tokens, targets)
        assert abs(float(loss) - want) < 1e-5

    def test_overflow_drops_still_match_oracle(self):
        """capacity_factor=0.5 forces real drops; both paths must drop
        the SAME tokens (first-come slot priority) and stay equal."""
        from ddlb_tpu.models.transformer import (
            reference_loss,
            router_capacity,
        )

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1, capacity_factor=0.5)
        )
        # the capacity must actually bind for the test to mean anything
        assert router_capacity(32, 2, cfg.router_topk, 0.5) < 32
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss, _ = _sharded_loss_and_grads(mesh, cfg, params, tokens, targets)
        assert abs(float(loss) - want) < 1e-5

    def test_int8_mlp_kernel_matches_oracle(self):
        """Routed dispatch slabs (zero-padded rows included) through the
        int8 STE kernel keep oracle parity — per-token scales are
        row-local, so padding rows can't perturb real rows."""
        from ddlb_tpu.models.transformer import reference_loss

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1, mlp_kernel="int8")
        )
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss, _ = _sharded_loss_and_grads(mesh, cfg, params, tokens, targets)
        assert abs(float(loss) - want) < 1e-5

    def test_top1_switch_style(self):
        from ddlb_tpu.models.transformer import reference_loss

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1, router_topk=1)
        )
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss, _ = _sharded_loss_and_grads(mesh, cfg, params, tokens, targets)
        assert abs(float(loss) - want) < 1e-5


class TestRouterTraining:
    def test_gate_receives_gradients(self):
        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1)
        )
        _, grads = _sharded_loss_and_grads(mesh, cfg, params, tokens, targets)
        assert float(np.max(np.abs(np.asarray(grads["router"])))) > 0

    def test_aux_term_changes_loss(self):
        from dataclasses import replace

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1)
        )
        loss_with, _ = _sharded_loss_and_grads(
            mesh, cfg, params, tokens, targets
        )
        cfg0 = replace(cfg, router_aux=0.0)
        loss_without, _ = _sharded_loss_and_grads(
            mesh, cfg0, params, tokens, targets
        )
        # the Switch LB loss is >= 1 by Cauchy-Schwarz, so the gap is
        # at least router_aux
        assert float(loss_with) - float(loss_without) >= cfg.router_aux * 0.9

    @pytest.mark.slow  # two full-model autodiff compiles (value_and_grad
    # through the shard_mapped flagship PLUS the manual-vjp 1F1B build,
    # both with a learned topk router) — the single heaviest tier-1 test
    # (~35 s of XLA CPU compile), outside the 870 s budget; router
    # training coverage stays in-tier (test_training_reduces_loss,
    # test_gate_receives_gradients) and 1F1B-vs-autodiff parity is owned
    # by test_pp_schedules
    def test_1f1b_parity_with_topk(self):
        from ddlb_tpu.models.pipeline import make_loss_and_grads_1f1b
        from ddlb_tpu.models.transformer import make_loss_fn

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1), batch=8,
        )
        cfg = cfg.__class__(**{**cfg.__dict__, "microbatches": 4})
        loss_fn, sh = make_loss_fn(mesh, cfg)
        fn, _ = make_loss_and_grads_1f1b(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        tok = jax.device_put(tokens, sh["data"])
        tgt = jax.device_put(targets, sh["data"])
        lg, gg = jax.jit(jax.value_and_grad(loss_fn))(p, tok, tgt)
        lo, go = jax.jit(fn)(p, tok, tgt)
        assert abs(float(lg) - float(lo)) < 1e-6
        for k in gg:
            a = np.asarray(gg[k], np.float32)
            b = np.asarray(go[k], np.float32)
            rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-12)
            assert rel < 2e-3, f"grad '{k}': rel={rel:.3e}"

    def test_training_reduces_loss(self):
        from ddlb_tpu.models.transformer import make_train_step

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1)
        )
        step, init_opt, sh = make_train_step(mesh, cfg, donate=False)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        tok = jax.device_put(tokens, sh["data"])
        tgt = jax.device_put(targets, sh["data"])
        opt = init_opt(p)
        losses = []
        for _ in range(3):
            p, opt, loss = step(p, opt, tok, tgt)
            losses.append(float(jax.block_until_ready(loss)))
        assert losses[-1] < losses[0]


class TestRouterPlumbing:
    def test_decode_rejects_topk(self):
        from ddlb_tpu.models.decode import make_decode_fn
        from ddlb_tpu.models.transformer import TransformerConfig
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        cfg = TransformerConfig(router="topk")
        with pytest.raises(ValueError, match="block router"):
            make_decode_fn(mesh, cfg)

    def test_transformer_step_sweeps_router(self):
        from ddlb_tpu.benchmark import benchmark_worker

        for router in ("block", "topk"):
            row = benchmark_worker(
                {
                    "primitive": "transformer_step",
                    "impl_id": f"spmd_{router}",
                    "base_implementation": "spmd",
                    "options": {
                        "router": router, "batch": 4, "vocab": 64,
                        "n_heads": 4, "microbatches": 2,
                        "attn_kernel": "einsum",
                    },
                    "m": 16,
                    "n": 32,
                    "k": 64,
                    "dtype": "float32",
                    "num_iterations": 1,
                    "num_warmups": 1,
                    "validate": True,
                    "time_measurement_backend": "host_clock",
                    "barrier_at_each_iteration": False,
                }
            )
            assert row["error"] == "", router
            assert row["valid"] is True, router

    def test_unknown_router_rejected(self):
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            make_stage_fn,
        )

        cfg = TransformerConfig(router="hashed")
        with pytest.raises(ValueError, match="unknown router"):
            make_stage_fn(cfg, tp=2, interpret=True)


class TestExpertChoice:
    """Expert-choice routing (each expert picks its top-C tokens):
    balanced by construction, gather-dispatch, gate-weighted scatter
    combine; oracle reproduces the identical per-shard math."""

    def test_matches_oracle(self):
        from ddlb_tpu.models.transformer import reference_loss

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=2)
        )
        from dataclasses import replace as _rp
        cfg = _rp(cfg, router="expert_choice")
        from ddlb_tpu.models.transformer import init_params

        params = init_params(cfg, pp=2, n_experts=2)
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss, grads = _sharded_loss_and_grads(
            mesh, cfg, params, tokens, targets
        )
        assert abs(float(loss) - want) < 1e-5
        assert float(np.max(np.abs(np.asarray(grads["router"])))) > 0

    def test_low_capacity_leaves_tokens_unserved(self):
        """cf < 1: fewer expert slots than tokens — some tokens ride the
        residual stream; parity must hold through the drop."""
        from ddlb_tpu.models.transformer import (
            init_params,
            reference_loss,
            router_capacity,
        )

        mesh, cfg, params, tokens, targets = _setup(
            dict(layers_per_stage=1, capacity_factor=0.5)
        )
        from dataclasses import replace as _rp
        cfg = _rp(cfg, router="expert_choice")
        assert router_capacity(32, 2, 1, 0.5) * 2 < 32  # slots < tokens
        params = init_params(cfg, pp=2, n_experts=2)
        want = float(reference_loss(params, tokens, targets, cfg, tp=2, dp=2))
        loss, _ = _sharded_loss_and_grads(mesh, cfg, params, tokens, targets)
        assert abs(float(loss) - want) < 1e-5

    def test_sweeps_through_worker(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_ec",
                "base_implementation": "spmd",
                "options": {
                    "router": "expert_choice", "batch": 4, "vocab": 64,
                    "n_heads": 4, "microbatches": 2,
                    "attn_kernel": "einsum",
                },
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True
