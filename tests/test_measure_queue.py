"""The resumable hardware row queue (scripts/measure_queue.py).

What matters: it replays the UNION of the four superseded measure_r*
batch lists in value order, checkpoints after every row, resumes
mid-queue, parks deterministically failing rows after two attempts, and
the deprecated shims still answer.
"""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "measure_queue", os.path.join(REPO, "scripts", "measure_queue.py")
)
mq = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(mq)


def _ok_row(config):
    return {
        "median time (ms)": 1.0,
        "Throughput (TFLOPS)": 10.0,
        "valid": True,
        "error": "",
        "unit": "TFLOPS",
    }


def _error_row(config):
    return {
        "median time (ms)": float("nan"),
        "Throughput (TFLOPS)": float("nan"),
        "valid": False,
        "error": "RESOURCE_EXHAUSTED",
        "unit": "TFLOPS",
    }


def test_queue_is_the_union_in_value_order():
    q = mq.build_queue()
    sections = [e["section"] for e in q]
    # value order: first occurrence of each section matches the
    # verdict-demand ranking (serving table first, r2 leftovers last)
    first_seen = []
    for s in sections:
        if s not in first_seen:
            first_seen.append(s)
    assert first_seen == [
        "r3-serving", "r3-int8", "r4-mfu", "r4-parity", "r3-trace",
        "r3-sched", "r4-spec", "r4-decode", "r4-window", "r4-hbm",
        "r2-mlp", "r2-decode",
    ]
    # the union covers every family the four batch scripts measured
    prims = {e["primitive"] for e in q if e["kind"] == "row"}
    assert {
        "transformer_decode", "transformer_step", "tp_columnwise",
        "ep_alltoall", "cp_ring_attention", "collectives",
    } <= prims
    # checkpoint keys are unique (r2_remaining's rows deduped into r2)
    keys = [mq.entry_key(e) for e in q]
    assert len(keys) == len(set(keys))
    # the r2_remaining decode rows appear exactly once
    r2_decode = [
        e for e in q
        if e["kind"] == "row" and e["section"] == "r2-decode"
        and e["options"].get("phase") == "decode" and e["m"] == 4096
    ]
    assert len(r2_decode) == 2  # bf16 + int8_weights, once each
    # non-row work carried over: kernel parity + xprof digest
    actions = {e["action"] for e in q if e["kind"] == "action"}
    assert {"kernel_parity", "xprof_summary"} <= actions


def test_budget_gate_sizes_batches():
    q = mq.build_queue()
    serving = [
        e for e in q
        if e["section"] == "r3-serving"
        and e.get("options", {}).get("phase") == "decode"
    ]
    by_ctx = {}
    for e in serving:
        by_ctx.setdefault(e["m"], set()).add(e["options"]["batch"])
    # one batch per context (lever A/B rows stay comparable), and the
    # 64k context is right-sized down by the HBM budget model
    assert all(len(bs) == 1 for bs in by_ctx.values())
    assert by_ctx[2048] == {8}
    assert by_ctx[65536] == {4}


def test_checkpoint_after_every_row_and_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    state = tmp_path / "state.json"
    ran1 = []

    def run1(config):
        ran1.append(config["base_implementation"])
        return _ok_row(config)

    rc = mq.main(
        ["--state", str(state), "--limit", "3", "--only", "r3-serving"],
        run_fn=run1,
    )
    assert rc == 0
    assert len(ran1) == 3
    st = json.loads(state.read_text())
    assert sum(1 for v in st.values() if v["done"]) == 3

    # resume continues MID-QUEUE: the next pass runs different rows
    ran2 = []

    def run2(config):
        ran2.append(json.dumps(config["options"], sort_keys=True))
        return _ok_row(config)

    rc = mq.main(
        ["--state", str(state), "--limit", "3", "--only", "r3-serving"],
        run_fn=run2,
    )
    assert rc == 0
    assert len(ran2) == 3
    st2 = json.loads(state.read_text())
    assert sum(1 for v in st2.values() if v["done"]) == 6
    # the first pass's rows were skipped, not re-run
    done_labels = [v["label"] for v in st2.values() if v["done"]]
    assert len(set(done_labels)) == 6


def test_failed_rows_retry_then_park(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    state = tmp_path / "state.json"
    attempts = []

    def always_oom(config):
        attempts.append(1)
        return _error_row(config)

    args = ["--state", str(state), "--limit", "1", "--only", "r3-serving"]
    # a pass with failed rows exits nonzero: the watcher's CAPTURED gate
    # reads rc==0, and a clean exit here would end the capture before
    # the retry ever happened
    assert mq.main(args, run_fn=always_oom) == 1
    assert mq.main(args, run_fn=always_oom) == 1  # retry (attempt 2)
    assert mq.main(args, run_fn=always_oom) == 1  # parked: next row runs
    assert len(attempts) == 3  # 2 on the first row, 1 on the next
    st = json.loads(state.read_text())
    first = next(iter(st.values()))
    assert first["attempts"] == mq.MAX_ATTEMPTS and not first["done"]


def test_deterministic_failure_parks_immediately(tmp_path, monkeypatch):
    """ISSUE 4: a deterministic failure (the classifier's split) parks on
    its FIRST pass — with a truthful attempt count and the persisted
    reason — instead of burning a second capture window."""
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    state = tmp_path / "state.json"
    attempts = []

    def bad_option(config):
        attempts.append(1)
        row = _error_row(config)
        row["error"] = "ValueError: m=96 must be divisible by 8"
        return row

    args = ["--state", str(state), "--limit", "1", "--only", "r4-hbm"]
    assert mq.main(args, run_fn=bad_option) == 1
    st = json.loads(state.read_text())
    rec = next(iter(st.values()))
    assert rec["parked"] is True
    assert rec["attempts"] == 1  # truthful: one pass actually ran
    assert rec["error_class"] == "deterministic"
    assert "ValueError" in rec["error"]
    # the parked entry is skipped on the next pass (the NEXT row runs)
    assert mq.main(args, run_fn=bad_option) == 1
    assert len(attempts) == 2  # second call was the second r4-hbm row


def test_smoke_queue_runs_without_hardware(tmp_path, monkeypatch):
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    state = tmp_path / "state.json"
    ran = []

    def run(config):
        ran.append(config["primitive"])
        return _ok_row(config)

    assert mq.main(["--smoke", "--state", str(state)], run_fn=run) == 0
    assert ran == ["tp_columnwise"]


def test_retired_shims_exit_with_pointer(tmp_path):
    """The measure_r* entry points are retired: each exits non-zero with
    a pointer to the queue command that replaced it (no forwarding, no
    backend touch — an old runbook gets an actionable message, never a
    silent half-run)."""
    for script, section in (
        ("measure_r2_hw.py", "r2"),
        ("measure_r2_remaining.py", "r2"),
        ("measure_r3_hw.py", "r3"),
        ("measure_r4_hw.py", "r4"),
    ):
        out = subprocess.run(
            [sys.executable, os.path.join("scripts", script)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode != 0
        combined = out.stdout + out.stderr
        assert "retired" in combined
        assert f"measure_queue.py --only {section}" in combined


def test_parked_only_failures_converge_to_rc_zero(tmp_path, monkeypatch):
    """Once every failure is parked, a drain pass runs nothing and exits
    0 — the watcher's CAPTURED gate closes on the converged state."""
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    state = tmp_path / "state.json"

    def always_oom(config):
        return _error_row(config)

    args = ["--state", str(state), "--limit", "1", "--only", "r4-hbm"]
    assert mq.main(args, run_fn=always_oom) == 1  # attempt 1, both rows
    assert mq.main(args, run_fn=always_oom) == 1
    assert mq.main(args, run_fn=always_oom) == 1  # attempt 2
    assert mq.main(args, run_fn=always_oom) == 1
    # everything parked: nothing runs, rc converges to 0
    assert mq.main(args, run_fn=always_oom) == 0


def test_mode_specific_default_state_paths():
    """--quick/--smoke measure under different protocols than the full
    queue, so each mode gets its own default checkpoint file."""
    import re

    src = open(os.path.join(REPO, "scripts", "measure_queue.py")).read()
    assert re.search(r'"_smoke" if smoke else "_quick" if quick', src)
