"""TPRowwise (sequence-parallel GEMM+RS) validation on the CPU mesh.

Mirrors the reference's per-rank row-slice validation
(/root/reference/ddlb/primitives/TPRowwise/tp_rowwise.py:153-184) through
the global-array shard comparison.
"""

import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 128, 64, 96  # m % 8 == 0, k % 8 == 0


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_jax_spmd(dtype):
    cls = load_impl_class("tp_rowwise", "jax_spmd")
    impl = cls(M, N, K, dtype=dtype)
    result = impl.run()
    assert result.shape == (M, N)  # globally [m, n], row-sharded over 'tp'
    shard_rows = {s.data.shape[0] for s in result.addressable_shards}
    assert shard_rows == {M // impl.num_partitions}
    assert impl.validate(result)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_xla_gspmd(dtype):
    cls = load_impl_class("tp_rowwise", "xla_gspmd")
    impl = cls(M, N, K, dtype=dtype)
    result = impl.run()
    assert result.shape == (M, N)
    assert impl.validate(result)


@pytest.mark.parametrize("size", ["sharded", "unsharded"])
def test_compute_only(size):
    cls = load_impl_class("tp_rowwise", "compute_only")
    impl = cls(M, N, K, dtype="float32", size=size)
    result = impl.run()
    assert result.shape == (M, N)
    assert impl.validate(result)


def test_shape_constraints():
    cls = load_impl_class("tp_rowwise", "jax_spmd")
    with pytest.raises(ValueError, match="k="):
        cls(M, N, K + 1)
    with pytest.raises(ValueError, match="m="):
        cls(M + 1, N, K)


def test_registry_errors():
    from ddlb_tpu.primitives.registry import load_impl_class as load

    with pytest.raises(ValueError, match="Unknown primitive"):
        load("tp_diagonal", "jax_spmd")
    with pytest.raises(ValueError, match="Unknown implementation"):
        load("tp_rowwise", "nvfuser")
