"""GSPMD vendor-slot tuning surface (VERDICT r1 item #7).

The reference's vendor implementation exposes real knobs (TE userbuffers
config, /root/reference/ddlb/primitives/TPColumnwise/
transformer_engine.py:51-72); the TPU analogue is per-executable XLA
compiler options. These tests pin the option schema, the option->flag
mapping, and that the sweep axis is drivable from a JSON config.
"""

import pytest

from ddlb_tpu.primitives.registry import (
    ALLOWED_PRIMITIVES,
    implementation_names,
    load_impl_class,
)
from ddlb_tpu.primitives.xla_options import (
    GSPMD_ALLOWED_VALUES,
    GSPMD_DEFAULT_OPTIONS,
    build_compiler_options,
)

# the families that actually register a compiler-driven member (e.g.
# cp_ring_attention's members are all explicit-collective; serving_load
# is a host-scheduled drive loop) — registry-driven so a new family
# without an xla_gspmd member doesn't fail by omission
GSPMD_PRIMITIVES = [
    p
    for p in ALLOWED_PRIMITIVES
    if "xla_gspmd" in implementation_names(p)
]


def test_mapping_tpu():
    opts = dict(GSPMD_DEFAULT_OPTIONS)
    out = build_compiler_options(opts, "tpu")
    assert out["xla_tpu_enable_latency_hiding_scheduler"] is True
    assert out["xla_tpu_enable_async_collective_fusion"] is True
    assert "xla_jf_spmd_threshold_for_windowed_einsum_mib" not in out  # auto

    out = build_compiler_options({**opts, "collective_matmul": "force"}, "tpu")
    assert out["xla_jf_spmd_threshold_for_windowed_einsum_mib"] == 0
    out = build_compiler_options({**opts, "collective_matmul": "off"}, "tpu")
    assert out["xla_jf_spmd_threshold_for_windowed_einsum_mib"] >= 1 << 30
    out = build_compiler_options(
        {**opts, "latency_hiding_scheduler": False}, "tpu"
    )
    assert out["xla_tpu_enable_latency_hiding_scheduler"] is False


def test_mapping_off_tpu_is_none():
    """CPU rejects TPU option names ('No such compile option'), so off-TPU
    the options must degrade to a no-op, keeping sim configs runnable."""
    assert build_compiler_options(dict(GSPMD_DEFAULT_OPTIONS), "cpu") is None


@pytest.mark.parametrize("primitive", GSPMD_PRIMITIVES)
def test_gspmd_impls_carry_option_schema(primitive):
    cls = load_impl_class(primitive, "xla_gspmd")
    for key in GSPMD_DEFAULT_OPTIONS:
        assert key in cls.DEFAULT_OPTIONS, (primitive, key)
        assert key in cls.ALLOWED_VALUES, (primitive, key)


def test_gspmd_option_rejected_value():
    cls = load_impl_class("tp_columnwise", "xla_gspmd")
    with pytest.raises(ValueError, match="collective_matmul"):
        cls(128, 32, 64, dtype="float32", collective_matmul="sometimes")


def test_gspmd_options_run_and_record(tmp_path):
    """Options sweep end-to-end from a JSON-style config on the CPU mesh:
    rows record the option string; impls construct and validate."""
    from ddlb_tpu.cli.benchmark import run_benchmark

    config = {
        "benchmark": {
            "primitive": "tp_columnwise",
            "m": [128],
            "n": [32],
            "k": [64],
            "dtype": "float32",
            "num_iterations": 2,
            "num_warmups": 1,
            "validate": True,
            "implementations": {
                "xla_gspmd": [
                    {
                        "latency_hiding_scheduler": [True, False],
                        "collective_matmul": ["auto", "force"],
                    }
                ],
            },
            "output_csv": str(tmp_path / "gspmd.csv"),
            "progress": False,
        }
    }
    df = run_benchmark(config)
    assert len(df) == 4  # 2 x 2 option cartesian product
    assert df["valid"].all()
    opts = set(df["option"])
    assert any("collective_matmul=force" in o for o in opts)
    assert any("latency_hiding_scheduler=False" in o for o in opts)


def test_gspmd_sets_compiler_options_attr():
    cls = load_impl_class("tp_columnwise", "xla_gspmd")
    impl = cls(128, 32, 64, dtype="float32")
    # CPU mesh: attribute exists (device_loop reads it) and is None off-TPU
    assert impl.xla_compiler_options is None


def test_gspmd_options_survive_device_loop_nesting(monkeypatch):
    """compiler_options are only legal on a top-level jit; nested inside
    the device_loop measurement program they must be dropped (the outer
    loop re-applies them). Regression: on real TPU every xla_gspmd row
    under time_measurement_backend=device_loop errored with
    'compiler_options can only be passed to top-level jax.jit'."""
    import ddlb_tpu.primitives.xla_options as xo
    from ddlb_tpu.benchmark import benchmark_worker

    # CPU accepts this option name, so the tuned executable really carries
    # compiler options during the test (off-TPU the mapping is None and
    # the bug would be invisible)
    monkeypatch.setattr(
        xo,
        "build_compiler_options",
        lambda options, platform: {"xla_cpu_enable_fast_math": False},
    )
    row = benchmark_worker(
        {
            "primitive": "tp_columnwise",
            "impl_id": "xla_gspmd_0",
            "base_implementation": "xla_gspmd",
            "options": {},
            "m": 128, "n": 32, "k": 64,
            "dtype": "float32",
            "num_iterations": 4,
            "num_warmups": 1,
            "validate": True,
            "time_measurement_backend": "device_loop",
            "device_loop_windows": 2,
            "device_loop_min_window_ms": 0,
            "barrier_at_each_iteration": False,
            "profile_dir": None,
        }
    )
    assert row["error"] == "", row["error"]
    assert row["valid"] is True
