"""Pure-collectives family on the 8-device CPU mesh.

Every member x op is validated against the host-computed expected global
result (collectives/base.py op table); the pallas member additionally
runs its RDMA rings under the distributed interpreter with the race
detector on, the same sanitizer bar as the fused ring kernels.
"""

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class

# m % d^2 == 0 for the chunked ops at d=8; k padded to lane width
M, K = 512, 256
N = 8  # unused by the family; small keeps host operand construction cheap
# the pallas rings stay inside the distributed interpreter's envelope
# (~12 KB per ring hop at d=8 — see ops/ring_collectives.py); protocol
# correctness is what these pin, hardware measures real payloads
M_RING, K_RING = 128, 128

ALL_OPS = (
    "all_gather",
    "all_reduce",
    "reduce_scatter",
    "all_to_all",
    "ppermute",
)


def _expected_shape(op, d):
    return {
        "all_gather": (M, K),
        "all_reduce": (M // d, K),
        "reduce_scatter": (M // d, K),
        "all_to_all": (M, K),
        "ppermute": (M, K),
    }[op]


@pytest.mark.parametrize("op", ALL_OPS)
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_jax_spmd(op, dtype):
    cls = load_impl_class("collectives", "jax_spmd")
    impl = cls(M, N, K, dtype=dtype, op=op)
    result = impl.run()
    assert result.shape == _expected_shape(op, impl.num_partitions)
    assert impl.validate(result)


def test_jax_spmd_rs_ag_strategy():
    cls = load_impl_class("collectives", "jax_spmd")
    impl = cls(M, N, K, dtype="float32", op="all_reduce", strategy="rs_ag")
    assert impl.validate(impl.run())


def test_hierarchical_all_reduce_single_slice():
    # one slice: the dcn axis has extent 1 and the decomposition
    # degenerates to rs_ag — same replicated sum
    cls = load_impl_class("collectives", "jax_spmd")
    impl = cls(
        M, N, K, dtype="float32", op="all_reduce", strategy="hierarchical"
    )
    result = impl.run()
    assert result.shape == (M // impl.num_partitions, K)
    assert impl.validate(result)


def test_hierarchical_all_reduce_two_slices(monkeypatch):
    # 2 simulated slices x 4 devices: the DCN phase genuinely crosses
    # the slice boundary on the hybrid mesh
    from ddlb_tpu.runtime import Runtime

    monkeypatch.setenv("DDLB_TPU_SIM_SLICES", "2")
    Runtime.reset()
    try:
        cls = load_impl_class("collectives", "jax_spmd")
        impl = cls(
            M, N, K, dtype="float32", op="all_reduce",
            strategy="hierarchical",
        )
        assert impl.mesh.axis_names == ("dcn", "ici")
        assert impl.mesh.devices.shape == (2, 4)
        assert impl.validate(impl.run())
    finally:
        monkeypatch.delenv("DDLB_TPU_SIM_SLICES")
        Runtime.reset()
        Runtime()  # rebuild the clean singleton for later tests


def test_hierarchical_guards():
    cls = load_impl_class("collectives", "jax_spmd")
    with pytest.raises(ValueError, match="all_reduce only"):
        cls(M, N, K, dtype="float32", op="all_gather",
            strategy="hierarchical")
    with pytest.raises(ValueError, match="transport axis"):
        cls(M, N, K, dtype="float32", op="all_reduce",
            strategy="hierarchical", transport="dcn")


@pytest.mark.parametrize("op", ALL_OPS)
def test_xla_gspmd(op):
    cls = load_impl_class("collectives", "xla_gspmd")
    impl = cls(M, N, K, dtype="float32", op=op)
    result = impl.run()
    assert result.shape == _expected_shape(op, impl.num_partitions)
    assert impl.validate(result)


@pytest.mark.parametrize("op", ["all_gather", "reduce_scatter", "all_reduce"])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_pallas_rings(op, dtype):
    cls = load_impl_class("collectives", "pallas")
    impl = cls(M_RING, N, K_RING, dtype=dtype, op=op)
    result = impl.run()
    assert result.shape == {
        "all_gather": (M_RING, K_RING),
        "all_reduce": (M_RING // impl.num_partitions, K_RING),
        "reduce_scatter": (M_RING // impl.num_partitions, K_RING),
    }[op]
    assert impl.validate(result)


@pytest.mark.parametrize("op", ["all_gather", "reduce_scatter"])
def test_pallas_race_detector(op):
    # the distributed interpreter checks the RDMA/semaphore protocol for
    # data races — any race raises inside run()
    cls = load_impl_class("collectives", "pallas")
    impl = cls(M_RING, N, K_RING, dtype="float32", op=op, detect_races=True)
    assert impl.validate(impl.run())


@pytest.mark.parametrize("size", ["sharded", "unsharded"])
def test_compute_only(size):
    cls = load_impl_class("collectives", "compute_only")
    impl = cls(M, N, K, dtype="float32", size=size)
    result = impl.run()
    assert impl.validate(result)
    rows = M // 8 if size == "sharded" else M
    assert result.shape == (rows, K)


def test_wire_bytes_metric():
    # the Throughput column must read per-device ring wire GB/s: flops()
    # is 1000x the documented byte counts
    cls = load_impl_class("collectives", "jax_spmd")
    d = 8
    shard_bytes = (M // d) * K * 4  # float32
    expect = {
        "all_gather": shard_bytes * (d - 1),
        "reduce_scatter": shard_bytes / d * (d - 1),
        "all_reduce": 2 * shard_bytes / d * (d - 1),
        "all_to_all": shard_bytes / d * (d - 1),
        "ppermute": shard_bytes,
    }
    for op, want in expect.items():
        impl = cls(M, N, K, dtype="float32", op=op)
        assert impl.wire_bytes() == pytest.approx(want), op
        assert impl.flops() == pytest.approx(1000.0 * want), op


def test_chunked_ops_reject_bad_m():
    cls = load_impl_class("collectives", "jax_spmd")
    with pytest.raises(ValueError, match="partitions\\^2"):
        cls(8 * 9, N, K, dtype="float32", op="reduce_scatter")
    with pytest.raises(ValueError, match="divisible by partitions"):
        cls(12, N, K, dtype="float32", op="all_gather")


def test_unknown_op_rejected():
    cls = load_impl_class("collectives", "jax_spmd")
    with pytest.raises(ValueError, match="op"):
        cls(M, N, K, dtype="float32", op="broadcast")


def test_runner_row():
    # one config through the shared worker: the row schema carries the
    # family and the Throughput column is finite (GB/s, not TFLOPS)
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(
        {
            "primitive": "collectives",
            "impl_id": "jax_spmd_t",
            "base_implementation": "jax_spmd",
            "options": {"op": "all_gather"},
            "m": M,
            "n": N,
            "k": K,
            "dtype": "float32",
            "num_iterations": 2,
            "num_warmups": 1,
            "validate": True,
            "time_measurement_backend": "host_clock",
            "barrier_at_each_iteration": False,
        }
    )
    assert row["valid"], row["error"]
    assert np.isfinite(row["Throughput (TFLOPS)"])
    # the schema says what the number is: this family reports bandwidth
    assert row["unit"] == "GB/s"
