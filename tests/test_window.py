"""Sliding-window (local) attention: kernel band masks + model paths.

``window > 0`` restricts each query to its ``window`` most recent
positions (inclusive). The flash kernels mask both band edges and SKIP
tiles entirely behind the band (forward and both backward grids); the
einsum paths and all oracles apply the identical two-sided mask.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _oracle(q, k, v, scale, window=0, row_offset=0):
    G = q.shape[1] // k.shape[1]
    kr = jnp.repeat(k, G, axis=1)
    vr = jnp.repeat(v, G, axis=1)
    s = jnp.einsum(
        "qhd,khd->hqk", q.astype(jnp.float32), kr.astype(jnp.float32)
    ) * scale
    sq, skv = q.shape[0], k.shape[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 0) + row_offset
    cols = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 1)
    m = rows >= cols
    if window:
        m &= cols > rows - window
    s = jnp.where(m[None], s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("hqk,khd->qhd", p, vr.astype(jnp.float32))


def _qkv(sq=256, h=4, h_kv=4, dh=16, seed=5):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(sq, h, dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(sq, h_kv, dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(sq, h_kv, dh)), jnp.float32)
    return q, k, v


class TestKernelWindow:
    @pytest.mark.parametrize("window", [32, 64, 100])
    def test_forward_matches_oracle(self, window):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv()
        scale = 1 / np.sqrt(q.shape[-1])
        o = flash_attention(
            q, k, v, scale=scale, block_q=32, block_kv=32,
            interpret=True, window=window,
        )
        want = _oracle(q, k, v, scale, window=window)
        assert float(jnp.max(jnp.abs(o - want))) < 1e-5

    def test_grads_match_oracle_with_gqa(self):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv(h=4, h_kv=2)
        scale = 1 / np.sqrt(q.shape[-1])
        W = 48

        def f(q, k, v):
            return flash_attention(
                q, k, v, scale=scale, block_q=32, block_kv=32,
                interpret=True, window=W,
            ).sum()

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(
            lambda q, k, v: _oracle(q, k, v, scale, window=W).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b in zip("qkv", got, want):
            assert a.shape == b.shape
            err = float(jnp.max(jnp.abs(a - b)))
            assert err < 2e-5, f"d{name}: {err:.2e}"

    def test_dynamic_offset_window(self):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv()
        scale = 1 / np.sqrt(q.shape[-1])
        o = flash_attention(
            q[:128], k, v, scale=scale, row_offset=jnp.int32(128),
            block_q=32, block_kv=32, interpret=True, window=64,
        )
        want = _oracle(q[:128], k, v, scale, window=64, row_offset=128)
        assert float(jnp.max(jnp.abs(o - want))) < 1e-5

    def test_window_changes_output(self):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv()
        scale = 1 / np.sqrt(q.shape[-1])
        kw = dict(scale=scale, block_q=32, block_kv=32, interpret=True)
        full = flash_attention(q, k, v, **kw)
        win = flash_attention(q, k, v, window=32, **kw)
        assert float(jnp.max(jnp.abs(full - win))) > 1e-3

    def test_bad_window_rejected(self):
        from ddlb_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv(sq=64)
        with pytest.raises(ValueError, match="window"):
            flash_attention(q, k, v, scale=0.1, interpret=True, window=-1)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(
                q, k, v, scale=0.1, interpret=True, window=8, causal=False
            )


class TestRingWindow:
    """Windowed ring attention: the band crosses chunk boundaries, dead
    hops are skipped, and forward + gradients match the one-device
    windowed oracle."""

    @pytest.mark.parametrize("d", [2, 4])
    @pytest.mark.parametrize("window", [5, 16, 31])
    def test_ring_flash_forward_matches_oracle(self, d, window):
        from jax.sharding import PartitionSpec as P

        from ddlb_tpu.ops.flash_attention import ring_flash_attention

        S, h, dh = 16 * d, 2, 8
        q, k, v = _qkv(sq=S, h=h, h_kv=h, dh=dh, seed=d)
        scale = 1.0 / np.sqrt(dh)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
        o = jax.shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, axis_name="tp", axis_size=d, scale=scale,
                block_q=8, block_kv=8, interpret=True, window=window,
            ),
            mesh=mesh, in_specs=(P("tp"),) * 3, out_specs=P("tp"),
            check_vma=False,
        )(q, k, v)
        want = _oracle(q, k, v, scale, window=window)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(want), rtol=0, atol=1e-5
        )

    def test_ring_flash_grads_match_oracle(self):
        from jax.sharding import PartitionSpec as P

        from ddlb_tpu.ops.flash_attention import ring_flash_attention

        d, W = 4, 11
        S, h, dh = 16 * d, 2, 8
        q, k, v = _qkv(sq=S, h=h, h_kv=h, dh=dh, seed=9)
        w_out = jnp.asarray(
            np.random.default_rng(7).normal(size=(S, h, dh)), jnp.float32
        )
        scale = 1.0 / np.sqrt(dh)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))

        def ring(q, k, v):
            return jax.shard_map(
                lambda q, k, v: ring_flash_attention(
                    q, k, v, axis_name="tp", axis_size=d, scale=scale,
                    block_q=8, block_kv=8, interpret=True, window=W,
                ),
                mesh=mesh, in_specs=(P("tp"),) * 3, out_specs=P("tp"),
                check_vma=False,
            )(q, k, v)

        g_ring = jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(ring(q, k, v) * w_out),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(
                _oracle(q, k, v, scale, window=W) * w_out
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b in zip("qkv", g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=1e-5,
                err_msg=f"d{name} mismatch",
            )

    @pytest.mark.parametrize("attn_kernel", ["einsum", "flash"])
    def test_ring_train_step_validates(self, attn_kernel):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_ring_window",
                "base_implementation": "spmd",
                "options": {
                    "attention": "ring", "attn_window": 8,
                    "attn_kernel": attn_kernel, "batch": 4, "vocab": 64,
                    "n_heads": 8, "microbatches": 2,
                },
                "m": 32,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True


class TestModelWindow:

    @pytest.mark.parametrize("attn_kernel", ["einsum", "flash"])
    def test_train_step_validates(self, attn_kernel):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_window",
                "base_implementation": "spmd",
                "options": {
                    "attn_window": 8, "attn_kernel": attn_kernel,
                    "batch": 4, "vocab": 64, "n_heads": 8,
                    "microbatches": 2,
                },
                "m": 32,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    @pytest.mark.parametrize(
        "opts",
        [
            {"phase": "decode"},
            {"phase": "decode", "kv_cache": "int8"},
            {"phase": "generate", "n_new": 5},
        ],
    )
    def test_serving_validates(self, opts):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_window",
                "base_implementation": "spmd",
                "options": {
                    "attn_window": 8, "batch": 8, "vocab": 64,
                    "n_heads": 8, "attn_kernel": "einsum", **opts,
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True
