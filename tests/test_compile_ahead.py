"""Compile-ahead sweep engine (utils/compile_ahead.py + runner wiring).

The engine's contract, pinned here on the CPU sim:

- executable signatures group a sweep so same-signature configs run
  adjacently, and the runner clears caches only at group boundaries;
- the background prefetch scheduler overlaps config N+1's compile with
  config N's run, falls back to synchronous compiles on failure, and
  never leaks a compile thread;
- every result row carries ``compile_time_s`` / ``compile_cache_hit``;
- with ``DDLB_TPU_COMPILE_CACHE`` set, a re-run sweep hits the
  persistent cache — the "resumed sweep re-pays nothing" property the
  whole engine exists for.
"""

import threading

import pytest

from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner, benchmark_worker
from ddlb_tpu.utils.compile_ahead import (
    CompileAheadScheduler,
    compile_metrics,
    config_signature,
    executable_signature,
    order_by_signature,
)

SHAPE = dict(m=64, n=32, k=32)


def _worker_config(**over):
    cfg = {
        "primitive": "tp_columnwise",
        "impl_id": "compute_only_0",
        "base_implementation": "compute_only",
        "options": {"size": "unsharded"},
        "dtype": "float32",
        "num_iterations": 2,
        "num_warmups": 1,
        "validate": False,
        "time_measurement_backend": "host_clock",
        "barrier_at_each_iteration": False,
        **SHAPE,
    }
    cfg.update(over)
    return cfg


# ---------------------------------------------------------------------------
# signatures + grouping
# ---------------------------------------------------------------------------


def test_signature_drops_measurement_irrelevant_keys():
    a = executable_signature(
        "tp_columnwise", "compute_only", {"size": "unsharded", "seed": 1},
        64, 32, 32, "float32",
    )
    b = executable_signature(
        "tp_columnwise", "compute_only", {"size": "unsharded", "seed": 2},
        64, 32, 32, "float32",
    )
    c = executable_signature(
        "tp_columnwise", "compute_only", {"size": "sharded"},
        64, 32, 32, "float32",
    )
    assert a == b  # seed never changes the compiled program
    assert a != c  # a real option does


def test_config_signature_matches_runner_key():
    cfg = _worker_config()
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"compute_only_0": {
            "implementation": "compute_only", "size": "unsharded",
        }},
        dtype="float32", progress=False, **SHAPE,
    )
    sig = runner._signature_key(
        "compute_only_0", {"implementation": "compute_only",
                           "size": "unsharded"},
    )
    # the runner merges DEFAULT_OPTIONS; the raw config signature merges
    # nothing — but both agree on the identity axes
    assert sig[0] == config_signature(cfg)[0] == "tp_columnwise"
    assert sig[1] == config_signature(cfg)[1] == "compute_only"
    assert sig[3:] == config_signature(cfg)[3:]


def test_order_by_signature_groups_adjacent_stable():
    items = [
        ("a_0", {"x": 1}), ("b_0", {"x": 2}),
        ("a_1", {"x": 1}), ("c_0", {"x": 3}), ("b_1", {"x": 2}),
    ]
    out = order_by_signature(items, lambda i, s: s["x"])
    assert out == [
        ("a_0", {"x": 1}), ("a_1", {"x": 1}),
        ("b_0", {"x": 2}), ("b_1", {"x": 2}),
        ("c_0", {"x": 3}),
    ]
    # all-distinct signatures: unchanged (the common case)
    distinct = [("a", {"x": 1}), ("b", {"x": 2})]
    assert order_by_signature(distinct, lambda i, s: s["x"]) == distinct


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


def test_scheduler_prefetch_wait_roundtrip():
    compiled = []
    sched = CompileAheadScheduler(
        compile_fn=lambda cfg: compiled.append(cfg["impl_id"])
    )
    sched.prefetch(_worker_config(impl_id="n_plus_1"))
    assert sched.wait(timeout=30) is True
    assert compiled == ["n_plus_1"]
    assert sched.prefetched == 1 and sched.failed == 0
    # thread reaped: nothing left in flight
    assert sched.wait() is False


def test_scheduler_worker_failure_shuts_thread_and_recovers(capsys):
    def boom(cfg):
        raise RuntimeError("backend exploded")

    sched = CompileAheadScheduler(compile_fn=boom)
    sched.prefetch(_worker_config())
    assert sched.wait(timeout=30) is False
    assert sched.failed == 1
    assert "falling back to synchronous compile" in capsys.readouterr().out
    # the failed thread is reaped, not leaked
    assert not any(
        t.name == "ddlb-compile-ahead" and t.is_alive()
        for t in threading.enumerate()
    )
    # and the scheduler keeps scheduling afterwards
    ok_calls = []
    sched._compile_fn = lambda cfg: ok_calls.append(1)
    sched.prefetch(_worker_config())
    assert sched.wait(timeout=30) is True
    assert ok_calls == [1]
    sched.shutdown()


def test_compile_metrics_are_thread_local():
    """A compile on another thread (the prefetch) must not pollute the
    measuring thread's open metrics scope."""
    import jax
    import jax.numpy as jnp

    def compile_something():
        with compile_metrics():
            jax.jit(lambda a: a * 2 + 1).lower(
                jnp.ones((4, 4), jnp.float32)
            ).compile()

    with compile_metrics() as mine:
        t = threading.Thread(target=compile_something)
        t.start()
        t.join(60)
    assert mine.compile_time_s == 0.0
    assert mine.cache_hits == 0 and mine.cache_misses == 0


# ---------------------------------------------------------------------------
# runner wiring
# ---------------------------------------------------------------------------


def test_rows_carry_compile_fields():
    row = benchmark_worker(_worker_config())
    assert row["compile_time_s"] > 0
    assert row["compile_cache_hit"] in (True, False)


def test_error_rows_carry_compile_fields():
    import math

    row = benchmark_worker(_worker_config(options={"size": "bogus"}))
    assert row["error"]
    assert "compile_time_s" in row and "compile_cache_hit" in row
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise", implementations={}, dtype="float32",
        progress=False, **SHAPE,
    )
    dead = runner._error_row(_worker_config(), "WorkerDied: test")
    assert math.isnan(dead["compile_time_s"])
    assert dead["compile_cache_hit"] is False


def test_subprocess_isolation_falls_back_to_sync(monkeypatch, tmp_path):
    """In subprocess mode the parent must never touch the accelerator:
    no scheduler even with the persistent cache configured."""
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"compute_only_0": {
            "implementation": "compute_only", "size": "unsharded",
        }},
        dtype="float32", progress=False, isolation="subprocess", **SHAPE,
    )
    assert runner._make_scheduler() is None
    monkeypatch.setattr(
        "ddlb_tpu.runtime.configure_compile_cache", lambda: None
    )


def test_no_cache_means_no_scheduler(monkeypatch):
    monkeypatch.delenv("DDLB_TPU_COMPILE_CACHE", raising=False)
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"compute_only_0": {
            "implementation": "compute_only", "size": "unsharded",
        }},
        dtype="float32", progress=False, **SHAPE,
    )
    assert runner._make_scheduler() is None
    # and the knob kills it outright
    runner.compile_ahead = False
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", "/tmp/whatever")
    assert runner._make_scheduler() is None


def test_runner_clears_caches_at_signature_boundaries(monkeypatch):
    """Three configs, two sharing a signature: one boundary clear + one
    end-of-sweep clear — not one per row."""
    import jax

    clears = []
    monkeypatch.setattr(jax, "clear_caches", lambda: clears.append(1))
    runner = PrimitiveBenchmarkRunner(
        "tp_rowwise",
        implementations={
            # a_0/a_1 share an executable signature; b_0 differs
            "a_0": {"implementation": "compute_only", "size": "unsharded"},
            "b_0": {"implementation": "compute_only", "size": "sharded"},
            "a_1": {"implementation": "compute_only", "size": "unsharded"},
        },
        dtype="float32", num_iterations=2, num_warmups=1, progress=False,
        validate=False, **SHAPE,
    )
    df = runner.run()
    assert len(df) == 3
    # grouping reordered the sweep: a_0, a_1, b_0
    assert list(df["implementation"]) == ["a_0", "a_1", "b_0"]
    assert len(clears) == 2  # one a->b boundary + one final clear


def test_persistent_cache_makes_repeat_sweep_hit(tmp_path, monkeypatch):
    """The acceptance property: with DDLB_TPU_COMPILE_CACHE set, pass 2
    of an identical sweep is served from the persistent cache —
    ``compile_cache_hit`` flips true and compile time collapses — even
    though ``jax.clear_caches()`` ran in between (resume semantics)."""
    import jax

    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    try:
        cfg = _worker_config(
            impl_id="cache_probe",
            m=96, n=48, k=48,  # shape not shared with other tests
        )
        # drop programs earlier tests compiled BEFORE this cache existed
        # (e.g. the runtime barrier), so the cold pass banks everything
        # the warm pass will need — in production the cache is configured
        # at process start and this is the natural state
        jax.clear_caches()
        cold = benchmark_worker(dict(cfg))
        assert cold["error"] == ""
        jax.clear_caches()
        warm = benchmark_worker(dict(cfg))
        assert warm["error"] == ""
        assert warm["compile_cache_hit"] is True
        assert warm["compile_time_s"] < cold["compile_time_s"]
    finally:
        # never leak the cache dir into the rest of the suite
        jax.config.update("jax_compilation_cache_dir", None)


@pytest.mark.slow
def test_compile_ahead_sweep_end_to_end(tmp_path, monkeypatch):
    """Full runner with scheduler engaged: the second same-signature row
    rides the first's prefetched executables via the disk cache."""
    import jax

    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    try:
        runner = PrimitiveBenchmarkRunner(
            "tp_columnwise",
            implementations={
                "compute_only_0": {
                    "implementation": "compute_only", "size": "unsharded",
                },
                "compute_only_1": {
                    "implementation": "compute_only", "size": "unsharded",
                },
            },
            dtype="float32", num_iterations=2, num_warmups=1,
            progress=False, validate=False, m=80, n=40, k=40,
        )
        df = runner.run()
        assert len(df) == 2
        assert bool(df.iloc[1]["compile_cache_hit"]) is True
        assert (
            df.iloc[1]["compile_time_s"] < df.iloc[0]["compile_time_s"]
        )
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_scheduler_never_stacks_or_blocks_on_a_busy_prefetch():
    """A prefetch wedged against a dying backend must not deadlock the
    sweep: prefetch() skips (never stacks a second thread), wait() obeys
    its timeout, and the sweep proceeds with synchronous compiles."""
    import time as time_mod

    release = threading.Event()

    def slow(cfg):
        release.wait(30)

    sched = CompileAheadScheduler(compile_fn=slow)
    sched.prefetch(_worker_config())
    # still compiling: a bounded wait returns promptly with False
    t0 = time_mod.monotonic()
    assert sched.wait(timeout=0.05) is False
    assert time_mod.monotonic() - t0 < 5
    # and scheduling over it skips instead of stacking
    sched.prefetch(_worker_config())
    assert sched.skipped == 1
    release.set()
    assert sched.wait(timeout=30) is True
    sched.shutdown()
