"""Rotary position embeddings: rotation math + every model path.

RoPE is applied after projection, before attention (and before the
cache write, so decode reads stored post-rotation keys). The oracles
apply the identical f32 rotation, so parity stays exact across gathered
and ring attention, both kernels, GQA, the 1F1B schedule, and the
serving phases including the int8 cache.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddlb_tpu.models.transformer import apply_rope


class TestRotation:
    def test_norm_preserved(self):
        """Rotations preserve the norm of each (i, i+half) pair."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 8, 4, 16)), jnp.float32)
        pos = jnp.arange(8, dtype=jnp.int32)[None]
        y = apply_rope(x, pos, 10000.0)
        nx = jnp.linalg.norm(x, axis=-1)
        ny = jnp.linalg.norm(y, axis=-1)
        assert float(jnp.max(jnp.abs(nx - ny))) < 1e-4

    def test_position_zero_is_identity(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(1, 1, 2, 8)), jnp.float32)
        y = apply_rope(x, jnp.zeros((1, 1), jnp.int32), 10000.0)
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-7)

    def test_relative_position_property(self):
        """q.k after RoPE depends only on the position DIFFERENCE."""
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)

        def dot_at(pq, pk):
            qr = apply_rope(q, jnp.full((1, 1), pq, jnp.int32), 10000.0)
            kr = apply_rope(k, jnp.full((1, 1), pk, jnp.int32), 10000.0)
            return float(jnp.sum(qr * kr))

        assert abs(dot_at(7, 3) - dot_at(14, 10)) < 1e-4
        assert abs(dot_at(7, 3) - dot_at(3, 7)) > 1e-3  # not symmetric

    def test_changes_attention(self):
        """RoPE must not be a silent no-op in the model."""
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
            reference_loss,
        )

        kw = dict(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=1,
        )
        tokens, targets = example_tokens(2, 16, 64)
        params = init_params(TransformerConfig(**kw), pp=1, n_experts=2)
        l0 = float(reference_loss(
            params, tokens, targets, TransformerConfig(**kw), tp=2, dp=1
        ))
        l1 = float(reference_loss(
            params, tokens, targets,
            TransformerConfig(rope=True, **kw), tp=2, dp=1,
        ))
        assert abs(l0 - l1) > 1e-5


class TestModelPaths:
    @pytest.mark.parametrize(
        "opts",
        [
            {"attn_kernel": "flash"},
            {"attention": "ring", "attn_kernel": "flash"},
            {"n_kv_heads": 2, "attn_kernel": "einsum"},
        ],
    )
    def test_train_step_validates(self, opts):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_step",
                "impl_id": "spmd_rope",
                "base_implementation": "spmd",
                "options": {
                    "rope": True, "batch": 4, "vocab": 64, "n_heads": 8,
                    "microbatches": 2, **opts,
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    @pytest.mark.parametrize(
        "opts",
        [
            {"phase": "decode"},
            {"phase": "decode", "kv_cache": "int8", "n_kv_heads": 2},
            {"phase": "generate", "n_new": 5},
        ],
    )
    def test_serving_validates(self, opts):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_rope",
                "base_implementation": "spmd",
                "options": {
                    "rope": True, "batch": 8, "vocab": 64, "n_heads": 8,
                    "attn_kernel": "einsum", **opts,
                },
                "m": 16,
                "n": 64,
                "k": 64,
                "dtype": "float32",
                "num_iterations": 1,
                "num_warmups": 1,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["error"] == ""
        assert row["valid"] is True

    def test_ragged_decode_rotates_per_sequence(self):
        """Ragged positions rotate each sequence at ITS position: rows
        must equal scalar runs at those positions (bitwise)."""
        from ddlb_tpu.models.decode import (
            init_cache,
            make_decode_fn,
            make_prefill_fn,
        )
        from ddlb_tpu.models.transformer import (
            TransformerConfig,
            example_tokens,
            init_params,
        )
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(4, 2))
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, d_ff=64,
            layers_per_stage=1, microbatches=1, attn_kernel="einsum",
            rope=True,
        )
        B, S0 = 8, 8
        params = init_params(cfg, pp=1, n_experts=2)
        prompt, _ = example_tokens(B, S0, cfg.vocab)
        prefill, sh = make_prefill_fn(mesh, cfg)
        p = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        cache = init_cache(cfg, B, S0 + 1, mesh=mesh)
        logits, cache = jax.jit(prefill)(p, cache, prompt)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        dec_s, _ = make_decode_fn(mesh, cfg)
        dec_r, _ = make_decode_fn(mesh, cfg, ragged=True)
        pos_vec = np.array([3, 5, 8, 2, 7, 4, 6, 1], np.int32)
        l_rag = np.asarray(
            jax.jit(dec_r)(p, cache, nxt, jnp.asarray(pos_vec))[0]
        )
        for i in range(B):
            l_i, _ = jax.jit(dec_s)(p, cache, nxt, jnp.int32(int(pos_vec[i])))
            np.testing.assert_array_equal(l_rag[i], np.asarray(l_i)[i])
