"""Semantic SPMD analyzer (ISSUE 9): trace builder + DDLB120-123.

Adversarial trace-builder fixtures (collectives under ``while``/``for``/
``cond``, nested shard_map, keyword vs positional axis, stability across
suppression comments), the four-rule fixture battery proving each rule
fires at the exact ``file:line`` (the acceptance criterion), the
repo-wide DDLB123 zero-drift gate, the ``--spmd-trace`` CLI, the
migrated/total DDLB101 inventory, and the ``flight_report.py --json``
``static_trace`` cross-reference.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

from ddlb_tpu.analysis import core, output  # noqa: E402
from ddlb_tpu.analysis.spmd import families  # noqa: E402
from ddlb_tpu.analysis.spmd.interp import trace_file  # noqa: E402
from ddlb_tpu.analysis.spmd.rules_spmd import WireDriftRule  # noqa: E402

DOC = '"""Fixture."""\n'

#: fixture preamble: the imports every mapped-body fixture needs
PRELUDE = DOC + (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "from jax.sharding import Mesh, PartitionSpec as P\n"
    "from ddlb_tpu.runtime import shard_map_compat\n"
    "\n"
)


def write_fixture(tmp_path, src, rel="ddlb_tpu/primitives/fake/impl.py"):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return path


def traces_of(tmp_path, src, rel="ddlb_tpu/primitives/fake/impl.py"):
    path = write_fixture(tmp_path, src, rel)
    ctx = core.build_context(path, root=tmp_path)
    return trace_file(ctx)


def analyze_fixture(tmp_path, src, rel="ddlb_tpu/primitives/fake/impl.py"):
    path = write_fixture(tmp_path, src, rel)
    return core.analyze([path], root=tmp_path, project_rules=False)


def by_rule(findings, rule_id):
    return [f for f in findings if f.rule == rule_id and f.counts]


def entries_of(traces, op=None):
    out = []
    for t in traces:
        for e in t.entries:
            if op is None or e.op == op:
                out.append(e)
    return out


# ---------------------------------------------------------------------------
# trace builder: adversarial structure fixtures
# ---------------------------------------------------------------------------


class TestTraceBuilder:
    def test_collectives_under_loops_and_while(self, tmp_path):
        src = PRELUDE + (
            "def build(mesh):\n"
            "    def body(i, x):\n"
            "        return jax.lax.psum(x, 'tp')\n"
            "    def step(x):\n"
            "        x = jax.lax.fori_loop(0, 4, body, x)\n"
            "        for _ in range(3):\n"
            "            x = jax.lax.psum_scatter(x, 'tp')\n"
            "        x = jax.lax.while_loop(\n"
            "            lambda c: True,\n"
            "            lambda c: jax.lax.all_gather(c, 'tp'), x)\n"
            "        return x\n"
            "    return shard_map_compat(step, mesh=mesh,\n"
            "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
        )
        traces = traces_of(tmp_path, src)
        # the fori body runs once per concrete trip; the python for
        # unrolls its real count; the while body runs once symbolically
        psums = entries_of(traces, "psum")
        assert len(psums) == 4
        assert all(f.kind == "loop" for e in psums for f in e.frames)
        scatters = entries_of(traces, "psum_scatter")
        assert len(scatters) == 3
        gathers = entries_of(traces, "all_gather")
        assert len(gathers) == 1
        assert any(
            f.kind == "while" for f in gathers[0].frames
        )

    def test_cond_arms_both_traced(self, tmp_path):
        src = PRELUDE + (
            "def build(mesh, flag):\n"
            "    def step(x):\n"
            "        return jax.lax.cond(\n"
            "            flag,\n"
            "            lambda v: jax.lax.psum(v, 'tp'),\n"
            "            lambda v: jax.lax.all_gather(v, 'tp'), x)\n"
            "    return shard_map_compat(step, mesh=mesh,\n"
            "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
        )
        traces = traces_of(tmp_path, src)
        assert len(entries_of(traces, "psum")) == 1
        assert len(entries_of(traces, "all_gather")) == 1
        arms = {
            e.frames[-1].arm
            for e in entries_of(traces)
            if e.frames and e.frames[-1].kind == "cond"
        }
        assert arms == {0, 1}

    def test_nested_shard_map_inner_body_traced(self, tmp_path):
        src = PRELUDE + (
            "def build(mesh, inner_mesh):\n"
            "    def inner(x):\n"
            "        return jax.lax.psum(x, 'ici')\n"
            "    def outer(x):\n"
            "        y = shard_map_compat(inner, mesh=inner_mesh,\n"
            "            in_specs=(P('ici'),), out_specs=P())(x)\n"
            "        return jax.lax.psum(y, 'tp')\n"
            "    return shard_map_compat(outer, mesh=mesh,\n"
            "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
        )
        traces = traces_of(tmp_path, src)
        by_axis = {e.axes: e.op for e in entries_of(traces, "psum")}
        assert ("ici",) in by_axis and ("tp",) in by_axis
        # the inner site opens its own trace with its own specs
        assert any(t.spec_axes == ("ici",) for t in traces)

    def test_axis_keyword_vs_positional(self, tmp_path):
        src = PRELUDE + (
            "def build(mesh):\n"
            "    def step(x):\n"
            "        a = jax.lax.psum(x, axis_name='tp')\n"
            "        b = jax.lax.psum(x, 'tp')\n"
            "        c = jax.lax.all_gather(x, 'tp', axis=0, tiled=True)\n"
            "        return a + b + c\n"
            "    return shard_map_compat(step, mesh=mesh,\n"
            "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
        )
        traces = traces_of(tmp_path, src)
        psums = entries_of(traces, "psum")
        assert [e.axes for e in psums] == [("tp",), ("tp",)]
        assert entries_of(traces, "all_gather")[0].axes == ("tp",)

    def test_trace_stable_across_suppression_comment(self, tmp_path):
        body = (
            "def build(mesh):\n"
            "    def step(x):\n"
            "        return jax.lax.psum(x, 'ep'){comment}\n"
            "    return shard_map_compat(step, mesh=mesh,\n"
            "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
        )
        bare = traces_of(
            tmp_path, PRELUDE + body.format(comment=""),
            rel="ddlb_tpu/primitives/fake/bare.py",
        )
        suppressed = traces_of(
            tmp_path,
            PRELUDE + body.format(
                comment="  # ddlb: ignore[DDLB120]"
            ),
            rel="ddlb_tpu/primitives/fake/supp.py",
        )
        key = lambda ts: [  # noqa: E731
            (e.op, e.axes, e.line) for e in entries_of(ts)
        ]
        assert key(bare) == key(suppressed)

    def test_ring_comprehension_recognized_bijective(self, tmp_path):
        src = PRELUDE + (
            "def build(mesh, d):\n"
            "    def step(x):\n"
            "        perm = [(i, (i + 1) % d) for i in range(d)]\n"
            "        return jax.lax.ppermute(x, 'tp', perm)\n"
            "    return shard_map_compat(step, mesh=mesh,\n"
            "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
        )
        traces = traces_of(tmp_path, src)
        (e,) = entries_of(traces, "ppermute")
        assert e.perm_pattern == "ring"


# ---------------------------------------------------------------------------
# the four rules fire at the exact file:line (acceptance fixtures)
# ---------------------------------------------------------------------------


#: mesh statically known: Mesh(devs, ("tp",)) resolves to axes=("tp",)
STATIC_MESH = PRELUDE + (
    "def build(devs):\n"                                       # line 7
    "    mesh = Mesh(devs, ('tp',))\n"                         # line 8
    "\n"
    "    def step(x):\n"                                       # line 10
    "        r = jax.lax.axis_index('tp')\n"                   # line 11
    "        y = jax.lax.psum(x, 'ep')\n"                      # line 12
    "        if r == 0:\n"                                     # line 13
    "            y = jax.lax.all_gather(y, 'tp')\n"            # line 14
    "        y = jax.lax.ppermute(y, 'tp', [(0, 1), (1, 0), (2, 1)])\n"
    "        return y\n"
    "    return shard_map_compat(step, mesh=mesh,\n"
    "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
)


class TestRuleFixtures:
    def test_ddlb120_undeclared_axis_fires_at_site(self, tmp_path):
        findings = by_rule(
            analyze_fixture(tmp_path, STATIC_MESH), "DDLB120"
        )
        assert [(f.line, f.col) for f in findings] == [(12, 13)]
        assert "axis 'ep'" in findings[0].message

    def test_ddlb120_negative_when_axis_declared(self, tmp_path):
        src = STATIC_MESH.replace("'ep'", "'tp'")
        assert by_rule(analyze_fixture(tmp_path, src), "DDLB120") == []

    def test_ddlb120_unknown_mesh_skips(self, tmp_path):
        # spec axes are a lower bound on the mesh, never the universe:
        # an unknown mesh must not produce false positives
        src = STATIC_MESH.replace("mesh = Mesh(devs, ('tp',))",
                                  "mesh = devs")
        assert by_rule(analyze_fixture(tmp_path, src), "DDLB120") == []

    def test_ddlb121_divergent_branch_fires_at_site(self, tmp_path):
        findings = by_rule(
            analyze_fixture(tmp_path, STATIC_MESH), "DDLB121"
        )
        assert [(f.line, f.col) for f in findings] == [(14, 17)]
        assert "line 13" in findings[0].message  # the divergence branch

    def test_ddlb121_negative_when_arms_match(self, tmp_path):
        # the same (op, axes) multiset on BOTH arms of a rank-dependent
        # branch is lock-step: every rank performs the collective
        findings = analyze_fixture(
            tmp_path,
            PRELUDE + (
                "def build(devs):\n"
                "    mesh = Mesh(devs, ('tp',))\n"
                "    def step(x):\n"
                "        r = jax.lax.axis_index('tp')\n"
                "        if r == 0:\n"
                "            y = jax.lax.psum(x, 'tp')\n"
                "        else:\n"
                "            y = jax.lax.psum(x, 'tp')\n"
                "        return y\n"
                "    return shard_map_compat(step, mesh=mesh,\n"
                "        in_specs=(P('tp'),), out_specs=P('tp'))\n"
            ),
        )
        assert by_rule(findings, "DDLB121") == []

    def test_ddlb122_non_bijective_perm_fires_at_site(self, tmp_path):
        findings = by_rule(
            analyze_fixture(tmp_path, STATIC_MESH), "DDLB122"
        )
        assert [(f.line, f.col) for f in findings] == [(15, 13)]
        assert "duplicate destination" in findings[0].message

    def test_ddlb122_negative_ring_perm(self, tmp_path):
        src = STATIC_MESH.replace(
            "[(0, 1), (1, 0), (2, 1)]",
            "[(0, 1), (1, 2), (2, 0)]",
        )
        assert by_rule(analyze_fixture(tmp_path, src), "DDLB122") == []

    def test_ddlb120_suppression_masks(self, tmp_path):
        src = STATIC_MESH.replace(
            "y = jax.lax.psum(x, 'ep')",
            "y = jax.lax.psum(x, 'ep')  # ddlb: ignore[DDLB120]",
        )
        findings = analyze_fixture(tmp_path, src)
        assert by_rule(findings, "DDLB120") == []
        (masked,) = [f for f in findings if f.rule == "DDLB120"]
        assert masked.suppressed


DRIFT_MEMBER = DOC + (
    "import jax\n"
    "from jax.sharding import PartitionSpec as P\n"
    "from ddlb_tpu.runtime import shard_map_compat\n"
    "\n"
    "\n"
    "class FakePrim:\n"                                        # line 7
    "    COST_SCHEDULE = 'sequential'\n"
    "    DEFAULT_OPTIONS = {}\n"
    "\n"
    "    def wire_bytes(self):\n"                              # line 11
    "        return float(self.m * self.k)__SKEW__\n"
    "\n"
    "    def _input_setup(self):\n"
    "        self.a, self.b = self._host_operands()\n"
    "\n"
    "        def step(a, b):\n"
    "            g = jax.lax.all_gather(a, 'tp', axis=0, tiled=True)\n"
    "            return g @ b\n"
    "\n"
    "        self._fn = shard_map_compat(\n"
    "            step, mesh=self.mesh,\n"
    "            in_specs=(P('tp', None), P(None, None)),\n"
    "            out_specs=P(None, None),\n"
    "        )\n"
)

FAKE_SHAPES = {"m": 128, "n": 64, "k": 64, "d": 4}
FAKE_TABLE = {
    "fake": {"impl": ("ddlb_tpu.primitives.fake.impl", "FakePrim")}
}


def drive_fake_member(tmp_path, skew):
    write_fixture(tmp_path, DRIFT_MEMBER.replace("__SKEW__", skew))
    registry = families.ClassRegistry(tmp_path)
    return families.trace_member(
        "fake", "impl", {}, registry, table=FAKE_TABLE,
        shapes=FAKE_SHAPES,
    )


class TestWireDrift:
    def test_ddlb123_skewed_formula_fires_at_def_line(self, tmp_path):
        # the correct wire for an all_gather of the [m/d, k] bf16 shard
        # is (m/d)*k*2*(d-1) = 12288; the skewed formula claims m*k
        report = drive_fake_member(tmp_path, skew="")
        assert report.status == "drift"
        assert report.wire_traced == pytest.approx(12288.0)
        assert report.wire_formula == pytest.approx(8192.0)
        (finding,) = WireDriftRule().findings_from([report])
        assert finding.rule == "DDLB123"
        assert finding.path == "ddlb_tpu/primitives/fake/impl.py"
        assert finding.line == 11  # the def wire_bytes line
        assert "12288" in finding.message

    def test_ddlb123_correct_formula_verifies(self, tmp_path):
        report = drive_fake_member(
            tmp_path,
            skew=" * 0 + (self.m // 4) * self.k * 2 * 3",
        )
        assert report.status == "verified", report.reason
        assert WireDriftRule().findings_from([report]) == []


# ---------------------------------------------------------------------------
# repo-wide gates + CLI + inventory + flight-report join
# ---------------------------------------------------------------------------


class TestRepoSurface:
    def test_every_family_verifies_with_zero_drift(self):
        reports = families.verify_families()
        by_status: dict = {}
        for r in reports:
            by_status.setdefault(r.status, []).append(r.label())
        assert by_status.get("drift", []) == []
        assert by_status.get("unresolved", []) == []
        # every registered family is exercised
        covered = {r.family for r in reports}
        assert covered == set(families.FAMILY_SHAPES)
        # the statically-checkable members all verify; since the Pallas
        # kernel model (ISSUE 13) traces kernel-internal DMA rings,
        # opacity is ONLY the compiler-scheduled class (xla_gspmd) —
        # down from 15 configs to 10 — and every remaining opaque
        # member carries a registered justification
        opaque = by_status.get("opaque", [])
        assert len(opaque) == 10, opaque
        for label in opaque:
            assert "xla_gspmd" in label, label
        opaque_keys = {
            (r.family, r.member)
            for r in reports
            if r.status == "opaque"
        }
        assert opaque_keys <= set(families.OPAQUE_JUSTIFIED)
        assert len(by_status.get("verified", [])) >= 50

    def test_spmd_trace_cli(self):
        proc = subprocess.run(
            [sys.executable, "scripts/analyze.py", "--spmd-trace",
             "cp_ring_attention"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        assert "cp_ring_attention/ring: verified" in proc.stdout
        assert "spmd-trace:" in proc.stdout

    def test_spmd_trace_cli_unknown_family(self):
        proc = subprocess.run(
            [sys.executable, "scripts/analyze.py", "--spmd-trace",
             "not_a_family"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 2
        assert "unknown family" in proc.stderr

    def test_inventory_shows_migrated_over_total(self, tmp_path):
        legacy = core.Finding(
            "DDLB101", "ddlb_tpu/primitives/tp_rowwise/impl.py", 1, 1,
            "m",
        )
        legacy.baselined = True
        migrated_src = DOC + (
            "from ddlb_tpu.runtime import shard_map_compat\n"
            "def build(step, mesh):\n"
            "    return shard_map_compat(step, mesh=mesh,\n"
            "        in_specs=(), out_specs=())\n"
        )
        path = write_fixture(
            tmp_path, migrated_src,
            rel="ddlb_tpu/primitives/tp_rowwise/done.py",
        )
        ctx = core.build_context(path, root=tmp_path)
        lines = output.shard_map_inventory([legacy], [ctx])
        assert "1/2 migrated" in lines[0]
        assert any(
            "tp_rowwise" in ln and "1 remaining, 1/2 migrated" in ln
            for ln in lines
        )
        # without contexts the historical remaining-only form renders
        old = output.shard_map_inventory([legacy])
        assert "1 legacy site(s)" in old[0]

    def test_static_site_index_joins_barrier_psum(self):
        from ddlb_tpu.analysis.spmd.sites import static_site_index

        index = static_site_index()
        barrier = index["runtime.barrier"]
        assert barrier["rel"] == "ddlb_tpu/runtime.py"
        assert barrier["fn"] == "barrier"
        assert any(
            c["op"] == "psum" and c["axes"] == ["_barrier"]
            for c in barrier["collectives"]
        )
        # host-only sites are indexed but carry no collectives
        assert index["pool.row"]["collectives"] == []

    def test_flight_report_static_cross_reference(self):
        sys.path.insert(0, str(REPO / "scripts"))
        try:
            import flight_report
        finally:
            sys.path.pop(0)
        report = {
            "divergence_site": "runtime.barrier",
            "ranks": {
                "0": {"inflight": [{"site": "runtime.collective"}]},
                "1": {"inflight": []},
            },
        }
        xref = flight_report.static_cross_reference(report)
        assert set(xref) == {"runtime.barrier", "runtime.collective"}
        assert xref["runtime.barrier"]["rel"] == "ddlb_tpu/runtime.py"
        # a clean report cross-references nothing (and costs nothing)
        assert flight_report.static_cross_reference(
            {"ranks": {"0": {"inflight": []}}}
        ) == {}
