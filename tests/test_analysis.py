"""The static-analysis engine and every rule (ISSUE 7).

Per-rule fixture snippets (positive + negative + suppressed), the
suppression/baseline machinery (incl. unused-suppression and
stale-baseline findings, growth refusal), SARIF 2.1.0 document shape,
the DDLB101 migration inventory, the legacy lint shim, and an
integration test asserting ``scripts/analyze.py`` exits 0 on the repo
itself.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

from ddlb_tpu.analysis import baseline as baseline_mod  # noqa: E402
from ddlb_tpu.analysis import core, output  # noqa: E402
from ddlb_tpu.analysis.rules_domain import family_of  # noqa: E402


def run_on(tmp_path, rel, src, project_rules=False):
    """Write one fixture file and run the per-file battery on it."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return core.analyze([path], root=REPO, project_rules=project_rules)


def rule_ids(findings, *, counting_only=True):
    return sorted(
        f.rule
        for f in findings
        if not counting_only or f.counts
    )


DOC = '"""Doc."""\n'


# ---------------------------------------------------------------------------
# engine: suppression, unused suppression, ordering
# ---------------------------------------------------------------------------


def test_syntax_error_is_a_finding(tmp_path):
    findings = run_on(tmp_path, "ddlb_tpu/foo.py", "def broken(:\n")
    assert rule_ids(findings) == ["DDLB001"]


def test_inline_suppression_masks_and_is_used(tmp_path):
    findings = run_on(
        tmp_path, "ddlb_tpu/foo.py",
        DOC + 'print("hi")  # ddlb: ignore[DDLB004]\n',
    )
    assert rule_ids(findings) == []  # suppressed, nothing else fires
    (f,) = [f for f in findings if f.rule == "DDLB004"]
    assert f.suppressed and not f.counts


def test_unused_suppression_is_an_error(tmp_path):
    findings = run_on(
        tmp_path, "ddlb_tpu/foo.py",
        DOC + 'x = 1  # ddlb: ignore[DDLB004]\n',
    )
    assert rule_ids(findings) == ["DDLB100"]


def test_suppression_inside_string_literal_does_not_apply(tmp_path):
    findings = run_on(
        tmp_path, "ddlb_tpu/foo.py",
        DOC + 'y = "# ddlb: ignore[DDLB004]"; print(y)\n',
    )
    assert "DDLB004" in rule_ids(findings)


def test_findings_sorted_by_location(tmp_path):
    findings = run_on(
        tmp_path, "ddlb_tpu/foo.py",
        DOC + 'print("a")\nprint("b")\n',
    )
    lines = [f.line for f in findings if f.rule == "DDLB004"]
    assert lines == sorted(lines) == [2, 3]


# ---------------------------------------------------------------------------
# ported style rules (DDLB002-DDLB006)
# ---------------------------------------------------------------------------


def test_undefined_name_positive_negative(tmp_path):
    findings = run_on(
        tmp_path, "ddlb_tpu/foo.py", DOC + "x = totally_undefined\n"
    )
    assert "DDLB002" in rule_ids(findings)
    findings = run_on(
        tmp_path, "ddlb_tpu/ok.py", DOC + "y = 1\nx = y\n"
    )
    assert "DDLB002" not in rule_ids(findings)


def test_forbidden_calls(tmp_path):
    src = DOC + (
        "import pickle, subprocess\n"
        "eval('1')\n"
        "pickle.loads(b'')\n"
        "subprocess.run('x', shell=True)\n"
    )
    findings = run_on(tmp_path, "scripts/foo.py", src)
    assert rule_ids(findings).count("DDLB003") == 3


def test_bare_print_scope(tmp_path):
    src = DOC + 'print("hi")\n'
    assert "DDLB004" in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", src))
    for exempt in ("ddlb_tpu/cli/foo.py", "ddlb_tpu/telemetry/foo.py",
                   "scripts/foo.py"):
        assert "DDLB004" not in rule_ids(run_on(tmp_path, exempt, src))


def test_docstring_rule(tmp_path):
    findings = run_on(tmp_path, "ddlb_tpu/foo.py", "x = 1\n")
    assert "DDLB005" in rule_ids(findings)
    findings = run_on(
        tmp_path, "ddlb_tpu/ok.py",
        DOC + "class Sole:\n    pass\n",  # sole public class: module doc
    )
    assert "DDLB005" not in rule_ids(findings)


def test_process_spawn_rule(tmp_path):
    src = DOC + "import multiprocessing as mp\np = mp.Process()\n"
    assert "DDLB006" in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", src))
    assert "DDLB006" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/pool.py", src)
    )


# ---------------------------------------------------------------------------
# domain rules (DDLB101-DDLB107)
# ---------------------------------------------------------------------------


def test_legacy_shard_map_positive_negative(tmp_path):
    pos = DOC + (
        "import jax\n"
        "f = jax.shard_map(lambda x: x, mesh=None, in_specs=(),"
        " out_specs=())\n"
    )
    findings = run_on(tmp_path, "ddlb_tpu/primitives/foo/bar.py", pos)
    assert "DDLB101" in rule_ids(findings)
    neg = DOC + (
        "from ddlb_tpu.runtime import shard_map_compat\n"
        "f = shard_map_compat(lambda x: x, None, (), ())\n"
    )
    assert "DDLB101" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/primitives/foo/bar.py", neg)
    )
    # runtime.py itself owns the compat shim
    assert "DDLB101" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/runtime.py", pos)
    )


def test_legacy_shard_map_experimental_import(tmp_path):
    src = DOC + "from jax.experimental.shard_map import shard_map\n"
    assert "DDLB101" in rule_ids(
        run_on(tmp_path, "ddlb_tpu/models/foo.py", src)
    )


def test_wall_clock_deadline_scope(tmp_path):
    src = DOC + "import time\nt = time.time()\n"
    assert "DDLB102" in rule_ids(run_on(tmp_path, "ddlb_tpu/pool.py", src))
    assert "DDLB102" in rule_ids(
        run_on(tmp_path, "ddlb_tpu/faults/heartbeat.py", src)
    )
    # monotonic is the required clock
    ok = DOC + "import time\nt = time.monotonic()\n"
    assert "DDLB102" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/pool.py", ok)
    )
    # observatory timestamping (any non-deadline file) is out of scope
    assert "DDLB102" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/observatory/store.py", src)
    )


def test_raw_env_read_forms(tmp_path):
    src = DOC + (
        "import os\n"
        'a = os.environ.get("DDLB_TPU_FOO")\n'
        'b = os.getenv("DDLB_TPU_BAR")\n'
        'c = os.environ["DDLB_TPU_BAZ"]\n'
        'd = "DDLB_TPU_QUX" in os.environ\n'
    )
    findings = run_on(tmp_path, "ddlb_tpu/foo.py", src)
    assert rule_ids(findings).count("DDLB103") == 4


def test_raw_env_read_constant_indirection(tmp_path):
    src = DOC + (
        "import os\n"
        'CHIP_ENV = "DDLB_TPU_CHIP"\n'
        "x = os.environ.get(CHIP_ENV, '')\n"
    )
    assert "DDLB103" in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", src))


def test_raw_env_write_and_exempt_files_ok(tmp_path):
    write = DOC + 'import os\nos.environ["DDLB_TPU_FOO"] = "1"\n'
    assert "DDLB103" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/foo.py", write)
    )
    read = DOC + 'import os\nv = os.environ.get("DDLB_TPU_FOO")\n'
    assert "DDLB103" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/envs.py", read)
    )
    assert "DDLB103" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/cli/launch.py", read)
    )


def test_unknown_fault_site_literal(tmp_path):
    bad = DOC + (
        "from ddlb_tpu import faults\n"
        'faults.inject("worker.nonexistent_phase")\n'
    )
    assert "DDLB104" in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", bad))
    ok = DOC + (
        "from ddlb_tpu import faults\n"
        'faults.inject("worker.setup")\n'
    )
    assert "DDLB104" not in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", ok))


def test_fault_plan_glob_must_match_a_site(tmp_path):
    bad = DOC + 'plan = {"site": "zz.*", "kind": "hang"}\n'
    assert "DDLB104" in rule_ids(run_on(tmp_path, "scripts/foo.py", bad))
    ok = DOC + 'plan = {"site": "worker.*", "kind": "hang"}\n'
    assert "DDLB104" not in rule_ids(run_on(tmp_path, "scripts/foo.py", ok))


def test_locked_sync_primitive(tmp_path):
    bad = DOC + (
        "import multiprocessing as mp\n"
        'v = mp.Value("d", 0.0)\n'
        'w = mp.Value("d", 0.0, lock=True)\n'
    )
    findings = run_on(tmp_path, "ddlb_tpu/foo.py", bad)
    assert rule_ids(findings).count("DDLB105") == 2
    ok = DOC + (
        "import multiprocessing as mp\n"
        'v = mp.Value("d", 0.0, lock=False)\n'
        "other = mp.Value\n"
    )
    assert "DDLB105" not in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", ok))


def test_unregistered_telemetry_name(tmp_path):
    bad = DOC + (
        "from ddlb_tpu import telemetry\n"
        'with telemetry.span("totally.made_up"):\n    pass\n'
    )
    assert "DDLB106" in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", bad))
    ok = DOC + (
        "from ddlb_tpu import telemetry\n"
        'with telemetry.span("worker.row"):\n    pass\n'
        'telemetry.record("runner.retries")\n'
        "telemetry.span(dynamic_name)\n"  # dynamic: skipped
        "dynamic_name = 'x'\n"
    )
    assert "DDLB106" not in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", ok))


def test_silent_swallow(tmp_path):
    bad = DOC + "try:\n    x = 1\nexcept Exception:\n    pass\n"
    assert "DDLB107" in rule_ids(run_on(tmp_path, "ddlb_tpu/foo.py", bad))
    narrow = DOC + "try:\n    x = 1\nexcept ValueError:\n    pass\n"
    assert "DDLB107" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/foo.py", narrow)
    )
    logged = DOC + (
        "from ddlb_tpu import telemetry\n"
        "try:\n    x = 1\nexcept Exception:\n    telemetry.warn('x')\n"
    )
    assert "DDLB107" not in rule_ids(
        run_on(tmp_path, "ddlb_tpu/foo.py", logged)
    )


# ---------------------------------------------------------------------------
# project rules (DDLB007, DDLB108)
# ---------------------------------------------------------------------------


def test_cost_model_coverage_fires_on_gap(monkeypatch):
    from ddlb_tpu.analysis.rules_project import CostModelCoverageRule
    from ddlb_tpu.perfmodel.cost import FAMILY_COST_MODELS

    ctxs = [core.build_context(REPO / "ddlb_tpu" / "schema.py", root=REPO)]
    assert list(CostModelCoverageRule().check_project(ctxs)) == []
    monkeypatch.delitem(FAMILY_COST_MODELS, "tp_columnwise")
    findings = list(CostModelCoverageRule().check_project(ctxs))
    assert findings and findings[0].rule == "DDLB007"
    assert "tp_columnwise" in findings[0].message


def test_row_schema_coverage_fires_on_unregistered_column(monkeypatch):
    from ddlb_tpu.analysis.rules_project import RowSchemaCoverageRule
    from ddlb_tpu.schema import ROW_COLUMNS

    ctxs = [core.build_context(REPO / "ddlb_tpu" / "schema.py", root=REPO)]
    assert list(RowSchemaCoverageRule().check_project(ctxs)) == []
    monkeypatch.delitem(ROW_COLUMNS, "retries")
    findings = list(RowSchemaCoverageRule().check_project(ctxs))
    assert findings and all(f.rule == "DDLB108" for f in findings)
    assert any("'retries'" in f.message for f in findings)


# ---------------------------------------------------------------------------
# baseline: masking, stale entries, shrink-only updates
# ---------------------------------------------------------------------------


def _print_findings(tmp_path, n=1):
    body = "".join(f'print("{i}")\n' for i in range(n))
    return run_on(tmp_path, "ddlb_tpu/foo.py", DOC + body)


def test_baseline_masks_known_findings(tmp_path):
    findings = _print_findings(tmp_path)
    bl = tmp_path / "baseline.json"
    assert baseline_mod.update(findings, bl) == []
    fresh = _print_findings(tmp_path)
    stale = baseline_mod.apply(fresh, baseline_mod.load(bl), bl)
    assert stale == []
    assert all(f.baselined for f in fresh if f.rule == "DDLB004")
    assert not any(f.counts for f in fresh)


def test_baseline_survives_line_drift(tmp_path):
    findings = _print_findings(tmp_path)
    bl = tmp_path / "baseline.json"
    baseline_mod.update(findings, bl)
    # same offending line, different line NUMBER
    drifted = run_on(
        tmp_path, "ddlb_tpu/foo.py", DOC + "x = 1\ny = 2\n" + 'print("0")\n'
    )
    baseline_mod.apply(drifted, baseline_mod.load(bl), bl)
    assert all(f.baselined for f in drifted if f.rule == "DDLB004")


def test_stale_baseline_entry_is_an_error(tmp_path):
    findings = _print_findings(tmp_path)
    bl = tmp_path / "baseline.json"
    baseline_mod.update(findings, bl)
    clean = run_on(tmp_path, "ddlb_tpu/foo.py", DOC + "x = 1\n")
    stale = baseline_mod.apply(clean, baseline_mod.load(bl), bl)
    assert len(stale) == 1
    assert stale[0].rule == baseline_mod.STALE_BASELINE_ID
    assert stale[0].counts


def test_stale_baseline_skipped_for_unanalyzed_files(tmp_path):
    """A subset sweep (--changed-only) must not report the untouched
    backlog as stale — only the full sweep can prove an entry dead."""
    findings = _print_findings(tmp_path)
    bl = tmp_path / "baseline.json"
    baseline_mod.update(findings, bl)
    entry_path = findings[0].path
    # the baselined file is NOT in the analyzed subset: no staleness
    stale = baseline_mod.apply(
        [], baseline_mod.load(bl), bl, analyzed={"some/other.py"}
    )
    assert stale == []
    # the full sweep (analyzed=None) still enforces shrinkage
    stale = baseline_mod.apply([], baseline_mod.load(bl), bl)
    assert len(stale) == 1
    # and a subset that DOES cover the file enforces it too
    stale = baseline_mod.apply(
        [], baseline_mod.load(bl), bl, analyzed={entry_path}
    )
    assert len(stale) == 1


def test_baseline_update_refuses_growth(tmp_path):
    bl = tmp_path / "baseline.json"
    baseline_mod.update(_print_findings(tmp_path, n=1), bl)
    grown = baseline_mod.update(_print_findings(tmp_path, n=2), bl)
    assert grown  # refused: returns the grown keys, writes nothing
    assert len(baseline_mod.load(bl)) == 1
    # explicit override allows it
    assert baseline_mod.update(
        _print_findings(tmp_path, n=2), bl, allow_growth=True
    ) == []
    assert sum(baseline_mod.load(bl).values()) == 2


def test_update_after_fix_shrinks_cleanly(tmp_path):
    """The documented workflow: fix a baselined site, re-run
    --update-baseline — the stale DDLB110 meta-finding appended by
    apply() must neither trip the growth refusal nor be written into
    the new baseline."""
    bl = tmp_path / "baseline.json"
    baseline_mod.update(_print_findings(tmp_path, n=2), bl)
    # one of the two sites got fixed
    fixed = _print_findings(tmp_path, n=1)
    fixed.extend(baseline_mod.apply(fixed, baseline_mod.load(bl), bl))
    assert any(
        f.rule == baseline_mod.STALE_BASELINE_ID for f in fixed
    )
    assert baseline_mod.update(fixed, bl) == []  # shrink accepted
    new = baseline_mod.load(bl)
    assert sum(new.values()) == 1
    assert not any(
        rule == baseline_mod.STALE_BASELINE_ID for (rule, _p, _s) in new
    )


def test_project_finding_suppression_outside_analyzed_set(tmp_path):
    """A ``# ddlb: ignore`` on a project-rule finding's line applies
    even when that file is not in the analyzed subset (the
    --changed-only case)."""
    root = tmp_path
    writer = root / "ddlb_tpu" / "benchmark.py"
    writer.parent.mkdir(parents=True)
    writer.write_text(
        DOC + 'row = {}\nrow["x"] = 1  # ddlb: ignore[DDLB555]\n'
    )
    other = root / "ddlb_tpu" / "other.py"
    other.write_text(DOC)

    class FakeProjectRule(core.ProjectRule):
        id = "DDLB555"
        name = "fake-project-rule"

        def check_project(self, contexts):
            return [
                core.Finding(
                    self.id, "ddlb_tpu/benchmark.py", 3, 1, "fake"
                )
            ]

    findings = core.analyze(
        [other], rules=[FakeProjectRule()], root=root
    )
    (f,) = [f for f in findings if f.rule == "DDLB555"]
    assert f.suppressed and not f.counts


def test_repo_baseline_is_current():
    """The committed baseline matches the tree exactly: no stale entries
    (shrink enforcement) and nothing new un-baselined. Keyed on content,
    so this is the 'baseline only ever shrinks' lint."""
    paths = core.expand_targets(
        [str(REPO / t) for t in
         ("ddlb_tpu", "tests", "scripts", "bench.py", "__graft_entry__.py")]
    )
    findings = core.analyze(paths, root=REPO)
    bl_path = REPO / baseline_mod.BASELINE_NAME
    stale = baseline_mod.apply(findings, baseline_mod.load(bl_path), bl_path)
    assert stale == [], [s.message for s in stale]
    leftovers = [output.text_line(f) for f in findings if f.counts]
    assert leftovers == []


# ---------------------------------------------------------------------------
# output: SARIF validity, JSON, inventory
# ---------------------------------------------------------------------------


def test_sarif_document_shape(tmp_path):
    findings = run_on(
        tmp_path, "ddlb_tpu/foo.py",
        DOC + 'print("a")  # ddlb: ignore[DDLB004]\nprint("b")\n',
    )
    doc = output.render_sarif(findings)
    # round-trips as JSON
    doc = json.loads(json.dumps(doc))
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "ddlb-analyze"
    rule_meta_ids = {r["id"] for r in driver["rules"]}
    assert {"DDLB101", "DDLB104", "DDLB106", "DDLB004"} <= rule_meta_ids
    for meta in driver["rules"]:
        assert meta["shortDescription"]["text"]
        assert meta["defaultConfiguration"]["level"] in ("error", "warning")
    results = run["results"]
    assert results, "findings must appear as results"
    for res in results:
        assert res["ruleId"] in rule_meta_ids | {"DDLB100", "DDLB110"}
        assert res["level"] in ("error", "warning")
        (loc,) = res["locations"]
        region = loc["physicalLocation"]["region"]
        assert region["startLine"] >= 1 and region["startColumn"] >= 1
    # the suppressed finding carries a SARIF suppressions entry
    suppressed = [r for r in results if r.get("suppressions")]
    assert len(suppressed) == 1
    assert suppressed[0]["suppressions"][0]["kind"] == "inSource"


def test_json_output_counts(tmp_path):
    findings = run_on(
        tmp_path, "ddlb_tpu/foo.py", DOC + 'print("a")\n'
    )
    doc = output.render_json(findings)
    assert doc["counts"]["errors"] == len(
        [f for f in findings if f.counts]
    )
    assert all(
        set(f) >= {"rule", "path", "line", "col", "severity", "message"}
        for f in doc["findings"]
    )


def test_shard_map_inventory_groups_by_family():
    assert family_of("ddlb_tpu/primitives/ep_alltoall/overlap.py") == (
        "ep_alltoall"
    )
    assert family_of("ddlb_tpu/models/decode.py") == "models/decode"
    f = core.Finding(
        "DDLB101", "ddlb_tpu/primitives/tp_rowwise/quantized.py", 1, 1, "m"
    )
    f.baselined = True  # inventory must count the baselined backlog
    lines = output.shard_map_inventory([f])
    assert lines and "1 legacy site(s)" in lines[0]
    assert any("tp_rowwise" in line for line in lines)


# ---------------------------------------------------------------------------
# legacy shim + CLI integration
# ---------------------------------------------------------------------------


def test_lint_shim_check_file(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_shim", REPO / "scripts" / "lint.py"
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    path = tmp_path / "ddlb_tpu" / "foo.py"
    path.parent.mkdir(parents=True)
    path.write_text(DOC + 'print("hi")\n')
    problems = lint.check_file(path)
    assert any("bare print()" in p for p in problems)


@pytest.mark.parametrize("flags", [[], ["--json"], ["--sarif"]])
def test_analyze_cli_clean_on_repo(flags):
    """The acceptance gate: the repo analyzes clean (exit 0) in every
    output mode, and the machine formats parse."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "analyze.py"), *flags],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "PYTHONDONTWRITEBYTECODE": "1"},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    if flags == ["--json"]:
        doc = json.loads(proc.stdout)
        assert doc["counts"]["errors"] == 0
        # the DDLB101 backlog is paid off (tp pallas moved to
        # shard_map_compat); the baseline must stay empty, not regrow
        assert doc["counts"]["baselined"] == 0
    elif flags == ["--sarif"]:
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
    else:
        assert "files clean" in proc.stdout
        assert "shard_map migration inventory" in proc.stdout


def test_analyze_cli_changed_only_runs():
    """--changed-only completes and reports (the pre-commit fast path);
    exit 0/1 both acceptable mid-edit — 2+ means the mode itself broke."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "scripts" / "analyze.py"),
            "--changed-only",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode in (0, 1), proc.stdout + proc.stderr
    assert "analyze" in proc.stdout + proc.stderr


def test_analyze_cli_refuses_subset_baseline_update():
    """--changed-only --update-baseline would silently drop every
    untouched baseline entry; the CLI must refuse the combination."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "scripts" / "analyze.py"),
            "--changed-only", "--update-baseline",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 2
    assert "full sweep" in proc.stderr


def test_analyze_cli_changed_only_bad_ref_fails_loudly():
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "scripts" / "analyze.py"),
            "--changed-only", "no-such-ref-zzz",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 2
    assert "merge base" in proc.stderr


def test_analyze_cli_list_rules():
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "scripts" / "analyze.py"),
            "--list-rules",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0
    for rule_id in ("DDLB101", "DDLB102", "DDLB103", "DDLB104", "DDLB105",
                    "DDLB106", "DDLB107", "DDLB108"):
        assert rule_id in proc.stdout
