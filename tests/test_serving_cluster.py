"""Disaggregated serving cluster (ISSUE 18).

Layered cheapest-first: the admission bucket's deterministic math, the
prefix-affinity router's policy, the cluster facade driven with REAL
(tiny, tp=1) engines — where token-level exactness against solo greedy
chains is provable, including through the prefill->decode handoff and
a mid-flight drain — then the family members end to end through
``benchmark_worker``, and the SLO gate's composition fencing.
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))


# ---------------------------------------------------------------------------
# admission: the token bucket + the census rate
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def _bucket(self, rate=10.0, burst=20.0):
        from ddlb_tpu.serve import TokenBucket

        return TokenBucket(rate, burst)

    def test_starts_full_no_cold_start_shed(self):
        b = self._bucket()
        assert b.level(0.0) == 20.0
        assert b.try_take(20.0, 0.0)  # the whole burst admits at t=0

    def test_all_or_nothing_debit(self):
        """A rejected request debits NOTHING — partial admission would
        starve every later request without admitting anyone."""
        b = self._bucket()
        assert b.try_take(15.0, 0.0)
        level = b.level(0.0)
        assert not b.try_take(10.0, 0.0)  # 5 < 10: reject
        assert b.level(0.0) == level      # untouched
        assert b.try_take(5.0, 0.0)       # exactly-fitting still admits

    def test_refill_rate_and_cap(self):
        b = self._bucket(rate=10.0, burst=20.0)
        assert b.try_take(20.0, 0.0)
        assert b.level(1.0) == pytest.approx(10.0)   # 1 s * 10 tps
        assert b.level(100.0) == pytest.approx(20.0)  # capped at burst

    def test_clock_is_monotone(self):
        """A caller stepping time backwards must not drain the bucket
        (refill clamps dt at 0 and keeps the furthest-seen clock)."""
        b = self._bucket()
        b.try_take(5.0, 10.0)
        level = b.level(10.0)
        assert b.level(3.0) == level

    def test_counters_and_validation(self):
        from ddlb_tpu.serve import TokenBucket

        b = self._bucket(rate=1.0, burst=1.0)
        assert b.try_take(1.0, 0.0)
        assert not b.try_take(5.0, 0.0)
        assert (b.admitted, b.rejected) == (1, 1)
        with pytest.raises(ValueError, match="rate_tps"):
            TokenBucket(0.0, 1.0)
        with pytest.raises(ValueError, match="burst_tokens"):
            TokenBucket(1.0, 0.0)

    def test_census_rate_finite_and_scales(self):
        from ddlb_tpu.perfmodel import ChipSpec
        from ddlb_tpu.perfmodel.specs import get_spec
        from ddlb_tpu.serve import decode_token_rate

        spec = get_spec("v5e")
        assert isinstance(spec, ChipSpec)
        kw = dict(
            ctx=64, d_model=64, d_ff=128, vocab=128, n_heads=4,
            batch=4, n_kv_heads=0, layers=2, kv_cache="bf16",
            mlp_kernel="bf16", attn_kernel="einsum", spec=spec,
        )
        r1 = decode_token_rate(n_devices=1, **kw)
        r4 = decode_token_rate(n_devices=4, **kw)
        assert 0.0 < r1 < float("inf")
        assert r4 == pytest.approx(4.0 * r1)


# ---------------------------------------------------------------------------
# the router policy
# ---------------------------------------------------------------------------


class TestPrefixAffinityRouter:
    def _router(self, n=3, imbalance=2.0):
        from ddlb_tpu.serve import PrefixAffinityRouter

        return PrefixAffinityRouter(n, imbalance)

    def test_least_outstanding_with_index_tiebreak(self):
        r = self._router()
        assert r.route(-1, [5, 2, 9]) == 1
        assert r.route(-1, [4, 4, 4]) == 0  # tie: lowest index

    def test_affinity_sticks_until_imbalanced(self):
        r = self._router()
        first = r.route(7, [0, 0, 0])     # records affinity for prefix 7
        assert r.affinity[7] == first
        # affine shard busier but within imbalance: affinity wins
        out = [0, 0, 0]
        out[first] = 2                     # 2 <= 2.0 * (0 + 1)
        assert r.route(7, out) == first
        assert r.affinity_hits == 1
        # drowning: 9 > 2.0 * (0 + 1) -> falls through to best
        out[first] = 9
        assert r.route(7, out) != first

    def test_drop_shard_forgets_and_rehomes(self):
        r = self._router()
        first = r.route(3, [0, 1, 1])
        assert first == 0
        r.drop_shard(0)
        assert 3 not in r.affinity
        nxt = r.route(3, [0, 1, 1])
        assert nxt in (1, 2)
        assert r.affinity[3] == nxt        # re-homed on a survivor
        r.drop_shard(1)
        r.drop_shard(2)
        with pytest.raises(RuntimeError, match="no live shards"):
            r.route(-1, [0, 0, 0])

    def test_validation(self):
        from ddlb_tpu.serve import PrefixAffinityRouter

        with pytest.raises(ValueError, match="n_shards"):
            PrefixAffinityRouter(0)
        with pytest.raises(ValueError, match="imbalance"):
            PrefixAffinityRouter(2, imbalance=0.5)

    def test_cost_weight_biases_load(self):
        """A degraded shard at weight w looks w-times as loaded, so it
        attracts proportionally less traffic instead of none (ISSUE 19:
        degraded-but-alive is a weight, not an exclusion)."""
        from ddlb_tpu.serve import PrefixAffinityRouter

        r = self._router()
        r.set_weight(0, 3.0)
        assert r.route(-1, [2, 2, 2]) == 1  # loads: 6, 2, 2
        assert r.route(-1, [1, 4, 4]) == 0  # 3 < 4: cheap enough again
        with pytest.raises(ValueError, match="weight"):
            r.set_weight(0, 0.5)

    def test_readmit_restores_excluded_shard_at_weight(self):
        from ddlb_tpu.serve import PrefixAffinityRouter

        r = self._router()
        r.drop_shard(0)
        assert 0 not in r.live_shards()
        r.readmit_shard(0, weight=2.0)
        assert 0 in r.live_shards()
        assert r.route(-1, [1, 3, 3]) == 0  # 2 < 3: back, cost-aware

    def test_grow_add_remove_track_elastic_pools(self):
        """Promotion wiring: ``grow`` widens the index space WITHOUT
        making the prefill indices routable; ``add_shard`` admits one
        mid-run; ``remove_shard`` retires it and forgets its
        affinities (a demoted shard must not keep attracting its old
        prefixes)."""
        from ddlb_tpu.serve import PrefixAffinityRouter

        r = PrefixAffinityRouter(2)
        r.grow(3)
        assert r.live_shards() == [0, 1]
        assert r.route(-1, [9, 9, 0]) in (0, 1)  # index 2 not routable
        r.add_shard(2)
        assert r.route(5, [9, 9, 0]) == 2
        assert r.affinity[5] == 2
        r.remove_shard(2)
        assert 5 not in r.affinity
        assert r.route(5, [0, 1, 0]) == 0


class TestKVBundle:
    def test_coerces_and_validates(self):
        from ddlb_tpu.serve import KVBundle

        b = KVBundle(
            request_id=0, tokens=[1, 2, 3], generated=1, remaining=2,
            prefix_id=-1, kv_tokens=3, payload_bytes=10.0, produced_s=0.0,
        )
        assert b.tokens.dtype == np.int32
        with pytest.raises(ValueError, match="remaining"):
            KVBundle(
                request_id=0, tokens=[1], generated=1, remaining=0,
                prefix_id=-1, kv_tokens=1, payload_bytes=0.0,
                produced_s=0.0,
            )


# ---------------------------------------------------------------------------
# the cluster facade on real engines: token-level exactness
# ---------------------------------------------------------------------------


def _tiny_world(n_engines):
    """``n_engines`` tp=1 engines sharing one set of params: with tp=1
    the block router pins every slot to expert 0, so a request's greedy
    chain is slot- AND engine-independent — solo replay is an exact
    oracle for anything the cluster schedules."""
    import jax

    from ddlb_tpu.models.decode import make_decode_fn
    from ddlb_tpu.models.serving import ContinuousBatchingEngine
    from ddlb_tpu.models.transformer import TransformerConfig, init_params

    cfg = TransformerConfig(
        vocab=64, d_model=32, n_heads=4, d_ff=64,
        layers_per_stage=1, microbatches=1, attn_kernel="einsum",
    )
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:1], dtype=object).reshape(1, 1),
        ("dp", "tp"),
    )
    params = init_params(cfg, pp=1, n_experts=1, seed=0)
    _, sh = make_decode_fn(mesh, cfg)
    params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}

    def make():
        return ContinuousBatchingEngine(
            mesh, cfg, params, max_batch=2, max_len=48
        )

    return [make() for _ in range(n_engines)], make


def _solo_tokens(make_engine, prompt, max_new):
    from ddlb_tpu.models.serving import Request

    eng = make_engine()
    eng.submit(Request(prompt, max_new=max_new))
    return eng.run()[0].tokens


def _pump_until_done(cluster, n, limit=500):
    t = 0.0
    while cluster.accounted < n:
        cluster.pump(t)
        t += 0.01
        limit -= 1
        assert limit > 0, "cluster failed to drain"


def _requests(rng, count, max_new_lo=1, max_new_hi=6):
    return [
        (
            rng.integers(1, 64, int(rng.integers(4, 10))).astype(np.int32),
            int(rng.integers(max_new_lo, max_new_hi + 1)),
        )
        for _ in range(count)
    ]


class TestClusterExactness:
    def test_routed_matches_solo_chains(self):
        from ddlb_tpu.serve import ServingCluster

        engines, make = _tiny_world(2)
        cluster = ServingCluster(engines)
        reqs = _requests(np.random.default_rng(0), 5)
        gids = {}
        for i, (prompt, max_new) in enumerate(reqs):
            gid, ok = cluster.submit(prompt, max_new, now_s=0.0)
            assert ok
            gids[gid] = i
        _pump_until_done(cluster, len(reqs))
        assert len(cluster.completions) == len(reqs)
        for c in cluster.completions:
            prompt, max_new = reqs[gids[c.request_id]]
            np.testing.assert_array_equal(
                c.tokens, _solo_tokens(make, prompt, max_new)
            )

    def test_disagg_handoff_chain_exact(self):
        """The tentpole invariant: prefill-pool first token + decode-
        pool continuation == the solo greedy chain, byte for byte; one
        handoff per request with budget past its prefill, zero for
        ``max_new=1`` (prefill WAS the whole job)."""
        from ddlb_tpu.serve import ServingCluster

        engines, make = _tiny_world(3)
        cluster = ServingCluster(
            engines[:2], engines[2:],
            bundle_bytes=lambda kv_tokens: 100.0 * kv_tokens,
            handoff_seconds=lambda b: b * 1e-9,
        )
        reqs = _requests(np.random.default_rng(1), 5)
        reqs[0] = (reqs[0][0], 1)  # force one prefill-only completion
        gids = {}
        for i, (prompt, max_new) in enumerate(reqs):
            gid, ok = cluster.submit(prompt, max_new, now_s=0.0)
            assert ok
            gids[gid] = i
        _pump_until_done(cluster, len(reqs))
        expect_handoffs = sum(1 for _, mn in reqs if mn > 1)
        assert cluster.counters["handoffs"] == expect_handoffs
        assert cluster.counters["handoff_bytes"] > 0
        assert cluster.counters["handoff_s"] > 0
        for c in cluster.completions:
            prompt, max_new = reqs[gids[c.request_id]]
            np.testing.assert_array_equal(
                c.tokens, _solo_tokens(make, prompt, max_new)
            )
            assert c.handoffs == (1 if max_new > 1 else 0)

    def test_drain_mid_flight_exact_and_zero_lost(self):
        """The chaos-drill half: evict mid-generation on the indicted
        shard, hand off to the survivor, and STILL land every request
        on its exact solo chain (the preempt-then-handoff ledger)."""
        from ddlb_tpu.serve import ServingCluster

        engines, make = _tiny_world(2)
        cluster = ServingCluster(engines)
        reqs = _requests(np.random.default_rng(2), 6, max_new_lo=4,
                         max_new_hi=8)
        gids = {}
        for i, (prompt, max_new) in enumerate(reqs):
            gid, _ = cluster.submit(prompt, max_new, now_s=0.0)
            gids[gid] = i
        cluster.pump(0.0)
        cluster.pump(0.01)  # some generation happens on both shards
        cluster.drain_shard(1, 0.02)
        assert cluster.queue_depths()[1] == -1
        assert cluster.counters["shards_excluded"] == 1
        assert cluster.counters["drained"] > 0
        _pump_until_done(cluster, len(reqs))
        assert len(cluster.completions) == len(reqs)  # zero lost
        for c in cluster.completions:
            assert c.shard == 0  # everything finished on the survivor
            prompt, max_new = reqs[gids[c.request_id]]
            np.testing.assert_array_equal(
                c.tokens, _solo_tokens(make, prompt, max_new)
            )

    def test_drain_last_shard_refused(self):
        from ddlb_tpu.serve import ServingCluster

        engines, _ = _tiny_world(1)
        cluster = ServingCluster(engines)
        with pytest.raises(RuntimeError, match="last live decode shard"):
            cluster.drain_shard(0, 0.0)

    def test_rejection_is_a_counted_outcome(self):
        from ddlb_tpu.serve import ServingCluster, TokenBucket

        engines, _ = _tiny_world(1)
        cluster = ServingCluster(
            engines, admission=TokenBucket(1.0, 4.0)
        )
        g0, ok0 = cluster.submit(np.array([1, 2, 3]), 4, now_s=0.0)
        g1, ok1 = cluster.submit(np.array([4, 5, 6]), 4, now_s=0.0)
        assert ok0 and not ok1  # bucket held 4 tokens, first took them
        _pump_until_done(cluster, 2)
        assert [c.request_id for c in cluster.completions] == [g0]
        assert cluster.rejections == [g1]
        assert cluster.counters["rejected"] == 1


# ---------------------------------------------------------------------------
# the family members end to end
# ---------------------------------------------------------------------------


def _cluster_config(member, **options):
    base = {
        "batch": 8, "vocab": 64, "n_heads": 8, "layers": 1,
        "rate": 200.0, "n_requests": 10, "out_mean": 3, "out_max": 5,
        "slo_ttft_ms": 4000.0, "slo_tpot_ms": 2000.0,
    }
    base.update(options)
    return {
        "primitive": "serving_load",
        "impl_id": f"{member}_0",
        "base_implementation": member,
        "options": base,
        "m": 8, "n": 32, "k": 64, "dtype": "float32",
        "num_iterations": 1, "num_warmups": 1, "validate": True,
        "time_measurement_backend": "host_clock",
        "barrier_at_each_iteration": False,
    }


class TestClusterFamily:
    def test_router_row_valid_with_cluster_columns(self):
        from ddlb_tpu.benchmark import benchmark_worker
        from ddlb_tpu.schema import ROW_COLUMNS

        row = benchmark_worker(_cluster_config("router", dp=2))
        assert row["error"] == "" and bool(row["valid"])
        for col in (
            "serve_topology", "serve_shards", "serve_shards_excluded",
            "serve_rejected", "serve_handoffs", "serve_handoff_bytes",
            "serve_handoff_ms", "serve_drained", "serve_affinity_hits",
        ):
            assert col in row, col
            assert col in ROW_COLUMNS, col
        assert row["serve_topology"] == "router:dp=2"
        assert int(row["serve_shards"]) == 2
        assert row["slo_completed"] == 2 * 10  # exactly-once, both drains

    def test_router_prefix_affinity_hits(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            _cluster_config(
                "router", dp=2, n_requests=16,
                prefix_pop=2, prefix_len=8,
            )
        )
        assert row["error"] == "" and bool(row["valid"])
        assert int(row["serve_affinity_hits"]) > 0
        assert int(row["serve_prefix_hits"]) > 0

    def test_disagg_row_prices_handoffs(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(_cluster_config("disagg"))
        assert row["error"] == "" and bool(row["valid"])
        assert row["serve_topology"] == "disagg:p1+d1"
        assert int(row["serve_handoffs"]) > 0
        assert float(row["serve_handoff_bytes"]) > 0
        assert float(row["serve_handoff_ms"]) > 0

    def test_admission_sheds_with_exact_accounting(self):
        """Overload against a deliberately tiny bucket: rejections are
        counted outcomes and the accounting validation (completed +
        rejected partition the trace) holds."""
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            _cluster_config(
                "router", dp=2, rate=500.0, n_requests=12,
                out_mean=6, out_max=10,
                admission="token_bucket", admission_rate_tps=5.0,
                admission_burst_s=1.0,
            )
        )
        assert row["error"] == "" and bool(row["valid"])
        assert int(row["serve_rejected"]) > 0

    def test_chaos_drill_drains_indicted_shard(self, monkeypatch):
        """The full drill: a seeded hang on shard 1's decode ticks
        breaks its TPOT SLO, the watch indicts it, in-flight work
        drains to shard 0 over the handoff path, and the accounting
        validation proves zero requests lost."""
        from ddlb_tpu.faults import plan as fault_plan

        plan = {
            "seed": 1,
            "rules": [{
                "site": "serve.decode_tick", "kind": "hang",
                "duration_s": 0.05, "match": {"shard": "1"},
                "fail_attempts": 1000000,
            }],
        }
        monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", json.dumps(plan))
        fault_plan.reset()
        try:
            from ddlb_tpu.benchmark import benchmark_worker

            row = benchmark_worker(
                _cluster_config(
                    "router", dp=2, rate=300.0, n_requests=16,
                    out_mean=8, out_max=12,
                    slo_tpot_ms=10.0, watch_ticks=4,
                )
            )
            assert row["error"] == "" and bool(row["valid"])
            assert int(row["serve_shards_excluded"]) == 1
            assert int(row["serve_drained"]) > 0
            assert int(row["serve_handoffs"]) > 0
            assert row["serve_topology"] == "router:dp=2:degraded=1"
            assert "serve.decode_tick" in str(row["fault_injected"])
        finally:
            monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
            fault_plan.reset()

    def test_disagg_cost_model_carries_handoff_wire_term(self):
        """The family cost model prices the planned handoff census as a
        wire term — a disagg member predicts strictly more than the
        same shape with the handoff bytes zeroed."""
        from ddlb_tpu.perfmodel.cost import _serving_cost
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("serving_load", "disagg")
        impl = cls(
            8, 32, 64, dtype="float32", rate=50.0, n_requests=6,
            batch=8, vocab=64, n_heads=8,
        )
        assert impl.handoff_bytes() > 0
        _, comm, _ = _serving_cost(impl, impl.runtime.chip_spec)
        assert comm > 0.0


# ---------------------------------------------------------------------------
# SLO gate composition fencing
# ---------------------------------------------------------------------------


def _record(run, topology=None, ttft95=20.0, goodput=5.0, impl="engine"):
    from ddlb_tpu.observatory import regress

    row = {
        "implementation": f"{impl}_0", "base_implementation": impl,
        "primitive": "serving_load", "option": "out_mean=4;rate=8.0",
        "m": 8, "n": 32, "k": 64, "dtype": "float32", "world_size": 4,
        "chip": "cpu-sim", "time_measurement_backend": "host_clock",
        "median time (ms)": 10.0,
        "slo_ttft_p50_ms": ttft95 * 0.6,
        "slo_ttft_p95_ms": ttft95,
        "slo_ttft_p99_ms": ttft95 * 1.2,
        "slo_tpot_p95_ms": 3.0,
        "slo_goodput_rps": goodput,
    }
    if topology is not None:
        row["serve_topology"] = topology
    return {
        "kind": "row", "run_id": run, "key": regress.row_key(row),
        "row": row,
    }


class TestSLOGateTopologyFencing:
    def _history(self, topology=None, n=4):
        return [
            _record(f"r{i}", topology=topology, ttft95=20.0 + 0.3 * i)
            for i in range(n)
        ]

    def test_cross_topology_never_gates(self):
        """A routed row 3x worse than single-engine history stays
        silent — different composition, different population; the
        healthy single-engine baseline must not indict the cluster
        (nor vice versa)."""
        from ddlb_tpu.observatory import regress

        cur = [_record("cur", topology="router:dp=2", ttft95=60.0)["row"]]
        assert (
            regress.detect_slo(
                cur, self._history(topology="single"), exclude_run="cur"
            )
            == []
        )
        # and degraded rows never gate against healthy cluster history
        deg = [
            _record(
                "cur", topology="router:dp=2:degraded=1", ttft95=60.0
            )["row"]
        ]
        assert (
            regress.detect_slo(
                deg,
                self._history(topology="router:dp=2"),
                exclude_run="cur",
            )
            == []
        )

    def test_same_topology_fires_and_stamps(self):
        from ddlb_tpu.observatory import regress

        cur = [_record("cur", topology="router:dp=2", ttft95=41.0)["row"]]
        findings = regress.detect_slo(
            cur, self._history(topology="router:dp=2"), exclude_run="cur"
        )
        assert findings
        assert all(
            f["serve_topology"] == "router:dp=2" for f in findings
        )

    def test_unstamped_history_is_the_legacy_single_bucket(self):
        """Rows banked before the cluster existed carry no
        serve_topology; they must keep gating single-engine rows (both
        unstamped and explicitly stamped "single") instead of being
        orphaned by the new column."""
        from ddlb_tpu.observatory import regress

        legacy_history = self._history(topology=None)
        unstamped = [_record("cur", ttft95=41.0)["row"]]
        stamped = [_record("cur", topology="single", ttft95=41.0)["row"]]
        for cur in (unstamped, stamped):
            findings = regress.detect_slo(
                cur, legacy_history, exclude_run="cur"
            )
            assert findings
            assert findings[0]["serve_topology"] == "single"


# ---------------------------------------------------------------------------
# fault sites, live stream, dashboard
# ---------------------------------------------------------------------------


class TestClusterPlumbing:
    def test_serve_cluster_sites_registered(self):
        from ddlb_tpu.faults.plan import SITES

        assert "serve.route" in SITES
        assert "serve.handoff" in SITES

    def test_live_fold_keeps_shard_depths(self):
        from ddlb_tpu.observatory import live

        state = live.fold(
            [
                {
                    "kind": "serving_tick", "pid": 1, "ts": 0.0,
                    "queue_depth": 3, "active": 2, "done": 1,
                    "total": 8, "shard_depths": [2, -1],
                },
            ]
        )
        assert state["serving"]["shard_depths"] == [2, -1]

    def test_dash_renders_shard_queues(self):
        import sweep_dash
        from ddlb_tpu.observatory import live

        state = live.fold(
            [
                {
                    "kind": "serving_tick", "pid": 1, "ts": 0.0,
                    "queue_depth": 3, "active": 2, "done": 1,
                    "total": 8, "shard_depths": [2, -1],
                },
            ]
        )
        text = sweep_dash.render_text(state)
        assert "shard queues" in text
        assert "s0:2" in text and "s1:drained" in text
        html = sweep_dash.render_html(state)
        assert "shard 1: drained" in html

    def test_option_schema_covers_cluster_knobs(self):
        """DDLB007's convention, asserted directly: every cluster knob
        is a schema-documented option with an allowed-values entry."""
        from ddlb_tpu.primitives.registry import load_impl_class

        for member, extra in (
            ("router", ("dp",)),
            ("disagg", ("prefill_shards", "decode_shards")),
        ):
            cls = load_impl_class("serving_load", member)
            defaults, allowed = cls.option_schema()
            for knob in (
                "admission", "admission_overcommit",
                "admission_rate_tps", "admission_burst_s",
                "affinity_imbalance", "watch_ticks", "watch_dominance",
            ) + extra:
                assert knob in defaults, (member, knob)
                assert knob in allowed, (member, knob)


# ---------------------------------------------------------------------------
# elastic pools: promote / demote / probation (ISSUE 19)
# ---------------------------------------------------------------------------


class TestElasticPools:
    """The resize controller and the exoneration loop on REAL tiny
    engines, where token-level exactness against solo greedy chains is
    still the oracle — a transition that generated a token twice, lost
    a request, or double-stamped a first token cannot match."""

    def _elastic_cluster(self, make_engines, **kw):
        from ddlb_tpu.serve import ServingCluster

        defaults = dict(
            elastic=True, resize_backlog=2, resize_cooldown=1000,
        )
        defaults.update(kw)
        return ServingCluster(*make_engines, **defaults)

    def test_promote_exact_and_zero_lost(self):
        """Decode backlog with prefill headroom promotes ONE prefill
        shard: its prefill work drains to the surviving prefill shard,
        the router gains a decode column, and every request still lands
        on its exact solo chain with exactly-once accounting."""
        engines, make = _tiny_world(3)
        cluster = self._elastic_cluster((engines[:1], engines[1:]))
        reqs = _requests(np.random.default_rng(7), 8, max_new_lo=4,
                         max_new_hi=6)
        gids = {}
        for i, (prompt, max_new) in enumerate(reqs):
            gid, ok = cluster.submit(prompt, max_new, now_s=0.0)
            assert ok
            gids[gid] = i
        assert len(cluster.queue_depths()) == 1
        _pump_until_done(cluster, len(reqs))
        assert cluster.counters["resizes"] >= 1
        assert any(
            ev.startswith("promote:") for ev in cluster.pool_history
        )
        assert len(cluster.queue_depths()) == 2  # gauge grew mid-run
        assert len(cluster.completions) == len(reqs)  # zero lost
        seen = set()
        for c in cluster.completions:
            assert c.request_id not in seen  # exactly-once
            seen.add(c.request_id)
            assert c.first_s <= c.finished_s
            prompt, max_new = reqs[gids[c.request_id]]
            np.testing.assert_array_equal(
                c.tokens, _solo_tokens(make, prompt, max_new)
            )

    def test_demote_returns_promoted_shard_home(self):
        """The reverse breath: once decode pressure clears and prefill
        backlog builds, the PROMOTED shard (home pool prefill) returns
        — the constructed decode pool never shrinks below its
        engineered size — and the max_new=1 burst that forced the
        demotion still completes exactly."""
        engines, make = _tiny_world(3)
        cluster = self._elastic_cluster(
            (engines[:1], engines[1:]), resize_cooldown=2
        )
        reqs = _requests(np.random.default_rng(8), 8, max_new_lo=4,
                        max_new_hi=6)
        for prompt, max_new in reqs:
            cluster.submit(prompt, max_new, now_s=0.0)
        _pump_until_done(cluster, len(reqs))
        assert any(
            ev.startswith("promote:") for ev in cluster.pool_history
        )
        # phase 2: a prefill-only burst piles the (now single-shard)
        # prefill pool while the decode pool sits idle
        burst = _requests(np.random.default_rng(9), 10, max_new_lo=1,
                          max_new_hi=1)
        gids = {}
        for prompt, max_new in burst:
            gid, ok = cluster.submit(prompt, max_new, now_s=1.0)
            assert ok
            gids[gid] = (prompt, max_new)
        _pump_until_done(cluster, len(reqs) + len(burst))
        assert any(
            ev.startswith("demote:") for ev in cluster.pool_history
        )
        demoted = [sh for sh in cluster.prefill if sh.home_pool == "prefill"]
        assert len(demoted) == 2  # both construction prefill shards home
        for sh in cluster.shards:
            assert sh.home_pool == "decode"  # engineered pool intact
        for c in cluster.completions:
            if c.request_id in gids:
                prompt, max_new = gids[c.request_id]
                np.testing.assert_array_equal(
                    c.tokens, _solo_tokens(make, prompt, max_new)
                )

    def test_probation_exonerates_healthy_shard(self):
        """A drained-but-healthy shard earns its way back: probe
        windows close healthy, ``exoneration_verdict`` corroborates,
        and the shard re-enters the router's candidate set with the
        re-admission counted and journaled."""
        engines, make = _tiny_world(2)
        from ddlb_tpu.serve import ServingCluster

        cluster = ServingCluster(
            engines, watch_ticks=2, probation_ticks=2, probe_interval=1
        )
        reqs = _requests(np.random.default_rng(10), 6, max_new_lo=3,
                         max_new_hi=5)
        gids = {}
        for i, (prompt, max_new) in enumerate(reqs):
            gid, _ = cluster.submit(prompt, max_new, now_s=0.0)
            gids[gid] = i
        cluster.pump(0.0)
        cluster.pump(0.01)
        cluster.drain_shard(1, 0.02)
        assert cluster.queue_depths()[1] == -1
        sh = cluster._all[1]
        assert sh.probation
        t, limit = 0.03, 400
        while cluster.counters["readmitted"] < 1:
            cluster.pump(t)
            t += 0.01
            limit -= 1
            assert limit > 0, "healthy shard never exonerated"
        assert any(
            ev.startswith("exonerate:1@") for ev in cluster.pool_history
        )
        assert not sh.excluded and not sh.probation
        assert 1 in cluster.router.live_shards()
        assert cluster.queue_depths()[1] >= 0
        # the ledger never saw a probe completion
        _pump_until_done(cluster, len(reqs))
        assert len(cluster.completions) == len(reqs)
        for c in cluster.completions:
            prompt, max_new = reqs[gids[c.request_id]]
            np.testing.assert_array_equal(
                c.tokens, _solo_tokens(make, prompt, max_new)
            )

    def test_probe_interval_paces_probe_ticks(self):
        """Probes ride the pump loop synchronously, so cadence is a
        live-traffic protection: with ``probe_interval=5`` the excluded
        engine steps on every fifth pump only."""
        engines, _ = _tiny_world(2)
        from ddlb_tpu.serve import ServingCluster

        cluster = ServingCluster(
            engines, watch_ticks=2, probation_ticks=3, probe_interval=5
        )
        reqs = _requests(np.random.default_rng(11), 4)
        for prompt, max_new in reqs:
            cluster.submit(prompt, max_new, now_s=0.0)
        cluster.pump(0.0)
        cluster.drain_shard(1, 0.01)
        probed = cluster._all[1].engine
        calls = {"n": 0}
        orig_step = probed.step

        def counted_step():
            calls["n"] += 1
            return orig_step()

        probed.step = counted_step
        start = cluster._pump_count
        for i in range(10):
            cluster.pump(0.02 + 0.01 * i)
        expect = sum(
            1 for p in range(start + 1, cluster._pump_count + 1)
            if p % 5 == 0
        )
        assert calls["n"] == expect


@pytest.mark.slow
def test_near_critical_load_elastic_member_accounts_exactly():
    """ROADMAP's measurement-hostility case: the disaggregated member
    driven at a near-critical arrival rate with elasticity armed. The
    interesting property is not latency (CPU-sim makes no promises
    there) but conservation: whatever the pools did — promote, demote,
    shed at the door — the row validates and the ledger partitions the
    trace exactly (completed + rejected == submitted, both drains)."""
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(
        _cluster_config(
            "disagg", prefill_shards=2, decode_shards=2,
            rate=400.0, n_requests=60, out_mean=8, out_max=12,
            elastic=1, resize_backlog=2, resize_cooldown=8,
            probation_ticks=2, watch_ticks=4,
        )
    )
    assert row["error"] == "" and bool(row["valid"])
    assert int(row["slo_completed"]) + int(row["serve_rejected"]) == 2 * 60
    assert row["serve_topology"].startswith("disagg:p2+d2")
    for col in ("serve_resizes", "serve_pool_history", "serve_readmitted"):
        assert col in row, col
