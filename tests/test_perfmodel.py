"""Perfmodel subsystem: spec registry, closed-form costs, row columns.

Covers the ISSUE 3 acceptance contract: spec lookup and the
``DDLB_TPU_CHIP`` env override; hand-computed closed-form cost checks
for all 9 primitive families; the ``roofline_frac`` ∈ (0, 1] invariant
on a CPU-sim sweep of the shipped ``scripts/config.json`` implementation
blocks; and error rows still carrying the new columns. Plus the
``scripts/perf_report.py`` ranking over the sweep's CSV and the
``utils/hbm_budget`` ↔ spec-registry capacity tie.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from ddlb_tpu.perfmodel.cost import (
    FAMILY_COST_MODELS,
    estimate,
    hierarchical_wire_bytes,
    ring_wire_bytes,
    striped_wire_bytes,
    torus_factors,
    wire_itemsize,
)
from ddlb_tpu.perfmodel.specs import (
    CHIP_SPECS,
    detect_spec,
    get_spec,
)
from ddlb_tpu.primitives.registry import ALLOWED_PRIMITIVES, load_impl_class

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GB = 1e9


# ---------------------------------------------------------------------------
# specs
# ---------------------------------------------------------------------------


class TestSpecs:
    def test_registry_entries(self):
        assert set(CHIP_SPECS) == {"v4", "v5e", "v5p", "v6e", "cpu-sim"}

    def test_published_numbers(self):
        v5e = get_spec("v5e")
        assert v5e.peak_tflops["bfloat16"] == 197.0
        assert v5e.hbm_gib == 16.0
        assert v5e.hbm_bw_gbs == 819.0
        assert get_spec("v4").hbm_gib == 32.0
        assert get_spec("v5p").peak_tflops["bfloat16"] == 459.0
        assert get_spec("v6e").peak_tflops["int8"] == 1836.0

    def test_alias_and_case_insensitive_lookup(self):
        assert get_spec("TPU v5 lite").name == "v5e"
        assert get_spec("Trillium").name == "v6e"
        assert get_spec("V5E").name == "v5e"

    def test_unknown_chip_raises(self):
        with pytest.raises(KeyError):
            get_spec("v99")

    def test_peak_flops_dtype_rules(self):
        v5e = get_spec("v5e")
        assert v5e.peak_flops("bfloat16") == 197.0e12
        # f32/f64: the 3-pass bf16x3 decomposition rate
        assert v5e.peak_flops("float32") == pytest.approx(197.0e12 / 3.0)
        assert v5e.peak_flops("float64") == pytest.approx(197.0e12 / 3.0)
        assert v5e.peak_flops("int8") == 394.0e12
        # v4 has no int8 entry: integer dtypes fall back to bf16 peak
        assert get_spec("v4").peak_flops("int32") == 275.0e12

    def test_link_bw_transport(self):
        v5e = get_spec("v5e")
        assert v5e.link_bw("ici") == 50.0 * GB
        assert v5e.link_bw("dcn") == 6.25 * GB

    def test_detect_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("DDLB_TPU_CHIP", "v4")
        assert detect_spec(device_kind="TPU v5 lite").name == "v4"
        monkeypatch.setenv("DDLB_TPU_CHIP", "nonsense")
        with pytest.raises(KeyError):
            detect_spec(device_kind="TPU v5 lite")

    def test_detect_from_device_kind(self, monkeypatch):
        monkeypatch.delenv("DDLB_TPU_CHIP", raising=False)
        assert detect_spec(device_kind="TPU v4", platform="tpu").name == "v4"
        assert (
            detect_spec(device_kind="TPU v5 lite", platform="tpu").name
            == "v5e"
        )
        assert (
            detect_spec(device_kind="TPU v6 lite", platform="tpu").name
            == "v6e"
        )
        # the "v5 lite" alias must win over v5p's bare "tpu v5"
        assert detect_spec(device_kind="TPU v5", platform="tpu").name == "v5p"
        # non-TPU platforms resolve to the calibrated sim entry
        assert detect_spec(device_kind="cpu", platform="cpu").name == "cpu-sim"

    def test_runtime_detection_on_sim(self, runtime, monkeypatch):
        monkeypatch.delenv("DDLB_TPU_CHIP", raising=False)
        assert runtime.chip_spec.name == "cpu-sim"
        monkeypatch.setenv("DDLB_TPU_CHIP", "v5p")
        assert runtime.chip_spec.name == "v5p"


# ---------------------------------------------------------------------------
# closed-form costs, one hand-computed check per family
# ---------------------------------------------------------------------------


V5E = None  # assigned lazily so collection stays import-cheap


def _v5e():
    global V5E
    if V5E is None:
        V5E = get_spec("v5e")
    return V5E


def _impl(primitive, name, m, n, k, dtype="bfloat16", **options):
    return load_impl_class(primitive, name)(m, n, k, dtype=dtype, **options)


def _stub(primitive, name, m, n, k, dtype="bfloat16", d=8, **options):
    """An uninitialized instance carrying only the shape/option state the
    cost model reads — ``flops()`` / ``wire_bytes()`` / ``COST_SCHEDULE``
    are all shape-only, so the closed forms are checkable without paying
    (or depending on) operand construction and the step compile."""
    cls = load_impl_class(primitive, name)
    impl = object.__new__(cls)
    impl.m, impl.n, impl.k = m, n, k
    impl.dtype = dtype
    impl.num_partitions = d
    defaults, _ = cls.option_schema()
    impl.options = {**defaults, **options}
    return impl


class TestStripedFormulas:
    """``torus_factors`` + ``striped_wire_bytes`` hand-computed, plus
    the conservation anchors that tie the striped composition to the
    hierarchical and flat formulas (ISSUE 16 satellite)."""

    def test_torus_factors_squarest_split(self):
        assert torus_factors(1) == (1, 1)
        assert torus_factors(4) == (2, 2)
        assert torus_factors(8) == (2, 4)
        assert torus_factors(12) == (3, 4)
        assert torus_factors(16) == (4, 4)
        assert torus_factors(256) == (16, 16)
        assert torus_factors(7) == (1, 7)  # primes stay 1 x n
        with pytest.raises(ValueError):
            torus_factors(0)

    def test_striped_all_reduce_hand_computed(self):
        # 4 slices x (4x4) torus, S bytes local: RS-intra S*15/16,
        # AR-inter 2*(S/16)*(3/4), AG-intra (S/16)*15 — two stripes
        # splitting the ICI share evenly
        s = 1024.0
        got = striped_wire_bytes("all_reduce", s, 4, (4, 4))
        assert got["ici"] == pytest.approx(s * 15.0 / 16.0 + s * 15.0 / 16.0)
        assert got["dcn"] == pytest.approx(2.0 * (s / 16.0) * 3.0 / 4.0)
        assert got["stripes"] == 2
        assert got["ici_per_stripe"] == pytest.approx(got["ici"] / 2.0)

    def test_striped_all_to_all_pays_per_axis(self):
        # the intra redistribution runs per torus axis: sum((a-1)/a)
        # instead of the flat slice's (15/16) — strictly more wire,
        # spread over two independent link families
        s = 1024.0
        got = striped_wire_bytes("all_to_all", s, 4, (4, 4))
        assert got["ici"] == pytest.approx(s * (3.0 / 4.0 + 3.0 / 4.0))
        assert got["dcn"] == pytest.approx(s * 3.0 / 4.0)
        hier = hierarchical_wire_bytes("all_to_all", s, 16, 4)
        assert got["ici"] > hier["ici"]
        assert got["dcn"] == pytest.approx(hier["dcn"])

    @pytest.mark.parametrize(
        "op", ["all_reduce", "all_gather", "reduce_scatter"]
    )
    def test_striped_class_totals_match_hierarchical(self, op):
        # striping re-partitions, it does not add wire: for the
        # reduction/gather shapes the class totals equal the two-level
        # composition over the full slice
        s = 4096.0
        got = striped_wire_bytes(op, s, 2, (2, 4))
        hier = hierarchical_wire_bytes(op, s, 8, 2)
        assert got["ici"] == pytest.approx(hier["ici"])
        assert got["dcn"] == pytest.approx(hier["dcn"])
        assert got["stripes"] == 2

    def test_hierarchical_all_reduce_total_matches_flat(self):
        # the sanity anchor the compositions hang off: AR's two-level
        # total equals the flat ring for any factorization
        s = 4096.0
        hier = hierarchical_wire_bytes("all_reduce", s, 8, 2)
        assert hier["ici"] + hier["dcn"] == pytest.approx(
            ring_wire_bytes("all_reduce", s, 16)
        )

    def test_striped_degenerate_axes_drop_stripes(self):
        # a 1-extent axis contributes no stripe; a 1xN torus is exactly
        # the hierarchical composition
        s = 512.0
        got = striped_wire_bytes("all_reduce", s, 2, (1, 8))
        hier = hierarchical_wire_bytes("all_reduce", s, 8, 2)
        assert got["stripes"] == 1
        assert got["ici"] == pytest.approx(hier["ici"])
        assert got["dcn"] == pytest.approx(hier["dcn"])

    def test_striped_single_slice_has_no_dcn(self):
        got = striped_wire_bytes("all_reduce", 512.0, 1, (2, 2))
        assert got["dcn"] == 0.0
        assert got["ici"] > 0.0

    def test_striped_single_chip_slice_is_dcn_only(self):
        got = striped_wire_bytes("all_gather", 512.0, 4, (1, 1))
        assert got["ici"] == 0.0
        assert got["stripes"] == 1
        assert got["dcn"] == pytest.approx(512.0 * 3.0)


class TestClosedFormCosts:
    """Each family's terms verified against the formulas stated in the
    family bases and perfmodel.cost, with d = 8 (the test sim)."""

    def test_every_registered_family_has_a_model(self):
        assert set(ALLOWED_PRIMITIVES) <= set(FAMILY_COST_MODELS)

    def test_wire_itemsize_rules(self):
        assert wire_itemsize("bfloat16") == 2
        assert wire_itemsize("float64") == 4  # device arrays run f32
        with pytest.raises(ValueError):
            wire_itemsize("complex64")

    def test_tp_columnwise(self):
        impl = _impl("tp_columnwise", "jax_spmd", 512, 512, 512)
        est = estimate(impl, _v5e())
        d = impl.num_partitions
        compute = 2.0 * 512**3 / d / 197e12
        comm = (512 // d) * 512 * 2 * (d - 1) / (50.0 * GB)
        assert est.compute_s == pytest.approx(compute)
        assert est.comm_s == pytest.approx(comm)
        # jax_spmd is sequential: AG then GEMM
        assert est.predicted_s == pytest.approx(compute + comm)
        assert est.bound == "comm"  # thin wire dominates at 512^3
        assert est.chip == "v5e"

    def test_tp_columnwise_overlap_takes_max(self):
        impl = _impl(
            "tp_columnwise", "overlap", 512, 512, 512,
            algorithm="p2p_pipeline",
        )
        est = estimate(impl, _v5e())
        assert est.predicted_s == pytest.approx(
            max(est.compute_s, est.comm_s)
        )

    def test_tp_columnwise_dcn_transport(self):
        impl = _impl(
            "tp_columnwise", "jax_spmd", 512, 512, 512, transport="dcn"
        )
        est = estimate(impl, _v5e())
        d = impl.num_partitions
        assert est.comm_s == pytest.approx(
            (512 // d) * 512 * 2 * (d - 1) / (6.25 * GB)
        )

    def test_tp_rowwise(self):
        impl = _stub("tp_rowwise", "jax_spmd", 512, 512, 512)
        est = estimate(impl, _v5e())
        d = impl.num_partitions
        assert est.comm_s == pytest.approx(
            (512 * 512 // d) * 2 * (d - 1) / (50.0 * GB)
        )
        assert est.compute_s == pytest.approx(2.0 * 512**3 / d / 197e12)

    def test_dp_allreduce_is_twice_the_rs_wire(self):
        rs = _stub("tp_rowwise", "jax_spmd", 512, 512, 512)
        ar = _stub("dp_allreduce", "jax_spmd", 512, 512, 512)
        assert ar.wire_bytes() == pytest.approx(2.0 * rs.wire_bytes())

    def test_ep_alltoall(self):
        impl = _stub("ep_alltoall", "jax_spmd", 512, 256, 128)
        est = estimate(impl, _v5e())
        d = impl.num_partitions
        wire = (512 // d) * (128 + 256) * 2 * (d - 1) / d
        assert est.comm_s == pytest.approx(wire / (50.0 * GB))
        assert est.compute_s == pytest.approx(
            2.0 * 512 * 256 * 128 / d / 197e12
        )

    def test_cp_ring_attention(self):
        # m=1024 seq, n=256 width, k=64 head_dim -> 4 heads
        impl = _stub("cp_ring_attention", "ring", 1024, 256, 64)
        est = estimate(impl, _v5e())
        d = impl.num_partitions
        compute = 2.0 * 1024 * 1024 * 256 / d / 197e12
        wire = 2.0 * (1024 // d) * 4 * 64 * 2 * (d - 1)
        assert est.compute_s == pytest.approx(compute)
        assert est.comm_s == pytest.approx(wire / (50.0 * GB))
        # the ring overlaps KV hops with block compute
        assert est.predicted_s == pytest.approx(
            max(est.compute_s, est.comm_s)
        )

    def test_cp_window_prunes_ring_hops(self):
        full = _stub("cp_ring_attention", "ring", 1024, 256, 64)
        # window of one local chunk: only 1 of the d-1 hops intersects
        chunk = 1024 // full.num_partitions
        windowed = _stub(
            "cp_ring_attention", "ring", 1024, 256, 64, window=chunk
        )
        d = full.num_partitions
        assert windowed.wire_bytes() == pytest.approx(
            full.wire_bytes() / (d - 1)
        )

    def test_cp_gqa_shrinks_wire(self):
        mha = _stub("cp_ring_attention", "ring", 1024, 256, 64)
        gqa = _stub(
            "cp_ring_attention", "ring", 1024, 256, 64, n_kv_heads=2
        )
        assert gqa.wire_bytes() == pytest.approx(mha.wire_bytes() / 2.0)

    def test_pp_pipeline(self):
        impl = _stub("pp_pipeline", "jax_spmd", 512, 256, 256)
        est = estimate(impl, _v5e())
        d = impl.num_partitions
        # flops = 2*m*k*n*d; per device one stage's stream: 2*m*k*n
        assert est.compute_s == pytest.approx(
            2.0 * 512 * 256 * 256 / 197e12
        )
        # the step's actual ppermute census (DDLB123): drain ring every
        # tick + activation ring on the mb+d-2 fill ticks, [rows, n]
        # bf16 each (k == n)
        mb = impl.options["microbatches"]
        rows = 512 // mb
        ticks = max(mb + d - 1, mb + 2 * d - 3)
        wire = (ticks + mb + d - 2) * rows * 256 * 2
        assert est.comm_s == pytest.approx(wire / (50.0 * GB))
        assert est.predicted_s == pytest.approx(
            max(est.compute_s, est.comm_s)
        )
        assert d == 8

    def test_pp_schedules_wire_counts_both_rings_every_tick(self):
        from ddlb_tpu.utils.pipeline_schedule import build_schedule

        impl = _stub("pp_pipeline", "schedules", 512, 256, 256)
        d = impl.num_partitions
        mb = impl.options["microbatches"]
        rows = 512 // mb
        ticks = build_schedule("1f1b", d, mb, 1).ticks
        hops = ticks * rows * (256 + 256) * 2
        collect = 2.0 * (mb * rows * 256 * 2) * (d - 1) / d
        assert impl.wire_bytes() == pytest.approx(hops + collect)
        assert d == 8

    def test_collectives_ring_and_copy_roofline(self):
        ag = _stub("collectives", "jax_spmd", 512, 8, 512, op="all_gather")
        est = estimate(ag, _v5e())
        d = ag.num_partitions
        shard = (512 // d) * 512 * 2
        assert est.comm_s == pytest.approx(shard * (d - 1) / (50.0 * GB))
        assert est.compute_s == 0.0
        assert est.bound == "comm"
        # the compute_only member is an HBM copy: payload read + written
        copy = _impl(
            "collectives", "compute_only", 512, 8, 512, size="sharded"
        )
        est2 = estimate(copy, _v5e())
        assert est2.hbm_s == pytest.approx(2.0 * shard / (819.0 * GB))
        assert est2.comm_s == 0.0
        assert est2.bound == "hbm"

    def test_transformer_step_compute_floor(self, runtime):
        # construction compiles the model: probe the census via the ABC
        # contract on an uninitialized instance (flops() is shape-only,
        # but the auto mesh factorization reads runtime.num_devices)
        cls = load_impl_class("transformer_step", "compute_only")
        impl = object.__new__(cls)
        impl.m, impl.n, impl.k = 128, 256, 512
        impl.dtype = "bfloat16"
        impl.num_partitions = 8
        impl.runtime = runtime
        defaults, _ = cls.option_schema()
        impl.options = dict(defaults)
        est_terms = FAMILY_COST_MODELS["transformer_step"](impl, _v5e())
        compute, comm, hbm = est_terms
        assert compute == pytest.approx(impl.flops() / 8 / 197e12)
        assert comm == 0.0 and hbm == 0.0

    def test_transformer_decode_hbm_census(self):
        from ddlb_tpu.utils.hbm_budget import decode_budget

        cls = load_impl_class("transformer_decode", "spmd")
        impl = object.__new__(cls)
        impl.m, impl.n, impl.k = 1024, 256, 512
        impl.dtype = "bfloat16"
        impl.num_partitions = 1
        defaults, _ = cls.option_schema()
        impl.options = dict(defaults)
        rep = decode_budget(
            ctx=1024, d_model=256, d_ff=512, vocab=defaults["vocab"],
            n_heads=defaults["n_heads"], batch=defaults["batch"],
            layers=defaults["layers"], phase="decode", validate=False,
        )
        expected = rep.components["weights"] + rep.components["kv_cache"]
        assert impl.hbm_bytes() == pytest.approx(expected)
        compute, comm, hbm = FAMILY_COST_MODELS["transformer_decode"](
            impl, _v5e()
        )
        assert hbm == pytest.approx(expected / (819.0 * GB))
        assert comm == 0.0

    def test_quantized_members_priced_at_int8_peak(self):
        q = _stub("tp_columnwise", "quantized", 512, 512, 512)
        bf = _stub("tp_columnwise", "jax_spmd", 512, 512, 512)
        assert q.cost_dtype() == "int8"
        est_q = estimate(q, _v5e())
        est_bf = estimate(bf, _v5e())
        # int8 MXU runs 2x the bf16 roofline -> half the compute floor
        assert est_q.compute_s == pytest.approx(est_bf.compute_s / 2.0)
        # the gathered shard travels int8 (half the bf16 wire) plus the
        # per-row f32 scales' ride-along all_gather (DDLB123)
        d = q.num_partitions
        assert q.wire_bytes() == pytest.approx(
            (512 // d) * (512 + 4) * (d - 1)
        )
        assert q.wire_bytes() < bf.wire_bytes()

    def test_quantized_reduction_wire_stays_operand_dtype(self):
        # tp_rowwise/dp quantized reduce in full precision: only the MXU
        # term is repriced, the wire census is the family's
        q = _stub("tp_rowwise", "quantized", 512, 512, 512)
        bf = _stub("tp_rowwise", "jax_spmd", 512, 512, 512)
        assert q.wire_bytes() == pytest.approx(bf.wire_bytes())
        assert q.cost_dtype() == "int8"
        # ep quantized: int8 dispatch (+ 4 B/token f32 scales on the
        # second all_to_all, DDLB123) + operand-dtype combine
        qep = _stub("ep_alltoall", "quantized", 512, 256, 128)
        d = qep.num_partitions
        expected = (512 // d) * (128 * 1 + 4 + 256 * 2) * (d - 1) / d
        assert qep.wire_bytes() == pytest.approx(expected)

    def test_speculate_hbm_floor_assumes_all_accepted(self):
        dec = _stub(
            "transformer_decode", "spmd", 1024, 256, 512, d=1,
            phase="generate", n_new=32,
        )
        spec = _stub(
            "transformer_decode", "spmd", 1024, 256, 512, d=1,
            phase="speculate", n_new=32, spec_k=4,
        )
        # same target-model per-pass census (the draft is excluded from
        # the floor), but only ceil(n_new/(spec_k+1)) verify passes —
        # speculation's bandwidth win over generate's n_new re-reads
        spec_passes = -(-32 // (4 + 1))  # = 7
        assert spec.hbm_bytes() == pytest.approx(
            dec.hbm_bytes() * spec_passes / 32
        )

    def test_collectives_copy_has_zero_wire_but_keeps_throughput(self):
        copy = _impl(
            "collectives", "compute_only", 512, 8, 512, size="sharded"
        )
        assert copy.wire_bytes() == 0.0  # no phantom collective_bytes
        d = copy.num_partitions
        payload = (512 // d) * 512 * 2
        assert copy.hbm_bytes() == pytest.approx(payload)
        # the family's GB/s Throughput convention survives the split
        assert copy.flops() == pytest.approx(1000.0 * payload)

    def test_compute_only_members_report_zero_wire(self):
        impl = _impl(
            "tp_columnwise", "compute_only", 512, 512, 512, size="sharded"
        )
        assert impl.wire_bytes() == 0.0
        est = estimate(impl, _v5e())
        assert est.comm_s == 0.0
        assert est.bound == "compute"

    def test_unknown_family_raises(self):
        class Fake:
            primitive_name = "not_a_family"

        with pytest.raises(ValueError):
            estimate(Fake(), _v5e())

    def test_roofline_frac_clamps_and_nans(self):
        impl = _impl("tp_columnwise", "jax_spmd", 512, 512, 512)
        est = estimate(impl, _v5e())
        assert est.roofline_frac(est.predicted_s * 10.0) == pytest.approx(0.1)
        assert est.roofline_frac(est.predicted_s / 10.0) == 1.0  # clamped
        assert math.isnan(est.roofline_frac(float("nan")))
        assert math.isnan(est.roofline_frac(0.0))


# ---------------------------------------------------------------------------
# row columns through the runner
# ---------------------------------------------------------------------------


PERF_COLUMNS = ("predicted_s", "roofline_frac", "bound", "chip")


def _worker_config(**over):
    cfg = {
        "primitive": "tp_columnwise",
        "impl_id": "jax_spmd_t",
        "base_implementation": "jax_spmd",
        "options": {},
        "m": 256,
        "n": 256,
        "k": 256,
        "dtype": "bfloat16",
        "num_iterations": 2,
        "num_warmups": 1,
        "validate": False,
    }
    cfg.update(over)
    return cfg


class TestRowColumns:
    def test_measured_row_carries_perf_columns(self, runtime, monkeypatch):
        monkeypatch.delenv("DDLB_TPU_CHIP", raising=False)
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(_worker_config())
        assert row["error"] == ""
        assert np.isfinite(row["predicted_s"]) and row["predicted_s"] > 0
        assert 0.0 < row["roofline_frac"] <= 1.0
        assert row["bound"] in ("compute", "comm", "hbm")
        assert row["chip"] == "cpu-sim"
        # the family wire census landed in the telemetry column too
        assert row["collective_bytes"] > 0

    def test_error_row_still_carries_perf_columns(self, runtime):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            _worker_config(options={"no_such_option": 1})
        )
        assert row["error"]
        for col in PERF_COLUMNS:
            assert col in row
        assert math.isnan(row["predicted_s"])
        assert math.isnan(row["roofline_frac"])
        assert row["bound"] == "" and row["chip"] == ""

    def test_error_row_after_construction_keeps_prediction(self, runtime):
        """A crash AFTER the impl exists (here: validation) must not
        lose the shape-only prediction — only roofline_frac needs the
        measurement."""
        from ddlb_tpu import benchmark as bench_mod

        class Boom(Exception):
            pass

        orig = bench_mod._timing_loop

        def exploding(*a, **k):
            raise Boom("timing crashed")

        bench_mod._timing_loop = exploding
        try:
            row = bench_mod.benchmark_worker(_worker_config())
        finally:
            bench_mod._timing_loop = orig
        assert "Boom" in row["error"]
        assert np.isfinite(row["predicted_s"]) and row["predicted_s"] > 0
        assert row["bound"] in ("compute", "comm", "hbm")
        assert math.isnan(row["roofline_frac"])

    def test_subprocess_death_row_has_default_columns(self):
        from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

        runner = PrimitiveBenchmarkRunner(
            "tp_columnwise", 256, 256, 256, {"jax_spmd_0": {}},
            isolation="subprocess",
        )
        row = runner._error_row(
            runner._worker_config("jax_spmd_0", {}), "WorkerDied: test"
        )
        for col in PERF_COLUMNS:
            assert col in row


# ---------------------------------------------------------------------------
# the acceptance sweep: scripts/config.json impl blocks on the CPU sim
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sweep_csv(tmp_path_factory):
    """One CPU-sim sweep over the SHIPPED scripts/config.json
    implementation blocks (shape reduced to 256^3 so 8 virtual devices
    finish in test time), written through the real runner CSV path."""
    from ddlb_tpu.cli import load_config, run_benchmark

    cfg = load_config(os.path.join(REPO, "scripts", "config.json"))
    bench = cfg["benchmark"]
    bench["m"] = bench["n"] = bench["k"] = [256]
    bench["num_iterations"] = 2
    bench["num_warmups"] = 1
    bench["validate"] = False
    bench["progress"] = False
    out = tmp_path_factory.mktemp("perfmodel") / "sweep.csv"
    bench["output_csv"] = str(out)
    run_benchmark(cfg)
    return out


class TestConfigSweepInvariant:
    def test_every_row_has_bounded_roofline_frac(self, sweep_csv):
        import pandas as pd

        df = pd.read_csv(sweep_csv)
        assert len(df) >= 10  # config.json expands to 11 impl configs
        for col in PERF_COLUMNS:
            assert col in df.columns
        assert (df["error"].fillna("") == "").all()
        assert df["predicted_s"].gt(0).all()
        assert df["roofline_frac"].gt(0).all()
        assert df["roofline_frac"].le(1.0).all()
        assert set(df["bound"]) <= {"compute", "comm", "hbm"}
        assert (df["chip"] == "cpu-sim").all()

    def test_perf_report_ranks_the_sweep(self, sweep_csv):
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "perf_report.py"),
                str(sweep_csv),
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "== tp_columnwise" in out.stdout
        assert "roofline" in out.stdout

    def test_perf_report_json_mode(self, sweep_csv):
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "perf_report.py"),
                str(sweep_csv),
                "--json",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        payload = json.loads(out.stdout)
        ranking = payload["families"]["tp_columnwise"]
        assert len(ranking) >= 5
        fracs = [
            e["roofline_frac"]
            for e in ranking
            if e["roofline_frac"] is not None
        ]
        # ranked descending by achieved fraction
        assert fracs == sorted(fracs, reverse=True)
        assert all(0.0 < f <= 1.0 for f in fracs)

    def test_perf_report_rejects_pre_perfmodel_csv(self, tmp_path):
        legacy = tmp_path / "legacy.csv"
        legacy.write_text("implementation,primitive\nx,tp_columnwise\n")
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "perf_report.py"),
                str(legacy),
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 2
        assert "predates" in out.stderr


# ---------------------------------------------------------------------------
# hbm_budget reads capacity from the registry
# ---------------------------------------------------------------------------


class TestBudgetSpecTie:
    def test_capacity_comes_from_registry(self):
        from ddlb_tpu.utils import hbm_budget

        assert hbm_budget.V5E_HBM_BYTES == get_spec("v5e").hbm_bytes
        assert hbm_budget.default_limit("v4") == pytest.approx(
            0.9 * get_spec("v4").hbm_bytes
        )

    def test_chip_override_resizes_gate(self, monkeypatch):
        from ddlb_tpu.utils.hbm_budget import decode_budget

        kwargs = dict(
            ctx=1024, d_model=256, d_ff=512, vocab=512, n_heads=8, batch=8
        )
        monkeypatch.delenv("DDLB_TPU_CHIP", raising=False)
        v5e_limit = decode_budget(**kwargs).limit
        monkeypatch.setenv("DDLB_TPU_CHIP", "v5p")
        v5p_limit = decode_budget(**kwargs).limit
        assert v5p_limit == pytest.approx(
            0.9 * get_spec("v5p").hbm_bytes
        )
        assert v5p_limit > v5e_limit
