"""Block autotuner: candidate grid, cache round-trip, member wiring.

The measured WINNER is only meaningful on hardware; what the sim pins is
the mechanism — candidates filtered by divisibility, unbuildable
candidates skipped not fatal, the winner persisted and reused without
re-measurement, and the ``tune`` option wired through the members'
option schemas (tune+explicit-blocks rejected, dead-option rules).
"""

import json

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class
from ddlb_tpu.utils import autotune as at


@pytest.fixture()
def cache(tmp_path, monkeypatch):
    path = tmp_path / "autotune.json"
    monkeypatch.setenv("DDLB_TPU_AUTOTUNE_CACHE", str(path))
    return path


def test_candidates_respect_divisibility():
    cands = list(at.gemm_block_candidates(512, 256, 512))
    assert cands, "grid must be non-empty"
    for bm, bn, bk in cands:
        assert 512 % bm == 0 and 256 % bn == 0 and 512 % bk == 0


def test_candidates_clamp_to_shape():
    for bm, bn, bk in at.gemm_block_candidates(256, 128, 256):
        assert bm <= 256 and bn <= 128 and bk <= 256


def test_autotune_picks_best_and_caches(cache, monkeypatch):
    calls = []

    def build(c):
        calls.append(c)
        import jax
        import jax.numpy as jnp

        return jax.jit(lambda x: x + 1), (jnp.ones((8, 8), jnp.float32),)

    # Deterministic fake timer: candidate (a,) "costs" a ms. The test
    # pins the MECHANISM (ranking, persistence, cache hit), not the
    # clock — real candidates differ by µs of CPU work here, and timing
    # them under host load made this test jitter-flaky (r4 verdict).
    import ddlb_tpu.utils.timing as timing

    def fake_measure(fn, args, num_iterations, **kw):
        fn(*args)  # the candidate must still build and run
        return [float(calls[-1][0])] * num_iterations

    monkeypatch.setattr(timing, "measure_device_loop", fake_measure)

    best = at.autotune(
        "fake_kernel", 64, 64, 64, "float32",
        [(8,), (1,)],  # slow candidate first: ranking, not ordering
        build,
        num_iterations=2,
        num_windows=1,
    )
    assert best == (1,)
    data = json.load(open(cache))
    (entry,) = data.values()
    assert entry["blocks"] == [1]
    assert len(entry["tried"]) == 2

    # second call: cache hit, no rebuilds
    calls.clear()
    again = at.autotune(
        "fake_kernel", 64, 64, 64, "float32", [(1,), (8,)], build,
    )
    assert again == (1,) and calls == []


def test_autotune_skips_unbuildable(cache):
    def build(c):
        if c == (2,):
            raise RuntimeError("VMEM")
        import jax
        import jax.numpy as jnp

        return jax.jit(lambda x: x + 1), (jnp.ones((8, 8)),)

    best = at.autotune(
        "fragile", 8, 8, 8, "float32", [(2,), (4,)], build,
        num_iterations=2, num_windows=1, min_window_s=0.0,
    )
    assert best == (4,)


def test_autotune_all_unbuildable_raises(cache):
    def build(c):
        raise RuntimeError("nope")

    with pytest.raises(ValueError, match="no candidate"):
        at.autotune(
            "dead", 8, 8, 8, "float32", [(2,)], build,
            num_iterations=2, num_windows=1,
        )


def test_tp_columnwise_tune_runs_and_caches(cache):
    cls = load_impl_class("tp_columnwise", "pallas")
    impl = cls(512, 256, 512, dtype="float32", tune=True)
    assert impl.validate(impl.run())
    data = json.load(open(cache))
    assert any(k.startswith("tp_columnwise_pallas_AG_before") for k in data)
    # reconstruction hits the cache (blocks equal, no growth in entries)
    cls(512, 256, 512, dtype="float32", tune=True)
    assert len(json.load(open(cache))) == len(data)


def test_tune_rejects_explicit_blocks():
    cls = load_impl_class("tp_columnwise", "pallas")
    with pytest.raises(ValueError, match="tune=true picks the blocks"):
        cls(512, 256, 512, dtype="float32", tune=True, block_m=512)


def test_tune_dead_with_ring_rdma():
    cls = load_impl_class("tp_columnwise", "pallas")
    with pytest.raises(ValueError, match="no effect"):
        cls(512, 256, 512, dtype="float32", algorithm="ring_rdma", tune=True)


def test_quantized_tune_dead_with_xla_kernel():
    cls = load_impl_class("tp_columnwise", "quantized")
    with pytest.raises(ValueError, match="no effect"):
        cls(512, 256, 512, dtype="bfloat16", kernel="xla", tune=True)


def test_quantized_pallas_tune(cache):
    cls = load_impl_class("tp_columnwise", "quantized")
    impl = cls(256, 256, 256, dtype="bfloat16", kernel="pallas", tune=True)
    assert impl.validate(impl.run())
    data = json.load(open(cache))
    assert any(k.startswith("int8_matmul_pallas") for k in data)


def test_ep_quantized_tunes_local_gemm_shape(cache):
    # the expert GEMM sees m/d rows; the cache key must record THAT shape
    cls = load_impl_class("ep_alltoall", "quantized")
    impl = cls(512, 256, 256, dtype="bfloat16", kernel="pallas", tune=True)
    assert impl.validate(impl.run())
    d = impl.num_partitions
    keys = list(json.load(open(cache)))
    assert any(k.startswith(f"int8_matmul_pallas:{512 // d}x256x256") for k in keys), keys


def test_cache_key_includes_partitions():
    from ddlb_tpu.utils.autotune import make_key

    assert ":d4:" in make_key("x", 8, 8, 8, "float32", 4)
    assert make_key("x", 8, 8, 8, "float32", 4) != make_key(
        "x", 8, 8, 8, "float32", 8
    )
