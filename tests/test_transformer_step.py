"""transformer_step: the flagship model's step through the benchmark
runner (VERDICT r1 item #4) — CSV rows, validation against the
single-device oracle, option/mesh sweeps, and shape-constraint errors,
all on the 8-device CPU mesh.
"""

import numpy as np
import pytest

from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner, benchmark_worker
from ddlb_tpu.primitives.registry import load_impl_class

# m=seq, n=d_model, k=d_ff; einsum attention keeps interpret-mode cost out
# of the suite (the flash path is pinned by tests/test_flash_grad.py)
SHAPE = dict(m=16, n=16, k=32)
SMALL = dict(
    batch=4, vocab=32, n_heads=4, microbatches=2, attn_kernel="einsum"
)


def _worker_config(**over):
    cfg = {
        "primitive": "transformer_step",
        "impl_id": "spmd_0",
        "base_implementation": "spmd",
        "options": dict(SMALL),
        "dtype": "float32",
        "num_iterations": 2,
        "num_warmups": 1,
        "validate": True,
        "time_measurement_backend": "host_clock",
        "barrier_at_each_iteration": False,
        **SHAPE,
    }
    cfg.update(over)
    return cfg


def test_spmd_train_step_row_validates():
    row = benchmark_worker(_worker_config())
    assert row["error"] == ""
    assert row["valid"] is True  # loss == single-device oracle loss
    assert row["mean time (ms)"] > 0
    assert row["Throughput (TFLOPS)"] > 0
    assert row["world_size"] == 8


def test_spmd_forward_mode_and_ring_attention():
    row = benchmark_worker(
        _worker_config(
            options={**SMALL, "mode": "forward", "attention": "ring"}
        )
    )
    assert row["error"] == ""
    assert row["valid"] is True


def test_compute_only_roofline_validates():
    row = benchmark_worker(
        _worker_config(
            impl_id="compute_only_0",
            base_implementation="compute_only",
            options={**SMALL, "mode": "forward"},
        )
    )
    assert row["error"] == ""
    assert row["valid"] is True


def test_train_flops_triple_of_forward():
    cls = load_impl_class("transformer_step", "spmd")
    train = cls(dtype="float32", **SHAPE, **SMALL)
    fwd = cls(dtype="float32", **SHAPE, mode="forward", **SMALL)
    assert train.flops() == pytest.approx(3.0 * fwd.flops())
    # census spot-check: layers*(8D^2+2SD+4DF) + 2DV per token, B*S tokens
    D, F, S, V, B = 16, 32, 16, 32, 4
    L = 2 * 1  # pp stages x layers_per_stage
    per_token = L * (8 * D * D + 2 * S * D + 4 * D * F) + 2 * D * V
    assert fwd.flops() == pytest.approx(B * S * per_token)


def test_explicit_mesh_factors_and_mismatch():
    cls = load_impl_class("transformer_step", "spmd")
    impl = cls(dtype="float32", **SHAPE, **SMALL, dp=1, tp=4, pp=2)
    assert impl.mesh.shape == {"dp": 1, "tp": 4, "pp": 2}
    with pytest.raises(ValueError, match="devices"):
        cls(dtype="float32", **SHAPE, **SMALL, dp=2, tp=4, pp=2)
    with pytest.raises(ValueError, match="all of dp/tp/pp"):
        cls(dtype="float32", **SHAPE, **SMALL, tp=4)


def test_shape_constraint_errors():
    cls = load_impl_class("transformer_step", "spmd")
    with pytest.raises(ValueError, match="d_model"):
        cls(m=16, n=18, k=32, dtype="float32", **SMALL)
    with pytest.raises(ValueError, match="batch"):
        cls(dtype="float32", **SHAPE, **{**SMALL, "batch": 3})
    with pytest.raises(ValueError, match="floating"):
        cls(dtype="int32", **SHAPE, **SMALL)
    with pytest.raises(ValueError, match="mode"):
        cls(dtype="float32", **SHAPE, **{**SMALL, "mode": "serve"})


def test_runner_sweep_attention_modes(tmp_path):
    """The sweep axis the VERDICT asks for: attention=gathered|ring
    through the same runner/CSV as every other primitive."""
    import pandas as pd

    csv = str(tmp_path / "model.csv")
    runner = PrimitiveBenchmarkRunner(
        "transformer_step",
        implementations={
            "spmd_0": {"implementation": "spmd", **SMALL,
                       "attention": "gathered"},
            "spmd_1": {"implementation": "spmd", **SMALL,
                       "attention": "ring"},
        },
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        **SHAPE,
    )
    df = runner.run()
    assert len(df) == 2
    assert df["valid"].all()
    on_disk = pd.read_csv(csv)
    assert sorted(on_disk["implementation"]) == ["spmd_0", "spmd_1"]
    assert any("attention=ring" in o for o in on_disk["option"])


def test_xla_gspmd_train_step_row_validates():
    """The compiler-partitioned step: GSPMD gets only the sharding
    annotations yet must reproduce the oracle loss exactly (same math)."""
    row = benchmark_worker(
        _worker_config(
            impl_id="xla_gspmd_0",
            base_implementation="xla_gspmd",
        )
    )
    assert row["error"] == ""
    assert row["valid"] is True
    assert row["world_size"] == 8


def test_xla_gspmd_forward_with_compiler_knobs():
    row = benchmark_worker(
        _worker_config(
            impl_id="xla_gspmd_0",
            base_implementation="xla_gspmd",
            options={
                **SMALL,
                "mode": "forward",
                "collective_matmul": "force",
            },
        )
    )
    assert row["error"] == ""
    assert row["valid"] is True
    assert "collective_matmul=force" in row["option"]


def test_device_loop_backend_on_model_step():
    """The compiled-loop timing backend handles the (params, opt) pytree
    via the token-first arg reorder; stats come from real windows."""
    row = benchmark_worker(
        _worker_config(
            time_measurement_backend="device_loop",
            validate=False,
            device_loop_windows=3,
            device_loop_min_window_ms=0,
        )
    )
    assert row["error"] == ""
    assert row["mean time (ms)"] > 0
    assert row["std time (ms)"] > 0
