"""End-to-end sweep runner tests on the CPU mesh.

Exercises the full reference call stack 3.1/3.2 (SURVEY.md section 3):
config -> expansion -> runner -> worker -> timing -> validation -> CSV.
"""

import json
import os

import pandas as pd
import pytest

from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner, benchmark_worker
from ddlb_tpu.cli.benchmark import run_benchmark

SHAPE = dict(m=128, n=32, k=64)


def _worker_config(**over):
    cfg = {
        "primitive": "tp_columnwise",
        "impl_id": "jax_spmd_0",
        "base_implementation": "jax_spmd",
        "options": {},
        "dtype": "float32",
        "num_iterations": 3,
        "num_warmups": 1,
        "validate": True,
        "time_measurement_backend": "host_clock",
        "barrier_at_each_iteration": True,
        "profile_dir": None,
        **SHAPE,
    }
    cfg.update(over)
    return cfg


def test_worker_row_schema():
    row = benchmark_worker(_worker_config())
    for col in (
        "implementation",
        "mean time (ms)",
        "std time (ms)",
        "min time (ms)",
        "max time (ms)",
        "m",
        "n",
        "k",
        "dtype",
        "Throughput (TFLOPS)",
        "unit",
        "world_size",
        "hostname",
        "time_measurement_backend",
        "barrier_at_each_iteration",
        "option",
        "valid",
    ):
        assert col in row, col
    assert row["valid"] is True
    assert row["mean time (ms)"] > 0
    assert row["Throughput (TFLOPS)"] > 0
    assert row["world_size"] == 8


@pytest.mark.parametrize("backend", ["host_clock", "device_loop"])
@pytest.mark.parametrize("barrier", [True, False])
def test_timing_backends(backend, barrier):
    row = benchmark_worker(
        _worker_config(
            time_measurement_backend=backend, barrier_at_each_iteration=barrier
        )
    )
    assert row["mean time (ms)"] > 0


def test_worker_crash_becomes_row():
    row = benchmark_worker(_worker_config(options={"order": "bogus"}))
    assert row["valid"] is False
    assert row["error"]


def test_unknown_timing_backend():
    with pytest.raises(ValueError, match="timing backend"):
        benchmark_worker(_worker_config(time_measurement_backend="cuda_event"))


def test_runner_csv_and_dataframe(tmp_path):
    csv = str(tmp_path / "out.csv")
    runner = PrimitiveBenchmarkRunner(
        "tp_rowwise",
        implementations={
            "jax_spmd_0": {"implementation": "jax_spmd"},
            "overlap_0": {"implementation": "overlap", "algorithm": "p2p_pipeline"},
        },
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=csv,
        progress=False,
        **SHAPE,
    )
    df = runner.run()
    assert len(df) == 2
    assert df["valid"].all()
    on_disk = pd.read_csv(csv)
    assert len(on_disk) == 2  # incremental append, one row per impl


def test_known_world_size_override_and_disk_cache(tmp_path, monkeypatch):
    """VERDICT r3 weak #6: the resume world-size probe honors the
    DDLB_TPU_WORLD_SIZE override and caches a probed value next to the
    CSV, so a resumed sweep on a hung relay never re-pays the 120 s
    probe."""
    csv = str(tmp_path / "out.csv")
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
        dtype="float32", output_csv=csv, progress=False,
        isolation="subprocess", **SHAPE,
    )
    # sim world (conftest) short-circuits everything: pin the env override
    # and cache layers by masking the sim count
    monkeypatch.setattr(
        "ddlb_tpu.envs.get_sim_device_count", lambda: 0
    )
    monkeypatch.setenv("DDLB_TPU_WORLD_SIZE", "16")
    assert runner._known_world_size() == 16
    monkeypatch.setenv("DDLB_TPU_WORLD_SIZE", "not-a-number")
    # falls through the override; a pre-seeded disk cache answers without
    # any subprocess probe
    with open(f"{csv}.world_size", "w") as f:
        f.write("4\n")
    assert runner._known_world_size() == 4
    # 0 = disabled (the DDLB_TPU_* env convention), not a world size
    monkeypatch.setenv("DDLB_TPU_WORLD_SIZE", "0")
    assert runner._known_world_size() == 4
    # and the memoized value sticks
    monkeypatch.delenv("DDLB_TPU_WORLD_SIZE")
    assert runner._known_world_size() == 4


def test_runner_rejects_unknown_primitive():
    with pytest.raises(ValueError, match="Unknown primitive"):
        PrimitiveBenchmarkRunner(
            "tp_diagonal", implementations={}, **SHAPE
        )


def test_run_benchmark_config_sweep(tmp_path):
    csv = str(tmp_path / "sweep_{timestamp}.csv")
    config = {
        "benchmark": {
            "primitive": "tp_columnwise",
            "m": [128],
            "n": [32, 64],
            "k": [64],
            "dtype": "float32",
            "num_iterations": 2,
            "num_warmups": 1,
            "validate": True,
            "implementations": {
                "jax_spmd": [{"order": ["AG_before", "AG_after"]}],
            },
            "output_csv": csv,
            "progress": False,
        }
    }
    df = run_benchmark(config)
    # 2 shapes x 2 option combos
    assert len(df) == 4
    assert df["valid"].all()
    written = [f for f in os.listdir(tmp_path) if f.endswith(".csv")]
    assert len(written) == 1
    assert "{timestamp}" not in written[0]


def test_plot_results(tmp_path):
    df = pd.DataFrame(
        [
            {
                "implementation": "jax_spmd_0",
                "option": "order=AG_before",
                "mean time (ms)": 1.0,
                "std time (ms)": 0.1,
                "m": 128,
                "n": 32,
                "k": 64,
                "dtype": "float32",
                "world_size": 8,
            }
        ]
    )
    path = PrimitiveBenchmarkRunner.plot_results(df, str(tmp_path / "plot.png"))
    assert os.path.exists(path)


def test_json_script_entry(tmp_path):
    """scripts/run_benchmark.py end-to-end with a JSON file."""
    config_path = tmp_path / "cfg.json"
    config_path.write_text(
        json.dumps(
            {
                "benchmark": {
                    "primitive": "tp_rowwise",
                    "m": [128],
                    "n": [32],
                    "k": [64],
                    "dtype": "float32",
                    "num_iterations": 2,
                    "num_warmups": 1,
                    "implementations": {"jax_spmd": [{}]},
                    "output_csv": str(tmp_path / "r.csv"),
                    "progress": False,
                }
            }
        )
    )
    from ddlb_tpu.cli import load_config

    df = run_benchmark(load_config(str(config_path)))
    assert len(df) == 1 and df["valid"].all()


def test_csv_append_aligns_to_existing_header(tmp_path):
    """Appends to a CSV written under an older schema stay parseable."""
    import pandas as pd

    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    path = tmp_path / "old.csv"
    pd.DataFrame(
        [{"implementation": "legacy", "mean time (ms)": 1.0, "valid": True}]
    ).to_csv(path, index=False)
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise",
        m=64,
        n=32,
        k=64,
        implementations={"compute_only_0": {"implementation": "compute_only"}},
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        output_csv=str(path),
        progress=False,
    )
    runner.run()
    df = pd.read_csv(path)  # must parse cleanly with the ORIGINAL columns
    assert list(df.columns) == ["implementation", "mean time (ms)", "valid"]
    assert len(df) == 2
