"""The ici/dcn transport & multi-slice topology axis (VERDICT r1 item #5).

The reference sweeps collective backends (nccl / ucc / ucc-tl-*,
/root/reference/ddlb/primitives/TPColumnwise/pytorch.py:32-45); the TPU
analogue is WHERE collectives ride — intra-slice ICI vs cross-slice DCN —
expressed as mesh device ordering (runtime.transport_mesh) plus a hybrid
(dcn, ici) mesh. Simulated slices (DDLB_TPU_SIM_SLICES) partition the CPU
mesh so the axis is sweepable and cross-"slice" collectives execute
without multi-slice hardware.
"""

import numpy as np
import pytest

from ddlb_tpu.runtime import Runtime


@pytest.fixture
def sliced_runtime(monkeypatch):
    """Runtime seeing the 8-device sim mesh as 2 slices of 4; restores the
    unsliced singleton afterwards."""
    monkeypatch.setenv("DDLB_TPU_SIM_SLICES", "2")
    Runtime.reset()
    try:
        yield Runtime()
    finally:
        monkeypatch.delenv("DDLB_TPU_SIM_SLICES")
        Runtime.reset()
        Runtime()  # rebuild the clean singleton for later tests


def test_slice_assignment_sim(sliced_runtime):
    rt = sliced_runtime
    assert rt.num_slices == 2
    assert rt.slice_ids == (0, 0, 0, 0, 1, 1, 1, 1)


def test_sim_slices_must_divide(monkeypatch):
    monkeypatch.setenv("DDLB_TPU_SIM_SLICES", "3")
    Runtime.reset()
    try:
        with pytest.raises(ValueError, match="does not divide"):
            Runtime()
    finally:
        monkeypatch.delenv("DDLB_TPU_SIM_SLICES")
        Runtime.reset()
        Runtime()


def test_transport_mesh_orders(sliced_runtime):
    rt = sliced_runtime
    ids = {d: i for i, d in enumerate(rt.devices)}
    ici = [ids[d] for d in rt.transport_mesh(("tp",), "ici").devices.flat]
    dcn = [ids[d] for d in rt.transport_mesh(("tp",), "dcn").devices.flat]
    # ici: slice-grouped (every hop intra-slice except one boundary)
    assert ici == [0, 1, 2, 3, 4, 5, 6, 7]
    # dcn: slices interleaved (EVERY neighbor hop crosses the boundary)
    assert dcn == [0, 4, 1, 5, 2, 6, 3, 7]
    with pytest.raises(ValueError, match="transport"):
        rt.transport_mesh(("tp",), "infiniband")


def test_transport_single_slice_is_identity(capsys):
    rt = Runtime()
    assert rt.num_slices == 1  # single-process sim: one "slice"
    mesh = rt.transport_mesh(("tp",), "dcn")
    assert list(mesh.devices.flat) == list(rt.devices)
    # a 'dcn' row on a one-slice topology would silently measure the ici
    # layout — the runtime must say so (code-review r2 finding)
    assert "single slice" in capsys.readouterr().out


def test_hybrid_mesh(sliced_runtime):
    mesh = sliced_runtime.hybrid_mesh(("dcn", "ici"))
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("dcn", "ici")
    ids = {d: i for i, d in enumerate(sliced_runtime.devices)}
    assert [[ids[d] for d in row] for row in mesh.devices] == [
        [0, 1, 2, 3],
        [4, 5, 6, 7],
    ]


def test_hybrid_mesh_collectives_execute(sliced_runtime):
    """Collectives on the hierarchical (dcn, ici) mesh actually run: a
    psum over each axis separately must see exactly that axis's extent,
    proving the two transport layers are independent reduction scopes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = sliced_runtime.hybrid_mesh(("dcn", "ici"))

    def body(x):
        over_ici = jax.lax.psum(x, "ici")  # intra-slice reduction
        over_dcn = jax.lax.psum(x, "dcn")  # cross-slice reduction
        return over_ici, over_dcn

    ones = jnp.ones((2, 4), jnp.float32)
    over_ici, over_dcn = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=P("dcn", "ici"),
            out_specs=(P("dcn", None), P(None, "ici")),
            check_vma=False,
        )
    )(ones)
    assert float(over_ici[0, 0]) == 4.0  # ici axis extent
    assert float(over_dcn[0, 0]) == 2.0  # dcn axis extent


@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_tp_transport_sweep(primitive, sliced_runtime, tmp_path):
    """The VERDICT done-criterion: tp primitives sweep transport=ici|dcn
    in sim, with cross-slice collectives executed and validated."""
    from ddlb_tpu.cli.benchmark import run_benchmark

    config = {
        "benchmark": {
            "primitive": primitive,
            "m": [128],
            "n": [32],
            "k": [64],
            "dtype": "float32",
            "num_iterations": 2,
            "num_warmups": 1,
            "validate": True,
            "implementations": {
                "jax_spmd": [{"transport": ["ici", "dcn"]}],
            },
            "output_csv": str(tmp_path / "transport.csv"),
            "progress": False,
        }
    }
    df = run_benchmark(config)
    assert len(df) == 2
    assert df["valid"].all()
    opts = sorted(df["option"])
    assert any("transport=dcn" in o for o in opts)
    assert any("transport=ici" in o for o in opts)


@pytest.mark.parametrize(
    "family", ["tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall"]
)
def test_quantized_transport_sweep(family, sliced_runtime):
    """The int8 members inherit the family transport axis: the int8-wire
    all-gather (columnwise) and dequantized-partial collectives ride the
    dcn-interleaved mesh and still validate."""
    from ddlb_tpu.primitives.registry import load_impl_class

    cls = load_impl_class(family, "quantized")
    for transport in ("ici", "dcn"):
        impl = cls(128, 32, 64, dtype="bfloat16", transport=transport)
        assert impl.validate(impl.run()), (family, transport)


def test_ring_kernel_on_dcn_mesh(sliced_runtime):
    """The RDMA ring kernel is transport-agnostic: on the interleaved
    (dcn) mesh every ppermute hop crosses the simulated slice boundary
    and the result must still validate."""
    from ddlb_tpu.primitives.registry import load_impl_class

    cls = load_impl_class("tp_columnwise", "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="ring_rdma", block_n=128, block_k=128, transport="dcn",
    )
    assert impl.validate(impl.run())


def test_transport_recorded_in_option_column():
    """Family-level BASE_OPTIONS surface in the recorded option string via
    the shared option_schema merge."""
    from ddlb_tpu.primitives.registry import load_impl_class

    cls = load_impl_class("tp_columnwise", "jax_spmd")
    defaults, allowed = cls.option_schema()
    assert defaults["transport"] == "ici"
    assert allowed["transport"] == ["ici", "dcn"]
    impl = cls(128, 32, 64, dtype="float32")
    assert impl.options["transport"] == "ici"
