"""Pallas kernel model + DDLB130-134 (ISSUE 13).

Fixture batteries proving each rule fires at the exact ``file:line``
(positive / negative / suppressed, the PR 9 acceptance pattern), VMEM
census hand-checks for the fused collective-matmul ring and flash
attention at canonical sweep shapes, exact DMA-semaphore protocol
counts for the ring kernels, the de-opaqued DDLB123 surface (pallas
members verify; unregistered/stale opacity is a finding), simulator
replay of a traced pallas ring landing on the chunk law, the parse
cache, and the ``--pallas-census`` CLI gate.
"""

from __future__ import annotations

import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

from ddlb_tpu.analysis import core  # noqa: E402
from ddlb_tpu.analysis.pallas import census as census_mod  # noqa: E402
from ddlb_tpu.analysis.pallas import rules_pallas  # noqa: E402
from ddlb_tpu.analysis.pallas.census import KernelSpec, run_census  # noqa: E402
from ddlb_tpu.analysis.spmd import families  # noqa: E402
from ddlb_tpu.analysis.spmd.rules_spmd import WireDriftRule  # noqa: E402
from ddlb_tpu.analysis.spmd.trace import Arr  # noqa: E402

DOC = '"""Fixture."""\n'

#: fixture preamble for kernel modules (5 lines, so line numbers below
#: are stable): the imports every pallas fixture needs
KPRELUDE = DOC + (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "from jax.experimental import pallas as pl\n"
    "from jax.experimental.pallas import tpu as pltpu\n"
)


def write_fixture(tmp_path, src, rel="ddlb_tpu/ops/fake_kernels.py"):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return path


def census_of(tmp_path, src, entry, args,
              rel="ddlb_tpu/ops/fake_kernels.py"):
    """Write a fixture kernel module and drive one entry point."""
    write_fixture(tmp_path, src, rel)
    dotted = rel[:-3].replace("/", ".") + "." + entry
    spec = KernelSpec(entry, dotted, lambda: (args, {}))
    return run_census(root=tmp_path, specs=[spec])


def by_path_line(findings):
    return [(f.path, f.line) for f in findings]


# ---------------------------------------------------------------------------
# fixture kernels
# ---------------------------------------------------------------------------

#: last block dim 136 > 128 and 136 % 128 != 0 — the DDLB131 positive;
#: shapes divide evenly so DDLB133 stays quiet, scratch is tiny so
#: DDLB130 stays quiet. pallas_call sits at line 10.
MISALIGNED = KPRELUDE + (
    "\n"
    "def _k(a_ref, o_ref):\n"                       # line 7
    "    o_ref[:] = a_ref[:]\n"                     # line 8
    "\n"
    "def misaligned(a):\n"                          # line 10
    "    m, n = a.shape\n"                          # line 11
    "    return pl.pallas_call(\n"                  # line 12
    "        _k,\n"
    "        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),\n"
    "        grid=(m // 96, n // 136),\n"
    "        in_specs=[pl.BlockSpec((96, 136), lambda i, j: (i, j))],\n"
    "        out_specs=pl.BlockSpec((96, 136), lambda i, j: (i, j)),\n"
    "    )(a)\n"
)

#: aligned blocks, huge f32 scratch (64 MiB > every TPU budget) — the
#: DDLB130 positive. pallas_call at line 12.
OVERBUDGET = KPRELUDE + (
    "\n"
    "def _k(a_ref, o_ref, acc):\n"
    "    o_ref[:] = a_ref[:]\n"
    "\n"
    "def overbudget(a):\n"
    "    m, n = a.shape\n"
    "    return pl.pallas_call(\n"                  # line 12
    "        _k,\n"
    "        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),\n"
    "        grid=(m // 128, n // 128),\n"
    "        in_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, j))],\n"
    "        out_specs=pl.BlockSpec((128, 128), lambda i, j: (i, j)),\n"
    "        scratch_shapes=[pltpu.VMEM((4096, 4096), jnp.float32)],\n"
    "    )(a)\n"
)

#: block 120 divides neither operand dim 900 — the DDLB133 positive
#: (f32 sublane 8 divides 120, so DDLB131 stays quiet).
INDIVISIBLE = KPRELUDE + (
    "\n"
    "def _k(a_ref, o_ref):\n"
    "    o_ref[:] = a_ref[:]\n"
    "\n"
    "def indivisible(a):\n"
    "    m, n = a.shape\n"
    "    return pl.pallas_call(\n"                  # line 12
    "        _k,\n"
    "        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),\n"
    "        grid=(m // 120, n // 128),\n"
    "        in_specs=[pl.BlockSpec((120, 128), lambda i, j: (i, j))],\n"
    "        out_specs=pl.BlockSpec((120, 128), lambda i, j: (i, j)),\n"
    "    )(a)\n"
)

#: a DMA started and never awaited (leaky) next to the balanced twin —
#: the DDLB132 positive/negative pair in one module.
LEAKY = KPRELUDE + (
    "\n"
    "def _leaky_k(a_ref, o_ref, sem):\n"
    "    pltpu.make_async_copy(a_ref, o_ref, sem).start()\n"
    "\n"
    "def _clean_k(a_ref, o_ref, sem):\n"
    "    cp = pltpu.make_async_copy(a_ref, o_ref, sem)\n"
    "    cp.start()\n"
    "    cp.wait()\n"
    "\n"
    "def leaky(a):\n"
    "    return pl.pallas_call(\n"                  # line 16
    "        _leaky_k,\n"
    "        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),\n"
    "        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],\n"
    "        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),\n"
    "        scratch_shapes=[pltpu.SemaphoreType.DMA],\n"
    "    )(a)\n"
    "\n"
    "def clean(a):\n"
    "    return pl.pallas_call(\n"                  # line 25
    "        _clean_k,\n"
    "        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),\n"
    "        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],\n"
    "        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),\n"
    "        scratch_shapes=[pltpu.SemaphoreType.DMA],\n"
    "    )(a)\n"
)


class TestRuleFixtures:
    def test_ddlb131_misaligned_block_fires_at_site(self, tmp_path):
        run = census_of(
            tmp_path, MISALIGNED, "misaligned",
            (Arr((960, 1360), "bfloat16"),),
        )
        findings = rules_pallas.TileAlignmentRule().findings_from(run)
        assert by_path_line(findings) == [
            ("ddlb_tpu/ops/fake_kernels.py", 12),
            ("ddlb_tpu/ops/fake_kernels.py", 12),
        ]  # the in block and the out block
        assert "136" in findings[0].message
        assert "not a multiple of 128" in findings[0].message
        # no cross-contamination: clean shapes elsewhere stay quiet
        assert rules_pallas.GridBlockRule().findings_from(run) == []
        assert rules_pallas.VmemBudgetRule().findings_from(run) == []

    def test_ddlb131_negative_aligned_blocks(self, tmp_path):
        src = MISALIGNED.replace("136", "128")
        run = census_of(
            tmp_path, src, "misaligned", (Arr((960, 1280), "bfloat16"),),
        )
        assert rules_pallas.TileAlignmentRule().findings_from(run) == []

    def test_ddlb131_under_granule_dims_pad_legally(self, tmp_path):
        # a [bq, 1] accumulator column (the flash m/l idiom) pads to a
        # lane, it is not misaligned
        src = MISALIGNED.replace("(96, 136)", "(96, 1)").replace(
            "n // 136", "n // 1"
        )
        run = census_of(
            tmp_path, src, "misaligned", (Arr((960, 4), "bfloat16"),),
        )
        assert rules_pallas.TileAlignmentRule().findings_from(run) == []

    def test_ddlb130_overbudget_scratch_fires_with_chips(self, tmp_path):
        run = census_of(
            tmp_path, OVERBUDGET, "overbudget",
            (Arr((1024, 1024), "bfloat16"),),
        )
        findings = rules_pallas.VmemBudgetRule().findings_from(run)
        (f,) = findings
        assert (f.path, f.line) == ("ddlb_tpu/ops/fake_kernels.py", 12)
        assert "exceeds" in f.message
        # 64 MiB scratch overruns every real TPU budget incl. Trillium
        for chip in ("v4", "v5e", "v5p", "v6e"):
            assert chip in f.message

    def test_ddlb130_uncovered_site_is_a_finding(self, tmp_path):
        path = write_fixture(tmp_path, OVERBUDGET)
        ctx = core.build_context(path, root=tmp_path)
        empty = census_mod.CensusRun()
        findings = rules_pallas.VmemBudgetRule().findings_from(
            empty, [ctx]
        )
        assert by_path_line(findings) == [
            ("ddlb_tpu/ops/fake_kernels.py", 12)
        ]
        assert "no kernel census" in findings[0].message

    def test_ddlb130_drive_error_is_a_finding(self, tmp_path):
        run = census_mod.CensusRun()
        run.errors.append(("broken", "NameError: nope"))
        (f,) = rules_pallas.VmemBudgetRule().findings_from(run)
        assert f.path == "ddlb_tpu/analysis/pallas/census.py"
        assert "broken" in f.message and "NameError" in f.message

    def test_ddlb130_incomplete_census_is_a_finding(self):
        # a body that did not interpret to completion may UNDERCOUNT
        # (missed run_scoped allocations, missed DMA events) — a green
        # gate over it would be a lie
        from ddlb_tpu.analysis.pallas.model import KernelCensus

        census = KernelCensus("_k", "ddlb_tpu/ops/fake.py", 7)
        census.incomplete = "interpretation budget exhausted"
        run = census_mod.CensusRun()
        run.censuses.append(census)
        (f,) = rules_pallas.VmemBudgetRule().findings_from(run)
        assert (f.path, f.line) == ("ddlb_tpu/ops/fake.py", 7)
        assert "did not interpret to completion" in f.message
        assert "budget exhausted" in f.message

    def test_ddlb132_leaky_dma_fires_and_clean_does_not(self, tmp_path):
        write_fixture(tmp_path, LEAKY)
        dotted = "ddlb_tpu.ops.fake_kernels."
        specs = [
            KernelSpec(
                "leaky", dotted + "leaky",
                lambda: ((Arr((256, 256), "bfloat16"),), {}),
            ),
            KernelSpec(
                "clean", dotted + "clean",
                lambda: ((Arr((256, 256), "bfloat16"),), {}),
            ),
        ]
        run = run_census(root=tmp_path, specs=specs)
        findings = rules_pallas.DmaSemaphoreRule().findings_from(run)
        assert by_path_line(findings) == [
            ("ddlb_tpu/ops/fake_kernels.py", 16)
        ]
        assert "sem" in findings[0].message
        assert "1 start(s) / 0 wait(s)" in findings[0].message

    def test_ddlb133_indivisible_block_fires_at_site(self, tmp_path):
        run = census_of(
            tmp_path, INDIVISIBLE, "indivisible",
            (Arr((900, 1280), "float32"),),
        )
        findings = rules_pallas.GridBlockRule().findings_from(run)
        assert findings
        assert {(f.path, f.line) for f in findings} == {
            ("ddlb_tpu/ops/fake_kernels.py", 12)
        }
        assert "900 % 120" in findings[0].message
        assert rules_pallas.TileAlignmentRule().findings_from(run) == []

    def test_ddlb133_negative_dividing_block(self, tmp_path):
        run = census_of(
            tmp_path, INDIVISIBLE, "indivisible",
            (Arr((960, 1280), "float32"),),
        )
        assert rules_pallas.GridBlockRule().findings_from(run) == []

    def test_census_findings_respect_inline_suppressions(self, tmp_path):
        # the engine applies ``# ddlb: ignore[...]`` on the finding's
        # line for project findings too — prove the pallas findings key
        # on the pallas_call line the comment can live on
        src = MISALIGNED.replace(
            "    return pl.pallas_call(\n",
            "    return pl.pallas_call(  # ddlb: ignore[DDLB131]\n",
        )
        run = census_of(
            tmp_path, src, "misaligned", (Arr((960, 1360), "bfloat16"),),
        )
        findings = rules_pallas.TileAlignmentRule().findings_from(run)
        assert findings
        ctx = core.build_context(
            tmp_path / "ddlb_tpu/ops/fake_kernels.py", root=tmp_path
        )
        core._apply_suppressions(ctx, findings)
        assert all(f.suppressed for f in findings)
        assert not any(f.counts for f in findings)


DDLB134_POSITIVE = DOC + (
    "from jax.experimental.pallas import tpu as pltpu\n"        # line 2
    "from jax.experimental.pallas.tpu import CompilerParams\n"  # line 3
    "\n"
    "\n"
    "def build():\n"                                            # line 6
    "    return pltpu.TPUCompilerParams(dimension_semantics=())\n"
)


class TestDirectCompilerParams:
    def test_ddlb134_fires_at_exact_sites(self, tmp_path):
        path = write_fixture(
            tmp_path, DDLB134_POSITIVE,
            rel="ddlb_tpu/ops/fake_params.py",
        )
        findings = [
            f
            for f in core.analyze([path], root=tmp_path,
                                  project_rules=False)
            if f.rule == "DDLB134" and f.counts
        ]
        assert [(f.line, f.col) for f in findings] == [(3, 1), (7, 12)]
        assert "pallas_compat" in findings[0].message

    def test_ddlb134_negative_through_the_bridge(self, tmp_path):
        src = DOC + (
            "from ddlb_tpu.ops.pallas_compat import CompilerParams\n"
            "\n"
            "\n"
            "def build():\n"
            "    return CompilerParams(dimension_semantics=())\n"
        )
        path = write_fixture(
            tmp_path, src, rel="ddlb_tpu/ops/fake_params.py"
        )
        findings = core.analyze([path], root=tmp_path,
                                project_rules=False)
        assert [f for f in findings if f.rule == "DDLB134"] == []

    def test_ddlb134_exempts_the_bridge_itself(self):
        ctx = core.build_context(
            REPO / "ddlb_tpu/ops/pallas_compat.py", root=REPO
        )
        rule = rules_pallas.DirectCompilerParamsRule()
        assert not rule.scope(ctx)

    def test_ddlb134_suppression_masks(self, tmp_path):
        src = DDLB134_POSITIVE.replace(
            "from jax.experimental.pallas.tpu import CompilerParams\n",
            "from jax.experimental.pallas.tpu import CompilerParams"
            "  # ddlb: ignore[DDLB134]\n",
        )
        path = write_fixture(
            tmp_path, src, rel="ddlb_tpu/ops/fake_params.py"
        )
        findings = [
            f
            for f in core.analyze([path], root=tmp_path,
                                  project_rules=False)
            if f.rule == "DDLB134"
        ]
        assert len(findings) == 2
        assert any(f.suppressed for f in findings)
        assert sum(1 for f in findings if f.counts) == 1

    def test_repo_has_no_direct_references(self):
        # the satellite fix: alltoall_matmul.py routed through the
        # bridge; nothing else regressed
        rule = rules_pallas.DirectCompilerParamsRule()
        paths = core.expand_targets([str(REPO / "ddlb_tpu")])
        hits = []
        for p in paths:
            ctx = core.build_context(p, root=REPO)
            if ctx.tree is not None and rule.scope(ctx):
                hits.extend(rule.check(ctx))
        assert hits == []


# ---------------------------------------------------------------------------
# real-kernel censuses: hand-checked working sets + protocol counts
# ---------------------------------------------------------------------------


def _spec(label):
    (spec,) = [s for s in census_mod.KERNEL_SPECS if s.label == label]
    return spec


class TestRepoCensus:
    def test_ring_ag_matmul_vmem_hand_check(self):
        run = run_census(specs=[_spec("ring_ag_matmul")])
        (census,) = [
            c for c in run.censuses if c.name == "_ag_matmul_kernel"
        ]
        m, k, n, d = 8192, 8192, 8192, 4
        m_loc, bn, bk = m // d, 512, 512
        acc = m_loc * bn * 4                     # f32 accumulator
        pipeline = 2 * (
            m_loc * bk * 2 + bk * bn * 2 + m_loc * bn * 2
        )                                        # a/b/out tiles, x2 each
        assert census.vmem_bytes() == pytest.approx(acc + pipeline)
        # the ring moves d-1 hops of the full [m/d, k] bf16 shard
        assert census.remote_hops == d - 1
        assert census.remote_bytes == pytest.approx(
            (d - 1) * m_loc * k * 2
        )

    def test_ring_protocol_semaphores_balance_exactly(self):
        run = run_census(specs=[_spec("ring_ag_matmul")])
        (census,) = [
            c for c in run.censuses if c.name == "_ag_matmul_kernel"
        ]
        d = 4
        counts = {
            name: (rec["starts"], rec["waits"])
            for name, rec in census.sems.items()
        }
        # d-1 RDMA sends; the credit protocol produces and drains d-1
        # credits (d-2 in-loop gates + the final drain) — the comments
        # in ops/collective_matmul.py, now machine-checked
        assert counts["send_sem"] == (d - 1, d - 1)
        assert counts["recv_sem"] == (d - 1, d - 1)
        assert counts["credit_sem"] == (d - 1, d - 1)
        assert counts["<barrier>"] == (2, 2)
        assert census.unbalanced_sems() == []

    def test_flash_forward_vmem_hand_check(self):
        run = run_census(specs=[_spec("flash_attention[tri]")])
        tri = [
            c for c in run.censuses if c.name == "_flash_kernel_tri"
        ][0]
        bq, dh = 1024, 128
        blocks = 4 * (2 * bq * dh * 2)       # q/k/v/out bf16 blocks x2
        lse = 2 * (bq * 1 * 4)               # lse f32 block x2
        scratch = bq * dh * 4 + 2 * (bq * 1 * 4)  # acc + m + l
        assert tri.vmem_bytes() == pytest.approx(blocks + lse + scratch)

    def test_census_covers_every_repo_site(self):
        run = census_mod.shared_run()
        paths = core.expand_targets([str(REPO / "ddlb_tpu")])
        ctxs = [core.build_context(p, root=REPO) for p in paths]
        sites = set(census_mod.pallas_call_sites(ctxs))
        covered = {(c.rel, c.line) for c in run.censuses}
        assert sites, "site enumeration found nothing"
        assert sites <= covered
        # and the rules stay clean on the repo itself
        for rule in rules_pallas.RULES:
            if hasattr(rule, "findings_from"):
                assert rule.findings_from(run, ctxs) == [], rule.id


# ---------------------------------------------------------------------------
# DDLB123: de-opaqued members + the registered-opacity discipline
# ---------------------------------------------------------------------------


def _fake_report(status, family="fakefam", member="fakemem"):
    r = families.MemberReport(family, member, {})
    r.status = status
    r.rel = "ddlb_tpu/primitives/fakefam/fakemem.py"
    return r


class TestOpaqueDiscipline:
    def test_collectives_pallas_members_now_verify(self):
        reports = families.verify_families(families=["collectives"])
        pallas = [r for r in reports if r.member == "pallas"]
        assert pallas, "collectives/pallas configs missing"
        assert {r.status for r in pallas} == {"verified"}
        # the remaining opacity in this family is the compiler class
        opaque = [r for r in reports if r.status == "opaque"]
        assert {r.member for r in opaque} == {"xla_gspmd"}

    def test_unregistered_opaque_member_is_a_finding(self):
        findings = WireDriftRule().findings_from(
            [_fake_report("opaque")], justified={}
        )
        (f,) = findings
        assert f.rule == "DDLB123"
        assert "no registered justification" in f.message
        assert "OPAQUE_JUSTIFIED" in f.message

    def test_registered_opaque_member_passes(self):
        findings = WireDriftRule().findings_from(
            [_fake_report("opaque")],
            justified={("fakefam", "fakemem"): "compiler-scheduled"},
        )
        assert findings == []

    def test_stale_justification_is_a_finding(self):
        findings = WireDriftRule().findings_from(
            [_fake_report("verified")],
            justified={("fakefam", "fakemem"): "no longer true"},
        )
        (f,) = findings
        assert "stale OPAQUE_JUSTIFIED" in f.message
        assert "now traces" in f.message
        assert f.path == "ddlb_tpu/analysis/spmd/families.py"
        # anchored at the registry definition line
        assert f.snippet.startswith("OPAQUE_JUSTIFIED")

    def test_justification_for_deleted_member_is_stale(self):
        # the family is still swept but the member is gone: the dead
        # entry must not persist silently
        findings = WireDriftRule().findings_from(
            [_fake_report("verified", member="other")],
            justified={("fakefam", "deleted"): "was opaque once"},
        )
        (f,) = findings
        assert "stale OPAQUE_JUSTIFIED" in f.message
        assert "no longer registered" in f.message

    def test_justification_outside_the_sweep_is_not_judged(self):
        # a fixture/subset sweep covering other families must not
        # declare the real registry's entries stale
        findings = WireDriftRule().findings_from(
            [_fake_report("verified")],
            justified={("real_family", "xla_gspmd"): "compiler"},
        )
        assert findings == []

    def test_real_registry_covers_exactly_the_xla_gspmd_class(self):
        assert set(families.OPAQUE_JUSTIFIED) == {
            (fam, "xla_gspmd")
            for fam in (
                "tp_columnwise", "tp_rowwise", "dp_allreduce",
                "ep_alltoall", "pp_pipeline", "collectives",
            )
        }


# ---------------------------------------------------------------------------
# simulator: the traced pallas ring replays onto the chunk law
# ---------------------------------------------------------------------------


class TestPallasReplay:
    @pytest.mark.parametrize("family", ["tp_columnwise", "tp_rowwise"])
    def test_ring_rdma_chunk_law_emerges(self, family):
        """The fused kernel's d-1 traced RDMA hops, replayed one stage
        per hop, must land on ``max(C, W) + min(C, W)/c`` with nothing
        about the law coded into the frontend — the pallas twin of the
        shard_map chunked-engine test."""
        from ddlb_tpu.analysis.spmd.families import member_schedule
        from ddlb_tpu.perfmodel.topology import flat_topology
        from ddlb_tpu.simulator.engine import replay
        from ddlb_tpu.simulator.frontends import program_from_schedule

        export = member_schedule(
            family, "pallas", {"algorithm": "ring_rdma"}
        )
        assert export["status"] == "verified", export["reason"]
        d = export["partitions"]
        assert export["chunks"] == d - 1
        assert len(export["entries"]) == d - 1
        assert all(
            e["op"] == "remote_copy" for e in export["entries"]
        )
        topo = flat_topology(d, "v5e")
        result = replay(program_from_schedule(export, topo), topo)
        compute, wire = result.compute_busy_s, result.comm_busy_s
        law = max(compute, wire) + min(compute, wire) / (d - 1)
        assert result.makespan_s == pytest.approx(law, rel=1e-12)
        # the traced wire survives the lowering intact
        assert sum(
            v for r, v in result.payload.items() if r.startswith("ici")
        ) == pytest.approx(export["wire_traced"])


# ---------------------------------------------------------------------------
# the parse cache + the CLI gate
# ---------------------------------------------------------------------------


class TestParseCache:
    def test_same_mtime_reuses_the_ast(self, tmp_path):
        path = write_fixture(tmp_path, DOC + "X = 1\n",
                             rel="ddlb_tpu/mod.py")
        a = core.build_context(path, root=tmp_path)
        b = core.build_context(path, root=tmp_path)
        assert a.tree is b.tree  # the expensive parse happened once

    def test_modified_file_reparses(self, tmp_path):
        import os

        path = write_fixture(tmp_path, DOC + "X = 1\n",
                             rel="ddlb_tpu/mod.py")
        a = core.build_context(path, root=tmp_path)
        path.write_text(DOC + "X = 2\n")
        os.utime(path, ns=(1, 1))  # force a distinct mtime either way
        b = core.build_context(path, root=tmp_path)
        assert a.tree is not b.tree
        assert b.source.endswith("X = 2\n")

    def test_mutable_state_is_fresh_per_context(self, tmp_path):
        src = DOC + "X = 1  # ddlb: ignore[DDLB999]\n"
        path = write_fixture(tmp_path, src, rel="ddlb_tpu/mod.py")
        a = core.build_context(path, root=tmp_path)
        a.used_suppressions.add((2, "DDLB999"))
        a.suppressions[2].add("DDLB000")
        b = core.build_context(path, root=tmp_path)
        assert b.used_suppressions == set()
        assert b.suppressions == {2: {"DDLB999"}}


class TestCensusCli:
    def test_pallas_census_gate_runs_clean(self):
        proc = subprocess.run(
            [sys.executable, "scripts/analyze.py", "--pallas-census"],
            cwd=REPO, capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        m = re.search(
            r"pallas-census: (\d+) distinct pallas_call site\(s\) "
            r"censused of (\d+)",
            proc.stdout,
        )
        assert m is not None, proc.stdout[-500:]
        assert m.group(1) == m.group(2)  # coverage is closed
        assert "0 finding(s)" in proc.stdout
        assert "VMEM budget table" in proc.stdout
