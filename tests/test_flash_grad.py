"""Flash attention training path: custom_vjp backward kernels vs autodiff
of the einsum reference formulation (interpret mode on the CPU mesh).

VERDICT r1 item #3: the flash kernel must have a backward (dQ/dK/dV
Pallas kernels wired through ``jax.custom_vjp``) and the flagship model
must train through it. These tests pin the op-level gradients, the
offset/ring variants, and the kernels' composition into the model.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_bwd,
    ring_flash_attention,
)


def _reference(q, k, v, scale, row_offset=0):
    """Einsum causal attention: q rows are global ``row_offset + i``."""
    sq, skv = q.shape[0], k.shape[0]
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 0) + row_offset
    cols = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 1)
    s = jnp.where((rows >= cols)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32)).astype(q.dtype)


def _rand(shape, seed, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(0, 1, shape), dtype)


@pytest.mark.parametrize("sq,skv,row_offset", [(32, 32, 0), (16, 64, 48)])
def test_flash_grads_match_autodiff(sq, skv, row_offset):
    """Full and offset-shard cases: dq/dk/dv vs autodiff of the einsum
    reference at f32/1e-5."""
    h, dh = 2, 8
    q, k, v = _rand((sq, h, dh), 0), _rand((skv, h, dh), 1), _rand((skv, h, dh), 2)
    w = _rand((sq, h, dh), 3)
    scale = 1.0 / np.sqrt(dh)

    def loss_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, scale, row_offset) * w)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, scale=scale, row_offset=row_offset,
            block_q=16, block_kv=16, interpret=True,
        )
        return jnp.sum(o * w)

    assert np.allclose(loss_ref(q, k, v), loss_flash(q, k, v), atol=1e-4)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_fl):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=1e-5,
            err_msg=f"d{name} mismatch",
        )


def test_flash_bwd_chunks_compose():
    """Per-chunk backward calls with the GLOBAL lse sum to the full
    backward — the property the ring backward relies on."""
    sq, h, dh, d = 32, 2, 8, 4
    skv = sq
    q, k, v = _rand((sq, h, dh), 0), _rand((skv, h, dh), 1), _rand((skv, h, dh), 2)
    do = _rand((sq, h, dh), 3)
    scale = 1.0 / np.sqrt(dh)
    from ddlb_tpu.ops.flash_attention import _flash_forward

    o, lse = _flash_forward(q, k, v, 0, scale, 8, 8, True)
    dq_full, dk_full, dv_full = flash_attention_bwd(
        q, k, v, o, lse, do, scale=scale, row_offset=0, col_offset=0,
        block_q=8, block_kv=8, interpret=True,
    )
    s_c = skv // d
    dq_sum = jnp.zeros_like(dq_full)
    dks, dvs = [], []
    for c in range(d):
        sl = slice(c * s_c, (c + 1) * s_c)
        dq_c, dk_c, dv_c = flash_attention_bwd(
            q, k[sl], v[sl], o, lse, do,
            scale=scale, row_offset=0, col_offset=c * s_c,
            block_q=8, block_kv=8, interpret=True,
        )
        dq_sum = dq_sum + dq_c
        dks.append(dk_c)
        dvs.append(dv_c)
    np.testing.assert_allclose(np.asarray(dq_sum), np.asarray(dq_full),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(jnp.concatenate(dks)),
                               np.asarray(dk_full), rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(jnp.concatenate(dvs)),
                               np.asarray(dv_full), rtol=0, atol=1e-5)


@pytest.mark.parametrize("d", [2, 4])
def test_ring_flash_grads_match_reference(d):
    """shard_map ring: forward and all three gradients vs the one-device
    reference; dK/dV accumulators travel the ring home."""
    S, h, dh = 16 * d, 2, 8
    q, k, v = _rand((S, h, dh), 0), _rand((S, h, dh), 1), _rand((S, h, dh), 2)
    w = _rand((S, h, dh), 3)
    scale = 1.0 / np.sqrt(dh)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))

    def ring(q, k, v):
        body = lambda q, k, v: ring_flash_attention(
            q, k, v, axis_name="tp", axis_size=d, scale=scale,
            block_q=8, block_kv=8, interpret=True,
        )
        return jax.shard_map(
            body, mesh=mesh, in_specs=(P("tp"),) * 3, out_specs=P("tp"),
            check_vma=False,
        )(q, k, v)

    o_ref = _reference(q, k, v, scale)
    o_ring = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(o_ref), np.asarray(o_ring),
                               rtol=0, atol=1e-5)
    loss_ref = lambda q, k, v: jnp.sum(_reference(q, k, v, scale) * w)
    loss_ring = lambda q, k, v: jnp.sum(ring(q, k, v) * w)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_ring):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=1e-5,
            err_msg=f"d{name} mismatch",
        )


def test_flash_bf16_forward_close():
    """bf16 operands stay within the primitive-contract tolerance."""
    sq, h, dh = 64, 2, 16
    q = _rand((sq, h, dh), 0, jnp.bfloat16)
    k = _rand((sq, h, dh), 1, jnp.bfloat16)
    v = _rand((sq, h, dh), 2, jnp.bfloat16)
    scale = 1.0 / np.sqrt(dh)
    o = flash_attention(q, k, v, scale=scale, block_q=16, block_kv=16,
                        interpret=True)
    o_ref = _reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        scale,
    )
    assert float(jnp.max(jnp.abs(o.astype(jnp.float32) - o_ref))) < 2e-2


@pytest.mark.slow  # full flagship forward under BOTH attention kernels
# (the flash one INTERPRETED on the CPU sim): ~60 s of compile for one
# equivalence check — unlocked by the transformer shard_map_compat
# migration but outside the tier-1 870 s budget
def test_model_flash_vs_einsum_losses_match():
    """The flagship model computes the same loss (and the same gradient
    step) with flash kernels as with the einsum formulation — both
    attention modes."""
    from ddlb_tpu.models.transformer import (
        TransformerConfig,
        example_tokens,
        init_params,
        make_train_step,
    )

    devices = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = jax.sharding.Mesh(devices, ("dp", "tp", "pp"))
    for attention in ("gathered", "ring"):
        losses = {}
        for kernel in ("flash", "einsum"):
            cfg = TransformerConfig(
                vocab=32, d_model=16, n_heads=4, d_ff=32,
                layers_per_stage=1, microbatches=2,
                attention=attention, attn_kernel=kernel,
            )
            train_step, init_opt, shardings = make_train_step(mesh, cfg)
            params = init_params(cfg, pp=2, n_experts=2)
            params = {
                k: jax.device_put(v, shardings[k]) for k, v in params.items()
            }
            opt_state = init_opt(params)
            tokens, targets = example_tokens(2 * cfg.microbatches, 16, cfg.vocab)
            tokens = jax.device_put(tokens, shardings["data"])
            targets = jax.device_put(targets, shardings["data"])
            step_losses = []
            for _ in range(2):
                params, opt_state, loss = train_step(
                    params, opt_state, tokens, targets
                )
                step_losses.append(float(loss))
            losses[kernel] = step_losses
        np.testing.assert_allclose(
            losses["flash"], losses["einsum"], rtol=0, atol=1e-4,
            err_msg=f"attention={attention}",
        )


def test_triangular_grid_matches_rectangular():
    """A literal row_offset=0 square call dispatches to the triangular
    grid (only live causal tiles visited); it must be BIT-exact against
    the rectangular masked grid a traced offset selects, in forward and
    in all three gradients."""
    from ddlb_tpu.ops.flash_attention import _flash_dyn_jit

    S, h, dh = 128, 2, 16
    q, k, v = _rand((S, h, dh), 0), _rand((S, h, dh), 1), _rand((S, h, dh), 2)
    scale = 1.0 / np.sqrt(dh)

    def tri(q, k, v):
        return flash_attention(
            q, k, v, scale=scale, block_q=32, block_kv=32, interpret=True
        )

    def rect(q, k, v):
        return _flash_dyn_jit(
            q, k, v, jnp.asarray(0, jnp.int32), scale, 32, 32, True, True, 0
        )

    np.testing.assert_array_equal(tri(q, k, v), rect(q, k, v))
    g_tri = jax.grad(lambda *a: jnp.sum(tri(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_rect = jax.grad(lambda *a: jnp.sum(rect(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_tri, g_rect):
        np.testing.assert_array_equal(a, b)


def test_static_zero_offset_nonsquare_falls_back():
    """Static offset 0 with skv != sq (or bq != bkv) cannot use the
    triangle; the dispatch must fall back to the rectangular grid and
    still match the reference."""
    sq, skv, h, dh = 32, 64, 2, 8
    q, k, v = _rand((sq, h, dh), 0), _rand((skv, h, dh), 1), _rand((skv, h, dh), 2)
    scale = 1.0 / np.sqrt(dh)
    o = flash_attention(
        q, k, v, scale=scale, block_q=16, block_kv=16, interpret=True
    )
    assert np.allclose(o, _reference(q, k, v, scale), atol=1e-5)
    # mixed blocks on a square shape: also rectangular, also exact
    o2 = flash_attention(
        q, k[:sq], v[:sq], scale=scale, block_q=16, block_kv=32, interpret=True
    )
    assert np.allclose(o2, _reference(q, k[:sq], v[:sq], scale), atol=1e-5)


def test_staircase_asymmetric_blocks_match_reference():
    """bq != bkv takes the generalized staircase live-tile grid (wider kv
    tiles halve the online-softmax rescale chain); forward and all three
    gradients must match autodiff of the einsum reference."""
    S, h, dh = 128, 2, 16
    q, k, v = _rand((S, h, dh), 0), _rand((S, h, dh), 1), _rand((S, h, dh), 2)
    scale = 1.0 / np.sqrt(dh)

    for bq, bkv in ((16, 32), (32, 16), (16, 64)):
        def flash(q, k, v, bq=bq, bkv=bkv):
            return flash_attention(
                q, k, v, scale=scale, block_q=bq, block_kv=bkv,
                interpret=True,
            )

        assert np.allclose(
            flash(q, k, v), _reference(q, k, v, scale), atol=1e-5
        ), (bq, bkv)
        g = jax.grad(lambda *a: jnp.sum(flash(*a) ** 2), argnums=(0, 1, 2))(
            q, k, v
        )
        g_ref = jax.grad(
            lambda *a: jnp.sum(_reference(*a, scale) ** 2), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(g, g_ref):
            assert np.allclose(a, b, atol=1e-4), (bq, bkv)
