"""Multi-process launcher: rank env plumbing, exit codes, supervision.

Fast by design: the children are tiny ``python -c`` scripts (no JAX, no
devices), so the launcher's own contracts — env fan-out, rank-0-last
output ordering, first-nonzero exit propagation, signal-death mapping,
the cross-rank watchdog's abort/classify/relaunch loop, and file-beat
liveness — are tier-1-testable without paying a distributed JAX world
(the real-world battery is ``scripts/chaos_launch.py``).
"""

import json
import os
import signal
import sys
import textwrap
import time

import pytest

from ddlb_tpu.cli.launch import (
    _rc_info,
    launch,
    launch_supervised,
)


def _lines(capsys):
    return capsys.readouterr().out.splitlines()


# ---------------------------------------------------------------------------
# Plain mode
# ---------------------------------------------------------------------------


def test_rank_env_plumbing(capsys):
    """Every child sees its rank identity (DDLB_TPU_NUM_PROCESSES /
    PROCESS_ID / COORD_ADDR) and, in CPU-sim mode, the forced cpu
    platform with the requested virtual device count."""
    code = (
        "import os; e = os.environ; "
        "print('ENV', e['DDLB_TPU_PROCESS_ID'], e['DDLB_TPU_NUM_PROCESSES'],"
        " e['DDLB_TPU_COORD_ADDR'], e['JAX_PLATFORMS'],"
        " 'host_platform_device_count=4' in e['XLA_FLAGS'].replace('--xla_force_',''))"
    )
    rc = launch(
        [sys.executable, "-c", code], processes=2, devices_per_process=4
    )
    assert rc == 0
    out = _lines(capsys)
    env_lines = sorted(line for line in out if "ENV" in line)
    assert len(env_lines) == 2
    coord0 = env_lines[0].split()[4]
    assert env_lines[0].startswith("[p0] ENV 0 2")
    assert env_lines[1].startswith("[p1] ENV 1 2")
    # one shared coordinator endpoint, cpu platform, 4 sim devices
    assert env_lines[1].split()[4] == coord0
    assert coord0.startswith("127.0.0.1:")
    assert all(line.split()[5] == "cpu" for line in env_lines)
    assert all(line.split()[6] == "True" for line in env_lines)


def test_rank0_output_printed_last(capsys):
    """Rank 0 owns the result table, so its buffered output must end
    the launch output regardless of completion order."""
    code = "import os; print('MARK', os.environ['DDLB_TPU_PROCESS_ID'])"
    assert launch([sys.executable, "-c", code], processes=3) == 0
    marks = [line for line in _lines(capsys) if "MARK" in line]
    assert marks == ["[p1] MARK 1", "[p2] MARK 2", "[p0] MARK 0"]


def test_first_nonzero_exit_code_propagated(capsys):
    code = (
        "import os, sys; "
        "sys.exit({'0': 0, '1': 3, '2': 5}[os.environ['DDLB_TPU_PROCESS_ID']])"
    )
    assert launch([sys.executable, "-c", code], processes=3) == 3
    assert "[p1] exit code 3" in _lines(capsys)


def test_signal_death_mapped_and_named(capsys):
    """A signal-killed child has a NEGATIVE returncode; the summary must
    name the signal and the launcher exit must be 128+signum, never the
    raw negative number."""
    code = (
        "import os, signal; "
        "os.environ['DDLB_TPU_PROCESS_ID'] == '1' and "
        "os.kill(os.getpid(), signal.SIGKILL)"
    )
    rc = launch([sys.executable, "-c", code], processes=2)
    assert rc == 128 + signal.SIGKILL
    out = _lines(capsys)
    assert "[p1] terminated by SIGKILL (exit code 137)" in out


def test_rc_info_mapping():
    assert _rc_info(0) == (0, "exit code 0")
    assert _rc_info(7)[0] == 7
    mapped, text = _rc_info(-signal.SIGTERM)
    assert mapped == 128 + signal.SIGTERM
    assert "SIGTERM" in text and "-15" not in text


# ---------------------------------------------------------------------------
# Supervised mode (scripted children, no JAX)
# ---------------------------------------------------------------------------


def _attempts(run_dir):
    with open(os.path.join(run_dir, "attempts.json")) as f:
        return json.load(f)


def test_supervised_relaunches_transient_world_failure(tmp_path, capsys):
    """Attempt 0: rank 1 dies with a coordinator-flap signature while
    rank 0 keeps running -> asymmetric death, classified transient, the
    WHOLE world relaunches (DDLB_TPU_WORLD_ATTEMPT=1 exported) and
    completes; attempts.json records both attempts."""
    code = textwrap.dedent(
        """
        import os, sys, time
        attempt = int(os.environ["DDLB_TPU_WORLD_ATTEMPT"])
        rank = os.environ["DDLB_TPU_PROCESS_ID"]
        if attempt == 0 and rank == "1":
            print("ConnectionError: coordinator unreachable")
            sys.exit(7)
        time.sleep(1.0)  # peers in flight when rank 1 dies
        print("WORK", rank, attempt)
        """
    )
    rc = launch_supervised(
        [sys.executable, "-c", code],
        processes=2,
        silence_timeout=30.0,
        world_retries=2,
        relaunch_backoff_s=0.05,
        run_dir=str(tmp_path),
    )
    assert rc == 0
    records = _attempts(str(tmp_path))
    assert [r["outcome"] for r in records] == ["failed", "ok"]
    assert records[0]["error_class"] == "transient"
    assert "rank 1" in records[0]["error"]
    assert records[0]["culprit_rank"] == 1
    out = "\n".join(_lines(capsys))
    # the relaunched world saw the incremented attempt counter
    assert "WORK 0 1" in out and "WORK 1 1" in out
    # live streaming, not after-exit buffering: child lines carry the
    # rank prefix as they arrive
    assert "[p1] ConnectionError: coordinator unreachable" in out


def test_supervised_aborts_silent_world_within_deadline(tmp_path):
    """A rank that produces no beat and no output is detected at the
    silence deadline and the whole world is torn down together."""
    code = (
        "import os, time; "
        "time.sleep(60 if os.environ['DDLB_TPU_PROCESS_ID'] == '1' else 0.2)"
    )
    t0 = time.monotonic()
    rc = launch_supervised(
        [sys.executable, "-c", code],
        processes=2,
        silence_timeout=2.0,
        world_retries=0,
        relaunch_backoff_s=0.05,
        run_dir=str(tmp_path),
    )
    elapsed = time.monotonic() - t0
    assert rc != 0
    assert elapsed < 30.0  # detection at ~2s + grace, never 60s
    (record,) = _attempts(str(tmp_path))
    assert record["outcome"] == "failed"
    assert "TimeoutError" in record["error"]
    assert record["error_class"] == "transient"
    assert record["silence_age_s"] >= 2.0


def test_supervised_deterministic_failure_not_relaunched(tmp_path):
    """A symmetric failure whose output tail classifies deterministic
    (a bad config, not a flaky environment) must not burn relaunches."""
    code = (
        "import sys; print('ValueError: bad sweep option'); sys.exit(2)"
    )
    rc = launch_supervised(
        [sys.executable, "-c", code],
        processes=2,
        silence_timeout=10.0,
        world_retries=3,
        relaunch_backoff_s=0.05,
        run_dir=str(tmp_path),
    )
    assert rc == 2
    records = _attempts(str(tmp_path))
    assert len(records) == 1  # no relaunch
    assert records[0]["error_class"] == "deterministic"


def test_supervised_classifies_final_error_not_incidental_tail(tmp_path):
    """Transient patterns are matched against the failing ranks' FINAL
    exception lines only: a benign mid-output mention of 'coordinator'
    (a recovered warning, an echoed address) must not turn a
    deterministic failure into a world relaunch."""
    code = textwrap.dedent(
        """
        import os, sys
        for _ in range(10):
            print("INFO: connected to coordinator at 127.0.0.1")
        print("ValueError: bad sweep option")
        sys.exit(2)
        """
    )
    rc = launch_supervised(
        [sys.executable, "-c", code],
        processes=2,
        silence_timeout=10.0,
        world_retries=3,
        relaunch_backoff_s=0.05,
        run_dir=str(tmp_path),
    )
    assert rc == 2
    records = _attempts(str(tmp_path))
    assert len(records) == 1  # no relaunch burned
    assert records[0]["error_class"] == "deterministic"


def test_supervised_file_beats_extend_silence_deadline(tmp_path):
    """A child that prints NOTHING but beats through its
    DDLB_TPU_BEAT_FILE outlives a silence deadline shorter than its
    runtime — the file-beat channel is what the watchdog reads."""
    code = textwrap.dedent(
        """
        import time
        from ddlb_tpu.faults import heartbeat
        for _ in range(16):  # ~3.2s of silent-but-beating work
            heartbeat.beat()
            time.sleep(0.2)
        """
    )
    rc = launch_supervised(
        [sys.executable, "-c", code],
        processes=2,
        silence_timeout=1.5,
        world_retries=0,
        relaunch_backoff_s=0.05,
        run_dir=str(tmp_path),
    )
    assert rc == 0
    (record,) = _attempts(str(tmp_path))
    assert record["outcome"] == "ok"
    # the beat files were actually written under the attempt dir
    attempt_dir = os.path.join(str(tmp_path), "attempt-0")
    assert os.path.exists(os.path.join(attempt_dir, "beat-p0"))
    assert os.path.exists(os.path.join(attempt_dir, "beat-p1"))


def test_supervised_world_retries_exhaust(tmp_path):
    """A world that keeps dying transiently stops at world_retries and
    reports the mapped exit code of the last attempt."""
    code = (
        "import os, sys, time\n"
        "if os.environ['DDLB_TPU_PROCESS_ID'] == '1':\n"
        "    print('RESOURCE_EXHAUSTED: flaky allocator'); sys.exit(9)\n"
        "time.sleep(0.5)\n"
    )
    rc = launch_supervised(
        [sys.executable, "-c", code],
        processes=2,
        silence_timeout=30.0,
        world_retries=1,
        relaunch_backoff_s=0.05,
        run_dir=str(tmp_path),
    )
    assert rc == 9
    records = _attempts(str(tmp_path))
    assert len(records) == 2
    assert all(r["outcome"] == "failed" for r in records)


def test_supervised_requires_at_least_one_process():
    with pytest.raises(ValueError, match="processes"):
        launch_supervised([sys.executable, "-c", "pass"], processes=0)
