"""Pallas kernel correctness: tiled GEMM and the RDMA ring collective
matmuls (interpret mode on the CPU mesh; the ring kernels run under the
distributed TPU interpreter, which emulates remote DMA and semaphores)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.ops.collective_matmul import ring_ag_matmul, ring_matmul_rs
from ddlb_tpu.ops.matmul import matmul
from ddlb_tpu.primitives.registry import load_impl_class


def test_pallas_matmul_interpret():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(0, 1, (256, 128)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 1, (128, 256)), jnp.float32)
    out = matmul(a, b, block_m=128, block_n=128, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=0, atol=1e-4
    )


def test_pallas_matmul_shape_errors():
    a = jnp.zeros((100, 64), jnp.float32)
    b = jnp.zeros((64, 64), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        matmul(a, b, block_m=64, interpret=True)
    with pytest.raises(ValueError, match="contraction mismatch"):
        matmul(jnp.zeros((64, 32)), jnp.zeros((64, 64)), interpret=True)


@pytest.mark.parametrize("d", [2, 4, 8])
def test_ring_ag_matmul(d):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    m, n, k = 16 * d, 32, 32
    rng = np.random.default_rng(1)
    a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
    b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
    f = jax.jit(
        jax.shard_map(
            lambda a_s, b_r: ring_ag_matmul(
                a_s, b_r, axis_size=d, block_n=32, block_k=32,
                interpret=pltpu.InterpretParams(),
            ),
            mesh=mesh,
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None),
            check_vma=False,
        )
    )
    out = np.asarray(
        f(
            jax.device_put(a, NamedSharding(mesh, P("tp", None))),
            jax.device_put(b, NamedSharding(mesh, P(None, None))),
        )
    )
    np.testing.assert_allclose(out, a @ b, rtol=0, atol=1e-4)


@pytest.mark.parametrize("d", [2, 4, 8])
def test_ring_matmul_rs(d):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    m, n, k = 16 * d, 32, 16 * d
    rng = np.random.default_rng(2)
    a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
    b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
    f = jax.jit(
        jax.shard_map(
            lambda a_s, b_s: ring_matmul_rs(
                a_s, b_s, axis_size=d, block_n=32, block_k=16,
                interpret=pltpu.InterpretParams(),
            ),
            mesh=mesh,
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
            check_vma=False,
        )
    )
    out = np.asarray(
        f(
            jax.device_put(a, NamedSharding(mesh, P(None, "tp"))),
            jax.device_put(b, NamedSharding(mesh, P("tp", None))),
        )
    )
    np.testing.assert_allclose(out, a @ b, rtol=0, atol=1e-4)


@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_pallas_impl_xla_collective(primitive):
    cls = load_impl_class(primitive, "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="xla_collective", block_m=128, block_n=128, block_k=128,
    )
    result = impl.run()
    assert result.shape == (128, 128)
    assert impl.validate(result)


@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_pallas_impl_ring_rdma(primitive):
    cls = load_impl_class(primitive, "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="ring_rdma", block_n=128, block_k=128,
    )
    result = impl.run()
    assert result.shape == (128, 128)
    assert impl.validate(result)


def test_pallas_impl_ring_rdma_race_detector():
    """The distributed interpreter's race detector runs clean on the ring
    kernel (the credit-semaphore protocol is what makes this pass)."""
    cls = load_impl_class("tp_columnwise", "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="ring_rdma", block_n=128, block_k=128, detect_races=True,
    )
    assert impl.validate(impl.run())


# ---------------------------------------------------------------------------
# Hardened RDMA-ring matrix (VERDICT r1 item #8): bf16 + f32, non-square
# shapes (both aspect ratios), d in {2, 4, 8}, race detection on both
# kernels, and a bit-level pin of the rs kernel's wire-dtype accumulation.
# ---------------------------------------------------------------------------

from ddlb_tpu.primitives.base import validation_atol  # noqa: E402


def _ring_ag_case(d, dtype, m, n, k, bn, bk, interpret):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    rng = np.random.default_rng(d * 7 + k)
    a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
    b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
    jdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    f = jax.jit(
        jax.shard_map(
            lambda a_s, b_r: ring_ag_matmul(
                a_s, b_r, axis_size=d, block_n=bn, block_k=bk,
                interpret=interpret,
            ),
            mesh=mesh,
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None),
            check_vma=False,
        )
    )
    out = f(
        jax.device_put(jnp.asarray(a, jdt), NamedSharding(mesh, P("tp", None))),
        jax.device_put(jnp.asarray(b, jdt), NamedSharding(mesh, P(None, None))),
    )
    ref = (
        np.asarray(jnp.asarray(a, jdt), np.float32)
        @ np.asarray(jnp.asarray(b, jdt), np.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref, rtol=0,
        atol=validation_atol(dtype, k),
    )


def _ring_rs_case(d, dtype, m, n, k, bn, bk, interpret):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    rng = np.random.default_rng(d * 11 + n)
    a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
    b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
    jdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    f = jax.jit(
        jax.shard_map(
            lambda a_s, b_s: ring_matmul_rs(
                a_s, b_s, axis_size=d, block_n=bn, block_k=bk,
                interpret=interpret,
            ),
            mesh=mesh,
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
            check_vma=False,
        )
    )
    out = f(
        jax.device_put(jnp.asarray(a, jdt), NamedSharding(mesh, P(None, "tp"))),
        jax.device_put(jnp.asarray(b, jdt), NamedSharding(mesh, P("tp", None))),
    )
    ref = (
        np.asarray(jnp.asarray(a, jdt), np.float32)
        @ np.asarray(jnp.asarray(b, jdt), np.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref, rtol=0,
        atol=validation_atol(dtype, k),
    )


@pytest.mark.parametrize("d", [2, 4, 8])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("aspect", ["wide", "tall"])
def test_ring_ag_matmul_matrix(d, dtype, aspect):
    m = 16 * d
    n, k = (96, 32) if aspect == "wide" else (32, 96)
    _ring_ag_case(d, dtype, m, n, k, bn=32, bk=32,
                  interpret=pltpu.InterpretParams())


@pytest.mark.parametrize("d", [2, 4, 8])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("aspect", ["wide", "tall"])
def test_ring_matmul_rs_matrix(d, dtype, aspect):
    m = 16 * d
    n, k = (96, 16 * d) if aspect == "wide" else (32, 48 * d)
    _ring_rs_case(d, dtype, m, n, k, bn=16, bk=16,
                  interpret=pltpu.InterpretParams())


@pytest.mark.parametrize("kernel", ["ag", "rs"])
@pytest.mark.parametrize("d", [2, 4])
def test_ring_kernels_race_detector(kernel, d):
    """Both RDMA kernels produce correct results with the distributed
    interpreter's race detector enabled — the credit-semaphore protocol
    must leave no unsynchronized buffer reuse at any world size."""
    params = pltpu.InterpretParams(detect_races=True)
    if kernel == "ag":
        _ring_ag_case(d, "float32", 16 * d, 32, 32, 16, 16, params)
    else:
        _ring_rs_case(d, "float32", 16 * d, 32, 16 * d, 16, 16, params)


def test_ring_matmul_rs_wire_dtype_pin():
    """Bit-level pin of the rs kernel's accumulation contract: local GEMMs
    accumulate in float32 (k-blocked), but the travelling partial sums
    ride the ring in the OPERAND dtype — so a bf16 run must equal a jnp
    simulation that casts each local partial to bf16 and folds in ring
    order (chunk c gathers devices c+1, c+2, ..., c; kernel schedule at
    ops/collective_matmul.py:270)."""
    d, m, n, k = 4, 32, 48, 64
    bn, bk = 16, 16
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    rng = np.random.default_rng(3)
    a32 = jnp.asarray(rng.uniform(-1, 1, (m, k)), jnp.float32)
    b32 = jnp.asarray(rng.uniform(-1, 1, (k, n)), jnp.float32)
    a = a32.astype(jnp.bfloat16)
    b = b32.astype(jnp.bfloat16)
    f = jax.jit(
        jax.shard_map(
            lambda a_s, b_s: ring_matmul_rs(
                a_s, b_s, axis_size=d, block_n=bn, block_k=bk,
                interpret=pltpu.InterpretParams(),
            ),
            mesh=mesh,
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
            check_vma=False,
        )
    )
    out = np.asarray(
        f(
            jax.device_put(a, NamedSharding(mesh, P(None, "tp"))),
            jax.device_put(b, NamedSharding(mesh, P("tp", None))),
        ).astype(jnp.float32)
    )

    m_loc, kd = m // d, k // d
    sim = np.zeros((m, n), np.float32)
    for c in range(d):
        acc = None
        for t in range(d):
            j = (c + 1 + t) % d  # device folding chunk c at ring step t
            a_rows = a[c * m_loc:(c + 1) * m_loc, j * kd:(j + 1) * kd]
            # k-blocked f32 accumulation exactly as _gemm_pipeline does
            part = jnp.zeros((m_loc, n), jnp.float32)
            for k0 in range(0, kd, bk):
                part = part + jnp.matmul(
                    a_rows[:, k0:k0 + bk].astype(jnp.float32),
                    b[j * kd + k0:j * kd + k0 + bk].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
            part = part.astype(jnp.bfloat16)  # wire dtype
            acc = part if acc is None else (part + acc)  # bf16 fold
        sim[c * m_loc:(c + 1) * m_loc] = np.asarray(acc.astype(jnp.float32))
    np.testing.assert_array_equal(out, sim)


from ddlb_tpu.ops.alltoall_matmul import alltoall_expert_matmul  # noqa: E402


@pytest.mark.parametrize("d", [2, 4, 8])
def test_alltoall_expert_matmul(d):
    """Kernel-level all-to-all: group e of each device's rows through
    expert e, token order preserved — checked against the blocked einsum
    oracle at d ring sizes (race detector on: the protocol has no credit
    gating, so the detector guards the slot-distinctness argument)."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    m, n, k = 8 * d * d, 32, 32
    g = m // (d * d)
    rng = np.random.default_rng(7)
    a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
    w = rng.uniform(-1, 1, (d, k, n)).astype(np.float32)
    f = jax.jit(
        jax.shard_map(
            lambda a_s, w_s: alltoall_expert_matmul(
                a_s, w_s[0], axis_size=d, block_n=32, block_k=32,
                interpret=pltpu.InterpretParams(detect_races=True),
            ),
            mesh=mesh,
            in_specs=(P("tp", None), P("tp", None, None)),
            out_specs=P("tp", None),
            check_vma=False,
        )
    )
    out = np.asarray(
        f(
            jax.device_put(a, NamedSharding(mesh, P("tp", None))),
            jax.device_put(w, NamedSharding(mesh, P("tp", None, None))),
        )
    )
    want = np.einsum(
        "pegk,ekn->pegn", a.reshape(d, d, g, k), w
    ).reshape(m, n)
    np.testing.assert_allclose(out, want, rtol=0, atol=1e-4)
