"""Pallas kernel correctness: tiled GEMM and the RDMA ring collective
matmuls (interpret mode on the CPU mesh; the ring kernels run under the
distributed TPU interpreter, which emulates remote DMA and semaphores)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.ops.collective_matmul import ring_ag_matmul, ring_matmul_rs
from ddlb_tpu.ops.matmul import matmul
from ddlb_tpu.primitives.registry import load_impl_class


def test_pallas_matmul_interpret():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(0, 1, (256, 128)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 1, (128, 256)), jnp.float32)
    out = matmul(a, b, block_m=128, block_n=128, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=0, atol=1e-4
    )


def test_pallas_matmul_shape_errors():
    a = jnp.zeros((100, 64), jnp.float32)
    b = jnp.zeros((64, 64), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        matmul(a, b, block_m=64, interpret=True)
    with pytest.raises(ValueError, match="contraction mismatch"):
        matmul(jnp.zeros((64, 32)), jnp.zeros((64, 64)), interpret=True)


@pytest.mark.parametrize("d", [2, 4, 8])
def test_ring_ag_matmul(d):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    m, n, k = 16 * d, 32, 32
    rng = np.random.default_rng(1)
    a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
    b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
    f = jax.jit(
        jax.shard_map(
            lambda a_s, b_r: ring_ag_matmul(
                a_s, b_r, axis_size=d, block_n=32, block_k=32,
                interpret=pltpu.InterpretParams(),
            ),
            mesh=mesh,
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None),
            check_vma=False,
        )
    )
    out = np.asarray(
        f(
            jax.device_put(a, NamedSharding(mesh, P("tp", None))),
            jax.device_put(b, NamedSharding(mesh, P(None, None))),
        )
    )
    np.testing.assert_allclose(out, a @ b, rtol=0, atol=1e-4)


@pytest.mark.parametrize("d", [2, 4, 8])
def test_ring_matmul_rs(d):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:d]), ("tp",))
    m, n, k = 16 * d, 32, 16 * d
    rng = np.random.default_rng(2)
    a = rng.uniform(-1, 1, (m, k)).astype(np.float32)
    b = rng.uniform(-1, 1, (k, n)).astype(np.float32)
    f = jax.jit(
        jax.shard_map(
            lambda a_s, b_s: ring_matmul_rs(
                a_s, b_s, axis_size=d, block_n=32, block_k=16,
                interpret=pltpu.InterpretParams(),
            ),
            mesh=mesh,
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
            check_vma=False,
        )
    )
    out = np.asarray(
        f(
            jax.device_put(a, NamedSharding(mesh, P(None, "tp"))),
            jax.device_put(b, NamedSharding(mesh, P("tp", None))),
        )
    )
    np.testing.assert_allclose(out, a @ b, rtol=0, atol=1e-4)


@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_pallas_impl_xla_collective(primitive):
    cls = load_impl_class(primitive, "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="xla_collective", block_m=128, block_n=128, block_k=128,
    )
    result = impl.run()
    assert result.shape == (128, 128)
    assert impl.validate(result)


@pytest.mark.parametrize("primitive", ["tp_columnwise", "tp_rowwise"])
def test_pallas_impl_ring_rdma(primitive):
    cls = load_impl_class(primitive, "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="ring_rdma", block_n=128, block_k=128,
    )
    result = impl.run()
    assert result.shape == (128, 128)
    assert impl.validate(result)


def test_pallas_impl_ring_rdma_race_detector():
    """The distributed interpreter's race detector runs clean on the ring
    kernel (the credit-semaphore protocol is what makes this pass)."""
    cls = load_impl_class("tp_columnwise", "pallas")
    impl = cls(
        128, 128, 128, dtype="float32",
        algorithm="ring_rdma", block_n=128, block_k=128, detect_races=True,
    )
    assert impl.validate(impl.run())
