"""Real multi-process distributed bootstrap over ``jax.distributed``.

Launches two OS processes, each owning 4 virtual CPU devices, that form an
8-device world through the coordinator (the analogue of the reference's
TCP-rendezvous process-group formation,
/root/reference/ddlb/primitives/TPColumnwise/pytorch.py:53-59), then runs a
full benchmark worker across the joint mesh — cross-process operand
construction, collectives, timing MAX-reduce and validation included.
"""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
from ddlb_tpu.benchmark import benchmark_worker

row = benchmark_worker({
    "primitive": "tp_columnwise",
    "impl_id": "jax_spmd_0",
    "base_implementation": "jax_spmd",
    "options": {},
    "m": 128, "n": 32, "k": 64,
    "dtype": "float32",
    "num_iterations": 2,
    "num_warmups": 1,
    "validate": True,
    "time_measurement_backend": "host_clock",
    "barrier_at_each_iteration": True,
    "profile_dir": None,
})
assert row["valid"], row
assert row["world_size"] == 8, row
assert row["num_processes"] == 2, row
print("CHILD_OK", row["world_size"], row["num_processes"])
"""


_CHILD_DCN = r"""
import os, sys
from ddlb_tpu.runtime import Runtime
from ddlb_tpu.benchmark import benchmark_worker

rt = Runtime()
# each process's devices stand in for one slice (slice id = process index)
assert rt.num_slices == 2, rt.slice_ids

row = benchmark_worker({
    "primitive": "tp_columnwise",
    "impl_id": "jax_spmd_0",
    "base_implementation": "jax_spmd",
    # dcn transport: the mesh interleaves the two process-"slices", so
    # EVERY collective hop crosses the process boundary (the DCN stand-in)
    "options": {"transport": "dcn"},
    "m": 128, "n": 32, "k": 64,
    "dtype": "float32",
    "num_iterations": 2,
    "num_warmups": 1,
    "validate": True,
    "time_measurement_backend": "host_clock",
    "barrier_at_each_iteration": True,
    "profile_dir": None,
})
assert row["valid"], row
assert "transport=dcn" in row["option"], row
print("CHILD_DCN_OK", row["world_size"], row["num_processes"])
"""


_CHILD_DECODE = r"""
import os, sys
from ddlb_tpu.benchmark import benchmark_worker

# the serving step across a REAL process boundary: the KV cache shards
# batch-over-dp/heads-over-tp across two processes; prefill fills it and
# the measured decode validates against the teacher-forced oracle
row = benchmark_worker({
    "primitive": "transformer_decode",
    "impl_id": "spmd_0",
    "base_implementation": "spmd",
    "options": {"batch": 8, "vocab": 64, "n_heads": 4, "dp": 2, "tp": 4},
    "m": 8, "n": 32, "k": 64,
    "dtype": "float32",
    "num_iterations": 2,
    "num_warmups": 1,
    "validate": True,
    "time_measurement_backend": "host_clock",
    "barrier_at_each_iteration": True,
    "profile_dir": None,
})
assert row["valid"], row
assert row["world_size"] == 8, row
print("CHILD_DEC_OK", row["world_size"], row["num_processes"])
"""


_CHILD_QUANTIZED = r"""
import os, sys
from ddlb_tpu.benchmark import benchmark_worker

# the int8-wire claim across a REAL process boundary: the all-gather
# moves int8 shards + scales between the two processes and the result
# still meets the quantization bound
row = benchmark_worker({
    "primitive": "tp_columnwise",
    "impl_id": "quantized_0",
    "base_implementation": "quantized",
    "options": {"quantize": "dynamic"},
    "m": 128, "n": 32, "k": 64,
    "dtype": "bfloat16",
    "num_iterations": 2,
    "num_warmups": 1,
    "validate": True,
    "time_measurement_backend": "host_clock",
    "barrier_at_each_iteration": True,
    "profile_dir": None,
})
assert row["valid"], row
assert row["world_size"] == 8, row
print("CHILD_Q_OK", row["world_size"], row["num_processes"])
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_world(tmp_path):
    _run_two_process(_CHILD, "CHILD_OK 8 2")


@pytest.mark.slow
def test_two_process_quantized_int8_wire(tmp_path):
    _run_two_process(_CHILD_QUANTIZED, "CHILD_Q_OK 8 2")


@pytest.mark.slow
def test_two_process_serving_decode(tmp_path):
    _run_two_process(_CHILD_DECODE, "CHILD_DEC_OK 8 2")


@pytest.mark.slow
def test_two_process_dcn_transport(tmp_path):
    """VERDICT r1 item #5: 2 processes x 4 devices standing in for 2
    slices; transport=dcn interleaves them so cross-'slice' collectives
    are exercised and validated."""
    _run_two_process(_CHILD_DCN, "CHILD_DCN_OK 8 2")


def _run_two_process(child_src, expect):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            {
                # neutralize any TPU plugin; pure CPU children
                "PALLAS_AXON_POOL_IPS": "",
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                "DDLB_TPU_SIM_DEVICES": "0",
                "DDLB_TPU_NUM_PROCESSES": "2",
                "DDLB_TPU_PROCESS_ID": str(pid),
                "DDLB_TPU_COORD_ADDR": f"127.0.0.1:{port}",
            }
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", child_src],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
        )
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outputs.append(out)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert expect in out, f"process {i} output:\n{out}"


@pytest.mark.slow
def test_launcher_cli_two_process_benchmark(tmp_path):
    """The mpirun-analogue launcher (cli/launch.py) fans the benchmark CLI
    over 2 processes x 4 devices; the joint 8-device world produces one
    validated CSV row."""
    import pandas as pd

    csv = tmp_path / "launched.csv"
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("DDLB_TPU_", "JAX_", "XLA_"))
    }
    out = subprocess.run(
        [
            sys.executable, "-m", "ddlb_tpu.cli.launch",
            "--processes", "2", "--devices-per-process", "4", "--",
            sys.executable, "-m", "ddlb_tpu.cli.benchmark",
            "--primitive", "tp_columnwise", "--impl", "jax_spmd",
            "-m", "128", "-n", "32", "-k", "64",
            "--dtype", "float32", "--num-iterations", "2",
            "--num-warmups", "1", "--csv", str(csv),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stdout + out.stderr
    df = pd.read_csv(csv)
    assert len(df) == 1
    assert bool(df.iloc[0]["valid"])
    assert int(df.iloc[0]["world_size"]) == 8
    assert int(df.iloc[0]["num_processes"]) == 2
