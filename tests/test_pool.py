"""Warm-worker pool (ISSUE 5): lease reuse, recycling, fault recovery.

What matters: a lease is keyed by environment signature (same signature
reuses the live worker, a mismatch forces a fresh one); ``pool_max_rows``
recycles workers on schedule, with 1 the spawn-per-row degenerate case
whose CSV schema is byte-identical to the pooled one; a killed/hung
worker's row is retried on a FRESH lease; and every row — measured and
error alike — carries truthful ``worker_reused`` / ``worker_setup_s``
columns. Lease mechanics run against stub workers (no processes);
recovery and schema tests drive real spawned children on the CPU sim.
"""

import json
import os

import pandas as pd
import pytest

from ddlb_tpu import faults
from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner
from ddlb_tpu.pool import SIGNATURE_ENV_KEYS, WorkerPool, pool_signature

SHAPE = dict(m=128, n=32, k=64)


def _runner(**over):
    kwargs = dict(
        implementations={
            "compute_only_0": {"implementation": "compute_only"},
            "jax_spmd_0": {"implementation": "jax_spmd"},
        },
        dtype="float32",
        num_iterations=2,
        num_warmups=1,
        progress=False,
        isolation="subprocess",
        retry_backoff_s=0.05,
        **SHAPE,
    )
    kwargs.update(over)
    return PrimitiveBenchmarkRunner("tp_columnwise", **kwargs)


# ---------------------------------------------------------------------------
# Lease mechanics (stub workers: no processes spawned)
# ---------------------------------------------------------------------------


class _StubWorker:
    """The lease-relevant surface of PoolWorker, no process behind it."""

    def __init__(self, signature):
        self.signature = signature
        self.rows_run = 0
        self.retired = False

    def alive(self):
        return not self.retired

    def retire(self, timeout=None, graceful=True):
        self.retired = True
        self.retired_gracefully = graceful


@pytest.fixture()
def stub_pool(monkeypatch):
    spawned = []

    def fake_spawn(self, signature):
        worker = _StubWorker(signature)
        spawned.append(worker)
        return worker

    monkeypatch.setattr(WorkerPool, "_spawn", fake_spawn)
    pool = WorkerPool(max_rows=0, worker_timeout=None)
    pool.spawned = spawned
    return pool


def test_same_signature_reuses_live_worker(stub_pool):
    sig = pool_signature()
    w1 = stub_pool.lease(sig)
    w1.rows_run += 1
    w2 = stub_pool.lease(sig)
    assert w2 is w1
    assert stub_pool.spawns == 1 and stub_pool.reuses == 1
    assert len(stub_pool.spawned) == 1


def test_signature_mismatch_forces_new_lease(stub_pool):
    w1 = stub_pool.lease(pool_signature())
    w2 = stub_pool.lease(pool_signature(extra={"mode": "other"}))
    assert w2 is not w1
    assert w1.retired  # the incompatible worker was torn down first
    assert stub_pool.spawns == 2 and stub_pool.respawns == 1


def test_env_change_changes_signature(monkeypatch):
    """Every spawn-baked env var participates in the signature, so e.g.
    switching the fault plan or the simulated world between rows can
    never hit a stale worker."""
    base = pool_signature()
    for key in SIGNATURE_ENV_KEYS:
        monkeypatch.setenv(key, "changed-for-test")
        assert pool_signature() != base, key
        monkeypatch.delenv(key)


def test_pool_max_rows_recycles(stub_pool):
    stub_pool.max_rows = 2
    sig = pool_signature()
    w1 = stub_pool.lease(sig)
    w1.rows_run = 2  # budget spent
    w2 = stub_pool.lease(sig)
    assert w2 is not w1
    assert w1.retired
    assert stub_pool.respawns == 1


def test_dead_worker_respawned(stub_pool):
    sig = pool_signature()
    w1 = stub_pool.lease(sig)
    w1.retired = True  # killed by the deadline policy
    w2 = stub_pool.lease(sig)
    assert w2 is not w1
    assert stub_pool.respawns == 1


def test_invalidate_then_fresh_lease(stub_pool):
    sig = pool_signature()
    w1 = stub_pool.lease(sig)
    stub_pool.invalidate()
    assert w1.retired
    w2 = stub_pool.lease(sig)
    assert w2 is not w1


# ---------------------------------------------------------------------------
# Real pooled sweeps (spawned children on the CPU sim)
# ---------------------------------------------------------------------------


def test_pooled_sweep_reuses_worker_and_attributes_setup(tmp_path):
    """One spawn serves the whole sweep: the first row pays worker
    setup, later rows carry worker_reused=True / worker_setup_s=0."""
    csv = str(tmp_path / "pooled.csv")
    df = _runner(output_csv=csv, worker_pool=True).run()
    assert len(df) == 2
    assert df["valid"].all(), list(df["error"])
    first, second = df.iloc[0], df.iloc[1]
    assert first["worker_reused"] == False  # noqa: E712
    assert first["worker_setup_s"] > 0
    assert second["worker_reused"] == True  # noqa: E712
    assert second["worker_setup_s"] == 0.0


def test_spawn_per_row_schema_identical(tmp_path):
    """worker_pool=False (the pool_max_rows=1 degenerate case) spawns
    per row and its CSV schema is byte-identical to the pooled one."""
    pooled_csv = str(tmp_path / "pooled.csv")
    spawn_csv = str(tmp_path / "spawn.csv")
    _runner(output_csv=pooled_csv, worker_pool=True).run()
    df = _runner(output_csv=spawn_csv, worker_pool=False).run()
    assert not df["worker_reused"].any()  # every row paid a fresh spawn
    assert (df["worker_setup_s"] > 0).all()
    pooled_header = pd.read_csv(pooled_csv, nrows=0).columns.tolist()
    spawn_header = pd.read_csv(spawn_csv, nrows=0).columns.tolist()
    assert pooled_header == spawn_header


def test_heartbeat_kill_respawns_and_retries(tmp_path, monkeypatch):
    """A worker hung mid-row is killed at the per-row deadline and the
    row retried on a FRESH lease — the pooled form of the ISSUE 4
    contract (zero rows lost, truthful attribution)."""
    plan = {
        "seed": 0,
        "rules": [
            {"site": "subprocess.entry", "kind": "hang",
             "match": {"impl": "jax_spmd_0"}, "fail_attempts": 1},
        ],
    }
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", json.dumps(plan))
    faults.reset()
    try:
        df = _runner(
            output_csv=str(tmp_path / "chaos.csv"),
            worker_pool=True,
            worker_timeout=6.0,
            max_retries=1,
        ).run()
    finally:
        monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
        faults.reset()
    assert len(df) == 2  # zero rows lost
    row = df[df["implementation"] == "jax_spmd_0"].iloc[0]
    assert row["valid"] == True  # noqa: E712
    assert row["retries"] == 1
    assert "subprocess.entry" in str(row["fault_injected"])
    # the recovered attempt ran on a fresh lease, not the killed worker
    assert row["worker_reused"] == False  # noqa: E712


def test_error_rows_carry_pool_columns(tmp_path, monkeypatch):
    """A worker that dies on every attempt still yields a row with the
    pool columns — the CSV header cannot drift between happy and error
    paths."""
    plan = {
        "seed": 0,
        "rules": [
            {"site": "subprocess.entry", "kind": "exit",
             "match": {"impl": "jax_spmd_0"}, "fail_attempts": 99},
        ],
    }
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", json.dumps(plan))
    faults.reset()
    try:
        df = _runner(
            implementations={"jax_spmd_0": {"implementation": "jax_spmd"}},
            output_csv=str(tmp_path / "err.csv"),
            worker_pool=True,
            max_retries=0,
        ).run()
    finally:
        monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
        faults.reset()
    row = df.iloc[0]
    assert "WorkerDied" in row["error"]
    assert "worker_reused" in df.columns and "worker_setup_s" in df.columns
    assert row["worker_reused"] == False  # noqa: E712


def test_reused_worker_resets_fault_counters_per_row(tmp_path, monkeypatch):
    """Determinism contract across execution modes: an ``at: [0]`` rule
    keys on the per-site call index, which the plan defines per ROW
    PROCESS — a reused worker must reset its counters at every row
    boundary so the same seeded plan injects identically pooled and
    spawn-per-row (both rows fault here, not just the warm worker's
    first)."""
    plan = {
        "seed": 0,
        "rules": [
            # deterministic kind: classified rows keep the lease warm
            # (a transient would invalidate it, masking the reuse path)
            {"site": "worker.warmup", "kind": "deterministic_error",
             "at": [0], "fail_attempts": 99},
        ],
    }
    monkeypatch.setenv("DDLB_TPU_FAULT_PLAN", json.dumps(plan))
    faults.reset()
    try:
        df = _runner(
            output_csv=str(tmp_path / "det.csv"),
            worker_pool=True,
            max_retries=0,
        ).run()
    finally:
        monkeypatch.delenv("DDLB_TPU_FAULT_PLAN")
        faults.reset()
    assert len(df) == 2
    for _, row in df.iterrows():
        assert "worker.warmup" in str(row["fault_injected"]), (
            row["implementation"], row["fault_injected"])
    # and the second row genuinely ran on the reused worker
    assert df.iloc[1]["worker_reused"] == True  # noqa: E712


def test_await_row_silent_kill_without_heartbeat_channel():
    """await_row advertises itself as the one shared hung/dead-child
    policy; a caller without a beat channel (heartbeat_channel=None)
    must still get the TimeoutError AwaitResult back from the
    silent-kill path, never an AttributeError after the kill."""
    import queue as queue_mod

    from ddlb_tpu.pool import await_row

    class _SilentProc:
        pid = 12345

        def is_alive(self):
            return True

        def kill(self):
            self.killed = True

        def join(self, timeout=None):
            pass

    proc = _SilentProc()
    result = await_row(
        proc, queue_mod.Queue(), None, worker_timeout=1.5
    )
    assert proc.killed
    assert result.worker_dead
    assert result.row is None
    assert "with no heartbeat" in result.error


def test_worker_pool_env_defaults(monkeypatch):
    from ddlb_tpu.envs import get_pool_max_rows, get_worker_pool

    monkeypatch.delenv("DDLB_TPU_WORKER_POOL", raising=False)
    monkeypatch.delenv("DDLB_TPU_POOL_MAX_ROWS", raising=False)
    assert get_worker_pool() is True  # default on
    assert get_pool_max_rows() == 0  # unlimited
    monkeypatch.setenv("DDLB_TPU_WORKER_POOL", "0")
    monkeypatch.setenv("DDLB_TPU_POOL_MAX_ROWS", "1")
    assert get_worker_pool() is False
    assert get_pool_max_rows() == 1
    runner = _runner(worker_pool=None, pool_max_rows=None)
    assert runner.worker_pool is False
    assert runner.pool_max_rows == 1


def test_pool_prefetch_rides_requests(tmp_path, monkeypatch):
    """With a persistent compile cache configured, the runner hands the
    NEXT config to the leased worker so its compile-ahead thread can
    prefetch (the cache dir afterwards holds banked executables)."""
    cache = tmp_path / "cc"
    monkeypatch.setenv("DDLB_TPU_COMPILE_CACHE", str(cache))
    df = _runner(worker_pool=True).run()
    assert df["valid"].all(), list(df["error"])
    # the worker's compiles (prefetch or row) banked into the cache dir
    assert cache.exists() and any(os.scandir(cache))
