"""The xprof trace digester that turns the MFU-breakdown capture into
an attributed top-op table inside the committed batch log."""

import importlib.util
import os

import pytest

_spec = importlib.util.spec_from_file_location(
    "xprof_summary",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "xprof_summary.py",
    ),
)
xp = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(xp)


def test_top_ops_from_real_trace(tmp_path):
    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    import jax
    import jax.numpy as jnp

    with jax.profiler.trace(str(tmp_path)):
        x = jnp.ones((256, 256))
        f = jax.jit(lambda a: (a @ a).sum())
        for _ in range(3):
            f(x).block_until_ready()

    line_name, rows = xp.top_ops(str(tmp_path), top_n=5)
    assert line_name is not None
    assert rows and len(rows) <= 5
    # fractions are of the busiest line's total: descending, in (0, 1]
    fracs = [frac for _, _, frac in rows]
    assert fracs == sorted(fracs, reverse=True)
    assert all(0 < f <= 1 for f in fracs)
    assert all(ms >= 0 for _, ms, _ in rows)


def test_empty_dir_reports_cleanly(tmp_path):
    assert xp.main(["x", str(tmp_path)]) == 1
