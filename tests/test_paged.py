"""Paged KV cache: identical tokens, smaller memory.

The contract has two halves, both pinned here on the sim mesh:

1. **Losslessness** — a paged engine produces integer-identical
   completions to the contiguous engine on the same workload (mixed
   prompt lengths, staggered admissions, slot reuse, int8 cache, GQA,
   shared prefix). Pages change where rows LIVE, never what they hold.
2. **The memory claim** — a pool smaller than the contiguous B x S_max
   still drains the workload (admissions defer FIFO-fairly under page
   pressure), pages recycle across waves without leaking, and shared
   prefix pages are table entries, not copies.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _cfg(**kw):
    from ddlb_tpu.models.transformer import TransformerConfig

    kw.setdefault("attn_kernel", "einsum")
    kw.setdefault("cache_layout", "paged")
    kw.setdefault("page_size", 8)
    return TransformerConfig(
        vocab=64, d_model=32, n_heads=4, d_ff=64,
        layers_per_stage=2, microbatches=1,
        **kw,
    )


def _engine(cfg, B=4, S_max=40, eos_id=None, num_pages=None):
    from ddlb_tpu.models.decode import make_decode_fn
    from ddlb_tpu.models.serving import ContinuousBatchingEngine
    from ddlb_tpu.models.transformer import init_params
    from ddlb_tpu.runtime import Runtime

    mesh = Runtime().mesh(("dp", "tp"), shape=(1, 2))
    params = init_params(cfg, pp=1, n_experts=2, seed=0)
    _, sh = make_decode_fn(mesh, cfg)
    params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    eng = ContinuousBatchingEngine(
        mesh, cfg, params, max_batch=B, max_len=S_max, eos_id=eos_id,
        num_pages=num_pages,
    )
    return eng, mesh, params


def _prompts(lengths, vocab=64, seed=5):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, s).astype(np.int32) for s in lengths]


def _by_request(completions):
    return {c.request_index: np.asarray(c.tokens) for c in completions}


def _run_both(paged_kw, engine_kw=None, lengths=(8, 11, 6, 9, 8, 7),
              max_new=6, prefix=None):
    """The same workload through a paged and a contiguous engine;
    returns (paged_completions, contiguous_completions, paged_engine)."""
    from ddlb_tpu.models.serving import Request

    engine_kw = engine_kw or {}
    outs = []
    eng_paged = None
    for layout in ("paged", "contiguous"):
        kw = dict(paged_kw)
        kw["cache_layout"] = layout
        cfg = _cfg(**kw)
        ekw = dict(engine_kw)
        if layout == "contiguous":
            ekw.pop("num_pages", None)
        eng, mesh, params = _engine(cfg, **ekw)
        if prefix is not None:
            eng.set_shared_prefix(prefix)
        for p in _prompts(lengths):
            eng.submit(Request(p, max_new=max_new))
        outs.append(_by_request(eng.run()))
        if layout == "paged":
            eng_paged = eng
    return outs[0], outs[1], eng_paged


class TestLossless:
    def test_equals_contiguous_mixed_lengths(self):
        paged, contig, _ = _run_both({})
        assert paged.keys() == contig.keys()
        for idx in paged:
            np.testing.assert_array_equal(paged[idx], contig[idx])

    def test_equals_contiguous_int8_gqa(self):
        paged, contig, _ = _run_both(
            {"kv_cache": "int8", "n_kv_heads": 2}
        )
        for idx in paged:
            np.testing.assert_array_equal(paged[idx], contig[idx])

    def test_prefix_sharing_lossless(self):
        # prefix spans 2 full pages (16 tokens) + a 3-token tail
        prefix = np.arange(1, 20, dtype=np.int32)
        rng = np.random.default_rng(9)
        lengths = (24, 27, 25, 26)
        prompts = []
        for s in lengths:
            p = rng.integers(1, 64, s).astype(np.int32)
            p[: prefix.size] = prefix
            prompts.append(p)

        from ddlb_tpu.models.serving import Request

        outs = []
        engines = []
        for layout in ("paged", "contiguous"):
            cfg = _cfg(cache_layout=layout)
            eng, _, _ = _engine(cfg, S_max=48)
            eng.set_shared_prefix(prefix)
            for p in prompts:
                eng.submit(Request(p, max_new=5))
            outs.append(_by_request(eng.run()))
            engines.append(eng)
        paged, contig = outs
        for idx in paged:
            np.testing.assert_array_equal(paged[idx], contig[idx])
        eng = engines[0]
        assert eng.stats.prefix_hits == len(prompts)
        # the shared span is table entries, not copies: per expert one
        # page set, regardless of how many slots used it
        assert len(eng._prefix_pages) == eng.tp * (prefix.size // 8)


def _oracle_chain(mesh, cfg, params, prompt, slot, B, n_new):
    """Row ``slot`` of a greedy generate carrying ``prompt`` in every
    row, on a CONTIGUOUS cache (layouts change where rows live, not the
    math — the slot index pins the block router's expert)."""
    from ddlb_tpu.models.decode import init_cache, make_generate_fn

    ccfg = dataclasses.replace(cfg, cache_layout="contiguous")
    gen, _ = make_generate_fn(mesh, ccfg, n_new=n_new)
    S0 = prompt.size
    batch = jnp.asarray(np.broadcast_to(prompt, (B, S0)).copy())
    cache = init_cache(ccfg, B, S0 + n_new, mesh=mesh)
    return np.asarray(jax.jit(gen)(params, cache, batch))[slot]


class TestPool:
    def test_small_pool_drains_with_deferrals(self):
        # each request needs ceil((8 + 6) / 8) = 2 pages; a 5-page pool
        # admits at most 2 at once where B=4 slots could run 4. Under
        # deferral, requests land in DIFFERENT slots than a contiguous
        # run would give them (slot -> expert -> tokens), so each
        # completion is pinned to its own slot's greedy oracle instead
        # of the contiguous engine's completions.
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, mesh, params = _engine(cfg, num_pages=5)
        prompts = _prompts((8, 8, 8, 8, 8, 8))
        for p in prompts:
            eng.submit(Request(p, max_new=6))
        done = eng.run()
        assert len(done) == len(prompts)
        for c in done:
            want = _oracle_chain(
                mesh, cfg, params, prompts[c.request_index], c.slot,
                eng.B, 6,
            )
            np.testing.assert_array_equal(c.tokens, want)
        assert eng.stats.admissions_deferred > 0
        assert eng.stats.peak_pages_in_use <= 5
        # drained: every page returned
        assert eng.stats.pages_in_use == 0

    def test_pool_recycles_without_leak(self):
        _, _, eng = _run_both({}, engine_kw={"num_pages": 6})
        assert eng.stats.pages_in_use == 0
        assert sorted(eng._free_pages) == list(range(6))

    def test_reset_reruns_identically(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, _, _ = _engine(cfg, num_pages=8)
        prompts = _prompts((8, 10, 7))
        for p in prompts:
            eng.submit(Request(p, max_new=5))
        first = _by_request(eng.run())
        eng.reset()
        for p in prompts:
            eng.submit(Request(p, max_new=5))
        second = _by_request(eng.run())
        assert first.keys() == second.keys()
        for idx in first:
            np.testing.assert_array_equal(first[idx], second[idx])


class TestBenchmarkMember:
    def test_serve_paged_through_worker(self):
        from ddlb_tpu.benchmark import benchmark_worker

        row = benchmark_worker(
            {
                "primitive": "transformer_decode",
                "impl_id": "spmd_paged",
                "base_implementation": "spmd",
                "options": {
                    "phase": "serve",
                    "n_requests": 6,
                    "n_new": 4,
                    "batch": 8,
                    "vocab": 64,
                    "n_heads": 8,
                    "layers": 1,
                    "attn_kernel": "einsum",
                    "cache_layout": "paged",
                    "page_size": 8,
                    "page_pool_frac": 0.5,
                },
                "m": 16,
                "n": 32,
                "k": 64,
                "dtype": "bfloat16",
                "num_iterations": 1,
                "num_warmups": 0,
                "validate": True,
                "time_measurement_backend": "host_clock",
                "barrier_at_each_iteration": False,
            }
        )
        assert row["valid"], row["error"]
        # the engine's drain stats ride the row (extra_row_fields)
        assert 0.0 < row["serve_occupancy"] <= 1.0
        assert row["serve_pages_capacity"] > 0
        assert 0 < row["serve_peak_pages"] <= row["serve_pages_capacity"]

    def test_paged_requires_serve_phase(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_decode", "spmd")
        with pytest.raises(ValueError, match="serve"):
            cls(
                16, 32, 64, dtype="bfloat16", phase="decode",
                cache_layout="paged", batch=8, vocab=64, n_heads=4,
            )

    def test_page_options_dead_when_contiguous(self):
        from ddlb_tpu.primitives.registry import load_impl_class

        cls = load_impl_class("transformer_decode", "spmd")
        with pytest.raises(ValueError, match="no effect"):
            cls(
                16, 32, 64, dtype="bfloat16", phase="decode",
                page_size=16, batch=8, vocab=64, n_heads=4,
            )


class TestCacheOps:
    def test_chunk_write_matches_contiguous(self):
        # the t>1 (speculative-verify chunk) write path, paged vs
        # contiguous: same rows land at the same logical positions.
        # No engine path drives this today (speculate is fixed-shape and
        # measures the contiguous layout); this pin keeps the branch
        # live for a future paged speculate without an engine detour.
        from ddlb_tpu.models.decode import (
            _cache_max_len,
            _cache_read,
            _cache_write,
            init_cache,
            init_paged_cache,
        )
        from ddlb_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(
            vocab=16, d_model=16, n_heads=2, d_ff=16,
            layers_per_stage=2, cache_layout="paged", page_size=4,
        )
        ccfg = dataclasses.replace(cfg, cache_layout="contiguous")
        b, S, t = 2, 16, 3
        rng = np.random.default_rng(3)
        k = jnp.asarray(rng.normal(0, 1, (b, t, 2, 8)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (b, t, 2, 8)), jnp.float32)

        paged = init_paged_cache(cfg, b, S, num_pages=b * (S // 4))
        # map every slot's pages (identity-ish shuffled layout)
        table = np.arange(b * (S // 4), dtype=np.int32)
        rng.shuffle(table)
        paged["table"] = jnp.asarray(table.reshape(b, S // 4))
        contig = init_cache(ccfg, b, S)

        start = 5  # crosses a page boundary (pages of 4: rows 5,6,7)
        for l in range(2):
            paged = _cache_write(paged, l, jnp.int32(start), k, v, False)
            contig = _cache_write(contig, l, jnp.int32(start), k, v, False)
        assert _cache_max_len(paged) == S
        for l in range(2):
            np.testing.assert_allclose(
                np.asarray(_cache_read(paged, "k", l, jnp.float32)),
                np.asarray(_cache_read(contig, "k", l, jnp.float32)),
                rtol=0, atol=0,
            )
            np.testing.assert_allclose(
                np.asarray(_cache_read(paged, "v", l, jnp.float32)),
                np.asarray(_cache_read(contig, "v", l, jnp.float32)),
                rtol=0, atol=0,
            )


class TestPrefixRetirement:
    """Clearing/replacing the shared prefix while slots are IN FLIGHT
    must not release pages their tables still map: a freed page would be
    reallocated by the next admission (or the replacement prefix's own
    scatter) and overwritten under an active sequence's reads. Release
    is refcounted: deferred until the last mapping slot finishes."""

    PREFIX = np.arange(1, 17, dtype=np.int32)   # 2 full pages of 8

    def _admit_four(self, eng, max_new=8):
        from ddlb_tpu.models.serving import Request

        rng = np.random.default_rng(11)
        prompts = []
        for _ in range(4):
            p = np.empty(20, np.int32)
            p[:16] = self.PREFIX
            p[16:] = rng.integers(1, 64, 4)
            prompts.append(p)
            eng.submit(Request(p, max_new=max_new))
        assert eng.admit_ready() == 4
        eng.step()
        eng.step()
        return prompts

    def test_clear_mid_flight_defers_release(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        # pool exactly = prefix (tp*2=4) + 4 slots x 2 fresh = 12: after
        # the four admissions the ONLY pages a new admission could get
        # are the prefix's — the bug would hand them over mid-read
        eng, mesh, params = _engine(cfg, S_max=40, num_pages=12)
        eng.set_shared_prefix(self.PREFIX)
        prompts = self._admit_four(eng)

        eng.set_shared_prefix(None)
        # pages retired, NOT freed: all four slots still map them
        assert len(eng._retired_prefix) == 1
        pages, slots = eng._retired_prefix[0]
        assert sorted(pages) and slots == {0, 1, 2, 3}
        assert eng.stats.pages_in_use == 12
        assert not eng._free_pages

        # a post-clear request must DEFER (no free pages), not steal the
        # retired prefix pages
        extra = np.arange(30, 42, dtype=np.int32)  # 12 tokens, no match
        eng.submit(Request(extra, max_new=4))
        assert eng.admit_ready() == 0
        assert not eng._free_pages  # retired pages stayed unavailable

        done = eng.run()
        assert len(done) == 5
        for c in done:
            p = prompts[c.request_index] if c.request_index < 4 else extra
            n_new = 8 if c.request_index < 4 else 4
            want = _oracle_chain(mesh, cfg, params, p, c.slot, eng.B, n_new)
            np.testing.assert_array_equal(c.tokens, want)
        # drained: retirement released everything back
        assert eng._retired_prefix == []
        assert sorted(eng._free_pages) == list(range(12))

    def test_replace_mid_flight_defers_old_release(self):
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        # ample pool: replacement seeds fresh pages while the old set
        # stays pinned under the four in-flight slots
        eng, mesh, params = _engine(cfg, S_max=40, num_pages=20)
        eng.set_shared_prefix(self.PREFIX)
        old_pages = sorted(eng._prefix_pages)
        prompts = self._admit_four(eng)

        new_prefix = np.arange(101, 118, dtype=np.int32)  # 17 tokens
        eng.set_shared_prefix(new_prefix)
        assert len(eng._retired_prefix) == 1
        assert sorted(eng._retired_prefix[0][0]) == old_pages
        # the new prefix's pages are disjoint from the retired set
        assert not set(eng._prefix_pages) & set(old_pages)

        # admissions under the NEW prefix while the old one drains
        rng = np.random.default_rng(13)
        extras = []
        for _ in range(2):
            p = np.empty(21, np.int32)
            p[:17] = new_prefix
            p[17:] = rng.integers(1, 64, 4)
            extras.append(p)
            eng.submit(Request(p, max_new=4))

        done = eng.run()
        assert len(done) == 6
        for c in done:
            p = (prompts[c.request_index] if c.request_index < 4
                 else extras[c.request_index - 4])
            n_new = 8 if c.request_index < 4 else 4
            want = _oracle_chain(mesh, cfg, params, p, c.slot, eng.B, n_new)
            np.testing.assert_array_equal(c.tokens, want)
        assert eng._retired_prefix == []
        assert eng.stats.pages_in_use == len(eng._prefix_pages)


class TestGuards:
    def test_paged_rejects_dp(self):
        from ddlb_tpu.models.decode import make_decode_fn
        from ddlb_tpu.runtime import Runtime

        mesh = Runtime().mesh(("dp", "tp"), shape=(2, 2))
        with pytest.raises(ValueError, match="dp=1"):
            make_decode_fn(mesh, _cfg(), ragged=True)

    def test_paged_pallas_decode_kernel_lossless(self):
        # the fused paged kernel through the engine: identical tokens to
        # the einsum paged path on the same workload
        einsum, _, _ = _run_both({})
        pallas, _, _ = _run_both({"decode_kernel": "pallas"})
        assert einsum.keys() == pallas.keys()
        for idx in einsum:
            np.testing.assert_array_equal(einsum[idx], pallas[idx])

    def test_page_size_must_divide_max_len(self):
        with pytest.raises(ValueError, match="page_size"):
            _engine(_cfg(page_size=7), S_max=40)

    def test_num_pages_requires_paged(self):
        with pytest.raises(ValueError, match="num_pages"):
            _engine(_cfg(cache_layout="contiguous"), num_pages=4)

    def test_pool_too_small_for_prefix(self):
        cfg = _cfg()
        eng, _, _ = _engine(cfg, S_max=48, num_pages=2)
        with pytest.raises(ValueError, match="page pool too small"):
            eng.set_shared_prefix(np.arange(1, 20, dtype=np.int32))
        # failure leaves a consistent engine: no half-set prefix, no
        # orphaned pages — serving continues as if no prefix were set
        assert eng._prefix_tokens is None
        assert eng.stats.pages_in_use == 0

        from ddlb_tpu.models.serving import Request

        eng.submit(Request(np.arange(1, 9, dtype=np.int32), max_new=4))
        done = eng.run()
        assert len(done) == 1

    def test_admit_raises_when_prefix_growth_makes_head_unfittable(self):
        # submit() screens against the prefix pin AT SUBMIT TIME; if the
        # prefix then grows, a queued head that can never fit must fail
        # loudly at admission, not defer forever (run() livelock)
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, _, _ = _engine(cfg, S_max=48, num_pages=8)
        eng.submit(Request(np.arange(1, 9, dtype=np.int32), max_new=24))
        # 24-token prefix pins tp*3 = 6 of 8 pages; the queued request
        # needs ceil((8+24)/8) = 4 > 2 attainable
        eng.set_shared_prefix(np.arange(1, 25, dtype=np.int32))
        with pytest.raises(RuntimeError, match="can\\s+ever free"):
            eng.run()

    def test_submit_rejects_unfittable_request(self):
        # a request that could NEVER fit the pool must fail at submit,
        # not spin run() forever with admissions deferring
        from ddlb_tpu.models.serving import Request

        cfg = _cfg()
        eng, _, _ = _engine(cfg, S_max=40, num_pages=2)
        with pytest.raises(ValueError, match="pages"):
            eng.submit(Request(np.arange(1, 20, dtype=np.int32), max_new=6))
