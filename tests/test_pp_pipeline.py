"""PPPipeline (pipeline-parallel staged GEMM chain) validation on the CPU
mesh.

Output is the replicated chain product ``x @ W_0 @ ... @ W_{d-1}``;
validation compares every shard against the host chain oracle with the
depth-scaled tolerance.
"""

import numpy as np
import pytest

from ddlb_tpu.primitives.registry import load_impl_class

M, N, K = 96, 64, 64  # k == n (stages compose); m % microbatches == 0


def _check_replicated(impl, result):
    assert result.shape == (M, N)
    shard_shapes = {s.data.shape for s in result.addressable_shards}
    assert shard_shapes == {(M, N)}
    assert impl.validate(result)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("microbatches", [1, 4])
def test_jax_spmd(dtype, microbatches):
    cls = load_impl_class("pp_pipeline", "jax_spmd")
    impl = cls(M, N, K, dtype=dtype, microbatches=microbatches)
    _check_replicated(impl, impl.run())


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_xla_gspmd(dtype):
    cls = load_impl_class("pp_pipeline", "xla_gspmd")
    impl = cls(M, N, K, dtype=dtype)
    _check_replicated(impl, impl.run())


@pytest.mark.parametrize("size", ["sharded", "unsharded"])
def test_compute_only(size):
    cls = load_impl_class("pp_pipeline", "compute_only")
    impl = cls(M, N, K, dtype="float32", size=size)
    result = impl.run()
    assert impl.validate(result)
    assert result.shape == (M, N)


def test_gpipe_matches_gspmd():
    """Hand-scheduled pipeline and compiler chain agree on seeded inputs."""
    spmd = load_impl_class("pp_pipeline", "jax_spmd")(
        M, N, K, dtype="float32", microbatches=2
    )
    gspmd = load_impl_class("pp_pipeline", "xla_gspmd")(M, N, K, dtype="float32")
    np.testing.assert_allclose(
        np.asarray(spmd.run()), np.asarray(gspmd.run()), atol=1e-4
    )


def test_chain_depth_matters():
    """The chain must apply all d stage weights in order — guard against a
    schedule that applies only the resident stage."""
    impl = load_impl_class("pp_pipeline", "jax_spmd")(M, N, K, dtype="float32")
    out = np.asarray(impl.run())
    a, w = impl._host_chain_operands()
    assert not np.allclose(out, a @ w[0], atol=1e-3)


def test_flops_counts_all_stages():
    impl = load_impl_class("pp_pipeline", "jax_spmd")(M, N, K, dtype="float32")
    assert impl.flops() == 2.0 * M * K * N * 8


def test_shape_constraints():
    cls = load_impl_class("pp_pipeline", "jax_spmd")
    with pytest.raises(ValueError, match="must equal"):
        cls(M, N + 8, K)
    with pytest.raises(ValueError, match="microbatches"):
        cls(M, N, K, microbatches=5)  # 96 % 5 != 0
    with pytest.raises(ValueError, match="floating"):
        cls(M, N, K, dtype="int32")
    with pytest.raises(ValueError, match="Unknown option"):
        cls(M, N, K, bogus=1)
