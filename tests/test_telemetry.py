"""Telemetry subsystem: span nesting/ordering, shard merge across
subprocesses, trace-file schema, metrics registry, and the metric
columns in runner rows (ISSUE 2 acceptance surface)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ddlb_tpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REQUIRED_ROW_KEYS = (
    "barrier_wait_s",
    "hbm_high_water_bytes",
    "loop_overhead_s",
    "collective_bytes",
)


@pytest.fixture
def trace_dir(tmp_path, monkeypatch):
    """Point DDLB_TPU_TRACE at a fresh dir for the duration of a test.

    The tracer singleton keys on (dir, pid), so a new tmp dir per test
    guarantees a fresh shard without touching telemetry internals.
    """
    d = tmp_path / "trace"
    monkeypatch.setenv("DDLB_TPU_TRACE", str(d))
    return d


def _span_events(directory):
    return [
        e for e in telemetry.read_events(str(directory)) if e.get("ph") == "X"
    ]


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def test_span_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("DDLB_TPU_TRACE", raising=False)
    with telemetry.span("nothing", cat="x"):
        assert telemetry.current_depth() == 0  # no stack when disabled
    assert telemetry.get_tracer() is None
    assert telemetry.merge_trace() is None


def test_span_nesting_and_ordering(trace_dir):
    with telemetry.span("outer", cat="a", tag="o"):
        assert telemetry.current_depth() == 1
        with telemetry.span("inner", cat="b"):
            assert telemetry.current_depth() == 2
    assert telemetry.current_depth() == 0

    events = _span_events(trace_dir)
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    outer, inner = by_name["outer"], by_name["inner"]
    # nesting depth recorded; inner closed first (JSONL order), and its
    # [ts, ts+dur] interval is contained in outer's
    assert outer["args"]["depth"] == 0
    assert inner["args"]["depth"] == 1
    assert events.index(inner) < events.index(outer)
    assert inner["ts"] >= outer["ts"] - 1.0  # µs clock granularity slack
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
    assert outer["args"]["tag"] == "o"


def test_trace_schema(trace_dir):
    with telemetry.span("s", cat="phase", extra=1):
        pass
    telemetry.instant("marker", note="hi")
    telemetry.completed_event("late", 0.25, cat="compile")
    events = telemetry.read_events(str(trace_dir))
    assert events, "no events written"
    for e in events:
        assert e["ph"] in ("X", "i", "M")
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        assert isinstance(e.get("args", {}), dict)
        if e["ph"] in ("X", "i"):
            assert isinstance(e["ts"], (int, float)) and e["ts"] > 0
            assert e["args"]["rank"] == 0
            assert e["args"]["host"]
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    late = [e for e in events if e["name"] == "late"][0]
    assert late["dur"] == pytest.approx(0.25e6)
    # rank-tagged process metadata for the merged multi-process view
    meta = [e for e in events if e["ph"] == "M"]
    assert any(m["args"]["name"].startswith("p0@") for m in meta)


def test_subprocess_shard_merge(trace_dir):
    """isolation='subprocess' contract: children write their own shards;
    the parent merges every shard into one Chrome trace.json."""
    with telemetry.span("parent_span", cat="row"):
        pass
    child = (
        "import os\n"
        "from ddlb_tpu import telemetry\n"
        "with telemetry.span('child_span', cat='row'):\n"
        "    pass\n"
    )
    env = dict(os.environ, DDLB_TPU_TRACE=str(trace_dir))
    out = subprocess.run(
        [sys.executable, "-c", child], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    shards = list(trace_dir.glob("trace-*.jsonl"))
    assert len(shards) == 2, [s.name for s in shards]

    merged = telemetry.merge_trace(str(trace_dir))
    assert merged and os.path.basename(merged) == "trace.json"
    with open(merged) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"parent_span", "child_span"} <= names
    pids = {
        e["pid"] for e in doc["traceEvents"]
        if e["name"] in ("parent_span", "child_span")
    }
    assert len(pids) == 2  # genuinely two processes on one timeline


def test_merge_is_deterministic_for_equal_timestamps(tmp_path):
    """ISSUE 14 satellite: equal-microsecond spans from different pids
    must not reorder across merges — the merge sorts by ts with a
    ``(pid, tid, seq)`` tie-break, so the output is a pure function of
    the shard CONTENTS (shard filenames embed pids that change every
    run and must not decide the order)."""
    from ddlb_tpu.telemetry import trace as trace_mod

    def shard(name, events):
        with open(tmp_path / name, "w", encoding="utf-8") as f:
            for event in events:
                f.write(json.dumps(event) + "\n")

    # two pids, every span at the SAME ts; within pid 7, two tids and
    # within one tid two emissions (the seq tie-break)
    shard(
        "trace-host-p0-9.jsonl",
        [
            {"ph": "M", "name": "process_name", "pid": 9, "tid": 0,
             "args": {"name": "p1@host"}},
            {"ph": "X", "name": "b2", "ts": 100.0, "dur": 1.0, "pid": 9,
             "tid": 1, "seq": 2},
            {"ph": "X", "name": "b1", "ts": 100.0, "dur": 1.0, "pid": 9,
             "tid": 1, "seq": 1},
        ],
    )
    shard(
        "trace-host-p0-7.jsonl",
        [
            {"ph": "X", "name": "a2", "ts": 100.0, "dur": 1.0, "pid": 7,
             "tid": 5, "seq": 1},
            {"ph": "X", "name": "a1", "ts": 100.0, "dur": 1.0, "pid": 7,
             "tid": 3, "seq": 1},
        ],
    )
    merged = telemetry.merge_trace(str(tmp_path))
    with open(merged) as f:
        first = [e["name"] for e in json.load(f)["traceEvents"]]
    # metadata first, then (pid, tid, seq) inside the equal-ts group
    assert first == ["process_name", "a1", "a2", "b1", "b2"]
    # merging again (and after renaming a shard, i.e. a different read
    # order) yields byte-identical output
    with open(merged, "rb") as f:
        doc1 = f.read()
    os.rename(
        tmp_path / "trace-host-p0-7.jsonl",
        tmp_path / "trace-host-p0-zz.jsonl",
    )
    telemetry.merge_trace(str(tmp_path))
    with open(merged, "rb") as f:
        assert f.read() == doc1
    assert trace_mod._merge_sort_key({"ph": "M"})[0] == 0


def test_tracer_stamps_monotonic_seq(trace_dir):
    for _ in range(3):
        with telemetry.span("good", cat="x"):
            pass
    events = [
        e for e in telemetry.read_events(str(trace_dir))
        if e.get("name") == "good"
    ]
    seqs = [e.get("seq") for e in events]
    assert all(isinstance(s, int) for s in seqs)
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_unwritable_trace_dir_disables_tracing(tmp_path, monkeypatch, capsys):
    """Telemetry must never abort the sweep it observes: an unwritable
    DDLB_TPU_TRACE degrades to one warning + tracing off, not an OSError
    escaping from span exits."""
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where the trace dir should be")
    monkeypatch.setenv("DDLB_TPU_TRACE", str(blocker / "sub"))
    with telemetry.span("survives", cat="x"):
        pass
    telemetry.log("still logs fine")
    assert telemetry.get_tracer() is None
    out = capsys.readouterr().out
    assert "tracing disabled" in out
    # warned once, not per span
    assert out.count("tracing disabled") == 1
    assert "still logs fine" in out


def test_corrupt_shard_lines_are_skipped(trace_dir):
    with telemetry.span("good", cat="x"):
        pass
    shard = next(trace_dir.glob("trace-*.jsonl"))
    with open(shard, "a") as f:
        f.write("{truncated-by-a-kill\n")
    events = telemetry.read_events(str(trace_dir))
    assert any(e["name"] == "good" for e in events)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_metrics_scope_counters_and_gauges():
    with telemetry.metrics_scope() as outer:
        telemetry.record("c", 1.0)
        with telemetry.metrics_scope() as inner:
            telemetry.record("c", 2.0)
            telemetry.record_max("g", 5.0)
            telemetry.record_max("g", 3.0)  # lower: gauge keeps the max
        telemetry.record("c", 0.5)
    assert inner.snapshot() == {"c": 2.0, "g": 5.0}
    assert outer.snapshot()["c"] == pytest.approx(3.5)  # nesting sums up
    assert outer.snapshot()["g"] == 5.0


def test_metrics_row_fields_defaults_and_types():
    with telemetry.metrics_scope() as scope:
        telemetry.record("barrier_wait_s", 0.125)
        telemetry.record_max("hbm_high_water_bytes", 12345.0)
    fields = scope.row_fields()
    assert set(fields) == set(telemetry.ROW_METRIC_DEFAULTS)
    assert fields["barrier_wait_s"] == pytest.approx(0.125)
    assert fields["hbm_high_water_bytes"] == 12345
    assert isinstance(fields["hbm_high_water_bytes"], int)
    assert fields["loop_overhead_s"] == 0.0  # never recorded -> default


def test_metrics_global_registry_receives_all_threads():
    import threading

    telemetry.record("global_probe", 1.0)

    def _bg():
        telemetry.record("global_probe", 2.0)

    t = threading.Thread(target=_bg)
    t.start()
    t.join()
    assert telemetry.global_snapshot()["global_probe"] >= 3.0


def test_metrics_scopes_do_not_leak_across_pool_threads():
    """The pool-worker layout (ISSUE 6 satellite): a long-lived child
    runs row N's metrics scope on its dispatch thread WHILE the
    compile-ahead scheduler prefetch-compiles row N+1 on a background
    thread — whatever the background thread records (its own scope or
    scopeless) must never land in the row's scope, and consecutive row
    scopes on the same thread must start empty (a reused worker runs
    many rows per process)."""
    import threading

    start = threading.Barrier(2, timeout=30)
    row_done = threading.Event()

    def _prefetch_thread():
        start.wait()  # guaranteed concurrent with the row scope below
        with telemetry.metrics_scope() as prefetch_scope:
            for _ in range(50):
                telemetry.record("barrier_wait_s", 1.0)
                telemetry.record_max("hbm_high_water_bytes", 999.0)
        telemetry.record("barrier_wait_s", 7.0)  # scopeless recording
        assert prefetch_scope.snapshot()["barrier_wait_s"] == 50.0
        row_done.wait(timeout=30)

    t = threading.Thread(target=_prefetch_thread)
    t.start()
    with telemetry.metrics_scope() as row1:
        start.wait()
        telemetry.record("barrier_wait_s", 0.25)
    row_done.set()
    t.join(timeout=30)
    assert not t.is_alive()
    # the row's scope saw ONLY the row thread's recording — none of the
    # background thread's 57.0 worth of counts, no gauge bleed
    assert row1.snapshot() == {"barrier_wait_s": 0.25}
    # and the NEXT row on this thread starts from zero
    with telemetry.metrics_scope() as row2:
        pass
    assert row2.snapshot() == {}
    assert row2.row_fields()["barrier_wait_s"] == 0.0


# ---------------------------------------------------------------------------
# runner rows carry the metric columns (acceptance criterion)
# ---------------------------------------------------------------------------


def _worker_config(**over):
    cfg = {
        "primitive": "tp_columnwise",
        "impl_id": "compute_only_0",
        "base_implementation": "compute_only",
        "options": {"size": "unsharded"},
        "m": 64, "n": 64, "k": 64,
        "num_iterations": 2,
        "num_warmups": 1,
        "validate": False,
    }
    cfg.update(over)
    return cfg


def test_runner_rows_carry_metric_columns():
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(_worker_config())
    for key in REQUIRED_ROW_KEYS:
        assert key in row, f"row missing {key}"
    assert row["barrier_wait_s"] >= 0.0
    assert row["error"] == ""


def test_device_loop_rows_record_loop_overhead():
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(_worker_config(
        time_measurement_backend="device_loop",
        num_iterations=4,
        device_loop_windows=2,
        device_loop_min_window_ms=0.0,
    ))
    assert row["error"] == ""
    assert np.isfinite(row["loop_overhead_s"])
    assert row["loop_overhead_s"] >= 0.0


def test_error_rows_carry_metric_columns_too():
    """The CSV header is fixed by the first row written: crashed rows
    must carry the same metric columns (at defaults)."""
    from ddlb_tpu.benchmark import make_result_row

    row = make_result_row(
        _worker_config(),
        times_ms=np.array([float("nan")]),
        flop_count=float("nan"),
        option_repr="-",
        valid=False,
        error="WorkerDied: test",
        world_size=-1,
        num_processes=1,
        platform="unknown",
    )
    for key in REQUIRED_ROW_KEYS:
        assert row[key] == telemetry.ROW_METRIC_DEFAULTS[key]


def test_collective_rows_record_wire_bytes():
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(_worker_config(
        primitive="collectives",
        impl_id="jax_spmd_0",
        base_implementation="jax_spmd",
        options={"op": "all_gather"},
        m=64, n=8, k=64,
    ))
    if row["error"]:
        pytest.skip(f"collective impl unavailable here: {row['error']}")
    assert row["collective_bytes"] > 0.0


# ---------------------------------------------------------------------------
# worker spans land in the trace with the phase categories the report
# aggregates (compile / timing / barrier / validate)
# ---------------------------------------------------------------------------


def test_worker_emits_phase_spans(trace_dir):
    from ddlb_tpu.benchmark import benchmark_worker

    row = benchmark_worker(_worker_config(validate=True))
    assert row["error"] == ""
    cats = {e.get("cat") for e in _span_events(trace_dir)}
    for needed in ("setup", "warmup", "timing", "barrier", "validate", "row"):
        assert needed in cats, f"missing phase category {needed} in {cats}"


# ---------------------------------------------------------------------------
# structured logger
# ---------------------------------------------------------------------------


def test_log_is_rank_tagged_and_forwardable(capsys):
    telemetry.log("hello world", key="v")
    out = capsys.readouterr().out
    # hw_common._forward_diagnostics surfaces child lines by this exact
    # prefix — the rank tag must not break it
    assert out.startswith("[ddlb_tpu]")
    assert "[p0]" in out
    assert "hello world" in out and "key=v" in out


def test_log_multiline_prefixes_every_line(capsys):
    telemetry.log("line1\nline2")
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    assert all(ln.startswith("[ddlb_tpu][p0]") for ln in lines)


def test_warn_level_prefix(capsys):
    telemetry.warn("something odd")
    assert "WARNING: something odd" in capsys.readouterr().out


def test_log_mirrors_into_trace(trace_dir, capsys):
    telemetry.log("traced line", field=3)
    events = telemetry.read_events(str(trace_dir))
    logs = [e for e in events if e.get("cat") == "log"]
    assert logs and logs[-1]["args"]["message"] == "traced line"


def test_log_reserved_field_names_do_not_crash(trace_dir, capsys):
    """Caller-chosen field names colliding with the trace event's own
    keys must never turn a diagnostic into a TypeError."""
    telemetry.log("collide", name="x", cat="y", message="z", level="w")
    out = capsys.readouterr().out
    assert "collide" in out and "name=x" in out
    logs = [
        e for e in telemetry.read_events(str(trace_dir))
        if e.get("cat") == "log"
    ]
    assert logs[-1]["args"]["field_name"] == "x"
    assert logs[-1]["args"]["message"] == "collide"
