"""Sphinx configuration for ddlb-tpu (reference skeleton: docs/source/conf.py:1-25)."""

project = "ddlb-tpu"
copyright = "2026, ddlb-tpu contributors"
author = "ddlb-tpu contributors"
release = "0.1.0"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]

templates_path = ["_templates"]
exclude_patterns = []

html_theme = "alabaster"
