#!/usr/bin/env python
"""Chaos battery for the distributed world: rank-targeted faults under
the supervised launcher.

``scripts/chaos_sweep.py`` proves the SINGLE-host parent<->child surface
self-heals; this battery proves the MULTI-PROCESS world does. It drives
a 2-process x 2-device CPU-sim world (a real ``jax.distributed``
rendezvous, cross-process collectives) through three rank-targeted
fault scenarios, each seeded at the ``runtime.barrier`` collective on
**rank 1 only** (the fault plan's ``ranks:`` selector) with
``fail_attempts: 1`` so the supervised relaunch clears it (the
``DDLB_TPU_WORLD_ATTEMPT`` floor):

- ``hang``  — rank 1 wedges mid-collective; rank 0 blocks in the psum
  forever. The watchdog's silence deadline must fire (beats stop
  world-wide), the coordinated abort must tear the world down, and the
  flight recorder must name rank 1 — beat ages CANNOT (every rank goes
  silent together once the world wedges; only the sequence join knows
  who never arrived).
- ``exit``  — rank 1 dies abruptly (``os._exit``); asymmetric-death
  detection, no silence wait.
- ``kill``  — rank 1 SIGKILLed (the OOM signature); the negative
  returncode must be mapped and named, never summarized as ``-9``.

Per scenario the battery asserts: detection within the silence
deadline, ``flight_report`` attribution (lagging rank == 1, divergence
site == ``runtime.barrier``), a successful world relaunch
(``attempts.json``: attempt 0 failed transient, attempt 1 ok), and a
complete CSV — every sweep row measured and valid, zero rows lost.
Exit code 0 iff every assertion holds; this script is the executable
acceptance test for ISSUE 8 (log banked at
``docs/chaos_launch_demo.log``; ``make chaos-launch`` runs it).

Usage: python scripts/chaos_launch.py [--seed 0] [--silence-timeout 25]
           [--keep DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROCESSES = 2
DEVICES_PER_PROCESS = 2
M, N, K = 64, 32, 32  # tiny: the battery tests supervision, not speed

#: slack on top of the silence deadline for detection-latency asserts
#: (poll slice + SIGTERM grace + beat-file staleness)
DETECTION_SLACK_S = 15.0


def build_plan(kind: str, seed: int) -> dict:
    """One rank-targeted rule: rank 1 faults at the barrier collective
    on the first world attempt; the relaunched world runs clean."""
    rule = {
        "site": "runtime.barrier",
        "kind": kind,
        "ranks": [1],
        "fail_attempts": 1,
    }
    if kind == "hang":
        rule["duration_s"] = 600.0
    return {"seed": seed, "rules": [rule]}


def child_command(csv: str) -> list:
    """The world's workload: a 2-row tp_columnwise sweep through the
    real benchmark CLI (both rows must survive the relaunch for the
    zero-rows-lost assertion)."""
    return [
        sys.executable, "-m", "ddlb_tpu.cli.benchmark",
        "--primitive", "tp_columnwise",
        "--impl", "jax_spmd", "--impl", "xla_gspmd",
        "-m", str(M), "-n", str(N), "-k", str(K),
        "--dtype", "float32",
        "--num-iterations", "2", "--num-warmups", "1",
        "--csv", csv,
    ]


def run_scenario(
    kind: str, seed: int, silence_timeout: float, base_dir: str,
    failures: list,
) -> None:
    """One fault scenario end to end; appends failed assertions."""
    from ddlb_tpu.cli.launch import launch_supervised
    from ddlb_tpu.faults import flightrec

    def check(ok, what):
        print(f"  {'PASS' if ok else 'FAIL'}  [{kind}] {what}", flush=True)
        if not ok:
            failures.append(f"[{kind}] {what}")

    run_dir = os.path.join(base_dir, f"scenario-{kind}")
    csv = os.path.join(run_dir, "rows.csv")
    os.makedirs(run_dir, exist_ok=True)
    os.environ["DDLB_TPU_FAULT_PLAN"] = json.dumps(build_plan(kind, seed))

    print(f"\n==== scenario [{kind}]: rank 1 faults at runtime.barrier "
          f"====", flush=True)
    t0 = time.monotonic()
    rc = launch_supervised(
        child_command(csv),
        processes=PROCESSES,
        devices_per_process=DEVICES_PER_PROCESS,
        silence_timeout=silence_timeout,
        world_retries=2,
        relaunch_backoff_s=0.2,
        run_dir=run_dir,
    )
    elapsed = time.monotonic() - t0
    print(f"\n== [{kind}] assertions ({elapsed:.1f}s) ==", flush=True)

    check(rc == 0, "supervised launch recovered (exit code 0)")

    with open(os.path.join(run_dir, "attempts.json")) as f:
        attempts = json.load(f)
    check(
        len(attempts) == 2,
        f"exactly one relaunch: {len(attempts)} attempts recorded",
    )
    first, last = attempts[0], attempts[-1]
    check(
        first["outcome"] == "failed"
        and first["error_class"] == "transient",
        f"attempt 0 failed and classified transient "
        f"({first['error_class']}: {first['error'][:80]})",
    )
    check(
        first.get("culprit_rank") == 1,
        f"culprit rank named: {first.get('culprit_rank')} (want 1)",
    )
    if kind == "hang":
        age = float(first.get("silence_age_s") or 0.0)
        check(
            silence_timeout <= age <= silence_timeout + DETECTION_SLACK_S,
            f"hang detected within the silence deadline: "
            f"silence age {age:.1f}s vs deadline {silence_timeout}s "
            f"(+{DETECTION_SLACK_S}s slack)",
        )
    else:
        check(
            "WorkerDied: rank 1" in first["error"],
            f"asymmetric rank death detected: {first['error'][:80]}",
        )
    if kind == "kill":
        check(
            "SIGKILL" in first["error"] and "-9" not in first["error"],
            f"signal death named, not numbered: {first['error'][:80]}",
        )
    check(last["outcome"] == "ok", "relaunched world completed cleanly")

    report = flightrec.analyze_run(
        os.path.join(run_dir, "attempt-0"), expected_ranks=PROCESSES
    )
    print(f"  flight verdict: {report.get('headline')}", flush=True)
    check(
        report.get("lagging_ranks") == [1],
        f"flight report names rank 1 as lagging: "
        f"{report.get('lagging_ranks')}",
    )
    check(
        report.get("divergence_site") == "runtime.barrier",
        f"divergence site attributed to the barrier collective: "
        f"{report.get('divergence_site')!r}",
    )

    import pandas as pd

    # last write wins per config: a failed attempt may have recorded
    # error rows (a gloo peer ERRORS through a dead-peer collective)
    # before the abort — the relaunch's appended rows supersede them,
    # and "zero rows lost" means every sweep config ends with a final
    # measured, valid row
    rows = pd.read_csv(csv).groupby("implementation").last().reset_index()
    check(
        len(rows) == 2 and set(rows["implementation"]) == {
            "jax_spmd_0", "xla_gspmd_0"
        },
        f"zero rows lost: {len(rows)}/2 sweep configs have a final row",
    )
    check(
        bool(rows["valid"].all()),
        "every config's final row measured valid after the relaunch",
    )
    check(
        set(rows["num_processes"]) == {PROCESSES}
        and set(rows["world_size"]) == {
            PROCESSES * DEVICES_PER_PROCESS
        },
        "rows measured on the joint multi-process world (4 devices, "
        "2 processes)",
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--silence-timeout", type=float, default=25.0,
        help="watchdog silence budget (must exceed the CPU-sim world's "
        "longest legitimate beat gap: jax import + distributed init)",
    )
    parser.add_argument(
        "--keep", default=None, metavar="DIR",
        help="keep run dirs under DIR instead of a deleted temp dir",
    )
    args = parser.parse_args(argv)

    base_dir = args.keep or tempfile.mkdtemp(prefix="ddlb_chaos_launch_")
    os.makedirs(base_dir, exist_ok=True)
    failures: list = []
    print(
        f"chaos_launch: {PROCESSES} ranks x {DEVICES_PER_PROCESS} devices "
        f"(CPU sim), seed={args.seed}, "
        f"silence_timeout={args.silence_timeout}s, run dirs {base_dir}",
        flush=True,
    )
    try:
        for kind in ("hang", "exit", "kill"):
            run_scenario(
                kind, args.seed, args.silence_timeout, base_dir, failures
            )
    finally:
        os.environ.pop("DDLB_TPU_FAULT_PLAN", None)
        if not args.keep:
            shutil.rmtree(base_dir, ignore_errors=True)

    if failures:
        print(f"\nchaos_launch: {len(failures)} assertion(s) FAILED",
              flush=True)
        return 1
    print(
        "\nchaos_launch: every rank-targeted fault detected, attributed, "
        "and healed by a world relaunch with zero rows lost — OK",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
