#!/usr/bin/env python
"""Prior-guided autotuner acceptance demo: search, prune, bank, consult.

The executable acceptance evidence for ISSUE 20, banked at
``docs/tune_demo.log``. Everything runs on the 2-device CPU sim, so it
is reproducible anywhere:

1. **Search**: four prior-guided searches (``tuner.driver.search``)
   over real pruned spaces — tp_columnwise/pallas GEMM tiles,
   tp_columnwise+dp_allreduce/overlap ``chunk_count``, and
   dp_allreduce/jax_spmd_hier ``composition`` — every trial banked to
   the observatory store under ``kind="tune"``. The demo passes
   ``prior_margin=1.1`` (the API default stays 1.5) so the transcript
   shows real pruning at CPU-sim prior spreads; the checks are that
   >= 50% of the combined feasible space is pruned before any compile,
   and that every search's banked winner is never worse than the
   registered default (the default is always measured, prior-exempt).
2. **Spearman**: prior-vs-measured rank agreement per search — the
   honesty number for the pruning (reported, not gated: a CPU host
   cannot promise monotone tile timings).
3. **Determinism**: a second forced pass against the same history bank
   reuses every banked trial (zero re-measures) and writes a
   byte-identical table file.
4. **Consult**: with ``DDLB_TPU_TUNING`` pointing at the banked table,
   re-running the same searches short-circuits on table hits with ZERO
   search trials, and a real sweep row (PrimitiveBenchmarkRunner)
   carries the winner's ``tuned`` / ``tuning_version`` / ``prior_rank``
   stamps; ``perf_report.py --tuned`` renders the table against its
   own search history.

Usage: python scripts/tune_demo.py [--log PATH] [--no-log]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# simulated mesh, set before anything touches JAX
os.environ.setdefault("DDLB_TPU_SIM_DEVICES", "2")


class Tee:
    """Print + capture, so the transcript lands in docs/ verbatim."""

    def __init__(self):
        self.lines = []

    def __call__(self, text=""):
        print(text, flush=True)
        self.lines.append(str(text))


def search_specs():
    """The four demo searches: tiles, two chunk depths, composition.
    Shapes satisfy every divisibility rule at d=2."""
    from ddlb_tpu.tuner.space import SearchSpec

    return [
        SearchSpec(
            "tp_columnwise", "pallas", 1024, 1024, 512,
            num_partitions=2, chip="cpu-sim",
        ),
        SearchSpec(
            "tp_columnwise", "overlap", 1024, 1024, 512,
            num_partitions=2, chip="cpu-sim",
            base_options=(("algorithm", "chunked"),),
        ),
        SearchSpec(
            "dp_allreduce", "overlap", 1024, 1024, 512,
            num_partitions=2, chip="cpu-sim",
            base_options=(("algorithm", "chunked"),),
        ),
        SearchSpec(
            "dp_allreduce", "jax_spmd_hier", 1024, 1024, 512,
            num_partitions=2, chip="cpu-sim",
        ),
    ]


def run_pass(specs, history_dir, say, *, force):
    from ddlb_tpu.tuner import driver

    results = []
    for spec in specs:
        result = driver.search(
            spec, prior_margin=1.1, patience=3,
            history_dir=history_dir, force=force,
            num_iterations=3, num_warmups=1,
        )
        results.append(result)
        if result.table_hit:
            say(
                f"  {spec.family}/{spec.impl}: TABLE HIT "
                f"(knobs {json.dumps(result.entry.knobs, sort_keys=True)}, "
                f"0 trials)"
            )
            continue
        fresh = sum(1 for t in result.trials if not t.from_bank)
        rho = result.spearman()
        say(
            f"  {spec.family}/{spec.impl} {spec.m}x{spec.n}x{spec.k}: "
            f"{result.candidates} candidates, {len(result.rejected)} "
            f"infeasible, {len(result.pruned)} pruned, "
            f"{len(result.trials)} trials ({fresh} fresh"
            f"{', early-stop' if result.early_stopped else ''})"
        )
        if result.entry is not None:
            speedup = result.default_ms / result.entry.measured_ms
            say(
                f"    winner {json.dumps(result.entry.knobs, sort_keys=True)}"
                f" @ {result.entry.measured_ms:.3f} ms "
                f"(default {result.default_ms:.3f} ms, {speedup:.2f}x, "
                f"prior rank {result.entry.prior_rank}, "
                f"Spearman {rho:+.2f})"
            )
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--log", default=os.path.join(REPO, "docs", "tune_demo.log"),
        help="transcript destination (default docs/tune_demo.log)",
    )
    parser.add_argument(
        "--no-log", action="store_true", help="stdout only, write no file"
    )
    args = parser.parse_args(argv)

    say = Tee()
    failures = []

    def check(ok, what):
        say(f"  {'PASS' if ok else 'FAIL'}  {what}")
        if not ok:
            failures.append(what)

    say("==== prior-guided autotuner demo ====")
    say()

    workdir = tempfile.mkdtemp(prefix="tune_demo_")
    history_dir = os.path.join(workdir, "history")
    table_path = os.path.join(workdir, "tuning.json")
    os.environ.pop("DDLB_TPU_TUNING", None)
    os.environ.pop("DDLB_TPU_CALIB", None)

    from ddlb_tpu.observatory import store
    from ddlb_tpu.tuner import driver

    specs = search_specs()

    # -- 1. the search pass: propose -> prune -> measure -> bank ------------
    say("-- search: four prior-guided searches (margin 1.1) --")
    results = run_pass(specs, history_dir, say, force=True)
    candidates = sum(r.candidates for r in results)
    pruned = sum(len(r.pruned) for r in results)
    say(
        f"  combined: {pruned}/{candidates} feasible candidates pruned "
        f"before any compile ({pruned / max(1, candidates):.0%})"
    )
    check(
        pruned / max(1, candidates) >= 0.5,
        "priors pruned >= 50% of the combined feasible space",
    )
    check(
        all(r.entry is not None for r in results),
        "every search banked a winner",
    )
    check(
        all(
            r.entry.measured_ms <= r.default_ms * (1 + 1e-9)
            for r in results
        ),
        "tuned winner never worse than the registered default "
        "(the default is always measured, prior-exempt)",
    )
    tune_records = list(store.iter_history(history_dir, kind="tune"))
    check(
        len(tune_records) == sum(len(r.trials) for r in results),
        f"all {len(tune_records)} trials banked under kind=\"tune\"",
    )
    say()

    # -- 2. bank the winners ------------------------------------------------
    say("-- bank: winners -> versioned (cpu-sim, host_clock) table --")
    table = driver.bank_winners(
        results, table_path, chip="cpu-sim", backend="host_clock"
    )
    check(table is not None, f"table written to {table_path}")
    if table is None:
        say(f"DEMO FAILED: {failures}")
        return 1
    say(f"  table {table.version} ({len(table.entries)} entries)")
    say()

    # -- 3. determinism: forced re-run against the same bank ----------------
    say("-- determinism: forced re-run reuses the banked trials --")
    rerun = run_pass(specs, history_dir, say, force=True)
    check(
        all(t.from_bank for r in rerun for t in r.trials),
        "re-run measured ZERO fresh trials (banked reuse)",
    )
    rerun_path = os.path.join(workdir, "tuning_rerun.json")
    driver.bank_winners(
        rerun, rerun_path, chip="cpu-sim", backend="host_clock"
    )
    with open(table_path, "rb") as fa, open(rerun_path, "rb") as fb:
        identical = fa.read() == fb.read()
    check(identical, "re-banked table is byte-identical (same fingerprint)")
    say()

    # -- 4. consult: table hits, stamped sweep rows, the report -------------
    say("-- consult: the runners read the table by default --")
    os.environ["DDLB_TPU_TUNING"] = table_path
    hits = run_pass(specs, history_dir, say, force=False)
    check(
        all(r.table_hit and not r.trials for r in hits),
        "table-primed searches short-circuit with ZERO search trials",
    )

    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    runner = PrimitiveBenchmarkRunner(
        "dp_allreduce", m=1024, n=1024, k=512,
        implementations={
            "overlap_0": {"implementation": "overlap", "algorithm": "chunked"}
        },
        dtype="float32", num_iterations=3, num_warmups=1,
        validate=True, isolation="none", progress=False,
        output_csv=os.path.join(workdir, "tuned_sweep.csv"),
        barrier_at_each_iteration=False,
    )
    df = runner.run()
    row = df.iloc[0].to_dict()
    winner = next(
        r.entry for r in results
        if (r.spec.family, r.spec.impl) == ("dp_allreduce", "overlap")
    )
    say(
        f"  sweep row: tuned={row.get('tuned')} "
        f"tuning_version={row.get('tuning_version')} "
        f"prior_rank={row.get('prior_rank')} "
        f"(winner knobs {json.dumps(winner.knobs, sort_keys=True)})"
    )
    check(
        bool(row.get("tuned"))
        and str(row.get("tuning_version")) == table.version,
        "a tuned sweep row carries tuned/tuning_version/prior_rank "
        "stamps at the table's version",
    )
    check(
        str(row.get("error") or "").strip() == "",
        "the tuned sweep row measured cleanly",
    )

    out = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
            "--tuned", "--table", table_path, "--history", history_dir,
            "--json",
        ],
        capture_output=True, text=True,
    )
    report_entries = 0
    try:
        doc = json.loads(out.stdout)
        report_entries = sum(
            len(rows) for rows in (doc.get("families") or {}).values()
        )
    except ValueError:
        pass
    check(
        out.returncode == 0 and report_entries == len(table.entries),
        f"perf_report --tuned renders all {len(table.entries)} banked "
        f"winners against the search history",
    )
    os.environ.pop("DDLB_TPU_TUNING", None)

    say()
    if failures:
        say(f"DEMO FAILED: {len(failures)} check(s): {failures}")
    else:
        say("DEMO PASSED: every check green")
    if not args.no_log:
        with open(args.log, "w") as f:
            f.write("\n".join(say.lines) + "\n")
        print(f"[transcript -> {args.log}]")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
