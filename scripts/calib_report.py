#!/usr/bin/env python
"""Calibration report: fit quality, residual distributions, drift gate.

The calibration observatory's CLI (ISSUE 17). Reads the history bank
(``DDLB_TPU_HISTORY`` or ``--history DIR``) and the calibration table
(``DDLB_TPU_CALIB`` or ``--calib PATH``) and reports:

- **fit quality** per ``(chip, backend)`` group: the fitted constants
  (per-row dispatch, per-step software overhead, per-hop link-class
  latencies), how many rows/keys backed the fit, and the residual MAD;
- **per-key residual distributions** over banked rows that carry a
  finite ``cal_residual_frac`` stamp, worst keys first (``--top``);
- **before/after prediction error**: the median relative error of the
  analytical lower bound vs the calibrated prediction over every
  fit-eligible banked row (``calib.predict_row`` scores rows banked
  before stamping existed);
- **the drift gate**: ``regress.detect_calibration`` on the latest
  banked run (or ``--run ID``) against its same-``cal_version``
  history — the direction-aware median+MAD gate that fires when
  measured rows drift slower than the model that priced them.

``--fit`` refits the table from the bank first and writes it to the
``--calib`` path (atomic), then reports against the fresh fit — the
one-command "re-anchor the model" loop.

Exit code: 0 clean, 1 when drift findings fired, 2 usage.

Usage: python scripts/calib_report.py [--history DIR] [--calib PATH]
           [--fit] [--run ID] [--json] [--top N]
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddlb_tpu.observatory import calibrate, regress, store  # noqa: E402
from ddlb_tpu.perfmodel import calib  # noqa: E402


def _finite(value):
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if math.isfinite(value) else None


def _median(values):
    values = sorted(values)
    if not values:
        return float("nan")
    mid = len(values) // 2
    if len(values) % 2:
        return values[mid]
    return 0.5 * (values[mid - 1] + values[mid])


def residual_distributions(records):
    """Per-key stats over banked rows stamped with a finite residual."""
    per_key = {}
    for record in records:
        if record.get("kind") != "row":
            continue
        row = record.get("row") or {}
        frac = _finite(row.get("cal_residual_frac"))
        if frac is None:
            continue
        key = record.get("key") or store.row_key(row)
        per_key.setdefault(key, {"row": row, "fracs": []})["fracs"].append(
            frac
        )
    stats = []
    for key, bucket in per_key.items():
        fracs = bucket["fracs"]
        med = _median(fracs)
        stats.append(
            {
                "key": key,
                "implementation": bucket["row"].get("implementation"),
                "primitive": bucket["row"].get("primitive"),
                "m": bucket["row"].get("m"),
                "n": bucket["row"].get("n"),
                "k": bucket["row"].get("k"),
                "rows": len(fracs),
                "median_frac": med,
                "worst_frac": max(fracs, key=abs),
            }
        )
    stats.sort(key=lambda s: -abs(s["median_frac"]))
    return stats


def before_after(records, table):
    """Median relative prediction error, analytical vs calibrated,
    over every fit-eligible banked row (stamped or not)."""
    before, after = [], []
    for record in records:
        if record.get("kind") != "row":
            continue
        row = record.get("row") or {}
        features = calib.row_features(row)
        if features is None:
            continue
        group = table.group(
            str(row.get("chip") or ""),
            str(row.get("time_measurement_backend") or "") or None,
        )
        if group is None:
            continue
        measured = float(features["measured_s"])
        predicted_cal = calib.predict_row(row, group)
        if predicted_cal is None or measured <= 0.0:
            continue
        before.append(
            abs(measured - float(features["predicted_s"])) / measured
        )
        after.append(abs(measured - predicted_cal) / measured)
    return {
        "rows": len(before),
        "median_rel_err_analytical": _median(before),
        "median_rel_err_calibrated": _median(after),
    }


def latest_run(records, run=None):
    """(current_rows, run_label, exclude_run) — latest banked run."""
    run_ids = [r.get("run_id") for r in records if r.get("kind") == "row"]
    run = run or (run_ids[-1] if run_ids else None)
    if run is None:
        return [], "(no runs banked)", None
    rows = [
        r["row"]
        for r in records
        if r.get("run_id") == run and r.get("kind") == "row"
    ]
    return rows, f"run {run}", run


def build_report(history_dir, calib_path, args):
    records = store.load_history(history_dir)
    table = None
    fitted = False
    if args.get("fit"):
        table = calibrate.calibrate_history(directory=history_dir)
        if table is not None and calib_path:
            calibrate.write_table(table, calib_path)
            fitted = True
    if table is None and calib_path:
        table = calib.load_table(calib_path)
    current, label, exclude = latest_run(records, args.get("run"))
    findings = (
        regress.detect_calibration(current, records, exclude_run=exclude)
        if current
        else []
    )
    report = {
        "history_dir": os.path.abspath(history_dir) if history_dir else "",
        "history_records": len(records),
        "calib_path": os.path.abspath(calib_path) if calib_path else "",
        "fitted": fitted,
        "table": table.to_json() if table is not None else None,
        "residuals": residual_distributions(records),
        "before_after": before_after(records, table) if table else None,
        "current": label,
        "current_rows": len(current),
        "drift_findings": findings,
    }
    return report


def print_report(report, top_n):
    print(f"calibration report — history {report['history_dir'] or '(unset)'}")
    table = report["table"]
    if table is None:
        print(
            "  no calibration table — pass --calib PATH (or set "
            "DDLB_TPU_CALIB), or refit from the bank with --fit"
        )
    else:
        print(
            f"  table {table['version']}"
            + (" (refit this run)" if report["fitted"] else "")
            + (f" @ {report['calib_path']}" if report["calib_path"] else "")
        )
        for key in sorted(table["groups"]):
            g = table["groups"][key]
            hops = ", ".join(
                f"{cls}={g['hop_s'][cls] * 1e6:.2f}us"
                for cls in sorted(g["hop_s"])
            )
            print(
                f"    {key:<24} dispatch={g['dispatch_s'] * 1e6:.2f}us "
                f"step={g['step_s'] * 1e6:.2f}us hop[{hops}] "
                f"({g['rows']} rows / {g['keys']} keys, "
                f"residual MAD {g['residual_mad_s'] * 1e6:.2f}us)"
            )
    ba = report.get("before_after")
    if ba and ba["rows"]:
        print(
            f"  prediction error over {ba['rows']} banked row(s): "
            f"analytical {ba['median_rel_err_analytical'] * 100:.1f}% -> "
            f"calibrated {ba['median_rel_err_calibrated'] * 100:.1f}% "
            f"(median relative)"
        )
    residuals = report["residuals"]
    if residuals:
        print(
            f"\n  stamped residuals, {len(residuals)} key(s), "
            f"worst |median| first:"
        )
        for s in residuals[:top_n]:
            shape = f"{s.get('m')}x{s.get('n')}x{s.get('k')}"
            print(
                f"    {str(s['implementation'])[:22]:<22} {shape:<13} "
                f"median {s['median_frac'] * 100:+6.1f}%  "
                f"worst {s['worst_frac'] * 100:+6.1f}%  ({s['rows']} rows)"
            )
        if len(residuals) > top_n:
            print(f"    ... and {len(residuals) - top_n} more (--top)")
    else:
        print("  no stamped residuals banked yet (runs need DDLB_TPU_CALIB)")
    findings = report["drift_findings"]
    print(f"\n  drift gate — current = {report['current']}:")
    if not findings:
        print("    no calibration drift detected")
        return
    print(f"    {len(findings)} drift finding(s), worst first:")
    for i, f in enumerate(findings[:top_n], 1):
        shape = f"{f.get('m')}x{f.get('n')}x{f.get('k')}"
        print(
            f"    {i:>2} {str(f.get('implementation'))[:22]:<22} "
            f"{shape:<13} residual {f['measured_ms']:+.3f} vs baseline "
            f"{f['baseline_ms']:+.3f} (z={f.get('z', float('nan')):.1f}, "
            f"table {f.get('cal_version')})"
        )


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    fit = "--fit" in argv
    argv = [a for a in argv if a != "--fit"]

    def _opt(flag, default=None):
        if flag in argv:
            i = argv.index(flag)
            if i + 1 >= len(argv):
                raise SystemExit(f"calib_report: {flag} needs a value")
            value = argv[i + 1]
            del argv[i: i + 2]
            return value
        return default

    args = {"run": _opt("--run"), "fit": fit}
    top_n = int(_opt("--top", "10"))
    history_dir = _opt("--history") or os.environ.get(
        "DDLB_TPU_HISTORY", ""
    ).strip()
    calib_path = _opt("--calib") or os.environ.get(
        "DDLB_TPU_CALIB", ""
    ).strip()
    if argv:
        print(f"calib_report: unknown argument(s): {argv}")
        return 2
    if not history_dir:
        print(
            "calib_report: no history bank — pass --history DIR or set "
            "DDLB_TPU_HISTORY (runs bank automatically when it is set)"
        )
        return 2
    report = build_report(history_dir, calib_path, args)
    if as_json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print_report(report, top_n)
    return 1 if report["drift_findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
