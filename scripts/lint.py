#!/usr/bin/env python
"""Undefined-name lint with zero third-party dependencies.

``make lint`` prefers pyflakes (dev extra); on a checkout without it this
checker is the floor instead of a bare syntax check, so an undefined name
fails the build either way (VERDICT r3 missing #4 / next #8: ``make
lint`` must never silently degrade to ``compileall``).

Method: per file, collect every module-level binding (imports, assigns,
defs, classes) with ``ast``, then walk ``symtable`` scopes; a symbol
referenced as global that is neither a module binding, a builtin, nor a
module dunder is reported. Files with wildcard imports skip the check
(their global namespace is unknowable statically). This is deliberately
a subset of pyflakes — no unused-import or redefinition warnings — and
conservative: scope kinds symtable can't resolve are never reported.
"""

from __future__ import annotations

import ast
import builtins
import sys
import symtable
from pathlib import Path

MODULE_DUNDERS = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__builtins__", "__loader__", "__path__", "__annotations__",
    "__all__", "__debug__", "__class__",
}


def _module_bindings(tree: ast.Module) -> set:
    """Every name the module's global namespace can bind at runtime."""
    names: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                names.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            names.add(node.id)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            names.update(node.names)
        elif isinstance(node, (ast.MatchAs, ast.MatchStar)):
            if node.name:  # match-case capture patterns bind raw strings
                names.add(node.name)
        elif isinstance(node, ast.MatchMapping) and node.rest:
            names.add(node.rest)
        elif hasattr(ast, "TypeAlias") and isinstance(
            node, ast.TypeAlias
        ):  # PEP 695 `type X = ...`
            names.add(node.name.id)
    return names


def _has_star_import(tree: ast.Module) -> bool:
    return any(
        isinstance(n, ast.ImportFrom) and any(a.name == "*" for a in n.names)
        for n in ast.walk(tree)
    )


def _global_refs(table: symtable.SymbolTable, out: set) -> None:
    """Names referenced as globals anywhere in the scope tree: unassigned
    global references in nested scopes, plus module-scope references that
    nothing assigns or imports. Scope resolution is symtable's, so
    parameters, locals, closures and class scopes are never reported."""
    is_module = table.get_type() == "module"
    for sym in table.get_symbols():
        if not sym.is_referenced() or sym.is_imported():
            continue
        if is_module:
            if not sym.is_assigned():
                out.add(sym.get_name())
        elif sym.is_global() and not sym.is_assigned():
            out.add(sym.get_name())
    for child in table.get_children():
        _global_refs(child, out)


#: bandit-lite: call patterns that have no legitimate use in this
#: codebase (subprocess always runs argv lists here; nothing evals
#: strings or loads pickles). A new hit is either a bug or needs an
#: explicit entry in the allowlist below with a justification.
_FORBIDDEN_CALLS = {
    "eval": "eval() on a string",
    "exec": "exec() on a string",
}
_FORBIDDEN_ATTRS = {
    ("pickle", "load"): "pickle.load (arbitrary code on untrusted data)",
    ("pickle", "loads"): "pickle.loads (arbitrary code on untrusted data)",
    ("os", "system"): "os.system (shell injection; use subprocess lists)",
}


def _security_checks(path: Path, tree: ast.Module) -> list:
    """The dangerous-call subset of bandit that matters for a benchmark
    framework: string eval/exec, pickle deserialization, shell=True.
    (VERDICT r4 missing #4: the reference's .lintrunner battery includes
    bandit; this is the zero-dependency floor for its findings class.)"""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _FORBIDDEN_CALLS:
            out.append(
                f"{path}:{node.lineno}: security: "
                f"{_FORBIDDEN_CALLS[fn.id]}"
            )
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            why = _FORBIDDEN_ATTRS.get((fn.value.id, fn.attr))
            if why:
                out.append(f"{path}:{node.lineno}: security: {why}")
        for kw in node.keywords:
            if (
                kw.arg == "shell"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            ):
                out.append(
                    f"{path}:{node.lineno}: security: shell=True "
                    f"(use an argv list)"
                )
    return out


#: package subtrees exempt from the bare-print ban: the CLI is the
#: user-facing stdout surface (results tables ARE its output), and the
#: telemetry logger is the one place a print legitimately lives (it is
#: what everything else must call instead)
_PRINT_EXEMPT_DIRS = {"cli", "telemetry"}


def _print_checks(path: Path, tree: ast.Module) -> list:
    """Ban bare ``print(`` in package code (ISSUE 2 satellite): on a
    multi-process pod untagged prints interleave unattributably, and the
    capture pipelines substring-match free text. Package diagnostics go
    through ``ddlb_tpu.telemetry.log`` (rank-tagged, trace-mirrored);
    scripts/ and tests/ are exempt (they are single-process drivers whose
    stdout is the artifact)."""
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(
                f"{path}:{node.lineno}: print: bare print() in package "
                f"code — use ddlb_tpu.telemetry.log (rank-tagged, "
                f"machine-parseable)"
            )
    return out


def _swallow_checks(path: Path, tree: ast.Module) -> list:
    """Ban silent broad-exception swallows in package code (ISSUE 4
    satellite): an ``except Exception: pass`` (or bare ``except:``)
    whose body does nothing turns a real failure into an invisible one —
    exactly the class the fault-injection harness exists to provoke.
    Every handler must re-raise, return an error value, or log via
    telemetry (any non-pass body satisfies the check); narrow exception
    types (``OSError``, ``ValueError``) remain legitimate control
    flow."""

    def _names(node):
        if node is None:
            return ["<bare>"]
        elts = node.elts if isinstance(node, ast.Tuple) else [node]
        out = []
        for e in elts:
            if isinstance(e, ast.Name):
                out.append(e.id)
            elif isinstance(e, ast.Attribute):
                out.append(e.attr)
            else:
                out.append("?")
        return out

    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        silent = all(
            isinstance(stmt, ast.Pass)
            or (
                isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis
            )
            for stmt in node.body
        )
        names = _names(node.type)
        broad = node.type is None or any(
            n in ("Exception", "BaseException") for n in names
        )
        if silent and broad:
            problems.append(
                f"{path}:{node.lineno}: swallow: silent "
                f"'except {', '.join(names)}: pass' — re-raise, return "
                f"an error row, or log via ddlb_tpu.telemetry"
            )
    return problems


def _process_spawn_checks(path: Path, tree: ast.Module) -> list:
    """Ban direct multiprocessing ``Process`` construction in package
    code outside ``pool.py`` (ISSUE 5 satellite): the warm-worker pool
    is the one spawner for row/worker processes, so future row
    execution cannot silently regress to cold spawn-per-row (and every
    spawn inherits the pool's heartbeat channel, daemon flag, and
    queue-release discipline)."""
    if path.name == "pool.py":
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        named = (
            fn.attr
            if isinstance(fn, ast.Attribute)
            else fn.id
            if isinstance(fn, ast.Name)
            else None
        )
        if named == "Process":
            out.append(
                f"{path}:{node.lineno}: process: direct Process() "
                f"construction — worker processes must come from "
                f"ddlb_tpu/pool.py (WorkerPool), so row execution "
                f"cannot regress to cold spawn-per-row"
            )
    return out


def _docstring_checks(path: Path, tree: ast.Module) -> list:
    """pydocstyle-lite floor for the PACKAGE (not tests/scripts): every
    module needs a docstring, and every public class needs one UNLESS it
    is its module's only public class and the module docstring exists —
    the one-member-class-per-file pattern here carries the design prose
    at module level, and duplicating it on the class would be noise.
    Function-level coverage is a judgment call the full pydocstyle dev
    extra makes; this presence tier is the non-negotiable floor."""
    out = []
    module_doc = ast.get_docstring(tree)
    if not module_doc:
        out.append(f"{path}:1: docstring: module has no docstring")
    public_classes = [
        n
        for n in ast.walk(tree)
        if isinstance(n, ast.ClassDef) and not n.name.startswith("_")
    ]
    sole = len(public_classes) == 1 and bool(module_doc)
    for node in public_classes:
        if not ast.get_docstring(node) and not sole:
            out.append(
                f"{path}:{node.lineno}: docstring: public class "
                f"'{node.name}' has no docstring"
            )
    return out


def check_file(path: Path) -> list:
    src = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(src, filename=str(path))
        table = symtable.symtable(src, str(path), "exec")
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]
    extra = _security_checks(path, tree)
    if path.parts[:1] == ("ddlb_tpu",) or "/ddlb_tpu/" in str(path):
        extra += _docstring_checks(path, tree)
        extra += _swallow_checks(path, tree)
        extra += _process_spawn_checks(path, tree)
        if not (set(path.parts) & _PRINT_EXEMPT_DIRS):
            extra += _print_checks(path, tree)
    if _has_star_import(tree):
        return extra
    bound = _module_bindings(tree)
    known = bound | MODULE_DUNDERS | set(dir(builtins))
    refs: set = set()
    _global_refs(table, refs)
    # line numbers only for reporting (first Load of the name anywhere)
    lines = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            lines.setdefault(node.id, node.lineno)
    return extra + [
        f"{path}:{lines.get(name, 1)}: undefined name '{name}'"
        for name in sorted(refs - known)
    ]


def _cost_model_coverage() -> list:
    """Perfmodel invariant (ISSUE 3 satellite): every registered
    primitive family must resolve a cost model, so a newly added family
    can never ship rows with a silent ``predicted_s=None``. Both modules
    are JAX-free by design, so this import is safe from the lint tier;
    an import failure is itself a finding (the invariant would otherwise
    vanish with the import)."""
    repo = Path(__file__).resolve().parent.parent
    if str(repo) not in sys.path:
        sys.path.insert(0, str(repo))
    try:
        from ddlb_tpu.perfmodel.cost import FAMILY_COST_MODELS
        from ddlb_tpu.primitives.registry import ALLOWED_PRIMITIVES
    except Exception as exc:
        return [
            f"perfmodel: cost-model coverage check failed to import: "
            f"{type(exc).__name__}: {exc}"
        ]
    return [
        f"perfmodel: primitive family '{fam}' has no cost model in "
        f"ddlb_tpu/perfmodel/cost.py FAMILY_COST_MODELS (rows would "
        f"carry silent predicted_s defaults)"
        for fam in ALLOWED_PRIMITIVES
        if fam not in FAMILY_COST_MODELS
    ]


#: the runner-path files whose row-column writes the schema check scans:
#: the one row constructor + every site that amends rows after the fact
#: (repo-relative). A new runner path that writes columns must be added
#: here — and its columns to ddlb_tpu/schema.py.
_ROW_WRITER_FILES = (
    "ddlb_tpu/benchmark.py",
    "ddlb_tpu/pool.py",
    "ddlb_tpu/telemetry/metrics.py",
    "ddlb_tpu/observatory/attribution.py",
    "scripts/hw_common.py",
)


def _written_row_columns(tree: ast.Module) -> set:
    """Every row-column name a file writes, statically:

    - keys of the dict literal ``make_result_row`` returns (the one
      row constructor);
    - keys of module-level ``*_ROW_DEFAULTS`` / ``ROW_METRIC_DEFAULTS``
      dict literals (merged into every row);
    - every ``row["<name>"] = ...`` subscript assignment (the
      amend-after-build sites: pool reuse columns, hbm peak, bank key).
    """
    columns: set = set()

    def _dict_keys(node):
        return {
            key.value
            for key in getattr(node, "keys", [])
            if isinstance(key, ast.Constant) and isinstance(key.value, str)
        }

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "make_result_row":
            for ret in ast.walk(node):
                if isinstance(ret, ast.Return) and isinstance(
                    ret.value, ast.Dict
                ):
                    columns |= _dict_keys(ret.value)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            # one node can be BOTH cases at once (`row["x"] = {...}`):
            # check the defaults-dict names and the row subscripts
            # independently, never as an either/or
            if isinstance(node.value, ast.Dict):
                names = [t.id for t in targets if isinstance(t, ast.Name)]
                if any(
                    n.endswith("_ROW_DEFAULTS") or n == "ROW_METRIC_DEFAULTS"
                    for n in names
                ):
                    columns |= _dict_keys(node.value)
            for target in targets:
                if (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "row"
                    and isinstance(target.slice, ast.Constant)
                    and isinstance(target.slice.value, str)
                ):
                    columns.add(target.slice.value)
    return columns


def _row_schema_coverage() -> list:
    """Row-schema invariant (ISSUE 6 satellite): every column a runner
    path writes must appear in the ``ddlb_tpu/schema.py`` registry with
    a non-empty docstring — the column set was previously re-stated ad
    hoc in benchmark.py, pool.py, hw_common.py and tests, with nothing
    keeping the statements in agreement."""
    repo = Path(__file__).resolve().parent.parent
    if str(repo) not in sys.path:
        sys.path.insert(0, str(repo))
    try:
        from ddlb_tpu.schema import ROW_COLUMNS
    except Exception as exc:
        return [
            f"schema: row-column registry failed to import: "
            f"{type(exc).__name__}: {exc}"
        ]
    problems = []
    for rel in _ROW_WRITER_FILES:
        path = repo / rel
        if not path.exists():
            problems.append(f"schema: row-writer file {rel} is missing")
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"), str(path))
        except SyntaxError:
            continue  # the per-file pass reports the syntax error
        for column in sorted(_written_row_columns(tree)):
            doc = ROW_COLUMNS.get(column)
            if doc is None:
                problems.append(
                    f"schema: {rel} writes row column {column!r} that is "
                    f"not registered in ddlb_tpu/schema.py ROW_COLUMNS"
                )
            elif not str(doc).strip():
                problems.append(
                    f"schema: ddlb_tpu/schema.py ROW_COLUMNS[{column!r}] "
                    f"has an empty docstring"
                )
    return problems


def main(argv) -> int:
    targets = []
    for arg in argv or ["."]:
        p = Path(arg)
        if p.is_dir():
            targets.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py" and p.exists():
            targets.append(p)
        else:
            # a missing target must fail like pyflakes would, not lint
            # nothing and exit 0
            print(f"lint: no such file or directory: {arg}", file=sys.stderr)
            return 2
    problems = []
    # repo-level invariants (not per-file): run once whenever the lint
    # sweep covers the package (the Makefile target always does)
    if any("ddlb_tpu" in p.parts for p in targets):
        problems.extend(_cost_model_coverage())
        problems.extend(_row_schema_coverage())
    for path in targets:
        if "__pycache__" in path.parts:
            continue
        problems.extend(check_file(path))
    for line in problems:
        print(line)
    if problems:
        print(f"lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print(f"lint: {len(targets)} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
