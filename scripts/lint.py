#!/usr/bin/env python
"""Thin shim over the ``ddlb_tpu/analysis`` rule engine (legacy entry).

Every check that used to live here — the undefined-name floor, the
bandit-lite battery, the bare-print / silent-swallow / ``Process()``
bans, docstring presence, cost-model and row-schema coverage — is now a
registered rule in ``ddlb_tpu.analysis`` (DDLB002-DDLB007, DDLB107,
DDLB108), running alongside the domain invariants (DDLB101-DDLB106)
with suppressions, a baseline, and SARIF output. ``make lint`` invokes
``scripts/analyze.py``; this module stays for callers of the old
interface:

- ``check_file(path)`` returns the legacy one-line problem strings for
  one file (per-file rules only);
- ``main(argv)`` lints the given targets with the legacy output format
  and exit codes (0 clean / 1 problems / 2 missing target).

New tooling should call ``scripts/analyze.py`` (or the
``ddlb_tpu.analysis`` API) directly — it adds the baseline layer,
``--changed-only``, ``--json`` and SARIF.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from ddlb_tpu.analysis import core  # noqa: E402


def check_file(path: Path) -> list:
    """Legacy single-file interface: one problem string per finding
    (per-file rules only; suppressed findings excluded)."""
    findings = core.analyze([Path(path)], root=REPO, project_rules=False)
    return [f.legacy_str() for f in findings if not f.suppressed]


def main(argv) -> int:
    targets = []
    for arg in argv or ["."]:
        p = Path(arg)
        if p.is_dir() or (p.suffix == ".py" and p.exists()):
            targets.append(arg)
        else:
            # a missing target must fail like pyflakes would, not lint
            # nothing and exit 0
            print(f"lint: no such file or directory: {arg}", file=sys.stderr)
            return 2
    paths = core.expand_targets(targets)
    findings = core.analyze(paths, root=REPO)
    # legacy surface: no baseline layer — mask exactly the findings the
    # committed baseline grandfathers so `lint` and `analyze` agree
    from ddlb_tpu.analysis import baseline as baseline_mod

    baseline_path = REPO / baseline_mod.BASELINE_NAME
    findings.extend(
        baseline_mod.apply(
            findings, baseline_mod.load(baseline_path), baseline_path,
            # partial target lists must not report the untouched
            # backlog as stale (analyze.py's full sweep is the gate)
            analyzed={core.relativize(p, root=REPO) for p in paths},
        )
    )
    problems = [f.legacy_str() for f in findings if f.counts]
    for line in problems:
        print(line)
    if problems:
        print(f"lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print(f"lint: {len(paths)} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
