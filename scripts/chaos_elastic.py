#!/usr/bin/env python
"""Elastic-serving chaos battery: detect -> resize -> recover -> exonerate.

The executable acceptance evidence for ISSUE 19, banked at
``docs/chaos_elastic_demo.log`` (``make chaos-elastic``). Where
``serving_cluster_demo.py`` proves a FIXED cluster survives an indicted
shard by limping on the survivors, this battery proves the ELASTIC
cluster closes the whole loop on CPU-sim:

1. **Clean baselines, banked, gate-checked**: the elastic disagg
   member (p2+d2) drains the seeded trace four times fault-free with
   the SLO watch, probation and the resize controller all ARMED. Every
   row banks into a history dir; no run may indict a shard or re-admit
   one (zero false indictments / exonerations on clean hardware), and
   the observatory's ``detect_slo`` gate over the banked rows must
   produce zero findings on the drill's subject metrics (TPOT p95,
   goodput) — the zero-false-positive side of the detectors the chaos
   run then relies on. Four baselines because the gate rightly refuses
   to judge against fewer than ``SLO_MIN_HISTORY`` banked rows.
2. **Seeded decode TPOT inflation that CLEARS mid-run**: the fault plan
   hangs shard 0's decode ticks (``match: {"shard": "0"}``) but only
   while the site's call count is below ``until`` — the
   fault-that-heals shape (a thermal excursion, a transient co-tenant).
   Shard 0 because the router's least-outstanding tiebreak routes the
   first idle-cluster arrivals there: the faulted shard sees traffic
   from the first pump, so the watch's evidence accrues
   deterministically inside the fault window.
3. **Detect -> drain**: the SLO watch indicts shard 0 (tick median
   dominant AND over the TPOT SLO) and drains its in-flight work to the
   surviving decode shard over priced KV handoffs.
4. **Resize**: down a decode shard, the survivor's backlog crosses
   ``resize_backlog`` while the prefill pool has headroom — the elastic
   controller PROMOTES a prefill shard into the decode pool
   (drain-to-survivors -> role-flip -> re-prewarm), restoring decode
   capacity; the row's TPOT p95 must land back inside the SLO bound.
5. **Exonerate -> re-admit**: the fault exhausts; the indicted shard's
   probation probes start coming back healthy, and once the window
   history clears ``observatory.health.exoneration_verdict`` the shard
   re-enters the router's live set cost-weighted. The row stamps
   ``serve_readmitted=1`` and journals every transition in
   ``serve_pool_history``.
6. **Zero lost, fenced**: the chaos row's ledger must balance —
   completed + rejected == submitted, exactly-once across indictment,
   promotion and re-admission — and its ``:degraded=1:elastic=R``
   topology stamp must fence it out of the clean baselines'
   ``detect_slo`` population (a transition-bearing latency distribution
   never sets the bar for a static one).

The chaos pass runs with ``validate=False``: the benchmark harness's
validation phase re-runs ``impl.run()`` (a SECOND drain), and the row's
``serve_*`` columns report the LAST drain — but the fault plan's
``until`` clock is process-global, so it would be exhausted before that
second drain began and the reported row would be fault-free. One
measured drain keeps the row's columns and the fault window on the same
drain; the clean baselines keep the full validation (and its
exactly-once trace check), and the chaos ledger is balanced from the
row's own columns instead.

Usage: python scripts/chaos_elastic.py [--out-dir DIR] [--log FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# simulated mesh, set before anything touches JAX. 4 devices: the
# disagg p2+d2 member gives each engine a disjoint tp=1 device
os.environ.setdefault("DDLB_TPU_SIM_DEVICES", "4")

# the serving demos' tiny model, soaked: many LONG requests. The
# row's TPOT p95 is a per-request average (workload/slo.py), and the
# pump loop is serial, so every hang-stalled pump gaps the in-flight
# requests of EVERY live lane — the excursion is a global tax, not a
# shard-0 tax. Two levers keep the recovered row's p95 inside the
# SLO: (a) long outputs amortize the tax — with ~63 decode gaps per
# request, even a request that eats every stalled pump of a 24-call
# fault window averages 80*24/63 ~= 30 ms/gap, under the 50 ms bound;
# (b) 240 requests put the distribution's top 5% at 12 requests,
# comfortably above the drained cohort (the only requests that also
# carry a re-queue wait in one of their gaps)
M, N, K = 16, 64, 128
MODEL = {
    "batch": 4, "vocab": 128, "n_heads": 4, "layers": 1,
    "n_requests": 240, "out_mean": 64, "out_max": 96,
}
#: arrivals spread over ~12s, well under the 8-lane cluster's token
#: throughput: clean queues stay shallow (admission waits land in a
#: request's first decode gap and would otherwise dominate its
#: average), while the hang still piles the decode backlog that trips
#: the resize controller — the stall, not arrival pressure, promotes
RATE = 20.0
#: the TPOT SLO the watch indicts against AND the recovery bound the
#: chaos row's pooled p95 must land back inside; TTFT is unconstrained
#: (this battery is about time-between-tokens, not queue position)
SLO_TPOT_MS = 50.0
ELASTIC = {
    "elastic": 1, "resize_backlog": 2, "resize_cooldown": 16,
    "probation_ticks": 3, "watch_ticks": 4, "watch_dominance": 2.0,
    "slo_ttft_ms": 10000.0, "slo_tpot_ms": SLO_TPOT_MS,
}
#: the seeded fault: +80 ms on every decode tick of shard 0 while the
#: site's call count is below ``until``. The window is sized for two
#: deadlines at once: long enough that the watch accrues its
#: ``watch_ticks`` of faulted evidence and indicts (~count 9-13 on
#: this trace: shard 0 takes the first arrivals), short enough that
#: the total stall budget — ``until * duration_s``, every stalled
#: pump gapping every live lane — amortizes under the SLO across each
#: request's ~63 gaps, and the probation probes turn healthy with
#: most of the drain still ahead so exoneration lands in-run
FAULT_HANG_S = 0.08
FAULT_UNTIL = 24


def impl_config():
    return {
        "implementation": "disagg", "rate": RATE,
        "prefill_shards": 2, "decode_shards": 2,
        **MODEL, **ELASTIC,
    }


class _Tee:
    """Mirror stdout into the banked demo log, minus the runner's
    per-row telemetry echo (the ``[ddlb_tpu]`` lines stay on the
    console; the banked transcript keeps the curated narrative)."""

    def __init__(self, path):
        self._file = open(path, "w", encoding="utf-8")
        self._stdout = sys.stdout
        self._at_line_start = True
        self._skipping = False

    def write(self, data):
        self._stdout.write(data)
        for line in data.splitlines(keepends=True):
            if self._at_line_start:
                self._skipping = line.startswith("[ddlb_tpu]")
            if not self._skipping:
                self._file.write(line)
            self._at_line_start = line.endswith("\n")

    def flush(self):
        self._stdout.flush()
        self._file.flush()


def run_pass(label, impls, csv_path, run_id, validate=True):
    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    print(f"\n==== {label} ====", flush=True)
    os.environ["DDLB_TPU_RUN_ID"] = run_id
    if os.path.exists(csv_path):
        os.remove(csv_path)
    runner = PrimitiveBenchmarkRunner(
        "serving_load", m=M, n=N, k=K,
        implementations=impls,
        # ONE measured drain, no warmup drain: the fault plan's ``until``
        # clock is the process-global site call count, so the measured
        # drain must be the FIRST drain that burns it (the chaos pass
        # also sets validate=False — the validation phase would re-drain
        # and overwrite the row's serve_* columns with a fault-free run)
        dtype="float32", num_iterations=1, num_warmups=0,
        validate=validate, isolation="none", progress=False,
        barrier_at_each_iteration=False,
        output_csv=csv_path,
    )
    t0 = time.monotonic()
    df = runner.run()
    wall = time.monotonic() - t0
    errors = int((df["error"].astype(str) != "").sum())
    invalid = int((~df["valid"].astype(bool)).sum())
    print(
        f"{label}: {len(df)} rows in {wall:.1f}s, {errors} error(s), "
        f"{invalid} invalid", flush=True,
    )
    assert errors == 0 and invalid == 0, f"{label} must run clean"
    return df


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out-dir", default=None, metavar="DIR")
    parser.add_argument(
        "--log",
        default=os.path.join(REPO, "docs", "chaos_elastic_demo.log"),
    )
    args = parser.parse_args(argv)
    os.makedirs(os.path.dirname(args.log), exist_ok=True)
    tee = _Tee(args.log)
    sys.stdout = tee
    work = args.out_dir or tempfile.mkdtemp(prefix="ddlb_chaos_elastic_")
    os.makedirs(work, exist_ok=True)
    history = os.path.join(work, "history")
    failures: list = []

    def check(ok, what):
        print(f"  {'PASS' if ok else 'FAIL'}  {what}", flush=True)
        if not ok:
            failures.append(what)

    saved_history = os.environ.get("DDLB_TPU_HISTORY")
    os.environ["DDLB_TPU_HISTORY"] = history
    try:
        import pandas as pd

        from ddlb_tpu.faults import plan as fault_plan
        from ddlb_tpu.observatory import health, regress, store

        print(
            f"elastic chaos battery — sim devices "
            f"{os.environ['DDLB_TPU_SIM_DEVICES']}, disagg p2+d2 elastic, "
            f"model {N}x{K} (batch {MODEL['batch']}, "
            f"{MODEL['n_requests']} requests at {RATE:.0f} req/s), "
            f"TPOT SLO {SLO_TPOT_MS:.0f} ms"
        )
        print(
            f"seeded fault: +{FAULT_HANG_S * 1000:.0f} ms on every decode "
            f"tick of shard 0 until site call {FAULT_UNTIL} (then it heals)"
        )

        # -- 1: clean baselines, banked, detectors armed ------------------
        # four, not two: the SLO gate withholds judgment below
        # SLO_MIN_HISTORY banked rows per fenced key (a one-row baseline
        # has zero MAD), so the zero-false-positive check is only
        # non-vacuous once each clean row faces >= that many others
        clean_rows = {}
        for run in (
            "elastic-clean-1", "elastic-clean-2",
            "elastic-clean-3", "elastic-clean-4",
        ):
            df = run_pass(
                f"clean baseline '{run}' (watch + probation + resize "
                f"controller armed, no fault)",
                {"disagg_0": impl_config()},
                os.path.join(work, f"{run}.csv"), run,
            )
            row = df.iloc[0]
            clean_rows[run] = row
            check(
                int(row["serve_shards_excluded"]) == 0
                and int(row["serve_readmitted"]) == 0,
                f"'{run}': zero false indictments / exonerations "
                f"(excluded={int(row['serve_shards_excluded'])}, "
                f"readmitted={int(row['serve_readmitted'])})",
            )
            check(
                ":degraded=" not in str(row["serve_topology"]),
                f"'{run}': topology {row['serve_topology']!r} carries no "
                f"degraded stamp",
            )
            print(
                f"  {run}: TPOT p95 {float(row['slo_tpot_p95_ms']):.1f} ms, "
                f"resizes={int(row['serve_resizes'])} "
                f"(pool breathing on clean load is policy, not a fault)"
            )

        # -- 2-5: the seeded chaos run ------------------------------------
        plan = {
            "seed": 19,
            "rules": [
                {
                    "site": "serve.decode_tick", "kind": "hang",
                    "duration_s": FAULT_HANG_S,
                    "match": {"shard": "0"},
                    "until": FAULT_UNTIL,
                    "fail_attempts": 1000000,
                }
            ],
        }
        print(
            "\n==== chaos run: TPOT inflation on decode shard 0 that "
            "clears mid-run ===="
        )
        drill = None
        for attempt in range(1, 4):
            os.environ["DDLB_TPU_FAULT_PLAN"] = json.dumps(plan)
            # fresh plan cache AND per-site call counters: the ``until``
            # window must restart for every attempt in this process
            fault_plan.reset()
            try:
                df = run_pass(
                    f"seeded elastic drill (attempt {attempt})",
                    {"disagg_chaos": impl_config()},
                    os.path.join(work, f"chaos{attempt}.csv"),
                    f"elastic-chaos-{attempt}",
                    validate=False,
                )
            finally:
                os.environ.pop("DDLB_TPU_FAULT_PLAN", None)
                fault_plan.reset()
            drill = df.iloc[0]
            history_str = str(drill["serve_pool_history"])
            topo = str(drill["serve_topology"])
            tpot_p95 = float(drill["slo_tpot_p95_ms"])
            # every leg of the loop is re-measurable: a host-contention
            # window can shift WHEN the watch/probes see their evidence,
            # so a failed leg retries the whole drill rather than
            # crashing the battery
            problems = []
            if "serve.decode_tick" not in str(drill["fault_injected"]):
                problems.append("the seeded hang never fired")
            if int(drill["serve_shards_excluded"]) != 1:
                problems.append(
                    f"expected exactly one indictment, got "
                    f"{int(drill['serve_shards_excluded'])}"
                )
            if int(drill["serve_drained"]) <= 0:
                problems.append("no in-flight requests drained")
            if int(drill["serve_resizes"]) < 1 or "promote:" not in (
                history_str
            ):
                problems.append(f"no promotion (journal [{history_str}])")
            if int(drill["serve_readmitted"]) != 1 or (
                "exonerate:0@" not in history_str
            ):
                problems.append(
                    f"shard 0 never exonerated (journal [{history_str}])"
                )
            if ":degraded=1" not in topo or ":elastic=" not in topo:
                problems.append(f"topology stamp {topo!r} incomplete")
            if tpot_p95 > SLO_TPOT_MS:
                problems.append(
                    f"TPOT p95 {tpot_p95:.1f} ms above the SLO bound"
                )
            print(
                f"attempt {attempt}: {topo}, pool history "
                f"[{history_str}], {int(drill['serve_drained'])} drained "
                f"over {int(drill['serve_handoffs'])} handoffs, TPOT p95 "
                f"{tpot_p95:.1f} ms (SLO {SLO_TPOT_MS:.0f} ms)"
            )
            if not problems:
                break
            for p in problems:
                print(f"attempt {attempt}: {p}", flush=True)
            if attempt < 3:
                print(f"attempt {attempt}: re-running the drill",
                      flush=True)
        check(
            "serve.decode_tick" in str(drill["fault_injected"]),
            "seeded decode-tick hang fired on the drill row",
        )
        check(
            int(drill["serve_shards_excluded"]) == 1
            and int(drill["serve_drained"]) > 0,
            f"SLO watch indicted shard 0 and drained its work "
            f"({int(drill['serve_drained'])} requests over "
            f"{int(drill['serve_handoffs'])} KV handoffs)",
        )
        check(
            int(drill["serve_resizes"]) >= 1
            and "promote:" in str(drill["serve_pool_history"]),
            f"elastic controller promoted a prefill shard into the "
            f"decode pool (journal: {drill['serve_pool_history']})",
        )
        check(
            float(drill["slo_tpot_p95_ms"]) <= SLO_TPOT_MS,
            f"TPOT p95 recovered inside the SLO bound "
            f"({float(drill['slo_tpot_p95_ms']):.1f} <= "
            f"{SLO_TPOT_MS:.0f} ms)",
        )
        check(
            int(drill["serve_readmitted"]) == 1
            and "exonerate:0@" in str(drill["serve_pool_history"]),
            "indicted shard passed probation, was exonerated and "
            "re-admitted",
        )
        check(
            ":degraded=1" in str(drill["serve_topology"])
            and ":elastic=" in str(drill["serve_topology"]),
            f"topology stamped {drill['serve_topology']!r}",
        )
        # the chaos pass skipped the harness validation phase (it would
        # re-drain fault-free and overwrite the row) — so balance the
        # ledger from the row's own columns: every submitted request
        # either completed or was shed at the door, exactly once, across
        # the indictment drain, the promotion and the re-admission
        completed = int(drill["slo_completed"])
        rejected = int(drill["serve_rejected"])
        check(
            completed + rejected == MODEL["n_requests"],
            f"ledger balances: {completed} completed + {rejected} "
            f"rejected == {MODEL['n_requests']} submitted (zero requests "
            f"lost across every transition)",
        )

        # -- 6: the observatory gates over the banked history -------------
        print("\n==== observatory gates over the banked history ====")
        records = store.load_history(history)
        banked = [r for r in records if r.get("kind", "row") == "row"]
        check(
            len(banked) >= 5,
            f"history banked every pass ({len(banked)} rows)",
        )
        # the gate metrics the chaos run relies on: the drill is about
        # time-between-tokens and throughput. The TTFT tail percentiles
        # stay out of the drill's zero-FP contract — on CPU-sim a
        # single mid-drain retrace lands ~25 ms in a ~5 ms p99, which
        # is real host behavior, not a detector defect
        drill_metrics = tuple(
            (m, d) for m, d in regress.SLO_METRICS
            if m in ("slo_tpot_p95_ms", "slo_goodput_rps")
        )
        for run, row in clean_rows.items():
            findings = regress.detect_slo(
                [row.to_dict()], records, metrics=drill_metrics,
                exclude_run=run,
            )
            check(
                findings == [],
                f"detect_slo over '{run}' vs the bank: zero findings "
                f"(no false positives on clean hardware)",
            )
        chaos_findings = regress.detect_slo(
            [drill.to_dict()], records,
            exclude_run=str(os.environ.get("DDLB_TPU_RUN_ID", "")),
        )
        check(
            chaos_findings == [],
            "detect_slo fences the chaos row out of the static "
            "baselines (distinct serve_topology stamp, zero findings)",
        )
        verdict = health.verdict_from_observations(
            health.observations_from_history(records)
        )
        check(
            verdict.get("status") != health.PERSISTENT,
            f"health verdict over the bank indicts nobody "
            f"({verdict.get('status')})",
        )
        print()
    finally:
        os.environ.pop("DDLB_TPU_FAULT_PLAN", None)
        if saved_history is None:
            os.environ.pop("DDLB_TPU_HISTORY", None)
        else:
            os.environ["DDLB_TPU_HISTORY"] = saved_history
        if not args.out_dir:
            shutil.rmtree(work, ignore_errors=True)
        sys.stdout = tee._stdout

    with open(args.log, "a", encoding="utf-8") as f:
        if failures:
            f.write(
                f"\nchaos_elastic: {len(failures)} assertion(s) FAILED\n"
            )
        else:
            f.write(
                "\nchaos_elastic: seeded TPOT inflation detected and "
                "indicted, a prefill shard promoted to recover the decode "
                "pool inside the SLO, the healed shard exonerated and "
                "re-admitted after probation, zero requests lost, and "
                "the clean baselines banked with zero detector false "
                "positives — OK\n"
            )
    if failures:
        print(f"\nchaos_elastic: {len(failures)} assertion(s) FAILED",
              flush=True)
        for what in failures:
            print(f"  FAIL {what}", flush=True)
        return 1
    print(
        "\nchaos_elastic: detect -> resize -> recover -> exonerate -> "
        "re-admit, zero requests lost — OK",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
