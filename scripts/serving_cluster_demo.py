#!/usr/bin/env python
"""Serving-cluster acceptance demo: router vs single, admission, drain.

The executable acceptance evidence for ISSUE 18, banked at
``docs/serving_cluster_demo.log``. Everything runs on the CPU sim with
the tiny serving model, so it is reproducible anywhere:

1. **Router vs single engine at fixed offered load**: the same seeded
   deep-overload trace drains through one tp=2 engine (``engine``) and
   through two tp=1 engines behind the prefix-affinity router
   (``router`` dp=2). Deep overload makes the contrast deterministic —
   TTFT is queue position x service time, and two admission doors
   drain the queue roughly twice as fast — so the routed row must beat
   the single-engine row on TTFT p95. A ``disagg`` (p1+d1) row rides
   along: its KV handoffs must be counted AND priced (the decode-census
   wire term from ``perfmodel/cost.kv_handoff_seconds``).
2. **Admission control under 1.5x-capacity overload**: service
   capacity is measured from the routed overload drain itself
   (requests / median drain wall), then the same trace is offered at
   1.5x that rate twice — once with the door open, once with the token
   bucket set to measured capacity. The controlled row sheds at the
   door (counted ``rejected`` outcomes, never losses — the row still
   validates exactly-once accounting) and its SLO attainment over the
   admitted work must be >= the uncontrolled row's.
3. **Chaos drill — indictment, drain, zero lost**: the fault plan
   hangs every decode tick of shard 1 (``match: {"shard": "1"}``), the
   SLO watch indicts it (worst median tick dominant AND over the TPOT
   SLO), and the cluster drains its in-flight requests to shard 0 over
   the KV-handoff path. The row must come back VALID — validation is
   exactly-once completion of every admitted request, i.e. the drill
   lost nothing — with the ``:degraded=1`` topology stamp.

Usage: python scripts/serving_cluster_demo.py [--out-dir DIR] [--log FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# simulated mesh, set before anything touches JAX. 2 devices: the router
# member splits them into two disjoint tp=1 engines; the single-engine
# baseline spans both as one dp=1 tp=2 mesh — same chips, different door
os.environ.setdefault("DDLB_TPU_SIM_DEVICES", "2")

# the serving_load demo's tiny model, unchanged: decode ticks cost real
# milliseconds so queueing under overload is physical, not simulated
M, N, K = 16, 64, 128
MODEL = {
    "batch": 4, "vocab": 128, "n_heads": 4, "layers": 1,
    "n_requests": 24, "out_mean": 4, "out_max": 8,
}
SLO = {"slo_ttft_ms": 75.0, "slo_tpot_ms": 30.0}
#: deep overload — the deterministic regime (see module docstring)
OVERLOAD_RATE = 768.0
#: the router members' shared Zipf prefix workload (affinity needs
#: repeated prefixes to have anything to stick to)
PREFIX = {"prefix_pop": 4, "prefix_len": 16}


class _Tee:
    """Mirror stdout into the banked demo log, minus the runner's
    per-row telemetry echo (the ``[ddlb_tpu]`` lines stay on the
    console; the banked transcript keeps the curated narrative)."""

    def __init__(self, path):
        self._file = open(path, "w", encoding="utf-8")
        self._stdout = sys.stdout
        self._at_line_start = True
        self._skipping = False

    def write(self, data):
        self._stdout.write(data)
        for line in data.splitlines(keepends=True):
            if self._at_line_start:
                self._skipping = line.startswith("[ddlb_tpu]")
            if not self._skipping:
                self._file.write(line)
            self._at_line_start = line.endswith("\n")

    def flush(self):
        self._stdout.flush()
        self._file.flush()


def run_pass(label, impls, csv_path, run_id):
    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    print(f"\n==== {label} ====", flush=True)
    os.environ["DDLB_TPU_RUN_ID"] = run_id
    if os.path.exists(csv_path):
        os.remove(csv_path)
    runner = PrimitiveBenchmarkRunner(
        "serving_load", m=M, n=N, k=K,
        implementations=impls,
        dtype="float32", num_iterations=3, num_warmups=1,
        validate=True, isolation="none", progress=False,
        # one aggregate window per drain pair: the drain IS the sample
        barrier_at_each_iteration=False,
        output_csv=csv_path,
    )
    t0 = time.monotonic()
    df = runner.run()
    wall = time.monotonic() - t0
    errors = int((df["error"].astype(str) != "").sum())
    invalid = int((~df["valid"].astype(bool)).sum())
    print(
        f"{label}: {len(df)} rows in {wall:.1f}s, {errors} error(s), "
        f"{invalid} invalid", flush=True,
    )
    assert errors == 0 and invalid == 0, f"{label} must run clean"
    return df


def one_row(df, impl):
    rows = df[df["base_implementation"] == impl]
    assert len(rows) == 1, f"expected one {impl} row, got {len(rows)}"
    return rows.iloc[0]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out-dir", default=os.path.join(REPO, "hwlogs"))
    parser.add_argument(
        "--log",
        default=os.path.join(REPO, "docs", "serving_cluster_demo.log"),
    )
    args = parser.parse_args(argv)
    os.makedirs(args.out_dir, exist_ok=True)
    os.makedirs(os.path.dirname(args.log), exist_ok=True)
    sys.stdout = _Tee(args.log)
    work = os.path.join(args.out_dir, "serving_cluster_demo")
    os.makedirs(work, exist_ok=True)

    print(
        f"serving-cluster demo — sim devices "
        f"{os.environ['DDLB_TPU_SIM_DEVICES']}, model {N}x{K} "
        f"(batch {MODEL['batch']}, {MODEL['n_requests']} requests), "
        f"overload {OVERLOAD_RATE:.0f} req/s"
    )

    # -- 1: router dp=2 vs single engine at fixed offered load ----------
    # single-digit-ms TTFT percentiles on a shared 2-core CPU host can
    # land in a co-tenant burst that slows one member's drain 10x for
    # ~30 s; the operator's remedy is to re-measure, and so is the
    # demo's — up to 3 passes, at least one of which must show the
    # routed win (the disagg/accounting assertions must hold EVERY pass)
    cmp_impls = {
        "engine_0": {"implementation": "engine", "rate": OVERLOAD_RATE,
                     **MODEL, **PREFIX, **SLO},
        "router_0": {"implementation": "router", "rate": OVERLOAD_RATE,
                     "dp": 2, **MODEL, **PREFIX, **SLO},
        "disagg_0": {"implementation": "disagg", "rate": 48.0,
                     "prefill_shards": 1, "decode_shards": 1,
                     **MODEL, **SLO},
    }
    routed = single = None
    for attempt in range(1, 4):
        df1 = run_pass(
            f"router vs single at {OVERLOAD_RATE:.0f} req/s "
            f"(attempt {attempt})",
            cmp_impls, os.path.join(work, f"compare{attempt}.csv"),
            f"cluster-compare-{attempt}",
        )
        single = one_row(df1, "engine")
        routed = one_row(df1, "router")
        disagg = one_row(df1, "disagg")
        # the disagg accounting bar holds on every attempt: handoffs
        # counted, bytes census'd, latency priced from the chip spec
        assert disagg["serve_topology"] == "disagg:p1+d1", disagg["serve_topology"]
        assert int(disagg["serve_handoffs"]) > 0, "no KV handoffs counted"
        assert float(disagg["serve_handoff_bytes"]) > 0.0
        assert float(disagg["serve_handoff_ms"]) > 0.0, (
            "handoff latency not priced"
        )
        assert routed["serve_topology"] == "router:dp=2"
        assert int(routed["serve_affinity_hits"]) > 0, (
            "prefix affinity never engaged on a Zipf prefix workload"
        )
        s_ttft = float(single["slo_ttft_p95_ms"])
        r_ttft = float(routed["slo_ttft_p95_ms"])
        print(
            f"TTFT p95 at {OVERLOAD_RATE:.0f} req/s: single {s_ttft:.1f} ms"
            f" vs routed dp=2 {r_ttft:.1f} ms "
            f"({s_ttft / max(r_ttft, 1e-9):.2f}x); disagg "
            f"{int(disagg['serve_handoffs'])} handoffs, "
            f"{float(disagg['serve_handoff_bytes']):.0f} B, "
            f"{float(disagg['serve_handoff_ms']):.4f} ms priced"
        )
        if r_ttft < s_ttft:
            break
        print(
            f"attempt {attempt}: routed did not beat single (host "
            f"contention window); re-measuring", flush=True,
        )
    assert float(routed["slo_ttft_p95_ms"]) < float(
        single["slo_ttft_p95_ms"]
    ), "routed dp=2 must beat the single engine on TTFT p95"

    # -- 2: admission control under 1.5x-capacity overload --------------
    # capacity measured from the routed overload drain itself: deep
    # overload means the drain wall IS the service time for the trace
    capacity_rps = MODEL["n_requests"] / (
        float(routed["median time (ms)"]) * 1e-3
    )
    overload_rps = 1.5 * capacity_rps
    # the bucket debits max_new tokens per admit; capacity in tokens/s
    # is the same drain's generated tokens over the same wall
    capacity_tps = capacity_rps * MODEL["out_mean"]
    print(
        f"\nmeasured routed capacity: {capacity_rps:.1f} req/s "
        f"(~{capacity_tps:.0f} tok/s); offering {overload_rps:.1f} req/s"
    )
    adm_common = {
        "rate": overload_rps, "dp": 2, **MODEL, **PREFIX, **SLO,
    }
    adm_impls = {
        "router_open": {
            "implementation": "router", "admission": "open", **adm_common,
        },
        "router_ctrl": {
            "implementation": "router", "admission": "token_bucket",
            "admission_rate_tps": capacity_tps,
            # the default 0.5 s burst window holds ~capacity_tps/2
            # tokens — several times this whole trace's demand, so the
            # bucket would never empty. Size the burst to the trace:
            # at 1.5x overload the arrival window runs a deficit of
            # n_requests*out_mean/3 (~32) tokens, so the smallest
            # allowed burst window (~15 tokens at this rate) forces
            # visible shedding while still absorbing jitter.
            "admission_burst_s": 0.01,
            **adm_common,
        },
    }
    ctrl = opened = None
    for attempt in range(1, 4):
        df2 = run_pass(
            f"admission at 1.5x capacity (attempt {attempt})", adm_impls,
            os.path.join(work, f"admission{attempt}.csv"),
            f"cluster-admission-{attempt}",
        )
        opened = df2[df2["option"].str.contains("admission=open")].iloc[0]
        ctrl = df2[df2["option"].str.contains("admission=token_bucket")].iloc[0]
        assert int(opened["serve_rejected"]) == 0, (
            "the open door must not shed"
        )
        assert int(ctrl["serve_rejected"]) > 0, (
            "the token bucket never shed under 1.5x-capacity overload"
        )
        att_open = float(opened["slo_attainment"])
        att_ctrl = float(ctrl["slo_attainment"])
        print(
            f"SLO attainment at {overload_rps:.0f} req/s: open "
            f"{att_open:.2f} vs controlled {att_ctrl:.2f} "
            f"({int(ctrl['serve_rejected'])} shed at the door, "
            f"0 lost — row validates exactly-once accounting)"
        )
        if att_ctrl >= att_open:
            break
        print(
            f"attempt {attempt}: controlled attainment below open (host "
            f"contention window); re-measuring", flush=True,
        )
    assert float(ctrl["slo_attainment"]) >= float(
        opened["slo_attainment"]
    ), "admission control must hold attainment >= uncontrolled"

    # -- 3: chaos drill — hang shard 1, indict, drain, zero lost --------
    plan = {
        "seed": 18,
        "rules": [
            {
                "site": "serve.decode_tick", "kind": "hang",
                "duration_s": 0.05,
                "match": {"shard": "1"},
                # fire on every tick of every drain
                "fail_attempts": 1000000,
            }
        ],
    }
    print(
        "\n==== chaos drill: hang decode shard 1 (+50 ms/tick), "
        "SLO watch must indict and drain it ===="
    )
    os.environ["DDLB_TPU_FAULT_PLAN"] = json.dumps(plan)
    from ddlb_tpu.faults import plan as fault_plan

    fault_plan.reset()  # drop the cached no-plan fast path
    try:
        chaos_impls = {
            "router_chaos": {
                "implementation": "router", "rate": 48.0, "dp": 2,
                "watch_ticks": 4, "watch_dominance": 2.0,
                **MODEL, **PREFIX,
                # a TPOT SLO the hung shard clearly violates: the watch
                # indicts on dominance AND SLO breach, never on skew alone
                "slo_ttft_ms": 75.0, "slo_tpot_ms": 10.0,
            },
        }
        df3 = run_pass(
            "chaos drill (seeded shard-1 hang)", chaos_impls,
            os.path.join(work, "chaos.csv"), "cluster-chaos",
        )
    finally:
        os.environ.pop("DDLB_TPU_FAULT_PLAN", None)
        fault_plan.reset()
    drill = one_row(df3, "router")
    assert (
        df3["fault_injected"].astype(str).str.contains("serve.decode_tick")
    ).any(), "the seeded hang never fired"
    assert int(drill["serve_shards_excluded"]) == 1, (
        "the SLO watch never indicted the hung shard"
    )
    assert int(drill["serve_drained"]) > 0, (
        "no in-flight requests drained over the handoff path"
    )
    assert str(drill["serve_topology"]).endswith(":degraded=1"), (
        drill["serve_topology"]
    )
    # run_pass already asserted valid=True: exactly-once completion of
    # every admitted request — the drill lost NOTHING
    print(
        f"chaos drill PASSED: shard 1 indicted and excluded, "
        f"{int(drill['serve_drained'])} in-flight request(s) drained to "
        f"the survivor over {int(drill['serve_handoffs'])} KV handoff(s), "
        f"topology {drill['serve_topology']}, row valid "
        f"(zero requests lost)"
    )
    print("\nserving-cluster demo PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
