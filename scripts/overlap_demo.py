#!/usr/bin/env python
"""Chunked-fusion engine demo: chunked vs unchunked overlap members.

The executable acceptance evidence for ISSUE 10, banked at
``docs/overlap_demo.log``. Everything runs on the 8-device CPU sim at
small shapes, so it is reproducible anywhere:

1. **Sweep**: every family with an overlap member (tp_columnwise,
   tp_rowwise, dp_allreduce, ep_alltoall) runs its legacy unchunked
   pipeline next to the shared chunked engine at ``chunk_count`` in
   {1, 2, 4, 8}, through the real benchmark runner — so every row
   carries the perfmodel columns (``predicted_s`` with the
   chunk-granularity fill/drain term) and the observatory attribution
   columns (``measured_overlap_frac``, ``phase_idle_s``), with
   validation ON (numerics against the single-device reference).
2. **Model self-check**: per chunked row, the chunk-extended
   ``predicted_s`` must equal the schedule law
   ``max(compute, comm) + min(compute, comm)/chunk_count`` recomposed
   from the row's own phase floors — the fill/drain term agreeing with
   the schedule the engine actually runs; ``chunk_count=1`` must price
   exactly the serial floor, and every chunked row's prediction must
   descend monotonically toward the ideal ``max()`` as chunks grow.
3. **Attribution contract**: every overlap row reports
   ``measured_overlap_frac`` — a finite [0, 1] fraction wherever the
   schedule has a hideable window, the schema-documented NaN on rows
   with none (the chunked engine at ``chunk_count=1``) — never inf.
4. **Ranking**: ``scripts/perf_report.py --overlap`` over the sweep's
   CSVs — the per-family, per-chunk_count view the CI target
   (``make overlap-report``) publishes.

CPU-sim caveat (same stance as the perfmodel demo): the calibrated
``cpu-sim`` spec is deliberately optimistic, so ABSOLUTE fractions are
tiny and a host CPU shows no real compute/collective overlap — the
demo proves the schedule law, the plumbing, and the numerics; achieved
overlap is a hardware measurement.

Usage: python scripts/overlap_demo.py [--log PATH] [--no-log]
"""

from __future__ import annotations

import argparse
import math
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# simulated mesh, set before anything touches JAX
os.environ.setdefault("DDLB_TPU_SIM_DEVICES", "8")

#: (family, (m, n, k), legacy unchunked baseline config); ep's m must
#: divide by d^2 * chunk_count at the deepest swept pipeline (8*8*8)
FAMILIES = [
    ("tp_columnwise", (256, 64, 64), {"algorithm": "coll_pipeline", "s": 4}),
    ("tp_rowwise", (256, 64, 64), {"algorithm": "coll_pipeline", "s": 4}),
    ("dp_allreduce", (256, 64, 64), {"algorithm": "coll_pipeline", "s": 4}),
    ("ep_alltoall", (512, 64, 64), {"algorithm": "coll_pipeline", "s": 2}),
]

CHUNK_COUNTS = (1, 2, 4, 8)


class Tee:
    """Print + capture, so the transcript lands in docs/ verbatim."""

    def __init__(self):
        self.lines = []

    def __call__(self, text=""):
        print(text, flush=True)
        self.lines.append(str(text))


def impl_map(legacy):
    configs = [dict(legacy)] + [
        {"algorithm": "chunked", "chunk_count": c} for c in CHUNK_COUNTS
    ]
    return {
        f"overlap_{i}": {"implementation": "overlap", **cfg}
        for i, cfg in enumerate(configs)
    }


def run_family(family, shape, legacy, csv_path):
    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    m, n, k = shape
    runner = PrimitiveBenchmarkRunner(
        family, m=m, n=n, k=k,
        implementations=impl_map(legacy),
        dtype="float32", num_iterations=20, num_warmups=3,
        validate=True, isolation="none", progress=False,
        output_csv=csv_path,
        # one aggregate window per row: the jitter-resistant protocol on
        # a contended CPU sim (same stance as the observatory demo)
        barrier_at_each_iteration=False,
    )
    return runner.run()


def _f(row, col):
    try:
        v = float(row[col])
    except (KeyError, TypeError, ValueError):
        return float("nan")
    return v


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--log", default=os.path.join(REPO, "docs", "overlap_demo.log"),
        help="transcript destination (default docs/overlap_demo.log)",
    )
    parser.add_argument(
        "--no-log", action="store_true", help="stdout only, write no file"
    )
    args = parser.parse_args(argv)

    say = Tee()
    failures = []

    def check(ok, what):
        say(f"  {'PASS' if ok else 'FAIL'}  {what}")
        if not ok:
            failures.append(what)

    workdir = tempfile.mkdtemp(prefix="overlap_demo_")
    say("==== chunked-fusion engine demo (8-device CPU sim, float32) ====")
    say(f"sweep: {len(FAMILIES)} families x (1 legacy + "
        f"{len(CHUNK_COUNTS)} chunked) overlap configs, validated rows")
    say()

    csvs = []
    for family, shape, legacy in FAMILIES:
        csv_path = os.path.join(workdir, f"{family}.csv")
        df = run_family(family, shape, legacy, csv_path)
        csvs.append(csv_path)
        m, n, k = shape
        say(f"-- {family} (m={m} n={n} k={k}) --")
        say(f"{'option':<38} {'pred us':>9} {'meas ms':>9} "
            f"{'roofline':>9} {'ovl frac':>9} {'valid':>5}")
        for _, row in df.iterrows():
            ov = _f(row, "measured_overlap_frac")
            ovs = f"{ov:.3f}" if not math.isnan(ov) else "nan"
            say(
                f"{str(row['option']):<38} "
                f"{_f(row, 'predicted_s') * 1e6:>9.3f} "
                f"{_f(row, 'median time (ms)'):>9.3f} "
                f"{_f(row, 'roofline_frac'):>9.2e} "
                f"{ovs:>9} "
                f"{str(row.get('valid', '')):>5}"
            )

        # -- per-family contracts -----------------------------------------
        err_rows = int((df["error"].astype(str).str.strip() != "").sum())
        check(err_rows == 0, f"{family}: all rows measured (0 errors)")
        check(
            bool((df["valid"].astype(str) == "True").all()),
            f"{family}: every overlap row validates vs the reference",
        )

        chunked = df[df["option"].astype(str).str.contains("algorithm=chunked")]
        by_c = {}
        law_ok, serial_ok = True, True
        for _, row in chunked.iterrows():
            opts = dict(
                p.split("=", 1) for p in str(row["option"]).split(";")
            )
            c = int(opts["chunk_count"])
            comp, comm = _f(row, "phase_compute_s"), _f(row, "phase_comm_s")
            pred = _f(row, "predicted_s")
            by_c[c] = pred
            want = max(comp, comm) + min(comp, comm) / c
            law_ok &= math.isfinite(pred) and abs(pred - want) <= 1e-12 * want
            if c == 1:
                serial_ok &= abs(pred - (comp + comm)) <= 1e-12 * (comp + comm)
        check(
            law_ok,
            f"{family}: predicted_s == max(comp,comm) + min(comp,comm)/c "
            f"on every chunked row (the schedule law)",
        )
        check(serial_ok, f"{family}: chunk_count=1 prices the serial floor")
        seq = [by_c[c] for c in sorted(by_c)]
        check(
            all(a > b for a, b in zip(seq, seq[1:])),
            f"{family}: predicted_s strictly descends as chunks grow "
            f"({' > '.join(f'{v * 1e6:.3f}us' for v in seq)})",
        )

        ovl = [
            _f(row, "measured_overlap_frac") for _, row in df.iterrows()
        ]
        check(
            all(math.isnan(v) or 0.0 <= v <= 1.0 for v in ovl)
            and not any(math.isinf(v) for v in ovl),
            f"{family}: measured_overlap_frac on every row is in [0,1] "
            f"or the schema-documented NaN — never inf",
        )
        c1 = chunked[
            chunked["option"].astype(str).str.contains("chunk_count=1;")
        ]
        check(
            all(
                math.isnan(_f(row, "measured_overlap_frac"))
                for _, row in c1.iterrows()
            ),
            f"{family}: chunk_count=1 reports NaN (no hideable window "
            f"at that granularity)",
        )
        say()

    # -- the CI ranking view ----------------------------------------------
    say("==== perf_report --overlap (per family and chunk_count) ====")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         "--overlap"] + csvs,
        capture_output=True, text=True,
    )
    say(out.stdout.rstrip())
    check(out.returncode == 0, "perf_report --overlap exits 0")

    say()
    if failures:
        say(f"DEMO FAILED: {len(failures)} check(s): {failures}")
    else:
        say("DEMO PASSED: every check green")
    if not args.no_log:
        with open(args.log, "w") as f:
            f.write("\n".join(say.lines) + "\n")
        print(f"[transcript -> {args.log}]")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
