#!/usr/bin/env python
"""Cross-rank skew report: the world timeline, stragglers, and the gate.

The temporal post-mortem of a multi-process run (ISSUE 14): where
``flight_report.py`` joins ranks by SEQUENCE number, this report joins
them by TIME — per-rank flight-recorder stamps aligned onto one world
clock via the run's own barrier exchanges (midpoint estimator + drift
fit, ``ddlb_tpu/telemetry/clocksync.py``), then folded into:

- the per-rank clock-offset table (offset, drift, uncertainty bound);
- the per-collective skew table: which collective waited how long on
  its last arrival, who arrived last, and the waited share of the
  collective's wall time;
- the worst-rank ranking (skew-seconds each rank caused as the last
  arrival) and the per-rank critical-path attribution — wall time
  split into compute / wire / skew-wait / host;
- with ``--history``, the observatory skew GATE: the named run's
  banked rows (``straggler_frac`` / ``skew_enter_s`` columns) against
  the per-key history baseline (``regress.detect_skew`` — median+MAD
  with absolute noise floors), findings ranked worst first.

Usage:
    python scripts/skew_report.py RUN_DIR [--ranks N] [--json]
        [--history DIR] [--run RUN_ID] [--top N]

Exit code: 1 when the gate flags a regression (or RUN_DIR has no
flight files), 0 otherwise — so CI and the demo can gate on it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddlb_tpu.observatory import regress, store, timeline  # noqa: E402


def _fmt_s(value, width=9):
    try:
        return f"{float(value):{width}.4f}"
    except (TypeError, ValueError):
        return " " * (width - 1) + "-"


def render_text(doc: dict, top: int = 12) -> str:
    """The human form: alignment, skew table, ranking, attribution."""
    lines = [f"skew report: {doc['run_dir']}", ""]
    ranks = doc.get("ranks", [])
    offsets = doc.get("offsets", {})
    n_ex = max(
        (o.get("n_exchanges", 0) for o in offsets.values()), default=0
    )
    lines.append(
        f"clock alignment: {doc.get('alignment')} "
        f"({len(ranks)} rank(s), {n_ex} exchange(s))"
    )
    for rank in ranks:
        fit = offsets.get(rank, {})
        if fit.get("rank") == fit.get("ref_rank"):
            continue
        lines.append(
            f"  rank {rank}: offset {fit.get('offset_s', 0.0):+.6f}s "
            f"± {fit.get('uncertainty_s', 0.0):.6f}s  "
            f"(drift {fit.get('drift_per_s', 0.0):+.2e}/s over "
            f"{fit.get('n_exchanges', 0)} exchange(s))"
        )
    for rank in doc.get("missing_ranks", []):
        lines.append(f"  rank {rank}: no flight file")

    collectives = doc.get("collectives", [])
    worst = sorted(
        collectives, key=lambda c: -c.get("skew_enter_s", 0.0)
    )[:top]
    lines.append("")
    lines.append(
        f"collectives ({len(collectives)} joined; worst arrival skew "
        f"first, top {len(worst)}):"
    )
    lines.append(
        f"  {'seq':>5} {'site':<22} {'t+s':>9} {'skew_enter':>10} "
        f"{'skew_exit':>9} {'total':>9} {'frac':>6}  straggler"
    )
    for c in worst:
        strag = c.get("straggler_rank", -1)
        lines.append(
            f"  {c['seq']:>5} {c['site']:<22} {_fmt_s(c.get('rel_s'))} "
            f"{_fmt_s(c.get('skew_enter_s'), 10)} "
            f"{_fmt_s(c.get('skew_exit_s'))} {_fmt_s(c.get('total_s'))} "
            f"{c.get('straggler_frac', 0.0):>6.2f}  "
            f"{'rank ' + str(strag) if strag >= 0 else '-'}"
        )

    lines.append("")
    lines.append("per-rank attribution (compute / wire / skew-wait / host):")
    for rank in ranks:
        acc = doc.get("attribution", {}).get(rank, {})
        lines.append(
            f"  rank {rank}: compute {_fmt_s(acc.get('compute_s'))}s  "
            f"wire {_fmt_s(acc.get('wire_s'))}s  "
            f"skew-wait {_fmt_s(acc.get('skew_wait_s'))}s  "
            f"host {_fmt_s(acc.get('host_s'))}s"
        )

    lines.append("")
    lines.append("worst ranks (skew-seconds caused as the last arrival):")
    for entry in doc.get("worst_ranks", []):
        lines.append(
            f"  rank {entry['rank']}: {entry['caused_skew_s']:.4f}s "
            f"across {entry['straggler_count']} collective(s)"
        )
    lines.append("")
    lines.append(f"verdict: {doc.get('headline', '')}")
    return "\n".join(lines)


def render_findings(findings: list) -> str:
    if not findings:
        return "gate: clean — no skew regression against history"
    lines = [f"gate: {len(findings)} skew regression finding(s), worst first:"]
    for f in findings:
        lines.append(
            f"  {f.get('metric')}: {f.get('measured_ms'):.4f} vs baseline "
            f"{f.get('baseline_ms'):.4f} (z={f.get('z'):.1f}, "
            f"x{f.get('ratio'):.2f}) straggler rank "
            f"{f.get('straggler_rank')} — {f.get('implementation')} "
            f"[{f.get('primitive')} {f.get('m')}x{f.get('n')}x{f.get('k')}]"
        )
    return "\n".join(lines)


def gate(history_dir: str, run_id):
    """(current_rows, findings): the named run's banked rows gated by
    ``regress.detect_skew`` against the rest of the history. Default
    run: the latest ``run_id`` in the bank."""
    records = store.load_history(history_dir)
    if run_id is None:
        row_records = [r for r in records if r.get("kind", "row") == "row"]
        run_id = row_records[-1].get("run_id") if row_records else None
    current = [
        r["row"]
        for r in records
        if r.get("run_id") == run_id and r.get("kind", "row") == "row"
    ]
    findings = regress.detect_skew(current, records, exclude_run=run_id)
    return run_id, current, findings


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("run_dir", help="flight-recorder run directory")
    parser.add_argument(
        "--ranks", type=int, default=None,
        help="expected world size (flags ranks that left no file)",
    )
    parser.add_argument(
        "--history", default=None,
        help="observatory history dir: run the skew gate against it",
    )
    parser.add_argument(
        "--run", default=None,
        help="run_id to gate (default: the latest banked run)",
    )
    parser.add_argument(
        "--top", type=int, default=12,
        help="collectives shown in the skew table (worst first)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)

    doc = timeline.build_world_timeline(
        args.run_dir, expected_ranks=args.ranks
    )
    findings = []
    run_id = None
    if args.history:
        run_id, _, findings = gate(args.history, args.run)

    if args.as_json:
        out = {"timeline": doc, "gated_run": run_id, "findings": findings}
        print(json.dumps(timeline.json_safe(out), indent=1, default=str))
    else:
        print(render_text(doc, top=args.top))
        if args.history:
            print()
            print(f"gated run: {run_id}")
            print(render_findings(findings))
    if not doc.get("ranks"):
        return 1
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
