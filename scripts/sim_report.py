#!/usr/bin/env python
"""Static performance simulator report: rank algorithms per topology.

The Big Send-off evaluation loop as a framework feature: for each
collective-bearing family, the flat ring, the HiCCL-style hierarchical
composition, and the multi-path striped composition are replayed on a
synthetic multi-pod topology (``ddlb_tpu.perfmodel.topology``) and
ranked by predicted makespan — before a single chip is booked. A
second section replays the *traced* schedules of real registered
members (the semantic SPMD interpreter's export) at their canonical
world size, with per-member predicted time, overlap fraction, and the
per-link utilization breakdown.

Usage:
    python scripts/sim_report.py [--topology SPEC] [--payload-mib N]
                                 [--families F1,F2] [--json]
    python scripts/sim_report.py --degrade CLASS=FACTOR[,...] [...]
    python scripts/sim_report.py --validate [--history DIR]

``--degrade`` (repeatable) replays the ranking on DEGRADED twins of the
topology (``perfmodel.topology.Degradation``): each spec is a
comma-joined ``class=factor`` list over the link-class resources
(``ici0``..``iciN-1``, ``dcn``), factor 0 meaning the link is down —
``--degrade dcn=0.25 --degrade ici1=0``. Per scenario the report shows
every algorithm's healthy vs degraded makespan, the slowdown ratio, and
the degraded replay's per-link utilization — the table where striping's
reroute around a dead torus axis (dead class at zero bytes, survivors
carrying its share) and its graceful degradation under a failing DCN
link are visible, quantifying FlexLink-style redundancy (arxiv
2510.15882) before any hardware fails for real. Unroutable
compositions (a flat ring through a downed link) report ``unroutable``
and rank last.

``--topology`` defaults to ``DDLB_TPU_TOPOLOGY``
(``envs.get_topology_override``; the benchmark CLI's ``--topology``
exports it) and then to the 1024-chip ``4pod1024`` preset. ``--json``
emits the same structure machine-readably.

``--validate`` runs the two simulator gates instead of the ranking:
float-precision agreement with the ``perfmodel.cost`` closed forms on
degenerate flat topologies for every registered family, and — when a
history bank is given via ``--history`` or ``DDLB_TPU_HISTORY`` — the
tolerance-gated join against banked observatory medians.

``--compare-members`` runs the member-twin gate
(``simulator.validate.member_twin_check``): the REAL topology-adaptive
members (``jax_spmd_hier``/``jax_spmd_striped``, ISSUE 16) trace at the
topology's own axis sizes and their replayed schedules land next to the
synthetic flat/hierarchical/striped builders — makespans within
tolerance (flat/hier are step-for-step identical; striped has its own
documented bar) and rankings agreeing (hier and striped both beat flat
on the multi-pod world). ``make ci`` runs this gate.

Exit codes: 0 success; 1 validation failure (or empty ranking); 2
usage errors (argparse).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_TOPOLOGY = "4pod1024"

#: family -> synthetic-ranking payload op (frontends.FAMILY_COLLECTIVES
#: restated: the ranking set is the explicit-collective families)
RANKED_FAMILIES = (
    "tp_columnwise",
    "tp_rowwise",
    "dp_allreduce",
    "ep_alltoall",
    "collectives",
)

#: traced members replayed in the per-member section: the baseline
#: explicit member and the chunked engine at two pipeline depths
TRACED_MEMBERS = (
    ("tp_columnwise", "jax_spmd", {}),
    ("tp_columnwise", "overlap", {"algorithm": "chunked", "chunk_count": 4}),
    ("tp_rowwise", "jax_spmd", {}),
    ("tp_rowwise", "overlap", {"algorithm": "chunked", "chunk_count": 4}),
    ("dp_allreduce", "jax_spmd", {}),
    ("dp_allreduce", "overlap", {"algorithm": "chunked", "chunk_count": 4}),
    ("ep_alltoall", "jax_spmd", {}),
    ("ep_alltoall", "overlap", {"algorithm": "chunked", "chunk_count": 4}),
    ("pp_pipeline", "schedules", {}),
)


def _fmt_s(seconds):
    if seconds is None or not math.isfinite(seconds):
        return "?"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.3f}ms"
    return f"{seconds * 1e6:.3f}us"


def build_degraded_ranking(topology, payload_bytes, families, degradations):
    """The degraded-world ranking: per scenario, per family, every
    synthetic algorithm replayed on the healthy topology AND its
    degraded twin (the scenario-independent healthy replays are cached
    per family/algo). Non-finite degraded makespans (a composition
    routed through a downed link) become ``routable: False`` with a
    None makespan; ``--json`` additionally passes the document through
    ``timeline.json_safe`` so the inf/NaN the infinite replay leaves in
    the busy/utilization fields never reach a strict parser."""
    from ddlb_tpu.simulator.engine import replay, summarize
    from ddlb_tpu.simulator.frontends import (
        FAMILY_COLLECTIVES,
        SYNTHETIC_ALGOS,
        synthetic_program,
    )

    healthy_cache = {}

    def healthy_makespan(family, algo, op):
        if (family, algo) not in healthy_cache:
            healthy_cache[(family, algo)] = replay(
                synthetic_program(algo, op, payload_bytes, topology),
                topology,
            ).makespan_s
        return healthy_cache[(family, algo)]

    scenarios = []
    for degradation in degradations:
        degraded_topo = topology.degraded(degradation)
        blocks = []
        for family in families:
            op = FAMILY_COLLECTIVES[family]
            rows = []
            for algo in SYNTHETIC_ALGOS:
                healthy_s = healthy_makespan(family, algo, op)
                # built against the DEGRADED topology so reroute-capable
                # compositions lay their stripes over surviving axes
                degraded = replay(
                    synthetic_program(
                        algo, op, payload_bytes, degraded_topo
                    ),
                    degraded_topo,
                )
                row = summarize(degraded, degraded_topo)
                routable = math.isfinite(degraded.makespan_s)
                row.update(
                    algo=algo,
                    healthy_s=healthy_s,
                    degraded_s=degraded.makespan_s if routable else None,
                    routable=routable,
                    slowdown=(
                        degraded.makespan_s / healthy_s
                        if routable and healthy_s > 0
                        else None
                    ),
                )
                if not routable:
                    row["makespan_s"] = None
                rows.append(row)
            rows.sort(
                key=lambda r: (
                    not r["routable"],
                    r["degraded_s"] if r["degraded_s"] is not None else 0.0,
                )
            )
            blocks.append({"family": family, "op": op, "rows": rows})
        scenarios.append(
            {"degradation": degradation.name, "families": blocks}
        )
    return scenarios


def print_degraded(topology, payload_bytes, scenarios):
    for scenario in scenarios:
        print(
            f"== degraded ranking under [{scenario['degradation']}] on "
            f"{topology.describe()} =="
        )
        print(f"   payload {payload_bytes / (1 << 20):.0f} MiB/device\n")
        for block in scenario["families"]:
            print(f"-- {block['family']} ({block['op']}) --")
            print(
                f"{'algo':<14} {'healthy':>12} {'degraded':>12} "
                f"{'slowdown':>9}  degraded link utilization"
            )
            for row in block["rows"]:
                if not row["routable"]:
                    print(
                        f"{row['algo']:<14} "
                        f"{_fmt_s(row['healthy_s']):>12} "
                        f"{'unroutable':>12} {'-':>9}  (routed through a "
                        f"downed link)"
                    )
                    continue
                links = " ".join(
                    f"{name}={info['bytes'] / (1 << 20):.1f}MiB"
                    for name, info in sorted(row["links"].items())
                    if name != "flat" and info["bytes"] > 0
                )
                links = links or "(no surviving-link traffic)"
                print(
                    f"{row['algo']:<14} {_fmt_s(row['healthy_s']):>12} "
                    f"{_fmt_s(row['degraded_s']):>12} "
                    f"{row['slowdown']:>8.2f}x  {links}"
                )
            print()


def build_ranking(topology, payload_bytes, families):
    from ddlb_tpu.simulator.engine import replay, summarize
    from ddlb_tpu.simulator.frontends import (
        FAMILY_COLLECTIVES,
        SYNTHETIC_ALGOS,
        synthetic_program,
    )

    ranking = []
    for family in families:
        op = FAMILY_COLLECTIVES[family]
        rows = []
        for algo in SYNTHETIC_ALGOS:
            program = synthetic_program(algo, op, payload_bytes, topology)
            result = replay(program, topology)
            row = summarize(result, topology)
            row["algo"] = algo
            rows.append(row)
        flat_s = next(
            r["makespan_s"] for r in rows if r["algo"] == "flat"
        )
        for row in rows:
            row["speedup_vs_flat"] = (
                flat_s / row["makespan_s"] if row["makespan_s"] > 0 else None
            )
        rows.sort(key=lambda r: r["makespan_s"])
        ranking.append({"family": family, "op": op, "rows": rows})
    return ranking


def build_member_section(members):
    from ddlb_tpu.analysis.core import repo_root
    from ddlb_tpu.analysis.spmd.families import ClassRegistry, member_schedule
    from ddlb_tpu.perfmodel.topology import flat_topology
    from ddlb_tpu.simulator.engine import replay, summarize
    from ddlb_tpu.simulator.frontends import (
        ProgramBuildError,
        program_from_schedule,
    )

    # one registry for the whole section: the members share most of
    # their statically-parsed module/base-class graph
    registry = ClassRegistry(repo_root())
    out = []
    for family, member, overrides in members:
        export = member_schedule(family, member, overrides, registry=registry)
        label = f"{family}/{member}" + (
            "[" + ",".join(f"{k}={v}" for k, v in sorted(overrides.items()))
            + "]"
            if overrides
            else ""
        )
        record = {
            "member": label,
            "trace_status": export["status"],
            "entries": len(export["entries"]),
        }
        topo = flat_topology(export["partitions"], "v5e")
        try:
            result = replay(program_from_schedule(export, topo), topo)
        except ProgramBuildError as exc:
            record["error"] = str(exc)
            out.append(record)
            continue
        record.update(summarize(result, topo))
        out.append(record)
    return out


def print_compare_members(summary):
    print(
        f"== real members vs synthetic twins on {summary['topology']} =="
    )
    print(
        f"{'family':<14} {'member':<18} {'composition':<13} "
        f"{'traced':>12} {'synthetic':>12} {'rel':>7} {'bar':>5}"
    )
    for rec in summary["records"]:
        print(
            f"{rec['family']:<14} {rec['member']:<18} "
            f"{rec['composition']:<13} {_fmt_s(rec['traced_s']):>12} "
            f"{_fmt_s(rec['synthetic_s']):>12} {rec['rel_err']:>7.3f} "
            f"{rec['rtol']:>5.2f}"
            + ("" if rec["ok"] else "  FAIL")
        )
    for failure in summary["failures"]:
        print(f"  FAIL {failure}")
    print(
        "MEMBER-TWIN " + ("PASSED" if summary["ok"] else "FAILED")
    )


def run_validation(history_dir):
    from ddlb_tpu.perfmodel import calib
    from ddlb_tpu.simulator.validate import (
        calibration_check,
        closed_form_check,
        history_check,
    )

    closed = closed_form_check()
    summary = {
        "closed_form": {
            "checked": len(closed),
            "failures": [r for r in closed if not r["ok"]],
            "max_rel_err": max((r["rel_err"] for r in closed), default=0.0),
        }
    }
    if history_dir:
        summary["history"] = history_check(history_dir)
        # Gate (3) only binds when a calibration table is active
        # (DDLB_TPU_CALIB); an uncalibrated world is judged by the
        # lower-bound gates alone rather than auto-failing --validate.
        if calib.get_table() is not None:
            summary["calibration"] = calibration_check(history_dir)
    return summary


def print_ranking(topology, payload_bytes, ranking):
    print(f"== simulated algorithm ranking on {topology.describe()} ==")
    print(f"   payload {payload_bytes / (1 << 20):.0f} MiB/device\n")
    for block in ranking:
        print(f"-- {block['family']} ({block['op']}) --")
        print(f"{'algo':<14} {'predicted':>12} {'vs flat':>8}  link busy fractions")
        for row in block["rows"]:
            links = " ".join(
                f"{name}={info['busy_frac']:.2f}"
                for name, info in sorted(row["links"].items())
                if info["busy_frac"] > 0
            )
            speed = row["speedup_vs_flat"]
            print(
                f"{row['algo']:<14} {_fmt_s(row['makespan_s']):>12} "
                f"{(f'{speed:.2f}x' if speed else '?'):>8}  {links}"
            )
        print()


def print_members(members):
    print("== traced member replays (canonical shapes, flat v5e world) ==")
    print(
        f"{'member':<52} {'trace':>9} {'steps':>6} {'predicted':>12} "
        f"{'ovl':>6}"
    )
    for rec in members:
        if "error" in rec:
            print(f"{rec['member']:<52} {rec['trace_status']:>9} "
                  f"-- {rec['error']}")
            continue
        ovl = rec.get("overlap_frac")
        print(
            f"{rec['member']:<52} {rec['trace_status']:>9} "
            f"{rec['events']:>6} {_fmt_s(rec['makespan_s']):>12} "
            f"{(f'{ovl:.2f}' if ovl is not None else 'nan'):>6}"
        )
    print()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--topology", default=None,
        help=f"topology spec or preset (default: DDLB_TPU_TOPOLOGY, then "
        f"{DEFAULT_TOPOLOGY})",
    )
    parser.add_argument(
        "--payload-mib", type=float, default=64.0,
        help="per-device collective payload for the ranking (MiB)",
    )
    parser.add_argument(
        "--families", default=None,
        help="comma-separated subset of the ranked families",
    )
    parser.add_argument(
        "--no-members", action="store_true",
        help="skip the traced per-member section (ranking only)",
    )
    parser.add_argument(
        "--degrade", action="append", default=None, metavar="SPEC",
        help="degradation scenario 'class=factor[,...]' (factor 0 = link "
        "down), repeatable — replays the ranking on the degraded twin "
        "of the topology next to the healthy one",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument(
        "--validate", action="store_true",
        help="run the closed-form + history validation gates instead "
        "(plus the calibration gate when DDLB_TPU_CALIB is set)",
    )
    parser.add_argument(
        "--compare-members", action="store_true",
        help="replay the real topology-adaptive members' traced "
        "schedules next to the synthetic flat/hier/striped builders and "
        "gate on tolerance + ranking agreement (member_twin_check)",
    )
    parser.add_argument(
        "--history", default=None,
        help="observatory history directory for the validation join "
        "(default: DDLB_TPU_HISTORY)",
    )
    args = parser.parse_args(argv)

    from ddlb_tpu import envs
    from ddlb_tpu.perfmodel.topology import resolve_topology

    spec = args.topology or envs.get_topology_override() or DEFAULT_TOPOLOGY
    try:
        topology = resolve_topology(spec)
    except (KeyError, ValueError) as exc:
        parser.error(f"bad --topology {spec!r}: {exc}")

    if args.compare_members:
        from ddlb_tpu.simulator.validate import member_twin_check

        summary = member_twin_check(topology=spec)
        if args.as_json:
            print(json.dumps(summary, indent=2))
        else:
            print_compare_members(summary)
        return 0 if summary["ok"] else 1

    if args.validate:
        history_dir = args.history or envs.get_history_dir() or None
        summary = run_validation(history_dir)
        ok = (
            not summary["closed_form"]["failures"]
            and ("history" not in summary or summary["history"]["ok"])
            and (
                "calibration" not in summary
                or summary["calibration"]["ok"]
            )
        )
        if args.as_json:
            print(json.dumps({"validation": summary, "ok": ok}, indent=2))
        else:
            cf = summary["closed_form"]
            print(
                f"closed-form agreement: {cf['checked']} configs, "
                f"{len(cf['failures'])} failures, max rel err "
                f"{cf['max_rel_err']:.2e}"
            )
            for failure in cf["failures"]:
                print(f"  FAIL {failure}")
            if "history" in summary:
                h = summary["history"]
                print(
                    f"history join: {h['checked']} keys checked, "
                    f"{h['skipped']} skipped, {len(h['violations'])} "
                    f"violations (rtol={h['rtol']}, "
                    f"lb_slack={h['lower_bound_slack']})"
                )
                for violation in h["violations"]:
                    print(f"  FAIL {violation}")
            if "calibration" in summary:
                c = summary["calibration"]
                print(
                    f"calibration join: {c['checked']} keys checked, "
                    f"{c['skipped']} skipped, {len(c['violations'])} "
                    f"violations (rtol={c['rtol']}, "
                    f"table {c['table_version'] or 'none'})"
                )
                for violation in c["violations"]:
                    print(f"  FAIL {violation}")
            print("VALIDATION " + ("PASSED" if ok else "FAILED"))
        return 0 if ok else 1

    families = RANKED_FAMILIES
    if args.families:
        wanted = [f.strip() for f in args.families.split(",") if f.strip()]
        unknown = [f for f in wanted if f not in RANKED_FAMILIES]
        if unknown:
            parser.error(
                f"unknown families {unknown}; ranked: {RANKED_FAMILIES}"
            )
        families = tuple(wanted)

    from ddlb_tpu.perfmodel.topology import parse_degradation

    degradations = []
    for spec_text in args.degrade or ():
        try:
            degradations.append(parse_degradation(spec_text))
        except ValueError as exc:
            parser.error(str(exc))

    payload = args.payload_mib * (1 << 20)
    if degradations:
        # degraded mode: the failure-scenario ranking replaces the
        # healthy ranking + member sections (healthy numbers ride along
        # per row as the slowdown baseline)
        scenarios = build_degraded_ranking(
            topology, payload, families, degradations
        )
        if not scenarios:
            print("nothing to rank", file=sys.stderr)
            return 1
        if args.as_json:
            from ddlb_tpu.observatory.timeline import json_safe

            print(
                json.dumps(
                    json_safe(
                        {
                            "topology": {
                                "spec": topology.name,
                                "chip": topology.chip.name,
                                "pods": topology.pods,
                                "ici_mesh": list(topology.ici_mesh),
                                "chips": topology.num_chips,
                            },
                            "payload_bytes": payload,
                            "degraded": scenarios,
                        }
                    ),
                    indent=2,
                )
            )
            return 0
        print_degraded(topology, payload, scenarios)
        return 0

    ranking = build_ranking(topology, payload, families)
    members = [] if args.no_members else build_member_section(TRACED_MEMBERS)
    if not ranking:
        print("nothing to rank", file=sys.stderr)
        return 1

    if args.as_json:
        print(
            json.dumps(
                {
                    "topology": {
                        "spec": topology.name,
                        "chip": topology.chip.name,
                        "pods": topology.pods,
                        "ici_mesh": list(topology.ici_mesh),
                        "chips": topology.num_chips,
                    },
                    "payload_bytes": payload,
                    "ranking": ranking,
                    "members": members,
                },
                indent=2,
            )
        )
        return 0
    print_ranking(topology, payload, ranking)
    if members:
        print_members(members)
    return 0


if __name__ == "__main__":
    sys.exit(main())
