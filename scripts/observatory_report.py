#!/usr/bin/env python
"""Regression report: the current run against the run-history bank.

The observatory's detector CLI (ISSUE 6): reads the history
``DDLB_TPU_HISTORY`` (or ``--history DIR``) that every runner path
banks into, picks the CURRENT run — the latest banked ``run_id`` by
default, an explicit ``--run ID``, or a sweep CSV via ``--current`` —
and flags rows that got slower than their per-key history:

- **history-backed findings**: measured median vs the key's history
  median, scaled by the MAD (robust to relay outliers; the MAD is
  floored at 5% of the median so a microsecond-tight history cannot
  turn jitter into a finding). Ranked by robust z, worst first.
- **prior-only advisories**: keys with NO history fall back to the
  perfmodel prior — a row measuring more than ``--prior-factor`` (5x)
  its own analytical lower bound is flagged, ranked after every
  history-backed finding (a lower bound is a weaker baseline than a
  measured median).

Exit code: 0 clean, 1 when regressions were found, 2 usage — so a
capture wrapper can gate on it (bench.py's roofline gate uses the same
library layer directly and stays soft by its own contract).

Usage: python scripts/observatory_report.py [--history DIR]
           [--current CSV | --run RUN_ID] [--json] [--top N]
           [--z-tol F] [--min-excess F] [--prior-factor F]
"""

from __future__ import annotations

import csv
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddlb_tpu.observatory import regress, store  # noqa: E402

#: identity columns that must compare as ints between a CSV (strings)
#: and banked rows (numbers) — key equality depends on it
_INT_COLUMNS = ("m", "n", "k", "world_size")


def _coerce(row):
    """Normalize one CSV row so its history key matches banked rows."""
    out = dict(row)
    for col in _INT_COLUMNS:
        try:
            out[col] = int(float(out[col]))
        except (KeyError, TypeError, ValueError):
            pass
    return out


def load_current(records, args):
    """(current_rows, run_label, exclude_run) per the CLI selection."""
    if args.get("current"):
        path = args["current"]
        with open(path, newline="", encoding="utf-8") as f:
            rows = [_coerce(r) for r in csv.DictReader(f)]
        return rows, f"CSV {path}", None
    run_ids = [r.get("run_id") for r in records if r.get("kind") == "row"]
    run = args.get("run") or (run_ids[-1] if run_ids else None)
    if run is None:
        return [], "(no runs banked)", None
    rows = [
        r["row"]
        for r in records
        if r.get("run_id") == run and r.get("kind") == "row"
    ]
    return rows, f"run {run}", run


def _drop_self_banked(records, current_rows):
    """Drop history records that are the CURRENT rows' own banked
    copies: a sweep run with DDLB_TPU_HISTORY set banks every row it
    writes to its CSV, so ``--current CSV`` would otherwise baseline
    against itself (identical key AND identical measured median — an
    exact self-match, so independent runs are never dropped)."""
    own = set()
    for row in current_rows:
        value = regress.finite(row.get(regress.MEASURE_COLUMN))
        if value is not None:
            own.add((regress.row_key(row), round(value, 9)))
    if not own:
        return records
    kept = []
    for record in records:
        row = record.get("row") or {}
        value = regress.finite(row.get(regress.MEASURE_COLUMN))
        key = record.get("key") or regress.row_key(row)
        if value is not None and (key, round(value, 9)) in own:
            continue
        kept.append(record)
    return kept


def build_report(history_dir, args):
    records = store.load_history(history_dir)
    current, label, exclude = load_current(records, args)
    banked_total = len(records)
    if args.get("current"):
        records = _drop_self_banked(records, current)
    self_excluded = banked_total - len(records)
    # the full gate: median time + every serving SLO percentile/goodput
    # column, one ranked list (regress.detect_all, ISSUE 11)
    findings = regress.detect_all(
        current,
        records,
        exclude_run=exclude,
        z_tol=float(args.get("z_tol", regress.Z_TOL)),
        min_excess=float(args.get("min_excess", regress.MIN_EXCESS)),
        prior_factor=float(args.get("prior_factor", regress.PRIOR_FACTOR)),
    )
    runs = {r.get("run_id") for r in records if r.get("kind") == "row"}
    return {
        "history_dir": os.path.abspath(history_dir) if history_dir else "",
        "history_records": banked_total,
        "history_baseline_records": len(records),
        "self_excluded": self_excluded,
        "history_runs": len(runs),
        "current": label,
        "current_rows": len(current),
        "measured_rows": sum(
            1
            for r in current
            if regress.finite(r.get(regress.MEASURE_COLUMN)) is not None
        ),
        "findings": findings,
    }


def print_report(report, top_n):
    print(
        f"observatory report — history {report['history_dir'] or '(unset)'}"
    )
    print(
        f"  {report['history_records']} banked rows across "
        f"{report['history_runs']} run(s); current = {report['current']} "
        f"({report['measured_rows']}/{report['current_rows']} rows "
        f"measured)"
    )
    if report.get("self_excluded"):
        print(
            f"  {report['self_excluded']} banked copy(ies) of the "
            f"current CSV's own rows excluded from the baseline"
        )
    findings = report["findings"]
    if not findings:
        print("  no regressions detected")
        return
    print(f"\n{len(findings)} regression(s), worst first:")
    print(
        f"  {'#':>2} {'impl':<18} {'shape':<13} "
        f"{'metric':<16} {'measured':>10} "
        f"{'baseline':>10} {'ratio':>6} {'z':>7}  source"
    )
    for i, f in enumerate(findings[:top_n], 1):
        shape = f"{f.get('m')}x{f.get('n')}x{f.get('k')}"
        z = f.get("z")
        z_txt = f"{z:7.1f}" if isinstance(z, float) and z == z else "      -"
        metric = str(f.get("metric") or regress.MEASURE_COLUMN)
        metric = metric.replace("median time (ms)", "median_ms")
        print(
            f"  {i:>2} {str(f.get('implementation'))[:18]:<18} "
            f"{shape:<13} {metric[:16]:<16} {f['measured_ms']:>9.3f}  "
            f"{f['baseline_ms']:>9.3f} {f['ratio']:>5.2f}x "
            f"{z_txt}  {f['source']}"
        )
    if len(findings) > top_n:
        print(f"  ... and {len(findings) - top_n} more (--top)")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]

    def _opt(flag, default=None):
        if flag in argv:
            i = argv.index(flag)
            if i + 1 >= len(argv):
                raise SystemExit(f"observatory_report: {flag} needs a value")
            value = argv[i + 1]
            del argv[i: i + 2]
            return value
        return default

    args = {
        "current": _opt("--current"),
        "run": _opt("--run"),
        "z_tol": _opt("--z-tol", regress.Z_TOL),
        "min_excess": _opt("--min-excess", regress.MIN_EXCESS),
        "prior_factor": _opt("--prior-factor", regress.PRIOR_FACTOR),
    }
    top_n = int(_opt("--top", "20"))
    history_dir = _opt("--history") or os.environ.get(
        "DDLB_TPU_HISTORY", ""
    ).strip()
    if argv:
        print(f"observatory_report: unknown argument(s): {argv}")
        return 2
    if not history_dir:
        print(
            "observatory_report: no history bank — pass --history DIR or "
            "set DDLB_TPU_HISTORY (runs bank automatically when it is set)"
        )
        return 2
    report = build_report(history_dir, args)
    if as_json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print_report(report, top_n)
    return 1 if report["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
