#!/usr/bin/env python
"""Perf-observatory demo: history, regression report, live dashboard.

The executable acceptance evidence for ISSUE 6, banked at
``docs/observatory_demo.log``. Everything runs on the CPU sim with the
SHIPPED ``scripts/config.json`` implementation matrix at a small shape
(the pool_demo protocol), so it is reproducible anywhere:

1. **Two banked baseline runs**: the pooled sweep runs twice with
   ``DDLB_TPU_HISTORY`` set — every row auto-banks into
   ``history.jsonl`` keyed by chip + impl + config + git rev. The FIRST
   pass also runs with ``DDLB_TPU_LIVE`` set AND the
   ``scripts/sweep_dash.py`` dashboard attached as a live tail
   (separate read-only process), and its per-row medians are compared
   against the SECOND pass (dashboard off): the timing deltas must be
   within CPU-sim noise — the dashboard observes without perturbing.
2. **A seeded regression**: the current run is banked as a copy of
   pass 2's rows with ONE implementation's measured times multiplied by
   3 (synthetic by design — the detector is what's under test, and a
   real slowdown of exactly known size cannot be injected honestly).
3. **Detection**: ``scripts/observatory_report.py`` compares the
   seeded run against the two banked baselines — the seeded row must be
   detected AND ranked first.
4. **Dashboard artifacts**: the final live-stream state is rendered as
   a text frame and as the static ``--html`` snapshot
   (``hwlogs/observatory_dash.html``).

Usage: python scripts/observatory_demo.py [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# simulated mesh, set before anything touches JAX (children inherit)
os.environ.setdefault("DDLB_TPU_SIM_DEVICES", "8")

M, N, K = 128, 64, 64  # small: every impl in config.json accepts it
SEED_FACTOR = 3.0


def seeded_impl(impl_map) -> str:
    """The impl the demo slows down: the matrix's last overlap member
    (the family whose regressions the observatory exists to catch)."""
    overlap = [i for i in impl_map if i.startswith("overlap")]
    return overlap[-1] if overlap else sorted(impl_map)[-1]


def load_impl_map() -> dict:
    from ddlb_tpu.cli.benchmark import (
        assign_impl_ids,
        generate_config_combinations,
    )

    with open(os.path.join(REPO, "scripts", "config.json")) as f:
        cfg = json.load(f)["benchmark"]
    return assign_impl_ids(generate_config_combinations(cfg["implementations"]))


def run_pass(impl_map, label):
    """One pooled subprocess-isolation sweep; returns (wall_s, df)."""
    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner

    print(f"\n==== {label} ({len(impl_map)} configs, pooled) ====",
          flush=True)
    runner = PrimitiveBenchmarkRunner(
        "tp_columnwise", m=M, n=N, k=K,
        implementations=impl_map,
        dtype="float32", num_iterations=30, num_warmups=3,
        validate=False, isolation="subprocess", progress=False,
        worker_pool=True,
        # one aggregate timing window per row (sync, N back-to-back
        # calls, sync): the jitter-resistant protocol on a contended
        # CPU sim, where per-iteration 8-way barriers amplify
        # scheduler noise far above any observer effect
        barrier_at_each_iteration=False,
    )
    t0 = time.monotonic()
    df = runner.run()
    wall = time.monotonic() - t0
    errors = int((df["error"].astype(str) != "").sum())
    print(f"{label}: {len(df)} rows in {wall:.1f}s, {errors} error(s)",
          flush=True)
    return wall, df


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out-dir", default=os.path.join(REPO, "hwlogs"),
        help="where the HTML snapshot lands",
    )
    args = parser.parse_args(argv)
    os.makedirs(args.out_dir, exist_ok=True)

    import tempfile

    workdir = tempfile.mkdtemp(prefix="observatory_demo_")
    hist_dir = os.path.join(workdir, "history")
    live_path = os.path.join(workdir, "live.jsonl")
    impl_map = load_impl_map()
    failures = []

    def check(ok, what):
        print(f"  {'PASS' if ok else 'FAIL'}  {what}", flush=True)
        if not ok:
            failures.append(what)

    # -- pass 0: unbanked warmup AND the noise reference: two
    # dashboard-off passes (this and pass 2) bound the machine's own
    # pass-to-pass jitter, which the attached pass is judged against ---
    _, df_ref = run_pass(impl_map, "pass 0: warmup / noise reference")

    # -- pass 1: dashboard ON (live stream + a real attached tail) ----------
    os.environ["DDLB_TPU_HISTORY"] = hist_dir
    os.environ["DDLB_TPU_RUN_ID"] = "baseline-1"
    os.environ["DDLB_TPU_LIVE"] = live_path
    open(live_path, "w").close()
    dash = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "sweep_dash.py"),
         live_path, "--interval", "0.5"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    time.sleep(2.0)  # let the tail's interpreter start before measuring
    try:
        wall_on, df_on = run_pass(impl_map, "pass 1: dashboard ATTACHED")
    finally:
        try:
            dash.wait(timeout=30)  # exits on sweep_done in piped mode
        except subprocess.TimeoutExpired:
            dash.kill()
    print(f"dashboard process exited rc={dash.returncode}", flush=True)

    # -- pass 2: dashboard OFF ----------------------------------------------
    os.environ["DDLB_TPU_RUN_ID"] = "baseline-2"
    os.environ.pop("DDLB_TPU_LIVE")
    wall_off, df_off = run_pass(impl_map, "pass 2: dashboard off")

    # -- dashboard perturbation check ---------------------------------------
    import math

    med_ref = df_ref.set_index("implementation")["median time (ms)"]
    med_on = df_on.set_index("implementation")["median time (ms)"]
    med_off = df_off.set_index("implementation")["median time (ms)"]
    # the MEDIAN of per-row ratios, not the sum: a real observer
    # overhead would shift every row systematically, while one row's
    # scheduler hiccup (routine on a shared CPU sim) dominates a sum
    agg = float((med_on / med_off).median())
    noise = float((med_ref / med_off).median())  # two dashboard-OFF passes
    print(
        f"\n== dashboard perturbation check ==\n"
        f"median per-row ratio: attached/off {agg:.3f} "
        f"(rows [{(med_on / med_off).min():.2f}, "
        f"{(med_on / med_off).max():.2f}])\n"
        f"machine noise reference (two dashboard-off passes): "
        f"median per-row ratio {noise:.3f}",
        flush=True,
    )
    # within noise = the attached pass's systematic shift is no more
    # than 1.5x what the machine shows between two dashboard-OFF
    # passes, floored at 25% absolute (this container's CPU-sim medians
    # routinely move that much between identical passes — the printed
    # reference ratio documents the machine's noise in every banked log)
    bound = max(1.5 * abs(math.log(noise)), math.log(1.25))
    check(
        abs(math.log(agg)) <= bound,
        f"timing deltas vs dashboard-off within noise "
        f"(|log median ratio| {abs(math.log(agg)):.3f} <= bound "
        f"{bound:.3f})",
    )

    # -- seeded regression run ----------------------------------------------
    from ddlb_tpu.observatory import store

    seed_impl = seeded_impl(impl_map)

    print(
        f"\n== seeding a regression: {seed_impl} x{SEED_FACTOR:.0f} "
        f"slower, banked as run 'seeded-3' ==",
        flush=True,
    )
    seeded_rows = 0
    for _, row in df_off.iterrows():
        banked = dict(row)
        if banked["implementation"] == seed_impl:
            for col in banked:
                if col.endswith("time (ms)"):
                    banked[col] = float(banked[col]) * SEED_FACTOR
            seeded_rows += 1
        store.bank_row(banked, run="seeded-3")
    check(seeded_rows == 1, f"seeded exactly one impl ({seed_impl})")

    # -- detection ----------------------------------------------------------
    print("\n== observatory_report.py on the seeded run ==", flush=True)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "observatory_report.py"),
         "--history", hist_dir, "--run", "seeded-3"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    sys.stdout.write(out.stdout)
    check(out.returncode == 1, "report exits 1 (regressions found)")
    ranked_first = [
        line for line in out.stdout.splitlines()
        if f" {seed_impl} " in f" {line} " and line.lstrip().startswith("1 ")
    ]
    check(bool(ranked_first),
          f"seeded slowdown ({seed_impl}) detected and ranked FIRST")
    n_found = [
        int(line.split()[0])
        for line in out.stdout.splitlines()
        if line.strip().endswith("regression(s), worst first:")
    ]
    check(n_found == [1], "no false positives among the unseeded rows")

    # -- dashboard artifacts -------------------------------------------------
    print("\n== final dashboard frame (sweep_dash.py --once) ==", flush=True)
    subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "sweep_dash.py"),
         live_path, "--once"],
        timeout=120, cwd=REPO,
    )
    snap = os.path.join(args.out_dir, "observatory_dash.html")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "sweep_dash.py"),
         live_path, "--html", snap],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    sys.stdout.write(out.stdout)
    check(
        out.returncode == 0 and os.path.getsize(snap) > 500,
        f"static HTML snapshot banked at {os.path.relpath(snap, REPO)}",
    )
    hist_records = len(store.load_history(hist_dir))
    print(
        f"\nhistory bank: {hist_records} rows across 3 runs "
        f"({len(impl_map)} configs x 2 baselines + 1 seeded)",
        flush=True,
    )

    if failures:
        print(f"\nobservatory_demo: {len(failures)} check(s) FAILED")
        return 1
    print("\nobservatory_demo: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
