#!/usr/bin/env python
"""Degraded-world chaos battery: detect -> indict -> mitigate, end to end.

The executable acceptance evidence for ISSUE 15, banked at
``docs/chaos_degrade_demo.log`` (``make chaos-degrade``). Where
``chaos_launch.py`` proves the world survives a rank that *dies*, this
battery proves it survives a rank that *limps* — the degraded-component
failure shape (one slow ICI link dragging every collective) that The
Big Send-off names as the dominant reliability problem at multi-pod
scale. Everything runs in REAL launched 3-process CPU-sim worlds (a
``jax.distributed`` rendezvous, cross-process collectives):

1. **Two clean worlds, banked, health-gated**: a 3-row sweep per world
   under ``--supervise`` semantics with the health gate ON — the
   per-key skew baselines bank, and the gate must indict NOTHING
   (zero false indictments on clean hardware).
2. **A seeded 4x link_slow**: the fault plan degrades the ICI link
   ``ici[1->2]`` to ``factor=0.25`` of its (simulated) rate — the
   affected rank 1 sleeps the deterministic payload-proportional extra
   time ``cost.link_slow_extra_s`` prices at every
   ``runtime.collective`` crossing. Nothing crashes; the world limps.
3. **Detection**: the observatory skew gate (``regress.detect_skew``
   against the clean baselines) fires on the seeded run and names
   rank 1.
4. **Indictment**: ``scripts/health_report.py`` folds the banked rows
   into a persistent-straggler verdict — rank 1, with the seeded link
   among the candidate hardware — and exits 1.
5. **Mitigation**: the supervised launcher's health gate reaches the
   same verdict from the attempt's own clock-aligned timeline and
   relaunches DEGRADED: the world shrinks around physical slot 1
   (survivors keep their slot ids via ``DDLB_TPU_PHYS_RANK``, so the
   seeded fault — keyed on the slot — cannot follow them), the sweep
   re-runs clean, and every config's final CSV row is measured and
   valid with ``world_degraded`` stamped: zero rows lost.
6. **Model closure**: the simulator's degraded-topology replay
   (``Degradation`` overlay, the same ``link_slow_extra_s`` wire
   formula) predicts the per-collective slowdown for the same fault,
   and the measured per-row arrival skew must fall within tolerance of
   it — the injection, the perfmodel and the simulator priced one
   closed form, and the measurement confirms it.
7. **Topology-adaptive re-run** (ISSUE 16): a second seeded world runs
   the REAL composed dp_allreduce member with ``composition=auto``.
   ``primitives.topo_compose.select_composition`` must pick
   ``striped`` on BOTH attempts — from the seeded fault plan on the
   full world, then from the ``DDLB_TPU_WORLD_DEGRADED`` stamp on the
   degraded relaunch — with zero rows lost and the resolved choice
   stamped on every row via the ``composition`` schema column (the
   same healthy parent process resolves ``auto`` -> ``flat``, the
   zero-false-positive side).

Usage: python scripts/chaos_degrade.py [--seed 0] [--keep DIR]
           [--log FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
from dataclasses import replace as dc_replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROCESSES = 3
DEVICES_PER_PROCESS = 2
#: tiny shapes: the battery tests the loop, not speed. M must divide
#: by the FULL world's partitions (3 procs x 2 devices = 6) AND the
#: shrunken world's (2 x 2 = 4) — the degraded relaunch re-runs the
#: same sweep on fewer chips
M, N, K = 96, 32, 48
ITERATIONS = 4         # barriered iterations = clock-sync exchanges
IMPLS = ("jax_spmd", "xla_gspmd", "compute_only")  # 3 rows = 3 observations

#: step 7's workload: the composed dp_allreduce member with the runtime
#: composition policy under test, a pinned-striped control, and the
#: family's flat baseline — still 3 rows, so the launcher's health gate
#: clears its MIN_OBSERVATIONS floor. M=96 divides the striped scatter
#: pieces on the full world (stripes 2 x intra 6 = 12) AND the shrunken
#: one (2 x 4 = 8)
AUTO_IMPLS = (
    "jax_spmd_hier;composition=auto",
    "jax_spmd_hier;composition=striped",
    "jax_spmd",
)

#: the seeded degradation: link ici[1->2] surviving at quarter rate.
#: SIM_LINK_GBS is the simulated healthy link rate the CPU-sim
#: realization prices against (the host never moves bytes at ICI
#: speeds) — chosen so the per-collective extra delay lands ~0.4s:
#: payload = ITERATIONS * 8 * PROCESSES = 96 B, extra = 96B * (1/0.25
#: - 1) / 720 B/s = 0.4s.
FACTOR = 0.25
LINK_INDEX = 1          # degrades rank 1 (direction tx)
SIM_LINK_GBS = 7.2e-7   # 720 B/s
PAYLOAD_BYTES = ITERATIONS * 8 * PROCESSES

#: measured-vs-predicted bracket: the injected sleep is a floor (the
#: scheduler can only add), unrelated barrier jitter rides along
BRACKET_LO, BRACKET_HI = 0.7, 3.5


class _Tee:
    """Mirror stdout into the banked demo log, minus the launched
    children's raw ``[p<rank>]`` lines (console keeps them; the banked
    transcript keeps the curated narrative)."""

    def __init__(self, path):
        self._file = open(path, "w", encoding="utf-8")
        self._stdout = sys.stdout
        self._eat_newline = False

    def write(self, data):
        self._stdout.write(data)
        for line in data.splitlines(keepends=True):
            if line.lstrip().startswith("[p"):
                self._eat_newline = not line.endswith("\n")
                continue
            if self._eat_newline and line.strip() == "":
                self._eat_newline = False
                continue
            self._file.write(line)
            self._eat_newline = False

    def flush(self):
        self._stdout.flush()
        self._file.flush()

    def close(self):
        self._file.close()


def child_command(csv: str, primitive="tp_columnwise", impls=IMPLS) -> list:
    """The world's workload: a 3-impl sweep through the real benchmark
    CLI — every row crosses ``runtime.collective`` once (the timing
    MAX-reduce), so each row is one straggler observation."""
    cmd = [
        sys.executable, "-m", "ddlb_tpu.cli.benchmark",
        "--primitive", primitive,
    ]
    for impl in impls:
        cmd += ["--impl", impl]
    cmd += [
        "-m", str(M), "-n", str(N), "-k", str(K),
        "--dtype", "float32",
        "--num-iterations", str(ITERATIONS), "--num-warmups", "1",
        "--csv", csv,
    ]
    return cmd


def build_plan(seed: int) -> dict:
    """The seeded degraded link: persistent (fail_attempts high — a bad
    link does not heal on a relaunch; only EXCLUDING its rank dodges
    it, which is exactly what the battery must prove)."""
    return {
        "seed": seed,
        "rules": [
            {
                "site": "runtime.collective",
                "kind": "link_slow",
                "topo": {
                    "axis": "ici",
                    "index": LINK_INDEX,
                    "direction": "tx",
                    "factor": FACTOR,
                },
                "sim_link_gbs": SIM_LINK_GBS,
                "fail_attempts": 99,
            }
        ],
    }


def run_world(
    name, base, history, plan=None, health_gate=True, world_retries=2,
    primitive="tp_columnwise", impls=IMPLS,
):
    """Launch one supervised 3-rank world; returns (rc, run_dir)."""
    from ddlb_tpu.cli.launch import launch_supervised

    run_dir = os.path.join(base, name)
    os.makedirs(run_dir, exist_ok=True)
    saved = {
        k: os.environ.get(k)
        for k in ("DDLB_TPU_HISTORY", "DDLB_TPU_RUN_ID",
                  "DDLB_TPU_FAULT_PLAN")
    }
    os.environ["DDLB_TPU_HISTORY"] = history
    os.environ["DDLB_TPU_RUN_ID"] = name
    if plan is not None:
        os.environ["DDLB_TPU_FAULT_PLAN"] = json.dumps(plan)
    else:
        os.environ.pop("DDLB_TPU_FAULT_PLAN", None)
    print(f"-- launching world '{name}' ({PROCESSES} ranks x "
          f"{DEVICES_PER_PROCESS} devices, health gate "
          f"{'on' if health_gate else 'off'})", flush=True)
    try:
        rc = launch_supervised(
            child_command(
                os.path.join(run_dir, "rows.csv"),
                primitive=primitive, impls=impls,
            ),
            processes=PROCESSES,
            devices_per_process=DEVICES_PER_PROCESS,
            silence_timeout=120.0,
            world_retries=world_retries,
            relaunch_backoff_s=0.2,
            run_dir=run_dir,
            health_gate=health_gate,
        )
    finally:
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
    print(f"-- world '{name}' exited rc={rc}", flush=True)
    return rc, run_dir


def predicted_extra_s() -> float:
    """The simulator's degraded-topology prediction for the seeded
    fault: one ``runtime.collective`` payload crossing the degraded
    link, replayed on the healthy world and its ``Degradation`` twin —
    priced with the SAME simulated link rate the injection used. Also
    pins the replay to the closed form (``link_slow_extra_s``) at float
    precision: the degraded analogue of the healthy closed-form gate."""
    from ddlb_tpu.perfmodel.cost import link_slow_extra_s
    from ddlb_tpu.perfmodel.specs import get_spec
    from ddlb_tpu.perfmodel.topology import Degradation, Topology
    from ddlb_tpu.simulator.engine import replay
    from ddlb_tpu.simulator.frontends import flat_ring_program

    spec = dc_replace(
        get_spec("cpu-sim"), name="sim-link",
        ici_bw_gbs=SIM_LINK_GBS, aliases=(),
    )
    topo = Topology(chip=spec, pods=1, ici_mesh=(PROCESSES,))
    degraded = topo.degraded(Degradation(factors={"ici0": FACTOR}))
    healthy_s = replay(
        flat_ring_program("ppermute", PAYLOAD_BYTES, topo), topo
    ).makespan_s
    degraded_s = replay(
        flat_ring_program("ppermute", PAYLOAD_BYTES, degraded), degraded
    ).makespan_s
    extra = degraded_s - healthy_s
    closed = link_slow_extra_s(
        PAYLOAD_BYTES, SIM_LINK_GBS * 1e9, FACTOR
    )
    if abs(extra - closed) > 1e-9 * max(closed, 1.0):
        raise SystemExit(
            f"degraded replay ({extra}) disagrees with the closed form "
            f"({closed}) — the Degradation overlay drifted from "
            f"cost.link_slow_extra_s"
        )
    return extra


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--keep", default=None, metavar="DIR",
        help="keep run dirs under DIR instead of a deleted temp dir",
    )
    parser.add_argument(
        "--log", default=os.path.join(REPO, "docs", "chaos_degrade_demo.log")
    )
    args = parser.parse_args(argv)

    tee = _Tee(args.log)
    sys.stdout = tee
    base = args.keep or tempfile.mkdtemp(prefix="ddlb_chaos_degrade_")
    os.makedirs(base, exist_ok=True)
    failures: list = []

    def check(ok, what):
        print(f"  {'PASS' if ok else 'FAIL'}  {what}", flush=True)
        if not ok:
            failures.append(what)

    try:
        import pandas as pd

        from ddlb_tpu.observatory import health, store
        from scripts.health_report import build_report
        from scripts.skew_report import gate

        history = os.path.join(base, "history")
        extra_pred = predicted_extra_s()
        print("==== degraded-world chaos battery: seeded 4x link_slow, "
              "detect -> indict -> mitigate ====")
        print(f"workload: {len(IMPLS)}-row tp_columnwise {M}x{N}x{K}, "
              f"{ITERATIONS} barriered iterations per row")
        print(f"seeded fault: ici[{LINK_INDEX}->{LINK_INDEX + 1}] at "
              f"{FACTOR}x rate ({SIM_LINK_GBS * 1e9:.0f} B/s healthy) — "
              f"simulator predicts +{extra_pred:.3f}s per collective "
              f"crossing")

        # -- 1: two clean worlds, banked, health gate on ----------------
        for name in ("clean-0", "clean-1"):
            rc, run_dir = run_world(name, base, history)
            check(rc == 0, f"clean world '{name}' completed (rc={rc})")
            with open(os.path.join(run_dir, "attempts.json")) as f:
                attempts = json.load(f)
            check(
                len(attempts) == 1 and attempts[0]["outcome"] == "ok",
                f"clean world '{name}': one attempt, outcome ok, no "
                f"indictment (health gate on)",
            )
        report = build_report(history_dir=history, ranks=PROCESSES)
        check(
            report["verdict"]["status"] != health.PERSISTENT,
            f"health report on the clean bank indicts nobody "
            f"({report['verdict']['status']})",
        )

        # -- 2-5: the seeded world ---------------------------------------
        print(f"\n==== seeded world: persistent link_slow on "
              f"ici[{LINK_INDEX}->{LINK_INDEX + 1}] ====")
        rc, run_dir = run_world(
            "seeded", base, history, plan=build_plan(args.seed)
        )
        check(rc == 0, f"supervised launch recovered degraded (rc={rc})")

        with open(os.path.join(run_dir, "attempts.json")) as f:
            attempts = json.load(f)
        check(
            len(attempts) == 2,
            f"exactly one degraded relaunch: {len(attempts)} attempts",
        )
        first, last = attempts[0], attempts[-1]
        check(
            first["outcome"] == "degraded",
            f"attempt 0 outcome 'degraded' ({first['outcome']})",
        )
        verdict = first.get("health") or {}
        check(
            verdict.get("status") == "persistent"
            and verdict.get("rank") == 1,
            f"launcher health gate indicted rank 1 as persistent "
            f"(got {verdict.get('status')}/{verdict.get('rank')})",
        )
        check(
            first.get("mitigation") == "exclude slot 1",
            f"mitigation recorded: {first.get('mitigation')!r}",
        )
        check(
            last["outcome"] == "ok"
            and last.get("world_degraded") is True
            and last.get("world_slots") == [0, 2],
            f"relaunched world ran DEGRADED on slots {last.get('world_slots')}"
            f" (outcome {last['outcome']})",
        )

        # -- 3: the skew gate against the clean baselines ----------------
        run_id, rows, findings = gate(history, "seeded")
        check(bool(findings), "observatory skew gate fired on the seeded run")
        if findings:
            check(
                findings[0].get("straggler_rank") == 1,
                f"top skew finding names rank 1 "
                f"({findings[0].get('straggler_rank')})",
            )

        # -- 4: the health report indicts rank 1 + the seeded link -------
        report = build_report(
            history_dir=history, run_id="seeded", ranks=PROCESSES
        )
        verdict = report["verdict"]
        check(
            verdict["status"] == health.PERSISTENT
            and verdict["rank"] == 1,
            f"health report indicts rank 1 as persistent "
            f"({verdict['status']}/{verdict['rank']})",
        )
        seeded_link = f"ici[{LINK_INDEX}->{LINK_INDEX + 1}]"
        check(
            seeded_link in verdict.get("links", []),
            f"seeded link {seeded_link} among the candidate hardware "
            f"({verdict.get('links')})",
        )

        # -- 5: zero rows lost, degraded stamps --------------------------
        csv = os.path.join(run_dir, "rows.csv")
        rows_df = (
            pd.read_csv(csv).groupby("implementation").last().reset_index()
        )
        check(
            len(rows_df) == len(IMPLS)
            and set(rows_df["implementation"])
            == {f"{impl}_0" for impl in IMPLS},
            f"zero rows lost: {len(rows_df)}/{len(IMPLS)} configs have a "
            f"final row",
        )
        check(
            bool(rows_df["valid"].all()),
            "every config's final row measured valid on the degraded world",
        )
        check(
            bool(rows_df["world_degraded"].all())
            and set(rows_df["num_processes"]) == {PROCESSES - 1}
            and set(rows_df["world_size"])
            == {(PROCESSES - 1) * DEVICES_PER_PROCESS},
            "final rows stamped world_degraded on the shrunken "
            f"{PROCESSES - 1}-rank world",
        )

        # -- 6: the simulator prediction brackets the measurement --------
        records = store.load_history(history)
        seeded_rows = [
            r["row"]
            for r in records
            if r.get("run_id") == "seeded"
            and r.get("kind", "row") == "row"
            and not bool(r["row"].get("world_degraded"))
        ]
        skews = [
            float(r["skew_enter_s"])
            for r in seeded_rows
            if isinstance(r.get("skew_enter_s"), (int, float))
            and r["skew_enter_s"] == r["skew_enter_s"]
        ]
        check(
            len(skews) == len(IMPLS),
            f"every degraded-attempt row folded its skew columns "
            f"({len(skews)}/{len(IMPLS)})",
        )
        if skews:
            med = sorted(skews)[len(skews) // 2]
            lo, hi = BRACKET_LO * extra_pred, BRACKET_HI * extra_pred
            check(
                lo <= med <= hi,
                f"simulator degraded-world prediction brackets the "
                f"measured skew: median {med:.3f}s vs predicted "
                f"+{extra_pred:.3f}s/collective (accept [{lo:.3f}, "
                f"{hi:.3f}])",
            )

        # -- 7: composition=auto re-run picks striped under the fault ----
        from ddlb_tpu.primitives.topo_compose import select_composition

        print("\n==== topology-adaptive re-run: dp_allreduce "
              "composition=auto under the same seeded fault ====")
        comp, reason = select_composition(
            "auto", PROCESSES * DEVICES_PER_PROCESS, 1
        )
        check(
            comp == "flat",
            f"healthy parent resolves auto -> {comp} ({reason})",
        )
        rc, run_dir = run_world(
            "seeded-auto", base, history, plan=build_plan(args.seed),
            primitive="dp_allreduce", impls=AUTO_IMPLS,
        )
        check(rc == 0, f"auto world recovered degraded (rc={rc})")
        with open(os.path.join(run_dir, "attempts.json")) as f:
            attempts = json.load(f)
        last = attempts[-1]
        check(
            len(attempts) == 2
            and last["outcome"] == "ok"
            and last.get("world_degraded") is True,
            f"auto world relaunched DEGRADED once "
            f"({len(attempts)} attempts, final {last['outcome']})",
        )
        auto_df = pd.read_csv(os.path.join(run_dir, "rows.csv"))
        final = (
            auto_df.groupby("implementation").last().reset_index()
        )
        check(
            len(final) == len(AUTO_IMPLS)
            and bool(final["valid"].all())
            and bool(final["world_degraded"].all()),
            f"zero rows lost: {len(final)}/{len(AUTO_IMPLS)} configs "
            f"measured valid on the degraded world",
        )
        auto_rows = auto_df[
            auto_df["option"].str.contains("composition=auto", na=False)
        ]
        check(
            len(auto_rows) == 2
            and set(auto_rows["composition"]) == {"striped"},
            f"composition=auto resolved striped on BOTH attempts — the "
            f"fault plan on the full world, the degraded stamp on the "
            f"relaunch ({sorted(set(auto_rows['composition']))} over "
            f"{len(auto_rows)} rows)",
        )
        pinned = auto_df[
            auto_df["option"].str.contains("composition=striped", na=False)
        ]
        check(
            len(pinned) > 0 and set(pinned["composition"]) == {"striped"},
            "pinned composition=striped control passes through unchanged",
        )
        flat_rows = auto_df[auto_df["implementation"] == "jax_spmd_0"]
        check(
            bool(flat_rows["composition"].isna().all()),
            "non-composed jax_spmd rows leave the composition column "
            "empty",
        )

        print()
    finally:
        os.environ.pop("DDLB_TPU_FAULT_PLAN", None)
        if not args.keep:
            shutil.rmtree(base, ignore_errors=True)
        sys.stdout = tee._stdout

    with open(args.log, "a", encoding="utf-8") as f:
        if failures:
            f.write(f"\nchaos_degrade: {len(failures)} assertion(s) FAILED\n")
        else:
            f.write(
                "\nchaos_degrade: seeded degraded link detected by the "
                "skew gate, indicted by the health verdict, mitigated by "
                "a degraded relaunch with zero rows lost, bracketed "
                "by the simulator's degraded-world prediction, and "
                "rerouted by composition=auto resolving striped on every "
                "attempt — OK\n"
            )
    if failures:
        print(f"\nchaos_degrade: {len(failures)} assertion(s) FAILED",
              flush=True)
        for what in failures:
            print(f"  FAIL {what}", flush=True)
        return 1
    print(
        "\nchaos_degrade: seeded degraded link detected, indicted, "
        "mitigated, model-bracketed, and rerouted (composition=auto -> "
        "striped) with zero rows lost — OK",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
