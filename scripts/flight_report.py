#!/usr/bin/env python
"""Post-mortem attribution over a collective flight-recorder run dir.

When a multi-process world wedges or dies, the supervisor (or the
operator, after a SIGKILL nothing supervised) is left with one question:
**which rank diverged, and at which collective**. Every rank recorded
its sequenced progress entries to ``flight-p<rank>.jsonl`` under the
shared run dir (``DDLB_TPU_FLIGHTREC``; see ``ddlb_tpu/faults/
flightrec.py``); this report joins them:

- per rank: the last *completed* sequence number, any entry still in
  flight (begun, never finished — a wedged collective), and the dump
  markers the SIGTERM handlers appended;
- across ranks: the highest common completed sequence, the **lagging
  rank(s)** (lowest completed sequence while peers advanced — the rank
  that never arrived at the collective its peers are stuck in), and the
  **divergence site**.

Usage:
    python scripts/flight_report.py RUN_DIR [--ranks N] [--json]

``--ranks N`` flags ranks that left no flight file at all (killed
before recording anything). ``--json`` emits the full report document
for the chaos battery / CI — including the TIME join (ISSUE 14): every
entry with an ``aligned_ts`` (clock-sync offsets from the run's own
barrier exchanges applied when available, raw monotonic otherwise,
``alignment: none|barrier`` flagged) plus the per-rank offset fits, so
the sequence join and the time join render from one document. Exit
code: 0 when the world shows no divergence, 1 when it does (or no
files were found) — so a supervised wrapper can gate on the verdict.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddlb_tpu.faults import flightrec  # noqa: E402


def render_text(report: dict) -> str:
    """The human form: per-rank progress table, then the verdict."""
    lines = [f"flight report: {report['run_dir']}", ""]
    ranks = report.get("ranks", {})
    if ranks:
        lines.append(
            f"{'rank':>5} {'pid':>8} {'completed':>10} {'entries':>8} "
            f"{'in flight':<28} dumps"
        )
        for rank in sorted(ranks):
            s = ranks[rank]
            inflight = (
                ", ".join(
                    f"{e['site']}#{e['seq']}" for e in s["inflight"]
                )
                or "-"
            )
            lines.append(
                f"{rank:>5} {str(s['pid']):>8} "
                f"{s['last_completed_seq']:>10} {s['entries']:>8} "
                f"{inflight:<28} {','.join(s['dumps']) or '-'}"
            )
    for rank in report.get("missing_ranks", []):
        lines.append(f"{rank:>5} {'-':>8} {'no flight file':>10}")
    lines.append("")
    if "common_seq" in report:
        lines.append(f"highest common completed seq: {report['common_seq']}")
        if report.get("lagging_ranks"):
            lines.append(f"lagging rank(s): {report['lagging_ranks']}")
        if report.get("divergence_site"):
            lines.append(f"divergence site: {report['divergence_site']}")
    lines.append(f"verdict: {report.get('headline', '')}")
    return "\n".join(lines)


def static_cross_reference(report: dict) -> dict:
    """The ``static_trace`` field of ``--json`` reports: every site the
    dumps name (in-flight entries + the divergence verdict) that the
    semantic SPMD pass also traced, mapped to its static location and
    the collective sequence certified there — runtime divergence joined
    to the exact code the analyzer walked. Best-effort: an environment
    without the analysis tier just omits the field's entries."""
    sites = set()
    div = report.get("divergence_site")
    if div:
        sites.add(str(div).split("#")[0])
    for state in report.get("ranks", {}).values():
        for e in state.get("inflight", ()):
            if e.get("site"):
                sites.add(e["site"])
    if not sites:
        return {}
    try:
        from ddlb_tpu.analysis.spmd.sites import static_site_index

        index = static_site_index()
    except Exception:
        return {}
    return {s: index[s] for s in sorted(sites) if s in index}


def diverged(report: dict) -> bool:
    """True when the report shows a problem worth a nonzero exit."""
    if not report.get("ranks"):
        return True
    if report.get("missing_ranks") or report.get("lagging_ranks"):
        return True
    return any(s["inflight"] for s in report["ranks"].values())


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("run_dir", help="flight-recorder run directory")
    parser.add_argument(
        "--ranks", type=int, default=None,
        help="expected world size (flags ranks that left no file)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    report = flightrec.analyze_run(args.run_dir, expected_ranks=args.ranks)
    if args.as_json:
        report["static_trace"] = static_cross_reference(report)
        # the time join rides the same document (ISSUE 14): every entry
        # with its clock-aligned timestamp + uncertainty, the per-rank
        # offset fits, and the alignment mode flag
        from ddlb_tpu.observatory import timeline as timeline_mod

        world = timeline_mod.build_world_timeline(
            args.run_dir, expected_ranks=args.ranks
        )
        report["alignment"] = world["alignment"]
        report["clock_offsets"] = world["offsets"]
        report["entries"] = world["events"]
        # non-finite sentinels (an unalignable rank's inf uncertainty)
        # must not become bare Infinity — strict parsers reject it
        report = timeline_mod.json_safe(report)
        print(json.dumps(report, indent=1, default=str))
    else:
        print(render_text(report))
    return 1 if diverged(report) else 0


if __name__ == "__main__":
    sys.exit(main())
