#!/usr/bin/env python
"""JSON-config entry point (reference /root/reference/scripts/run_benchmark.py:10-32).

Usage:
    python scripts/run_benchmark.py [config.json]

On a TPU pod, launch one process per host (the reference's ``mpirun -np N``
becomes the pod runtime or SLURM starting N host processes; ``ddlb_tpu``
reads the same env fallback chains).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ddlb_tpu.cli import load_config, run_benchmark


def main() -> None:
    config_path = (
        sys.argv[1]
        if len(sys.argv) > 1
        else os.path.join(os.path.dirname(__file__), "config.json")
    )
    run_benchmark(load_config(config_path))


if __name__ == "__main__":
    main()
