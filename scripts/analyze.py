#!/usr/bin/env python
"""Static-analysis CLI for the ddlb_tpu rule engine (make analyze/lint).

Runs every registered rule (``ddlb_tpu/analysis``) over the repo's
Python tree, applies inline suppressions and the committed baseline,
and exits 1 on any non-baselined error. Output modes: human text
(default, with the DDLB101 shard_map migration inventory), ``--json``,
and ``--sarif`` (SARIF 2.1.0 for code-scanning UIs).

Common invocations::

    python scripts/analyze.py                  # full repo, text
    python scripts/analyze.py --changed-only   # pre-commit fast path
    python scripts/analyze.py --sarif > out.sarif
    python scripts/analyze.py --update-baseline  # after fixing sites

The baseline (``analysis_baseline.json``) is shrink-only: stale entries
are DDLB110 errors, and ``--update-baseline`` refuses growth without
``--allow-baseline-growth`` (new violations get fixed or suppressed
with a reviewed ``# ddlb: ignore[rule-id]`` comment, never silently
grandfathered).
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from ddlb_tpu.analysis import core  # noqa: E402
from ddlb_tpu.analysis import baseline as baseline_mod  # noqa: E402
from ddlb_tpu.analysis import output  # noqa: E402

#: the default analysis sweep — same surface as the old make lint
DEFAULT_TARGETS = (
    "ddlb_tpu", "tests", "scripts", "bench.py", "__graft_entry__.py",
)


def _changed_files(ref: str) -> list:
    """Python files changed vs the merge-base with ``ref`` plus the
    working tree — the fast pre-commit surface. Falls back through
    origin/main -> main -> HEAD~1 when ``ref`` is empty; an
    unresolvable base raises (analyzing nothing must never look like a
    clean pass)."""
    candidates = [ref] if ref else ["origin/main", "main", "HEAD~1"]
    base = None
    for cand in candidates:
        proc = subprocess.run(
            ["git", "merge-base", "HEAD", cand],
            cwd=REPO, capture_output=True, text=True,
        )
        if proc.returncode == 0:
            base = proc.stdout.strip()
            break
    if base is None:
        raise ValueError(
            f"cannot resolve a merge base against "
            f"{' / '.join(candidates)} — fix the ref or run the full "
            f"sweep"
        )
    names = set()
    diffs = [
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "diff", "--name-only", f"{base}..HEAD"],
    ]
    for cmd in diffs:
        proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
        if proc.returncode == 0:
            names.update(
                line.strip()
                for line in proc.stdout.splitlines()
                if line.strip().endswith(".py")
            )
    return sorted(
        REPO / name for name in names if (REPO / name).exists()
    )


def spmd_trace(family: str) -> int:
    """Dump one family's (or every family's) member collective traces —
    the ``--spmd-trace`` debugging surface over the same driver DDLB123
    verifies with."""
    from ddlb_tpu.analysis.spmd import families as families_mod

    known = sorted(families_mod.FAMILY_SHAPES)
    if family != "all" and family not in known:
        print(
            f"analyze: unknown family {family!r} — one of: "
            f"{', '.join(known)} (or 'all')",
            file=sys.stderr,
        )
        return 2
    wanted = None if family == "all" else [family]
    reports = families_mod.verify_families(families=wanted)
    drift = 0
    for report in reports:
        for line in report.describe():
            print(line)
        drift += report.status == "drift"
    statuses = {}
    for report in reports:
        statuses[report.status] = statuses.get(report.status, 0) + 1
    summary = ", ".join(
        f"{n} {status}" for status, n in sorted(statuses.items())
    )
    print(f"spmd-trace: {len(reports)} member config(s): {summary}")
    return 1 if drift else 0


def pallas_census() -> int:
    """Dump every kernel census (the ``--pallas-census`` mode): the
    per-``pallas_call`` VMEM/tile/DMA/wire breakdown, the budget table
    against every registered chip spec, and the DDLB130-133 findings —
    exit 1 on any finding, so ``make ci`` fails on an unmodeled or
    over-budget kernel."""
    from ddlb_tpu.analysis.pallas import census as census_mod
    from ddlb_tpu.analysis.pallas import rules_pallas
    from ddlb_tpu.perfmodel.specs import CHIP_SPECS

    contexts = [
        core.build_context(p, root=REPO)
        for p in core.expand_targets([str(REPO / "ddlb_tpu")])
    ]
    run = census_mod.shared_run()
    for census in run.censuses:
        for line in census.describe():
            print(line)
        print()
    chips = sorted(CHIP_SPECS.values(), key=lambda s: s.name)
    print(
        "VMEM budget table (census total vs per-chip capacity, "
        "canonical sweep shapes):"
    )
    header = f"  {'kernel':44s}" + "".join(
        f"{s.name:>10s}" for s in chips
    )
    print(header)
    seen = set()
    for census in run.censuses:
        key = (census.rel, census.line)
        if key in seen:
            continue
        seen.add(key)
        total = census.vmem_bytes()
        label = f"{census.name} ({census.rel.rsplit('/', 1)[-1]})"
        if total is None:
            print(f"  {label:44s}" + "  unsizeable")
            continue
        cells = "".join(
            f"{'OVER' if total > s.vmem_bytes else 'ok':>7s}"
            f"{total / (1 << 20):>3.0f}M"
            if total > s.vmem_bytes
            else f"{total / (1 << 20):>9.1f}M"
            for s in chips
        )
        print(f"  {label:44s}{cells}")
    findings = []
    for rule in rules_pallas.RULES:
        if hasattr(rule, "findings_from"):
            findings.extend(rule.findings_from(run, contexts))
    # same masking contract as the main sweep: inline suppressions on
    # the finding's line, then the committed baseline — the gate fails
    # only on NON-masked findings (the Makefile's stated behavior)
    by_rel = {ctx.rel: ctx for ctx in contexts}
    for f in findings:
        ctx = by_rel.get(f.path)
        if ctx is not None:
            core._apply_suppressions(ctx, [f])
    baseline_mod.apply(
        findings,
        baseline_mod.load(REPO / baseline_mod.BASELINE_NAME),
        REPO / baseline_mod.BASELINE_NAME,
    )
    for f in findings:
        print(output.text_line(f))
    counting = sum(1 for f in findings if f.counts)
    n_sites = len(census_mod.pallas_call_sites(contexts))
    print(
        f"pallas-census: {len(seen)} distinct pallas_call site(s) "
        f"censused of {n_sites} in ddlb_tpu/, "
        f"{counting} finding(s) ({len(findings) - counting} masked), "
        f"{len(run.errors)} drive error(s)"
    )
    return 1 if counting else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="analyze.py",
        description="ddlb_tpu static analysis (rule catalog: "
        "docs/source/static_analysis.rst)",
    )
    parser.add_argument(
        "targets", nargs="*",
        help=f"files/dirs to analyze (default: {' '.join(DEFAULT_TARGETS)})",
    )
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    mode.add_argument(
        "--sarif", action="store_true", help="SARIF 2.1.0 document"
    )
    parser.add_argument(
        "--baseline", default=str(REPO / baseline_mod.BASELINE_NAME),
        help="baseline file (default: analysis_baseline.json at the "
        "repo root)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (every grandfathered finding counts)",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings "
        "(shrink-only unless --allow-baseline-growth)",
    )
    parser.add_argument(
        "--allow-baseline-growth", action="store_true",
        help="let --update-baseline add entries (reviewed exception)",
    )
    parser.add_argument(
        "--changed-only", nargs="?", const="", metavar="REF",
        default=None,
        help="analyze only files changed vs the merge-base with REF "
        "(default origin/main, then main, then HEAD~1) plus the "
        "working tree — the pre-commit fast path",
    )
    parser.add_argument(
        "--show-masked", action="store_true",
        help="also print suppressed/baselined findings in text mode",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--spmd-trace", metavar="FAMILY", default=None,
        help="dump the semantic SPMD collective traces for one "
        "registered family ('all' for every family) and exit — the "
        "DDLB123 debugging surface",
    )
    parser.add_argument(
        "--pallas-census", action="store_true",
        help="dump every Pallas kernel's VMEM/tile/DMA census and the "
        "per-chip budget table, exit 1 on any DDLB130-133 finding — "
        "the kernel-model debugging surface (and the make ci gate)",
    )
    args = parser.parse_args(argv)

    if args.spmd_trace is not None:
        return spmd_trace(args.spmd_trace)

    if args.pallas_census:
        return pallas_census()

    if args.list_rules:
        for rule in core.all_rules():
            kind = (
                "project" if isinstance(rule, core.ProjectRule) else "file"
            )
            print(f"{rule.id}  {rule.severity:5s} {kind:7s} {rule.name}")
            print(f"        {rule.rationale}")
        return 0

    if args.changed_only is not None:
        if args.update_baseline:
            # the baseline is written from the analyzed findings; a
            # subset sweep would silently drop every untouched entry
            print(
                "analyze: --update-baseline requires the full sweep "
                "(drop --changed-only)",
                file=sys.stderr,
            )
            return 2
        try:
            paths = _changed_files(args.changed_only)
        except ValueError as exc:
            print(f"analyze: {exc}", file=sys.stderr)
            return 2
        if not paths:
            print("analyze: no changed Python files")
            return 0
    else:
        targets = args.targets or [
            str(REPO / t) for t in DEFAULT_TARGETS
        ]
        try:
            paths = core.expand_targets(targets)
        except FileNotFoundError as exc:
            # a missing target must fail like pyflakes would, not lint
            # nothing and exit 0
            print(
                f"analyze: no such file or directory: {exc.args[0]}",
                file=sys.stderr,
            )
            return 2

    contexts: list = []
    findings = core.analyze(paths, root=REPO, contexts_out=contexts)

    baseline_path = Path(args.baseline)
    if not args.no_baseline:
        known = baseline_mod.load(baseline_path)
        # staleness is provable only by the FULL sweep; a changed-only
        # run must not report the untouched backlog as stale
        analyzed = None
        if args.changed_only is not None:
            analyzed = {core.relativize(p, root=REPO) for p in paths}
        findings.extend(
            baseline_mod.apply(
                findings, known, baseline_path, analyzed=analyzed
            )
        )

    if args.update_baseline:
        grown = baseline_mod.update(
            findings, baseline_path,
            allow_growth=args.allow_baseline_growth,
        )
        if grown:
            print(
                "analyze: baseline would GROW — fix or suppress these "
                "instead (or pass --allow-baseline-growth):",
                file=sys.stderr,
            )
            for line in grown:
                print(f"  {line}", file=sys.stderr)
            return 1
        print(f"analyze: baseline written to {baseline_path}")
        # fresh mask so the exit code reflects the file just written
        for f in findings:
            f.baselined = False
        findings = [
            f for f in findings
            if f.rule != baseline_mod.STALE_BASELINE_ID
        ]
        baseline_mod.apply(
            findings, baseline_mod.load(baseline_path), baseline_path
        )

    errors = sum(1 for f in findings if f.counts)

    if args.json:
        print(output.dump_json(output.render_json(findings)), end="")
    elif args.sarif:
        print(output.dump_json(output.render_sarif(findings)), end="")
    else:
        for line in output.render_text(
            findings, show_masked=args.show_masked
        ):
            print(line)
        # migrated/total progress needs the full sweep's ASTs; a
        # changed-only subset would under-count the migrated side
        inventory_ctx = contexts if args.changed_only is None else ()
        for line in output.shard_map_inventory(findings, inventory_ctx):
            print(line)
        masked = sum(
            1 for f in findings if f.suppressed or f.baselined
        )
        if errors:
            print(
                f"analyze: {errors} error(s) in {len(paths)} file(s) "
                f"({masked} masked)",
                file=sys.stderr,
            )
        else:
            print(
                f"analyze: {len(paths)} files clean "
                f"({masked} masked finding(s))"
            )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
