#!/usr/bin/env python
"""The one resumable hardware row queue (supersedes measure_r{2,3,4}_hw
and measure_r2_remaining).

Four generations of armed batch scripts each re-ran from their own top on
every relay window, re-paying compiles and re-measuring banked rows. This
queue replays the UNION of their row lists in **value order** (the
verdict-demanded headline rows first — same rationale as the watcher's
batch ordering), **checkpoints after every row** to
``hwlogs/queue_state.json``, and **resumes mid-queue**: a short relay
window drains the most-demanded rows first, and a second window starts
where the first died instead of at the top.

Compile banking: the queue exports ``DDLB_TPU_COMPILE_CACHE`` (default
``hwlogs/compile_cache``) so every per-row child process reuses the
persistent XLA compilation cache — a row retried after a flap, or a
config sharing executables with an earlier row, skips the cold compile
it already paid for (see ddlb_tpu/utils/compile_ahead.py; rows record
``compile_time_s`` / ``compile_cache_hit``).

Failure policy mirrors the watcher's: an errored row is retried on the
next pass, but after MAX_ATTEMPTS failed attempts it is parked (a
deterministically failing config must not re-burn capture windows).

Usage: python scripts/measure_queue.py [--quick] [--smoke] [--list]
           [--only SECTION_PREFIX] [--limit N] [--state PATH]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# telemetry is deliberately JAX-free (like this driver: the TPU backend
# must never initialize in the queue process) — spans around each row
# attempt and parking decision make a capture window's trace attributable
from ddlb_tpu import telemetry  # noqa: E402
# the transient-vs-deterministic split shared with the sweep runner
# (also JAX-free): deterministic failures park IMMEDIATELY instead of
# burning a second capture-window pass on a config that cannot succeed
from ddlb_tpu.faults.classify import (  # noqa: E402
    DEGRADED,
    DETERMINISTIC,
    classify_error,
)
# the live sweep stream (also JAX-free, env-gated): park decisions feed
# the scripts/sweep_dash.py dashboard next to the pool's worker events
from ddlb_tpu.observatory import live  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATE_PATH = os.path.join(REPO, "hwlogs", "queue_state.json")
COMPILE_CACHE_DEFAULT = os.path.join(REPO, "hwlogs", "compile_cache")

MAX_ATTEMPTS = 2

V5E_HBM_GBPS = 819.0
V5E_PEAK_BF16_TFLOPS = 197.0

# the serving-table model (scripts/measure_r3_hw.py section 1)
D, F, V, HEADS, B, LAYERS = 2048, 8192, 16384, 16, 8, 1
DH = D // HEADS


# ---------------------------------------------------------------------------
# Queue construction: the union of the four batch lists, value-ordered
# ---------------------------------------------------------------------------


def _row(section, label, primitive, impl, m, n, k, derive=None,
         proto_overrides=None, note=None, **options):
    return {
        "kind": "row",
        "section": section,
        "label": label,
        "primitive": primitive,
        "impl": impl,
        "m": m,
        "n": n,
        "k": k,
        "options": options,
        "proto_overrides": proto_overrides or {},
        "derive": derive,
        "note": note,
    }


def _action(section, label, action):
    return {
        "kind": "action",
        "section": section,
        "label": label,
        "action": action,
    }


def entry_key(entry) -> str:
    """Stable checkpoint identity of one queue entry — the caller-config
    form (same philosophy as hw_common's bank_key: options as the script
    spells them, before DEFAULT merging)."""
    if entry["kind"] == "action":
        # label included: the generic "noop" skip marker appears once per
        # skipped section and each must checkpoint independently
        return json.dumps(
            {"action": entry["action"], "label": entry["label"]},
            sort_keys=True,
        )
    return json.dumps(
        {
            "primitive": entry["primitive"],
            "impl": entry["impl"],
            "m": entry["m"],
            "n": entry["n"],
            "k": entry["k"],
            "options": entry["options"],
            "proto_overrides": entry["proto_overrides"],
        },
        sort_keys=True,
        default=str,
    )


def build_queue(quick: bool = False, smoke: bool = False):
    """The full value-ordered entry list (pure: no JAX, no hardware —
    the HBM budget model is plain arithmetic)."""
    from ddlb_tpu.utils.hbm_budget import fit_batch

    q = []
    if smoke:
        # plumbing test without the relay: one tiny roofline row (the
        # least-demanding impl, runs on every backend), no TPU-only
        # sections (kernel parity needs a real chip)
        q.append(_row(
            "smoke", "gemm roofline smoke 128^3", "tp_columnwise",
            "compute_only", 128, 128, 128, size="unsharded",
        ))
        return q

    # -- 1) r3 serving table: the oldest unmet verdict asks ------------------
    contexts = (2048, 8192) if quick else (2048, 8192, 32768, 65536)
    for ctx in contexts:
        b_ctx, rep = fit_batch(
            preferred_batch=B, ctx=ctx, d_model=D, d_ff=F, vocab=V,
            n_heads=HEADS, layers=LAYERS, phase="decode", validate=True,
        )
        note = f"[budget] ctx={ctx}: batch={b_ctx}  {rep.line()}"
        if not rep.fits:
            # recorded at build so the skip is visible in --list output
            q.append(_action(
                "r3-serving", f"SKIPPED ctx={ctx}: no batch fits", "noop"
            ))
            continue
        levers = (
            (f"bf16 cache, MHA @ {ctx} B={b_ctx}", {}),
            (f"int8 cache, MHA @ {ctx} B={b_ctx}", {"kv_cache": "int8"}),
            (f"bf16 cache, GQA4 @ {ctx} B={b_ctx}", {"n_kv_heads": 4}),
            (f"int8 cache, GQA4 @ {ctx} B={b_ctx}",
             {"n_kv_heads": 4, "kv_cache": "int8"}),
            (f"int8 cache + int8 weights @ {ctx} B={b_ctx}",
             {"kv_cache": "int8", "mlp_kernel": "int8_weights"}),
        )
        for label, extra in levers:
            q.append(_row(
                "r3-serving", label, "transformer_decode", "spmd",
                ctx, D, F, derive="serving", note=note,
                batch=b_ctx, vocab=V, n_heads=HEADS, phase="decode",
                attn_kernel="flash", **extra,
            ))
            note = None  # budget line prints once per context
    q.append(_row(
        "r3-serving", "prefill 2k (flash)", "transformer_decode", "spmd",
        2048, D, F, batch=B, vocab=V, n_heads=HEADS, phase="prefill",
        attn_kernel="flash",
    ))
    n_new = 32
    for lbl, extra in (
        (f"generate 2k+{n_new} bf16 MHA", {}),
        (f"generate 2k+{n_new} int8+GQA4",
         {"kv_cache": "int8", "n_kv_heads": 4}),
    ):
        q.append(_row(
            "r3-serving", lbl, "transformer_decode", "spmd", 2048, D, F,
            derive="generate", batch=B, vocab=V, n_heads=HEADS,
            phase="generate", n_new=n_new, attn_kernel="einsum", **extra,
        ))

    # -- 2) r3 int8 Pallas tile sweep + autotuned rows -----------------------
    M = N = K = 8192
    q.append(_row("r3-int8", "XLA int8 (reference)", "tp_columnwise",
                  "quantized", M, N, K, kernel="xla", quantize="static"))
    q.append(_row("r3-int8", "pallas int8 AUTOTUNED", "tp_columnwise",
                  "quantized", M, N, K, kernel="pallas", quantize="static",
                  tune=True))
    q.append(_row("r3-int8", "pallas bf16 AUTOTUNED", "tp_columnwise",
                  "pallas", M, N, K, tune=True))
    tiles = (
        [(1024, 1024, 1024), (512, 1024, 1024)]
        if quick
        else [
            (1024, 1024, 1024), (512, 1024, 1024), (1024, 512, 1024),
            (1024, 1024, 512), (512, 512, 2048), (2048, 1024, 512),
            (512, 2048, 1024),
        ]
    )
    for bm, bn, bk in tiles:
        q.append(_row(
            "r3-int8", f"pallas int8 tiles ({bm},{bn},{bk})",
            "tp_columnwise", "quantized", M, N, K,
            kernel="pallas", quantize="static",
            block_m=bm, block_n=bn, block_k=bk,
        ))

    # -- 3) r4 MFU-vs-shape curve --------------------------------------------
    curve = [
        (2048, 2048, 8192, 16),
        (4096, 2048, 8192, 16),  # the 0.80-MFU BASELINE.md point
        (8192, 2048, 8192, 16),
        (4096, 4096, 16384, 32),
    ]
    if not quick:
        curve.append((8192, 4096, 16384, 32))
    for seq, d, f, heads in curve:
        q.append(_row(
            "r4-mfu", f"train seq={seq} d={d} ff={f} h={heads}",
            "transformer_step", "spmd", seq, d, f, derive="mfu",
            proto_overrides={"validate": False},
            mode="train", attn_kernel="flash", batch=1, vocab=V,
            n_heads=heads, microbatches=1, pp=1, tp=1, dp=1,
        ))

    # -- 4) r4 compiled-vs-interpreted kernel parity (world=1 self-DMA) -----
    q.append(_action(
        "r4-parity", "compiled vs interpreted kernel parity",
        "kernel_parity",
    ))

    # -- 5) r3 xprof trace of the MFU headline + top-op digest --------------
    q.append(_row(
        "r3-trace", "MFU-headline train step (xprof trace)",
        "transformer_step", "spmd", 4096, D, F, derive="mfu",
        proto_overrides={
            "validate": False, "profile_dir": "profiles/mfu_breakdown",
        },
        mode="train", attn_kernel="flash", batch=1, vocab=V,
        n_heads=HEADS, microbatches=1, pp=1, tp=1, dp=1,
    ))
    q.append(_action("r3-trace", "xprof top-op digest", "xprof_summary"))

    # -- 6) r3 schedules + GQA train row -------------------------------------
    model = dict(batch=4, vocab=V, n_heads=HEADS, microbatches=4,
                 pp=1, tp=1, dp=1)
    for sched in ("gpipe", "1f1b"):
        q.append(_row(
            "r3-sched",
            f"train schedule={sched} (single chip: pp=1 degenerate)",
            "transformer_step", "spmd", 2048, D, F,
            mode="train", schedule=sched, attn_kernel="flash", **model,
        ))
    q.append(_row(
        "r3-sched", "train GQA4 flash", "transformer_step", "spmd",
        4096, D, F, mode="train", attn_kernel="flash", n_kv_heads=4,
        batch=4, vocab=V, n_heads=HEADS, microbatches=1, pp=1, tp=1, dp=1,
    ))

    # -- 7) r4 speculative decoding + continuous batching --------------------
    n_new = 64
    for phase, extra in (
        ("generate", {}),
        ("speculate", {"spec_k": 4, "draft_layers": 1}),
        ("speculate", {"spec_k": 8, "draft_layers": 1}),
    ):
        q.append(_row(
            "r4-spec", f"{phase} 2k+{n_new} {extra or ''}",
            "transformer_decode", "spmd", 2048, D, F, derive="speculate",
            proto_overrides={"validate": False},
            phase=phase, n_new=n_new, batch=8, vocab=V, n_heads=16,
            layers=2, attn_kernel="einsum", **extra,
        ))
    n_req = 16
    for lbl, extra in (
        ("contiguous", {}),
        ("paged 1.0", {"cache_layout": "paged", "page_pool_frac": 1.0}),
        ("paged 0.5", {"cache_layout": "paged", "page_pool_frac": 0.5}),
        ("paged 0.5 + fused kernel",
         {"cache_layout": "paged", "page_pool_frac": 0.5,
          "decode_kernel": "pallas"}),
    ):
        q.append(_row(
            "r4-spec", f"serve {n_req} reqs @2k, n_new<={n_new} [{lbl}]",
            "transformer_decode", "spmd", 2048, D, F, derive="serve",
            proto_overrides={
                "validate": False,
                "time_measurement_backend": "host_clock",
            },
            phase="serve", n_new=n_new, n_requests=n_req, batch=8,
            vocab=V, n_heads=16, layers=2, attn_kernel="einsum",
            dp=1, tp=1, **extra,
        ))

    # -- 8) r4 fused decode-attention kernel A/B -----------------------------
    for ctx in (8192, 32768, 65536):
        b_ctx, rep = fit_batch(
            preferred_batch=8, ctx=ctx, d_model=D, d_ff=F, vocab=V,
            n_heads=HEADS, layers=LAYERS, phase="decode", validate=False,
        )
        note = f"[budget] ctx={ctx}: batch={b_ctx}  {rep.line()}"
        if not rep.fits:
            q.append(_action(
                "r4-decode", f"SKIPPED ctx={ctx}: no batch fits", "noop"
            ))
            continue
        for lbl, extra in (
            ("bf16 MHA", {}),
            ("int8+GQA4", {"kv_cache": "int8", "n_kv_heads": 4}),
        ):
            for dk in ("einsum", "pallas"):
                q.append(_row(
                    "r4-decode",
                    f"decode @{ctx} {lbl} kernel={dk} B={b_ctx}",
                    "transformer_decode", "spmd", ctx, D, F, note=note,
                    proto_overrides={"validate": False},
                    phase="decode", batch=b_ctx, vocab=V, n_heads=HEADS,
                    attn_kernel="flash", decode_kernel=dk, **extra,
                ))
                note = None

    # -- 9) r4 windowed flash attention --------------------------------------
    for w in (0, 4096):
        q.append(_row(
            "r4-window", f"flash seq=32k window={w or 'full'}",
            "cp_ring_attention", "flash", 32768, 2048, 128,
            proto_overrides={"validate": False},
            window=w, block_q=1024, block_kv=1024,
        ))

    # -- 10) r4 HBM-copy roofline --------------------------------------------
    for m_pay in (8192, 32768):
        q.append(_row(
            "r4-hbm", f"hbm copy roofline {m_pay}x8192 bf16",
            "collectives", "compute_only", m_pay, 8, 8192,
            derive="hbm_copy", size="unsharded",
        ))

    # -- 11) r2 forward-mode MLP kernel A/B ----------------------------------
    model = dict(batch=1, vocab=V, n_heads=HEADS, microbatches=1)
    for mlp in ("bf16", "int8", "int8_weights"):
        q.append(_row(
            "r2-mlp", f"forward mlp_kernel={mlp}", "transformer_step",
            "spmd", 4096, 2048, 8192, mode="forward", mlp_kernel=mlp,
            attn_kernel="flash", **model,
        ))

    # -- 12) r2 decode/prefill/ep rows (union of r2_hw + r2_remaining) ------
    serve = dict(batch=8, vocab=V, n_heads=HEADS)
    for ctx in (1024, 4096) if quick else (1024, 4096, 8192):
        for mlp in ("bf16", "int8_weights"):
            q.append(_row(
                "r2-decode", f"decode ctx={ctx} mlp={mlp}",
                "transformer_decode", "spmd", ctx, 2048, 8192,
                phase="decode", mlp_kernel=mlp, **serve,
            ))
    q.append(_row(
        "r2-decode", "prefill 1k", "transformer_decode", "spmd",
        1024, 2048, 8192, phase="prefill", **serve,
    ))
    q.append(_row("r2-decode", "ep_alltoall jax_spmd", "ep_alltoall",
                  "jax_spmd", 8192, 8192, 8192))
    q.append(_row("r2-decode", "ep_alltoall quantized", "ep_alltoall",
                  "quantized", 8192, 8192, 8192, quantize="static"))

    # drop exact duplicates (r2_remaining rows re-listed by r2_hw etc.),
    # first occurrence wins so value order is preserved
    seen, unique = set(), []
    for entry in q:
        key = entry_key(entry)
        if key in seen:
            continue
        seen.add(key)
        unique.append(entry)
    return unique


# ---------------------------------------------------------------------------
# Derived per-row prints (ported from the superseded batch scripts)
# ---------------------------------------------------------------------------


def _decode_bytes(ctx, b, n_kv, kv_cache, mlp_kernel, tp=1):
    """HBM bytes read per decode step (the bandwidth model): K+V cache at
    the context length + this chip's weights once (measure_r3_hw)."""
    h_kv = n_kv or HEADS
    kv_bytes = 1 if kv_cache == "int8" else 2
    cache = 2 * LAYERS * b * ctx * h_kv * DH * kv_bytes
    if kv_cache == "int8":
        cache += 2 * LAYERS * b * ctx * h_kv * 4  # f32 scales
    w_bytes = 1 if mlp_kernel == "int8_weights" else 2
    kv_frac = h_kv / HEADS
    weights = (
        LAYERS * ((2 + 2 * kv_frac) * D * D * 2 + 2 * D * F * w_bytes / tp)
        + D * V * 2
    )
    return cache + weights


def _finite(x):
    import math

    try:
        return math.isfinite(float(x))
    except (TypeError, ValueError):
        return False


def _derive_print(entry, row):
    """The batch scripts' per-row follow-up lines, keyed by entry."""
    opts = entry.get("options", {})
    t_ms = row.get("median time (ms)")
    if not _finite(t_ms) or row.get("error"):
        return
    derive = entry.get("derive")
    if derive == "serving":
        b = opts.get("batch", B)
        gb = _decode_bytes(
            entry["m"], b, opts.get("n_kv_heads", 0),
            opts.get("kv_cache", "bf16"), opts.get("mlp_kernel", "bf16"),
        ) / 1e9
        frac = gb / (t_ms / 1e3) / V5E_HBM_GBPS
        print(
            f"    -> {t_ms / b:.3f} ms/token  {b / t_ms * 1e3:,.0f} tok/s   "
            f"bytes-read model {gb:.2f} GB/step  HBM fraction {frac:.2f}",
            flush=True,
        )
    elif derive == "generate":
        b, n_new = opts.get("batch", B), opts.get("n_new", 32)
        print(
            f"    -> {b * n_new / t_ms * 1e3:,.0f} generated tok/s end to end",
            flush=True,
        )
    elif derive == "speculate":
        b, n_new = opts.get("batch", 8), opts.get("n_new", 64)
        print(f"    -> {b * n_new / t_ms * 1e3:,.0f} tok/s end to end",
              flush=True)
        if "spec_accept_rate" in row:
            print(
                f"    -> measured acceptance rate "
                f"{row['spec_accept_rate']:.3f} over "
                f"{row.get('spec_rounds')} verify rounds",
                flush=True,
            )
    elif derive == "serve":
        n_req, n_new = opts.get("n_requests", 16), opts.get("n_new", 64)
        total_new = sum(1 + ((i + 3) % n_new) for i in range(n_req))
        print(
            f"    -> {total_new / t_ms * 1e3:,.0f} sustained tok/s "
            f"({total_new} tokens drained)",
            flush=True,
        )
        if "serve_occupancy" in row:
            pages = (
                f"  peak pages {row['serve_peak_pages']}"
                f"/{row.get('serve_pages_capacity')}"
                if "serve_peak_pages" in row
                else ""
            )
            print(
                f"    -> occupancy {row['serve_occupancy']:.3f}  deferrals "
                f"{row.get('serve_admissions_deferred')}{pages}",
                flush=True,
            )
    elif derive == "hbm_copy":
        gb = entry["m"] * 8192 * 2 / 1e9
        print(
            f"    -> payload {gb:.2f} GB  copy GB/s "
            f"{gb / (t_ms / 1e3):,.0f}  (raw HBM r+w ~2x)",
            flush=True,
        )
    elif derive == "mfu":
        tf = row.get("Throughput (TFLOPS)")
        if _finite(tf):
            print(f"    -> MFU {tf / V5E_PEAK_BF16_TFLOPS:.3f}", flush=True)


# ---------------------------------------------------------------------------
# Actions (non-row work carried over from the batch scripts)
# ---------------------------------------------------------------------------


def _run_parity() -> bool:
    """Compiled-vs-interpreted Pallas kernel parity at world=1 self-DMA
    (measure_r4_hw section 2); needs a real TPU. Returns ok.

    MUST run in a child process (``--parity-child``), never in the queue
    driver: importing jax here initializes the TPU backend, and libtpu
    locks the chip to this process for its lifetime — a driver that ran
    parity inline would starve every later per-row child of the chip.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ddlb_tpu.ops.alltoall_matmul import alltoall_expert_matmul
    from ddlb_tpu.ops.collective_matmul import ring_ag_matmul, ring_matmul_rs
    from ddlb_tpu.runtime import shard_map_compat

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    rng = np.random.default_rng(11)
    m, n, k = 256, 256, 256
    a = jnp.asarray(rng.uniform(-1, 1, (m, k)), jnp.float32)
    b = jnp.asarray(rng.uniform(-1, 1, (k, n)), jnp.float32)
    w = jnp.asarray(rng.uniform(-1, 1, (1, k, n)), jnp.float32)

    def both(tag, fn, in_specs, out_specs, *operands):
        outs = {}
        for mode, interp in (
            ("compiled", None),
            ("interpret", pltpu.InterpretParams()),
        ):
            f = jax.jit(
                shard_map_compat(
                    lambda *xs: fn(*xs, interp),
                    mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_vma=False,
                )
            )
            placed = [
                jax.device_put(o, NamedSharding(mesh, s))
                for o, s in zip(operands, in_specs)
            ]
            outs[mode] = np.asarray(jax.block_until_ready(f(*placed)))
        err = float(np.max(np.abs(outs["compiled"] - outs["interpret"])))
        ok = err <= 1e-5
        print(f"{tag}: max|compiled - interpret| = {err:.2e}  "
              f"{'OK' if ok else 'MISMATCH'}", flush=True)
        return ok

    oks = [
        both(
            "ring_ag_matmul",
            lambda a_s, b_r, ip: ring_ag_matmul(
                a_s, b_r, axis_size=1, block_n=128, block_k=128, interpret=ip
            ),
            (P("tp", None), P(None, None)), P(None, None), a, b,
        ),
        both(
            "ring_matmul_rs",
            lambda a_s, b_s, ip: ring_matmul_rs(
                a_s, b_s, axis_size=1, block_n=128, block_k=128, interpret=ip
            ),
            (P(None, "tp"), P("tp", None)), P("tp", None), a, b,
        ),
        both(
            "alltoall_expert_matmul",
            lambda a_s, w_s, ip: alltoall_expert_matmul(
                a_s, w_s[0], axis_size=1, block_n=128, block_k=128,
                interpret=ip,
            ),
            (P("tp", None), P("tp", None, None)), P("tp", None), a, w,
        ),
    ]
    if not all(oks):
        print("KERNEL PARITY FAILURE — do not trust sim-only rows",
              flush=True)
        return False
    return True


def _run_action(entry) -> bool:
    action = entry["action"]
    if action == "noop":
        print(entry["label"], flush=True)
        return True
    if action == "kernel_parity":
        # subprocess like every row: the driver must stay JAX-free (the
        # TPU backend locks the chip to the first process that opens it,
        # which would starve every later per-row child — the queue's
        # whole reason to exist is not burning capture windows)
        import subprocess

        print("== compiled vs interpreted kernel parity ==", flush=True)
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--parity-child"],
                timeout=1800, capture_output=True, text=True, cwd=REPO,
            )
        except subprocess.TimeoutExpired:
            print("kernel parity child hung >1800s (killed)", flush=True)
            return False
        sys.stdout.write(out.stdout)
        if out.returncode != 0 and out.stderr:
            sys.stdout.write(out.stderr[-2000:])
        sys.stdout.flush()
        return out.returncode == 0
    if action == "xprof_summary":
        # soft-fail like the r3 batch: a digest timeout must not burn the
        # remaining queue (the trace stays on disk for offline analysis)
        import subprocess

        try:
            rc = subprocess.run(
                [sys.executable, "scripts/xprof_summary.py",
                 "profiles/mfu_breakdown", "15"],
                timeout=600, check=False, cwd=REPO,
            ).returncode
            return rc == 0
        except subprocess.TimeoutExpired:
            print("xprof_summary timed out after 600s; trace left for "
                  "offline analysis", flush=True)
            return False
    raise ValueError(f"unknown action {action!r}")


# ---------------------------------------------------------------------------
# Checkpoint state
# ---------------------------------------------------------------------------


def _is_parked(rec) -> bool:
    """Parked = exhausted its attempt budget, OR explicitly parked early
    (deterministic failure). A separate flag keeps the persisted attempt
    count truthful: an early-parked entry records how many passes
    actually ran, not a fabricated MAX_ATTEMPTS."""
    return not rec.get("done") and (
        bool(rec.get("parked")) or rec.get("attempts", 0) >= MAX_ATTEMPTS
    )


def _load_state(path):
    try:
        with open(path) as f:
            state = json.load(f)
        return state if isinstance(state, dict) else {}
    except (OSError, ValueError):
        return {}


def _save_state(path, state) -> None:
    """Atomic replace: a kill mid-write (relay flap under the watcher's
    timeout) must not corrupt the resume record."""
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Drive
# ---------------------------------------------------------------------------


def _run_row(entry, base_proto, run_fn):
    """One measured row + the shared summary line (hw_common style)."""
    config = {
        "primitive": entry["primitive"],
        "impl_id": f"{entry['impl']}_hw",
        "base_implementation": entry["impl"],
        "options": dict(entry["options"]),
        "m": entry["m"],
        "n": entry["n"],
        "k": entry["k"],
        **base_proto,
        **entry["proto_overrides"],
    }
    row = run_fn(config)
    t = row.get("median time (ms)", float("nan"))
    unit = "GB/s" if row.get("unit") == "GB/s" else "TF"
    hbm = (
        f"  hbm-peak {row['hbm_peak_gib']:.2f} GiB"
        if "hbm_peak_gib" in row
        else ""
    )
    compile_s = row.get("compile_time_s")
    comp = (
        f"  compile {compile_s:.1f}s"
        f"{' (cache hit)' if row.get('compile_cache_hit') else ''}"
        if _finite(compile_s)
        else ""
    )
    print(
        f"{entry['primitive']:18s} {entry['impl']:10s} "
        f"m={entry['m']:<6d} {entry['label']} -> "
        f"median {t if _finite(t) else float('nan'):.3f} ms  "
        f"{row.get('Throughput (TFLOPS)', float('nan')):.1f} {unit}  "
        f"valid={row.get('valid')} err={row.get('error') or '-'}"
        f"{hbm}{comp}",
        flush=True,
    )
    _derive_print(entry, row)
    return row


def _print_parked_summary(queue, state) -> None:
    """End-of-run table of parked entries with their persisted reasons
    (last error + transient/degraded/deterministic class), so a parked
    row is diagnosable from the run log alone."""
    parked = []
    for entry in queue:
        rec = state.get(entry_key(entry), {})
        if _is_parked(rec):
            parked.append((entry, rec))
    if not parked:
        return
    print(f"\n== parked entries ({len(parked)}) ==", flush=True)
    print(f"{'label':<44} {'att':>3} {'class':<13} last error")
    for entry, rec in parked:
        print(
            f"{entry['label'][:44]:<44} {rec.get('attempts', 0):>3} "
            f"{(rec.get('error_class') or '-'):<13} "
            f"{(rec.get('error') or '-')[:90]}",
            flush=True,
        )


def main(argv=None, run_fn=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--parity-child" in argv:
        # the chip-owning child for the kernel_parity action (see
        # _run_action); everything else stays in the JAX-free driver
        return 0 if _run_parity() else 1
    quick = "--quick" in argv
    smoke = "--smoke" in argv
    list_only = "--list" in argv

    def _opt(flag, default=None):
        if flag in argv:
            return argv[argv.index(flag) + 1]
        return default

    only = _opt("--only")
    limit = _opt("--limit")
    limit = int(limit) if limit else None
    # one state file per measurement MODE: quick (4 windows) and smoke
    # (tiny sim rows) measure different things than the full protocol,
    # so a row banked under a weaker mode must never mark the full
    # protocol's row done (an explicit --state overrides)
    mode_suffix = "_smoke" if smoke else "_quick" if quick else ""
    default_state = os.path.join(
        REPO, "hwlogs", f"queue_state{mode_suffix}.json"
    )
    state_path = _opt("--state", default_state)

    if smoke:
        # force the sim BEFORE any jax-touching import: with a hung relay
        # plugin installed, an unpinned backend blocks on the exact
        # condition smoke mode exists to avoid (measure_r4_hw lesson)
        os.environ.setdefault("DDLB_TPU_SIM_DEVICES", "1")
    # compile banking across rows, retries and relay windows
    os.environ.setdefault("DDLB_TPU_COMPILE_CACHE", COMPILE_CACHE_DEFAULT)

    queue = build_queue(quick=quick, smoke=smoke)
    if only and not smoke:
        # smoke mode's tiny plumbing queue is its own section; a section
        # filter forwarded by a deprecated shim must not empty it
        queue = [e for e in queue if e["section"].startswith(only)]
    state = _load_state(state_path)

    if list_only:
        for i, entry in enumerate(queue):
            rec = state.get(entry_key(entry), {})
            status = (
                "done" if rec.get("done")
                else f"parked x{rec['attempts']}" if _is_parked(rec)
                else f"failed x{rec['attempts']}" if rec.get("attempts")
                else "pending"
            )
            print(f"{i:3d} [{entry['section']:10s}] {status:9s} "
                  f"{entry['label']}")
        return 0

    from hw_common import proto

    base_proto = proto(quick)
    pooled_runner = None
    if run_fn is None:
        from ddlb_tpu.envs import get_worker_pool

        if get_worker_pool():
            # warm-worker pool (ISSUE 5): ONE leased child per
            # environment signature serves every row this pass —
            # JAX import + PJRT init paid once per capture window, not
            # once per attempt; transient failures retire the lease so
            # retries get a fresh process (hw_common.PooledRunner)
            from hw_common import PooledRunner

            pooled_runner = PooledRunner()
            run_fn = pooled_runner
        else:
            from hw_common import run_isolated

            run_fn = run_isolated

    ran = failed = skipped = 0
    parity_ok = True
    for entry in queue:
        key = entry_key(entry)
        rec = state.get(key, {"attempts": 0, "done": False})
        if rec.get("done"):
            skipped += 1
            continue
        if _is_parked(rec):
            print(f"[queue] parked after {rec['attempts']} failed "
                  f"attempt(s): {entry['label']}", flush=True)
            telemetry.instant(
                "queue.parked", cat="queue", label=entry["label"],
                attempts=rec["attempts"],
            )
            live.post_event(
                "queue_parked", label=entry["label"],
                attempts=rec["attempts"],
            )
            skipped += 1
            continue
        if limit is not None and ran >= limit:
            break
        if entry.get("note"):
            print(entry["note"], flush=True)
        ran += 1
        attempt = rec.get("attempts", 0) + 1
        if entry["kind"] == "action":
            with telemetry.span(
                "queue.action", cat="queue", section=entry["section"],
                label=entry["label"], attempt=attempt,
            ):
                try:
                    ok = _run_action(entry)
                except Exception as exc:
                    print(f"[queue] action {entry['action']} crashed: "
                          f"{type(exc).__name__}: {exc}", flush=True)
                    ok = False
            if entry["action"] == "kernel_parity" and not ok:
                parity_ok = False
            rec = {
                "attempts": attempt,
                "done": ok,
                "label": entry["label"],
            }
        else:
            with telemetry.span(
                "queue.row", cat="queue", section=entry["section"],
                label=entry["label"], attempt=attempt,
            ):
                row = _run_row(entry, base_proto, run_fn)
            err = str(row.get("error") or "")
            ok = not err
            # the park reason is PERSISTED (last error + its class) so a
            # parked entry is diagnosable from queue_state.json and the
            # end-of-run summary, without grepping capture logs
            cls = str(row.get("error_class") or "") or classify_error(
                err, valid=bool(row.get("valid", True))
            )
            rec = {
                "attempts": attempt,
                "done": ok,
                "label": entry["label"],
                "error": err,
                "error_class": cls,
            }
            if not ok:
                failed += 1
                if cls in (DETERMINISTIC, DEGRADED) and attempt < MAX_ATTEMPTS:
                    # a deterministic failure (bad option, validation
                    # mismatch) returns the same answer on every pass,
                    # and a degraded one (downed/slow link, indicted
                    # peer) hits the same bad hardware: park now
                    # instead of re-burning MAX_ATTEMPTS relay windows
                    # (attempts stays truthful — the parked flag is
                    # what later passes honor; the degraded remedy is
                    # the supervised launcher's shrunken relaunch, not
                    # a queue retry)
                    rec["parked"] = True
                    print(
                        f"[queue] parking immediately ({cls} "
                        f"failure): {entry['label']} — {err[:120]}",
                        flush=True,
                    )
                    telemetry.instant(
                        "queue.parked", cat="queue", label=entry["label"],
                        attempts=attempt, error_class=cls,
                    )
                    live.post_event(
                        "queue_parked", label=entry["label"],
                        attempts=attempt, error_class=cls,
                    )
        state[key] = rec
        # checkpoint after EVERY entry: a flap mid-queue loses nothing
        _save_state(state_path, state)

    if pooled_runner is not None:
        # bounded retire of the leased worker (sentinel, join, kill on
        # teardown hang); pool children are daemons, so even a crashed
        # driver cannot orphan a chip-holding child
        pooled_runner.shutdown()
    print(
        f"measure_queue: {ran} run, {failed} failed, {skipped} skipped "
        f"(state: {state_path})",
        flush=True,
    )
    _print_parked_summary(queue, state)
    # per-row children wrote their own shards (DDLB_TPU_TRACE propagates
    # through the environment); join them into the loadable trace.json
    merged = telemetry.merge_trace()
    if merged:
        print(f"[queue] trace merged: {merged}", flush=True)
    # nonzero on ANY failed row this pass, not just parity: the watcher
    # gates its CAPTURED sentinel on rc==0, so a clean-exit-with-errors
    # would end the capture before the retry-then-park policy ever ran.
    # Parked rows are skipped (not failed) on later passes, so a queue
    # whose only failures are exhausted converges back to rc 0.
    if not parity_ok or failed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
